"""Request-level serving telemetry tests (ISSUE 7): bucketed histogram
math, request-context propagation across a real client→server hop, the
/metrics + /debug/telemetry scrape plane, SLO burn-rate windows,
goodput partitioning on synthetic streams, the per-process telemetry
exporter, and the fleet aggregator (incl. a genuine two-process merge
driven through a subprocess server).
"""
import io
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from paddle_tpu import observability as obs
from paddle_tpu.distributed.fleet.elastic import ElasticManager
from paddle_tpu.inference.serving import InferenceClient, InferenceServer
from paddle_tpu.observability import (
    export, goodput, metrics, request_trace, slo, trace,
)
from paddle_tpu.observability.metrics import _Hist

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def telemetry():
    """Full stack on, clean registries, everything off again after.
    Reset BEFORE attach: attach() declares the schema zeros a reset
    would wipe."""
    metrics.reset()
    trace.clear()
    obs.flight.clear()
    obs.attach(crash_hook=False)
    yield
    obs.detach()
    metrics.reset()
    trace.clear()
    obs.flight.clear()


class _StubPredictor:
    def __init__(self, service_time=0.0):
        self.service_time = float(service_time)

    def get_input_names(self):
        return ["x"]

    def get_output_names(self):
        return ["y"]

    def run(self, inputs):
        if self.service_time:
            time.sleep(self.service_time)
        return [np.asarray(inputs[0])]


def _wait_for(pred, timeout=5.0):
    """Poll until `pred()` is truthy: the handler's final accounting
    runs AFTER the response body is written, so a scrape immediately
    following a response can legitimately race it by a few µs."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return bool(pred())


def _post_npz(address, arrays, headers=()):
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    hdrs = {"Content-Type": "application/octet-stream"}
    hdrs.update(dict(headers))
    req = urllib.request.Request(address + "/predict",
                                 data=buf.getvalue(), headers=hdrs)
    return urllib.request.urlopen(req, timeout=30)


# --------------------------------------------------------------------------
# histogram buckets + percentile math (satellite: _Hist.summary fixes)
# --------------------------------------------------------------------------

def test_hist_even_count_p50_is_midpoint():
    h = _Hist()
    for v in (1.0, 2.0, 3.0, 4.0):
        h.observe(v)
    s = h.summary()
    assert s["p50"] == 2.5  # previously r[n//2] == 3.0
    assert s["p99"] >= s["p95"] >= s["p50"]


def test_hist_small_reservoir_p95_interpolates():
    h = _Hist()
    for v in (1.0, 2.0, 3.0):
        h.observe(v)
    s = h.summary()
    assert 2.8 <= s["p95"] < 3.0  # previously snapped to an index
    assert 2.9 <= s["p99"] <= 3.0


def test_hist_bucket_percentiles_beyond_reservoir():
    # 10k uniform values >> 256-slot reservoir: percentiles must come
    # from the buckets (ALL observations), not the last 256 samples
    h = _Hist()
    for v in range(1, 10001):
        h.observe(float(v))
    s = h.summary()
    assert s["count"] == 10000
    assert 4000 < s["p50"] < 6000
    assert 8800 < s["p95"] < 10000
    assert 9400 < s["p99"] <= 10000
    assert s["buckets"]  # sparse counts present for the fleet merge
    assert sum(s["buckets"].values()) == 10000


def test_hist_known_distribution_bucket_interpolation():
    # every value in one bucket: percentile clamps into [min, max]
    h = _Hist()
    for _ in range(100):
        h.observe(50.0)
    assert h.percentile(0.5) == pytest.approx(50.0)
    assert h.percentile(0.99) == pytest.approx(50.0)


def test_prometheus_renders_histogram_buckets_and_quantiles():
    reg = metrics.MetricsRegistry(enabled=True)
    for v in (0.5, 5.0, 50.0, 500.0):
        reg.observe("req.ms", v, endpoint="p")
    text = reg.to_prometheus()
    assert "# TYPE paddle_tpu_req_ms histogram" in text
    # cumulative le-series over the fixed ladder, +Inf closes it
    assert 'paddle_tpu_req_ms_bucket{endpoint="p",le="1"} 1' in text
    assert 'paddle_tpu_req_ms_bucket{endpoint="p",le="+Inf"} 4' in text
    assert 'paddle_tpu_req_ms_count{endpoint="p"} 4' in text
    assert 'paddle_tpu_req_ms_sum{endpoint="p"} 555.5' in text
    # percentiles live in a DISTINCT gauge family: bare-name quantile
    # samples inside a TYPE histogram block are invalid OpenMetrics
    assert '# TYPE paddle_tpu_req_ms_quantile gauge' in text
    assert 'paddle_tpu_req_ms_quantile{endpoint="p",quantile="0.95"}' \
        in text
    assert 'paddle_tpu_req_ms{endpoint="p",quantile=' not in text
    # cumulative counts are monotone over the ladder
    import re

    counts = [int(m.group(1)) for m in re.finditer(
        r'paddle_tpu_req_ms_bucket\{endpoint="p",le="[^"]+"\} (\d+)',
        text)]
    assert counts == sorted(counts)


# --------------------------------------------------------------------------
# request context: identity, headers, hops
# --------------------------------------------------------------------------

def test_request_context_header_round_trip():
    ctx = request_trace.new_context()
    hdrs = ctx.to_headers()
    assert hdrs["X-Request-Id"] == ctx.request_id
    got = request_trace.RequestContext.from_headers(hdrs)
    assert got.request_id == ctx.request_id
    assert got.trace_id == ctx.trace_id
    assert got.parent_id == ctx.span_id  # we are the next hop
    assert got.hop == 1


def test_request_context_child_and_malformed_traceparent():
    ctx = request_trace.new_context(request_id="abc-123")
    kid = ctx.child()
    assert kid.request_id == "abc-123"
    assert kid.trace_id == ctx.trace_id
    assert kid.parent_id == ctx.span_id
    assert kid.hop == ctx.hop + 1
    # bad traceparent, good id: context still continues under the id
    got = request_trace.RequestContext.from_headers(
        {"X-Request-Id": "abc-123", "traceparent": "zz-nonsense"})
    assert got.request_id == "abc-123"
    # hostile id is replaced, not echoed
    got2 = request_trace.RequestContext.from_headers(
        {"X-Request-Id": "bad id\nwith newline",
         "traceparent": "also-bad"})
    assert got2 is None
    assert request_trace.continue_from_headers({}).request_id


def test_request_context_activate_scopes():
    assert request_trace.current() is None
    ctx = request_trace.new_context()
    with request_trace.activate(ctx):
        assert request_trace.current() is ctx
        inner = request_trace.new_context()
        with request_trace.activate(inner):
            assert request_trace.current() is inner
        assert request_trace.current() is ctx
    assert request_trace.current() is None


# --------------------------------------------------------------------------
# SLO tracker: availability, burn rate, window expiry, shed reasons
# --------------------------------------------------------------------------

class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def test_slo_burn_rate_and_window_expiry():
    clk = _Clock()
    tr = slo.SLOTracker(window_s=60.0, clock=clk)
    tr.objective("predict", latency_target_ms=100.0, availability=0.9)
    for i in range(8):
        tr.observe("predict", 50.0, ok=True)
    for _ in range(2):
        tr.observe("predict", 500.0, ok=False, reason="error")
    rep = tr.report(publish_gauges=False)["endpoints"]["predict"]
    assert rep["requests"] == 10
    assert rep["availability"] == pytest.approx(0.8)
    # error rate 0.2 against a 0.1 budget: burning 2x
    assert rep["burn_rate"] == pytest.approx(2.0)
    assert rep["burn_severity"] == "ok"
    assert rep["latency_target_met_frac"] == pytest.approx(0.8)
    assert rep["latency_ms"]["p50"] == pytest.approx(50.0)
    # the window slides: everything ages out
    clk.t += 120.0
    rep2 = tr.report(publish_gauges=False)["endpoints"]["predict"]
    assert rep2["requests"] == 0
    assert "burn_rate" not in rep2
    assert rep2["lifetime_requests"] == 10


def test_slo_shed_reasons_and_severity():
    clk = _Clock()
    tr = slo.SLOTracker(window_s=60.0, clock=clk)
    tr.objective("predict", availability=0.999)
    tr.observe("predict", 10.0, ok=True)
    for _ in range(3):
        tr.record_shed("predict", "queue_full")
    tr.record_shed("predict", "draining")
    rep = tr.report(publish_gauges=False)["endpoints"]["predict"]
    assert rep["errors_by_reason"] == {"shed:queue_full": 3,
                                       "shed:draining": 1}
    assert rep["burn_rate"] > slo._BURN_FAST
    assert rep["burn_severity"] == "page"


def test_slo_objective_validation():
    with pytest.raises(ValueError):
        slo.SLOTracker().objective("p", availability=1.0)


def test_slo_publishes_gauges(telemetry):
    tr = slo.SLOTracker(window_s=60.0)
    tr.objective("predict")
    tr.observe("predict", 5.0, ok=True)
    tr.report()
    g = metrics.snapshot()["gauges"]
    assert g["slo.burn_rate{endpoint=predict}"] == 0.0
    assert g["slo.availability{endpoint=predict}"] == 1.0


# --------------------------------------------------------------------------
# serving e2e: scrape plane, id echo, phase spans, one-id retry
# --------------------------------------------------------------------------

def test_serving_scrape_plane_and_request_id(telemetry):
    srv = InferenceServer(predictor=_StubPredictor()).start()
    try:
        out = InferenceClient(srv.address).predict(
            x=np.ones((2, 2), np.float32))
        assert np.array_equal(out["y"], np.ones((2, 2), np.float32))
        assert _wait_for(lambda: metrics.snapshot()["counters"].get(
            "serving.requests{status=ok}") == 1)

        # /metrics: Prometheus text with real bucket series
        with urllib.request.urlopen(srv.address + "/metrics",
                                    timeout=10) as r:
            assert "text/plain" in r.headers["Content-Type"]
            text = r.read().decode()
        assert '_bucket{' in text
        assert 'paddle_tpu_serving_requests{status="ok"} 1' in text
        assert 'paddle_tpu_serving_phase_ms_bucket' in text
        assert 'paddle_tpu_slo_burn_rate{endpoint="predict"}' in text

        # /debug/telemetry: one-stop JSON snapshot
        with urllib.request.urlopen(srv.address + "/debug/telemetry",
                                    timeout=10) as r:
            snap = json.loads(r.read())
        assert snap["slo"]["endpoints"]["predict"]["requests"] == 1
        assert "admission" in snap and "metrics" in snap
        assert snap["readiness"]["ready"] is True

        # X-Request-Id: echoed when supplied, minted when absent
        with _post_npz(srv.address, {"x": np.ones((1,), np.float32)},
                       headers=[("X-Request-Id", "req-42")]) as r:
            assert r.headers["X-Request-Id"] == "req-42"
        with _post_npz(srv.address,
                       {"x": np.ones((1,), np.float32)}) as r:
            assert r.headers["X-Request-Id"]

        # error responses echo too (bad body -> 400)
        req = urllib.request.Request(
            srv.address + "/predict", data=b"not-an-npz",
            headers={"Content-Type": "application/octet-stream",
                     "X-Request-Id": "bad-1"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400
        assert ei.value.headers["X-Request-Id"] == "bad-1"
    finally:
        srv.shutdown()


def test_phase_spans_correlate_across_the_hop(telemetry):
    srv = InferenceServer(predictor=_StubPredictor()).start()
    try:
        InferenceClient(srv.address).predict(
            x=np.ones((2, 2), np.float32))
        assert _wait_for(lambda: any(
            e["name"] == "serving.request" for e in trace.events()))
    finally:
        srv.shutdown()
    by_name = {}
    for e in trace.events():
        by_name.setdefault(e["name"], []).append(e)
    for name in ("client.predict", "serving.request",
                 "serving.admission", "serving.predict",
                 "serving.serialize"):
        assert name in by_name, sorted(by_name)
    rid = by_name["client.predict"][0]["args"]["request_id"]
    for name in ("serving.request", "serving.admission",
                 "serving.predict", "serving.serialize"):
        assert by_name[name][0]["args"]["request_id"] == rid
    # the server hop continued, not restarted, the trace
    assert by_name["serving.request"][0]["args"]["hop"] == 1
    assert by_name["serving.request"][0]["args"]["status"] == "ok"
    # phase histograms observed under the declared labels
    hists = metrics.snapshot()["histograms"]
    for phase in ("queue", "admission", "predict", "serialize"):
        key = f"serving.phase_ms{{endpoint=predict,phase={phase}}}"
        assert key in hists, sorted(hists)
    assert "serving.request_ms{endpoint=predict,status=ok}" in hists


def test_client_retry_reuses_one_request_id(telemetry):
    srv = InferenceServer(predictor=_StubPredictor(), max_inflight=1,
                          queue_depth=0).start()
    blocker = srv.admission.admit()  # occupy the only slot

    def release(_secs):
        blocker.release(ok=True)

    try:
        client = InferenceClient(srv.address, retries=2, sleep=release)
        out = client.predict(x=np.ones((1,), np.float32))
        assert "y" in out
        assert _wait_for(lambda: sum(
            1 for e in trace.events()
            if e["name"] == "serving.request") == 2)
    finally:
        srv.shutdown()
    reqs = [e for e in trace.events() if e["name"] == "serving.request"]
    assert len(reqs) == 2  # the shed attempt and the successful one
    assert reqs[0]["args"]["request_id"] == reqs[1]["args"]["request_id"]
    statuses = sorted(e["args"]["status"] for e in reqs)
    assert statuses == ["ok", "shed"]
    counters = metrics.snapshot()["counters"]
    assert counters["serving.requests{status=shed}"] == 1
    assert counters["serving.requests{status=ok}"] == 1
    assert counters["client.requests{status=shed_retry}"] == 1
    # the shed burned SLO budget under its reason label
    rep = srv.slo.report(publish_gauges=False)["endpoints"]["predict"]
    assert rep["errors_by_reason"] == {"shed:queue_full": 1}


def test_queue_phase_span_under_contention(telemetry):
    srv = InferenceServer(predictor=_StubPredictor(service_time=0.05),
                          max_inflight=1, queue_depth=8).start()
    try:
        threads = [threading.Thread(
            target=lambda: InferenceClient(srv.address).predict(
                x=np.ones((1,), np.float32))) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        srv.shutdown()
    queue_spans = [e for e in trace.events()
                   if e["name"] == "serving.queue"]
    assert queue_spans  # somebody actually camped the queue
    assert queue_spans[0]["args"].get("request_id")


# --------------------------------------------------------------------------
# goodput partition on synthetic streams
# --------------------------------------------------------------------------

def _rec(wall_ms, n=1, compile=False):
    return {"phase": "step_stats", "wall_ms": wall_ms, "n_steps": n,
            "compile": compile}


def test_goodput_partition_categories():
    records = [_rec(1000.0, compile=True)] + [_rec(100.0)] * 10
    flight_events = [
        {"kind": "resilience.guard_skip", "t": 10.0},
        {"kind": "resilience.guard_rollback", "t": 11.0},
        {"kind": "resilience.retry", "t": 12.0, "delay": 0.5},
        {"kind": "resilience.drain_begin", "t": 20.0},
        {"kind": "resilience.drain_complete", "t": 20.25},
    ]
    rep = goodput.partition(records, flight_events, wall_s=4.0)
    assert rep["productive_s"] == pytest.approx(1.0)
    assert rep["lost"]["compile_s"] == pytest.approx(1.0)
    # 2 guard events x 100 ms median steady step
    assert rep["lost"]["rollback_s"] == pytest.approx(0.2)
    assert rep["lost"]["retry_s"] == pytest.approx(0.5)
    assert rep["lost"]["preemption_s"] == pytest.approx(0.25)
    assert rep["lost"]["other_s"] == pytest.approx(
        4.0 - 1.0 - 1.95, abs=1e-6)
    assert rep["productive_frac"] == pytest.approx(0.25)
    assert rep["lost_frac"] == pytest.approx(0.75)
    assert rep["steps"] == 10 and rep["rollback_events"] == 2


def test_goodput_without_wall_accounts_exactly():
    rep = goodput.partition([_rec(200.0), _rec(50.0, compile=True)])
    assert rep["wall_s"] == pytest.approx(0.25)
    assert rep["lost"]["other_s"] == 0.0
    assert rep["productive_frac"] == pytest.approx(0.8)


def test_goodput_publish_and_rows(telemetry):
    rep = goodput.partition([_rec(100.0)] * 4, wall_s=1.0)
    goodput.publish(rep)
    g = metrics.snapshot()["gauges"]
    assert g["goodput.productive_frac"] == pytest.approx(0.4)
    assert g["goodput.lost_s{category=other}"] == pytest.approx(0.6)
    rows = goodput.metric_rows(rep, degraded=True)
    assert [r["metric"] for r in rows] == ["goodput.productive_frac",
                                           "goodput.lost_frac"]
    assert all(r["degraded"] for r in rows)
    assert rows[1]["lower_better"] is True


def test_goodput_rows_gate_through_perf_gate(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_pg", os.path.join(REPO, "tools", "perf_gate.py"))
    pg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pg)

    rep = goodput.partition([_rec(100.0)] * 4, wall_s=1.0)
    results = tmp_path / "results.json"
    with open(results, "w") as f:
        for row in goodput.metric_rows(rep):
            f.write(json.dumps(row) + "\n")
        f.write(json.dumps({"metric": "demo_tokens", "value": 10.0,
                            "unit": "tok/s"}) + "\n")
    baseline = tmp_path / "base.jsonl"
    baseline.write_text(json.dumps(
        {"metric": "demo_tokens", "value": 10.0}) + "\n")
    # goodput rows are NEW (unbaselined): gate passes
    rc = pg.main([str(results), "--baseline", str(baseline),
                  "--static-budget", "", "--update"])
    assert rc == 0
    # after --update the baseline carries goodput rows and still
    # validates (--check-only: the acceptance hook)
    rc = pg.main(["--check-only", "--baseline", str(baseline),
                  "--static-budget", ""])
    assert rc == 0
    base = pg.load_baseline(str(baseline))
    assert "goodput.productive_frac" in base
    assert base["goodput.lost_frac"]["lower_better"] is True


# --------------------------------------------------------------------------
# exporter: schema, incremental shipping, digest
# --------------------------------------------------------------------------

def test_exporter_dump_schema_and_incremental(tmp_path, telemetry):
    metrics.inc("serving.requests", status="ok")
    with trace.span("work.a"):
        pass
    ex = export.TelemetryExporter(outdir=str(tmp_path), interval_s=999,
                                  host="h1", pid=101, rank=3)
    path = ex.dump_once()
    assert os.path.basename(path) == "telemetry_h1_101_r3.jsonl"
    with trace.span("work.b"):
        pass
    obs.flight.record("demo.event", detail=1)
    ex.dump_once(reason="final")
    entries = [json.loads(l) for l in open(path)]
    assert export.validate_telemetry_stream(entries) == []
    assert [e["seq"] for e in entries] == [1, 2]
    # incremental: the second dump ships only the NEW span + flight
    names1 = [e["name"] for e in entries[0]["trace_events"]]
    names2 = [e["name"] for e in entries[1]["trace_events"]]
    assert "work.a" in names1 and "work.a" not in names2
    assert "work.b" in names2
    assert [e["kind"] for e in entries[1]["flight_events"]] \
        == ["demo.event"]
    assert entries[0]["metrics"]["counters"][
        "serving.requests{status=ok}"] == 1
    d = ex.digest()
    assert d["requests"] == 1 and d["rank"] == 3

    # validator catches a seq regression
    bad = entries + [dict(entries[0], seq=1)]
    assert export.validate_telemetry_stream(bad)


def test_exporter_periodic_thread(tmp_path, telemetry):
    ex = export.TelemetryExporter(outdir=str(tmp_path), interval_s=0.05,
                                  host="h2", pid=202)
    ex.start()
    time.sleep(0.25)
    ex.stop()
    entries = [json.loads(l) for l in open(ex.path)]
    assert len(entries) >= 2  # periodic dumps plus the final one
    assert entries[-1]["reason"] == "final"
    assert export.validate_telemetry_stream(entries) == []


def test_analyze_chip_log_validates_telemetry_stream(tmp_path,
                                                     telemetry):
    ex = export.TelemetryExporter(outdir=str(tmp_path), interval_s=999,
                                  host="h3", pid=303)
    ex.dump_once()
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "analyze_chip_log.py"), ex.path],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "telemetry_dumps" in out.stdout
    # a corrupt line (wrong pid type) must fail the CI hook
    with open(ex.path, "a") as f:
        entry = json.loads(open(ex.path).readline())
        entry["pid"] = "not-an-int"
        entry["seq"] = 99
        f.write(json.dumps(entry) + "\n")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "analyze_chip_log.py"), ex.path],
        capture_output=True, text=True)
    assert out.returncode == 1


# --------------------------------------------------------------------------
# elastic: rank digests ride the heartbeat store
# --------------------------------------------------------------------------

class _DictStore:
    def __init__(self):
        self.d = {}

    def set(self, k, v):
        self.d[k] = v

    def get(self, k, timeout=None):
        return self.d[k]

    def check(self, k):
        return k in self.d


def test_elastic_heartbeat_carries_telemetry_digest():
    st = _DictStore()
    m = ElasticManager(store=st, job_id="tele", np_range="2",
                       heartbeat_interval=60.0)
    m.attach_telemetry(lambda: {"host": "h", "requests": 7})
    m._set_heartbeat()
    assert st.check("elastic/tele/telemetry/0")
    digs = m.telemetry_digests()
    assert digs[0]["requests"] == 7
    # a broken digest fn must not cost the beat
    m.attach_telemetry(lambda: (_ for _ in ()).throw(RuntimeError("x")))
    m._set_heartbeat()  # no raise
    assert st.check(m._hb_key())


# --------------------------------------------------------------------------
# aggregator: merge + rollup over synthetic per-process dumps
# --------------------------------------------------------------------------

def _dump_line(host, pid, seq, wall_epoch, trace_events,
               counters=None, hists=None, slo_ep=None, rank=None):
    line = {"phase": "telemetry_dump", "t": "2026-08-04T00:00:00",
            "schema": "telemetry_dump/v1", "host": host, "pid": pid,
            "rank": rank, "run_id": f"proc_{pid}", "seq": seq,
            "reason": "periodic", "wall": wall_epoch + 1.0,
            "trace_wall_epoch": wall_epoch,
            "trace_events": trace_events, "flight_events": [],
            "metrics": {"counters": counters or {}, "gauges": {},
                        "histograms": hists or {}}}
    if slo_ep is not None:
        line["slo"] = {"schema": "slo/v1", "window_s": 300.0,
                       "endpoints": {"predict": slo_ep}}
    return line


def _agg():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_tagg", os.path.join(REPO, "tools", "telemetry_agg.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_aggregator_merges_two_processes(tmp_path):
    agg = _agg()
    span = {"name": "client.predict", "cat": "client", "ph": "X",
            "ts": 100.0, "dur": 50.0, "pid": 11, "tid": 1,
            "args": {"request_id": "r-1"}}
    span2 = {"name": "serving.predict", "cat": "serving", "ph": "X",
             "ts": 10.0, "dur": 40.0, "pid": 22, "tid": 1,
             "args": {"request_id": "r-1"}}
    h1 = {"count": 2, "total": 30.0, "min": 10.0, "max": 20.0,
          "buckets": {"10": 1, "31.62": 1}}
    h2 = {"count": 2, "total": 300.0, "min": 100.0, "max": 200.0,
          "buckets": {"100": 1, "316.2": 1}}
    with open(tmp_path / "telemetry_a_11.jsonl", "w") as f:
        f.write(json.dumps(_dump_line(
            "a", 11, 1, 1000.0, [span],
            counters={"serving.requests{status=ok}": 2},
            hists={"serving.request_ms": h1},
            slo_ep={"requests": 10, "errors": 1,
                    "errors_by_reason": {"shed:queue_full": 1},
                    "objective": {"latency_target_ms": 100.0,
                                  "availability": 0.9,
                                  "error_budget": 0.1}})) + "\n")
    with open(tmp_path / "telemetry_b_22.jsonl", "w") as f:
        f.write(json.dumps(_dump_line(
            "b", 22, 1, 1002.0, [span2],
            counters={"serving.requests{status=ok}": 3},
            hists={"serving.request_ms": h2},
            slo_ep={"requests": 10, "errors": 3,
                    "errors_by_reason": {"shed:deadline": 3},
                    "objective": {"latency_target_ms": 100.0,
                                  "availability": 0.9,
                                  "error_budget": 0.1}})) + "\n")

    streams = agg.load_dumps(str(tmp_path))
    assert len(streams) == 2
    doc = agg.merge_timeline(streams)
    procs = doc["otherData"]["processes"]
    assert sorted(procs.values()) == ["a:11", "b:22"]
    by_name = {e["name"]: e for e in doc["traceEvents"]
               if e.get("ph") == "X"}
    # both processes' spans survive, joined by request_id
    assert by_name["client.predict"]["args"]["request_id"] == "r-1"
    assert by_name["serving.predict"]["args"]["request_id"] == "r-1"
    # pids remapped to the merged doc's stable ids (distinct tracks)
    assert by_name["client.predict"]["pid"] \
        != by_name["serving.predict"]["pid"]
    # wall-epoch re-basing: process b's epoch is 2 s later, so its
    # ts shifted by +2e6 us relative to its own clock
    assert by_name["serving.predict"]["ts"] == pytest.approx(
        10.0 + 2e6)
    assert by_name["client.predict"]["ts"] == pytest.approx(100.0)

    roll = agg.rollup(streams)
    assert roll["counters"]["serving.requests{status=ok}"] == 5
    merged_h = roll["histograms"]["serving.request_ms"]
    assert merged_h["count"] == 4
    assert merged_h["min"] == 10.0 and merged_h["max"] == 200.0
    assert sum(merged_h["buckets"].values()) == 4
    assert "p95" in merged_h
    ep = roll["slo"]["predict"]
    assert ep["requests"] == 20 and ep["errors"] == 4
    assert ep["errors_by_reason"] == {"shed:queue_full": 1,
                                      "shed:deadline": 3}
    # fleet error rate 0.2 over a 0.1 budget: burn 2x
    assert ep["burn_rate"] == pytest.approx(2.0)


def test_aggregator_cli_flags_schema_errors(tmp_path):
    agg = _agg()
    with open(tmp_path / "telemetry_x_1.jsonl", "w") as f:
        f.write(json.dumps({"phase": "telemetry_dump", "t": "x",
                            "schema": "telemetry_dump/v1"}) + "\n")
    rc = agg.main([str(tmp_path), "--quiet"])
    assert rc == 2


# --------------------------------------------------------------------------
# the two-process acceptance demo: client process + server subprocess,
# merged by tools/telemetry_agg.py into one request-correlated timeline
# --------------------------------------------------------------------------

_CHILD = r"""
import os, sys, time
import numpy as np
from paddle_tpu import observability as obs
from paddle_tpu.observability.export import TelemetryExporter
from paddle_tpu.inference.serving import InferenceServer

class Stub:
    def get_input_names(self): return ["x"]
    def get_output_names(self): return ["y"]
    def run(self, inputs):
        time.sleep(0.05)
        return [np.asarray(inputs[0])]

obs.attach(crash_hook=False)
srv = InferenceServer(predictor=Stub(), max_inflight=1,
                      queue_depth=8).start()
ex = TelemetryExporter(outdir=sys.argv[1], interval_s=999,
                       slo=srv.slo.report)
print(srv.address, flush=True)
sys.stdin.readline()
ex.dump_once(reason="final")
srv.shutdown()
print("done", flush=True)
"""


def test_two_process_demo_merged_timeline(tmp_path, telemetry):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PADDLE_TPU_METRICS="1", PADDLE_TPU_TRACE="1")
    child = subprocess.Popen(
        [sys.executable, "-c", _CHILD, str(tmp_path)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
        env=env, cwd=REPO)
    try:
        address = child.stdout.readline().strip()
        assert address.startswith("http://"), address

        client = InferenceClient(address, timeout=60.0)
        results = []

        def one(i):
            out = client.predict(x=np.full((2,), float(i), np.float32))
            results.append(out)

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 4

        with urllib.request.urlopen(address + "/metrics",
                                    timeout=30) as r:
            assert '_bucket{' in r.read().decode()
        with _post_npz(address, {"x": np.ones((1,), np.float32)},
                       headers=[("X-Request-Id", "demo-req")]) as r:
            assert r.headers["X-Request-Id"] == "demo-req"

        # wait for the server's final accounting (the handler books a
        # request AFTER its response bytes go out) before the child
        # snapshots its telemetry
        def _server_booked():
            with urllib.request.urlopen(address + "/debug/telemetry",
                                        timeout=30) as r:
                snap = json.loads(r.read())
            return snap["metrics"]["counters"].get(
                "serving.requests{status=ok}", 0) >= 5

        assert _wait_for(_server_booked, timeout=10.0)

        # client-side dump next to the server's
        ex = export.TelemetryExporter(outdir=str(tmp_path),
                                      interval_s=999, host="client")
        ex.dump_once(reason="final")
        child.stdin.write("\n")
        child.stdin.flush()
        assert child.stdout.readline().strip() == "done"
    finally:
        child.stdin.close()
        child.wait(timeout=60)

    agg = _agg()
    streams = agg.load_dumps(str(tmp_path))
    assert len(streams) == 2
    for _path, entries in streams:
        assert export.validate_telemetry_stream(entries) == []
    out = str(tmp_path / "merged.json")
    doc = agg.merge_timeline(streams)
    with open(out, "w") as f:
        json.dump(doc, f)
    procs = doc["otherData"]["processes"]
    assert len(procs) == 2

    # one request's spans appear on BOTH processes' tracks, joined by
    # request_id: the client attempt and the server-side phases
    spans_by_pid = {}
    for e in doc["traceEvents"]:
        if e.get("ph") == "X" and e.get("args", {}).get("request_id"):
            spans_by_pid.setdefault(e["pid"], {}).setdefault(
                e["args"]["request_id"], set()).add(e["name"])
    assert len(spans_by_pid) == 2
    (pid_a, reqs_a), (pid_b, reqs_b) = sorted(spans_by_pid.items())
    client_reqs = reqs_a if any("client.predict" in names
                                for names in reqs_a.values()) else reqs_b
    server_reqs = reqs_b if client_reqs is reqs_a else reqs_a
    shared = set(client_reqs) & set(server_reqs)
    assert shared  # same request ids on both tracks
    rid = sorted(shared)[0]
    assert "client.predict" in client_reqs[rid]
    assert {"serving.request", "serving.admission", "serving.predict",
            "serving.serialize"} <= server_reqs[rid]
    # under 4-way contention against max_inflight=1 somebody queued
    all_server_names = set().union(*server_reqs.values())
    assert "serving.queue" in all_server_names

    # fleet rollup sees both sides
    roll = agg.rollup(streams)
    assert roll["counters"].get(
        "serving.requests{status=ok}", 0) >= 5
    assert roll["counters"].get(
        "client.requests{status=ok}", 0) >= 4
    assert "predict" in roll["slo"]


# --------------------------------------------------------------------------
# schema: attach() pre-declares the serving/client status counters
# --------------------------------------------------------------------------

def test_attach_declares_request_status_schema(telemetry):
    counters = metrics.snapshot()["counters"]
    for s in ("ok", "client_error", "shed", "timeout", "error"):
        assert counters[f"serving.requests{{status={s}}}"] == 0
    for s in ("ok", "shed_retry", "error"):
        assert counters[f"client.requests{{status={s}}}"] == 0
