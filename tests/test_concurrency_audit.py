"""Layer 5 concurrency-auditor tests (PT501–PT505).

Same contract as test_analysis.py: every rule's firing condition is
pinned by one positive AND one negative fixture, the live serving
modules must audit clean (that IS the CI gate for this layer), and the
suppression round-trip (finding -> annotate -> clean) is exercised so
an annotation typo can't silently disarm the gate.
"""
import json
import os
import subprocess
import sys
import textwrap

from paddle_tpu.analysis import concurrency_audit as ca
from paddle_tpu.analysis import threadmodel as tm

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(violations):
    return {v.rule for v in violations}


def run(src):
    return ca.analyze_source(textwrap.dedent(src), "fix.py")


# ----------------------- PT501 blocking call under lock -----------------


PT501_POS = """
    import threading
    import time

    class Poller:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def poll(self):
            with self._lock:
                time.sleep(1.0)      # PT501: stall under the lock
                self._n += 1
"""

PT501_NEG = """
    import threading
    import time

    class Poller:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def poll(self):
            time.sleep(1.0)          # sleep BEFORE taking the lock
            with self._lock:
                self._n += 1
"""


def test_pt501_positive():
    v = [x for x in run(PT501_POS) if x.rule == "PT501"]
    assert len(v) == 1, run(PT501_POS)
    assert "time.sleep" in v[0].message and "_lock" in v[0].message


def test_pt501_negative():
    assert "PT501" not in rules_of(run(PT501_NEG))


PT501_INTERPROCEDURAL = """
    import threading
    import time

    class Monitor:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def step(self):
            with self._lock:
                self._refresh()      # PT501 at THIS call site

        def background(self):
            self._refresh()          # also called lock-free, so the
                                     # helper gets no propagated lock

        def _refresh(self):
            time.sleep(0.5)
            self._n = 1
"""


def test_pt501_interprocedural_one_level():
    v = [x for x in run(PT501_INTERPROCEDURAL) if x.rule == "PT501"]
    assert len(v) == 1, run(PT501_INTERPROCEDURAL)
    assert "_refresh" in v[0].message and "step" in v[0].message
    # anchored at step's call site, not inside the helper body
    assert v[0].line == PT501_INTERPROCEDURAL.count("\n", 0,
        PT501_INTERPROCEDURAL.index("# PT501 at THIS")) + 1


def test_pt501_timeouts_and_own_cv_wait_are_exempt():
    src = """
        import threading

        class Worker:
            def __init__(self):
                self._cv = threading.Condition()
                self._ready = False

            def wait_ready(self):
                with self._cv:
                    while not self._ready:
                        self._cv.wait(timeout=1.0)

            def join_child(self, t):
                with self._cv:
                    t.join(2.0)      # positional timeout: bounded
    """
    assert "PT501" not in rules_of(run(src))


# ----------------------- PT502 lock-order inversion ---------------------


PT502_POS = """
    import threading

    class Triple:
        def __init__(self):
            self._a_lock = threading.Lock()
            self._b_lock = threading.Lock()
            self._c_lock = threading.Lock()

        def ab(self):
            with self._a_lock:
                with self._b_lock:
                    pass

        def bc(self):
            with self._b_lock:
                with self._c_lock:
                    pass

        def ca(self):
            with self._c_lock:
                with self._a_lock:
                    pass
"""

PT502_NEG = """
    import threading

    class Triple:
        def __init__(self):
            self._a_lock = threading.Lock()
            self._b_lock = threading.Lock()
            self._c_lock = threading.Lock()

        def ab(self):
            with self._a_lock:
                with self._b_lock:
                    pass

        def bc(self):
            with self._b_lock:
                with self._c_lock:
                    pass

        def ac(self):
            with self._a_lock:      # consistent global order a<b<c
                with self._c_lock:
                    pass
"""


def test_pt502_three_lock_cycle():
    v = [x for x in run(PT502_POS) if x.rule == "PT502"]
    assert len(v) == 1, run(PT502_POS)
    for lk in ("_a_lock", "_b_lock", "_c_lock"):
        assert f"Triple.{lk}" in v[0].message


def test_pt502_consistent_order_clean():
    assert "PT502" not in rules_of(run(PT502_NEG))


def test_pt502_cross_class_edge():
    src = """
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self.owner = Owner()

            def put(self):
                with self._lock:
                    self.owner.flush()   # takes Owner._lock under ours

        class Owner:
            def __init__(self):
                self._lock = threading.Lock()
                self.store = Store()

            def flush(self):
                with self._lock:
                    pass

            def drain(self):
                with self._lock:
                    self.store.put()     # opposite order -> cycle
    """
    v = [x for x in run(src) if x.rule == "PT502"]
    assert len(v) == 1, run(src)
    assert "Store._lock" in v[0].message and "Owner._lock" in v[0].message


# ----------------------- PT503 unguarded cross-thread state -------------


PT503_POS = """
    import threading

    class Exporter:
        def __init__(self):
            self.stats = {}
            self._thread = None

        def start(self):
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True)
            self._thread.start()

        def _loop(self):
            self.stats["n"] = 1      # written on the loop thread

        def do_GET(self):            # second root: per-request handler
            body = self.stats
            return body
"""

PT503_NEG = """
    import threading

    class Exporter:
        def __init__(self):
            self._lock = threading.Lock()
            self.stats = {}
            self._thread = None

        def start(self):
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True)
            self._thread.start()

        def _loop(self):
            with self._lock:
                self.stats["n"] = 1

        def do_GET(self):
            with self._lock:
                body = dict(self.stats)
            return body
"""


def test_pt503_positive_http_handler_second_root():
    v = [x for x in run(PT503_POS) if x.rule == "PT503"]
    assert len(v) == 1, run(PT503_POS)
    assert "stats" in v[0].message
    assert "root:_loop" in v[0].message
    assert "root:<http-handler>" in v[0].message


def test_pt503_negative_guarded():
    assert "PT503" not in rules_of(run(PT503_NEG))


def test_pt503_handler_only_class_has_no_external_root():
    # a pure request-handler class: do_GET/do_POST run on per-request
    # handler INSTANCES, so same-instance attrs never race
    src = """
        class Handler:
            def do_GET(self):
                self.body = "x"

            def do_POST(self):
                self.body = "y"
    """
    assert "PT503" not in rules_of(run(src))


# ----------------------- PT504 guard drift ------------------------------


PT504_POS = """
    import threading

    class Split:
        def __init__(self):
            self._lock = threading.Lock()
            self._aux_lock = threading.Lock()
            self._n = 0

        def bump(self):
            with self._lock:
                self._n += 1

        def read(self):
            with self._aux_lock:     # PT504: different lock, same attr
                return self._n
"""

PT504_NEG = """
    import threading

    class Split:
        def __init__(self):
            self._lock = threading.Lock()
            self._aux_lock = threading.Lock()
            self._n = 0

        def bump(self):
            with self._lock:
                self._n += 1

        def read(self):
            with self._lock:
                return self._n
"""


def test_pt504_positive_disjoint_locks():
    v = [x for x in run(PT504_POS) if x.rule == "PT504"]
    assert len(v) == 1, run(PT504_POS)
    assert "_aux_lock" in v[0].message and "_lock" in v[0].message


def test_pt504_negative_same_lock():
    assert "PT504" not in rules_of(run(PT504_NEG))


def test_pt504_annotation_contradicts_inference():
    # the machine-read guard-claim grammar: a def-line ok[PT102]
    # "callers hold the lock" annotation is a CLAIM, and a call site
    # inference proves lock-free contradicts it — loudly
    src = """
        import threading

        class Ledger:
            def __init__(self):
                self._lock = threading.Lock()
                self._rows = {}

            def put(self, k, v):
                with self._lock:
                    self._entry(k)[0] = v

            def peek(self, k):
                return self._entry(k)   # no lock held here

            def _entry(self, k):  # pt-lint: ok[PT101,PT102] (callers hold _lock)
                if k not in self._rows:
                    self._rows[k] = [None]
                return self._rows[k]
    """
    v = [x for x in run(src) if x.rule == "PT504"]
    assert len(v) == 1, run(src)
    assert "peek" in v[0].message
    assert "contradicts inference" in v[0].message


def test_pt504_honoured_annotation_is_clean():
    src = """
        import threading

        class Ledger:
            def __init__(self):
                self._lock = threading.Lock()
                self._rows = {}

            def put(self, k, v):
                with self._lock:
                    self._entry(k)[0] = v

            def peek(self, k):
                with self._lock:
                    return self._entry(k)

            def _entry(self, k):  # pt-lint: ok[PT101,PT102] (callers hold _lock)
                if k not in self._rows:
                    self._rows[k] = [None]
                return self._rows[k]
    """
    assert rules_of(run(src)) == set()


# ----------------------- PT505 condition-variable misuse ----------------


PT505_POS_IF = """
    import threading

    class Gate:
        def __init__(self):
            self._cv = threading.Condition()
            self._open = False

        def pass_through(self):
            with self._cv:
                if not self._open:   # PT505: `if`, not `while`
                    self._cv.wait()
"""

PT505_POS_NOTIFY = """
    import threading

    class Gate:
        def __init__(self):
            self._cv = threading.Condition()
            self._open = False

        def release(self):
            self._open = True
            self._cv.notify_all()    # PT505: cv not held
"""

PT505_NEG = """
    import threading

    class Gate:
        def __init__(self):
            self._cv = threading.Condition()
            self._open = False

        def pass_through(self):
            with self._cv:
                while not self._open:
                    self._cv.wait()

        def release(self):
            with self._cv:
                self._open = True
                self._cv.notify_all()
"""


def test_pt505_wait_under_if_not_while():
    v = [x for x in run(PT505_POS_IF) if x.rule == "PT505"]
    assert len(v) == 1, run(PT505_POS_IF)
    assert "spurious wakeups" in v[0].message


def test_pt505_notify_without_cv_held():
    v = [x for x in run(PT505_POS_NOTIFY) if x.rule == "PT505"]
    assert len(v) == 1, run(PT505_POS_NOTIFY)
    assert "notify_all" in v[0].message


def test_pt505_negative():
    assert "PT505" not in rules_of(run(PT505_NEG))


# ----------------------- inference internals ----------------------------


def test_threadmodel_condition_aliasing():
    src = textwrap.dedent("""
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)
    """)
    fm = tm.build_file_model(src, "fix.py")
    (cls,) = fm.classes
    assert cls.canon("_cv") == "_lock"
    assert cls.holds({"_cv"}, "_lock")


def test_threadmodel_construction_only_helpers():
    src = textwrap.dedent("""
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._setup()

            def _setup(self):
                self._n = 0
    """)
    fm = tm.build_file_model(src, "fix.py")
    (cls,) = fm.classes
    assert "_setup" in cls.construction_only


def test_threadmodel_locked_suffix_presumes_sole_lock():
    src = textwrap.dedent("""
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def bump(self):
                with self._lock:
                    self._bump_locked()

            def _bump_locked(self):
                self._n += 1
    """)
    fm = tm.build_file_model(src, "fix.py")
    (cls,) = fm.classes
    tm.apply_presumed_locks(cls)
    assert cls.presumed["_bump_locked"] == frozenset({"_lock"})


# ----------------------- suppression round-trip -------------------------


def test_suppression_round_trip_finding_annotate_clean():
    dirty = """
        import threading
        import time

        class Poller:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def poll(self):
                with self._lock:
                    time.sleep(1.0)
                    self._n += 1
    """
    assert "PT501" in rules_of(run(dirty))
    annotated = dirty.replace(
        "time.sleep(1.0)",
        "time.sleep(1.0)  # pt-lint: ok[PT501] (test-only stub)")
    assert "PT501" not in rules_of(run(annotated))
    # the annotation is rule-scoped: it must NOT disarm other rules
    wrong_rule = dirty.replace(
        "time.sleep(1.0)",
        "time.sleep(1.0)  # pt-lint: ok[PT503] (wrong rule id)")
    assert "PT501" in rules_of(run(wrong_rule))


# ----------------------- live serving modules audit clean ---------------


def test_live_serving_modules_audit_clean():
    """The modules the ISSUE names: router, fleet, scheduler (engine),
    autoscaler, overload/QoS — plus observability.  Zero unsuppressed
    PT501–PT505 findings, with the baseline EMPTY."""
    files = [
        "paddle_tpu/inference/router.py",
        "paddle_tpu/inference/fleet.py",
        "paddle_tpu/inference/autoscaler.py",
        "paddle_tpu/inference/qos.py",
        "paddle_tpu/inference/serving.py",
        "paddle_tpu/inference/engine/engine.py",
        "paddle_tpu/observability/export.py",
        "paddle_tpu/observability/timeseries.py",
    ]
    for rel in files:
        assert os.path.exists(os.path.join(REPO, rel)), rel
    v = ca.analyze_files(
        [(os.path.join(REPO, rel), rel) for rel in files])
    assert v == [], "\n".join(
        f"{x.file}:{x.line} {x.rule} {x.message}" for x in v)


def test_whole_program_audit_clean():
    v = ca.analyze_project(REPO)
    assert v == [], "\n".join(
        f"{x.file}:{x.line} {x.rule} {x.message}" for x in v)


def test_baseline_is_empty():
    with open(os.path.join(REPO, "tools", "lint_baseline.json")) as f:
        baseline = json.load(f)
    assert baseline.get("counts") == {}


# ----------------------- CLI integration --------------------------------


def test_cli_conc_in_default_check_layers():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "pt_lint.py"),
         "--check", "--layers", "ast,lock,conc"],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "conc" in proc.stdout


def test_cli_select_and_emit_json(tmp_path):
    out = tmp_path / "findings.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "pt_lint.py"),
         "--layers", "conc",
         "--select", "PT501,PT502,PT503,PT504,PT505",
         "--emit", str(out)],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rows = json.loads(out.read_text())
    assert rows == []  # the tree is clean; the file must still exist


def test_conc_gate_catches_new_violation_in_synthetic_tree(tmp_path):
    """The gate wiring end-to-end on a synthetic repo root: a PT501
    under paddle_tpu/ surfaces through analyze_repo(layers=("conc",))
    and diffs as NEW against an empty baseline."""
    import paddle_tpu.analysis as A

    pkg = tmp_path / "paddle_tpu"
    pkg.mkdir()
    (tmp_path / "tools").mkdir()
    (pkg / "bad.py").write_text(textwrap.dedent("""
        import threading
        import time

        class Stall:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def tick(self):
                with self._lock:
                    time.sleep(1.0)
                    self._n += 1
    """))
    v = A.analyze_repo(str(tmp_path), layers=("conc",))
    assert rules_of(v) == {"PT501"}, A.render_report(v)
    new, known, stale = A.diff_against_baseline(v, {})
    assert len(new) == 1 and not known and not stale
