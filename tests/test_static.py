"""Static-graph API: program build, Executor.run, minimize, inference save.

Parity model: the reference's static tests (`test/legacy_test/` Executor
paths, SURVEY §3.4) — build program with static.data + layers, run feeds,
train with minimize, freeze with save_inference_model.
"""
import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu import static
from paddle_tpu.core.export_compat import jax_export_available

requires_jax_export = pytest.mark.skipif(
    not jax_export_available(),
    reason="jax.export unavailable in this jax build")


@pytest.fixture(autouse=True)
def _static_mode():
    static.reset_default_programs()
    P.enable_static()
    yield
    P.disable_static()
    static.reset_default_programs()


def test_build_and_run_forward():
    x = static.data("x", [-1, 4], "float32")
    y = P.matmul(x, P.ones([4, 3]))
    z = P.add(y, P.full([3], 1.0))
    exe = static.Executor()
    exe.run(static.default_startup_program())
    xv = np.random.rand(2, 4).astype(np.float32)
    (out,) = exe.run(feed={"x": xv}, fetch_list=[z])
    np.testing.assert_allclose(out, xv @ np.ones((4, 3)) + 1.0, rtol=1e-6)
    # second run with a different batch size: separate compile, same program
    xv8 = np.random.rand(8, 4).astype(np.float32)
    (out8,) = exe.run(feed={"x": xv8}, fetch_list=[z])
    assert out8.shape == (8, 3)


def test_variable_properties():
    x = static.data("img", [-1, 1, 28, 28], "float32")
    # reference parity: symbolic (batch) dims surface as -1 — reading the
    # internal placeholder 1 as a concrete batch size would bake it in
    assert x.shape == [-1, 1, 28, 28]
    assert x.declared_shape == [-1, 1, 28, 28]
    with pytest.raises(RuntimeError):
        x.numpy()


def test_layers_record_and_minimize():
    import paddle_tpu.nn as nn

    x = static.data("x", [4, 8], "float32")
    label = static.data("label", [4, 1], "float32")
    lin = nn.Linear(8, 1)
    pred = lin(x)
    loss = P.mean(P.square(P.subtract(pred, label)))
    opt = P.optimizer.SGD(learning_rate=0.1,
                          parameters=list(lin.parameters()))
    opt.minimize(loss)

    exe = static.Executor()
    exe.run(static.default_startup_program())
    rng = np.random.RandomState(0)
    xv = rng.rand(4, 8).astype(np.float32)
    yv = (xv.sum(1, keepdims=True) * 0.5).astype(np.float32)
    losses = []
    for _ in range(30):
        (lv,) = exe.run(feed={"x": xv, "label": yv}, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.1, losses[:3] + losses[-3:]


def test_adam_static_matches_eager():
    import paddle_tpu.nn as nn

    # static
    w0 = np.random.RandomState(1).rand(6, 2).astype(np.float32)
    x = static.data("x", [5, 6], "float32")
    lin = nn.Linear(6, 2)
    lin.weight.set_value(w0)
    lin.bias.set_value(np.zeros(2, np.float32))
    loss = P.mean(P.square(lin(x)))
    opt = P.optimizer.Adam(learning_rate=0.01,
                           parameters=list(lin.parameters()))
    opt.minimize(loss)
    exe = static.Executor()
    xv = np.random.RandomState(2).rand(5, 6).astype(np.float32)
    static_losses = [float(exe.run(feed={"x": xv}, fetch_list=[loss])[0])
                     for _ in range(5)]

    # eager twin
    P.disable_static()
    lin2 = nn.Linear(6, 2)
    lin2.weight.set_value(w0)
    lin2.bias.set_value(np.zeros(2, np.float32))
    opt2 = P.optimizer.Adam(learning_rate=0.01,
                            parameters=list(lin2.parameters()))
    eager_losses = []
    xt = P.to_tensor(xv)
    for _ in range(5):
        l2 = P.mean(P.square(lin2(xt)))
        eager_losses.append(float(l2.numpy()))
        l2.backward()
        opt2.step()
        opt2.clear_grad()
    np.testing.assert_allclose(static_losses, eager_losses, rtol=1e-4)


def test_append_backward_grads():
    x = static.data("x", [3, 4], "float32")
    w = P.create_parameter([4, 2], "float32")
    loss = P.sum(P.matmul(x, w))
    pairs = static.append_backward(loss)
    assert len(pairs) >= 1
    exe = static.Executor()
    xv = np.ones((3, 4), np.float32)
    grads = exe.run(feed={"x": xv}, fetch_list=[g for _, g in pairs])
    # d(sum(x@w))/dw = x^T @ ones = column sums broadcast
    np.testing.assert_allclose(grads[0], np.full((4, 2), 3.0), rtol=1e-6)


@requires_jax_export
def test_save_load_inference_model(tmp_path):
    import paddle_tpu.nn as nn

    x = static.data("x", [-1, 4], "float32")
    lin = nn.Linear(4, 3)
    out = nn.functional.softmax(lin(x))
    exe = static.Executor()
    prefix = str(tmp_path / "model")
    static.save_inference_model(prefix, [x], [out], exe)

    prog, feeds, fetches = static.load_inference_model(prefix, exe)
    xv = np.random.rand(2, 4).astype(np.float32)
    (ref,) = exe.run(feed={"x": xv}, fetch_list=[out])
    (got,) = exe.run(prog, feed={"x": xv})
    np.testing.assert_allclose(got, ref, rtol=1e-5)


@requires_jax_export
def test_inference_predictor(tmp_path):
    import paddle_tpu.nn as nn
    from paddle_tpu import inference

    x = static.data("x", [-1, 4], "float32")
    lin = nn.Linear(4, 3)
    out = lin(x)
    exe = static.Executor()
    prefix = str(tmp_path / "pred")
    static.save_inference_model(prefix, [x], [out], exe)

    cfg = inference.Config(prefix)
    predictor = inference.create_predictor(cfg)
    assert predictor.get_input_names() == ["x"]
    h = predictor.get_input_handle("x")
    xv = np.random.rand(2, 4).astype(np.float32)
    h.copy_from_cpu(xv)
    predictor.run()
    got = predictor.get_output_handle(
        predictor.get_output_names()[0]).copy_to_cpu()
    (ref,) = exe.run(feed={"x": xv}, fetch_list=[out])
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_static_dropout_resamples_per_run():
    import paddle_tpu.nn as nn

    x = static.data("x", [4, 8], "float32")
    y = nn.functional.dropout(x, 0.5, training=True)
    exe = static.Executor()
    xv = np.ones((4, 8), np.float32)
    a = exe.run(feed={"x": xv}, fetch_list=[y])[0]
    b = exe.run(feed={"x": xv}, fetch_list=[y])[0]
    assert not np.array_equal(a, b)


def test_program_guard_isolation():
    main1 = static.Program()
    with static.program_guard(main1):
        a = static.data("a", [2, 2], "float32")
        b = P.scale(a, 2.0)
    assert static.default_main_program() is not main1
    exe = static.Executor()
    (r,) = exe.run(main1, feed={"a": np.eye(2, dtype=np.float32)},
                   fetch_list=[b])
    np.testing.assert_allclose(r, 2 * np.eye(2))


def test_static_surface_complete_vs_reference():
    import ast
    import os

    ref = "/root/reference/python/paddle/static/__init__.py"
    if not os.path.exists(ref):
        pytest.skip("reference not mounted")

    def ref_all(path):
        for node in ast.walk(ast.parse(open(path).read())):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "__all__":
                        return [e.value for e in node.value.elts
                                if isinstance(e, ast.Constant)]
        return []

    missing = [n for n in ref_all(ref) if not hasattr(static, n)]
    assert not missing, f"static missing: {missing}"
    nn_ref = "/root/reference/python/paddle/static/nn/__init__.py"
    missing = [n for n in ref_all(nn_ref) if not hasattr(static.nn, n)]
    assert not missing, f"static.nn missing: {missing}"


def test_static_save_load_and_ema(tmp_path):
    import paddle_tpu.nn as nn

    P.enable_static()
    try:
        static.reset_default_programs()
        x = static.data("x", [-1, 4], "float32")
        lin = nn.Linear(4, 2)
        out = lin(x)
        prog = static.default_main_program()
        w0 = lin.weight.numpy().copy()
        p = static.save(prog, str(tmp_path / "m"))
        lin.weight.set_value(np.zeros_like(w0))
        static.load(prog, str(tmp_path / "m"))
        np.testing.assert_allclose(lin.weight.numpy(), w0)

        # program state helpers round-trip too
        st = static.load_program_state(str(tmp_path / "m"))
        lin.weight.set_value(np.zeros_like(w0))
        static.set_program_state(prog, st)
        np.testing.assert_allclose(lin.weight.numpy(), w0)

        # EMA: after updates, apply swaps averaged weights in
        ema = static.ExponentialMovingAverage(decay=0.5)
        ema.update()
        lin.weight.set_value(w0 * 3)
        ema.update()
        with ema.apply():
            avg = lin.weight.numpy()
            assert not np.allclose(avg, w0 * 3)
        np.testing.assert_allclose(lin.weight.numpy(), w0 * 3)
    finally:
        P.disable_static()
        static.reset_default_programs()


def test_static_nn_control_flow_and_pyfunc():
    # eager-mode cond/case/switch_case/while_loop
    t = P.to_tensor(np.float32(1.0))
    out = static.nn.cond(t > 0, lambda: P.ones([2]), lambda: P.zeros([2]))
    np.testing.assert_allclose(out.numpy(), 1.0)
    out = static.nn.case([(t > 5, lambda: P.zeros([1]))],
                         default=lambda: P.ones([1]))
    np.testing.assert_allclose(out.numpy(), 1.0)
    out = static.nn.switch_case(P.to_tensor(np.int32(1)),
                                {0: lambda: P.zeros([1]),
                                 1: lambda: P.ones([1])})
    np.testing.assert_allclose(out.numpy(), 1.0)
    i, = static.nn.while_loop(lambda i: i < 5, lambda i: (i + 2,),
                              [P.to_tensor(np.float32(0))])
    assert float(i.numpy()) == 6.0
    # LoD sequence ops gate loudly
    with pytest.raises(NotImplementedError):
        static.nn.sequence_pool(None, "sum")
