"""paddle_tpu.sparse: COO/CSR ops vs dense NumPy reference + grads.

Parity model: reference sparse tests (`test/legacy_test/test_sparse_*.py`)
— construct, convert, op, compare against the dense computation.
"""
import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu import sparse


def _rand_coo(shape=(4, 5), nnz=6, seed=0):
    rng = np.random.RandomState(seed)
    flat = rng.choice(shape[0] * shape[1], size=nnz, replace=False)
    idx = np.stack(np.unravel_index(flat, shape)).astype(np.int64)
    vals = rng.randn(nnz).astype(np.float32)
    return idx, vals


def test_coo_roundtrip():
    idx, vals = _rand_coo()
    s = sparse.sparse_coo_tensor(idx, vals, [4, 5])
    d = s.to_dense().numpy()
    ref = np.zeros((4, 5), np.float32)
    ref[idx[0], idx[1]] = vals
    np.testing.assert_allclose(d, ref)
    s2 = P.to_tensor(ref).to_sparse_coo(2)
    np.testing.assert_allclose(s2.to_dense().numpy(), ref)
    assert s.is_sparse_coo() and not s.is_sparse_csr()


def test_csr_roundtrip():
    idx, vals = _rand_coo()
    s = sparse.sparse_coo_tensor(idx, vals, [4, 5]).to_sparse_csr()
    assert s.is_sparse_csr()
    ref = np.zeros((4, 5), np.float32)
    ref[idx[0], idx[1]] = vals
    np.testing.assert_allclose(s.to_dense().numpy(), ref)
    coo_back = s.to_sparse_coo()
    np.testing.assert_allclose(coo_back.to_dense().numpy(), ref)


def test_unary_ops_and_grad():
    idx, vals = _rand_coo(seed=1)
    s = sparse.sparse_coo_tensor(idx, np.abs(vals) + 0.5, [4, 5],
                                 stop_gradient=False)
    out = sparse.sqrt(s)
    ref = np.zeros((4, 5), np.float32)
    ref[idx[0], idx[1]] = np.sqrt(np.abs(vals) + 0.5)
    np.testing.assert_allclose(out.to_dense().numpy(), ref, rtol=1e-6)
    # grad flows to values
    loss = P.sum(out.values())
    loss.backward()
    g = s.grad.numpy()
    np.testing.assert_allclose(g, 0.5 / np.sqrt(np.abs(vals) + 0.5),
                               rtol=1e-5)


def test_binary_add_union_pattern():
    a = sparse.sparse_coo_tensor([[0, 1], [0, 1]], [1.0, 2.0], [2, 2])
    b = sparse.sparse_coo_tensor([[0, 1], [1, 1]], [10.0, 20.0], [2, 2])
    c = sparse.add(a, b)
    np.testing.assert_allclose(
        c.to_dense().numpy(), [[1.0, 10.0], [0.0, 22.0]])


def test_spmm_vs_dense_and_grad():
    idx, vals = _rand_coo((4, 5), 7, seed=2)
    s = sparse.sparse_coo_tensor(idx, vals, [4, 5], stop_gradient=False)
    dense = P.to_tensor(np.random.RandomState(3).rand(5, 3).astype(
        np.float32), stop_gradient=False)
    out = sparse.matmul(s, dense)
    ref = np.zeros((4, 5), np.float32)
    ref[idx[0], idx[1]] = vals
    np.testing.assert_allclose(out.numpy(), ref @ dense.numpy(), rtol=1e-5)
    P.sum(out).backward()
    assert s.grad is not None and dense.grad is not None
    np.testing.assert_allclose(dense.grad.numpy(),
                               ref.T @ np.ones((4, 3), np.float32),
                               rtol=1e-5)


def test_masked_matmul_sddmm():
    rng = np.random.RandomState(4)
    x = rng.rand(4, 6).astype(np.float32)
    y = rng.rand(6, 5).astype(np.float32)
    idx, _ = _rand_coo((4, 5), 6, seed=5)
    mask = sparse.sparse_coo_tensor(idx, np.ones(6, np.float32), [4, 5])
    out = sparse.masked_matmul(P.to_tensor(x), P.to_tensor(y), mask)
    full = x @ y
    np.testing.assert_allclose(
        np.asarray(out.values().numpy()), full[idx[0], idx[1]], rtol=1e-5)


def test_csr_softmax_rows():
    idx, vals = _rand_coo((4, 5), 8, seed=6)
    csr = sparse.sparse_coo_tensor(idx, vals, [4, 5]).to_sparse_csr()
    out = sparse.softmax(csr)
    dense = csr.to_dense().numpy()
    # reference: softmax over nonzero entries per row
    ref = np.zeros_like(dense)
    for i in range(4):
        nz = dense[i] != 0
        if nz.any():
            e = np.exp(dense[i][nz] - dense[i][nz].max())
            ref[i][nz] = e / e.sum()
    np.testing.assert_allclose(out.to_dense().numpy(), ref, rtol=1e-5)


def test_coalesce_sums_duplicates():
    s = sparse.sparse_coo_tensor([[0, 0, 1], [1, 1, 0]], [1.0, 2.0, 3.0],
                                 [2, 2])
    c = s.coalesce()
    assert c.nnz == 2
    np.testing.assert_allclose(c.to_dense().numpy(), [[0, 3.0], [3.0, 0]])


def test_sparse_nn_layers():
    idx, vals = _rand_coo((4, 5), 6, seed=7)
    s = sparse.sparse_coo_tensor(idx, vals, [4, 5])
    out = sparse.nn.ReLU()(s)
    np.testing.assert_allclose(out.to_dense().numpy(),
                               np.maximum(s.to_dense().numpy(), 0))


def test_subm_conv3d_keeps_pattern():
    rng = np.random.RandomState(8)
    dense = np.zeros((1, 4, 4, 4, 2), np.float32)
    dense[0, 1, 1, 1] = rng.rand(2)
    dense[0, 2, 3, 0] = rng.rand(2)
    s = P.to_tensor(dense).to_sparse_coo(4)
    conv = sparse.nn.SubmConv3D(2, 3, 3, padding=1)
    out = conv(s)
    assert out.dense_shape == (1, 4, 4, 4, 3)
    np.testing.assert_array_equal(np.asarray(out.indices_arr),
                                  np.asarray(s.indices_arr))
