"""Top-level API surface: summary/flops, version, places, iinfo/finfo,
static AMP."""
import numpy as np

import paddle_tpu as P
import paddle_tpu.nn as nn


def test_summary_counts_params(capsys):
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    info = P.summary(m, (1, 8))
    assert info["total_params"] == 8 * 16 + 16 + 16 * 4 + 4
    out = capsys.readouterr().out
    assert "Linear" in out and "Total params" in out


def test_flops_linear():
    m = nn.Linear(8, 16)
    n = P.flops(m, (4, 8))
    assert n == 8 * 16 * 4  # MACs per sample * batch


def test_version_and_places():
    assert P.version.full_version == P.__version__
    assert "cpu" in repr(P.CPUPlace())
    assert "tpu" in repr(P.CUDAPlace(0))
    assert P.get_cudnn_version() is None


def test_iinfo_finfo():
    assert P.iinfo("int32").max == 2**31 - 1
    assert P.finfo("float32").dtype == np.float32
    assert P.finfo("bfloat16").bits == 16


def test_static_amp_autocast_records_casts():
    from paddle_tpu import amp, static

    static.reset_default_programs()
    P.enable_static()
    try:
        x = static.data("x", [4, 8], "float32")
        lin = nn.Linear(8, 8)
        with amp.auto_cast():
            y = P.matmul(x, lin.weight)
        exe = static.Executor()
        (out,) = exe.run(feed={"x": np.ones((4, 8), np.float32)},
                         fetch_list=[y], return_numpy=False)
        assert "bfloat16" in str(out.dtype)
    finally:
        P.disable_static()
        static.reset_default_programs()
