"""Top-level API surface: summary/flops, version, places, iinfo/finfo,
static AMP."""
import numpy as np
import pytest

import paddle_tpu as P
import paddle_tpu.nn as nn


def test_summary_counts_params(capsys):
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    info = P.summary(m, (1, 8))
    assert info["total_params"] == 8 * 16 + 16 + 16 * 4 + 4
    out = capsys.readouterr().out
    assert "Linear" in out and "Total params" in out


def test_flops_linear():
    m = nn.Linear(8, 16)
    n = P.flops(m, (4, 8))
    assert n == 8 * 16 * 4  # MACs per sample * batch


def test_version_and_places():
    assert P.version.full_version == P.__version__
    assert "cpu" in repr(P.CPUPlace())
    assert "tpu" in repr(P.CUDAPlace(0))
    assert P.get_cudnn_version() is None


def test_iinfo_finfo():
    assert P.iinfo("int32").max == 2**31 - 1
    assert P.finfo("float32").dtype == np.float32
    assert P.finfo("bfloat16").bits == 16


def test_static_amp_autocast_records_casts():
    from paddle_tpu import amp, static

    static.reset_default_programs()
    P.enable_static()
    try:
        x = static.data("x", [4, 8], "float32")
        lin = nn.Linear(8, 8)
        with amp.auto_cast():
            y = P.matmul(x, lin.weight)
        exe = static.Executor()
        (out,) = exe.run(feed={"x": np.ones((4, 8), np.float32)},
                         fetch_list=[y], return_numpy=False)
        assert "bfloat16" in str(out.dtype)
    finally:
        P.disable_static()
        static.reset_default_programs()


def test_functional_surface_complete_vs_reference():
    """Every name in the reference nn.functional __all__ resolves here."""
    import ast
    import os

    ref = "/root/reference/python/paddle/nn/functional/__init__.py"
    if not os.path.exists(ref):
        pytest.skip("reference not mounted")
    names = []
    for node in ast.walk(ast.parse(open(ref).read())):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    names = [e.value for e in node.value.elts
                             if isinstance(e, ast.Constant)]
    missing = [n for n in names if not hasattr(P.nn.functional, n)]
    assert not missing, f"nn.functional missing: {missing}"


def test_new_functionals_behave():
    import paddle_tpu.nn.functional as F

    rs = np.random.RandomState(0)
    a = rs.randn(3, 4).astype(np.float32)
    b = rs.randn(3, 4).astype(np.float32)
    np.testing.assert_allclose(
        F.pairwise_distance(P.to_tensor(a), P.to_tensor(b),
                            epsilon=0.0).numpy(),
        np.linalg.norm(a - b, axis=-1), rtol=1e-5)

    x = rs.randn(1, 1, 2, 2).astype(np.float32)
    out = F.zeropad2d(P.to_tensor(x), [1, 2, 3, 4])
    assert out.shape == [1, 1, 2 + 3 + 4, 2 + 1 + 2]

    # inplace activation twins
    t = P.to_tensor(a.copy())
    F.tanh_(t)
    np.testing.assert_allclose(t.numpy(), np.tanh(a), rtol=1e-5)

    # dice loss: perfect prediction -> ~0
    import jax

    lbl = rs.randint(0, 3, (4, 1)).astype(np.int64)
    perfect = np.eye(3, dtype=np.float32)[lbl[:, 0]]
    v = float(F.dice_loss(P.to_tensor(perfect),
                          P.to_tensor(lbl)).numpy())
    assert v < 1e-3

    # gaussian_nll_loss matches the formula
    mu = rs.randn(5).astype(np.float32)
    y = rs.randn(5).astype(np.float32)
    var = (rs.rand(5).astype(np.float32) + 0.5)
    got = float(F.gaussian_nll_loss(P.to_tensor(mu), P.to_tensor(y),
                                    P.to_tensor(var)).numpy())
    ref = np.mean(0.5 * (np.log(var) + (y - mu) ** 2 / var))
    np.testing.assert_allclose(got, ref, rtol=1e-5)

    # multi_margin_loss basic ordering: correct-confident < wrong
    logits_good = np.array([[5.0, 0.0, 0.0]], np.float32)
    logits_bad = np.array([[0.0, 5.0, 0.0]], np.float32)
    lab = np.array([[0]], np.int64)
    lg = float(F.multi_margin_loss(P.to_tensor(logits_good),
                                   P.to_tensor(lab)).numpy())
    lb = float(F.multi_margin_loss(P.to_tensor(logits_bad),
                                   P.to_tensor(lab)).numpy())
    assert lg < lb

    # hsigmoid_loss runs + grads flow
    x = P.to_tensor(rs.randn(4, 6).astype(np.float32),
                    stop_gradient=False)
    w = P.to_tensor(rs.randn(9, 6).astype(np.float32))
    lbl10 = P.to_tensor(rs.randint(0, 10, (4, 1)).astype(np.int64))
    loss = F.hsigmoid_loss(x, lbl10, 10, w)
    loss.backward()
    assert np.isfinite(float(loss.numpy()))
    assert x.grad is not None

    # triplet_margin_with_distance_loss: satisfied triplet -> 0
    anch = P.to_tensor(np.zeros((2, 3), np.float32))
    pos = P.to_tensor(np.zeros((2, 3), np.float32))
    neg = P.to_tensor(np.ones((2, 3), np.float32) * 10)
    v = float(F.triplet_margin_with_distance_loss(anch, pos, neg).numpy())
    assert v == 0.0

    # gather_tree follows parent pointers
    ids = np.array([[[2, 5]], [[3, 6]]], np.int32)      # T=2, B=1, W=2
    par = np.array([[[0, 0]], [[1, 0]]], np.int32)
    out = F.gather_tree(P.to_tensor(ids), P.to_tensor(par)).numpy()
    # beam 0 at t=1 came from parent 1 -> t=0 token is ids[0,0,1]=5
    assert out[0, 0, 0] == 5 and out[1, 0, 0] == 3

    # sparse_attention with a full pattern == dense attention
    B, H, S, D = 1, 2, 4, 8
    q = rs.randn(B, H, S, D).astype(np.float32)
    k = rs.randn(B, H, S, D).astype(np.float32)
    vv = rs.randn(B, H, S, D).astype(np.float32)
    offset = np.tile(np.arange(0, (S + 1) * S, S,
                               dtype=np.int32)[:S + 1], (B, H, 1))
    columns = np.tile(np.tile(np.arange(S, dtype=np.int32), S),
                      (B, H, 1))
    out = F.sparse_attention(P.to_tensor(q), P.to_tensor(k),
                             P.to_tensor(vv), P.to_tensor(offset),
                             P.to_tensor(columns)).numpy()
    logits = np.einsum("bhsd,bhtd->bhst", q, k) / np.sqrt(D)
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    ref_out = np.einsum("bhst,bhtd->bhsd", probs, vv)
    np.testing.assert_allclose(out, ref_out, rtol=1e-4, atol=1e-5)


def test_top_level_surface_complete_vs_reference():
    """Every name in the reference paddle __all__ resolves at top level."""
    import ast
    import os

    ref = "/root/reference/python/paddle/__init__.py"
    if not os.path.exists(ref):
        pytest.skip("reference not mounted")
    names = []
    for node in ast.walk(ast.parse(open(ref).read())):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    names = [e.value for e in node.value.elts
                             if isinstance(e, ast.Constant)]
    missing = [n for n in names if not hasattr(P, n)]
    assert not missing, f"paddle.* missing: {missing}"


def test_top_level_additions_behave():
    rs = np.random.RandomState(0)
    # unfold (tensor sliding windows, window dim last)
    x = np.arange(10, dtype=np.float32)
    out = P.unfold(P.to_tensor(x), 0, 4, 2).numpy()
    assert out.shape == (4, 4)
    np.testing.assert_allclose(out[1], x[2:6])
    # pdist == condensed distance matrix
    a = rs.rand(5, 3).astype(np.float32)
    got = P.pdist(P.to_tensor(a)).numpy()
    iu = np.triu_indices(5, k=1)
    ref = np.linalg.norm(a[:, None] - a[None, :], axis=-1)[iu]
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
    # column/row stack
    c = P.column_stack([P.to_tensor(x[:4]), P.to_tensor(x[4:8])])
    assert c.shape == [4, 2]
    # randint_like respects shape
    r = P.randint_like(P.to_tensor(np.zeros((3, 2), np.int32)), 0, 9)
    assert r.shape == [3, 2]
    # inplace twins
    t = P.to_tensor(np.array([1.0, 2.0], np.float32))
    P.square_(t)
    np.testing.assert_allclose(t.numpy(), [1.0, 4.0])
    # batch combinator
    batches = list(P.batch(lambda: iter(range(7)), 3)())
    assert [len(b) for b in batches] == [3, 3, 1]


def test_lbfgs_and_rprop_converge():
    import paddle_tpu.nn as nn

    P.seed(0)
    rs = np.random.RandomState(0)
    xs = rs.randn(32, 3).astype(np.float32)
    w_true = np.array([[1.5], [-2.0], [0.5]], np.float32)
    ys = xs @ w_true

    lin = nn.Linear(3, 1)
    opt = P.optimizer.LBFGS(parameters=lin.parameters(), max_iter=10)

    def closure():
        loss = ((lin(P.to_tensor(xs)) - P.to_tensor(ys)) ** 2).mean()
        loss.backward()
        return loss

    final = opt.step(closure)
    assert final < 1e-3, final

    lin2 = nn.Linear(3, 1)
    opt2 = P.optimizer.Rprop(learning_rate=0.01,
                             parameters=lin2.parameters())
    losses = []
    for _ in range(30):
        loss = ((lin2(P.to_tensor(xs)) - P.to_tensor(ys)) ** 2).mean()
        loss.backward()
        opt2.step()
        opt2.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.2


def test_beam_search_decoder():
    import paddle_tpu.nn as nn

    P.seed(0)

    class ToyCell(nn.Layer):
        """Deterministic 'cell': logits favor (prev_id + 1) mod V."""

        def __init__(self, v):
            super().__init__()
            self.v = v
            self.lin = nn.Linear(1, v)

        def forward(self, inp, states):
            ids = P.cast(inp.squeeze(-1), "int32")
            import jax.numpy as jnp

            nxt = (ids._value + 1) % self.v
            import jax

            logits = jax.nn.one_hot(nxt, self.v) * 10.0
            return P.Tensor(logits), states

    cell = ToyCell(6)
    dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=5,
                               beam_size=2)
    init = P.zeros([3, 4])  # batch of 3, dummy state
    ids, scores = nn.dynamic_decode(dec, inits=init, max_step_num=8)
    out = np.asarray(ids.numpy())
    # best beam should walk 1,2,3,4,5 then hold at end token
    np.testing.assert_array_equal(out[0, :5, 0], [1, 2, 3, 4, 5])
    assert scores.shape == [3, 2]


def test_new_layer_wrappers_smoke():
    import paddle_tpu.nn as nn

    rs = np.random.RandomState(0)
    x = P.to_tensor(rs.randn(2, 3, 4, 4).astype(np.float32))
    assert nn.Softmax2D()(x).shape == [2, 3, 4, 4]
    np.testing.assert_allclose(
        np.asarray(nn.Softmax2D()(x).numpy()).sum(1), 1.0, rtol=1e-5)
    u = nn.Unflatten(1, [1, 3])(x)
    assert u.shape == [2, 1, 3, 4, 4]
    # losses
    mm = nn.MultiMarginLoss()(
        P.to_tensor(rs.randn(4, 5).astype(np.float32)),
        P.to_tensor(rs.randint(0, 5, (4, 1)).astype(np.int64)))
    assert np.isfinite(float(mm.numpy()))
    gnll = nn.GaussianNLLLoss()(
        P.to_tensor(rs.randn(4).astype(np.float32)),
        P.to_tensor(rs.randn(4).astype(np.float32)),
        P.to_tensor((rs.rand(4) + 0.5).astype(np.float32)))
    assert np.isfinite(float(gnll.numpy()))
    hs = nn.HSigmoidLoss(6, 10)(
        P.to_tensor(rs.randn(4, 6).astype(np.float32)),
        P.to_tensor(rs.randint(0, 10, (4, 1)).astype(np.int64)))
    assert np.isfinite(float(hs.numpy()))
    # saved_tensors_hooks is a LOUD gate
    with pytest.raises(NotImplementedError):
        with P.autograd.saved_tensors_hooks(lambda t: t, lambda t: t):
            pass


def test_all_subnamespace_surfaces_vs_reference():
    """Machine check: every reference __all__ name resolves in the
    matching paddle_tpu namespace, across the whole package tree."""
    import ast
    import os

    R = "/root/reference/python/paddle/"
    if not os.path.exists(R):
        pytest.skip("reference not mounted")

    def ref_all(path):
        try:
            tree = ast.parse(open(path).read())
        except Exception:
            return []
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "__all__":
                        return [e.value for e in node.value.elts
                                if isinstance(e, ast.Constant)]
        return []

    import paddle_tpu.inference as I

    pairs = [
        (P.linalg, "linalg.py"), (P.fft, "fft.py"), (P.signal, "signal.py"),
        (P.sparse, "sparse/__init__.py"),
        (P.distribution, "distribution/__init__.py"),
        (P.vision.ops, "vision/ops.py"),
        (P.vision.transforms, "vision/transforms/__init__.py"),
        (P.vision, "vision/__init__.py"),
        (P.static, "static/__init__.py"),
        (P.static.nn, "static/nn/__init__.py"),
        (P.distributed, "distributed/__init__.py"),
        (P.distributed.fleet, "distributed/fleet/__init__.py"),
        (P.nn, "nn/__init__.py"),
        (P.nn.functional, "nn/functional/__init__.py"),
        (P.io, "io/__init__.py"), (P.metric, "metric/__init__.py"),
        (P.amp, "amp/__init__.py"),
        (P.optimizer, "optimizer/__init__.py"),
        (P.autograd, "autograd/__init__.py"),
        (P.geometric, "geometric/__init__.py"),
        (P.jit, "jit/__init__.py"), (P.profiler, "profiler/__init__.py"),
        (P.quantization, "quantization/__init__.py"),
        (P.device, "device/__init__.py"), (P.text, "text/__init__.py"),
        (P.audio, "audio/__init__.py"), (P.utils, "utils/__init__.py"),
        (P.incubate, "incubate/__init__.py"),
        (P.incubate.nn, "incubate/nn/__init__.py"),
        (P.incubate.nn.functional, "incubate/nn/functional/__init__.py"),
        (I, "inference/__init__.py"),
    ]
    problems = {}
    for mod, rel in pairs:
        missing = [n for n in ref_all(R + rel) if not hasattr(mod, n)]
        if missing:
            problems[rel] = missing
    assert not problems, f"surface gaps: {problems}"
