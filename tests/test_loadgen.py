"""tools/loadgen.py tests (ISSUE 14): the open-loop property (arrival
times are a function of phases+seed only), schedule determinism,
fingerprint parity with InferenceClient, misbehavior assignment,
report accounting, and one small real-socket e2e against a toy
InferenceServer (well-behaved + disconnecting + oversized clients,
with token-replay verification active)."""
import json
import os
import random
import sys

import numpy as np
import pytest

from paddle_tpu import observability as obs
from paddle_tpu.inference.fleet import EchoPredictor, ToyEngine, toy_token
from paddle_tpu.inference.serving import InferenceClient, InferenceServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
try:
    import loadgen
finally:
    sys.path.pop(0)


@pytest.fixture(autouse=True)
def _telemetry():
    obs.attach(crash_hook=False)
    yield
    obs.detach()


# --------------------------------------------------------------------------
# the workload definition (transport-free)
# --------------------------------------------------------------------------

def test_arrival_specs_carry_their_phase():
    wl = loadgen.SharedPrefixWorkload(seed=0)
    phases = loadgen.surge_phases(base_rps=30, warm_s=1, surge_s=1,
                                  cool_s=1)
    names = {spec["phase"] for _, spec in wl.arrivals(phases)}
    assert names == {"warm", "surge", "cool"}


def test_arrivals_are_open_loop_and_deterministic():
    wl = loadgen.SharedPrefixWorkload(seed=7, tenants=2)
    phases = loadgen.surge_phases(base_rps=20.0, surge_mult=10.0,
                                  warm_s=1.0, surge_s=1.0, cool_s=1.0)
    a1 = list(loadgen.SharedPrefixWorkload(seed=7, tenants=2)
              .arrivals(phases, random.Random(7)))
    a2 = list(wl.arrivals(phases, random.Random(7)))
    # same seed → identical schedule: times AND specs (minus the id
    # counter, which is per-workload-instance)
    assert [t for t, _ in a1] == [t for t, _ in a2]
    assert [s["prompt"] for _, s in a1] == [s["prompt"] for _, s in a2]
    # the 10x step is visible in the arrival density, phase by phase
    warm = [t for t, _ in a1 if t < 1.0]
    surge = [t for t, _ in a1 if 1.0 <= t < 2.0]
    assert len(surge) > 4 * len(warm) > 0
    # open loop: times are monotonically increasing offsets that never
    # depend on anything but the schedule
    assert all(b > a for a, b in zip([t for t, _ in a1],
                                     [t for t, _ in a1][1:]))


def test_diurnal_phases_swing_between_base_and_peak():
    phases = loadgen.diurnal_phases(base_rps=4.0, peak_mult=3.0,
                                    period_s=10.0, steps=10)
    rates = [p.rps for p in phases]
    assert len(phases) == 10
    assert min(rates) == pytest.approx(4.0)
    assert max(rates) == pytest.approx(12.0, rel=0.1)
    assert sum(p.duration_s for p in phases) == pytest.approx(10.0)


def test_shared_prefix_tenants_and_misbehavior_split():
    wl = loadgen.SharedPrefixWorkload(
        seed=0, tenants=3, system_prompt_tokens=16,
        misbehave_disconnect=0.2, misbehave_ignore_retry=0.2,
        misbehave_oversize=0.2)
    rng = random.Random(0)
    specs = [wl.sample(rng) for _ in range(400)]
    by_behavior: dict = {}
    for s in specs:
        by_behavior[s["behavior"]] = by_behavior.get(s["behavior"], 0) + 1
        # every prompt starts with its tenant's full shared prefix
        assert s["prompt"][:16] == wl.tenant_prompts[s["tenant"]]
    assert set(by_behavior) == {"well_behaved", "disconnect",
                                "ignore_retry_after", "oversize"}
    for k in ("disconnect", "ignore_retry_after", "oversize"):
        assert 0.1 < by_behavior[k] / len(specs) < 0.3
    # tenants sharing a prefix fingerprint alike → affinity exercised
    fp = {t: loadgen.prefix_fingerprint(wl.tenant_prompts[t] + [1, 2])
          for t in range(3)}
    assert len(set(fp.values())) == 3


def test_prefix_fingerprint_matches_inference_client():
    ids = list(range(40))
    assert loadgen.prefix_fingerprint(ids) == \
        InferenceClient.prefix_fingerprint(np.asarray(ids, np.int64))
    assert loadgen.prefix_fingerprint([1, 2, 3]) is None  # < 1 page


def test_schedule_burst_fixed_count_spread():
    wl = loadgen.SharedPrefixWorkload(seed=1)
    sched = wl.schedule_burst(8, window_s=0.4)
    assert len(sched) == 8
    assert sched[0][0] == 0.0 and sched[-1][0] < 0.4


def test_report_accounting():
    rows = [
        {"kind": "generate", "behavior": "well_behaved", "status": "ok",
         "latency_s": 0.01 * (i + 1), "tokens": 5, "detail": None,
         "id": i, "tenant": 0} for i in range(4)]
    rows += [
        {"kind": "generate", "behavior": "well_behaved",
         "status": "replayed", "latency_s": 0.1, "tokens": 2,
         "detail": "token 1 wrong", "id": 9, "tenant": 0},
        {"kind": "predict", "behavior": "well_behaved", "status": "shed",
         "latency_s": 0.1, "tokens": 0, "detail": None, "id": 10,
         "tenant": 0},
        {"kind": "generate", "behavior": "disconnect",
         "status": "abandoned", "latency_s": 0.05, "tokens": 1,
         "detail": None, "id": 11, "tenant": 1},
    ]
    s = loadgen.LoadReport(rows, wall_s=2.0).summary()
    assert s["requests"] == 7 and s["ok"] == 4 and s["shed"] == 1
    assert s["replayed"] == 1 and s["abandoned"] == 1
    assert s["admitted_failures"] == 1           # only the replay
    assert s["tokens"] == 4 * 5 + 2 + 1
    assert s["tokens_per_sec"] == pytest.approx(23 / 2.0)
    assert s["latency_ms"]["generate"]["n"] == 4  # ok rows only
    assert "generate:replayed:token 1 wrong" in s["failure_detail"]


def test_report_counts_resumed_streams_as_real_oks():
    """A stream the router resumed mid-flight (ISSUE 20) lands as a
    REAL ok — counted in resumed_streams, never in admitted_failures —
    while a resume that replayed a token is still a failure."""
    rows = [
        {"kind": "generate", "behavior": "well_behaved",
         "status": "ok", "latency_s": 0.02, "tokens": 8,
         "detail": None, "id": 0, "tenant": 0, "resumed": 1},
        {"kind": "generate", "behavior": "well_behaved",
         "status": "ok", "latency_s": 0.02, "tokens": 8,
         "detail": None, "id": 1, "tenant": 0, "resumed": 0},
        {"kind": "generate", "behavior": "well_behaved",
         "status": "replayed", "latency_s": 0.02, "tokens": 3,
         "detail": "token 2 wrong", "id": 2, "tenant": 0,
         "resumed": 1},
    ]
    s = loadgen.LoadReport(rows, wall_s=1.0).summary()
    assert s["ok"] == 2
    assert s["resumed_streams"] == 2     # one ok + one failed resume
    assert s["admitted_failures"] == 1   # the replay, nothing else


def test_consume_stream_reads_resumed_from_done_record():
    """The stream consumer extracts `resumed` from the final record and
    still holds the exact-prefix bar for resumed streams."""
    runner = loadgen.OpenLoopRunner("127.0.0.1:1",
                                    loadgen.SharedPrefixWorkload())
    prompt = [3, 4]
    toks = [11, 12, 13]

    def resp(final):
        lines = [json.dumps({"token": t}).encode() + b"\n"
                 for t in toks]
        return iter(lines + [json.dumps(final).encode() + b"\n"])

    spec = {"prompt": prompt, "behavior": "well_behaved",
            "kind": "generate", "id": 0, "tenant": 0}
    ok = runner._consume_stream(spec, resp(
        {"done": True, "output_ids": prompt + toks, "resumed": 2}),
        conn=None)
    assert ok[0] == "ok" and ok[4] == 2
    # a resumed stream with a corrupted final record is still caught
    # (and still counts as resumed — the failure is not laundered)
    bad = runner._consume_stream(spec, resp(
        {"done": True, "output_ids": prompt + toks + [99],
         "resumed": 1}), conn=None)
    assert bad[0] == "replayed" and bad[4] == 1


# --------------------------------------------------------------------------
# e2e against a real toy server (sockets, no jax)
# --------------------------------------------------------------------------

def test_open_loop_runner_e2e_toy_server():
    srv = InferenceServer(predictor=EchoPredictor(),
                          engine=ToyEngine(max_slots=4,
                                           token_time=0.005),
                          request_timeout=20.0).start()
    try:
        wl = loadgen.SharedPrefixWorkload(
            seed=3, tenants=2, generate_frac=0.6, max_new_tokens=6)
        runner = loadgen.OpenLoopRunner(
            srv.address, wl, seed=3, expected_token=toy_token,
            timeout=20.0)
        report = runner.run(schedule=wl.schedule_burst(10,
                                                       window_s=0.2))
        s = report.summary()
        assert s["requests"] == 10
        assert s["admitted_failures"] == 0, s["failure_detail"]
        assert s["ok"] == 10                 # all well-behaved, verified
        assert s["tokens"] > 0 and "generate" in s["latency_ms"]
        # client-side ITL/TPOT (ISSUE 15): every generate stream with
        # ≥2 tokens contributed gaps; the toy engine paces tokens at
        # token_time, so the median gap sits near it
        assert s["itl_ms"] is not None and s["itl_ms"]["n"] > 0
        assert 1.0 <= s["itl_ms"]["p50"] <= 200.0
        assert s["tpot_ms"] is not None
        # per-phase breakdown: schedule_burst stamps phase="burst"
        assert s["phases"]["burst"]["requests"] == 10
        assert s["phases"]["burst"]["admitted_failures"] == 0
        assert "latency_ms" in s["phases"]["burst"]

        # misbehaving clients: the deliberate disconnect is abandoned
        # (and verified up to the cut), the oversized body 400s — and
        # neither counts as a fleet failure
        bad = loadgen.SharedPrefixWorkload(
            seed=4, tenants=2, generate_frac=1.0, max_new_tokens=6,
            misbehave_disconnect=1.0)
        r2 = loadgen.OpenLoopRunner(
            srv.address, bad, seed=4, expected_token=toy_token,
            timeout=20.0)
        s2 = r2.run(schedule=bad.schedule_burst(3, 0.1)).summary()
        assert s2["abandoned"] == 3 and s2["admitted_failures"] == 0

        ugly = loadgen.SharedPrefixWorkload(
            seed=5, tenants=2, misbehave_oversize=1.0)
        r3 = loadgen.OpenLoopRunner(
            srv.address, ugly, seed=5, timeout=20.0,
            oversize_bytes=64 * 1024)
        s3 = r3.run(schedule=ugly.schedule_burst(2, 0.1)).summary()
        assert s3["client_errors"] == 2 and s3["admitted_failures"] == 0
    finally:
        srv.shutdown()


def test_replay_detector_catches_a_wrong_token():
    srv = InferenceServer(engine=ToyEngine(max_slots=2,
                                           token_time=0.005),
                          request_timeout=20.0).start()
    try:
        wl = loadgen.SharedPrefixWorkload(seed=6, generate_frac=1.0,
                                          max_new_tokens=4)

        def wrong(prompt, i):  # an expectation the server can't meet
            return toy_token(prompt, i) + (1 if i == 2 else 0)

        runner = loadgen.OpenLoopRunner(srv.address, wl, seed=6,
                                        expected_token=wrong,
                                        timeout=20.0)
        s = runner.run(schedule=wl.schedule_burst(2, 0.05)).summary()
        assert s["replayed"] == 2 and s["admitted_failures"] == 2
    finally:
        srv.shutdown()
