"""Overload- and preemption-resilience tests (ISSUE 5): admission
control (bounded queue, AIMD limit, deadline sheds), graceful drain,
liveness/readiness split, HTTP status discipline, client retry-on-429,
and the preemption-safe training shutdown (SIGTERM → verified
checkpoint → TrainingPreempted → bit-for-bit resume).  Deterministic,
CPU-only, fast; the seeded concurrent matrices live under the `chaos`
marker (tools/chaos_check.py scenarios), outside tier-1.
"""
import io
import os
import signal as _signal
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as P
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet, topology
from paddle_tpu.distributed.checkpoint import (
    CheckpointManager, verify_checkpoint,
)
from paddle_tpu.distributed.fleet.elastic import (
    ELASTIC_EXIT_CODE, ElasticManager,
)
from paddle_tpu.inference.serving import (
    InferenceClient, InferenceServer, _positional_order,
)
from paddle_tpu.observability import metrics
from paddle_tpu.resilience.overload import AdmissionController, ShedError
from paddle_tpu.resilience.preemption import (
    PreemptionGuard, TrainingPreempted,
)


# --------------------------------------------------------------------------
# shared stubs
# --------------------------------------------------------------------------

class _Clock:
    """Injectable monotonic clock for wait-free admission tests."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class _StubPredictor:
    """Duck-typed predictor: records the inputs it was fed, optionally
    sleeps (overload tests) or fails (readiness tests)."""

    def __init__(self, inputs=("x",), outputs=("y",), fn=None,
                 service_time=0.0):
        self._inputs = list(inputs)
        self._outputs = list(outputs)
        self.fn = fn or (lambda ins: [np.asarray(ins[0])])
        self.service_time = float(service_time)
        self.calls = []

    def get_input_names(self):
        return list(self._inputs)

    def get_output_names(self):
        return list(self._outputs)

    def run(self, inputs):
        self.calls.append([np.asarray(a) for a in inputs])
        if self.service_time:
            time.sleep(self.service_time)
        return self.fn(inputs)


def _server(**kw):
    kw.setdefault("predictor", _StubPredictor())
    srv = InferenceServer(**kw)
    srv._retry.sleep = lambda s: None
    return srv


def _post_raw(address, data, timeout=10):
    req = urllib.request.Request(
        address + "/predict", data=data,
        headers={"Content-Type": "application/octet-stream"})
    return urllib.request.urlopen(req, timeout=timeout)


def _post_npz(address, arrays, timeout=10):
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    with _post_raw(address, buf.getvalue(), timeout=timeout) as r:
        with np.load(io.BytesIO(r.read())) as z:
            return {k: z[k] for k in z.files}


# --------------------------------------------------------------------------
# admission controller: queue bound, deadline sheds, AIMD, drain
# --------------------------------------------------------------------------

def test_admission_basic_and_queue_full_shed():
    clk = _Clock()
    ctrl = AdmissionController(max_inflight=1, queue_depth=0, clock=clk)
    t1 = ctrl.admit()  # free slot admits even with queue_depth=0
    with pytest.raises(ShedError) as ei:
        ctrl.admit()
    assert ei.value.reason == "queue_full"
    assert ei.value.http_status == 429
    assert ctrl.stats()["shed"]["queue_full"] == 1
    t1.release(ok=True)
    ctrl.admit().release()  # slot freed → admits again


def test_admission_deadline_shed_uses_latency_estimate():
    clk = _Clock()
    ctrl = AdmissionController(max_inflight=1, queue_depth=4, clock=clk)
    t = ctrl.admit()
    clk.advance(1.0)
    t.release()  # observed latency EWMA = 1.0s
    assert ctrl.stats()["ewma_latency"] == pytest.approx(1.0)
    hold = ctrl.admit()
    # one request ahead at 1s each: estimated completion ~2s; a 0.5s
    # deadline cannot be met → shed at the door, not timed out later
    with pytest.raises(ShedError) as ei:
        ctrl.admit(deadline=clk() + 0.5)
    assert ei.value.reason == "deadline"
    assert ei.value.retry_after >= 1.0
    hold.release()


def test_admission_queue_wait_deadline_real_clock():
    ctrl = AdmissionController(max_inflight=1, queue_depth=2,
                               queue_timeout=0.05)
    hold = ctrl.admit()
    t0 = time.monotonic()
    with pytest.raises(ShedError) as ei:
        ctrl.admit()  # queues, then sheds when queue_timeout elapses
    # no request deadline was involved: the honest reason is the
    # operator queue timeout, not "deadline" (ISSUE 18 bugfix)
    assert ei.value.reason == "queue_timeout"
    assert time.monotonic() - t0 >= 0.04
    hold.release()


def test_admission_aimd_decreases_then_recovers():
    clk = _Clock()
    ctrl = AdmissionController(max_inflight=8, queue_depth=8,
                               latency_target=0.1, clock=clk)
    assert ctrl.limit == 8
    for _ in range(6):  # sustained 1s latencies vs a 0.1s target
        t = ctrl.admit()
        clk.advance(1.0)
        t.release()
    assert ctrl.limit == 1  # multiplicative decrease to the floor
    for _ in range(40):  # fast completions decay the EWMA under target
        t = ctrl.admit()
        clk.advance(0.001)
        t.release()
    assert 1 < ctrl.limit <= 8  # additive increase probes back up


def test_admission_drain_sheds_new_and_queued():
    ctrl = AdmissionController(max_inflight=1, queue_depth=2,
                               queue_timeout=5.0)
    hold = ctrl.admit()
    shed = []

    def queued():
        try:
            ctrl.admit()
        except ShedError as e:
            shed.append(e.reason)

    th = threading.Thread(target=queued)
    th.start()
    time.sleep(0.05)  # let it enter the wait queue
    ctrl.begin_drain()
    th.join(timeout=2)
    assert shed == ["draining"]  # queued waiter shed on drain
    with pytest.raises(ShedError) as ei:
        ctrl.admit()  # new arrivals shed immediately
    assert ei.value.reason == "draining"
    assert ei.value.http_status == 503
    hold.release()
    assert ctrl.drain(timeout=1.0) is True  # in-flight finished → drained


def test_admission_drain_timeout_reports_false():
    ctrl = AdmissionController(max_inflight=1, queue_depth=0)
    ctrl.admit()  # never released
    assert ctrl.drain(timeout=0.05) is False


# --------------------------------------------------------------------------
# satellite: positional arr_N ordering
# --------------------------------------------------------------------------

def test_positional_order_sorts_numeric_suffix():
    keys = [f"arr_{i}" for i in range(12)]
    assert _positional_order(sorted(keys)) == keys  # lexicographic undone
    assert _positional_order(["b", "arr_2", "a", "arr_10"]) == \
        ["arr_2", "arr_10", "a", "b"]


def test_predict_positional_fallback_feeds_numeric_order():
    pred = _StubPredictor(inputs=[f"in_{i}" for i in range(12)],
                          outputs=["y"],
                          fn=lambda ins: [np.asarray(ins[0])])
    srv = _server(predictor=pred)
    arrays = {f"arr_{i}": np.full((1,), float(i), np.float32)
              for i in range(12)}
    srv.predict(arrays)
    fed = [float(a[0]) for a in pred.calls[0]]
    assert fed == [float(i) for i in range(12)]  # arr_2 before arr_10


# --------------------------------------------------------------------------
# satellite: HTTP status discipline (400 vs 429/503 vs 500)
# --------------------------------------------------------------------------

def _http_code(fn):
    try:
        fn()
    except urllib.error.HTTPError as e:
        return e.code, e.headers
    raise AssertionError("expected an HTTPError")


def test_http_bad_body_and_deterministic_errors_are_400():
    srv = _server().start()
    try:
        code, _ = _http_code(lambda: _post_raw(srv.address, b"not-an-npz"))
        assert code == 400
        bad = _server(predictor=_StubPredictor(
            fn=lambda ins: (_ for _ in ()).throw(ValueError("bad rank"))))
        bad.start()
        try:
            code, _ = _http_code(lambda: _post_npz(
                bad.address, {"x": np.zeros((1, 2), np.float32)}))
            assert code == 400  # deterministic model error: client fault
        finally:
            bad.shutdown()
    finally:
        srv.shutdown()


def test_http_internal_error_is_500_and_timeout_is_503():
    boom = _server(predictor=_StubPredictor(
        fn=lambda ins: (_ for _ in ()).throw(RuntimeError("boom"))),
        request_retries=1).start()
    try:
        code, headers = _http_code(lambda: _post_npz(
            boom.address, {"x": np.zeros((1, 2), np.float32)}))
        assert code == 500
        assert headers.get("Retry-After") is None
    finally:
        boom.shutdown()
    # slow, failing predictor exhausts the request deadline between
    # retries → DeadlineExceeded (a TimeoutError) → 503 + Retry-After
    slow = _server(predictor=_StubPredictor(
        fn=lambda ins: (_ for _ in ()).throw(RuntimeError("flaky")),
        service_time=0.15), request_retries=3, request_timeout=0.1)
    slow._retry.sleep = time.sleep  # real backoff so the deadline binds
    slow.start()
    try:
        code, headers = _http_code(lambda: _post_npz(
            slow.address, {"x": np.zeros((1, 2), np.float32)}))
        assert code == 503
        assert headers.get("Retry-After") is not None
    finally:
        slow.shutdown()


# --------------------------------------------------------------------------
# tentpole: overload shed + all-admitted-complete (acceptance criterion)
# --------------------------------------------------------------------------

def test_overload_sheds_excess_and_admitted_all_complete():
    metrics.enable()
    metrics.reset()
    srv = _server(predictor=_StubPredictor(service_time=0.08),
                  max_inflight=2, queue_depth=2,
                  request_timeout=10.0).start()
    n = 8  # 2x the admit+queue capacity
    barrier = threading.Barrier(n)
    results = []
    lock = threading.Lock()

    def one(i):
        x = np.full((1, 2), float(i), np.float32)
        barrier.wait()
        try:
            out = _post_npz(srv.address, {"x": x}, timeout=10)
            row = ("ok", bool(np.array_equal(out["y"], x)), None)
        except urllib.error.HTTPError as e:
            row = ("shed", e.code, e.headers.get("Retry-After"))
        with lock:
            results.append(row)

    threads = [threading.Thread(target=one, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    try:
        oks = [r for r in results if r[0] == "ok"]
        sheds = [r for r in results if r[0] == "shed"]
        assert len(oks) + len(sheds) == n
        assert all(r[1] for r in oks)       # zero admitted failures
        assert len(sheds) >= 1              # overload actually shed
        assert all(r[1] in (429, 503) for r in sheds)
        assert all(r[2] is not None for r in sheds)  # Retry-After set
        snap = metrics.snapshot()["counters"]
        counted = sum(v for k, v in snap.items()
                      if k.startswith("resilience.shed_requests"))
        assert counted == len(sheds)        # ledger matches reality
    finally:
        srv.shutdown()
        metrics.disable()
        metrics.reset()


# --------------------------------------------------------------------------
# tentpole: liveness/readiness split + graceful drain + socket close
# --------------------------------------------------------------------------

def test_ready_flips_during_drain_while_health_stays_live():
    srv = _server(predictor=_StubPredictor(service_time=0.4)).start()
    client = InferenceClient(srv.address, timeout=10, retries=0)
    assert client.ready()["ready"] is True
    done = {}

    def request():
        done["out"] = _post_npz(
            srv.address, {"x": np.ones((1, 2), np.float32)})

    req = threading.Thread(target=request)
    req.start()
    time.sleep(0.1)  # request in flight
    stopper = threading.Thread(target=srv.shutdown)
    stopper.start()
    time.sleep(0.1)  # drain begun, request still running
    rd = client.ready()
    assert rd["ready"] is False and rd["reason"] == "draining"
    assert client.health()["status"] == "ok"  # liveness never flips
    req.join(timeout=10)
    stopper.join(timeout=10)
    assert "out" in done  # the in-flight request finished during drain
    # after drain: socket CLOSED (the leak this PR fixes), not just idle
    assert srv._httpd.socket.fileno() == -1
    with pytest.raises(urllib.error.URLError):
        InferenceClient(srv.address, timeout=0.5, retries=0).health()
    assert srv.shutdown() is True  # idempotent


def test_shutdown_idempotent_without_start():
    srv = _server()
    assert srv.shutdown() is True  # never-started server: no hang
    assert srv.shutdown() is True
    assert srv._httpd.socket.fileno() == -1


def test_ready_flips_on_consecutive_predictor_failures():
    pred = _StubPredictor(
        fn=lambda ins: (_ for _ in ()).throw(RuntimeError("wedged")))
    srv = _server(predictor=pred, request_retries=1, ready_window=3)
    for _ in range(3):
        with pytest.raises(RuntimeError):
            srv.predict({"x": np.zeros((1, 2), np.float32)})
    ok, reason = srv.readiness()
    assert not ok and reason == "predictor_failing"
    pred.fn = lambda ins: [np.asarray(ins[0])]  # predictor recovers
    srv.predict({"x": np.zeros((1, 2), np.float32)})
    assert srv.readiness() == (True, "ok")


def test_client_fault_errors_do_not_flip_readiness():
    """Deterministic (400-class) request errors are the CLIENT's fault:
    one misbehaving client must not drive a healthy server not-ready."""
    srv = _server(predictor=_StubPredictor(
        fn=lambda ins: (_ for _ in ()).throw(ValueError("bad dtype"))),
        request_retries=1, ready_window=3)
    for _ in range(5):
        with pytest.raises(ValueError):
            srv.predict({"x": np.zeros((1, 2), np.float32)})
    assert srv.readiness() == (True, "ok")


_VICTIM = r"""
import sys, time
sys.path.insert(0, {repo!r})
import numpy as np
from paddle_tpu.inference.serving import InferenceServer

class Slow:
    def get_input_names(self): return ["x"]
    def get_output_names(self): return ["y"]
    def run(self, inputs):
        time.sleep(0.8)
        return [np.asarray(inputs[0])]

srv = InferenceServer(predictor=Slow())
guard = srv.install_preemption()
srv.start()
print(srv.address, flush=True)
guard.wait()
srv.shutdown()
print(f"DRAINED_EXIT reason={{guard.reason}}", flush=True)
"""


def test_sigterm_to_serving_process_drains_in_flight(tmp_path):
    """Acceptance: a REAL SIGTERM to a separate serving process lets
    the in-flight request finish (200, full service time) before the
    socket closes and the process exits 0."""
    import subprocess
    import sys

    script = tmp_path / "victim.py"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script.write_text(_VICTIM.format(repo=repo))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.Popen([sys.executable, str(script)], env=env,
                         stdout=subprocess.PIPE, text=True)
    try:
        addr = p.stdout.readline().strip()
        assert addr.startswith("http://")
        result = {}

        def request():
            t0 = time.monotonic()
            out = _post_npz(addr, {"x": np.ones((1, 2), np.float32)},
                            timeout=15)
            result["y"] = out["y"]
            result["elapsed"] = time.monotonic() - t0

        th = threading.Thread(target=request)
        th.start()
        time.sleep(0.2)  # request mid-service (0.8s)
        p.send_signal(_signal.SIGTERM)
        th.join(timeout=15)
        out, _ = p.communicate(timeout=15)
    finally:
        if p.poll() is None:
            p.kill()
    assert "y" in result and result["elapsed"] > 0.5  # finished, not cut
    assert "DRAINED_EXIT reason=signal:SIGTERM" in out
    assert p.returncode == 0  # clean exit after the drain


# --------------------------------------------------------------------------
# satellite: client timeout + bounded retry honoring Retry-After
# --------------------------------------------------------------------------

class _FlakyHTTPServer:
    """Raw stub server: serves `codes` (with Retry-After: 0) then a
    valid npz response — exercises the client's retry loop alone."""

    def __init__(self, codes):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        state = {"codes": list(codes)}
        self.state = state

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                if state["codes"]:
                    code = state["codes"].pop(0)
                    self.send_response(code)
                    self.send_header("Retry-After", "0")
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                buf = io.BytesIO()
                np.savez(buf, y=np.ones((1,), np.float32))
                body = buf.getvalue()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        h, p = self.httpd.server_address[:2]
        self.address = f"http://{h}:{p}"

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_client_bounded_retry_honors_retry_after():
    stub = _FlakyHTTPServer([429, 503])
    sleeps = []
    try:
        client = InferenceClient(stub.address, timeout=5, retries=2,
                                 sleep=sleeps.append)
        out = client.predict(x=np.zeros((1,), np.float32))
        assert np.array_equal(out["y"], np.ones((1,), np.float32))
        # two retryable failures → two waits, Retry-After(0) clamped up
        assert len(sleeps) == 2 and all(0.05 <= s <= 5.0 for s in sleeps)
    finally:
        stub.close()
    # retries exhausted → the status surfaces, bounded (no infinite loop)
    stub2 = _FlakyHTTPServer([429, 429, 429])
    try:
        client = InferenceClient(stub2.address, timeout=5, retries=1,
                                 sleep=lambda s: None)
        with pytest.raises(urllib.error.HTTPError) as ei:
            client.predict(x=np.zeros((1,), np.float32))
        assert ei.value.code == 429
    finally:
        stub2.close()


# --------------------------------------------------------------------------
# preemption guard: trip semantics, signals, maintenance hook
# --------------------------------------------------------------------------

def test_preemption_guard_trip_fires_callbacks_once():
    g = PreemptionGuard()
    seen = []
    g.on_preempt(lambda r: seen.append(("early", r)))
    assert not g.preempted
    g.trip("signal:SIGTERM")
    g.trip("signal:SIGINT")  # second trip: counted nowhere, no refire
    assert g.preempted and g.reason == "signal:SIGTERM"  # first wins
    g.on_preempt(lambda r: seen.append(("late", r)))  # late → immediate
    assert seen == [("early", "signal:SIGTERM"), ("late", "signal:SIGTERM")]
    assert g.wait(timeout=0.01) is True


def test_preemption_guard_maintenance_hook_rate_limited():
    clk = _Clock()
    pending = {"v": None}
    calls = []

    def hook():
        calls.append(clk())
        return pending["v"]

    g = PreemptionGuard(maintenance_hook=hook, maintenance_interval=5.0,
                        clock=clk)
    assert g.check() is False
    clk.advance(1.0)
    assert g.check() is False
    assert len(calls) == 1  # polled once inside the interval
    clk.advance(5.0)
    pending["v"] = "terminate-on-host-maintenance"
    assert g.check() is True
    assert g.reason == "maintenance:terminate-on-host-maintenance"


def test_preemption_guard_real_sigterm_and_uninstall():
    metrics.enable()
    metrics.reset()
    prev = _signal.getsignal(_signal.SIGTERM)
    g = PreemptionGuard().install()
    try:
        os.kill(os.getpid(), _signal.SIGTERM)
        deadline = time.monotonic() + 2.0
        while not g.preempted and time.monotonic() < deadline:
            time.sleep(0.005)  # handler runs between bytecodes
        assert g.preempted and g.reason == "signal:SIGTERM"
        snap = metrics.snapshot()["counters"]
        assert snap.get("preemption.signals{signal=SIGTERM}", 0) == 1
    finally:
        g.uninstall()
        metrics.disable()
        metrics.reset()
    assert _signal.getsignal(_signal.SIGTERM) is prev  # restored
    g.uninstall()  # idempotent


# --------------------------------------------------------------------------
# preemption-safe training: checkpoint at safe point, resume bit-for-bit
# --------------------------------------------------------------------------

def _make_guarded_step(mgr=None):
    topology.reset_topology()
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1,
                               "pp_degree": 1, "sep_degree": 1,
                               "sharding_degree": 2}
    fleet.init(is_collective=True, strategy=strategy)
    P.seed(0)
    model = fleet.distributed_model(nn.Linear(8, 4))
    opt = P.optimizer.SGD(parameters=model.parameters(),
                          learning_rate=0.1)
    step = model.build_train_step(opt, nn.MSELoss(), guard=True)
    if mgr is not None:
        step.attach_checkpoint_manager(mgr)
    return step


def _batch():
    P.seed(1)
    return P.randn([8, 8]), P.randn([8, 4])


def test_preemption_checkpoint_resume_bit_for_bit(tmp_path):
    metrics.enable()
    metrics.reset()
    try:
        # reference: 6 uninterrupted guarded steps
        ref_step = _make_guarded_step()
        x, y = _batch()
        ref_losses = [float(ref_step(x, y)) for _ in range(6)]
        ref_params = {k: np.asarray(v._value) for k, v in
                      ref_step.train_state_dict().items()}

        # preempted run: trip after 3 steps → safe point checkpoints
        mgr = CheckpointManager(str(tmp_path), keep_last_k=2)
        step = _make_guarded_step(mgr)
        x, y = _batch()
        guard = PreemptionGuard()
        step.attach_preemption_guard(guard)
        pre_losses = [float(step(x, y)) for _ in range(3)]
        guard.trip("signal:SIGTERM")
        with pytest.raises(TrainingPreempted) as ei:
            step(x, y)
        exc = ei.value
        assert exc.checkpoint_dir is not None and exc.step == 3
        assert verify_checkpoint(exc.checkpoint_dir)["unverified"] == 0
        snap = metrics.snapshot()["counters"]
        assert snap.get("preemption.checkpoints", 0) == 1

        # resume on a FRESH step: rollback() loads the emergency
        # checkpoint; the continued trajectory is bit-for-bit the
        # reference's (guarded fault-free path is select-not-recompute)
        step2 = _make_guarded_step(mgr)
        assert step2.rollback() == 3
        x, y = _batch()
        post_losses = [float(step2(x, y)) for _ in range(3)]
        assert pre_losses + post_losses == ref_losses  # exact floats
        got = {k: np.asarray(v._value) for k, v in
               step2.train_state_dict().items()}
        for k, v in ref_params.items():
            np.testing.assert_array_equal(got[k], v, err_msg=k)
    finally:
        metrics.disable()
        metrics.reset()


def test_preemption_checkpoints_once_and_reraises():
    """A tripped guard without a manager still raises (no save), and a
    second call after the trip raises again without double-saving."""
    step = _make_guarded_step()
    x, y = _batch()
    float(step(x, y))
    g = PreemptionGuard()
    step.attach_preemption_guard(g)
    g.trip("maintenance:test")
    with pytest.raises(TrainingPreempted) as ei:
        step(x, y)
    assert ei.value.checkpoint_dir is None  # no manager attached
    assert ei.value.exit_code == 0
    # a caller ignoring the exception must not silently keep training
    with pytest.raises(TrainingPreempted) as ei2:
        step(x, y)
    assert ei2.value is ei.value  # same exception, no double save


def test_run_steps_checks_preemption_at_entry():
    step = _make_guarded_step()
    x, y = _batch()
    xs = P.to_tensor(np.stack([x.numpy()] * 2))
    ys = P.to_tensor(np.stack([y.numpy()] * 2))
    float(step.run_steps(xs, ys).numpy()[-1])  # scan path works
    g = PreemptionGuard()
    step.attach_preemption_guard(g)
    g.trip("signal:SIGTERM")
    with pytest.raises(TrainingPreempted):
        step.run_steps(xs, ys)


# --------------------------------------------------------------------------
# elastic: preempted rank deregisters instead of vanishing
# --------------------------------------------------------------------------

class _DictStore:
    def __init__(self):
        self.d = {}

    def set(self, k, v):
        self.d[k] = v

    def get(self, k, timeout=None):
        return self.d[k]

    def check(self, k):
        return k in self.d


def test_elastic_deregisters_on_preemption():
    st = _DictStore()
    m = ElasticManager(store=st, job_id="preempt", np_range="1",
                       heartbeat_interval=0.05, heartbeat_ttl=0.5)
    g = PreemptionGuard()
    m.attach_preemption_guard(g, install=False)
    assert g.exit_code == ELASTIC_EXIT_CODE  # relaunch protocol rides
    m.register()
    assert m._thread is not None and m._thread.is_alive()
    g.trip("signal:SIGTERM")
    deadline = time.monotonic() + 2.0
    while m._thread is not None and m._thread.is_alive() and \
            time.monotonic() < deadline:
        time.sleep(0.01)
    assert m._thread is None or not m._thread.is_alive()  # beat stopped
    assert not st.check("elastic/preempt/done")  # NOT marked complete


# --------------------------------------------------------------------------
# schema: the new counters/gauges are pre-declared by attach()
# --------------------------------------------------------------------------

def test_attach_declares_overload_preemption_schema():
    from paddle_tpu import observability as obs

    metrics.reset()
    obs.attach(crash_hook=False)
    try:
        snap = metrics.snapshot()
        for key in ("resilience.shed_requests{reason=queue_full}",
                    "resilience.shed_requests{reason=deadline}",
                    "resilience.shed_requests{reason=draining}",
                    "preemption.signals{signal=SIGTERM}",
                    "preemption.signals{signal=SIGINT}",
                    "preemption.maintenance_events",
                    "preemption.checkpoints", "preemption.drains"):
            assert key in snap["counters"] and \
                snap["counters"][key] == 0, key
        for key in ("serving.inflight", "serving.queue_depth",
                    "serving.admission_limit"):
            assert key in snap["gauges"], key
    finally:
        obs.detach()
        metrics.reset()


# --------------------------------------------------------------------------
# chaos tier: seeded overload + preemption matrix (tools/chaos_check.py)
# --------------------------------------------------------------------------

def _load_chaos_tool():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "chaos_check", os.path.join(os.path.dirname(__file__), os.pardir,
                                    "tools", "chaos_check.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.chaos
@pytest.mark.slow  # tier-1 runs `-m 'not slow'`; chaos rides slow tier
def test_chaos_overload_scenario():
    mod = _load_chaos_tool()
    for seed in (0, 1):
        report = mod.run_overload(requests=24, max_inflight=2,
                                  queue_depth=3, service_time=0.05,
                                  seed=seed)
        assert report["recovered"], report


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_preemption_scenario(tmp_path):
    mod = _load_chaos_tool()
    report = mod.run_preemption(steps=10, seed=0, preempt_at=4,
                                root=str(tmp_path))
    assert report["recovered"], report
    assert report["checkpoint_verified"] and report["preempted"]
