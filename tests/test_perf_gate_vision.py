"""Vision throughput metrics gate like GPT's (ISSUE 10 satellite):
`swin_t_train_images_per_sec_per_chip` / `resnet50_...` rows from
bench.py round-trip through tools/perf_gate.py --update and then gate
regressions — vision can no longer regress silently while only the GPT
headline is floored."""
import importlib.util
import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VISION_METRICS = ("swin_t_train_images_per_sec_per_chip",
                  "resnet50_train_images_per_sec_per_chip")


def _pg():
    spec = importlib.util.spec_from_file_location(
        "_perf_gate", os.path.join(REPO, "tools", "perf_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_emits_vision_metrics():
    """bench.py's secondary-bench source carries both vision metrics
    (the strings are what chip_session/perf_gate key on — a rename
    would orphan every baseline row)."""
    with open(os.path.join(REPO, "bench.py")) as f:
        src = f.read()
    for m in VISION_METRICS:
        assert f'"{m}"' in src, m


def test_vision_rows_update_round_trip(tmp_path):
    """--update appends the vision rows to the baseline; a later run
    gates them: within tolerance passes, a regression beyond tolerance
    fails — the full acceptance loop on both vision metrics."""
    pg = _pg()
    baseline = tmp_path / "baseline.jsonl"
    baseline.write_text("")  # start empty

    results = [{"metric": m, "value": 100.0, "unit": "images/s"}
               for m in VISION_METRICS]
    n = pg.update_baseline(results, str(baseline))
    assert n == 2
    base = pg.load_baseline(str(baseline))
    assert set(base) == set(VISION_METRICS)

    ok_rows = [{"metric": m, "value": 95.0, "unit": "images/s"}
               for m in VISION_METRICS]
    failures, _ = pg.gate(ok_rows, base, tolerance=0.10)
    assert failures == []

    bad_rows = [{"metric": VISION_METRICS[0], "value": 50.0,
                 "unit": "images/s"}]
    failures, report = pg.gate(bad_rows, base, tolerance=0.10)
    assert len(failures) == 1 and VISION_METRICS[0] in failures[0], \
        report


def test_degraded_vision_rows_never_update_or_gate(tmp_path):
    """CPU-proxy (degraded) vision rows are excluded from --update and
    skipped by the gate — a proxy number must never become or be judged
    against an on-chip floor."""
    pg = _pg()
    baseline = tmp_path / "baseline.jsonl"
    baseline.write_text(json.dumps(
        {"metric": VISION_METRICS[0], "value": 100.0,
         "unit": "images/s"}) + "\n")
    degraded = [{"metric": VISION_METRICS[0], "value": 1.0,
                 "unit": "images/s", "degraded": True}]
    assert pg.update_baseline(degraded, str(baseline)) == 0
    failures, report = pg.gate(degraded, pg.load_baseline(str(baseline)))
    assert failures == [] and any("SKIP" in l for l in report)
