"""Model zoo: LLaMA (GQA), ViT, and the extra vision families.

Parity model: reference model-zoo smoke tests (`test/legacy_test/
test_vision_models.py` style — construct, forward, shape-check) plus a
train-step check on the flagship language models.
"""
import os

import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                               LlamaPretrainingCriterion, llama_pipe_layers,
                               llama_tiny)
from paddle_tpu.vision import models as V


@pytest.mark.slow
def test_llama_forward_and_train_step():
    cfg = llama_tiny()
    model = LlamaForCausalLM(cfg)
    crit = LlamaPretrainingCriterion()
    rng = np.random.RandomState(0)
    ids = P.to_tensor(rng.randint(0, cfg.vocab_size, (2, 16)), dtype="int64")
    labels = P.to_tensor(rng.randint(0, cfg.vocab_size, (2, 16)),
                         dtype="int64")
    logits = model(ids)
    assert logits.shape == [2, 16, cfg.vocab_size]
    loss = crit(logits, labels)
    loss.backward()
    opt = P.optimizer.AdamW(1e-3, parameters=list(model.parameters()))
    opt.step()
    opt.clear_grad()
    loss2 = crit(model(ids), labels)
    assert float(loss2.numpy()) < float(loss.numpy())


def test_llama_gqa_heads():
    cfg = llama_tiny(num_heads=4, num_kv_heads=2)
    model = LlamaForCausalLM(cfg)
    hd = cfg.hidden_size // cfg.num_heads
    qkv_w = model.model.layers[0].attn.qkv_proj.weight
    # fused qkv: q (4 heads) + k (2) + v (2)
    assert qkv_w.shape[-1] == (4 + 2 + 2) * hd
    ids = P.to_tensor(np.zeros((1, 8), np.int64))
    out = model(ids)
    assert out.shape == [1, 8, cfg.vocab_size]


def test_llama_pipe_layers_compose():
    cfg = llama_tiny()
    layers = llama_pipe_layers(cfg)
    assert len(layers) == cfg.num_layers + 2
    x = P.to_tensor(np.zeros((1, 8), np.int64))
    h = layers[0](x)
    for blk in layers[1:-1]:
        h = blk(h)
    out = layers[-1](h)
    assert out.shape == [1, 8, cfg.vocab_size]


def test_llama_jit_parity():
    cfg = llama_tiny()
    model = LlamaForCausalLM(cfg)
    model.eval()
    ids = P.to_tensor(np.arange(16, dtype=np.int64).reshape(1, 16) % 100)
    eager = model(ids)
    st = P.jit.to_static(model)
    jit_out = st(ids)
    np.testing.assert_allclose(eager.numpy(), jit_out.numpy(), rtol=2e-5,
                               atol=1e-5)


@pytest.mark.slow
def test_llama_incremental_decode_matches_full():
    """KV-cache decode must equal full-sequence attention (RoPE offsets)."""
    from paddle_tpu.models.llama import LlamaAttention

    cfg = llama_tiny(num_heads=4, num_kv_heads=2)
    attn = LlamaAttention(cfg)
    attn.eval()
    rng = np.random.RandomState(0)
    x_full = P.to_tensor(rng.rand(1, 6, cfg.hidden_size).astype(np.float32))
    full_out = attn(x_full)
    hd = cfg.hidden_size // cfg.num_heads
    cache = (P.to_tensor(np.zeros((1, 0, cfg.num_kv_heads, hd), np.float32)),
             P.to_tensor(np.zeros((1, 0, cfg.num_kv_heads, hd), np.float32)))
    outs = []
    for t in range(6):
        xt = P.to_tensor(x_full.numpy()[:, t:t + 1])
        out_t, cache = attn(xt, cache=cache)
        outs.append(out_t.numpy()[:, 0])
    np.testing.assert_allclose(np.stack(outs, axis=1), full_out.numpy(),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_vit_forward():
    m = V.VisionTransformer(img_size=32, patch_size=8, embed_dim=64,
                            depth=2, num_heads=4, num_classes=10)
    x = P.to_tensor(np.random.RandomState(0).rand(2, 3, 32, 32)
                    .astype(np.float32))
    out = m(x)
    assert out.shape == [2, 10]
    loss = P.mean(P.square(out))
    loss.backward()
    assert m.blocks[0].attn.qkv.weight.grad is not None


@pytest.mark.parametrize("ctor,img", [
    (lambda: V.AlexNet(num_classes=10), 224),
    (lambda: V.SqueezeNet("1.1", num_classes=10), 224),
    (lambda: V.DenseNet((2, 2), growth=8, num_classes=10, init_ch=16), 64),
    (lambda: V.ShuffleNetV2(0.5, num_classes=10), 64),
    (lambda: V.GoogLeNet(num_classes=10), 64),
])
@pytest.mark.slow
def test_vision_zoo_smoke(ctor, img):
    m = ctor()
    m.eval()
    x = P.to_tensor(np.random.RandomState(1).rand(1, 3, img, img)
                    .astype(np.float32))
    out = m(x)
    assert out.shape == [1, 10]


@pytest.mark.slow
def test_fused_chunked_ce_matches_plain():
    """The chunked online-logsumexp CE must match F.cross_entropy in value
    AND gradient (it is the default GPT loss for large vocabs)."""
    import jax.numpy as jnp

    from paddle_tpu.models.gpt import _chunked_softmax_ce
    import paddle_tpu.nn.functional as F

    rs = np.random.RandomState(4)
    n, v = 64, 9001  # odd vocab: exercises padding
    logits = rs.randn(n, v).astype(np.float32)
    labels = rs.randint(0, v, (n,)).astype(np.int32)
    labels[:5] = -100  # ignore_index tokens

    def fused(lg):
        total, count = _chunked_softmax_ce(lg, jnp.asarray(labels), -100)
        return total / count

    def plain(lg):
        return F.cross_entropy(
            P.Tensor(lg), P.Tensor(jnp.asarray(labels)),
            reduction="mean", ignore_index=-100)._value

    import jax

    f_val, f_grad = jax.value_and_grad(fused)(jnp.asarray(logits))
    p_val, p_grad = jax.value_and_grad(plain)(jnp.asarray(logits))
    np.testing.assert_allclose(float(f_val), float(p_val), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(f_grad), np.asarray(p_grad),
                               rtol=1e-4, atol=1e-6)

    # bf16 logits leg (the dtype the GPT head actually produces)
    lb = jnp.asarray(logits, jnp.bfloat16)
    fb = jax.value_and_grad(fused)(lb)
    assert np.isfinite(float(fb[0]))
    assert fb[1].dtype == jnp.bfloat16


@pytest.mark.parametrize("ctor,img", [
    ("mobilenet_v1", 64), ("mobilenet_v3_small", 64),
    ("mobilenet_v3_large", 64), ("resnext50_32x4d", 64),
    ("wide_resnet50_2", 64), ("densenet169", 64), ("inception_v3", 128),
    ("shufflenet_v2_x0_5", 64),
])
@pytest.mark.slow
def test_vision_zoo_extended_forward(ctor, img):
    """New zoo families: forward shape + grads flow (tiny inputs)."""
    from paddle_tpu.vision import models as V

    P.seed(0)
    m = getattr(V, ctor)(num_classes=7)
    m.eval()
    x = P.to_tensor(np.random.RandomState(0)
                    .randn(2, 3, img, img).astype(np.float32))
    out = m(x)
    assert out.shape == [2, 7]
    assert np.isfinite(out.numpy()).all()


@pytest.mark.slow
def test_gpt_generate_matches_full_forward_loop():
    """generate() (static KV cache + decode kernel path) must produce the
    same greedy tokens as re-running the full forward every step."""
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(7)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=64, use_rope=True)
    model = GPTForCausalLM(cfg)
    model.eval()
    rs = np.random.RandomState(0)
    prompt = rs.randint(0, cfg.vocab_size, (2, 5))

    # naive: full forward each step, greedy
    ids = prompt.copy()
    for _ in range(6):
        logits = model(P.to_tensor(ids, "int32")).numpy()
        ids = np.concatenate([ids, logits[:, -1].argmax(-1)[:, None]
                              .astype(ids.dtype)], axis=1)

    out = model.generate(P.to_tensor(prompt, "int32"), max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(out._value), ids)


@pytest.mark.slow
def test_llama_generate_gqa_matches_full_forward_loop():
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    P.seed(11)
    cfg = LlamaConfig(vocab_size=89, hidden_size=32, num_layers=2,
                      num_heads=4, num_kv_heads=2, max_seq_len=64,
                      ffn_hidden=64)
    model = LlamaForCausalLM(cfg)
    model.eval()
    rs = np.random.RandomState(1)
    prompt = rs.randint(0, cfg.vocab_size, (2, 4))

    ids = prompt.copy()
    for _ in range(5):
        logits = model(P.to_tensor(ids, "int32")).numpy()
        ids = np.concatenate([ids, logits[:, -1].argmax(-1)[:, None]
                              .astype(ids.dtype)], axis=1)

    out = model.generate(P.to_tensor(prompt, "int32"), max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(out._value), ids)


def test_generate_eos_stops_early_and_sampling_runs():
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(3)
    cfg = GPTConfig(vocab_size=31, hidden_size=16, num_layers=1,
                    num_heads=2, max_seq_len=32, use_rope=True)
    model = GPTForCausalLM(cfg)
    model.eval()
    prompt = P.to_tensor(np.zeros((1, 3), np.int64), "int32")
    out = model.generate(prompt, max_new_tokens=8, do_sample=True,
                         temperature=0.9, top_k=5, seed=0)
    arr = np.asarray(out._value)
    assert arr.shape[0] == 1 and 4 <= arr.shape[1] <= 11
    # eos: greedy emits SOME token t at step1; using it as eos stops at 1
    g = model.generate(prompt, max_new_tokens=8)
    first = int(np.asarray(g._value)[0, 3])
    g2 = model.generate(prompt, max_new_tokens=8, eos_token_id=first)
    assert np.asarray(g2._value).shape[1] == 4


def test_generate_per_row_eos_freezes_rows():
    """Rows that emit eos are frozen to eos while other rows continue
    (r3 review finding: all() only stopped on simultaneous finish)."""
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(5)
    cfg = GPTConfig(vocab_size=23, hidden_size=16, num_layers=1,
                    num_heads=2, max_seq_len=32, use_rope=True)
    model = GPTForCausalLM(cfg)
    model.eval()
    prompt_np = np.array([[1, 2, 3], [4, 5, 6]])
    base = model.generate(P.to_tensor(prompt_np, "int32"), max_new_tokens=6)
    arr = np.asarray(base._value)
    # pick row 0's first generated token as eos: row 0 freezes immediately
    eos = int(arr[0, 3])
    out = np.asarray(model.generate(P.to_tensor(prompt_np, "int32"),
                                    max_new_tokens=6,
                                    eos_token_id=eos)._value)
    assert (out[0, 3:] == eos).all()  # frozen row: eos-padded


def test_generate_program_cache_reused():
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(6)
    cfg = GPTConfig(vocab_size=19, hidden_size=16, num_layers=1,
                    num_heads=2, max_seq_len=32, use_rope=True)
    model = GPTForCausalLM(cfg)
    model.eval()
    prompt = P.to_tensor(np.ones((1, 3), np.int64), "int32")
    # each signature caches a (prefill, decode) pair + the chunked-scan
    # decode program
    model.generate(prompt, max_new_tokens=2)
    assert len(model._gen_cache) == 2
    model.generate(prompt, max_new_tokens=2)   # same sig -> cache hit
    assert len(model._gen_cache) == 2
    model.generate(prompt, max_new_tokens=2, do_sample=True, seed=0)
    assert len(model._gen_cache) == 4


def test_generate_chunked_decode_crosses_boundaries(monkeypatch):
    """The scanned-decode fast path must be bit-identical across chunk
    boundaries (token stream, PRNG order, eos trim) to a 1-token-per-
    dispatch run — shrink DECODE_CHUNK so a short generate spans several
    scans, and compare against CHUNK=1 which degenerates to the
    single-step sequence."""
    from paddle_tpu.models import generation
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    cfg = GPTConfig(vocab_size=23, hidden_size=16, num_layers=1,
                    num_heads=2, max_seq_len=64, use_rope=True)
    prompt_np = np.ones((2, 3), np.int64)

    def run(chunk, **kw):
        monkeypatch.setattr(generation, "DECODE_CHUNK", chunk)
        P.seed(6)
        model = GPTForCausalLM(cfg)
        model.eval()
        return model.generate(P.to_tensor(prompt_np, "int32"),
                              max_new_tokens=11, **kw).numpy()

    # greedy, sampling (same seed -> same key stream), and eos trim
    np.testing.assert_array_equal(run(4), run(1))
    np.testing.assert_array_equal(run(4, do_sample=True, seed=3),
                                  run(1, do_sample=True, seed=3))
    a = run(4, eos_token_id=5)
    b = run(1, eos_token_id=5)
    np.testing.assert_array_equal(a, b)


def test_llama_gqa_cache_stores_kv_heads_only():
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(vocab_size=31, hidden_size=32, num_layers=1,
                      num_heads=4, num_kv_heads=2, max_seq_len=64,
                      ffn_hidden=64)
    model = LlamaForCausalLM(cfg)
    caches = model.init_kv_caches(2, 10)
    k, v = caches[0]
    assert k.shape[1] == 2  # kv heads, not 4 query heads


def test_generate_left_padded_ragged_batch():
    """Ragged prompts via attention_mask: every row must generate the SAME
    tokens as running it alone unpadded (pad slots masked out of
    attention, rotary positions shifted per row)."""
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(21)
    cfg = GPTConfig(vocab_size=83, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=64, use_rope=True)
    model = GPTForCausalLM(cfg)
    model.eval()
    rs = np.random.RandomState(2)
    row_a = rs.randint(1, cfg.vocab_size, (6,))   # length 6
    row_b = rs.randint(1, cfg.vocab_size, (3,))   # length 3

    # solo references (no padding)
    ref_a = np.asarray(model.generate(
        P.to_tensor(row_a[None], "int32"), max_new_tokens=4)._value)[0, 6:]
    ref_b = np.asarray(model.generate(
        P.to_tensor(row_b[None], "int32"), max_new_tokens=4)._value)[0, 3:]

    # left-padded ragged batch
    ids = np.zeros((2, 6), np.int64)
    mask = np.zeros((2, 6), np.int64)
    ids[0] = row_a; mask[0] = 1
    ids[1, 3:] = row_b; mask[1, 3:] = 1
    out = np.asarray(model.generate(
        P.to_tensor(ids, "int32"), max_new_tokens=4,
        attention_mask=P.to_tensor(mask, "int32"))._value)
    np.testing.assert_array_equal(out[0, 6:], ref_a)
    np.testing.assert_array_equal(out[1, 6:], ref_b)


def test_generate_left_padded_gqa_llama():
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    P.seed(23)
    cfg = LlamaConfig(vocab_size=71, hidden_size=32, num_layers=2,
                      num_heads=4, num_kv_heads=2, max_seq_len=64,
                      ffn_hidden=64)
    model = LlamaForCausalLM(cfg)
    model.eval()
    rs = np.random.RandomState(3)
    row = rs.randint(1, cfg.vocab_size, (4,))
    ref = np.asarray(model.generate(
        P.to_tensor(row[None], "int32"), max_new_tokens=3)._value)[0, 4:]
    ids = np.zeros((2, 7), np.int64)
    mask = np.zeros((2, 7), np.int64)
    ids[0, 3:] = row; mask[0, 3:] = 1
    ids[1] = rs.randint(1, cfg.vocab_size, (7,)); mask[1] = 1
    out = np.asarray(model.generate(
        P.to_tensor(ids, "int32"), max_new_tokens=3,
        attention_mask=P.to_tensor(mask, "int32"))._value)
    np.testing.assert_array_equal(out[0, 7:], ref)


def test_generate_left_padded_learned_positions():
    """Non-rope GPT (learned wpe positions): the per-row position shift in
    GPTModel.forward must make padded rows match solo generation."""
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(27)
    cfg = GPTConfig(vocab_size=67, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=64, use_rope=False)
    model = GPTForCausalLM(cfg)
    model.eval()
    rs = np.random.RandomState(6)
    row = rs.randint(1, cfg.vocab_size, (3,))
    ref = np.asarray(model.generate(
        P.to_tensor(row[None], "int32"), max_new_tokens=4)._value)[0, 3:]
    ids = np.zeros((2, 6), np.int64); mask = np.zeros((2, 6), np.int64)
    ids[0, 3:] = row; mask[0, 3:] = 1
    ids[1] = rs.randint(1, cfg.vocab_size, (6,)); mask[1] = 1
    out = np.asarray(model.generate(
        P.to_tensor(ids, "int32"), max_new_tokens=4,
        attention_mask=P.to_tensor(mask, "int32"))._value)
    np.testing.assert_array_equal(out[0, 6:], ref)


def test_generate_rejects_bad_masks():
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    cfg = GPTConfig(vocab_size=31, hidden_size=16, num_layers=1,
                    num_heads=2, max_seq_len=32, use_rope=True)
    model = GPTForCausalLM(cfg)
    model.eval()
    ids = P.to_tensor(np.ones((1, 4), np.int64), "int32")
    with pytest.raises(ValueError, match="LEFT-padded"):
        model.generate(ids, max_new_tokens=2,
                       attention_mask=P.to_tensor(
                           np.array([[1, 1, 1, 0]]), "int32"))
    with pytest.raises(ValueError, match="contiguous"):
        model.generate(ids, max_new_tokens=2,
                       attention_mask=P.to_tensor(
                           np.array([[1, 0, 1, 1]]), "int32"))


@pytest.mark.slow
def test_beam_search_beats_or_equals_greedy():
    """num_beams=1 == greedy exactly; wider beams find a sequence whose
    total log-prob is >= greedy's (the point of beam search)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(31)
    cfg = GPTConfig(vocab_size=43, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=64, use_rope=True)
    model = GPTForCausalLM(cfg)
    model.eval()
    prompt_np = np.array([[7, 9, 11]])
    prompt = P.to_tensor(prompt_np, "int32")

    greedy = np.asarray(model.generate(prompt, max_new_tokens=5)._value)
    beam1 = np.asarray(model.generate(prompt, max_new_tokens=5,
                                      num_beams=1)._value)
    np.testing.assert_array_equal(greedy, beam1)

    beam4 = np.asarray(model.generate(prompt, max_new_tokens=5,
                                      num_beams=4)._value)
    assert beam4.shape == greedy.shape

    def seq_logprob(full):
        ids = P.to_tensor(full[:, :-1], "int32")
        logits = np.asarray(model(ids)._value, np.float32)
        lp = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), -1))
        tot = 0.0
        for t in range(prompt_np.shape[1] - 1, full.shape[1] - 1):
            tot += lp[0, t, full[0, t + 1]]
        return tot

    assert seq_logprob(beam4) >= seq_logprob(greedy) - 1e-4


def test_beam_search_eos_and_errors():
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(33)
    cfg = GPTConfig(vocab_size=29, hidden_size=16, num_layers=1,
                    num_heads=2, max_seq_len=32, use_rope=True)
    model = GPTForCausalLM(cfg)
    model.eval()
    prompt = P.to_tensor(np.array([[1, 2]]), "int32")
    out = np.asarray(model.generate(prompt, max_new_tokens=6, num_beams=3,
                                    eos_token_id=5)._value)
    assert out.shape[1] <= 8
    gen = out[0, 2:]
    if (gen == 5).any():  # once eos appears, only eos follows (pool tail)
        first = int(np.argmax(gen == 5))
        assert (gen[first:] == 5).all()
    with pytest.raises(ValueError, match="do_sample"):
        model.generate(prompt, max_new_tokens=2, num_beams=2,
                       do_sample=True)


def test_beam_search_keeps_finished_hypothesis():
    """A hypothesis that ends with eos must stay selectable even when live
    continuations out-score it in the raw beam (finished pool, r3 review
    finding): with a length_penalty strongly favoring short outputs, a
    finished short hypothesis must win over full-length live beams when
    its normalized score is higher."""
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(37)
    cfg = GPTConfig(vocab_size=23, hidden_size=16, num_layers=1,
                    num_heads=2, max_seq_len=32, use_rope=True)
    model = GPTForCausalLM(cfg)
    model.eval()
    prompt = P.to_tensor(np.array([[1, 2, 3]]), "int32")
    # pick the greedy second token as eos so SOME beam finishes early
    base = np.asarray(model.generate(prompt, max_new_tokens=2)._value)
    eos = int(base[0, 4])
    out = np.asarray(model.generate(
        prompt, max_new_tokens=8, num_beams=4, eos_token_id=eos,
        length_penalty=0.0)._value)
    gen = out[0, 3:]
    if (gen == eos).any():
        first = int(np.argmax(gen == eos))
        assert (gen[first:] == eos).all()


def test_fused_head_ce_matches_unfused():
    """cfg.fused_head_ce + GPTPretrainingCriterion(model=...): the
    projection fuses into the chunked CE (no [B,S,V] logits). Losses and
    parameter updates (incl. the tied embedding, which now gets its
    head-side gradient through the fused VJP) must match the unfused
    path step for step."""
    from paddle_tpu.distributed import fleet, topology
    from paddle_tpu.models.gpt import (
        GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
    )

    kw = dict(vocab_size=317, hidden_size=64, num_layers=2, num_heads=4,
              max_seq_len=32, dropout=0.0)
    losses = {}
    for fused in (False, True):
        topology.reset_topology()
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sep_degree": 1, "sharding_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)
        P.seed(7)
        model = GPTForCausalLM(GPTConfig(fused_head_ce=fused, **kw))
        crit = GPTPretrainingCriterion(model=model if fused else None)
        dm = fleet.distributed_model(model)
        opt = fleet.distributed_optimizer(
            P.optimizer.SGD(parameters=model.parameters(),
                            learning_rate=0.1))
        step = dm.build_train_step(opt, crit)
        rs = np.random.RandomState(0)
        ids = P.to_tensor(rs.randint(0, 317, (2, 32)), "int32")
        lab = P.to_tensor(rs.randint(0, 317, (2, 32)), "int32")
        losses[fused] = [float(step(ids, lab)) for _ in range(3)]
    np.testing.assert_allclose(losses[False], losses[True], rtol=2e-5)


def test_fused_head_ce_mismatched_criterion_raises():
    """A fused_head_ce model paired with a PLAIN criterion must fail
    loudly — hidden states silently scored as logits was the failure
    mode (r4 review)."""
    from paddle_tpu.models.gpt import (
        GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
    )

    P.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=32, num_layers=1,
                    num_heads=2, max_seq_len=16, fused_head_ce=True)
    model = GPTForCausalLM(cfg)
    model.train()
    ids = P.randint(0, 256, [2, 16])
    out = model(ids)
    crit = GPTPretrainingCriterion()  # no model= — mismatch
    with pytest.raises(RuntimeError, match="fused_head_ce"):
        crit(out, ids)
    # fused=False with model= is the same mismatch (r4 ADVICE): hidden
    # states would silently fall through to the plain-CE path
    crit2 = GPTPretrainingCriterion(model=model, fused=False)
    with pytest.raises(RuntimeError, match="fused_head_ce"):
        crit2(out, ids)


@pytest.mark.slow
def test_fused_head_ce_cuts_xla_temp_buffers():
    """The memory claim behind cut-CE (VERDICT r4 Next #4), chip-free:
    XLA's buffer assignment for the compiled train step must shrink by at
    least the [B,S,V] logits+cotangent when the head fuses into the
    chunked CE. tools/memory_report.py prints the full table."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    from memory_report import step_memory

    base = dict(vocab_size=50304, hidden_size=64, num_layers=2,
                num_heads=4, max_seq_len=128, dropout=0.0)
    batch, seq = 4, 128
    plain = step_memory(dict(base, fused_head_ce=False), batch, seq)
    fused = step_memory(dict(base, fused_head_ce=True), batch, seq)
    # [B,S,V] f32 logits alone: 4*128*50304*4 ≈ 98 MiB. XLA keeps parts
    # of the logits chain in bf16, so demand 0.75x of the f32 size —
    # still only satisfiable if the [B,S,V] buffers actually vanished
    # (measured: 95 MiB saved here; 1,809 MiB at B8 S512 h256, PERF.md)
    logits_mb = batch * seq * 50304 * 4 / 2**20
    assert plain["temp_mb"] - fused["temp_mb"] >= 0.75 * logits_mb, (
        plain, fused)


@pytest.mark.slow
def test_train_step_has_no_f32_operand_gemms():
    """MFU guard (tools/hlo_audit.py): every dot in the bf16 AMP train
    step must take bf16 OPERANDS (f32 accumulation via
    preferred_element_type is the full-rate MXU mode; an f32-operand dot
    runs at quarter rate). The round-5 audit measured 40/40 bf16 — this
    pins it."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    from hlo_audit import audit_hlo, train_step_hlo

    report = audit_hlo(train_step_hlo(batch=2, seq=256, layers=2))
    assert report["dot_counts"]["f32_operands"] == 0, report
    assert report["dot_counts"]["mixed"] == 0, report
    assert not report["big_non_bf16_dots"], report
    assert report["dot_counts"]["bf16_operands"] > 0, report


# =============================== ERNIE ===============================


def _ernie_batch(cfg, B=4, S=32, seed=0):
    rs = np.random.RandomState(seed)
    ids = rs.randint(1, cfg.vocab_size, (B, S))
    ids[:, -4:] = cfg.pad_token_id
    labels = np.full((B, S), -100)
    labels[:, 2:6] = rs.randint(1, cfg.vocab_size, (B, 4))
    nsp = rs.randint(0, 2, (B,))
    return ids, labels, nsp


def test_ernie_pretraining_overfits():
    """ERNIE encoder family (BASELINE config 4's named model): MLM+NSP
    objective over the nn.TransformerEncoder stack must optimize."""
    from paddle_tpu.models import (
        ErnieForPretraining, ErniePretrainingCriterion, ernie_tiny,
    )

    P.seed(0)
    cfg = ernie_tiny(dropout=0.0)
    m = ErnieForPretraining(cfg)
    crit = ErniePretrainingCriterion()
    ids_np, labels_np, nsp_np = _ernie_batch(cfg)
    ids = P.to_tensor(ids_np, "int32")
    labels = P.to_tensor(labels_np, "int64")
    nsp = P.to_tensor(nsp_np, "int64")
    opt = P.optimizer.AdamW(parameters=m.parameters(), learning_rate=5e-3)
    losses = []
    for _ in range(8):
        logits, nsp_logits = m(ids)
        loss = crit(logits, nsp_logits, labels, nsp)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(loss._value)))
    assert losses[-1] < losses[0] * 0.7, losses
    # MLM-only mode (no NSP labels) returns just the masked-CE term of
    # the same total, so it is strictly below MLM+NSP
    solo = crit(logits, nsp_logits, labels)
    assert float(solo) < losses[-1] + 1e-6
    assert np.isfinite(float(solo))


def test_ernie_padding_tokens_do_not_leak():
    """The [B,S] 1/0 attention mask becomes a stop-gradient additive
    bias: changing a PADDING token's id must not change any real token's
    logits (the bias path the fused biased-flash tier streams on TPU)."""
    from paddle_tpu.models import ErnieForPretraining, ernie_tiny

    P.seed(1)
    cfg = ernie_tiny(dropout=0.0)
    m = ErnieForPretraining(cfg)
    m.eval()
    ids_np, _, _ = _ernie_batch(cfg, seed=2)
    mask = P.to_tensor((ids_np != cfg.pad_token_id).astype(np.float32))
    ids2_np = ids_np.copy()
    ids2_np[0, -1] = 7  # mutate a padded slot
    lg1, _ = m(P.to_tensor(ids_np, "int32"), attention_mask=mask)
    lg2, _ = m(P.to_tensor(ids2_np, "int32"), attention_mask=mask)
    real = np.s_[:, :-4]
    np.testing.assert_allclose(np.asarray(lg1._value)[real],
                               np.asarray(lg2._value)[real], atol=1e-4)
