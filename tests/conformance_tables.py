"""Conformance-sweep op tables — the single source shared by
tests/test_op_conformance.py (which parametrizes FROM OPS_MANIFEST.json and
resolves specs here) and tools/gen_op_manifest.py (which stamps each op's
manifest `conformance` entry from these tables).

Reference role: the per-op metadata rows of `paddle/phi/api/yaml/ops.yaml`
(backward link, inplace map) — here the `grad` bit is machine-true: it is
exactly the set of ops whose numeric-grad check the sweep executes.
"""
import numpy as np

rs = np.random.RandomState(11)


def _pos(shape):
    return np.asarray(rs.rand(*shape) + 0.5, np.float32)


def _std(shape):
    return np.asarray(rs.randn(*shape), np.float32)


def _unit(shape):
    return np.asarray(rs.rand(*shape) * 1.6 - 0.8, np.float32)


# name -> (input factory, numpy ref or None, grad-checkable)
UNARY_OPS = {
    "abs": (_std, np.abs, True),
    "acos": (_unit, np.arccos, True),
    "acosh": (lambda s: _pos(s) + 1.0, np.arccosh, True),
    "asin": (_unit, np.arcsin, True),
    "asinh": (_std, np.arcsinh, True),
    "atan": (_std, np.arctan, True),
    "atanh": (_unit, np.arctanh, True),
    "ceil": (_std, np.ceil, False),
    "cos": (_std, np.cos, True),
    "cosh": (_std, np.cosh, True),
    "digamma": (_pos, None, True),
    "erf": (_std, None, True),
    "erfinv": (_unit, None, True),
    "exp": (_std, np.exp, True),
    "expm1": (_std, np.expm1, True),
    "floor": (_std, np.floor, False),
    "frac": (_std, lambda x: x - np.trunc(x), False),
    "i0": (_pos, None, True),
    "i0e": (_pos, None, True),
    "i1": (_pos, None, True),
    "i1e": (_pos, None, True),
    "gammaln": (_pos, None, True),
    "lgamma": (_pos, None, True),
    "log": (_pos, np.log, True),
    "log10": (_pos, np.log10, True),
    "log1p": (_pos, np.log1p, True),
    "log2": (_pos, np.log2, True),
    "logit": (lambda s: np.asarray(rs.rand(*s) * 0.8 + 0.1, np.float32),
              None, True),
    "neg": (_std, np.negative, True),
    "reciprocal": (_pos, np.reciprocal, True),
    "round": (_std, np.round, False),
    "rsqrt": (_pos, lambda x: 1 / np.sqrt(x), True),
    "sigmoid": (_std, lambda x: 1 / (1 + np.exp(-x)), True),
    "sign": (_std, np.sign, False),
    "signbit": (_std, np.signbit, False),
    "sin": (_std, np.sin, True),
    "sinh": (_std, np.sinh, True),
    "sqrt": (_pos, np.sqrt, True),
    "square": (_std, np.square, True),
    "tan": (_unit, np.tan, True),
    "tanh": (_std, np.tanh, True),
    "trunc": (_std, np.trunc, False),
}

BINARY_OPS = {
    "add": (np.add, True),
    "subtract": (np.subtract, True),
    "multiply": (np.multiply, True),
    "divide": (np.true_divide, True),
    "maximum": (np.maximum, True),
    "minimum": (np.minimum, True),
    "pow": (None, True),
    "atan2": (np.arctan2, True),
    "fmax": (np.fmax, True),
    "fmin": (np.fmin, True),
    "hypot": (np.hypot, True),
    "ldexp": (None, False),
    "logaddexp": (np.logaddexp, True),
    "nextafter": (np.nextafter, False),
    "remainder": (None, False),
    "floor_divide": (None, False),
    "lerp": (None, True),
}

REDUCTIONS = {
    "sum": np.sum, "mean": np.mean, "max": np.max, "min": np.min,
    "prod": np.prod, "std": None, "var": None, "median": None,
    "logsumexp": None, "all": None, "any": None,
    "amax": np.max, "amin": np.min, "nansum": np.nansum,
    "nanmean": np.nanmean,
}




def specs():
    """{name: {kind, grad}} for the manifest generator."""
    out = {}
    for n, (_, _, g) in UNARY_OPS.items():
        out[n] = {"kind": "unary", "grad": bool(g)}
    for n, (_, g) in BINARY_OPS.items():
        out[n] = {"kind": "binary", "grad": bool(g)}
    for n in REDUCTIONS:
        out[n] = {"kind": "reduction", "grad": False}
    for n in COMPARISON_OPS:
        out[n] = {"kind": "comparison", "grad": False}
    for n in INT_BINARY_OPS:
        out[n] = {"kind": "int_binary", "grad": False}
    for n in INT_UNARY_OPS:
        out[n] = {"kind": "int_unary", "grad": False}
    return out


# comparison / logical binaries: float inputs, bool outputs, no grads
COMPARISON_OPS = {
    "equal": np.equal,
    "not_equal": np.not_equal,
    "greater_than": np.greater,
    "greater_equal": np.greater_equal,
    "less_than": np.less,
    "less_equal": np.less_equal,
    "logical_and": np.logical_and,
    "logical_or": np.logical_or,
    "logical_xor": np.logical_xor,
}

# integer binaries (bitwise + number theory)
INT_BINARY_OPS = {
    "bitwise_and": np.bitwise_and,
    "bitwise_or": np.bitwise_or,
    "bitwise_xor": np.bitwise_xor,
    "gcd": np.gcd,
    "lcm": np.lcm,
}

# unary over ints
INT_UNARY_OPS = {
    "bitwise_not": np.bitwise_not,
}
