"""Decode-time masked MHA vs a full-attention reference."""
import numpy as np

import paddle_tpu as P
from paddle_tpu.incubate.nn import functional as IF


def test_masked_multihead_attention_decode_loop():
    B, H, D, S = 2, 2, 8, 6
    rng = np.random.RandomState(0)
    cache = P.to_tensor(np.zeros((2, B, H, S, D), np.float32))
    toks = rng.rand(S, B, 3 * H * D).astype(np.float32)

    outs = []
    for t in range(4):
        x = P.to_tensor(toks[t])
        seq = P.to_tensor(np.full((B,), t, np.int32))
        out, cache = IF.masked_multihead_attention(
            x, cache_kv=cache, sequence_lengths=seq)
        outs.append(out.numpy())

    # reference: causal attention of token t over tokens 0..t
    qkv = toks[:4].reshape(4, B, 3, H, D)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    for t in range(4):
        ref = np.zeros((B, H, D), np.float32)
        for b in range(B):
            for h in range(H):
                sc = np.array([q[t, b, h] @ k[j, b, h] for j in range(t + 1)])
                sc = sc / np.sqrt(D)
                p = np.exp(sc - sc.max())
                p /= p.sum()
                ref[b, h] = sum(p[j] * v[j, b, h] for j in range(t + 1))
        np.testing.assert_allclose(outs[t], ref.reshape(B, H * D),
                                   rtol=1e-4, atol=1e-5)
