"""Decode-time masked MHA vs a full-attention reference."""
import numpy as np

import paddle_tpu as P
from paddle_tpu.incubate.nn import functional as IF


def test_masked_multihead_attention_decode_loop():
    B, H, D, S = 2, 2, 8, 6
    rng = np.random.RandomState(0)
    cache = P.to_tensor(np.zeros((2, B, H, S, D), np.float32))
    toks = rng.rand(S, B, 3 * H * D).astype(np.float32)

    outs = []
    for t in range(4):
        x = P.to_tensor(toks[t])
        seq = P.to_tensor(np.full((B,), t, np.int32))
        out, cache = IF.masked_multihead_attention(
            x, cache_kv=cache, sequence_lengths=seq)
        outs.append(out.numpy())

    # reference: causal attention of token t over tokens 0..t
    qkv = toks[:4].reshape(4, B, 3, H, D)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    for t in range(4):
        ref = np.zeros((B, H, D), np.float32)
        for b in range(B):
            for h in range(H):
                sc = np.array([q[t, b, h] @ k[j, b, h] for j in range(t + 1)])
                sc = sc / np.sqrt(D)
                p = np.exp(sc - sc.max())
                p /= p.sum()
                ref[b, h] = sum(p[j] * v[j, b, h] for j in range(t + 1))
        np.testing.assert_allclose(outs[t], ref.reshape(B, H * D),
                                   rtol=1e-4, atol=1e-5)


def test_block_multihead_attention_decode_matches_dense():
    """Paged-cache decode == dense-cache attention on the same tokens."""
    import paddle_tpu.incubate.nn.functional as IF

    rs = np.random.RandomState(0)
    B, H, D, BS, NBLK = 2, 2, 8, 4, 6  # block_size 4, 6-block pool
    max_blocks_per_seq = 3
    # two sequences with 5 and 2 cached tokens
    lens = np.array([5, 2], np.int32)
    kc = np.zeros((NBLK, H, BS, D), np.float32)
    vc = np.zeros((NBLK, H, BS, D), np.float32)
    bt = np.array([[0, 2, 4], [1, 3, 5]], np.int32)
    dense_k = np.zeros((B, H, 12, D), np.float32)
    dense_v = np.zeros((B, H, 12, D), np.float32)
    for b in range(B):
        for t in range(lens[b]):
            kv = rs.randn(H, D).astype(np.float32)
            vv = rs.randn(H, D).astype(np.float32)
            phys = bt[b, t // BS]
            kc[phys, :, t % BS] = kv
            vc[phys, :, t % BS] = vv
            dense_k[b, :, t] = kv
            dense_v[b, :, t] = vv
    qkv = rs.randn(B, 3 * H * D).astype(np.float32)
    out, kc2, vc2 = IF.block_multihead_attention(
        P.to_tensor(qkv), P.to_tensor(kc), P.to_tensor(vc),
        P.to_tensor(lens * 0), P.to_tensor(lens), P.to_tensor(lens * 0 + 1),
        block_tables=P.to_tensor(bt), block_size=BS)
    # dense reference: append new token, causal-decode attention
    q3 = qkv.reshape(B, 3, H, D)
    q, kn, vn = q3[:, 0], q3[:, 1], q3[:, 2]
    for b in range(B):
        dense_k[b, :, lens[b]] = kn[b]
        dense_v[b, :, lens[b]] = vn[b]
    logits = np.einsum("bhd,bhsd->bhs", q, dense_k) / np.sqrt(D)
    valid = np.arange(12)[None, :] <= lens[:, None]
    logits = np.where(valid[:, None, :], logits, -1e30)
    pr = np.exp(logits - logits.max(-1, keepdims=True))
    pr /= pr.sum(-1, keepdims=True)
    ref = np.einsum("bhs,bhsd->bhd", pr, dense_v).reshape(B, H * D)
    np.testing.assert_allclose(np.asarray(out.numpy()), ref, rtol=1e-4,
                               atol=1e-5)
    # the new token landed in the right physical block slot
    kc2 = np.asarray(kc2.numpy())
    assert np.allclose(kc2[bt[0, 1], :, 1], kn[0])  # seq0: pos5 -> blk1 slot1


def test_variable_length_memory_efficient_attention_lengths():
    """Per-row kv lengths must actually mask (r4 fix: seq_lens were
    silently ignored): row 0 truncated to 3 keys == dense attention on
    the 3-key prefix; explicit scale honored."""
    import numpy as np

    import paddle_tpu as P
    from paddle_tpu.incubate.nn import functional as IF

    rs = np.random.RandomState(0)
    B, H, S, D = 2, 2, 8, 16
    q = P.to_tensor(rs.randn(B, H, S, D).astype(np.float32))
    k = P.to_tensor(rs.randn(B, H, S, D).astype(np.float32))
    v = P.to_tensor(rs.randn(B, H, S, D).astype(np.float32))
    kv_lens = P.to_tensor(np.array([3, 8], np.int32))
    scale = 0.31
    out = IF.variable_length_memory_efficient_attention(
        q, k, v, seq_lens=kv_lens, kv_seq_lens=kv_lens, scale=scale)
    o = np.asarray(out.numpy())

    def dense(qr, kr, vr):
        logits = np.einsum("hqd,hkd->hqk", qr, kr) * scale
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        return np.einsum("hqk,hkd->hqd", p, vr)

    qn, kn, vn = (np.asarray(t.numpy()) for t in (q, k, v))
    # row 0: only first 3 keys participate
    np.testing.assert_allclose(
        o[0], dense(qn[0], kn[0, :, :3], vn[0, :, :3]), rtol=2e-5,
        atol=2e-5)
    # row 1: full length
    np.testing.assert_allclose(o[1], dense(qn[1], kn[1], vn[1]),
                               rtol=2e-5, atol=2e-5)


def test_fused_api_loud_unsupported_params():
    """Parameters the TPU build cannot honor must raise, not silently
    no-op (r4 silent-parameter audit)."""
    import numpy as np
    import pytest

    import paddle_tpu as P
    from paddle_tpu.incubate.nn import functional as IF
    import paddle_tpu.nn.functional as F

    x = P.to_tensor(np.ones((2, 4, 8), np.float32))
    w = P.to_tensor(np.ones((8,), np.float32))
    with pytest.raises(NotImplementedError, match="quant_scale"):
        IF.fused_rms_norm(x, w, quant_scale=0.5)
    q = P.to_tensor(np.ones((1, 4, 2, 8), np.float32))
    with pytest.raises(NotImplementedError, match="time_major"):
        F.fused_rotary_position_embedding(q, time_major=True)
    with pytest.raises(NotImplementedError, match="group"):
        F.margin_cross_entropy(
            P.to_tensor(np.zeros((2, 4), np.float32)),
            P.to_tensor(np.zeros((2,), np.int64)), group=object())
    with pytest.warns(UserWarning, match="fastemit"):
        F.rnnt_loss(P.to_tensor(np.zeros((1, 2, 2, 3), np.float32)),
                    P.to_tensor(np.zeros((1, 1), np.int32)),
                    P.to_tensor(np.array([2], np.int32)),
                    P.to_tensor(np.array([1], np.int32)),
                    fastemit_lambda=0.001)


def test_ctc_loss_norm_by_times():
    """norm_by_times divides each sample's loss by its input length
    (warpctc semantics; was silently ignored)."""
    import numpy as np

    import paddle_tpu as P
    import paddle_tpu.nn.functional as F

    rs = np.random.RandomState(0)
    T, B, C, L = 6, 2, 5, 2
    lp = P.to_tensor(
        np.log(np.random.RandomState(0).dirichlet(np.ones(C), (T, B))
               .astype(np.float32)))
    labels = P.to_tensor(rs.randint(1, C, (B, L)), "int32")
    in_len = P.to_tensor(np.array([6, 4], np.int32))
    lab_len = P.to_tensor(np.array([2, 1], np.int32))
    plain = F.ctc_loss(lp, labels, in_len, lab_len, reduction="none")
    normed = F.ctc_loss(lp, labels, in_len, lab_len, reduction="none",
                        norm_by_times=True)
    np.testing.assert_allclose(
        np.asarray(normed.numpy()),
        np.asarray(plain.numpy()) / np.array([6.0, 4.0]), rtol=1e-6)


def test_fused_linear_activation_trans_x_matrix_dims_only():
    """trans_x must transpose the MATRIX dims (reference
    fused_gemm_epilogue semantics), not reverse all dims — a 3-D input
    through .T would silently produce a wrong layout (r4 ADVICE)."""
    rng = np.random.RandomState(1)
    x = rng.randn(2, 8, 4).astype(np.float32)   # [batch, k, m] pre-trans
    w = rng.randn(8, 5).astype(np.float32)
    b = rng.randn(5).astype(np.float32)
    out = IF.fused_linear_activation(
        P.to_tensor(x), P.to_tensor(w), P.to_tensor(b), trans_x=True,
        activation="relu")
    ref = np.maximum(np.swapaxes(x, -1, -2) @ w + b, 0.0)
    assert list(out.shape) == [2, 4, 5]
    np.testing.assert_allclose(np.asarray(out.numpy()), ref, rtol=1e-5)
