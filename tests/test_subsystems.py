"""Aux subsystem tests: distributed checkpoint, hapi Model, profiler,
launcher env, jit save/load."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as P
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet, topology
from paddle_tpu.core.export_compat import jax_export_available

requires_jax_export = pytest.mark.skipif(
    not jax_export_available(),
    reason="jax.export unavailable in this jax build")


@pytest.fixture(autouse=True)
def fresh_topology():
    topology.reset_topology()
    yield
    topology.reset_topology()


def test_dist_checkpoint_roundtrip(tmp_path):
    from paddle_tpu.distributed.checkpoint import (
        load_state_dict, save_state_dict,
    )

    P.seed(0)
    m = nn.Linear(8, 8)
    sd = m.state_dict()
    save_state_dict(sd, str(tmp_path / "ckpt"))
    m2 = nn.Linear(8, 8)
    sd2 = m2.state_dict()
    load_state_dict(sd2, str(tmp_path / "ckpt"))
    np.testing.assert_allclose(sd2["weight"].numpy(), sd["weight"].numpy())


def test_dist_checkpoint_reshard(tmp_path):
    """Save sharded one way, load into a differently-sharded target."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as Pt

    from paddle_tpu.distributed.checkpoint import (
        load_state_dict, save_state_dict,
    )

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 2, "pp_degree": 1,
                               "sep_degree": 1, "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    topo = fleet.get_hybrid_communicate_group()
    data = np.arange(64, dtype=np.float32).reshape(8, 8)
    # saved dp-sharded on rows
    src = P.Tensor(jax.device_put(
        data, NamedSharding(topo.spmd_mesh, Pt("dp", None))))
    save_state_dict({"w": src}, str(tmp_path / "ck2"))
    # load into an mp-sharded-on-cols target
    tgt = P.Tensor(jax.device_put(
        np.zeros((8, 8), np.float32),
        NamedSharding(topo.spmd_mesh, Pt(None, "mp"))))
    load_state_dict({"w": tgt}, str(tmp_path / "ck2"))
    np.testing.assert_allclose(np.asarray(tgt._value), data)
    assert "mp" in str(tgt._value.sharding.spec)


def test_dist_checkpoint_no_full_materialization(tmp_path):
    """Loading a sharded target must assemble per-device blocks only —
    never the full global tensor on host (reference point-to-point load,
    load_state_dict.py:65)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as Pt

    from paddle_tpu.distributed.checkpoint import (
        load_state_dict, save_state_dict,
    )
    from paddle_tpu.distributed.checkpoint.api import last_load_stats

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 1,
                               "sep_degree": 1, "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    topo = fleet.get_hybrid_communicate_group()
    data = np.arange(256, dtype=np.float32).reshape(16, 16)
    # saved mp-sharded on cols, dp-replicated (exercises save dedup too)
    src = P.Tensor(jax.device_put(
        data, NamedSharding(topo.spmd_mesh, Pt(None, "mp"))))
    save_state_dict({"w": src}, str(tmp_path / "ck3"))
    # target sharded over BOTH axes: blocks are 8x8 = 64 elems
    tgt = P.Tensor(jax.device_put(
        np.zeros((16, 16), np.float32),
        NamedSharding(topo.spmd_mesh, Pt("dp", "mp"))))
    load_state_dict({"w": tgt}, str(tmp_path / "ck3"))
    np.testing.assert_allclose(np.asarray(tgt._value), data)
    assert last_load_stats["full_materialized"] == []
    assert last_load_stats["max_block_elems"] <= 64, last_load_stats


def test_dist_checkpoint_bf16_bit_exact(tmp_path):
    """bfloat16 shards must round-trip bit-for-bit (no float32 detour)."""
    import jax.numpy as jnp
    import ml_dtypes

    from paddle_tpu.distributed.checkpoint import (
        load_state_dict, save_state_dict,
    )

    rs = np.random.RandomState(7)
    vals = rs.randn(32, 8).astype(ml_dtypes.bfloat16)
    src = P.Tensor(jnp.asarray(vals))
    save_state_dict({"p": src}, str(tmp_path / "ckbf"))
    tgt = P.Tensor(jnp.zeros((32, 8), jnp.bfloat16))
    load_state_dict({"p": tgt}, str(tmp_path / "ckbf"))
    out = np.asarray(tgt._value)
    assert out.dtype == ml_dtypes.bfloat16
    assert np.array_equal(
        out.view(np.uint16), vals.view(np.uint16))


def test_dist_checkpoint_async_save(tmp_path):
    """async_save: snapshot is taken synchronously (mutating the state
    dict right after save must not corrupt the checkpoint), IO runs on a
    background thread, wait_async_save() is the completion barrier."""
    import jax.numpy as jnp

    from paddle_tpu.distributed.checkpoint import (
        load_state_dict, save_state_dict, wait_async_save,
    )
    from paddle_tpu.distributed.checkpoint import api as ck_api

    data = np.arange(64, dtype=np.float32).reshape(8, 8)
    src = P.Tensor(jnp.asarray(data))
    sd = {"w": src}
    save_state_dict(sd, str(tmp_path / "cka"), async_save=True)
    assert ck_api._async_save_thread is not None  # really backgrounded
    # clobber the live tensor immediately — the snapshot must be immune
    sd["w"]._value = jnp.zeros((8, 8), jnp.float32)
    wait_async_save()
    assert ck_api._async_save_thread is None
    tgt = P.Tensor(jnp.zeros((8, 8), jnp.float32))
    load_state_dict({"w": tgt}, str(tmp_path / "cka"))
    np.testing.assert_allclose(np.asarray(tgt._value), data)

    # load right after an async save (no explicit wait): load's own
    # barrier must see the finished file
    save_state_dict({"w": P.Tensor(jnp.asarray(data * 2))},
                    str(tmp_path / "ckb"), async_save=True)
    tgt2 = P.Tensor(jnp.zeros((8, 8), jnp.float32))
    load_state_dict({"w": tgt2}, str(tmp_path / "ckb"))
    np.testing.assert_allclose(np.asarray(tgt2._value), data * 2)


def test_hapi_model_fit(tmp_path):
    from paddle_tpu.hapi import Model
    from paddle_tpu.metric import Accuracy
    from paddle_tpu.vision.datasets import FakeData

    P.seed(0)
    net = nn.Sequential(nn.Flatten(), nn.Linear(48, 10))
    model = Model(net)
    model.prepare(
        optimizer=P.optimizer.Adam(parameters=net.parameters(),
                                   learning_rate=1e-2),
        loss=nn.CrossEntropyLoss(),
        metrics=Accuracy())
    data = FakeData(size=64, image_shape=(3, 4, 4), num_classes=10)
    model.fit(data, batch_size=16, epochs=1, verbose=0)
    res = model.evaluate(data, batch_size=16)
    assert "loss" in res and "acc" in res
    preds = model.predict(data, batch_size=16, stack_outputs=True)
    assert preds[0].shape == (64, 10)
    model.save(str(tmp_path / "m"))
    model.load(str(tmp_path / "m"))


def test_profiler_chrome_export(tmp_path):
    import paddle_tpu.profiler as profiler

    prof = profiler.Profiler(
        scheduler=profiler.make_scheduler(record=2),
        on_trace_ready=None, timer_only=True)
    prof.start()
    for _ in range(2):
        with profiler.RecordEvent("train_step"):
            (P.randn([32, 32]) @ P.randn([32, 32])).numpy()
        prof.step()
    prof.stop()
    path = prof.export(str(tmp_path / "trace.json"))
    with open(path) as f:
        trace = json.load(f)
    names = {e["name"] for e in trace["traceEvents"]}
    assert "train_step" in names
    agg = prof.summary()
    assert "train_step" in agg


def test_launcher_env_build():
    from paddle_tpu.distributed.launch.main import build_env, parse_args

    args = parse_args(["--nnodes", "2", "--rank", "1",
                       "--master", "10.0.0.1:8476", "train.py"])
    env = build_env(args)
    assert env["PADDLE_TRAINER_ID"] == "1"
    assert env["PADDLE_TRAINERS_NUM"] == "2"
    assert env["COORDINATOR_ADDRESS"] == "10.0.0.1:8476"


@pytest.mark.slow
def test_launcher_end_to_end(tmp_path):
    """Shell out to the REAL launcher (SURVEY §4 mechanism 2c — the
    reference's test_communication_api_base.py:59 drives
    `python -m paddle.distributed.launch` the same way): two workers on
    localhost, per-rank env wiring, per-rank log files, rc 0."""
    import subprocess
    import sys

    script = tmp_path / "worker.py"
    script.write_text(
        "import os\n"
        "print('rank', os.environ['PADDLE_TRAINER_ID'], 'of',\n"
        "      os.environ['PADDLE_TRAINERS_NUM'], flush=True)\n")
    log_dir = tmp_path / "logs"
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # keep workers off the tunnel
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--log_dir", str(log_dir), str(script)],
        env=env, cwd=repo, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "rank 0 of 2" in (log_dir / "workerlog.0").read_text()
    assert "rank 1 of 2" in (log_dir / "workerlog.1").read_text()


@pytest.mark.slow
def test_launcher_restart_recovers_and_gives_up(tmp_path):
    """--max_restart semantics end-to-end: a worker that fails once is
    relaunched and the pod exits 0; a permanently failing worker exhausts
    the budget and the launcher surfaces its exit code."""
    import subprocess
    import sys

    marker = tmp_path / "attempted"
    flaky = tmp_path / "flaky.py"
    flaky.write_text(
        f"import os, sys\n"
        f"m = {str(marker)!r}\n"
        f"if not os.path.exists(m):\n"
        f"    open(m, 'w').close()\n"
        f"    sys.exit(7)\n"
        f"print('recovered', flush=True)\n")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--max_restart", "2", "--log_dir", str(tmp_path / "l1"),
         str(flaky)],
        env=env, cwd=repo, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "restarting pod" in r.stderr

    dead = tmp_path / "dead.py"
    dead.write_text("import sys; sys.exit(9)\n")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--max_restart", "1", "--log_dir", str(tmp_path / "l2"),
         str(dead)],
        env=env, cwd=repo, capture_output=True, text=True, timeout=120)
    assert r.returncode == 9, (r.stdout, r.stderr)
    assert "giving up" in r.stderr


def _launch_two_process(tmp_path, worker_src, timeout=420):
    """Shared 2-process launcher harness: writes the worker (sys.path
    preamble prepended), scrubs the TPU tunnel out of the env, launches
    via `paddle_tpu.distributed.launch`, asserts rc == 0, and returns
    {rank: workerlog text}."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = tmp_path / "worker.py"
    worker.write_text(
        f"import os, sys\nsys.path.insert(0, {repo!r})\n" + worker_src)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # keep ranks off the tunnel
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    log_dir = tmp_path / "logs"
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--log_dir", str(log_dir),
         str(worker)],
        env=env, cwd=repo, capture_output=True, text=True, timeout=timeout)
    logs = {i: (log_dir / f"workerlog.{i}").read_text()
            for i in range(2) if (log_dir / f"workerlog.{i}").exists()}
    assert r.returncode == 0, (r.stdout, r.stderr, logs)
    return logs


@pytest.mark.slow
def test_launcher_two_process_jax_distributed(tmp_path):
    """REAL multi-process collective through the launcher (SURVEY §2.2
    TCPStore role → jax coordination service): two ranks initialize
    jax.distributed over the launcher-provided COORDINATOR_ADDRESS, see
    a 2-device global topology, and allgather across processes."""
    logs = _launch_two_process(tmp_path, (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from paddle_tpu.distributed.parallel import init_parallel_env\n"
        "init_parallel_env()\n"
        "assert jax.process_count() == 2, jax.process_count()\n"
        "assert jax.device_count() == 2, jax.device_count()\n"
        "from jax.experimental import multihost_utils\n"
        "rank = jax.process_index()\n"
        "got = multihost_utils.process_allgather(\n"
        "    jnp.asarray([float(rank + 1)]))\n"
        "assert got.ravel().tolist() == [1.0, 2.0], got\n"
        "print('rank', rank, 'allgather ok', flush=True)\n"))
    text = "".join(logs.values())
    assert "rank 0 allgather ok" in text and "rank 1 allgather ok" in text


def _two_process_training(tmp_path, dp, mp, sharding, per_rank_seed):
    """Two launcher-spawned processes over the jax coordination service
    form one global 2-device mesh and run the compiled hybrid train step
    (SURVEY §2.2 comm backend at scale). Returns per-rank loss strings."""
    logs = _launch_two_process(tmp_path, (
        "import numpy as np\n"
        "import jax\n"
        "import paddle_tpu as P\n"
        "from paddle_tpu.distributed import fleet, topology\n"
        "from paddle_tpu.distributed.parallel import init_parallel_env\n"
        "from paddle_tpu.models.gpt import (GPTForCausalLM,\n"
        "    GPTPretrainingCriterion, gpt_tiny)\n"
        "init_parallel_env()\n"
        "assert jax.process_count() == 2\n"
        "rank = jax.process_index()\n"
        "topology.reset_topology()\n"
        "strategy = fleet.DistributedStrategy()\n"
        f"strategy.hybrid_configs = {{'dp_degree': {dp}, "
        f"'mp_degree': {mp},\n"
        "    'pp_degree': 1, 'sep_degree': 1, "
        f"'sharding_degree': {dp if sharding else 1}}}\n"
        + ("strategy.sharding = True\n"
           "strategy.sharding_configs = {'stage': 2}\n" if sharding
           else "")
        + "fleet.init(is_collective=True, strategy=strategy)\n"
        "P.seed(0)  # same init on both ranks\n"
        "model = fleet.distributed_model(GPTForCausalLM(gpt_tiny()))\n"
        "opt = fleet.distributed_optimizer(P.optimizer.AdamW(\n"
        "    parameters=model.parameters(), learning_rate=1e-3))\n"
        "crit = GPTPretrainingCriterion()\n"
        + (f"rs = np.random.RandomState(100 + rank)\n" if per_rank_seed
           else "rs = np.random.RandomState(100)\n")
        + "ids = P.to_tensor(rs.randint(0, 1024, (2, 32)), 'int32')\n"
        "labels = P.to_tensor(rs.randint(0, 1024, (2, 32)), 'int32')\n"
        "losses = [float(model.train_batch((ids, labels), optimizer=opt,\n"
        "    loss_fn=crit)) for _ in range(3)]\n"
        "assert all(np.isfinite(l) for l in losses), losses\n"
        "assert losses[-1] < losses[0], losses\n"
        "print('rank', rank, 'losses', [round(l, 6) for l in losses],\n"
        "      flush=True)\n"))
    import re as _re

    return {i: _re.search(r"losses \[([^\]]+)\]", logs[i]).group(1)
            for i in logs}


@pytest.mark.slow
def test_two_process_data_parallel_training(tmp_path):
    """dp=2 + ZeRO-2 across processes: each rank feeds its LOCAL batch
    shard; grads all-reduce and dp-sharded optimizer slots assemble
    across processes. Losses identical on both ranks and decreasing."""
    got = _two_process_training(tmp_path, dp=2, mp=1, sharding=True,
                                per_rank_seed=True)
    assert got[0] == got[1], got


@pytest.mark.slow
def test_two_process_tensor_parallel_training(tmp_path):
    """mp=2 across processes: Column/RowParallelLinear weights are
    SHARDED over non-addressable devices (global-array assembly in
    _put_state) and activations all-reduce over ICI-analog sockets.
    Same data both ranks; losses identical and decreasing."""
    got = _two_process_training(tmp_path, dp=1, mp=2, sharding=False,
                                per_rank_seed=False)
    assert got[0] == got[1], got


@pytest.mark.slow
def test_two_process_spmd_pipeline(tmp_path):
    """pp=2 ACROSS processes: the collective (one-program) pipeline runs
    stage 0 on rank 0's device and stage 1 on rank 1's, boundary
    activations crossing processes as ppermute collectives — the thing
    the per-stage-jit tier cannot do (a process cannot jit onto devices
    it does not own). Both ranks must see the sequential oracle's values
    and gradients."""
    logs = _launch_two_process(tmp_path, (
        "import numpy as np\n"
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "import paddle_tpu  # noqa: F401 (plugin/bootstrap parity)\n"
        "from paddle_tpu.distributed.parallel import init_parallel_env\n"
        "from paddle_tpu.distributed.pipeline_spmd import (\n"
        "    spmd_pipeline, spmd_pipeline_reference, stack_stages)\n"
        "init_parallel_env()\n"
        "assert jax.process_count() == 2\n"
        "mesh = Mesh(np.array(jax.devices()), ('pp',))\n"
        "def block(p, a):\n"
        "    h = jax.nn.gelu(a @ p['w'] + p['b'])\n"
        "    return a + h\n"
        "rs = np.random.RandomState(0)\n"
        "stages = [{'w': jnp.asarray(rs.randn(8, 8) * 0.1, jnp.float32),\n"
        "           'b': jnp.asarray(rs.randn(8) * 0.1, jnp.float32)}\n"
        "          for _ in range(2)]\n"
        "x = jnp.asarray(rs.randn(4, 2, 8), jnp.float32)\n"
        "stacked = jax.tree_util.tree_map(\n"
        "    lambda l: jax.device_put(l, NamedSharding(\n"
        "        mesh, P(*(('pp',) + (None,) * (l.ndim - 1))))),\n"
        "    stack_stages(stages))\n"
        "def loss_pp(s, x):\n"
        "    return jnp.mean(spmd_pipeline(block, s, x, mesh=mesh) ** 2)\n"
        "def loss_seq(ss, x):\n"
        "    return jnp.mean(spmd_pipeline_reference(block, ss, x) ** 2)\n"
        "lp, gp = jax.value_and_grad(loss_pp)(stacked, x)\n"
        "lw, gw = jax.value_and_grad(loss_seq)(stages, x)\n"
        "gw = stack_stages(gw)\n"
        "lp = float(jax.device_get(lp))\n"
        "np.testing.assert_allclose(lp, float(lw), rtol=2e-5)\n"
        "for k in ('w', 'b'):\n"
        "    got = np.asarray(jax.device_get(\n"
        "        jax.jit(lambda g: g, out_shardings=NamedSharding(\n"
        "            mesh, P()))(gp[k])))\n"
        "    np.testing.assert_allclose(got, np.asarray(gw[k]),\n"
        "                               rtol=2e-4, atol=2e-6)\n"
        "print('rank', jax.process_index(), 'spmd-pp parity ok',\n"
        "      flush=True)\n"))
    text = "".join(logs.values())
    assert "rank 0 spmd-pp parity ok" in text
    assert "rank 1 spmd-pp parity ok" in text


@requires_jax_export
def test_jit_save_load_roundtrip(tmp_path):
    P.seed(0)
    m = nn.Sequential(nn.Linear(6, 12), nn.ReLU(), nn.Linear(12, 3))
    m.eval()
    x = P.randn([2, 6])
    P.jit.save(m, str(tmp_path / "net"), input_spec=[x._value])
    loaded = P.jit.load(str(tmp_path / "net"))
    np.testing.assert_allclose(loaded(x).numpy(), m(x).numpy(), rtol=1e-6)


def test_amp_train_step_casts_float_inputs():
    """bf16 AMP train step with float32 image inputs: the step must cast
    floating batch leaves to the compute dtype (conv operands must agree
    — regression for the f32-input/bf16-weight conv mismatch)."""
    import jax.numpy as jnp

    from paddle_tpu.nn import Conv2D, CrossEntropyLoss, Flatten, Linear
    from paddle_tpu import nn as pnn

    class Tiny(pnn.Layer):
        def __init__(self):
            super().__init__()
            self.conv = Conv2D(3, 4, 3)
            self.flat = Flatten()
            self.fc = Linear(4 * 6 * 6, 5)

        def forward(self, x):
            return self.fc(self.flat(self.conv(x)))

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 1, "sep_degree": 1,
                               "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    model = fleet.distributed_model(Tiny())
    opt = fleet.distributed_optimizer(
        P.optimizer.SGD(parameters=model.parameters(), learning_rate=1e-2))
    step = model.build_train_step(opt, CrossEntropyLoss(),
                                  amp_dtype="bfloat16")
    imgs = P.to_tensor(np.random.RandomState(0)
                       .randn(2, 3, 8, 8).astype(np.float32))
    lbl = P.to_tensor(np.array([1, 3]), "int32")
    l1 = float(np.asarray(step(imgs, lbl)._value))
    l2 = float(np.asarray(step(imgs, lbl)._value))
    assert np.isfinite(l1) and np.isfinite(l2)


@requires_jax_export
def test_inference_http_serving(tmp_path):
    """Inference serving tier (reference deployment surface role): save
    an inference model, serve it over HTTP, predict via the client."""
    from paddle_tpu import static
    from paddle_tpu.inference.serving import InferenceClient, InferenceServer

    P.enable_static()
    try:
        x = static.data("x", [-1, 4], "float32")
        lin = nn.Linear(4, 3)
        out = nn.functional.softmax(lin(x))
        exe = static.Executor()
        prefix = str(tmp_path / "served")
        static.save_inference_model(prefix, [x], [out], exe)
        xv = np.random.RandomState(0).rand(2, 4).astype(np.float32)
        (ref,) = exe.run(feed={"x": xv}, fetch_list=[out])
    finally:
        P.disable_static()

    srv = InferenceServer(prefix, port=0).start()
    try:
        client = InferenceClient(srv.address)
        h = client.health()
        assert h["status"] == "ok" and h["inputs"] == ["x"]
        outs = client.predict(x=xv)
        (got,) = outs.values()
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
    finally:
        srv.shutdown()


def test_hapi_fit_amp_and_accumulation(tmp_path):
    """prepare(amp_configs=...) and accumulate_grad_batches are honored
    (previously silent no-op args)."""
    from paddle_tpu.hapi import Model
    from paddle_tpu.vision.datasets import FakeData

    P.seed(0)
    net = nn.Sequential(nn.Flatten(), nn.Linear(48, 10))
    model = Model(net)
    model.prepare(
        optimizer=P.optimizer.SGD(parameters=net.parameters(),
                                  learning_rate=1e-2),
        loss=nn.CrossEntropyLoss(),
        amp_configs={"level": "O1", "dtype": "bfloat16"})
    assert model._amp_level == "O1"
    data = FakeData(size=32, image_shape=(3, 4, 4), num_classes=10)
    model.fit(data, batch_size=8, epochs=1, verbose=0,
              accumulate_grad_batches=2)
    res = model.evaluate(data, batch_size=8)
    assert np.isfinite(res["loss"])


def test_hapi_fit_data_parallel():
    """With a dp>1 topology initialized, prepare() wraps the network in
    DataParallel so fit syncs grads across dp ranks."""
    from paddle_tpu.distributed.parallel import DataParallel
    from paddle_tpu.hapi import Model
    from paddle_tpu.vision.datasets import FakeData

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1,
                               "pp_degree": 1, "sep_degree": 1,
                               "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    P.seed(0)
    net = nn.Sequential(nn.Flatten(), nn.Linear(48, 10))
    model = Model(net)
    model.prepare(
        optimizer=P.optimizer.SGD(parameters=net.parameters(),
                                  learning_rate=1e-2),
        loss=nn.CrossEntropyLoss())
    assert isinstance(model.network, DataParallel)
    data = FakeData(size=16, image_shape=(3, 4, 4), num_classes=10)
    model.fit(data, batch_size=8, epochs=1, verbose=0)


def test_reduce_lr_on_plateau_callback():
    from paddle_tpu.hapi.callbacks import ReduceLROnPlateau

    class _Opt:
        def __init__(self):
            self._lr = 0.1

        def get_lr(self):
            return self._lr

        def set_lr(self, v):
            self._lr = v

    class _Model:
        pass

    cb = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=2,
                           verbose=0, min_lr=0.01)
    m = _Model(); m._optimizer = _Opt()
    cb.model = m
    cb.on_eval_end({"loss": 1.0})
    for _ in range(2):  # no improvement x2 -> reduce
        cb.on_eval_end({"loss": 1.0})
    assert abs(m._optimizer.get_lr() - 0.05) < 1e-9
    cb.on_eval_end({"loss": 0.5})   # improvement resets
    assert abs(m._optimizer.get_lr() - 0.05) < 1e-9
    import pytest

    with pytest.raises(ValueError):
        ReduceLROnPlateau(factor=1.5)
    from paddle_tpu.hapi.callbacks import WandbCallback

    with pytest.raises(ImportError, match="wandb"):
        WandbCallback()


def _resume_run(topo_cfg, batches, n_steps, ckpt=None, save_at=None,
                save_path=None):
    """Build a fresh GPT-tiny hybrid step under `topo_cfg`, optionally
    load a training checkpoint, run `n_steps`, optionally save. Uses a
    DECAYING LR schedule so a resume that restarts the scheduler (while
    the Adam step counter continues) shows up as diverging losses.
    Returns the per-step losses."""
    from paddle_tpu.models.gpt import (
        GPTForCausalLM, GPTPretrainingCriterion, gpt_tiny,
    )

    topology.reset_topology()
    strategy = fleet.DistributedStrategy()
    cfg = dict({"pp_degree": 1, "sep_degree": 1, "sharding_degree": 1},
               **topo_cfg)
    strategy.hybrid_configs = cfg
    if cfg["sharding_degree"] > 1:
        strategy.sharding = True
        strategy.sharding_configs = {"stage": 2}
    fleet.init(is_collective=True, strategy=strategy)
    P.seed(0)
    model = fleet.distributed_model(
        GPTForCausalLM(gpt_tiny(dropout=0.0)))
    sched = P.optimizer.lr.StepDecay(learning_rate=1e-3, step_size=2,
                                     gamma=0.5)
    opt = fleet.distributed_optimizer(P.optimizer.AdamW(
        parameters=model.parameters(), learning_rate=sched))
    step = model.build_train_step(opt, GPTPretrainingCriterion())
    if ckpt is not None:
        step.load_train_state(ckpt)
    losses = []
    for i in range(n_steps):
        ids, labels = batches[i]
        losses.append(float(step(P.to_tensor(ids, "int32"),
                                 P.to_tensor(labels, "int32"))))
        sched.step()
        if save_at is not None and i + 1 == save_at:
            step.save_train_state(save_path)
    return losses


@pytest.mark.slow
def test_train_resume_exact_and_across_topologies(tmp_path):
    """Exact training resume (VERDICT aux: checkpoint/resume at depth):
    params + every AdamW slot + the step counter (bias correction!)
    round-trip through the distributed checkpoint.

    Same topology: the resumed run's losses must match the uninterrupted
    run's almost bitwise. Different topology (dp4·mp2 -> dp2·mp4): the
    checkpoint reshards leaf-by-leaf on load; losses match to reduction-
    order tolerance."""
    rs = np.random.RandomState(0)
    batches = [(rs.randint(0, 1024, (4, 32)), rs.randint(0, 1024, (4, 32)))
               for _ in range(6)]
    a = _resume_run({"dp_degree": 4, "mp_degree": 2}, batches, 6)
    ck = str(tmp_path / "resume_ck")
    b_head = _resume_run({"dp_degree": 4, "mp_degree": 2}, batches, 3,
                         save_at=3, save_path=ck)
    np.testing.assert_allclose(b_head, a[:3], rtol=1e-6)
    # same-topology resume: steps 4-6 continue as if never interrupted
    b_tail = _resume_run({"dp_degree": 4, "mp_degree": 2}, batches[3:], 3,
                         ckpt=ck)
    np.testing.assert_allclose(b_tail, a[3:], rtol=1e-5)
    # cross-topology resume: the same checkpoint restores into a
    # dp2·mp4 step (params AND slots resharded); only reduction order
    # may differ
    c_tail = _resume_run({"dp_degree": 2, "mp_degree": 4}, batches[3:], 3,
                         ckpt=ck)
    np.testing.assert_allclose(c_tail, a[3:], rtol=5e-4)
    # ZeRO-2 slots: dp4-sharded moments reshard into a dp2-sharded step
    z = _resume_run({"dp_degree": 4, "mp_degree": 2,
                     "sharding_degree": 4}, batches, 3,
                    save_at=3, save_path=str(tmp_path / "z_ck"))
    np.testing.assert_allclose(z, a[:3], rtol=1e-5)
    z_tail = _resume_run({"dp_degree": 2, "mp_degree": 4,
                          "sharding_degree": 2}, batches[3:], 3,
                         ckpt=str(tmp_path / "z_ck"))
    np.testing.assert_allclose(z_tail, a[3:], rtol=5e-4)
    # strictness: a different model's checkpoint refuses to partially
    # resume (missing leaves raise instead of silently mixing loaded
    # and fresh state)
    from paddle_tpu.models.gpt import (
        GPTForCausalLM, GPTPretrainingCriterion, gpt_tiny,
    )

    topology.reset_topology()
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 2,
                               "pp_degree": 1, "sep_degree": 1,
                               "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    P.seed(0)
    other = fleet.distributed_model(GPTForCausalLM(
        gpt_tiny(dropout=0.0, num_layers=3)))
    oopt = fleet.distributed_optimizer(P.optimizer.AdamW(
        parameters=other.parameters(), learning_rate=1e-3))
    ostep = other.build_train_step(oopt, GPTPretrainingCriterion())
    with pytest.raises(ValueError, match="missing"):
        ostep.load_train_state(ck)
