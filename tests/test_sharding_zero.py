"""ZeRO-1 pod training on the virtual 8-device CPU mesh (ISSUE 11).

The contract under test (docs/SHARDING.md):

* ZeRO-1 is the DEFAULT multi-chip configuration (fleet
  ``sharding_degree`` wiring) and its loss trajectory is BIT-IDENTICAL
  to the replicated stage-0 step when the quantized collective tier is
  off — sharding the weight update must cost nothing numerically.
* Params and optimizer slots genuinely live dp-sharded between steps
  (1/dp bytes per device), the lowered program carries no big
  replicated arguments (PT403 ≈ 0), and checkpoints reshard across
  stages bit-for-bit.
* The EQuARX tier (``PADDLE_TPU_COLLECTIVE_PRECISION``) converges
  within tolerance and the wire-honest shard_map collectives bound
  their quantization error.
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as P
from paddle_tpu.distributed import collective, fleet, quantized, topology


@pytest.fixture(autouse=True)
def fresh_topology():
    topology.reset_topology()
    yield
    topology.reset_topology()


def _strategy(dp=8, mp=1, sharding_degree=None, stage=None):
    s = fleet.DistributedStrategy()
    s.hybrid_configs = {
        "dp_degree": dp, "mp_degree": mp, "pp_degree": 1, "sep_degree": 1,
        "sharding_degree": dp if sharding_degree is None else
        sharding_degree,
    }
    if stage is not None:
        s.sharding = True
        s.sharding_configs = {"stage": stage}
    return s


def _gpt_step(dp=8, mp=1, stage=None, force_stage=None, precision=None,
              grad_clip_norm=None, vocab=256, hidden=64, layers=2):
    """A tiny-GPT train step under the given fleet config.  With
    ``stage=None`` the fleet wiring resolves the stage (the path users
    get); ``force_stage`` pins it explicitly."""
    from paddle_tpu.models.gpt import (
        GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
    )

    topology.reset_topology()
    fleet.init(is_collective=True, strategy=_strategy(dp, mp, stage=stage))
    P.seed(0)
    cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden,
                    num_layers=layers, num_heads=4, max_seq_len=32)
    m = fleet.distributed_model(GPTForCausalLM(cfg))
    o = fleet.distributed_optimizer(P.optimizer.AdamW(
        parameters=m.parameters(), learning_rate=1e-3))
    kw = {}
    if force_stage is not None:
        kw["sharding_stage"] = force_stage
    if precision is not None:
        kw["collective_precision"] = precision
    if grad_clip_norm is not None:
        kw["grad_clip_norm"] = grad_clip_norm
    return m.build_train_step(o, GPTPretrainingCriterion(), **kw), cfg


def _run(step, ids_np, lab_np, n):
    out = []
    for i in range(n):
        ids = P.to_tensor(ids_np[i], "int32")
        lab = P.to_tensor(lab_np[i], "int32")
        out.append(float(step(ids, lab)))
    return out


def _batches(n, batch=8, seq=32, vocab=256, seed=0):
    rs = np.random.RandomState(seed)
    return (rs.randint(0, vocab, (n, batch, seq)),
            rs.randint(0, vocab, (n, batch, seq)))


# ----------------------- spec planning (satellite) -----------------------


def test_plan_specs_stage_0_1_3():
    """Stage-0/1/3 storage planning: stage 0 leaves params+slots on the
    mpu placements; stage 1 dp-shards BOTH (weight-update sharding);
    stage 3 dp-shards params and slots inherit the param's spec — the
    fixed `base` path must not pick a SECOND dp dim for slots."""
    specs = {}
    for stg in (0, 1, 3):
        step, _ = _gpt_step(dp=8, force_stage=stg, layers=1)
        step.init_state()
        p = step._p_spec
        s = step._s_spec
        specs[stg] = (p, s)
        big = [n for n in p if "wte" in n][0]
        if stg == 0:
            assert all("dp" not in sp for sp in p.values()), p
            assert all("dp" not in sp for sd in s.values()
                       for sp in sd.values()), s
        else:
            assert "dp" in p[big], p[big]
            assert all("dp" in sp for sp in s[big].values()), s[big]
            # slots inherit the param's storage spec exactly (no
            # double-sharding onto another dim)
            for k, sp in s[big].items():
                assert sp == p[big], (stg, k, sp, p[big])
    # stage 1 and stage 3 share storage planning; they differ in the
    # step's gather schedule, not the specs
    assert specs[1] == specs[3]


def test_fleet_sharding_strategy_wiring():
    """fleet.distributed_optimizer users get the strategy's ZeRO stage:
    explicit sharding_configs win, sharding_degree>1 defaults to ZeRO-1
    (the multi-chip default), degree 1 stays stage 0."""
    assert fleet.resolve_sharding_stage(_strategy(8)) == 1
    assert fleet.resolve_sharding_stage(
        _strategy(8, sharding_degree=1)) == 0
    assert fleet.resolve_sharding_stage(_strategy(8, stage=2)) == 2
    assert fleet.resolve_sharding_stage(_strategy(8, stage=3)) == 3
    assert fleet.resolve_sharding_stage(
        _strategy(1, sharding_degree=1)) == 0

    step, _ = _gpt_step(dp=8, layers=1)           # wiring end-to-end
    assert step.sharding_stage == 1
    step, _ = _gpt_step(dp=8, stage=2, layers=1)
    assert step.sharding_stage == 2


# ----------------------- the tentpole: bit-identity -----------------------


def test_zero1_bit_identical_to_replicated():
    """Acceptance: the ZeRO-1 trajectory is bit-identical to the
    replicated stage-0 step with the quantized tier off, while params
    and optimizer slots genuinely live at 1/dp bytes per device."""
    ids_np, lab_np = _batches(8)
    s0, _ = _gpt_step(dp=8, force_stage=0)
    l0 = _run(s0, ids_np, lab_np, 8)
    s1, _ = _gpt_step(dp=8)                       # auto ZeRO-1
    assert s1.sharding_stage == 1
    assert s1.collective_precision is None
    l1 = _run(s1, ids_np, lab_np, 8)
    assert l0 == l1, f"ZeRO-1 diverged: {l0} vs {l1}"

    # storage proof: sharded params/slots hold 1/8 of the bytes locally
    big = max(s1._state["params"].values(), key=lambda v: v.nbytes)
    assert big.nbytes // big.addressable_shards[0].data.nbytes == 8
    slot = next(v for sd in s1._state["opt"]["slots"].values()
                for v in sd.values())
    assert "dp" in str(slot.sharding.spec)

    # reassembled params match the replicated run to float tolerance:
    # the embedding grad's scatter-add reduces in a different order per
    # partitioning (ULP), and Adam's /sqrt(v)+eps amplifies that for
    # tiny-magnitude biases — the loss trajectory above stays bit-equal
    p0 = {n: np.asarray(v) for n, v in s0._state["params"].items()}
    p1 = {n: np.asarray(v) for n, v in s1._state["params"].items()}
    for n in p0:
        np.testing.assert_allclose(p0[n], p1[n], atol=1e-4, rtol=1e-4,
                                   err_msg=n)


def test_zero1_knob_off_spellings_stay_exact():
    """'f32'/'full'/'' all mean the exact tier; the trajectory stays
    bit-identical through every spelling of 'off'."""
    assert quantized.collective_precision("f32") is None
    assert quantized.collective_precision("full") is None
    assert quantized.collective_precision("") is None
    ids_np, lab_np = _batches(3)
    s0, _ = _gpt_step(dp=8, force_stage=0, layers=1)
    l0 = _run(s0, ids_np, lab_np, 3)
    s1, _ = _gpt_step(dp=8, precision="f32", layers=1)
    assert s1.collective_precision is None
    l1 = _run(s1, ids_np, lab_np, 3)
    assert l0 == l1


def test_precision_knob_validation():
    with pytest.raises(ValueError, match="COLLECTIVE_PRECISION"):
        quantized.collective_precision("int4")
    os.environ[quantized.ENV_KNOB] = "bogus"
    try:
        with pytest.raises(ValueError, match="bogus"):
            _gpt_step(dp=8, layers=1)
    finally:
        os.environ.pop(quantized.ENV_KNOB)


@pytest.mark.parametrize("precision", ["bf16", "int8"])
def test_zero1_quantized_tier_converges(precision):
    """The quantized tier trades exactness for wire bytes: the loss
    trajectory must track the exact run within tolerance and keep
    training (EQuARX's claim, scaled to the proxy)."""
    ids_np, lab_np = _batches(6)
    s0, _ = _gpt_step(dp=8, force_stage=0, layers=1)
    l0 = _run(s0, ids_np, lab_np, 6)
    sq, _ = _gpt_step(dp=8, precision=precision, layers=1)
    assert sq.collective_precision == precision
    lq = _run(sq, ids_np, lab_np, 6)
    np.testing.assert_allclose(lq, l0, rtol=2e-3)
    assert lq[-1] < lq[0]       # still learning


def test_zero1_grad_clip_within_tolerance():
    """Under clipping the global norm reduces over dp-sharded leaves —
    same math, different reduction order, so tolerance not bits."""
    ids_np, lab_np = _batches(3)
    s0, _ = _gpt_step(dp=8, force_stage=0, layers=1, grad_clip_norm=0.5)
    l0 = _run(s0, ids_np, lab_np, 3)
    s1, _ = _gpt_step(dp=8, layers=1, grad_clip_norm=0.5)
    l1 = _run(s1, ids_np, lab_np, 3)
    np.testing.assert_allclose(l1, l0, rtol=1e-5)


def test_zero1_run_steps_matches_sequential():
    """The scanned multi-step program composes with ZeRO-1: N steps in
    one compiled scan == N sequential dispatches, bit-for-bit."""
    ids_np, lab_np = _batches(3)
    sa, _ = _gpt_step(dp=8, layers=1)
    seq = _run(sa, ids_np, lab_np, 3)
    sb, _ = _gpt_step(dp=8, layers=1)
    losses = sb.run_steps(P.to_tensor(ids_np, "int32"),
                          P.to_tensor(lab_np, "int32"))
    assert [float(x) for x in np.asarray(losses._value)] == seq


# ----------------------- quantized collectives (wire tier) ---------------


def test_quantize_chunked_roundtrip():
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(1000).astype(np.float32) * 3.0)
    q, scales, pad = quantized.quantize_chunked(x)
    assert q.dtype == jnp.int8 and pad == (-1000) % quantized.CHUNK
    y = quantized.dequantize_chunked(q, scales, (1000,), pad)
    assert float(jnp.max(jnp.abs(x - y))) <= float(
        jnp.max(jnp.abs(x))) / 127.0 + 1e-7
    # zero chunks survive (scale clamps to 1, result exactly zero)
    z = quantized.qdq(jnp.zeros((512,), jnp.float32), "int8")
    assert np.array_equal(np.asarray(z), np.zeros(512, np.float32))
    # exactly-representable values round-trip exactly
    e = jnp.asarray([0.0, 127.0, -127.0, 64.0] * 64, jnp.float32)
    assert np.array_equal(np.asarray(quantized.qdq(e, "int8")),
                          np.asarray(e))
    # integer payloads NEVER ride the lossy codec: an int32 count must
    # come back exact even with the knob set
    ints = jnp.asarray([0, 1, 123456789, -7], jnp.int32)
    for prec in ("int8", "bf16"):
        assert np.array_equal(np.asarray(quantized.qdq(ints, prec)),
                              np.asarray(ints)), prec


def test_quantized_wire_collectives_bound_error():
    """The shard_map tier is the honest EQuARX recipe: shared pmax
    scales, int32 accumulation, dequantize — per-element error of the
    SUM bounded by dp * per-replica quantization step."""
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as PS

    fleet.init(is_collective=True, strategy=_strategy(8))
    mesh = topology.get_topology().spmd_mesh
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(8, 16, 8).astype(np.float32))
    xs = jax.device_put(x, NamedSharding(mesh, PS("dp")))
    exact = np.sum(np.asarray(x), axis=0)
    bound = 8 * float(np.abs(np.asarray(x)).max()) / 127.0

    def smap(fn):
        try:
            return shard_map(fn, mesh=mesh, in_specs=(PS("dp"),),
                             out_specs=PS("dp"), check_vma=False)
        except TypeError:
            return shard_map(fn, mesh=mesh, in_specs=(PS("dp"),),
                             out_specs=PS("dp"), check_rep=False)

    out = np.asarray(smap(
        lambda v: quantized.psum(v[0], "dp", "int8")[None])(xs))[0]
    assert np.abs(out - exact).max() <= bound

    got = np.asarray(smap(
        lambda v: quantized.psum_scatter(v[0], "dp", 8, "int8")[None])(
        xs)).reshape(16, 8)
    assert np.abs(got - exact).max() <= bound

    # the scatter really lowers to the reduce-scatter collective
    jx = str(jax.make_jaxpr(smap(
        lambda v: quantized.psum_scatter(v[0], "dp", 8, "int8")[None]))(
        xs))
    assert "reduce_scatter" in jx or "psum_scatter" in jx, jx

    # exact tier == plain psum bits
    ex = np.asarray(smap(
        lambda v: quantized.psum(v[0], "dp", None)[None])(xs))[0]
    assert np.array_equal(ex, np.asarray(smap(
        lambda v: jax.lax.psum(v[0], "dp")[None])(xs))[0])

    # integer payloads reduce exactly even under the int8 tier
    xi = jnp.asarray(rs.randint(-1000, 1000, (8, 16)).astype(np.int32))
    xis = jax.device_put(xi, NamedSharding(mesh, PS("dp")))
    gi = np.asarray(smap(
        lambda v: quantized.psum(v[0], "dp", "int8")[None])(xis))[0]
    assert np.array_equal(gi, np.sum(np.asarray(xi), axis=0))


def test_collective_api_precision_knob():
    """distributed.all_reduce / reduce_scatter honor the knob (arg and
    env spellings) and count the quantized tier."""
    from jax.sharding import NamedSharding, PartitionSpec as PS

    from paddle_tpu import observability as obs
    from paddle_tpu.observability import metrics as obs_metrics

    obs.attach()
    fleet.init(is_collective=True, strategy=_strategy(8))
    mesh = topology.get_topology().spmd_mesh
    rs = np.random.RandomState(1)
    base = rs.randn(8, 4).astype(np.float32)
    x = jax.device_put(jnp.asarray(base), NamedSharding(mesh, PS("dp")))
    exact_rows = base.sum(axis=0)

    t = P.Tensor(x)
    collective.all_reduce(t, precision="int8")
    got = t.numpy()
    # psum over dp of per-shard rows: every row -> the cross-replica sum
    # of the row set; int8 error bounded by 8 * absmax / 127
    bound = 8 * np.abs(base).max() / 127.0
    for r in range(8):
        assert np.abs(got[r] - exact_rows).max() <= bound + 1e-6

    snap = obs_metrics.snapshot()
    quant = [k for k in snap.get("counters", snap)
             if "collective.quantized" in str(k)]
    assert quant, snap

    # reduce_scatter quantized: replicated input, scattered summed rows
    y = P.Tensor(jnp.asarray(base))
    out = collective.reduce_scatter(None, y, precision="int8")
    arr = np.asarray(out._value if hasattr(out, "_value") else out)
    assert arr.shape == (8, 4)
    assert np.abs(arr - 8 * base).max() <= 8 * np.abs(base).max() / 127.0 \
        + 1e-6


# ----------------------- checkpoint resharding (satellite) ---------------


def test_sharded_checkpoint_roundtrips_across_stages(tmp_path):
    """Save under ZeRO-1, restore into a replicated stage-0 step (and
    the reverse): the reassembled params AND optimizer slots match
    bit-for-bit — the distributed checkpoint reshards leaf-by-leaf."""
    ids_np, lab_np = _batches(2)

    s1, _ = _gpt_step(dp=8, layers=1)
    _run(s1, ids_np, lab_np, 2)
    d1 = str(tmp_path / "zero1")
    s1.save_train_state(d1)

    s0, _ = _gpt_step(dp=8, force_stage=0, layers=1)
    s0.init_state()
    s0.load_train_state(d1)
    ref = {n: np.asarray(v) for n, v in s1._state["params"].items()}
    got = {n: np.asarray(v) for n, v in s0._state["params"].items()}
    for n in ref:
        assert np.array_equal(ref[n], got[n]), n
        assert "dp" not in str(s0._state["params"][n].sharding.spec)
    for n, sd in s1._state["opt"]["slots"].items():
        for k in sd:
            assert np.array_equal(
                np.asarray(sd[k]),
                np.asarray(s0._state["opt"]["slots"][n][k])), (n, k)
    assert int(np.asarray(s0._state["opt"]["step"])) == 2

    # reverse: stage-0 state into a fresh ZeRO-1 step, still bit-equal,
    # and the loaded leaves land SHARDED
    _run(s0, ids_np, lab_np, 1)
    d0 = str(tmp_path / "stage0")
    s0.save_train_state(d0)
    s2, _ = _gpt_step(dp=8, layers=1)
    s2.init_state()
    s2.load_train_state(d0)
    big = max(s2._state["params"].values(), key=lambda v: v.nbytes)
    assert "dp" in str(big.sharding.spec)
    for n, v in s0._state["params"].items():
        assert np.array_equal(np.asarray(v),
                              np.asarray(s2._state["params"][n])), n
    # and both resume to the same next loss, bit-for-bit
    la = _run(s0, ids_np[1:], lab_np[1:], 1)
    lb = _run(s2, ids_np[1:], lab_np[1:], 1)
    assert la == lb


# ----------------------- static placement proof -----------------------


def test_zero1_lowered_program_sheds_replicated_args():
    """PT403 over the REAL lowered ZeRO-1 step: no argument ≥0.05 MiB
    stays replicated, and the jaxpr shows no all_gather→reduce
    anti-pattern — the static twin of the acceptance ratchet."""
    from paddle_tpu.analysis import perf_audit

    ids_np, lab_np = _batches(1, vocab=1024)
    step, _ = _gpt_step(dp=8, layers=1, vocab=1024)
    low = step.lower(P.to_tensor(ids_np[0], "int32"),
                     P.to_tensor(lab_np[0], "int32"))
    text = low.as_text()
    m = perf_audit.replicated_args(text)
    assert m["pt403_replicated_count"] == 0, m
    assert m["pt403_replicated_mbytes"] <= 0.05, m
    placed, _ = step._place_batch(
        (P.to_tensor(ids_np[0], "int32"),
         P.to_tensor(lab_np[0], "int32")), batch_axis=0)
    s = step._state
    jaxpr = jax.make_jaxpr(step._step_fn)(
        s["params"], s["opt"], s["buffers"], s["key"],
        jnp.asarray(1e-3, jnp.float32), *placed)
    pats = perf_audit.collective_patterns(jaxpr)
    assert pats["pt404_allgather_reduce"] == 0
    # the compiled program schedules per-parameter collectives (one per
    # grad at its production point), not a single fused barrier
    cc = perf_audit.collective_hlo_counts(low.compile().as_text())
    n_params = len(step._state["params"])
    assert cc["pt404_opt_all_reduce_count"] + \
        cc["pt404_opt_reduce_scatter_count"] >= n_params // 2

    # and the committed budget GATES the fused-barrier direction: the
    # deficit metric is budgeted 0, so counts falling below one-per-
    # param reads as a regression, not an improvement
    from paddle_tpu.analysis import report as rpt
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    budget = rpt.load_budget(
        os.path.join(repo, "tools", "perf_budget.json"))
    assert budget["gpt_sharded_train_step"]["pt404_grad_sync_deficit"] \
        == 0
    reg, _, _ = rpt.diff_against_budget(
        {"gpt_sharded_train_step": {"pt404_grad_sync_deficit": 9}},
        budget)
    assert ("gpt_sharded_train_step", "pt404_grad_sync_deficit", 9, 0) \
        in reg


def test_pt403_findings_name_owning_params():
    """PT403 messages carry the owning parameter names (arg index →
    flattened name) so budget regressions are actionable from lint
    output alone."""
    from paddle_tpu.analysis import perf_audit

    text = """
  func.func public @main(
    %arg0: tensor<512x512xf32> {x}, %arg1: tensor<512x512xf32>
      {mhlo.sharding = "{devices=[8,1]0,1,2,3,4,5,6,7}"},
    %arg2: tensor<8xi32>) -> (tensor<f32>) {
"""
    details = perf_audit.replicated_arg_details(
        text, min_mbytes=0.5,
        arg_names=["param.gpt.wte.weight", "param.sharded", "batch.0"])
    assert details == [("param.gpt.wte.weight", 1.0)]
    v, m = perf_audit.audit_program_texts(
        "fix", stablehlo_text=text, min_replicated_mbytes=0.5,
        arg_names=["param.gpt.wte.weight", "param.sharded", "batch.0"])
    assert m["pt403_replicated_count"] == 1
    pt403 = [x for x in v if x.rule == "PT403"]
    assert pt403 and "param.gpt.wte.weight" in pt403[0].message
    # without names the finding still localizes by arg index
    v2, _ = perf_audit.audit_program_texts(
        "fix", stablehlo_text=text, min_replicated_mbytes=0.5)
    assert "arg0" in [x for x in v2 if x.rule == "PT403"][0].message


# ----------------------- bench rows / perf_gate (satellite) ---------------


def test_multichip_rows_perf_gate_roundtrip(tmp_path):
    """bench.py's multichip_sharded_* rows gate through perf_gate:
    --update seeds the baseline from a healthy proof row, the same row
    passes the gate, and a replicated-update regression (ratio 8→1)
    fails it; degraded trend rows never gate."""
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "_perf_gate", os.path.join(repo, "tools", "perf_gate.py"))
    pg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pg)

    good = [{"metric": "multichip_sharded_param_shard_ratio",
             "value": 8.0, "unit": "x", "vs_baseline": 1.0},
            {"metric": "multichip_sharded_train_tokens_per_sec",
             "value": 5000.0, "unit": "tokens/s", "vs_baseline": 0.0,
             "degraded": True}]
    baseline = str(tmp_path / "baseline.jsonl")
    pg.update_baseline(good, baseline)
    base = pg.load_baseline(baseline)
    assert "multichip_sharded_param_shard_ratio" in base
    # the degraded trend row never seeds a floor
    assert "multichip_sharded_train_tokens_per_sec" not in base
    fails, _ = pg.gate(good, dict(base))
    assert fails == []
    regressed = [{"metric": "multichip_sharded_param_shard_ratio",
                  "value": 1.0, "unit": "x", "vs_baseline": 0.125}]
    fails, _ = pg.gate(regressed, dict(base))
    assert len(fails) == 1, fails


@pytest.mark.slow
def test_multichip_sharded_probe_subprocess():
    """The real bench probe: a fresh 8-virtual-device subprocess trains
    the ZeRO-1 GPT and reports the placement proof."""
    import json
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"),
         "--multichip-sharded-probe"],
        capture_output=True, text=True, timeout=900, env=env)
    line = [ln for ln in r.stdout.splitlines() if ln.startswith("{")][-1]
    probe = json.loads(line)
    assert probe["param_shard_ratio"] == 8.0
    assert probe["replicated_arg_count"] == 0
    assert probe["sharding_stage"] == 1
    assert probe["tokens_per_sec"] > 0
