"""Pallas kernel tests — run through the interpreter on CPU so the exact
kernel code is validated without hardware (SURVEY §4.5 fake-backend
strategy)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas import flash_attention as fa

rs = np.random.RandomState(0)


def _rand(shape):
    return jnp.asarray(rs.randn(*shape), jnp.float32)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_matches_reference(causal):
    B, S, H, D = 2, 256, 2, 64
    q, k, v = _rand((B, S, H, D)), _rand((B, S, H, D)), _rand((B, S, H, D))
    out = fa._flash_core(q, k, v, causal, 128, 128)
    ref = fa._ref_attention(q, k, v, None, causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_matches_reference(causal):
    B, S, H, D = 1, 128, 2, 64
    q, k, v = _rand((B, S, H, D)), _rand((B, S, H, D)), _rand((B, S, H, D))

    def loss_flash(q, k, v):
        return jnp.sum(fa._flash_core(q, k, v, causal, 64, 64) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(fa._ref_attention(q, k, v, None, causal) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_flash_uneven_blocks():
    # seq not a multiple of the block: pallas pads the trailing block
    B, S, H, D = 1, 192, 2, 64
    q, k, v = _rand((B, S, H, D)), _rand((B, S, H, D)), _rand((B, S, H, D))
    out = fa._flash_core(q, k, v, True, 128, 128)
    ref = fa._ref_attention(q, k, v, None, True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_bf16_io():
    B, S, H, D = 1, 128, 2, 64
    q = _rand((B, S, H, D)).astype(jnp.bfloat16)
    k = _rand((B, S, H, D)).astype(jnp.bfloat16)
    v = _rand((B, S, H, D)).astype(jnp.bfloat16)
    out = fa._flash_core(q, k, v, True, 64, 64)
    assert out.dtype == jnp.bfloat16
    ref = fa._ref_attention(q, k, v, None, True)
    np.testing.assert_allclose(out.astype(jnp.float32),
                               ref.astype(jnp.float32), atol=3e-2, rtol=3e-2)


# ===================== fused norm (rms / layernorm) =====================

from paddle_tpu.ops.pallas import fused_norm as fn_mod


def _rms_ref(z, w, b, eps):
    z32 = z.astype(jnp.float32)
    ms = jnp.mean(z32 * z32, axis=-1, keepdims=True)
    y = z32 * jax.lax.rsqrt(ms + eps)
    if w is not None:
        y = y * w.astype(jnp.float32)
    if b is not None:
        y = y + b.astype(jnp.float32)
    return y.astype(z.dtype)


def _ln_ref(z, w, b, eps):
    z32 = z.astype(jnp.float32)
    mu = jnp.mean(z32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(z32 - mu), axis=-1, keepdims=True)
    y = (z32 - mu) * jax.lax.rsqrt(var + eps)
    if w is not None:
        y = y * w.astype(jnp.float32)
    if b is not None:
        y = y + b.astype(jnp.float32)
    return y.astype(z.dtype)


@pytest.mark.parametrize("kind", ["rms", "ln"])
def test_fused_norm_forward_matches_reference(kind):
    R, D = 24, 256
    x = _rand((R, D))
    w = _rand((D,))
    b = _rand((D,))
    out = fn_mod.fused_norm_pallas(x, w, b, eps=1e-6, kind=kind)
    ref = (_rms_ref if kind == "rms" else _ln_ref)(x, w, b, 1e-6)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("kind", ["rms", "ln"])
def test_fused_norm_residual_bias_forward(kind):
    B, S, D = 2, 8, 128
    x = _rand((B, S, D))
    w = _rand((D,))
    bias = _rand((D,))
    res = _rand((B, S, D))
    out, z = fn_mod.fused_norm_pallas(x, w, None, bias, res,
                                      eps=1e-6, kind=kind)
    z_ref = x + bias + res
    ref = (_rms_ref if kind == "rms" else _ln_ref)(z_ref, w, None, 1e-6)
    np.testing.assert_allclose(z, z_ref, atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("kind", ["rms", "ln"])
def test_fused_norm_grads_match_reference(kind):
    R, D = 16, 128
    x = _rand((R, D))
    w = _rand((D,))
    b = _rand((D,))

    def loss_pallas(x, w, b):
        return jnp.sum(fn_mod.fused_norm_pallas(x, w, b, eps=1e-6,
                                                kind=kind) ** 2)

    def loss_ref(x, w, b):
        return jnp.sum(
            (_rms_ref if kind == "rms" else _ln_ref)(x, w, b, 1e-6) ** 2)

    g1 = jax.grad(loss_pallas, argnums=(0, 1, 2))(x, w, b)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(x, w, b)
    for a, c in zip(g1, g2):
        np.testing.assert_allclose(a, c, atol=5e-4, rtol=5e-4)


def test_fused_norm_residual_grads():
    R, D = 16, 128
    x = _rand((R, D))
    w = _rand((D,))
    bias = _rand((D,))
    res = _rand((R, D))

    def loss_pallas(x, w, bias, res):
        y, z = fn_mod.fused_norm_pallas(x, w, None, bias, res, eps=1e-6,
                                        kind="rms")
        return jnp.sum(y ** 2) + jnp.sum(z ** 3)

    def loss_ref(x, w, bias, res):
        z = x + bias + res
        y = _rms_ref(z, w, None, 1e-6)
        return jnp.sum(y ** 2) + jnp.sum(z ** 3)

    g1 = jax.grad(loss_pallas, argnums=(0, 1, 2, 3))(x, w, bias, res)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(x, w, bias, res)
    for a, c in zip(g1, g2):
        np.testing.assert_allclose(a, c, atol=5e-4, rtol=5e-4)


# ============================== fused rope ==============================

from paddle_tpu.ops.pallas import rope as rope_mod


def _rope_phases(s, d, base=10000.0):
    inv = 1.0 / (base ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    t = jnp.arange(s, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return (jnp.cos(emb)[None, :, None, :], jnp.sin(emb)[None, :, None, :])


def _rope_ref(x, cos, sin):
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rot = jnp.concatenate([-x2, x1], axis=-1)
    return x * cos + rot * sin


def test_rope_forward_matches_reference():
    B, S, H, D = 2, 16, 4, 64
    x = _rand((B, S, H, D))
    cos, sin = _rope_phases(S, D)
    out = rope_mod.rope_pallas(x, cos, sin)
    ref = _rope_ref(x, cos, sin)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_rope_grad_matches_reference():
    B, S, H, D = 1, 8, 2, 64
    x = _rand((B, S, H, D))
    cos, sin = _rope_phases(S, D)
    g1 = jax.grad(lambda x: jnp.sum(rope_mod.rope_pallas(x, cos, sin) ** 2))(x)
    g2 = jax.grad(lambda x: jnp.sum(_rope_ref(x, cos, sin) ** 2))(x)
    np.testing.assert_allclose(g1, g2, atol=5e-5, rtol=5e-5)


# ====================== blocked KV-cache decode ======================

# the package re-exports the function under the module's name — import the
# function straight from the submodule via sys.modules
import importlib
da_mod = importlib.import_module("paddle_tpu.ops.pallas.decode_attention")


def test_decode_attention_matches_full_softmax():
    B, H, S, D = 2, 4, 64, 64
    q = _rand((B, H, D))
    kc = _rand((B, H, S, D))
    vc = _rand((B, H, S, D))
    pos = jnp.asarray([5, 33], jnp.int32)
    out = da_mod.decode_attention(q, kc, vc, pos, block_k=16)
    # reference: full-cache softmax with position mask
    scale = 1.0 / np.sqrt(D)
    scores = jnp.einsum("bhd,bhsd->bhs", q, kc) * scale
    valid = jnp.arange(S)[None, None, :] <= pos[:, None, None]
    scores = jnp.where(valid, scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)
    ref = jnp.einsum("bhs,bhsd->bhd", p, vc)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_decode_attention_pos_zero_and_full():
    B, H, S, D = 1, 2, 32, 64
    q = _rand((B, H, D))
    kc = _rand((B, H, S, D))
    vc = _rand((B, H, S, D))
    for p0 in (0, S - 1):
        pos = jnp.asarray([p0], jnp.int32)
        out = da_mod.decode_attention(q, kc, vc, pos, block_k=8)
        scale = 1.0 / np.sqrt(D)
        scores = jnp.einsum("bhd,bhsd->bhs", q, kc) * scale
        valid = jnp.arange(S)[None, None, :] <= pos[:, None, None]
        scores = jnp.where(valid, scores, -1e30)
        pr = jax.nn.softmax(scores, axis=-1)
        ref = jnp.einsum("bhs,bhsd->bhd", pr, vc)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_causal_cross_length_matches_reference():
    """Bottom-right-aligned causal (reference tril k=sk-sq) when
    seq_q != seq_k — decode/chunked-prefill shape (r3 review finding)."""
    B, H, D = 2, 2, 32
    for sq, sk in [(16, 64), (64, 16), (24, 40)]:
        q = _rand((B, sq, H, D))
        k = _rand((B, sk, H, D))
        v = _rand((B, sk, H, D))
        ref = fa._ref_attention(q, k, v, None, True)
        out = fa._flash_core(q, k, v, True, 8, 8)
        if sq > sk:
            # rows with an empty attention window are degenerate
            # (reference softmaxes all -inf to uniform; kernel emits 0) —
            # compare only rows that attend to at least one key
            valid_rows = slice(sq - sk, None)
            np.testing.assert_allclose(
                np.asarray(out)[:, valid_rows], np.asarray(ref)[:, valid_rows],
                atol=2e-5, rtol=2e-5)
        else:
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       atol=2e-5, rtol=2e-5)


def test_flash_causal_cross_length_grads():
    B, H, D, sq, sk = 1, 2, 16, 16, 48
    q = _rand((B, sq, H, D))
    k = _rand((B, sk, H, D))
    v = _rand((B, sk, H, D))
    g_ref = jax.grad(lambda q, k, v: fa._ref_attention(
        q, k, v, None, True).sum(), argnums=(0, 1, 2))(q, k, v)
    g_pal = jax.grad(lambda q, k, v: fa._flash_core(
        q, k, v, True, 8, 8).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_pal):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-5, rtol=3e-5)


def test_flash_indivisible_seq_raises_loud():
    """seq % 8 != 0 must be a loud error when the kernel is invoked
    DIRECTLY without padding. The public entry handles odd lengths by
    zero-padding + real-length masking on TPU (see
    test_flash_padded_odd_lengths_match_reference); on CPU (interpret
    mode gated off) it uses the reference path — correct either way."""
    q = _rand((1, 20, 2, 16))
    with pytest.raises(ValueError, match="seq % 8"):
        fa._flash_core(q, q, q, True, 8, 8)
    # public entry: correct on every backend (reference path here;
    # padded kernel on TPU)
    out = fa.flash_attention_fwd(q, q, q, is_causal=True)
    ref = fa._ref_attention(q, q, q, None, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_mh_forward_matches_transpose_path(causal):
    """All-heads-in-block forward (_fwd_mh, zero layout changes) must be
    numerically identical to the transpose path — including the LSE, so
    either forward can feed the same backward."""
    B, S, H, D = 2, 128, 3, 32
    q, k, v = _rand((B, S, H, D)), _rand((B, S, H, D)), _rand((B, S, H, D))
    out_mh, lse_mh = fa._fwd_mh(q, k, v, causal, 64, 64)
    out_t, lse_t = fa._fwd(q, k, v, causal, 64, 64)
    np.testing.assert_allclose(out_mh, out_t, atol=1e-6, rtol=1e-6)
    np.testing.assert_allclose(lse_mh, lse_t, atol=1e-6, rtol=1e-6)
    ref = fa._ref_attention(q, k, v, None, causal)
    np.testing.assert_allclose(out_mh, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_padded_odd_lengths_match_reference(causal):
    """Odd (ViT-style) sequence lengths: zero-pad to a multiple of 8,
    mask on the REAL lengths inside the kernels, slice the output.
    Values and grads must match the unpadded reference exactly — padded
    keys contribute nothing, padded query rows carry no gradient."""
    B, SQ, SK, H, D = 2, 52, 52, 2, 16
    q, k, v = _rand((B, SQ, H, D)), _rand((B, SK, H, D)), _rand((B, SK, H, D))
    pad = (-SQ) % 8
    w = ((0, 0), (0, pad), (0, 0), (0, 0))
    qp, kp, vp = jnp.pad(q, w), jnp.pad(k, w), jnp.pad(v, w)
    out = fa._flash_core(qp, kp, vp, causal, 8, 8, SQ, SK)[:, :SQ]
    ref = fa._ref_attention(q, k, v, None, causal)
    np.testing.assert_allclose(out, ref, atol=3e-5, rtol=3e-5)

    def loss_flash(q_, k_, v_):
        qq, kk, vv = jnp.pad(q_, w), jnp.pad(k_, w), jnp.pad(v_, w)
        o = fa._flash_core(qq, kk, vv, causal, 8, 8, SQ, SK)[:, :SQ]
        return (o.astype(jnp.float32) * 0.01).sum()

    def loss_ref(q_, k_, v_):
        o = fa._ref_attention(q_, k_, v_, None, causal)
        return (o.astype(jnp.float32) * 0.01).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=3e-5, rtol=3e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_mh_backward_matches_transpose_path(causal):
    """End-to-end mh core (fwd+bwd, zero layout changes) must produce the
    same gradients as the transpose core — both share _dq_loop/_dkv_loop,
    so any drift means the layouts plumb different data."""
    B, S, H, D = 2, 128, 3, 32
    q, k, v = _rand((B, S, H, D)), _rand((B, S, H, D)), _rand((B, S, H, D))

    def loss(core, q_, k_, v_):
        return (core(q_, k_, v_, causal, 64, 64)
                .astype(jnp.float32) * 0.01).sum()

    g_t = jax.grad(lambda *a: loss(fa._flash_core, *a),
                   argnums=(0, 1, 2))(q, k, v)
    g_mh = jax.grad(lambda *a: loss(fa._flash_core_mh, *a),
                    argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_t, g_mh):
        np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_kv_native_matches_transpose_path(causal):
    """Mixed-layout core (K/V/dK/dV stay [B,S,H,D]; round-5 kv kernels):
    forward, LSE, and all three gradients must be numerically identical
    to the transpose core — the loop bodies are shared, so any drift
    means the layouts plumb different data."""
    B, S, H, D = 2, 128, 3, 32
    q, k, v = _rand((B, S, H, D)), _rand((B, S, H, D)), _rand((B, S, H, D))
    out_kv, lse_kv = fa._fwd_kv(jnp.swapaxes(q, 1, 2), k, v, causal,
                                64, 64)
    out_t, lse_t = fa._fwd(q, k, v, causal, 64, 64)
    np.testing.assert_allclose(jnp.swapaxes(out_kv, 1, 2), out_t,
                               atol=1e-6, rtol=1e-6)
    np.testing.assert_allclose(lse_kv, lse_t, atol=1e-6, rtol=1e-6)

    def loss(core, q_, k_, v_):
        return (core(q_, k_, v_, causal, 64, 64)
                .astype(jnp.float32) * 0.01).sum()

    g_t = jax.grad(lambda *a: loss(fa._flash_core, *a),
                   argnums=(0, 1, 2))(q, k, v)
    g_kv = jax.grad(lambda *a: loss(fa._flash_core_kv, *a),
                    argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_t, g_kv):
        np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_kv_native_gqa_matches_transpose_path(causal):
    """kv-native GQA: the grouped-KV read (hh // rep) and the
    group-summed dK/dV must match the transpose grouped core."""
    B, S, HQ, HKV, D = 2, 128, 4, 2, 32
    q = _rand((B, S, HQ, D))
    k = _rand((B, S, HKV, D))
    v = _rand((B, S, HKV, D))

    def loss(core, q_, k_, v_):
        return (core(q_, k_, v_, causal, 64, 64)
                .astype(jnp.float32) * 0.01).sum()

    out_kv = fa._flash_core_kv(q, k, v, causal, 64, 64)
    out_t = fa._flash_core(q, k, v, causal, 64, 64)
    np.testing.assert_allclose(out_kv, out_t, atol=1e-6, rtol=1e-6)
    g_t = jax.grad(lambda *a: loss(fa._flash_core, *a),
                   argnums=(0, 1, 2))(q, k, v)
    g_kv = jax.grad(lambda *a: loss(fa._flash_core_kv, *a),
                    argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_t, g_kv):
        np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_flat_native_matches_transpose_path(causal):
    """Flat-native core (all operands ride unpadded [B,S,H*D] views,
    per-head 64-lane slices): forward and all three gradients must be
    numerically identical to the transpose core, MHA and GQA."""
    B, S, H, D = 2, 128, 3, 32
    q, k, v = _rand((B, S, H, D)), _rand((B, S, H, D)), _rand((B, S, H, D))

    def loss(core, q_, k_, v_):
        return (core(q_, k_, v_, causal, 64, 64)
                .astype(jnp.float32) * 0.01).sum()

    out_f = fa._flash_core_flat(q, k, v, causal, 64, 64)
    out_t = fa._flash_core(q, k, v, causal, 64, 64)
    np.testing.assert_allclose(out_f, out_t, atol=1e-6, rtol=1e-6)
    g_t = jax.grad(lambda *a: loss(fa._flash_core, *a),
                   argnums=(0, 1, 2))(q, k, v)
    g_f = jax.grad(lambda *a: loss(fa._flash_core_flat, *a),
                   argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_t, g_f):
        np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-6)

    # GQA: grouped KV lane reads + group-summed dk/dv
    HQ, HKV = 4, 2
    q2 = _rand((B, S, HQ, D))
    k2 = _rand((B, S, HKV, D))
    v2 = _rand((B, S, HKV, D))
    out_f = fa._flash_core_flat(q2, k2, v2, causal, 64, 64)
    out_t = fa._flash_core(q2, k2, v2, causal, 64, 64)
    np.testing.assert_allclose(out_f, out_t, atol=1e-6, rtol=1e-6)
    g_t = jax.grad(lambda *a: loss(fa._flash_core, *a),
                   argnums=(0, 1, 2))(q2, k2, v2)
    g_f = jax.grad(lambda *a: loss(fa._flash_core_flat, *a),
                   argnums=(0, 1, 2))(q2, k2, v2)
    for a, b in zip(g_t, g_f):
        np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-6)


def test_flash_kv_native_dispatch_gate(monkeypatch):
    """FLAGS_flash_layout=kv routes eligible unpadded shapes through the
    kv-native core and leaves VMEM-infeasible shapes on the transpose
    path (_kv_native_ok)."""
    B, S, H, D = 2, 128, 2, 64
    q = _rand((B, S, H, D))
    assert fa._kv_native_ok(q, q)
    big = jax.ShapeDtypeStruct((1, 8192, 32, 128), jnp.bfloat16)

    class _Fake:
        shape = big.shape
        dtype = jnp.dtype(jnp.bfloat16)

    assert not fa._kv_native_ok(_Fake(), _Fake())
    assert fa._flat_native_ok(q, q)  # H*D = 128: lane-aligned, D%64==0

    class _OffTile:  # H*D = 64 — below the 128-lane tile
        shape = (2, 128, 4, 16)
        dtype = jnp.dtype(jnp.bfloat16)

    assert fa._kv_native_ok(_OffTile(), _OffTile())  # kv: no lane gate
    assert not fa._flat_native_ok(_OffTile(), _OffTile())

    class _OffHead:  # H*D = 128 lane-aligned but D=32: not compile-proven
        shape = (2, 128, 4, 32)
        dtype = jnp.dtype(jnp.bfloat16)

    assert fa._kv_native_ok(_OffHead(), _OffHead())  # kv: no width gate
    assert not fa._flat_native_ok(_OffHead(), _OffHead())

    class _Mid:  # VMEM-borderline: feasible at 512 blocks, not at 1024
        shape = (1, 1024, 12, 64)
        dtype = jnp.dtype(jnp.bfloat16)

    # advisor-medium r5: the gate estimates with the blocks that will
    # REALLY run — tuned 1024-blocks must be gated as 1024, not as the
    # old hardcoded 512 estimate
    assert fa._kv_native_ok(_Mid(), _Mid(), 512, 512)
    assert not fa._kv_native_ok(_Mid(), _Mid(), 1024, 1024)

    monkeypatch.setenv("FLAGS_flash_layout", "kv")
    # on CPU the public entry routes to the reference path
    # (flash_attention_available gates on TPU); force the interpreter
    # kernels so the dispatch decision itself is what's under test
    monkeypatch.setattr(fa, "flash_attention_available", lambda q_: True)
    called = {}
    orig = fa._flash_core_kv

    def spy(*a, **kw):
        called["kv"] = True
        return orig(*a, **kw)

    monkeypatch.setattr(fa, "_flash_core_kv", spy)
    out = fa.flash_attention_fwd(q, q, q, is_causal=True)
    assert called.get("kv"), "kv layout flag did not route to the kv core"
    ref = fa._ref_attention(q, q, q, None, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # flat (and auto, which prefers flat) route to the flat core
    orig_flat = fa._flash_core_flat

    def spy_flat(*a, **kw):
        called["flat"] = True
        return orig_flat(*a, **kw)

    monkeypatch.setattr(fa, "_flash_core_flat", spy_flat)
    for flag in ("flat", "auto"):
        called.pop("flat", None)
        monkeypatch.setenv("FLAGS_flash_layout", flag)
        out = fa.flash_attention_fwd(q, q, q, is_causal=True)
        assert called.get("flat"), (
            f"layout {flag!r} did not route to the flat core")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


def test_flash_gqa_expand_flag_routes(monkeypatch):
    """FLAGS_flash_gqa_expand forces the expanded-KV path: the kernels
    then see Hkv == Hq (and the result still matches the reference)."""
    from paddle_tpu.core import flags as _flags

    B, S, HQ, HKV, D = 2, 128, 4, 2, 32
    q = _rand((B, S, HQ, D))
    k = _rand((B, S, HKV, D))
    v = _rand((B, S, HKV, D))
    monkeypatch.setattr(fa, "flash_attention_available", lambda q_: True)
    # pin the layout: an inherited FLAGS_flash_layout=flat/kv would route
    # past the _flash_core spy and fail this test spuriously
    monkeypatch.setenv("FLAGS_flash_layout", "transpose")
    seen = {}
    orig = fa._flash_core

    def spy(q_, k_, v_, *a, **kw):
        seen["h_kv"] = k_.shape[2]
        return orig(q_, k_, v_, *a, **kw)

    monkeypatch.setattr(fa, "_flash_core", spy)
    _flags.set_flags({"FLAGS_flash_gqa_expand": True})
    try:
        out = fa.flash_attention_fwd(q, k, v, is_causal=True)
    finally:
        _flags.set_flags({"FLAGS_flash_gqa_expand": False})
    assert seen.get("h_kv") == HQ, "expand flag did not expand KV heads"
    ref = fa._ref_attention(q, k, v, None, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # default: grouped (KV stays shrunk)
    seen.clear()
    out = fa.flash_attention_fwd(q, k, v, is_causal=True)
    assert seen.get("h_kv") == HKV
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gqa_matches_expanded_reference(causal):
    """GQA-native kernels (Hkv < Hq, grouped via index maps — KV never
    expands in memory): values and grads must equal running the expanded
    MHA reference; dk/dv come back at the KV head count, equal to the
    group-summed expanded grads."""
    B, S, HQ, HKV, D = 2, 128, 4, 2, 32
    rep = HQ // HKV
    q = _rand((B, S, HQ, D))
    k = _rand((B, S, HKV, D))
    v = _rand((B, S, HKV, D))
    out = fa._flash_core(q, k, v, causal, 64, 64)
    ref = fa._ref_attention(q, k, v, None, causal)  # expands internally
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def loss_flash(q_, k_, v_):
        o = fa._flash_core(q_, k_, v_, causal, 64, 64)
        return (o.astype(jnp.float32) * 0.01).sum()

    def loss_ref(q_, k_, v_):
        ke = jnp.repeat(k_, rep, axis=2)
        ve = jnp.repeat(v_, rep, axis=2)
        o = fa._ref_attention(q_, ke, ve, None, causal)
        return (o.astype(jnp.float32) * 0.01).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    assert gf[1].shape == (B, S, HKV, D)  # grads at KV head count
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=3e-5, rtol=3e-5)


# ====================== varlen (packed) attention ======================

from paddle_tpu.ops.pallas import varlen_attention as vla


@pytest.mark.parametrize("causal", [False, True])
def test_varlen_attention_matches_per_sequence_dense(causal):
    """Packed ragged batch through the segment-masked kernels must equal
    running each sequence separately through dense attention — values
    and grads; segments must not leak into each other."""
    lens = [13, 37, 6]
    H, D = 2, 32
    T = sum(lens)
    cu = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
    q = _rand((T, H, D))
    k = _rand((T, H, D))
    v = _rand((T, H, D))
    scale = 0.17  # non-default: the explicit-scale plumbing must matter

    def ref(q_, k_, v_):
        outs = []
        for i in range(len(lens)):
            s, e = int(cu[i]), int(cu[i + 1])
            qs = q_[None, s:e]  # [1, L, H, D]
            logits = jnp.einsum("bqhd,bkhd->bhqk", qs.astype(jnp.float32),
                                k_[None, s:e].astype(jnp.float32)) * scale
            if causal:
                L = e - s
                m = jnp.tril(jnp.ones((L, L), bool))
                logits = jnp.where(m, logits, -1e30)
            p = jax.nn.softmax(logits, axis=-1)
            outs.append(jnp.einsum(
                "bhqk,bkhd->bqhd", p,
                v_[None, s:e].astype(jnp.float32))[0])
        return jnp.concatenate(outs, axis=0).astype(q_.dtype)

    out = vla.varlen_attention(q, k, v, cu, cu, scale=scale,
                               causal=causal, block_q=16, block_k=16)
    np.testing.assert_allclose(out, ref(q, k, v), atol=3e-5, rtol=3e-5)

    def loss_vl(q_, k_, v_):
        o = vla.varlen_attention(q_, k_, v_, cu, cu, scale=scale,
                                 causal=causal, block_q=16, block_k=16)
        return (o.astype(jnp.float32) * 0.01).sum()

    def loss_ref(q_, k_, v_):
        return (ref(q_, k_, v_).astype(jnp.float32) * 0.01).sum()

    gf = jax.grad(loss_vl, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=3e-5, rtol=3e-5)


def test_flash_attn_unpadded_api():
    """nn.functional surface (reference flash_attention.py:302):
    Tensor in/out, (out, None) tuple, scale honored."""
    import paddle_tpu as P
    import paddle_tpu.nn.functional as F

    lens = [5, 11]
    T, H, D = sum(lens), 2, 16
    cu = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
    rs_ = np.random.RandomState(3)
    q = P.to_tensor(rs_.randn(T, H, D).astype(np.float32))
    out, sm = F.flash_attn_unpadded(
        q, q, q, P.to_tensor(cu), P.to_tensor(cu),
        max_seqlen_q=max(lens), max_seqlen_k=max(lens),
        scale=1.0 / np.sqrt(D), causal=True)
    assert sm is None
    assert list(out.shape) == [T, H, D]
    assert np.isfinite(out.numpy()).all()


def test_flash_attn_unpadded_rejects_unsupported():
    """Loud errors for semantics the fused path cannot honor: prob
    dropout, mismatched causal packings, return_softmax."""
    import paddle_tpu as P
    import paddle_tpu.nn.functional as F

    T, H, D = 16, 2, 16
    q = P.to_tensor(np.random.RandomState(0).randn(T, H, D)
                    .astype(np.float32))
    cu_a = P.to_tensor(np.array([0, 8, 16], np.int32))
    cu_b = P.to_tensor(np.array([0, 4, 16], np.int32))
    kw = dict(max_seqlen_q=8, max_seqlen_k=8, scale=0.25)
    with pytest.raises(NotImplementedError, match="softmax"):
        F.flash_attn_unpadded(q, q, q, cu_a, cu_a, return_softmax=True,
                              **kw)
    with pytest.raises(NotImplementedError, match="dropout"):
        F.flash_attn_unpadded(q, q, q, cu_a, cu_a, dropout=0.1, **kw)
    with pytest.raises(NotImplementedError, match="identical"):
        F.flash_attn_unpadded(q, q, q, cu_a, cu_b, causal=True, **kw)
    # dropout accepted outside training (inference parity)
    out, _ = F.flash_attn_unpadded(q, q, q, cu_a, cu_a, dropout=0.1,
                                   training=False, **kw)
    assert np.isfinite(out.numpy()).all()


def test_sdp_kernel_policy_context():
    """sdp_kernel() (reference flash_attention.py:27): constrains which
    backend scaled_dot_product_attention picks; restores on exit; all
    backends disabled is a loud error."""
    import paddle_tpu as P
    import paddle_tpu.nn.functional as F
    from paddle_tpu.nn.functional import attention as attn_mod

    x = P.to_tensor(np.random.RandomState(0)
                    .randn(1, 16, 2, 16).astype(np.float32))
    with F.sdp_kernel(enable_math=True, enable_flash=False,
                      enable_mem_efficient=False):
        assert attn_mod._sdp_policy == {"math": True, "flash": False}
        out = F.scaled_dot_product_attention(x, x, x, is_causal=True)
        assert np.isfinite(out.numpy()).all()
    assert attn_mod._sdp_policy == {"math": True, "flash": True}
    with pytest.raises(RuntimeError, match="backend"):
        with F.sdp_kernel(enable_math=False, enable_flash=False,
                          enable_mem_efficient=False):
            F.scaled_dot_product_attention(x, x, x, is_causal=True)
    # math disabled + flash enabled-but-unavailable (CPU eager has no
    # Mosaic kernel): silently falling through to the disabled math path
    # would violate the policy — must raise instead (ADVICE r4)
    with pytest.raises(RuntimeError, match="unavailable"):
        with F.sdp_kernel(enable_math=False, enable_flash=True,
                          enable_mem_efficient=False):
            F.scaled_dot_product_attention(x, x, x, is_causal=True)


# ===================== biased (additive-mask) flash =====================


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("bshape", [(2, 2, 64, 128), (1, 1, 64, 128),
                                    (2, 1, 64, 128)])
def test_flash_biased_matches_reference(causal, bshape):
    """Additive bias streamed blockwise must equal the reference's
    full-logits bias add — values and q/k/v grads (bias gets zero grad
    by contract; the entry gates on stop_gradient)."""
    B, SQ, SK, H, D = 2, 64, 128, 2, 16
    q, k, v = _rand((B, SQ, H, D)), _rand((B, SK, H, D)), _rand((B, SK, H, D))
    bias = _rand(bshape) * 0.3
    out = fa._flash_core_b(q, k, v, bias, causal, 32, 128)
    ref = fa._ref_attention(q, k, v, jnp.broadcast_to(
        bias, (B, H, SQ, SK)), causal)
    np.testing.assert_allclose(out, ref, atol=3e-5, rtol=3e-5)

    def loss_b(q_, k_, v_):
        o = fa._flash_core_b(q_, k_, v_, bias, causal, 32, 128)
        return (o.astype(jnp.float32) * 0.01).sum()

    def loss_ref(q_, k_, v_):
        o = fa._ref_attention(q_, k_, v_, jnp.broadcast_to(
            bias, (B, H, SQ, SK)), causal)
        return (o.astype(jnp.float32) * 0.01).sum()

    gf = jax.grad(loss_b, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=3e-5, rtol=3e-5)
    # bias cotangent is zero by contract
    gb = jax.grad(lambda b_: (fa._flash_core_b(
        q, k, v, b_, causal, 32, 128).astype(jnp.float32) * 0.01).sum())(
        bias)
    np.testing.assert_allclose(gb, np.zeros_like(bias))


def test_flash_biased_bool_mask_and_gate():
    """Boolean masks convert to additive -inf on the biased core
    (exercised DIRECTLY — the entry falls back on CPU, so the gate logic
    is tested as a unit)."""
    B, S, H, D = 1, 128, 2, 16
    q = _rand((B, S, H, D))
    keep = jnp.asarray(
        np.random.RandomState(0).rand(1, 1, S, S) > 0.3)
    bias = jnp.where(keep, 0.0, fa.NEG_INF).astype(jnp.float32)
    out = fa._flash_core_b(q, q, q, bias, False, 64, 128)
    ref = fa._ref_attention(q, q, q, keep, False)
    np.testing.assert_allclose(out, ref, atol=3e-5, rtol=3e-5)
    # gate unit tests: accepts the canonical shape, rejects GQA, odd
    # lengths, and non-broadcastable masks
    kgqa = jnp.zeros((B, S, 1, D))
    assert fa._biased_flash_ok(q, q, jnp.zeros((1, 1, S, S)))
    assert fa._biased_flash_ok(q, q, jnp.zeros((B, H, S, S)))
    assert not fa._biased_flash_ok(q, kgqa, jnp.zeros((1, 1, S, S)))
    assert not fa._biased_flash_ok(q, q, jnp.zeros((1, 1, S, S - 8)))
    assert not fa._biased_flash_ok(q, q, jnp.zeros((3, 1, S, S)))
    q_odd = _rand((B, 200, H, D))
    assert not fa._biased_flash_ok(q_odd, q_odd,
                                   jnp.zeros((1, 1, 200, 200)))


def test_tuned_blocks_untuned_default(monkeypatch):
    """Autotune-cold default = the hardware sweep winner that FITS the
    shape under the tightened 8 MB bound (PERF.md r5: (512,1024) wins
    fwd+bwd at the bench and LLaMA shapes), never an oversized pair."""
    from paddle_tpu.ops.pallas import autotune

    monkeypatch.setattr(autotune, "_enabled", lambda: False)
    # bench shape B32 H12 S1024 D64: winner fits well under 8 MB
    assert fa._tuned_blocks(32, 1024, 1024, 12, 64, jnp.bfloat16,
                            True) == (512, 1024)
    # LLaMA-class shape: same winner at D=128
    assert fa._tuned_blocks(8, 2048, 2048, 16, 128, jnp.bfloat16,
                            True) == (512, 1024)
    # biased at S=2048 the (512,1024) bias band alone is 8 MB — the
    # default must shrink rather than return an unvalidated near-limit
    # pair (vmem_est omits backward-only accumulators)
    bq, bk = fa._tuned_blocks(8, 2048, 2048, 16, 128, jnp.bfloat16,
                              True, biased=True)
    assert (bq, bk) != (512, 1024) and bq <= 512
    # short sequences: blocks clamp to the sequence
    bq, bk = fa._tuned_blocks(8, 128, 128, 4, 64, jnp.bfloat16, True)
    assert bq <= 128 and bk <= 128


def test_autotune_pick_contract(monkeypatch, tmp_path):
    """autotune.pick's (f, x) chainable-runner contract (round-5 timing
    methodology v2): candidates are timed inside one compiled loop, the
    winner is disk-cached, and cache hits skip the search. The TPU gate
    is bypassed so the search path runs on CPU."""
    from paddle_tpu.ops.pallas import autotune

    monkeypatch.setattr(autotune, "_CACHE_PATH",
                        str(tmp_path / "autotune.json"))
    # monkeypatch restores _cache to None at teardown — without this the
    # fake test keys would stay in the module-global cache and a later
    # in-process search would _save() them into the user's real cache
    monkeypatch.setattr(autotune, "_cache", None)

    class _Dev:
        platform = "tpu"
        device_kind = "test-kind"

    monkeypatch.setattr(autotune.jax, "devices", lambda: [_Dev()])
    calls = []

    def run(cfg):
        calls.append(cfg)
        # millisecond-scale per iteration: with a microsecond toy body the
        # n2-vs-n1 slope is pure scheduler noise under a loaded CPU and
        # every candidate can "fail" its timing (observed flake: no cache
        # write -> the re-search assertion below trips)
        w = jnp.eye(256, dtype=jnp.float32) * (
            1.0 if cfg == "small" else 1.0001)

        def f(y):
            return y @ w

        return f, jnp.ones((256, 256), jnp.float32)

    got = autotune.pick("testop", "sig1", ["small", "big"], run, "small")
    assert got in ("small", "big")
    assert set(calls) == {"small", "big"}
    # disk-cached: a fresh in-process cache still skips the search
    calls.clear()
    autotune._cache = None
    again = autotune.pick("testop", "sig1", ["small", "big"], run, "small")
    assert again == got
    assert calls == []
    # a failing candidate just loses; the survivor wins
    def run2(cfg):
        if cfg == "bad":
            raise RuntimeError("no compile")
        w2 = jnp.eye(128, dtype=jnp.float32)
        return (lambda y: y @ w2 + 1.0), jnp.zeros((128, 128), jnp.float32)

    assert autotune.pick("testop", "sig2", ["bad", "ok"], run2,
                         "bad") == "ok"


@pytest.mark.slow
def test_train_step_layout_parity(monkeypatch):
    """FULL GPT train step, loss parity across flash layouts: on the
    interpreter every layout runs the same shared recurrences, so three
    steps of training must produce identical losses whether the flash
    dispatch routes transpose, kv-native, or flat-native. Guards the
    opt-in layouts at the train-step level (not just the kernel level)."""
    import paddle_tpu as P
    from paddle_tpu.distributed import fleet, topology
    from paddle_tpu.models.gpt import (
        GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
    )

    import paddle_tpu.ops.pallas as _pl

    # BOTH bindings: fa.flash_attention_fwd consults the module global,
    # but nn.functional.attention gates on the package re-export — the
    # unpatched one silently routes everything to the reference path
    monkeypatch.setattr(fa, "flash_attention_available", lambda q_: True)
    monkeypatch.setattr(_pl, "flash_attention_available",
                        lambda q_: True)
    # hidden 128 / 2 heads -> head_dim 64, H*D = 128: satisfies both the
    # lane-alignment gate AND the d%64 head-width gate (_flat_native_ok)
    # so kv/flat route
    kw = dict(vocab_size=211, hidden_size=128, num_layers=2, num_heads=2,
              max_seq_len=32, dropout=0.0, attn_dropout=0.0)
    losses = {}
    routed = {}
    cores = {"transpose": "_flash_core", "kv": "_flash_core_kv",
             "flat": "_flash_core_flat"}
    for layout in ("transpose", "kv", "flat"):
        monkeypatch.setenv("FLAGS_flash_layout", layout)
        orig_core = getattr(fa, cores[layout])

        def spy(*a, _oc=orig_core, _ly=layout, **kw2):
            routed[_ly] = True
            return _oc(*a, **kw2)

        monkeypatch.setattr(fa, cores[layout], spy)
        topology.reset_topology()
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sep_degree": 1, "sharding_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)
        P.seed(11)
        model = GPTForCausalLM(GPTConfig(**kw))
        crit = GPTPretrainingCriterion()
        dm = fleet.distributed_model(model)
        opt = fleet.distributed_optimizer(
            P.optimizer.SGD(parameters=model.parameters(),
                            learning_rate=0.1))
        step = dm.build_train_step(opt, crit)
        rs = np.random.RandomState(3)
        ids = P.to_tensor(rs.randint(0, 211, (2, 32)), "int32")
        lab = P.to_tensor(rs.randint(0, 211, (2, 32)), "int32")
        losses[layout] = [float(step(ids, lab)) for _ in range(3)]
        monkeypatch.setattr(fa, cores[layout], orig_core)
        assert routed.get(layout), (
            f"layout {layout!r} never reached its flash core — "
            "dispatch fell back, the parity comparison would be vacuous")
    np.testing.assert_allclose(losses["transpose"], losses["kv"],
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(losses["transpose"], losses["flat"],
                               rtol=1e-6, atol=1e-6)
