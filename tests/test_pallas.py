"""Pallas kernel tests — run through the interpreter on CPU so the exact
kernel code is validated without hardware (SURVEY §4.5 fake-backend
strategy)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas import flash_attention as fa

rs = np.random.RandomState(0)


def _rand(shape):
    return jnp.asarray(rs.randn(*shape), jnp.float32)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_matches_reference(causal):
    B, S, H, D = 2, 256, 2, 64
    q, k, v = _rand((B, S, H, D)), _rand((B, S, H, D)), _rand((B, S, H, D))
    out = fa._flash_core(q, k, v, causal, 128, 128)
    ref = fa._ref_attention(q, k, v, None, causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_matches_reference(causal):
    B, S, H, D = 1, 128, 2, 64
    q, k, v = _rand((B, S, H, D)), _rand((B, S, H, D)), _rand((B, S, H, D))

    def loss_flash(q, k, v):
        return jnp.sum(fa._flash_core(q, k, v, causal, 64, 64) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(fa._ref_attention(q, k, v, None, causal) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_flash_uneven_blocks():
    # seq not a multiple of the block: pallas pads the trailing block
    B, S, H, D = 1, 192, 2, 64
    q, k, v = _rand((B, S, H, D)), _rand((B, S, H, D)), _rand((B, S, H, D))
    out = fa._flash_core(q, k, v, True, 128, 128)
    ref = fa._ref_attention(q, k, v, None, True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_bf16_io():
    B, S, H, D = 1, 128, 2, 64
    q = _rand((B, S, H, D)).astype(jnp.bfloat16)
    k = _rand((B, S, H, D)).astype(jnp.bfloat16)
    v = _rand((B, S, H, D)).astype(jnp.bfloat16)
    out = fa._flash_core(q, k, v, True, 64, 64)
    assert out.dtype == jnp.bfloat16
    ref = fa._ref_attention(q, k, v, None, True)
    np.testing.assert_allclose(out.astype(jnp.float32),
                               ref.astype(jnp.float32), atol=3e-2, rtol=3e-2)
