"""Pipeline-parallel tests on the virtual 8-device CPU mesh."""
import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu.distributed import fleet, topology
from paddle_tpu.distributed.pipeline import (
    PipelineLayer, PipelineParallel, bubble_fraction, interleaved_order,
    segment_layers,
)
from paddle_tpu.models.gpt import (
    GPTForCausalLM, GPTPretrainingCriterion, gpt_pipe_layers, gpt_tiny,
)


@pytest.fixture(autouse=True)
def fresh_topology():
    topology.reset_topology()
    yield
    topology.reset_topology()


def _init(pp=4, dp=2, mp=1):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": mp,
                               "pp_degree": pp, "sep_degree": 1,
                               "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)


def test_segment_layers():
    import paddle_tpu.nn as nn

    layers = [nn.Linear(4, 4) for _ in range(10)]
    segs = segment_layers(layers, 4)
    assert sum(len(s) for s in segs) == 10
    assert len(segs) == 4
    assert all(len(s) >= 1 for s in segs)


@pytest.mark.slow
@pytest.mark.parametrize("schedule", ["1F1B", "FThenB"])
def test_pp_training_decreases(schedule):
    _init(pp=4, dp=2)
    P.seed(0)
    cfg = gpt_tiny(tie_embeddings=False, dropout=0.0)
    pipe = PipelineLayer(gpt_pipe_layers(cfg),
                         loss_fn=GPTPretrainingCriterion())
    opt = P.optimizer.AdamW(parameters=pipe.parameters(), learning_rate=1e-3)
    runner = PipelineParallel(pipe, opt, num_micro_batches=4,
                              schedule=schedule)
    ids = P.randint(0, cfg.vocab_size, [8, 16])
    labels = P.randint(0, cfg.vocab_size, [8, 16])
    losses = [float(runner.train_batch((ids, labels))) for _ in range(4)]
    assert losses[-1] < losses[0], losses
    assert all(np.isfinite(l) for l in losses)


def test_pp_matches_single_process():
    """PP-partitioned model must match the non-pipelined model step for step
    (same init, same data, SGD)."""
    P.seed(0)
    cfg = gpt_tiny(tie_embeddings=False, dropout=0.0, num_layers=2)

    # baseline: plain eager model
    _init(pp=1, dp=1)
    P.seed(123)
    layers_a = gpt_pipe_layers(cfg)
    import paddle_tpu.nn as nn

    seq_model = nn.Sequential(*layers_a)
    crit = GPTPretrainingCriterion()
    opt_a = P.optimizer.SGD(parameters=seq_model.parameters(),
                            learning_rate=0.1)
    ids = P.randint(0, cfg.vocab_size, [4, 16])
    labels = P.randint(0, cfg.vocab_size, [4, 16])
    base_losses = []
    for _ in range(3):
        loss = crit(seq_model(ids), labels)
        loss.backward()
        opt_a.step()
        opt_a.clear_grad()
        base_losses.append(float(loss))

    # pipeline: same init (reseed), pp=2
    topology.reset_topology()
    _init(pp=2, dp=1)
    P.seed(123)
    layers_b = gpt_pipe_layers(cfg)
    pipe = PipelineLayer(layers_b, loss_fn=GPTPretrainingCriterion())
    opt_b = P.optimizer.SGD(parameters=pipe.parameters(), learning_rate=0.1)
    runner = PipelineParallel(pipe, opt_b, num_micro_batches=2)
    pp_losses = [float(runner.train_batch((ids, labels))) for _ in range(3)]

    np.testing.assert_allclose(base_losses, pp_losses, rtol=2e-4)


def test_interleaved_order_valid_and_distinct():
    """VPP order covers every (chunk, op, mb) once, respects dependencies,
    and actually differs from the non-interleaved schedule."""
    pp, v, m = 4, 2, 8
    order = interleaved_order(pp, v, m)
    n_chunks = pp * v
    assert len(order) == 2 * n_chunks * m
    assert len(set(order)) == len(order)
    fdone, bdone = set(), set()
    for (c, op, mb) in order:
        assert 0 <= c < n_chunks and 0 <= mb < m
        if op == "F":
            if c > 0:
                assert (c - 1, mb) in fdone, (c, mb)
            fdone.add((c, mb))
        else:
            assert (c, mb) in fdone
            if c < n_chunks - 1:
                assert (c + 1, mb) in bdone
            bdone.add((c, mb))
    plain = interleaved_order(pp, 1, m)
    assert order != plain


def test_vpp_reduces_bubble():
    """Megatron's point: bubble fraction shrinks ~1/v at equal total work."""
    pp, m = 4, 8
    b1 = bubble_fraction(pp, m, v=1)
    b2 = bubble_fraction(pp, m, v=2)
    assert 0.0 < b2 < b1, (b1, b2)
    # analytic bound: 1F1B bubble = (pp-1)/(m + pp - 1); VPP divides the
    # fill/drain time by v (allow slack for schedule granularity)
    assert b2 <= b1 * 0.75, (b1, b2)


@pytest.mark.slow
def test_vpp_parity_with_plain_pipeline():
    """num_virtual_pipeline_stages=2 must give the same losses as the
    non-interleaved pipeline (same init/data/SGD)."""
    cfg = gpt_tiny(tie_embeddings=False, dropout=0.0, num_layers=4)

    _init(pp=2, dp=1)
    P.seed(123)
    layers_a = gpt_pipe_layers(cfg)
    pipe_a = PipelineLayer(layers_a, loss_fn=GPTPretrainingCriterion())
    opt_a = P.optimizer.SGD(parameters=pipe_a.parameters(), learning_rate=0.1)
    runner_a = PipelineParallel(pipe_a, opt_a, num_micro_batches=2)
    ids = P.randint(0, cfg.vocab_size, [4, 16])
    labels = P.randint(0, cfg.vocab_size, [4, 16])
    plain_losses = [float(runner_a.train_batch((ids, labels)))
                    for _ in range(3)]

    topology.reset_topology()
    _init(pp=2, dp=1)
    P.seed(123)
    layers_b = gpt_pipe_layers(cfg)
    pipe_b = PipelineLayer(layers_b, loss_fn=GPTPretrainingCriterion(),
                           num_virtual_pipeline_stages=2)
    assert len(pipe_b.stages) == 4  # pp=2 × vpp=2 chunks
    opt_b = P.optimizer.SGD(parameters=pipe_b.parameters(), learning_rate=0.1)
    runner_b = PipelineParallel(pipe_b, opt_b, num_micro_batches=2)
    vpp_losses = [float(runner_b.train_batch((ids, labels)))
                  for _ in range(3)]

    np.testing.assert_allclose(plain_losses, vpp_losses, rtol=2e-4)


def test_pp_state_dict_roundtrip():
    _init(pp=2, dp=1)
    P.seed(0)
    cfg = gpt_tiny(tie_embeddings=False, num_layers=2)
    pipe = PipelineLayer(gpt_pipe_layers(cfg),
                         loss_fn=GPTPretrainingCriterion())
    opt = P.optimizer.SGD(parameters=pipe.parameters(), learning_rate=0.1)
    runner = PipelineParallel(pipe, opt, num_micro_batches=2)
    ids = P.randint(0, cfg.vocab_size, [4, 16])
    labels = P.randint(0, cfg.vocab_size, [4, 16])
    runner.train_batch((ids, labels))
    sd = runner.state_dict()
    assert len(sd) == len(pipe.state_dict())


def test_pp_zero_sharding_composition():
    """PP composed with ZeRO slot sharding (sharding_stage=2): optimizer
    slots live dp-sharded on each stage submesh, training still converges,
    and the post-step states keep the dp partitioning (VERDICT r3 Next #3)."""
    _init(pp=2, dp=2, mp=2)
    P.seed(0)
    cfg = gpt_tiny(tie_embeddings=False, dropout=0.0, num_layers=2)
    pipe = PipelineLayer(gpt_pipe_layers(cfg),
                         loss_fn=GPTPretrainingCriterion())
    opt = P.optimizer.AdamW(parameters=pipe.parameters(), learning_rate=1e-3)
    runner = PipelineParallel(pipe, opt, num_micro_batches=2,
                              sharding_stage=2)
    ids = P.randint(0, cfg.vocab_size, [4, 16])
    labels = P.randint(0, cfg.vocab_size, [4, 16])
    losses = [float(runner.train_batch((ids, labels))) for _ in range(3)]
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses
    # slots must actually be dp-sharded AFTER an update (the constraint
    # pins the partitioning across steps, not just at init)
    dp_sharded = 0
    for state in runner._opt_states:
        for sd in state["slots"].values():
            for v in sd.values():
                spec = getattr(getattr(v, "sharding", None), "spec", ())
                if "dp" in tuple(spec):
                    dp_sharded += 1
    assert dp_sharded > 0, "no optimizer slot carries a dp sharding"


@pytest.mark.slow
def test_pp_train_resume_exact(tmp_path):
    """PP-tier training resume: per-stage params + AdamW slots + step
    counters round-trip; the resumed run's losses match the
    uninterrupted run's."""
    rs = np.random.RandomState(0)
    batches = [(rs.randint(0, 1024, (8, 16)), rs.randint(0, 1024, (8, 16)))
               for _ in range(4)]

    def run(feed, ckpt=None, save_at=None, save_path=None):
        topology.reset_topology()
        _init(pp=2, dp=1)
        P.seed(0)
        cfg = gpt_tiny(tie_embeddings=False, dropout=0.0, num_layers=2)
        pipe = PipelineLayer(gpt_pipe_layers(cfg),
                             loss_fn=GPTPretrainingCriterion())
        # decaying schedule: a resume that restarted the scheduler while
        # the Adam step counter continued would diverge visibly
        sched = P.optimizer.lr.StepDecay(learning_rate=1e-3, step_size=1,
                                         gamma=0.5)
        opt = P.optimizer.AdamW(parameters=pipe.parameters(),
                                learning_rate=sched)
        runner = PipelineParallel(pipe, opt, num_micro_batches=2)
        if ckpt is not None:
            runner.load_train_state(ckpt)
        losses = []
        for i, (ids, labels) in enumerate(feed):
            losses.append(float(runner.train_batch(
                (P.to_tensor(ids, "int32"), P.to_tensor(labels, "int32")))))
            sched.step()
            if save_at is not None and i + 1 == save_at:
                runner.save_train_state(save_path)
        return losses

    a = run(batches)
    ck = str(tmp_path / "pp_ck")
    head = run(batches[:2], save_at=2, save_path=ck)
    np.testing.assert_allclose(head, a[:2], rtol=1e-6)
    # resumed run continues on the LATER batches as if never interrupted
    np.testing.assert_allclose(run(batches[2:], ckpt=ck), a[2:],
                               rtol=1e-5)
