"""Pipeline-parallel tests on the virtual 8-device CPU mesh."""
import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu.distributed import fleet, topology
from paddle_tpu.distributed.pipeline import (
    PipelineLayer, PipelineParallel, segment_layers,
)
from paddle_tpu.models.gpt import (
    GPTForCausalLM, GPTPretrainingCriterion, gpt_pipe_layers, gpt_tiny,
)


@pytest.fixture(autouse=True)
def fresh_topology():
    topology.reset_topology()
    yield
    topology.reset_topology()


def _init(pp=4, dp=2, mp=1):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": mp,
                               "pp_degree": pp, "sep_degree": 1,
                               "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)


def test_segment_layers():
    import paddle_tpu.nn as nn

    layers = [nn.Linear(4, 4) for _ in range(10)]
    segs = segment_layers(layers, 4)
    assert sum(len(s) for s in segs) == 10
    assert len(segs) == 4
    assert all(len(s) >= 1 for s in segs)


@pytest.mark.parametrize("schedule", ["1F1B", "FThenB"])
def test_pp_training_decreases(schedule):
    _init(pp=4, dp=2)
    P.seed(0)
    cfg = gpt_tiny(tie_embeddings=False, dropout=0.0)
    pipe = PipelineLayer(gpt_pipe_layers(cfg),
                         loss_fn=GPTPretrainingCriterion())
    opt = P.optimizer.AdamW(parameters=pipe.parameters(), learning_rate=1e-3)
    runner = PipelineParallel(pipe, opt, num_micro_batches=4,
                              schedule=schedule)
    ids = P.randint(0, cfg.vocab_size, [8, 16])
    labels = P.randint(0, cfg.vocab_size, [8, 16])
    losses = [float(runner.train_batch((ids, labels))) for _ in range(4)]
    assert losses[-1] < losses[0], losses
    assert all(np.isfinite(l) for l in losses)


def test_pp_matches_single_process():
    """PP-partitioned model must match the non-pipelined model step for step
    (same init, same data, SGD)."""
    P.seed(0)
    cfg = gpt_tiny(tie_embeddings=False, dropout=0.0, num_layers=2)

    # baseline: plain eager model
    _init(pp=1, dp=1)
    P.seed(123)
    layers_a = gpt_pipe_layers(cfg)
    import paddle_tpu.nn as nn

    seq_model = nn.Sequential(*layers_a)
    crit = GPTPretrainingCriterion()
    opt_a = P.optimizer.SGD(parameters=seq_model.parameters(),
                            learning_rate=0.1)
    ids = P.randint(0, cfg.vocab_size, [4, 16])
    labels = P.randint(0, cfg.vocab_size, [4, 16])
    base_losses = []
    for _ in range(3):
        loss = crit(seq_model(ids), labels)
        loss.backward()
        opt_a.step()
        opt_a.clear_grad()
        base_losses.append(float(loss))

    # pipeline: same init (reseed), pp=2
    topology.reset_topology()
    _init(pp=2, dp=1)
    P.seed(123)
    layers_b = gpt_pipe_layers(cfg)
    pipe = PipelineLayer(layers_b, loss_fn=GPTPretrainingCriterion())
    opt_b = P.optimizer.SGD(parameters=pipe.parameters(), learning_rate=0.1)
    runner = PipelineParallel(pipe, opt_b, num_micro_batches=2)
    pp_losses = [float(runner.train_batch((ids, labels))) for _ in range(3)]

    np.testing.assert_allclose(base_losses, pp_losses, rtol=2e-4)


def test_pp_state_dict_roundtrip():
    _init(pp=2, dp=1)
    P.seed(0)
    cfg = gpt_tiny(tie_embeddings=False, num_layers=2)
    pipe = PipelineLayer(gpt_pipe_layers(cfg),
                         loss_fn=GPTPretrainingCriterion())
    opt = P.optimizer.SGD(parameters=pipe.parameters(), learning_rate=0.1)
    runner = PipelineParallel(pipe, opt, num_micro_batches=2)
    ids = P.randint(0, cfg.vocab_size, [4, 16])
    labels = P.randint(0, cfg.vocab_size, [4, 16])
    runner.train_batch((ids, labels))
    sd = runner.state_dict()
    assert len(sd) == len(pipe.state_dict())
