"""text.datasets loaders against tiny synthetic archives in the official
formats (reference test strategy: corpus fixtures, no network)."""
import gzip
import io
import os
import tarfile
import zipfile

import numpy as np
import pytest

from paddle_tpu.text import (
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16,
)


def _add_bytes(tf, name, data):
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tf.addfile(info, io.BytesIO(data))


def test_imdb(tmp_path):
    p = tmp_path / "aclImdb_v1.tar.gz"
    docs = {
        "aclImdb/train/pos/0.txt": b"good great good film, truly great!",
        "aclImdb/train/neg/0.txt": b"bad awful bad film.",
        "aclImdb/test/pos/0.txt": b"great good",
        "aclImdb/test/neg/0.txt": b"awful bad bad",
    }
    with tarfile.open(p, "w:gz") as tf:
        for name, data in docs.items():
            _add_bytes(tf, name, data)
    ds = Imdb(data_file=str(p), mode="train", cutoff=1)
    # vocabulary: words with freq > 1 across the whole corpus (byte
    # tokens, like the reference's bytes-level tokenizer; imdb.py:127)
    assert set(ds.word_idx) >= {b"good", b"great", b"bad", "<unk>"}
    assert len(ds) == 2
    doc, label = ds[0]
    assert doc.ndim == 1 and label.shape == (1,)
    labels = sorted(int(ds[i][1][0]) for i in range(len(ds)))
    assert labels == [0, 1]  # pos=0, neg=1
    # punctuation stripped: no OOV spike from "film," vs "film"
    test = Imdb(data_file=str(p), mode="test", cutoff=1)
    assert len(test) == 2


def test_imdb_requires_local_file():
    with pytest.raises(RuntimeError, match="local archive"):
        Imdb(data_file=None, download=True)


def test_imikolov(tmp_path):
    p = tmp_path / "simple-examples.tgz"
    train = b"the cat sat\nthe dog sat\n"
    valid = b"the cat ran\n"
    with tarfile.open(p, "w:gz") as tf:
        _add_bytes(tf, "./simple-examples/data/ptb.train.txt", train)
        _add_bytes(tf, "./simple-examples/data/ptb.valid.txt", valid)
    ds = Imikolov(data_file=str(p), data_type="NGRAM", window_size=2,
                  mode="train", min_word_freq=0)
    grams = [tuple(int(x) for x in ds[i]) for i in range(len(ds))]
    # "<s> the cat sat <e>" -> 4 bigrams per line
    assert len(grams) == 8
    seq = Imikolov(data_file=str(p), data_type="SEQ", window_size=-1,
                   mode="test", min_word_freq=0)
    src, trg = seq[0]
    assert src[0] == seq.word_idx["<s>"]
    assert trg[-1] == seq.word_idx["<e>"]
    np.testing.assert_array_equal(src[1:], trg[:-1])


def test_movielens(tmp_path):
    p = tmp_path / "ml-1m.zip"
    with zipfile.ZipFile(p, "w") as z:
        z.writestr("ml-1m/movies.dat",
                   "1::Toy Story (1995)::Animation|Comedy\n"
                   "2::Heat (1995)::Action\n")
        z.writestr("ml-1m/users.dat",
                   "1::F::1::10::48067\n2::M::25::16::70072\n")
        z.writestr("ml-1m/ratings.dat",
                   "1::1::5::978300760\n2::2::1::978302109\n"
                   "1::2::4::978301968\n2::1::3::978300275\n")
    train = Movielens(data_file=str(p), mode="train", test_ratio=0.0)
    assert len(train) == 4
    ex = train[0]
    # usr(4) + movie(3) + rating(1) feature groups
    assert len(ex) == 8
    uid, gender, age, job, mid, cats, title, rating = ex
    assert uid.shape == (1,) and rating.shape == (1,)
    assert rating[0] in (5.0, -3.0, 3.0, 1.0)  # r*2-5 for r in 5,1,4,3
    test = Movielens(data_file=str(p), mode="test", test_ratio=1.0)
    assert len(test) == 4


def test_conll05st(tmp_path):
    words = b"The\ncat\nsat\n\n"
    props = b"-  *\nsit  (V*)\n-  (A1*)\n\n"
    # column 0 = predicate lemmas; column 1 = one predicate's labels
    words_gz = gzip.compress(words)
    props_gz = gzip.compress(props)
    p = tmp_path / "conll05st-tests.tar.gz"
    with tarfile.open(p, "w:gz") as tf:
        _add_bytes(tf, "conll05st-release/test.wsj/words/test.wsj.words.gz",
                   words_gz)
        _add_bytes(tf, "conll05st-release/test.wsj/props/test.wsj.props.gz",
                   props_gz)
    wd = tmp_path / "words.dict"
    wd.write_text("The\ncat\nsat\n")
    vd = tmp_path / "verbs.dict"
    vd.write_text("sit\n")
    td = tmp_path / "targets.dict"
    td.write_text("B-V\nI-V\nB-A1\nI-A1\n")
    ds = Conll05st(data_file=str(p), word_dict_file=str(wd),
                   verb_dict_file=str(vd), target_dict_file=str(td),
                   emb_file=None)
    assert len(ds) == 1
    (word_idx, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_idx, mark,
     label_idx) = ds[0]
    assert word_idx.tolist() == [0, 1, 2]
    # predicate at position 1 ("cat" row labeled (V*))
    assert mark.tolist() == [1, 1, 1]
    assert pred_idx.tolist() == [0, 0, 0]
    ldict = ds.get_dict()[2]
    assert label_idx.tolist() == [ldict["O"], ldict["B-V"], ldict["B-A1"]]


def test_uci_housing(tmp_path):
    rows = np.arange(14 * 10, dtype=np.float64).reshape(10, 14)
    p = tmp_path / "housing.data"
    with open(p, "w") as f:
        for r in rows:
            f.write(" ".join(str(x) for x in r) + "\n")
    train = UCIHousing(data_file=str(p), mode="train")
    test = UCIHousing(data_file=str(p), mode="test")
    assert len(train) == 8 and len(test) == 2
    feat, target = train[0]
    assert feat.shape == (13,) and target.shape == (1,)
    assert feat.dtype == np.float32
    # normalized features: (x - mean) / (max - min), target raw
    assert abs(float(feat[0]) - (-0.5)) < 1e-6
    assert float(target[0]) == 13.0


def test_wmt14(tmp_path):
    p = tmp_path / "wmt14.tgz"
    src_dict = b"<s>\n<e>\n<unk>\nhello\nworld\n"
    trg_dict = b"<s>\n<e>\n<unk>\nbonjour\nmonde\n"
    pairs = b"hello world\tbonjour monde\nhello\tbonjour\n"
    with tarfile.open(p, "w:gz") as tf:
        _add_bytes(tf, "wmt14/src.dict", src_dict)
        _add_bytes(tf, "wmt14/trg.dict", trg_dict)
        _add_bytes(tf, "wmt14/train/train", pairs)
    ds = WMT14(data_file=str(p), mode="train", dict_size=5)
    assert len(ds) == 2
    src, trg, trg_next = ds[0]
    assert src.tolist() == [0, 3, 4, 1]  # <s> hello world <e>
    assert trg.tolist() == [0, 3, 4]
    assert trg_next.tolist() == [3, 4, 1]
    sd, td = ds.get_dict()
    assert sd["hello"] == 3 and td["monde"] == 4
    rd, _ = ds.get_dict(reverse=True)
    assert rd[3] == "hello"


def test_wmt16(tmp_path):
    p = tmp_path / "wmt16.tar.gz"
    train = b"a b a\tx y\nb a\ty\n"
    val = b"a\tx\n"
    with tarfile.open(p, "w:gz") as tf:
        _add_bytes(tf, "wmt16/train", train)
        _add_bytes(tf, "wmt16/val", val)
        _add_bytes(tf, "wmt16/test", val)
    ds = WMT16(data_file=str(p), mode="train", src_dict_size=10,
               trg_dict_size=10, lang="en")
    assert ds.src_dict["<s>"] == 0 and ds.src_dict["<unk>"] == 2
    assert ds.src_dict["a"] == 3  # most frequent after specials
    src, trg, trg_next = ds[0]
    assert src.tolist() == [0, 3, 4, 3, 1]
    assert trg[0] == 0 and trg_next[-1] == 1
    np.testing.assert_array_equal(trg[1:], trg_next[:-1])
    val_ds = WMT16(data_file=str(p), mode="val", src_dict_size=10,
                   trg_dict_size=10)
    assert len(val_ds) == 1
