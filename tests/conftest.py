"""Test env: force a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding tests run on
`--xla_force_host_platform_device_count=8` CPU devices (the same trick the
reference uses for mesh emulation, cf. SURVEY.md §4 note). The axon TPU
plugin (registered by sitecustomize at interpreter start) is unregistered
here so tests never block on the TPU tunnel.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.pop("PALLAS_AXON_REMOTE_COMPILE", None)

try:  # drop the axon PJRT backend factory before jax initializes backends
    from jax._src import xla_bridge as _xb

    for reg in ("_backend_factories",):
        d = getattr(_xb, reg, None)
        if isinstance(d, dict):
            d.pop("axon", None)
except Exception as _e:  # metrics don't exist this early: say it on stderr
    print(f"conftest: axon factory drop failed ({_e!r}) — tests may "
          f"touch the TPU tunnel", file=sys.stderr)

# sitecustomize imported jax before this conftest ran, so the config already
# captured JAX_PLATFORMS=axon — override it at the config level too.
import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except Exception as _e:
    print(f"conftest: jax_platforms override failed ({_e!r})",
          file=sys.stderr)

# Persistent XLA compilation cache: compile-heavy 8-device-mesh tests
# dominate suite time (VERDICT r3 Weak #6); a warm cache turns repeat runs
# from minutes of XLA compiles into disk reads. Safe under pytest-xdist —
# the cache uses per-entry atomic file writes.
try:
    _cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
except Exception as _e:
    print(f"conftest: compile-cache setup failed ({_e!r}) — repeat "
          f"runs will recompile", file=sys.stderr)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(0)
