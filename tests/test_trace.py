"""Unified trace timeline tests (ISSUE 2): span tracer nesting/threads,
Perfetto round-trip, signal correlation (RecordEvent scopes, flight
instants, StepTimer frames), xla_cost capture on a jitted fn, the
profiler chrome-export pid/tid fix, and the perf_gate
pass/regress/update/check-only/merge paths.
"""
from __future__ import annotations

import importlib.util
import json
import os
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu import observability as obs
from paddle_tpu.observability import flight, metrics, step_stats, trace, \
    xla_cost

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)


def _reset_telemetry():
    trace.clear()
    trace.disable()
    metrics.reset()
    metrics.disable()
    flight.clear()


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Each test starts from a disabled, empty tracer/registry/ring (the
    defaults are process-global)."""
    _reset_telemetry()
    yield
    _reset_telemetry()


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        "_" + name, os.path.join(ROOT, "tools", name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ============================ span tracer ============================

def test_span_nesting_and_args():
    trace.enable()
    with trace.span("outer", kind="a"):
        assert trace.current_span() == "outer"
        with trace.span("inner") as sp:
            sp.args["extra"] = 42
    evts = [e for e in trace.events() if e["ph"] == "X"]
    assert [e["name"] for e in evts] == ["inner", "outer"]  # close order
    inner, outer = evts
    assert inner["args"]["parent"] == "outer"
    assert inner["args"]["extra"] == 42
    assert outer["args"]["kind"] == "a"
    # child strictly inside parent on the timeline
    assert inner["ts"] >= outer["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-6
    assert inner["tid"] == outer["tid"]


def test_span_disabled_is_noop():
    assert not trace.enabled()
    with trace.span("nope"):
        pass
    assert trace.begin("x") is None
    trace.end(None)
    trace.instant("nope")
    trace.frame("nope", 10.0)
    assert trace.events() == []


def test_disable_mid_span_pops_stack():
    """end() after a mid-span disable must still pop the thread-local
    stack: a leaked entry would mislabel every later span's parent and
    grow the stack on each toggle."""
    trace.enable()
    sp = trace.begin("outer")
    trace.disable()
    trace.end(sp)
    assert trace.current_span() is None
    trace.enable()
    with trace.span("later"):
        pass
    later = [e for e in trace.events() if e["name"] == "later"][0]
    assert "parent" not in later["args"]


def test_traced_decorator():
    trace.enable()

    @trace.traced("my_fn", cat="op")
    def f(x):
        return x + 1

    assert f(1) == 2
    evts = trace.events()
    assert evts and evts[0]["name"] == "my_fn" and evts[0]["cat"] == "op"


def test_span_nesting_under_threads():
    """Each thread gets its own small stable tid and its own nesting
    stack; spans from different threads never share a stack."""
    trace.enable()
    barrier = threading.Barrier(2)

    def worker(tag):
        barrier.wait()
        with trace.span(f"{tag}.outer"):
            with trace.span(f"{tag}.inner"):
                pass

    threads = [threading.Thread(target=worker, args=(f"t{i}",))
               for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    evts = {e["name"]: e for e in trace.events()}
    assert len(evts) == 4
    assert evts["t0.inner"]["tid"] == evts["t0.outer"]["tid"]
    assert evts["t1.inner"]["tid"] == evts["t1.outer"]["tid"]
    assert evts["t0.outer"]["tid"] != evts["t1.outer"]["tid"]
    assert evts["t0.inner"]["args"]["parent"] == "t0.outer"
    assert evts["t1.inner"]["args"]["parent"] == "t1.outer"
    # tids are small and stable, not raw thread idents (~1e14): the
    # GLOBAL tracer numbers every span-emitting thread the test
    # session ever had, so the bound is the design constraint — real
    # threads must sort BELOW the synthetic-track base — not an
    # arbitrary small count that suite growth can tip over
    assert all(e["tid"] < trace._VIRTUAL_SORT_BASE
               for e in evts.values())


def test_bounded_buffer_reports_drops():
    tr = trace.SpanTracer(capacity=8, enabled=True)
    for i in range(20):
        tr.instant(f"e{i}")
    assert len(tr.events()) == 8
    assert tr.dropped() == 12
    assert [e["name"] for e in tr.events()] == [f"e{i}" for i in range(12, 20)]
    assert tr.to_chrome()["otherData"]["dropped_events"] == 12


def test_perfetto_roundtrip(tmp_path):
    """export -> json.load -> schema check (the acceptance-criteria
    'json.loads cleanly' property plus the metadata Perfetto needs)."""
    trace.enable()
    with trace.span("work", step=1):
        trace.instant("decision", tier="flat")
    trace.frame("step 0", 5000.0, track="steps:run1", step=0)
    trace.counter("mem", track="mem:run1", bytes=123)
    path = str(tmp_path / "trace.json")
    assert trace.export(path) == path
    with open(path) as f:
        doc = json.load(f)
    evts = doc["traceEvents"]
    assert doc["displayTimeUnit"] == "ms"
    assert doc["otherData"]["schema"] == trace.SCHEMA_VERSION
    by_ph = {}
    for e in evts:
        by_ph.setdefault(e["ph"], []).append(e)
    # metadata: process_name + thread_name for the real thread AND the
    # synthetic tracks
    meta_names = {(e["name"], e["args"].get("name")) for e in by_ph["M"]}
    assert ("process_name", "paddle_tpu") in meta_names
    assert any(n == "thread_name" and v == "steps:run1"
               for n, v in meta_names)
    assert any(n == "thread_name" and v == "mem:run1"
               for n, v in meta_names)
    # every non-meta event carries pid/tid/ts
    for e in evts:
        if e["ph"] == "M":
            continue
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert e["ts"] >= 0
    assert by_ph["X"] and by_ph["i"] and by_ph["C"]
    # frames/counters sit on synthetic tracks distinct from the thread
    assert by_ph["C"][0]["tid"] != by_ph["i"][0]["tid"]


def test_trace_jsonl_stream_validates(tmp_path):
    trace.enable()
    with trace.span("s"):
        trace.instant("i")
    path = str(tmp_path / "trace.jsonl")
    trace.dump_jsonl(path)
    entries = [json.loads(l) for l in open(path)]
    assert all(e["phase"] == trace.TRACE_PHASE and "t" in e
               for e in entries)
    assert trace.validate_trace_stream(entries) == []
    s = trace.summarize_trace_stream(entries)
    assert s["events"] == 2 and s["by_ph"]["X"] == 1
    # corrupt entries are called out
    bad = [{"phase": trace.TRACE_PHASE, "ph": "X", "name": "x",
            "ts": -1.0, "pid": 1, "tid": 1, "dur": "slow"},
           {"phase": trace.TRACE_PHASE, "ph": "Z", "name": "y"}]
    errs = trace.validate_trace_stream(entries + bad)
    assert len(errs) >= 3


# ========================= signal correlation =========================

def test_record_event_emits_span():
    import paddle_tpu.profiler as profiler

    trace.enable()
    with profiler.RecordEvent("train_step"):
        with profiler.RecordEvent("fwd"):
            pass
    evts = {e["name"]: e for e in trace.events() if e["ph"] == "X"}
    assert set(evts) == {"train_step", "fwd"}
    assert evts["fwd"]["cat"] == "user_scope"
    assert evts["fwd"]["args"]["parent"] == "train_step"


def test_flight_events_become_instants():
    trace.enable()
    flight.get_recorder().enabled = True
    flight.record("flash.gate_reject", gate="kv", reason="vmem")
    evts = [e for e in trace.events() if e["ph"] == "i"]
    assert evts and evts[0]["name"] == "flash.gate_reject"
    assert evts[0]["args"]["reason"] == "vmem"
    # ring still recorded normally
    assert any(e["kind"] == "flash.gate_reject" for e in flight.events())


def test_step_timer_emits_frames():
    trace.enable()
    timer = step_stats.StepTimer(run_id="fr", read_device_memory=False)
    timer.record(0.05, compile_step=True)
    timer.record(0.01, n_steps=4)
    frames = [e for e in trace.events() if e["cat"] == "step"]
    assert len(frames) == 2
    assert frames[0]["name"] == "compile+step"
    assert frames[1]["name"] == "steps 1..4"
    assert frames[1]["args"]["n_steps"] == 4
    assert frames[1]["dur"] == pytest.approx(0.01 * 1e6, rel=1e-2)
    # both frames on the same per-run synthetic track
    assert frames[0]["tid"] == frames[1]["tid"] >= 1000


def test_collective_span_on_timeline():
    import paddle_tpu as P
    from paddle_tpu.distributed import collective, fleet, topology

    topology.reset_topology()
    fleet.init(is_collective=True)
    trace.enable()
    t = P.to_tensor(np.ones((4,), np.float32))
    collective.all_reduce(t)
    spans = [e for e in trace.events() if e["ph"] == "X"]
    assert any(e["name"] == "all_reduce" and e["cat"] == "collective"
               for e in spans)


# ============================ xla_cost ============================

def test_xla_cost_capture_on_jitted_fn():
    """instrument(): first call per signature compiles inside an
    xla.compile span carrying cost_analysis flops/bytes, gauges land on
    the registry, and replays don't recompile."""
    trace.enable()
    metrics.enable()
    inst = xla_cost.instrument(jax.jit(lambda x: x @ x), label="mm")
    x = jnp.ones((32, 32), jnp.float32)
    np.testing.assert_allclose(np.asarray(inst(x)),
                               np.asarray(x @ x), rtol=1e-6)
    inst(x)  # replay: no second compile span
    spans = [e for e in trace.events()
             if e["ph"] == "X" and e["name"] == "xla.compile:mm"]
    assert len(spans) == 1
    assert spans[0]["cat"] == "compile"
    assert spans[0]["args"]["flops"] > 0
    assert "bytes_accessed" in spans[0]["args"]
    snap = metrics.snapshot()
    assert snap["gauges"]["xla.cost.flops{label=mm}"] > 0
    assert xla_cost.last_costs("mm")["flops"] == spans[0]["args"]["flops"]
    # flight carries the compile event too (crash-dump evidence)
    assert any(e["kind"] == "xla.compile" for e in flight.events())
    # a new signature is a new compile span
    inst(jnp.ones((16, 16), jnp.float32))
    spans = [e for e in trace.events()
             if e["ph"] == "X" and e["name"] == "xla.compile:mm"]
    assert len(spans) == 2


def test_xla_cost_tracer_guard_and_disabled_passthrough():
    inst = xla_cost.instrument(jax.jit(lambda x: (x * x).sum()), "sq")
    x = jnp.ones((8,), jnp.float32)
    # telemetry off: plain jit passthrough, nothing captured
    assert float(inst(x)) == 8.0
    assert xla_cost.last_costs("sq") is None
    # telemetry on under an outer trace: Compiled refuses tracers, the
    # guard must route through the composable jit path
    trace.enable()
    g = jax.grad(lambda x: inst(x))(x)
    np.testing.assert_allclose(np.asarray(g), 2 * np.ones((8,)), rtol=1e-6)
    assert float(inst(x)) == 8.0  # concrete call still captures
    assert xla_cost.last_costs("sq")["flops"] >= 0


def test_jit_to_static_compile_span():
    """The StaticFunction build path carries the instrument: telemetry-on
    first call produces an annotated compile span."""
    import paddle_tpu as P

    trace.enable()
    metrics.enable()

    @P.jit.to_static
    def f(x):
        return x * 2.0

    a = P.to_tensor(np.ones((4,), np.float32))
    out = f(a)
    np.testing.assert_allclose(out.numpy(), 2 * np.ones((4,)), rtol=1e-6)
    spans = [e for e in trace.events()
             if e["ph"] == "X" and e["name"].startswith("xla.compile:jit::")]
    assert spans and "flops" in spans[0]["args"]


# ====================== profiler chrome export ======================

def test_profiler_chrome_export_pid_tid_metadata(tmp_path):
    """Satellite: exported host traces carry process_name/thread_name
    metadata and small stable per-thread tids so nested scopes render
    in Perfetto instead of collapsing onto one row."""
    import paddle_tpu.profiler as profiler

    prof = profiler.Profiler(timer_only=True)
    prof.start()

    def worker():
        with profiler.RecordEvent("w.outer"):
            with profiler.RecordEvent("w.inner"):
                pass

    with profiler.RecordEvent("main.scope"):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    path = str(tmp_path / "host.trace.json")
    prof._export_chrome(path)
    prof.stop()
    with open(path) as f:
        doc = json.load(f)
    evts = doc["traceEvents"]
    meta = [e for e in evts if e["ph"] == "M"]
    xs = [e for e in evts if e["ph"] == "X"]
    assert any(m["name"] == "process_name" for m in meta)
    tids = {e["tid"] for e in xs}
    assert len(tids) == 2  # main thread + worker
    assert all(isinstance(t, int) and 0 < t < 100 for t in tids)
    named = {m["tid"] for m in meta if m["name"] == "thread_name"}
    assert tids <= named
    pid = os.getpid()
    assert all(e["pid"] == pid for e in xs)
    by_name = {e["name"]: e for e in xs}
    assert by_name["w.inner"]["tid"] == by_name["w.outer"]["tid"]
    assert by_name["w.outer"]["tid"] != by_name["main.scope"]["tid"]


# ============================ perf gate ============================

def _write_jsonl(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


def test_perf_gate_pass_regress_update(tmp_path):
    pg = _load_tool("perf_gate")
    baseline = str(tmp_path / "base.jsonl")
    _write_jsonl(baseline, [
        {"metric": "m.tokens", "value": 100.0, "unit": "tok/s",
         "captured_at": 100.0},
        {"metric": "m.tokens", "value": 90.0, "unit": "tok/s",
         "captured_at": 50.0},  # stale row must not win
        {"metric": "m.lat_ms", "value": 10.0, "lower_better": True,
         "captured_at": 100.0},
        {"metric": "m.degraded", "value": 5.0, "degraded": True,
         "captured_at": 100.0},  # degraded baseline rows are ignored
    ])
    results = str(tmp_path / "res.json")

    # within tolerance (higher-better -5% at 10%): pass
    _write_jsonl(results, [{"metric": "m.tokens", "value": 95.0}])
    assert pg.main([results, "--baseline", baseline]) == 0

    # beyond tolerance: regression exit code
    _write_jsonl(results, [{"metric": "m.tokens", "value": 80.0}])
    assert pg.main([results, "--baseline", baseline]) == 2

    # per-metric tolerance override rescues it
    assert pg.main([results, "--baseline", baseline,
                    "--metric-tolerance", "m.tokens=0.25"]) == 0

    # lower-better: value above floor fails
    _write_jsonl(results, [{"metric": "m.lat_ms", "value": 12.0}])
    assert pg.main([results, "--baseline", baseline]) == 2
    _write_jsonl(results, [{"metric": "m.lat_ms", "value": 10.5}])
    assert pg.main([results, "--baseline", baseline]) == 0

    # degraded current rows are skipped, new metrics pass
    _write_jsonl(results, [
        {"metric": "m.tokens", "value": 1.0, "degraded": True},
        {"metric": "m.new", "value": 7.0}])
    assert pg.main([results, "--baseline", baseline]) == 0

    # --update rolls the baseline: the new floor now gates
    _write_jsonl(results, [{"metric": "m.tokens", "value": 200.0}])
    assert pg.main([results, "--baseline", baseline, "--update"]) == 0
    _write_jsonl(results, [{"metric": "m.tokens", "value": 150.0}])
    assert pg.main([results, "--baseline", baseline]) == 2


def test_perf_gate_telemetry_derived_metrics(tmp_path):
    """A headline row with an embedded telemetry block gates the derived
    mfu (higher-better) and steady-wall (lower-better) series."""
    pg = _load_tool("perf_gate")
    head = {"metric": "m", "value": 100.0,
            "telemetry": {"metrics": {}, "step_stats": {
                "mfu": 0.40, "wall_ms": {"mean": 210.0, "count": 5}}}}
    results = str(tmp_path / "res.json")
    _write_jsonl(results, [head])
    rows = pg.load_results(results)
    by_m = {r["metric"]: r for r in rows}
    assert by_m["m.mfu"]["value"] == pytest.approx(0.40)
    assert by_m["m.steady_wall_ms"]["lower_better"] is True
    baseline = str(tmp_path / "base.jsonl")
    _write_jsonl(baseline, [{"metric": "m", "value": 100.0}])
    assert pg.main([results, "--baseline", baseline, "--update"]) == 0
    # mfu collapse now fails the gate even with the headline flat
    head2 = {"metric": "m", "value": 100.0,
             "telemetry": {"metrics": {}, "step_stats": {
                 "mfu": 0.20, "wall_ms": {"mean": 210.0, "count": 5}}}}
    _write_jsonl(results, [head2])
    assert pg.main([results, "--baseline", baseline]) == 2


def test_perf_gate_check_only_smoke():
    """Satellite CI hook: the repo's own baseline validates (fast,
    non-slow — this is the smoke the suite always runs)."""
    pg = _load_tool("perf_gate")
    assert pg.main(["--check-only"]) == 0


def test_perf_gate_check_only_catches_corruption(tmp_path):
    pg = _load_tool("perf_gate")
    bad = str(tmp_path / "bad.jsonl")
    with open(bad, "w") as f:
        f.write('{"metric": "ok", "value": 1.0}\nnot json\n'
                '{"metric": "noval"}\n')
    assert pg.main(["--check-only", "--baseline", bad]) == 1
    missing = str(tmp_path / "missing.jsonl")
    assert pg.main(["--check-only", "--baseline", missing]) == 1


def test_perf_gate_merge_trace(tmp_path):
    """Merge mode folds tracer export + step_stats JSONL + flight dump
    into one Perfetto file that json.loads cleanly."""
    pg = _load_tool("perf_gate")
    # span file from a real tracer
    trace.enable()
    with trace.span("compile", flops=123.0):
        pass
    span_file = trace.export(str(tmp_path / "spans.json"))
    # step stats stream
    steps = str(tmp_path / "steps.jsonl")
    timer = step_stats.StepTimer(run_id="r1", sink=steps,
                                 read_device_memory=False)
    timer.record(0.2, compile_step=True)
    timer.record(0.01, n_steps=3)
    # flight dump
    flight.get_recorder().enabled = True
    flight.record("jit.retrace", fn="f")
    fdump = flight.dump(str(tmp_path / "flight.jsonl"))
    out = str(tmp_path / "merged.json")
    rc = pg.main(["--merge-trace", out, "--spans", span_file,
                  "--step-stats", steps, "--flight", fdump])
    assert rc == 0
    with open(out) as f:
        doc = json.load(f)
    evts = doc["traceEvents"]
    names = [e["name"] for e in evts]
    assert "compile" in names            # span survived
    assert "compile+step" in names       # step frame reconstructed
    assert "jit.retrace" in names        # flight instant folded
    # the three families live on distinct processes
    pids = {e["pid"] for e in evts if e["ph"] != "M"}
    assert len(pids) >= 3
    # step frames accumulate: steady frame starts after the compile wall
    step_evts = [e for e in evts if e.get("cat") == "step"]
    assert step_evts[1]["ts"] == pytest.approx(
        step_evts[0]["ts"] + step_evts[0]["dur"], rel=1e-6)


# ======================= analyze_chip_log hook =======================

def test_analyze_chip_log_validates_trace_stream(tmp_path):
    """Satellite: the chip-log analyzer digests and validates trace
    JSONL streams interleaved with step_stats."""
    acl = _load_tool("analyze_chip_log")
    log = tmp_path / "log.jsonl"
    rows = [
        {"phase": "step_stats", "t": "t1", "run_id": "r1", "step": 0,
         "n_steps": 1, "wall_ms": 100.0, "compile": True},
        {"phase": "trace_event", "t": "t2", "name": "fwd", "ph": "X",
         "ts": 0.0, "dur": 5.0, "pid": 1, "tid": 1},
        {"phase": "trace_event", "t": "t3", "name": "gate", "ph": "i",
         "ts": 2.0, "pid": 1, "tid": 1},
    ]
    log.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    text = acl.digest(acl.load(str(log)))
    assert "## trace_events" in text and "## step_stats" in text
    assert "schema errors" not in text
    # a corrupt trace entry fails the digest AND the CLI exit code
    rows.append({"phase": "trace_event", "t": "t4", "name": "bad",
                 "ph": "X", "ts": 1.0, "pid": 1, "tid": 1, "dur": -3.0})
    log.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    text = acl.digest(acl.load(str(log)))
    assert "schema errors" in text
    assert acl.main(["analyze_chip_log.py", str(log)]) == 1


# ========================== attach wiring ==========================

def test_attach_enables_tracer_detach_disables():
    assert not trace.enabled()
    obs.attach(crash_hook=False)
    assert trace.enabled() and metrics.enabled()
    with trace.span("alive"):
        pass
    assert any(e["name"] == "alive" for e in trace.events())
    obs.detach()
    assert not trace.enabled() and not metrics.enabled()


def test_export_compat_available_or_clear_error():
    """The lazy jax.export shim either resolves a usable module or
    raises the actionable ExportUnavailableError — never an import-time
    death (the satellite's collection-safety contract)."""
    from paddle_tpu.core import export_compat as ec

    if ec.jax_export_available():
        je = ec.get_jax_export()
        assert hasattr(je, "export")
    else:
        with pytest.raises(ec.ExportUnavailableError,
                           match="jax.export"):
            ec.get_jax_export()
