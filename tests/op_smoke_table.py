"""Breadth smoke sweep table — one executable check per op.

Every entry is a zero-argument callable that runs the op on valid inputs
and asserts the result: a numpy-reference value check where one is cheap,
otherwise shape/dtype/property checks. tests/test_op_smoke.py parametrizes
over the manifest's kind="smoke" conformance entries and executes these;
tools/gen_op_manifest.py treats membership here as the op's conformance
evidence — so "present ⇒ tested" is a machine property, not a regex guess.

Reference role: breadth tier of the `test/legacy_test/` OpTest sweep
(SURVEY.md §4.1) for ops outside the elementwise conformance tables.
"""
import numpy as np

import paddle_tpu as P

rs = np.random.RandomState(23)

SMOKE_OPS = {}


def _op(name):
    def deco(f):
        SMOKE_OPS[name] = f
        return f
    return deco


def T(a):
    return P.to_tensor(np.asarray(a))


def ck(out, ref, **kw):
    kw.setdefault("rtol", 1e-5)
    kw.setdefault("atol", 1e-5)
    np.testing.assert_allclose(np.asarray(out.numpy(), np.float64),
                               np.asarray(ref, np.float64), **kw)


def cks(out, shape):
    assert list(out.shape) == list(shape), (out.shape, shape)


F32 = np.float32

# ---------------------------------------------------------------- linalg
X34 = rs.rand(3, 4).astype(F32)
X44 = rs.rand(4, 4).astype(F32) + np.eye(4, dtype=F32) * 4
SYM = (X44 + X44.T).astype(F32)


@_op("mm")
def _mm():
    b = rs.rand(4, 2).astype(F32)
    ck(P.mm(T(X34), T(b)), X34 @ b)


@_op("mv")
def _mv():
    v = rs.rand(4).astype(F32)
    ck(P.mv(T(X34), T(v)), X34 @ v)


@_op("dot")
def _dot():
    a = rs.rand(5).astype(F32); b = rs.rand(5).astype(F32)
    ck(P.dot(T(a), T(b)), np.dot(a, b))


@_op("inner")
def _inner():
    a = rs.rand(2, 3).astype(F32); b = rs.rand(4, 3).astype(F32)
    ck(P.inner(T(a), T(b)), np.inner(a, b))


@_op("kron")
def _kron():
    a = rs.rand(2, 2).astype(F32); b = rs.rand(3, 1).astype(F32)
    ck(P.kron(T(a), T(b)), np.kron(a, b))


@_op("matrix_power")
def _matrix_power():
    ck(P.matrix_power(T(X44), 3), np.linalg.matrix_power(X44, 3),
       rtol=1e-3, atol=1e-3)


@_op("multi_dot")
def _multi_dot():
    a = rs.rand(2, 3).astype(F32); b = rs.rand(3, 4).astype(F32)
    c = rs.rand(4, 2).astype(F32)
    ck(P.multi_dot([T(a), T(b), T(c)]), a @ b @ c)


@_op("tensordot")
def _tensordot():
    a = rs.rand(2, 3, 4).astype(F32); b = rs.rand(4, 3, 5).astype(F32)
    ck(P.tensordot(T(a), T(b), axes=[[1, 2], [1, 0]]),
       np.tensordot(a, b, axes=[[1, 2], [1, 0]]))


@_op("det")
def _det():
    ck(P.det(T(X44)), np.linalg.det(X44), rtol=1e-3)


@_op("slogdet")
def _slogdet():
    sign, logd = np.linalg.slogdet(X44)
    out = P.slogdet(T(X44))
    ck(out[0], sign); ck(out[1], logd, rtol=1e-3)


@_op("solve")
def _solve():
    b = rs.rand(4, 2).astype(F32)
    ck(P.solve(T(X44), T(b)), np.linalg.solve(X44, b), rtol=1e-3,
       atol=1e-3)


@_op("cholesky_solve")
def _cholesky_solve():
    L = np.linalg.cholesky(SYM).astype(F32)
    b = rs.rand(4, 1).astype(F32)
    ck(P.cholesky_solve(T(b), T(L), upper=False),
       np.linalg.solve(SYM, b), rtol=1e-2, atol=1e-2)


@_op("triangular_solve")
def _triangular_solve():
    U = np.triu(X44)
    b = rs.rand(4, 2).astype(F32)
    ck(P.triangular_solve(T(U), T(b), upper=True),
       np.linalg.solve(U, b), rtol=1e-2, atol=1e-2)


@_op("eig")
def _eig():
    vals, vecs = P.eig(T(X44))
    v = np.asarray(vals.numpy()); V = np.asarray(vecs.numpy())
    np.testing.assert_allclose(X44.astype(np.complex64) @ V, V * v[None, :],
                               rtol=1e-2, atol=1e-2)


@_op("eigh")
def _eigh():
    w, v = np.linalg.eigh(SYM)
    wo, vo = P.eigh(T(SYM))
    ck(wo, w, rtol=1e-3, atol=1e-3)
    cks(vo, v.shape)


@_op("eigvals")
def _eigvals():
    out = np.sort_complex(np.asarray(P.eigvals(T(SYM)).numpy()))
    ref = np.sort_complex(np.linalg.eigvals(SYM))
    np.testing.assert_allclose(out, ref, rtol=1e-2, atol=1e-2)


@_op("eigvalsh")
def _eigvalsh():
    ck(P.eigvalsh(T(SYM)), np.linalg.eigvalsh(SYM), rtol=1e-3, atol=1e-3)


@_op("pinv")
def _pinv():
    ck(P.pinv(T(X34)), np.linalg.pinv(X34), rtol=1e-2, atol=1e-2)


@_op("matrix_rank")
def _matrix_rank():
    ck(P.matrix_rank(T(X44)), np.linalg.matrix_rank(X44))


@_op("lstsq")
def _lstsq():
    a = rs.rand(5, 3).astype(F32); b = rs.rand(5, 2).astype(F32)
    sol = P.lstsq(T(a), T(b))[0]
    ref = np.linalg.lstsq(a, b, rcond=None)[0]
    ck(sol, ref, rtol=1e-2, atol=1e-2)


@_op("lu")
def _lu():
    lu_t, piv = P.lu(T(X44))[:2]
    cks(lu_t, (4, 4)); assert piv.shape[-1] == 4


@_op("lu_unpack")
def _lu_unpack():
    lu_t, piv = P.lu(T(X44))[:2]
    pmat, L, U = P.lu_unpack(lu_t, piv)
    rec = np.asarray(pmat.numpy()) @ np.asarray(L.numpy()) \
        @ np.asarray(U.numpy())
    np.testing.assert_allclose(rec, X44, rtol=1e-3, atol=1e-3)


@_op("householder_product")
def _householder_product():
    v = rs.rand(4, 3).astype(F32); tau = rs.rand(3).astype(F32)
    cks(P.householder_product(T(v), T(tau)), (4, 3))


@_op("pca_lowrank")
def _pca_lowrank():
    x = rs.rand(6, 4).astype(F32)
    U, S, V = P.pca_lowrank(T(x), q=3)
    cks(U, (6, 3)); cks(S, (3,)); cks(V, (4, 3))


@_op("corrcoef")
def _corrcoef():
    x = rs.rand(3, 8).astype(F32)
    ck(P.corrcoef(T(x)), np.corrcoef(x), rtol=1e-3, atol=1e-3)


@_op("cdist")
def _cdist():
    a = rs.rand(3, 4).astype(F32); b = rs.rand(5, 4).astype(F32)
    ref = np.sqrt(((a[:, None, :] - b[None, :, :]) ** 2).sum(-1))
    ck(P.cdist(T(a), T(b)), ref, rtol=1e-3, atol=1e-3)


@_op("cross")
def _cross():
    a = rs.rand(3, 5).astype(F32); b = rs.rand(3, 5).astype(F32)
    ck(P.cross(T(a), T(b), axis=0), np.cross(a, b, axis=0))


@_op("vander")
def _vander():
    x = rs.rand(4).astype(F32)
    ck(P.vander(T(x), 3), np.vander(x, 3))


# ------------------------------------------------------------ reductions+
@_op("count_nonzero")
def _count_nonzero():
    x = (rs.rand(3, 4) > 0.5).astype(F32)
    ck(P.count_nonzero(T(x)), np.count_nonzero(x))
    ck(P.count_nonzero(T(x), axis=1), np.count_nonzero(x, axis=1))


@_op("mode")
def _mode():
    x = np.array([[1., 2., 2., 3.], [0., 0., 1., 5.]], F32)
    vals, idx = P.mode(T(x), axis=1)
    ck(vals, [2., 0.])


@_op("kthvalue")
def _kthvalue():
    x = rs.rand(3, 6).astype(F32)
    vals, idx = P.kthvalue(T(x), 2, axis=1)
    ck(vals, np.sort(x, axis=1)[:, 1])


@_op("quantile")
def _quantile():
    x = rs.rand(3, 8).astype(F32)
    ck(P.quantile(T(x), 0.5, axis=1), np.quantile(x, 0.5, axis=1),
       rtol=1e-3, atol=1e-3)


@_op("nanquantile")
def _nanquantile():
    x = rs.rand(3, 8).astype(F32); x[0, 0] = np.nan
    ck(P.nanquantile(T(x), 0.5, axis=1), np.nanquantile(x, 0.5, axis=1),
       rtol=1e-3, atol=1e-3)


@_op("nanmedian")
def _nanmedian():
    x = rs.rand(3, 7).astype(F32); x[1, 2] = np.nan
    ck(P.nanmedian(T(x), axis=1), np.nanmedian(x, axis=1), rtol=1e-3)


@_op("cummax")
def _cummax():
    x = rs.randn(3, 5).astype(F32)
    vals, idx = P.cummax(T(x), axis=1)
    ck(vals, np.maximum.accumulate(x, axis=1))


@_op("cummin")
def _cummin():
    x = rs.randn(3, 5).astype(F32)
    vals, idx = P.cummin(T(x), axis=1)
    ck(vals, np.minimum.accumulate(x, axis=1))


@_op("cumprod")
def _cumprod():
    x = (rs.rand(3, 4) + 0.5).astype(F32)
    ck(P.cumprod(T(x), dim=1), np.cumprod(x, axis=1))


@_op("logcumsumexp")
def _logcumsumexp():
    x = rs.randn(3, 5).astype(F32)
    ck(P.logcumsumexp(T(x), axis=1),
       np.log(np.cumsum(np.exp(x), axis=1)), rtol=1e-4, atol=1e-4)


@_op("trapezoid")
def _trapezoid():
    y = rs.rand(3, 6).astype(F32)
    ck(P.trapezoid(T(y), dx=0.5, axis=1),
       np.trapezoid(y, dx=0.5, axis=1))


@_op("cumulative_trapezoid")
def _cumulative_trapezoid():
    y = rs.rand(6).astype(F32)
    ref = np.array([np.trapezoid(y[:i + 1]) for i in range(1, 6)])
    ck(P.cumulative_trapezoid(T(y)), ref, rtol=1e-4, atol=1e-4)


@_op("diff")
def _diff():
    x = rs.rand(3, 6).astype(F32)
    ck(P.diff(T(x), axis=1), np.diff(x, axis=1))


# ------------------------------------------------------ shape manipulation
@_op("transpose")
def _transpose():
    x = rs.rand(2, 3, 4).astype(F32)
    ck(P.transpose(T(x), perm=[2, 0, 1]), np.transpose(x, (2, 0, 1)))


@_op("moveaxis")
def _moveaxis():
    x = rs.rand(2, 3, 4).astype(F32)
    ck(P.moveaxis(T(x), 0, 2), np.moveaxis(x, 0, 2))


@_op("flip")
def _flip():
    ck(P.flip(T(X34), axis=[1]), np.flip(X34, axis=1))


@_op("reverse")
def _reverse():
    ck(P.reverse(T(X34), axis=[0]), np.flip(X34, axis=0))


@_op("roll")
def _roll():
    ck(P.roll(T(X34), shifts=2, axis=1), np.roll(X34, 2, axis=1))


@_op("rot90")
def _rot90():
    ck(P.rot90(T(X34), k=1, axes=(0, 1)), np.rot90(X34, 1, (0, 1)))


@_op("tile")
def _tile():
    ck(P.tile(T(X34), [2, 1]), np.tile(X34, (2, 1)))


@_op("expand")
def _expand():
    x = rs.rand(1, 4).astype(F32)
    ck(P.expand(T(x), [3, 4]), np.broadcast_to(x, (3, 4)))


@_op("expand_as")
def _expand_as():
    x = rs.rand(1, 4).astype(F32)
    ck(P.expand_as(T(x), T(X34)), np.broadcast_to(x, (3, 4)))


@_op("broadcast_to")
def _broadcast_to():
    x = rs.rand(4).astype(F32)
    ck(P.broadcast_to(T(x), [3, 4]), np.broadcast_to(x, (3, 4)))


@_op("broadcast_tensors")
def _broadcast_tensors():
    a = rs.rand(1, 4).astype(F32); b = rs.rand(3, 1).astype(F32)
    oa, ob = P.broadcast_tensors([T(a), T(b)])
    ck(oa, np.broadcast_to(a, (3, 4)))
    ck(ob, np.broadcast_to(b, (3, 4)))


@_op("broadcast_shape")
def _broadcast_shape():
    assert list(P.broadcast_shape([1, 4], [3, 1])) == [3, 4]


@_op("repeat_interleave")
def _repeat_interleave():
    ck(P.repeat_interleave(T(X34), 2, axis=1), np.repeat(X34, 2, axis=1))


@_op("squeeze")
def _squeeze():
    x = rs.rand(3, 1, 4).astype(F32)
    ck(P.squeeze(T(x), axis=1), x[:, 0, :])


@_op("unsqueeze")
def _unsqueeze():
    ck(P.unsqueeze(T(X34), axis=1), X34[:, None, :])


@_op("flatten")
def _flatten():
    x = rs.rand(2, 3, 4).astype(F32)
    ck(P.flatten(T(x), 1, 2), x.reshape(2, 12))


@_op("unflatten")
def _unflatten():
    x = rs.rand(2, 12).astype(F32)
    ck(P.unflatten(T(x), 1, [3, 4]), x.reshape(2, 3, 4))


@_op("chunk")
def _chunk():
    outs = P.chunk(T(X34), 2, axis=1)
    ck(outs[0], X34[:, :2]); ck(outs[1], X34[:, 2:])


@_op("split")
def _split():
    outs = P.split(T(X34), [1, 3], axis=1)
    ck(outs[0], X34[:, :1]); ck(outs[1], X34[:, 1:])


@_op("split_with_num")
def _split_with_num():
    outs = P.split_with_num(T(X34), 2, axis=1)
    ck(outs[0], X34[:, :2])


@_op("tensor_split")
def _tensor_split():
    outs = P.tensor_split(T(X34), 3, axis=1)
    refs = np.array_split(X34, 3, axis=1)
    for o, r in zip(outs, refs):
        ck(o, r)


@_op("dsplit")
def _dsplit():
    x = rs.rand(2, 3, 4).astype(F32)
    outs = P.dsplit(T(x), 2)
    refs = np.dsplit(x, 2)
    for o, r in zip(outs, refs):
        ck(o, r)


@_op("unbind")
def _unbind():
    outs = P.unbind(T(X34), axis=0)
    assert len(outs) == 3
    ck(outs[1], X34[1])


@_op("atleast_1d")
def _atleast_1d():
    assert P.atleast_1d(T(np.float32(2.0))).shape == [1]


@_op("atleast_2d")
def _atleast_2d():
    assert P.atleast_2d(T(np.ones(3, F32))).shape == [1, 3]


@_op("atleast_3d")
def _atleast_3d():
    assert P.atleast_3d(T(np.ones((2, 3), F32))).shape == \
        list(np.atleast_3d(np.ones((2, 3))).shape)


@_op("crop")
def _crop():
    ck(P.crop(T(X34), shape=[2, 2], offsets=[1, 1]), X34[1:3, 1:3])


@_op("slice")
def _slice():
    ck(P.slice(T(X34), axes=[0, 1], starts=[1, 0], ends=[3, 2]),
       X34[1:3, 0:2])


@_op("strided_slice")
def _strided_slice():
    ck(P.strided_slice(T(X34), axes=[1], starts=[0], ends=[4],
                       strides=[2]), X34[:, ::2])


@_op("meshgrid")
def _meshgrid():
    a = np.arange(3).astype(F32); b = np.arange(2).astype(F32)
    xa, xb = P.meshgrid(T(a), T(b))
    ra, rb = np.meshgrid(a, b, indexing="ij")
    ck(xa, ra); ck(xb, rb)


@_op("tril")
def _tril():
    ck(P.tril(T(X44)), np.tril(X44))


@_op("triu")
def _triu():
    ck(P.triu(T(X44), 1), np.triu(X44, 1))


@_op("tril_")
def _tril_():
    t = T(X44)
    P.tril_(t)
    ck(t, np.tril(X44))


@_op("diagflat")
def _diagflat():
    x = rs.rand(3).astype(F32)
    ck(P.diagflat(T(x), 1), np.diagflat(x, 1))


# --------------------------------------------------------------- indexing
@_op("gather")
def _gather():
    idx = np.array([2, 0], np.int32)
    ck(P.gather(T(X34), T(idx), axis=0), X34[idx])


@_op("gather_nd")
def _gather_nd():
    idx = np.array([[0, 1], [2, 3]], np.int32)
    ck(P.gather_nd(T(X34), T(idx)), X34[idx[:, 0], idx[:, 1]])


@_op("index_select")
def _index_select():
    idx = np.array([3, 1], np.int32)
    ck(P.index_select(T(X34), T(idx), axis=1), X34[:, idx])


@_op("index_sample")
def _index_sample():
    idx = np.array([[0, 1], [2, 2], [3, 0]], np.int32)
    ck(P.index_sample(T(X34), T(idx)),
       np.take_along_axis(X34, idx, axis=1))


@_op("index_add")
def _index_add():
    idx = np.array([0, 2], np.int32)
    val = rs.rand(2, 4).astype(F32)
    ref = X34.copy(); np.add.at(ref, idx, val)
    ck(P.index_add(T(X34), T(idx), 0, T(val)), ref)


@_op("index_fill")
def _index_fill():
    idx = np.array([1], np.int32)
    ref = X34.copy(); ref[:, 1] = 9.0
    ck(P.index_fill(T(X34), T(idx), 1, 9.0), ref)


@_op("index_put")
def _index_put():
    ii = np.array([0, 2], np.int32); jj = np.array([1, 3], np.int32)
    v = np.array([7.0, 8.0], F32)
    ref = X34.copy(); ref[ii, jj] = v
    ck(P.index_put(T(X34), (T(ii), T(jj)), T(v)), ref)


@_op("take")
def _take():
    idx = np.array([0, 5, 11], np.int32)
    ck(P.take(T(X34), T(idx)), np.take(X34, idx))


@_op("put_along_axis")
def _put_along_axis():
    idx = np.array([[1], [0], [2]], np.int32)
    v = np.array([[5.], [6.], [7.]], F32)
    ref = X34.copy()
    np.put_along_axis(ref, idx, v, axis=1)
    ck(P.put_along_axis(T(X34), T(idx), T(v), 1), ref)


@_op("masked_select")
def _masked_select():
    m = X34 > 0.5
    ck(P.masked_select(T(X34), T(m)), X34[m])


@_op("masked_fill")
def _masked_fill():
    m = X34 > 0.5
    ref = np.where(m, np.float32(-1.0), X34)
    ck(P.masked_fill(T(X34), T(m), -1.0), ref)


@_op("masked_scatter")
def _masked_scatter():
    m = X34 > 0.5
    v = np.arange(12, dtype=F32)
    ref = X34.copy(); ref[m] = v[:m.sum()]
    ck(P.masked_scatter(T(X34), T(m), T(v)), ref)


@_op("scatter")
def _scatter():
    idx = np.array([1, 0], np.int32)
    upd = rs.rand(2, 4).astype(F32)
    ref = X34.copy(); ref[idx] = upd
    ck(P.scatter(T(X34), T(idx), T(upd), overwrite=True), ref)


@_op("scatter_nd")
def _scatter_nd():
    idx = np.array([[1], [3]], np.int32)
    upd = rs.rand(2, 4).astype(F32)
    ref = np.zeros((5, 4), F32); np.add.at(ref, idx[:, 0], upd)
    ck(P.scatter_nd(T(idx), T(upd), [5, 4]), ref)


@_op("scatter_nd_add")
def _scatter_nd_add():
    idx = np.array([[0], [2]], np.int32)
    upd = rs.rand(2, 4).astype(F32)
    ref = X34.copy()
    np.add.at(ref, idx[:, 0], upd)
    ck(P.scatter_nd_add(T(X34), T(idx), T(upd)), ref)


@_op("select_scatter")
def _select_scatter():
    v = rs.rand(4).astype(F32)
    ref = X34.copy(); ref[1] = v
    ck(P.select_scatter(T(X34), T(v), 0, 1), ref)


@_op("fill_diagonal")
def _fill_diagonal():
    ref = X44.copy(); np.fill_diagonal(ref, 0.5)
    ck(P.fill_diagonal(T(X44), 0.5), ref)


@_op("fill_diagonal_tensor")
def _fill_diagonal_tensor():
    v = rs.rand(4).astype(F32)
    ref = X44.copy(); ref[np.arange(4), np.arange(4)] = v
    ck(P.fill_diagonal_tensor(T(X44), T(v)), ref)


@_op("fill")
def _fill():
    ck(P.fill(T(X34), 2.5), np.full_like(X34, 2.5))


@_op("searchsorted")
def _searchsorted():
    seq = np.sort(rs.rand(8)).astype(F32)
    v = rs.rand(5).astype(F32)
    ck(P.searchsorted(T(seq), T(v)), np.searchsorted(seq, v))


@_op("bucketize")
def _bucketize():
    seq = np.sort(rs.rand(6)).astype(F32)
    v = rs.rand(4).astype(F32)
    ck(P.bucketize(T(v), T(seq)), np.searchsorted(seq, v))


# ------------------------------------------------------------- activations
def _np_sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


ACT_REFS = {
    "relu": lambda x: np.maximum(x, 0),
    "relu6": lambda x: np.clip(x, 0, 6),
    "leaky_relu": lambda x: np.where(x > 0, x, 0.01 * x),
    "elu": lambda x: np.where(x > 0, x, np.exp(x) - 1),
    "celu": lambda x: np.maximum(x, 0) + np.minimum(0, np.exp(x) - 1),
    "selu": lambda x: 1.0507009873554805 * np.where(
        x > 0, x, 1.6732632423543772 * (np.exp(x) - 1)),
    "silu": lambda x: x * _np_sigmoid(x),
    "swish": lambda x: x * _np_sigmoid(x),
    "mish": lambda x: x * np.tanh(np.log1p(np.exp(x))),
    "softplus": lambda x: np.log1p(np.exp(x)),
    "softsign": lambda x: x / (1 + np.abs(x)),
    "hardswish": lambda x: x * np.clip(x + 3, 0, 6) / 6,
    "hardsigmoid": lambda x: np.clip(x * 0.1666667 + 0.5, 0, 1),
    "hardtanh": lambda x: np.clip(x, -1, 1),
    "hardshrink": lambda x: np.where(np.abs(x) > 0.5, x, 0),
    "softshrink": lambda x: np.where(
        x > 0.5, x - 0.5, np.where(x < -0.5, x + 0.5, 0)),
    "thresholded_relu": lambda x: np.where(x > 1.0, x, 0.0),
    "gelu": lambda x: x * 0.5 * (
        1 + np.vectorize(__import__("math").erf)(x / np.sqrt(2))),
}


def _mk_act(name, ref):
    def f():
        x = rs.randn(3, 4).astype(F32)
        ck(getattr(P.nn.functional, name)(T(x)), ref(x),
           rtol=1e-4, atol=1e-4)
    return f


for _n, _r in ACT_REFS.items():
    SMOKE_OPS[_n] = _mk_act(_n, _r)


@_op("stanh")
def _stanh():
    x = rs.randn(3, 4).astype(F32)
    ck(P.stanh(T(x)), 1.7159 * np.tanh(0.67 * x), rtol=1e-4, atol=1e-4)


@_op("prelu")
def _prelu():
    x = rs.randn(2, 3, 4).astype(F32)
    w = np.array([0.1, 0.2, 0.3], F32)
    ref = np.where(x > 0, x, x * w[None, :, None])
    ck(P.nn.functional.prelu(T(x), T(w)), ref)


@_op("rrelu")
def _rrelu():
    x = rs.randn(3, 4).astype(F32)
    slope = (0.125 + 1 / 3.0) / 2
    ck(P.nn.functional.rrelu(T(x), training=False),
       np.where(x > 0, x, slope * x), rtol=1e-4, atol=1e-4)


@_op("maxout")
def _maxout():
    x = rs.rand(2, 6, 3).astype(F32)  # NCL with C=6, groups=2
    out = P.nn.functional.maxout(T(x), groups=2, axis=1)
    # reference layout: out[:, j] = max_k x[:, j + (C//groups)*k]
    ref = x.reshape(2, 2, 3, 3).max(axis=1)
    ck(out, ref)


@_op("gumbel_softmax")
def _gumbel_softmax():
    x = rs.randn(4, 5).astype(F32)
    out = P.nn.functional.gumbel_softmax(T(x), hard=False)
    np.testing.assert_allclose(np.asarray(out.numpy()).sum(-1),
                               np.ones(4), rtol=1e-4)
    hard = P.nn.functional.gumbel_softmax(T(x), hard=True)
    h = np.asarray(hard.numpy())
    assert ((h == 0) | (h == 1)).all() and (h.sum(-1) == 1).all()


# ------------------------------------------------------------------- norms
@_op("layer_norm")
def _layer_norm():
    x = rs.randn(3, 8).astype(F32)
    w = rs.rand(8).astype(F32); b = rs.rand(8).astype(F32)
    mu = x.mean(-1, keepdims=True); var = x.var(-1, keepdims=True)
    ref = (x - mu) / np.sqrt(var + 1e-5) * w + b
    ck(P.nn.functional.layer_norm(T(x), 8, T(w), T(b)), ref,
       rtol=1e-4, atol=1e-4)


@_op("rms_norm")
def _rms_norm():
    x = rs.randn(3, 8).astype(F32)
    w = rs.rand(8).astype(F32)
    ref = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6) * w
    ck(P.nn.functional.rms_norm(T(x), T(w)), ref, rtol=1e-4, atol=1e-4)


@_op("group_norm")
def _group_norm():
    x = rs.randn(2, 4, 3, 3).astype(F32)
    g = x.reshape(2, 2, 2 * 9)
    mu = g.mean(-1)[:, :, None]; var = g.var(-1)[:, :, None]
    ref = ((g - mu) / np.sqrt(var + 1e-5)).reshape(2, 4, 3, 3)
    ck(P.nn.functional.group_norm(T(x), 2), ref, rtol=1e-4, atol=1e-4)


@_op("instance_norm")
def _instance_norm():
    x = rs.randn(2, 3, 4, 4).astype(F32)
    f = x.reshape(2, 3, 16)
    mu = f.mean(-1)[..., None]; var = f.var(-1)[..., None]
    ref = ((f - mu) / np.sqrt(var + 1e-5)).reshape(x.shape)
    ck(P.nn.functional.instance_norm(T(x)), ref, rtol=1e-4, atol=1e-4)


@_op("batch_norm")
def _batch_norm():
    x = rs.randn(4, 3, 2, 2).astype(F32)
    rm = np.zeros(3, F32); rv = np.ones(3, F32)
    out = P.nn.functional.batch_norm(T(x), T(rm), T(rv), training=False)
    ck(out, x, rtol=1e-4, atol=1e-4)  # identity stats => ~identity


@_op("bilinear")
def _bilinear():
    x1 = rs.rand(5, 3).astype(F32); x2 = rs.rand(5, 4).astype(F32)
    w = rs.rand(2, 3, 4).astype(F32)
    ref = np.einsum("bi,oij,bj->bo", x1, w, x2)
    ck(P.nn.functional.bilinear(T(x1), T(x2), T(w)), ref,
       rtol=1e-4, atol=1e-4)


# -------------------------------------------------------------- nn spatial
@_op("conv2d")
def _conv2d():
    x = np.ones((1, 1, 4, 4), F32)
    w = np.ones((1, 1, 3, 3), F32)
    out = P.nn.functional.conv2d(T(x), T(w))
    ck(out, np.full((1, 1, 2, 2), 9.0))


@_op("conv2d_transpose")
def _conv2d_transpose():
    x = np.ones((1, 1, 2, 2), F32)
    w = np.ones((1, 1, 3, 3), F32)
    out = P.nn.functional.conv2d_transpose(T(x), T(w))
    cks(out, (1, 1, 4, 4))
    assert float(np.asarray(out.numpy()).sum()) == 4 * 9.0


@_op("conv3d")
def _conv3d():
    x = np.ones((1, 1, 3, 3, 3), F32)
    w = np.ones((1, 1, 2, 2, 2), F32)
    ck(P.nn.functional.conv3d(T(x), T(w)), np.full((1, 1, 2, 2, 2), 8.0))


@_op("conv3d_transpose")
def _conv3d_transpose():
    x = np.ones((1, 1, 2, 2, 2), F32)
    w = np.ones((1, 1, 2, 2, 2), F32)
    out = P.nn.functional.conv3d_transpose(T(x), T(w))
    cks(out, (1, 1, 3, 3, 3))


@_op("unfold")
def _unfold():
    x = np.arange(16, dtype=F32).reshape(1, 1, 4, 4)
    out = P.nn.functional.unfold(T(x), 2)
    cks(out, (1, 4, 9))


@_op("fold")
def _fold():
    x = rs.rand(1, 4, 9).astype(F32)
    out = P.nn.functional.fold(T(x), (4, 4), 2)
    cks(out, (1, 1, 4, 4))


@_op("affine_grid")
def _affine_grid():
    theta = np.tile(np.array([[[1, 0, 0], [0, 1, 0]]], F32), (1, 1, 1))
    grid = P.nn.functional.affine_grid(T(theta), [1, 1, 3, 3])
    cks(grid, (1, 3, 3, 2))


@_op("grid_sample")
def _grid_sample():
    x = rs.rand(1, 1, 3, 3).astype(F32)
    theta = np.array([[[1, 0, 0], [0, 1, 0]]], F32)
    grid = P.nn.functional.affine_grid(T(theta), [1, 1, 3, 3])
    out = P.nn.functional.grid_sample(T(x), grid)
    ck(out, x, rtol=1e-3, atol=1e-3)  # identity warp


@_op("pixel_shuffle")
def _pixel_shuffle():
    x = rs.rand(1, 4, 2, 2).astype(F32)
    out = P.nn.functional.pixel_shuffle(T(x), 2)
    ref = x.reshape(1, 2, 2, 2, 2).transpose(0, 3, 1, 4, 2)
    ref = ref.reshape(1, 1, 4, 4)
    cks(out, (1, 1, 4, 4))


@_op("pixel_unshuffle")
def _pixel_unshuffle():
    x = rs.rand(1, 1, 4, 4).astype(F32)
    out = P.nn.functional.pixel_unshuffle(T(x), 2)
    cks(out, (1, 4, 2, 2))


@_op("channel_shuffle")
def _channel_shuffle():
    x = np.arange(8, dtype=F32).reshape(1, 8, 1, 1)
    out = P.nn.functional.channel_shuffle(T(x), 2)
    ref = x.reshape(1, 2, 4, 1, 1).transpose(0, 2, 1, 3, 4).reshape(x.shape)
    ck(out, ref)


@_op("temporal_shift")
def _temporal_shift():
    x = rs.rand(4, 8, 2, 2).astype(F32)  # N*T=4 (T=2), C=8
    out = P.temporal_shift(T(x), seg_num=2)
    cks(out, x.shape)


@_op("pad")
def _pad():
    x = rs.rand(1, 1, 3, 3).astype(F32)
    out = P.pad(T(x), [1, 1, 2, 2], value=0.0)
    ref = np.pad(x, ((0, 0), (0, 0), (2, 2), (1, 1)))
    ck(out, ref)


# ------------------------------------------------------------------ losses
@_op("nll_loss")
def _nll_loss():
    logp = np.log(rs.dirichlet(np.ones(4), 3).astype(F32))
    lbl = np.array([0, 2, 3])
    ref = -logp[np.arange(3), lbl].mean()
    ck(P.nn.functional.nll_loss(T(logp.astype(F32)), T(lbl.astype(np.int32))),
       ref, rtol=1e-4, atol=1e-4)


@_op("log_loss")
def _log_loss():
    p = rs.rand(4, 1).astype(F32) * 0.8 + 0.1
    y = (rs.rand(4, 1) > 0.5).astype(F32)
    eps = 1e-4
    ref = -y * np.log(p + eps) - (1 - y) * np.log(1 - p + eps)
    ck(P.nn.functional.log_loss(T(p), T(y)), ref, rtol=1e-4, atol=1e-4)


@_op("identity_loss")
def _identity_loss():
    x = rs.rand(3).astype(F32)
    ck(P.identity_loss(T(x), reduction="none"), x)


@_op("label_smooth")
def _label_smooth():
    y = np.eye(3, dtype=F32)
    ref = 0.9 * y + 0.1 / 3
    ck(P.nn.functional.label_smooth(T(y), epsilon=0.1), ref,
       rtol=1e-4, atol=1e-4)


# ------------------------------------------------------- predicates & meta
@_op("is_tensor")
def _is_tensor():
    assert P.is_tensor(T(X34)) and not P.is_tensor(X34)


@_op("is_complex")
def _is_complex():
    assert P.is_complex(T(np.complex64(1j)))
    assert not P.is_complex(T(X34))


@_op("is_floating_point")
def _is_floating_point():
    assert P.is_floating_point(T(X34))
    assert not P.is_floating_point(T(np.int32(1)))


@_op("is_integer")
def _is_integer():
    assert P.is_integer(T(np.int32(1)))
    assert not P.is_integer(T(X34))


@_op("is_empty")
def _is_empty():
    assert bool(P.is_empty(T(np.zeros((0, 3), F32))).numpy())
    assert not bool(P.is_empty(T(X34)).numpy())


@_op("isclose")
def _isclose():
    a = np.array([1.0, 2.0], F32); b = np.array([1.0, 2.1], F32)
    np.testing.assert_array_equal(
        np.asarray(P.isclose(T(a), T(b)).numpy(), bool),
        np.isclose(a, b))


@_op("isinf")
def _isinf():
    x = np.array([1.0, np.inf, -np.inf], F32)
    np.testing.assert_array_equal(
        np.asarray(P.isinf(T(x)).numpy(), bool), np.isinf(x))


@_op("isnan")
def _isnan():
    x = np.array([1.0, np.nan], F32)
    np.testing.assert_array_equal(
        np.asarray(P.isnan(T(x)).numpy(), bool), np.isnan(x))


@_op("equal_all")
def _equal_all():
    assert bool(P.equal_all(T(X34), T(X34)).numpy())
    assert not bool(P.equal_all(T(X34), T(X34 + 1)).numpy())


@_op("numel")
def _numel():
    assert int(P.numel(T(X34)).numpy()) == 12


@_op("logical_not")
def _logical_not():
    x = np.array([0.0, 1.0, 2.0], F32)
    np.testing.assert_array_equal(
        np.asarray(P.logical_not(T(x)).numpy(), bool), np.logical_not(x))


@_op("logical_not_")
def _logical_not_():
    x = np.array([True, False])
    t = T(x)
    P.logical_not_(t)
    np.testing.assert_array_equal(np.asarray(t.numpy(), bool), ~x)


# ---------------------------------------------------------------- creation
@_op("assign")
def _assign():
    ck(P.assign(T(X34)), X34)


@_op("cast")
def _cast():
    out = P.cast(T(X34), "int32")
    assert "int32" in str(out.dtype)
    ck(out, X34.astype(np.int32))


@_op("cast_")
def _cast_():
    t = T(X34)
    out = P.cast_(t, "int32")
    assert "int32" in str(out.dtype)


@_op("create_tensor")
def _create_tensor():
    t = P.create_tensor("float32")
    assert "float32" in str(t.dtype)


@_op("empty")
def _empty():
    assert P.empty([2, 3]).shape == [2, 3]


@_op("empty_like")
def _empty_like():
    assert P.empty_like(T(X34)).shape == [3, 4]


@_op("full_like")
def _full_like():
    ck(P.full_like(T(X34), 7.0), np.full_like(X34, 7.0))


@_op("ones_like")
def _ones_like():
    ck(P.ones_like(T(X34)), np.ones_like(X34))


@_op("linspace")
def _linspace():
    ck(P.linspace(0, 1, 5), np.linspace(0, 1, 5))


@_op("logspace")
def _logspace():
    ck(P.logspace(0, 2, 3), np.logspace(0, 2, 3), rtol=1e-4)


@_op("gaussian")
def _gaussian():
    out = P.gaussian([1000], mean=2.0, std=0.5)
    v = np.asarray(out.numpy())
    assert abs(v.mean() - 2.0) < 0.1 and abs(v.std() - 0.5) < 0.1


@_op("randperm")
def _randperm():
    v = np.sort(np.asarray(P.randperm(16).numpy()))
    np.testing.assert_array_equal(v, np.arange(16))


@_op("one_hot")
def _one_hot():
    idx = np.array([0, 2, 1], np.int32)
    ck(P.one_hot(T(idx), 3), np.eye(3, dtype=F32)[idx])


# ---------------------------------------------------------------- complex
CPLX = (rs.rand(3, 2).astype(F32) + 1j * rs.rand(3, 2).astype(F32)).astype(
    np.complex64)


@_op("complex")
def _complex():
    a = rs.rand(3).astype(F32); b = rs.rand(3).astype(F32)
    out = np.asarray(P.complex(T(a), T(b)).numpy())
    np.testing.assert_allclose(out, a + 1j * b, rtol=1e-5)


@_op("conj")
def _conj():
    np.testing.assert_allclose(np.asarray(P.conj(T(CPLX)).numpy()),
                               np.conj(CPLX), rtol=1e-5)


@_op("angle")
def _angle():
    ck(P.angle(T(CPLX)), np.angle(CPLX), rtol=1e-4, atol=1e-4)


@_op("imag")
def _imag():
    ck(P.imag(T(CPLX)), CPLX.imag)


@_op("as_complex")
def _as_complex():
    x = rs.rand(3, 2).astype(F32)
    out = np.asarray(P.as_complex(T(x)).numpy())
    np.testing.assert_allclose(out, x[:, 0] + 1j * x[:, 1], rtol=1e-5)


@_op("as_real")
def _as_real():
    out = np.asarray(P.as_real(T(CPLX)).numpy())
    np.testing.assert_allclose(out[..., 0], CPLX.real, rtol=1e-5)
    np.testing.assert_allclose(out[..., 1], CPLX.imag, rtol=1e-5)


@_op("polar")
def _polar():
    r = rs.rand(4).astype(F32); th = rs.rand(4).astype(F32)
    out = np.asarray(P.polar(T(r), T(th)).numpy())
    np.testing.assert_allclose(out, r * np.exp(1j * th), rtol=1e-4)


# ------------------------------------------------------------ scalar math
@_op("deg2rad")
def _deg2rad():
    ck(P.deg2rad(T(X34)), np.deg2rad(X34))


@_op("rad2deg")
def _rad2deg():
    ck(P.rad2deg(T(X34)), np.rad2deg(X34), rtol=1e-4)


@_op("sgn")
def _sgn():
    x = rs.randn(3, 4).astype(F32)
    ck(P.sgn(T(x)), np.sign(x))


@_op("heaviside")
def _heaviside():
    x = rs.randn(4).astype(F32); y = rs.rand(4).astype(F32)
    ck(P.heaviside(T(x), T(y)), np.heaviside(x, y))


@_op("nan_to_num")
def _nan_to_num():
    x = np.array([1.0, np.nan, np.inf, -np.inf], F32)
    ck(P.nan_to_num(T(x)), np.nan_to_num(x))


@_op("mod")
def _mod():
    x = rs.randn(3, 4).astype(F32); y = rs.rand(3, 4).astype(F32) + 0.5
    ck(P.mod(T(x), T(y)), np.mod(x, y), rtol=1e-4, atol=1e-4)


@_op("floor_mod")
def _floor_mod():
    x = rs.randn(3, 4).astype(F32); y = rs.rand(3, 4).astype(F32) + 0.5
    ck(P.floor_mod(T(x), T(y)), np.mod(x, y), rtol=1e-4, atol=1e-4)


@_op("increment")
def _increment():
    ck(P.increment(T(X34), 2.0), X34 + 2.0)


@_op("frexp")
def _frexp():
    x = (rs.rand(5).astype(F32) + 0.1) * 8
    m, e = P.frexp(T(x))
    rec = np.asarray(m.numpy()) * np.exp2(np.asarray(e.numpy(), F32))
    np.testing.assert_allclose(rec, x, rtol=1e-5)


@_op("clip_by_norm")
def _clip_by_norm():
    x = rs.randn(3, 4).astype(F32)
    out = P.clip_by_norm(T(x), 1.0)
    n = np.linalg.norm(x)
    ref = x if n <= 1.0 else x / n
    ck(out, ref, rtol=1e-4, atol=1e-4)


@_op("renorm")
def _renorm():
    x = rs.randn(3, 4).astype(F32)
    out = P.renorm(T(x), 2.0, 0, 1.0)
    norms = np.linalg.norm(np.asarray(out.numpy()), axis=1)
    assert (norms <= 1.0 + 1e-4).all()


@_op("polygamma")
def _polygamma():
    from scipy import special

    x = rs.rand(4).astype(F32) + 1.0
    ck(P.polygamma(T(x), 1), special.polygamma(1, x), rtol=1e-3,
       atol=1e-3)


@_op("combinations")
def _combinations():
    import itertools

    x = np.arange(4, dtype=F32)
    out = P.combinations(T(x), 2)
    ref = np.array(list(itertools.combinations(x, 2)), F32)
    ck(out, ref)


@_op("histogram")
def _histogram():
    x = rs.rand(50).astype(F32)
    out = P.histogram(T(x), bins=10, min=0.0, max=1.0)
    ref, _ = np.histogram(x, bins=10, range=(0.0, 1.0))
    ck(out, ref)


@_op("histogramdd")
def _histogramdd():
    x = rs.rand(30, 2).astype(F32)
    hist, edges = P.histogramdd(T(x), bins=4,
                                ranges=[0.0, 1.0, 0.0, 1.0])
    ref, _ = np.histogramdd(x, bins=4, range=[(0, 1), (0, 1)])
    ck(hist, ref)


@_op("sequence_mask")
def _sequence_mask():
    lens = np.array([1, 3, 2], np.int32)
    out = np.asarray(P.nn.functional.sequence_mask(T(lens), maxlen=4)
                     .numpy())
    ref = (np.arange(4)[None, :] < lens[:, None])
    np.testing.assert_array_equal(out.astype(bool), ref)


@_op("shard_index")
def _shard_index():
    idx = np.array([[0], [5], [9], [3]], np.int64)
    out = np.asarray(P.shard_index(T(idx.astype(np.int32)), 10, 2, 0,
                                   -1).numpy())
    shard = 5  # ceil(10/2)
    ref = np.where((idx >= 0) & (idx < shard), idx, -1)
    np.testing.assert_array_equal(out, ref)


@_op("embedding")
def _embedding():
    w = rs.rand(6, 3).astype(F32)
    ids = np.array([[0, 2], [5, 1]], np.int32)
    ck(P.nn.functional.embedding(T(ids), T(w)), w[ids])


@_op("add_n")
def _add_n():
    a = rs.rand(3, 4).astype(F32); b = rs.rand(3, 4).astype(F32)
    ck(P.add_n([T(a), T(b)]), a + b)


@_op("multiplex")
def _multiplex():
    a = rs.rand(3, 4).astype(F32); b = rs.rand(3, 4).astype(F32)
    idx = np.array([[0], [1], [0]], np.int32)
    ref = np.stack([a, b])[idx[:, 0], np.arange(3)]
    ck(P.multiplex([T(a), T(b)], T(idx)), ref)


@_op("accuracy")
def _accuracy():
    probs = np.array([[0.1, 0.9], [0.8, 0.2]], F32)
    lbl = np.array([[1], [1]], np.int32)
    out = float(np.asarray(P.accuracy(T(probs), T(lbl), k=1).numpy()))
    assert abs(out - 0.5) < 1e-6


@_op("auc")
def _auc():
    probs = np.stack([1 - np.linspace(0.1, 0.9, 8),
                      np.linspace(0.1, 0.9, 8)], axis=1).astype(F32)
    lbl = (np.linspace(0.1, 0.9, 8) > 0.5).astype(np.int32)[:, None]
    out = P.auc(T(probs), T(lbl))
    v = float(np.asarray((out[0] if isinstance(out, (tuple, list))
                          else out).numpy()))
    assert 0.9 <= v <= 1.0  # perfectly separable


@_op("view_as")
def _view_as():
    x = rs.rand(2, 6).astype(F32)
    other = rs.rand(3, 4).astype(F32)
    ck(P.view_as(T(x), T(other)), x.reshape(3, 4))


# ------------------------------------------------- random / inplace-random
def _check_inplace_random(name, call, lo=None, hi=None):
    x = np.zeros((200,), F32)
    t = T(x)
    out = call(t)
    v = np.asarray(t.numpy())
    assert np.isfinite(v).all() and v.std() > 0
    if lo is not None:
        assert (v >= lo).all()
    if hi is not None:
        assert (v <= hi).all()


@_op("uniform_")
def _uniform_():
    _check_inplace_random("uniform_", lambda t: P.uniform_(t, -1, 1),
                          -1.0, 1.0)


@_op("normal_")
def _normal_():
    _check_inplace_random("normal_", lambda t: P.normal_(t, 0.0, 1.0))


@_op("cauchy_")
def _cauchy_():
    _check_inplace_random("cauchy_", lambda t: P.cauchy_(t))


@_op("exponential_")
def _exponential_():
    _check_inplace_random("exponential_", lambda t: P.exponential_(t),
                          lo=0.0)


@_op("geometric_")
def _geometric_():
    x = np.zeros((100,), F32)
    t = T(x)
    P.geometric_(t, 0.5)
    v = np.asarray(t.numpy())
    assert (v >= 0).all() and v.std() > 0


@_op("multinomial")
def _multinomial():
    p = np.array([0.1, 0.0, 0.9], F32)
    out = np.asarray(P.multinomial(T(p), 20, replacement=True).numpy())
    assert out.min() >= 0 and out.max() <= 2 and (out != 1).all()


@_op("standard_gamma")
def _standard_gamma():
    a = np.full((100,), 2.0, F32)
    v = np.asarray(P.standard_gamma(T(a)).numpy())
    assert (v > 0).all() and abs(v.mean() - 2.0) < 0.6


@_op("binomial")
def _binomial():
    n = np.full((100,), 10.0, F32)
    p = np.full((100,), 0.5, F32)
    v = np.asarray(P.binomial(T(n), T(p)).numpy())
    assert (v >= 0).all() and (v <= 10).all()


@_op("top_p_sampling")
def _top_p_sampling():
    probs = np.asarray(rs.dirichlet(np.ones(8), 4), F32)
    ps = np.full((4,), 0.8, F32)
    vals, ids = P.top_p_sampling(T(probs), T(ps))
    i = np.asarray(ids.numpy())
    assert i.min() >= 0 and i.max() < 8


# ---------------------------------------------------------- geometric ops
@_op("send_uv")
def _send_uv():
    x = rs.rand(4, 3).astype(F32); y = rs.rand(4, 3).astype(F32)
    src = np.array([0, 1, 2], np.int32)
    dst = np.array([1, 2, 3], np.int32)
    ck(P.geometric.send_uv(T(x), T(y), T(src), T(dst), "add"),
       x[src] + y[dst])


@_op("weighted_sample_neighbors")
def _weighted_sample_neighbors():
    row = np.array([1, 2, 0, 2, 0, 1], np.int32)       # CSC neighbors
    colptr = np.array([0, 2, 4, 6], np.int32)
    w = rs.rand(6).astype(F32)
    nodes = np.array([0, 1], np.int32)
    out = P.geometric.weighted_sample_neighbors(
        T(row), T(colptr), T(w), T(nodes), sample_size=1)
    neigh = np.asarray(out[0].numpy())
    assert neigh.shape[0] == 2


# --------------------------------------------------------- vision / detect
@_op("matrix_nms")
def _matrix_nms():
    boxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]]],
                     F32)
    scores = np.array([[[0.9, 0.85, 0.7]]], F32).repeat(2, axis=1)
    out = P.vision.ops.matrix_nms(T(boxes), T(scores), 0.1, 0.0, 10, 5)
    assert out is not None


@_op("yolo_box")
def _yolo_box():
    x = rs.rand(1, 18, 4, 4).astype(F32)  # 3 anchors * (5+1 class)
    img = np.array([[32, 32]], np.int32)
    boxes, scores = P.vision.ops.yolo_box(
        T(x), T(img), anchors=[10, 13, 16, 30, 33, 23], class_num=1,
        conf_thresh=0.01, downsample_ratio=8)
    assert boxes.shape[0] == 1 and scores.shape[0] == 1


@_op("yolo_loss")
def _yolo_loss():
    # real composed implementation: finite per-image loss, grads flow,
    # and a matching prediction scores lower than a mismatched one
    rs2 = np.random.RandomState(5)
    anchors = [10, 14, 23, 27, 37, 58]
    gt = np.array([[[0.5, 0.5, 0.2, 0.2]]], F32)
    lbl = np.array([[1]], np.int32)

    def head(obj_logit, correct_cls):
        x = np.zeros((1, 3 * 7, 4, 4), F32)
        v = x.reshape(1, 3, 7, 4, 4)
        v[:, :, 4] = -8.0                   # everything background...
        a_best = 0  # 0.2*32=6.4px -> anchor (10,14) has best wh-IoU
        v[0, a_best, 4, 2, 2] = obj_logit   # ...except the gt cell
        v[0, a_best, 5 + (1 if correct_cls else 0), 2, 2] = 6.0
        return v.reshape(1, 21, 4, 4)

    def loss_of(arr):
        out = P.vision.ops.yolo_loss(
            T(arr), T(gt), T(lbl), anchors=anchors, anchor_mask=[0, 1, 2],
            class_num=2, ignore_thresh=0.7, downsample_ratio=8)
        return np.asarray(out.numpy())

    good = loss_of(head(6.0, True))
    bad = loss_of(head(-8.0, False))
    assert good.shape == (1,)
    assert np.isfinite(good).all() and np.isfinite(bad).all()
    assert good[0] < bad[0]

    # grads flow through the head
    t = P.to_tensor(head(0.0, True), stop_gradient=False)
    P.vision.ops.yolo_loss(
        t, T(gt), T(lbl), anchors=anchors, anchor_mask=[0, 1, 2],
        class_num=2, ignore_thresh=0.7, downsample_ratio=8).sum().backward()
    g = np.asarray(t.grad.numpy())
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


@_op("psroi_pool")
def _psroi_pool():
    x = rs.rand(1, 8, 6, 6).astype(F32)  # C = out_c * ps*ps = 2*2*2
    boxes = np.array([[0, 0, 4, 4]], F32)
    num = np.array([1], np.int32)
    out = P.vision.ops.psroi_pool(T(x), T(boxes), T(num), 2)
    cks(out, (1, 2, 2, 2))


@_op("distribute_fpn_proposals")
def _distribute_fpn_proposals():
    rois = np.array([[0, 0, 10, 10], [0, 0, 120, 120]], F32)
    outs = P.vision.ops.distribute_fpn_proposals(T(rois), 2, 5, 4, 224)
    assert outs is not None


@_op("generate_proposals")
def _generate_proposals():
    scores = rs.rand(1, 3, 4, 4).astype(F32)
    deltas = rs.rand(1, 12, 4, 4).astype(F32)
    img = np.array([[32.0, 32.0]], F32)
    anchors = rs.rand(4, 4, 3, 4).astype(F32) * 16
    var = np.ones((4, 4, 3, 4), F32)
    rois, roi_probs, num = P.vision.ops.generate_proposals(
        T(scores), T(deltas), T(img), T(anchors), T(var),
        pre_nms_top_n=10, post_nms_top_n=5)
    assert rois.shape[-1] == 4


@_op("class_center_sample")
def _class_center_sample():
    lbl = np.array([0, 3, 5, 3], np.int32)
    remapped, sampled = P.nn.functional.class_center_sample(T(lbl), 8, 4)
    assert sampled.shape[0] >= 3  # the 3 positive classes survive


# --------------------------------------------- remaining inplace twins
@_op("addmm_")
def _addmm_():
    a = rs.rand(3, 2).astype(F32); b = rs.rand(2, 3).astype(F32)
    inp = rs.rand(3, 3).astype(F32)
    t = T(inp)
    P.addmm_(t, T(a), T(b))
    ck(t, inp + a @ b, rtol=1e-4, atol=1e-4)


@_op("clip_")
def _clip_():
    t = T(X34)
    P.clip_(t, 0.25, 0.75)
    ck(t, np.clip(X34, 0.25, 0.75))


@_op("cumsum_")
def _cumsum_():
    t = T(X34)
    P.cumsum_(t, axis=1)
    ck(t, np.cumsum(X34, axis=1))


@_op("cumprod_")
def _cumprod_():
    t = T(X34)
    P.cumprod_(t, dim=1)
    ck(t, np.cumprod(X34, axis=1))


@_op("mod_")
def _mod_():
    y = rs.rand(3, 4).astype(F32) + 0.5
    t = T(X34)
    P.mod_(t, T(y))
    ck(t, np.mod(X34, y), rtol=1e-4, atol=1e-4)


@_op("floor_mod_")
def _floor_mod_():
    y = rs.rand(3, 4).astype(F32) + 0.5
    t = T(X34)
    P.floor_mod_(t, T(y))
    ck(t, np.mod(X34, y), rtol=1e-4, atol=1e-4)


@_op("nan_to_num_")
def _nan_to_num_():
    x = np.array([1.0, np.nan], F32)
    t = T(x)
    P.nan_to_num_(t)
    ck(t, np.nan_to_num(x))


@_op("scale_")
def _scale_():
    t = T(X34)
    P.scale_(t, 2.0, 1.0)
    ck(t, X34 * 2.0 + 1.0)


@_op("renorm_")
def _renorm_():
    t = T(X34)
    P.renorm_(t, 2.0, 0, 1.0)
    assert (np.linalg.norm(np.asarray(t.numpy()), axis=1)
            <= 1.0 + 1e-4).all()


@_op("polygamma_")
def _polygamma_():
    from scipy import special

    x = rs.rand(4).astype(F32) + 1.0
    t = T(x)
    P.polygamma_(t, 1)
    ck(t, special.polygamma(1, x), rtol=1e-3, atol=1e-3)


@_op("multigammaln_")
def _multigammaln_():
    from scipy import special

    x = rs.rand(4).astype(F32) + 3.0
    t = T(x)
    P.multigammaln_(t, 2)
    ck(t, special.multigammaln(x[:, None], 2).ravel()
       if hasattr(special, "multigammaln") else t.numpy(),
       rtol=1e-3, atol=1e-3)


@_op("masked_fill_")
def _masked_fill_():
    m = X34 > 0.5
    t = T(X34)
    P.masked_fill_(t, T(m), -1.0)
    ck(t, np.where(m, np.float32(-1.0), X34))


@_op("masked_scatter_")
def _masked_scatter_():
    m = X34 > 0.5
    v = np.arange(12, dtype=F32)
    ref = X34.copy(); ref[m] = v[:m.sum()]
    t = T(X34)
    P.masked_scatter_(t, T(m), T(v))
    ck(t, ref)


@_op("index_add_")
def _index_add_():
    idx = np.array([0, 2], np.int32)
    val = rs.rand(2, 4).astype(F32)
    ref = X34.copy(); np.add.at(ref, idx, val)
    t = T(X34)
    P.index_add_(t, T(idx), 0, T(val))
    ck(t, ref)


@_op("index_fill_")
def _index_fill_():
    idx = np.array([1], np.int32)
    ref = X34.copy(); ref[:, 1] = 9.0
    t = T(X34)
    P.index_fill_(t, T(idx), 1, 9.0)
    ck(t, ref)


@_op("index_put_")
def _index_put_():
    ii = np.array([0, 2], np.int32); jj = np.array([1, 3], np.int32)
    v = np.array([7.0, 8.0], F32)
    ref = X34.copy(); ref[ii, jj] = v
    t = T(X34)
    P.index_put_(t, (T(ii), T(jj)), T(v))
    ck(t, ref)


@_op("put_along_axis_")
def _put_along_axis_():
    idx = np.array([[1], [0], [2]], np.int32)
    v = np.array([[5.], [6.], [7.]], F32)
    ref = X34.copy(); np.put_along_axis(ref, idx, v, axis=1)
    t = T(X34)
    P.put_along_axis_(t, T(idx), T(v), 1)
    ck(t, ref)


@_op("scatter_")
def _scatter_():
    idx = np.array([1, 0], np.int32)
    upd = rs.rand(2, 4).astype(F32)
    ref = X34.copy(); ref[idx] = upd
    t = T(X34)
    P.scatter_(t, T(idx), T(upd), overwrite=True)
    ck(t, ref)


@_op("reshape_")
def _reshape_():
    t = T(X34)
    P.reshape_(t, [4, 3])
    ck(t, X34.reshape(4, 3))


@_op("flatten_")
def _flatten_():
    x = rs.rand(2, 3, 4).astype(F32)
    t = T(x)
    P.flatten_(t, 0, 1)
    ck(t, x.reshape(6, 4))


@_op("squeeze_")
def _squeeze_():
    x = rs.rand(3, 1, 4).astype(F32)
    t = T(x)
    P.squeeze_(t, axis=1)
    ck(t, x[:, 0, :])


@_op("unsqueeze_")
def _unsqueeze_():
    t = T(X34)
    P.unsqueeze_(t, axis=0)
    ck(t, X34[None])


@_op("transpose_")
def _transpose_():
    t = T(X34)
    P.transpose_(t, perm=[1, 0])
    ck(t, X34.T)


@_op("t_")
def _t_():
    t = T(X34)
    P.t_(t)
    ck(t, X34.T)


@_op("triu_")
def _triu_():
    t = T(X44)
    P.triu_(t)
    ck(t, np.triu(X44))


@_op("where_")
def _where_():
    cond = X34 > 0.5
    y = np.zeros_like(X34)
    t = T(X34)
    P.where_(T(cond), t, T(y))  # reference: inplace on x
    ck(t, np.where(cond, X34, y))


@_op("unique")
def _unique():
    x = np.array([3., 1., 2., 1., 3.], F32)
    out = P.unique(T(x))
    ck(out, np.unique(x))


@_op("unique_consecutive")
def _unique_consecutive():
    x = np.array([1., 1., 2., 2., 3., 1.], F32)
    out = P.unique_consecutive(T(x))
    ref = np.array([1., 2., 3., 1.], F32)
    ck(out, ref)
