"""Auto-tuner, cost model, RPC, elastic manager.

Parity model: reference `test/auto_tuner/` (search+prune) and
`test/legacy_test/test_rpc*.py` (sync/async calls, worker infos).
"""
import os
import time

import numpy as np
import pytest

from paddle_tpu.cost_model import (TransformerShape, V5P, allreduce_cost,
                                   matmul_cost, memory_per_chip,
                                   train_step_cost)
from paddle_tpu.distributed.auto_tuner import (AutoTuner, Candidate,
                                               default_candidates)


def _shape_7b():
    return TransformerShape(hidden=4096, ffn_hidden=11008, num_heads=32,
                            seq_len=2048, vocab_size=32000, num_layers=32)


def test_cost_model_basics():
    c = matmul_cost(4096, 4096, 4096)
    assert c.compute_s > 0 and c.memory_s > 0
    # ring allreduce approaches 2x bytes/bw for large n
    a = allreduce_cost(1e9, 64)
    assert 1.9e9 / V5P.ici_bw < a.comm_s < 2.0e9 / V5P.ici_bw
    assert allreduce_cost(1e9, 1).comm_s == 0.0


def test_memory_model_scales_down_with_sharding():
    s = _shape_7b()
    m0 = memory_per_chip(s, 1, dp=8, sharding_stage=0)
    m3 = memory_per_chip(s, 1, dp=8, sharding_stage=3)
    assert m3 < m0 * 0.5


def test_candidates_respect_divisibility():
    cands = default_candidates(n_chips=8, global_batch=32, num_heads=32,
                               num_layers=32)
    assert cands
    for c in cands:
        assert c.dp * c.mp * c.pp == 8
        assert 32 % c.dp == 0


def test_autotuner_prunes_and_ranks():
    s = _shape_7b()
    tuner = AutoTuner(s, n_chips=64, global_batch=512, n_hosts=1)
    ranked = tuner.search()
    assert ranked, "no feasible candidate for 7B on 64 chips"
    # every survivor fits the memory budget
    assert all(c.est_mem_bytes <= tuner.mem_budget for c in ranked)
    # ranking is sorted
    times = [c.est_time_s for c in ranked]
    assert times == sorted(times)
    # 7B on one chip without sharding must be pruned
    single = AutoTuner(s, n_chips=1, global_batch=8)
    assert single.search() == []


def test_autotuner_tune_runs_trials():
    s = _shape_7b()
    tuner = AutoTuner(s, n_chips=8, global_batch=64)

    calls = []

    def trial(c):
        calls.append(c)
        return c.est_time_s * 1.1  # pretend-measured

    best = tuner.tune(trial, max_trials=3)
    assert best is not None and len(calls) == 3
    assert best[0] is calls[0]  # analytic best wins the pretend trials


def test_rpc_sync_async_roundtrip():
    from paddle_tpu.distributed import rpc

    os.environ["PADDLE_MASTER"] = "127.0.0.1:8612"
    try:
        me = rpc.init_rpc("worker0", rank=0, world_size=1)
        assert me.name == "worker0"
        assert rpc.get_worker_info("worker0").rank == 0
        r = rpc.rpc_sync("worker0", max, args=([3, 1, 2],))
        assert r == 3
        fut = rpc.rpc_async("worker0", pow, args=(2, 10))
        assert fut.result(10) == 1024
        # exceptions propagate
        with pytest.raises(ZeroDivisionError):
            rpc.rpc_sync("worker0", divmod, args=(1, 0))
        # unpicklable replies surface a clear error, not a dropped socket
        import threading

        with pytest.raises(RuntimeError, match="not picklable"):
            rpc.rpc_sync("worker0", threading.Lock)
    finally:
        rpc.shutdown()
        os.environ.pop("PADDLE_MASTER", None)


def test_elastic_manager_membership():
    from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                      ElasticStatus)
    from paddle_tpu.distributed.store import TCPStore

    store = TCPStore("127.0.0.1", 8613, is_master=True)
    mgr = ElasticManager(store=store, job_id="t1", np_range="1:2",
                         heartbeat_interval=0.2, heartbeat_ttl=2.0)
    mgr.register()
    time.sleep(0.3)
    assert mgr.alive_ranks(2) == [0]
    # 1 of 2 alive but min_np=1 + elastic level → RESTART (scale-in)
    assert mgr.watch(2) == ElasticStatus.RESTART
    # full membership + not done → HOLD
    assert mgr.watch(1) == ElasticStatus.HOLD
    mgr.mark_done()
    assert mgr.watch(1) == ElasticStatus.COMPLETED
    mgr.exit()


def test_elastic_fault_tolerance_restarts():
    from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                      ElasticStatus)
    from paddle_tpu.distributed.store import TCPStore

    store = TCPStore("127.0.0.1", 8614, is_master=True)
    mgr = ElasticManager(store=store, job_id="t2", np_range="2",
                         heartbeat_interval=0.2, heartbeat_ttl=2.0)
    mgr.register()
    time.sleep(0.3)
    # fixed world of 2, only rank 0 alive → RESTART (not ERROR)
    assert mgr.watch(2) == ElasticStatus.RESTART
    mgr.exit()
