"""SPMD collective pipeline (one-program, ppermute stage shifts) vs the
sequential oracle — values AND gradients.

The schedule itself is what's under test: a wrong permutation, a
mis-clamped injection index, or a collection off-by-one produces wrong
values; a wrong psum/where masking produces wrong or scaled gradients.
Reference role: fleet/meta_parallel/pipeline_parallel.py:440 +
pp_utils/p2p_communication.py (send/recv tier), rebuilt as collectives.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.distributed.pipeline_spmd import (
    spmd_pipeline, spmd_pipeline_reference, stack_stages,
)


# jax 0.4.x expresses partial-manual shard_map via `auto=` and its SPMD
# partitioner cannot place PartitionId inside such a region (the pp+dp /
# pp+mp compositions below hit "PartitionId ... UNIMPLEMENTED").  The
# modern toolchain (axis_names=) partitions these fine — gate, don't fail.
_partial_manual_ok = False
try:
    import inspect as _inspect

    from paddle_tpu.distributed.pipeline_spmd import shard_map as _sm

    _partial_manual_ok = "axis_names" in _inspect.signature(_sm).parameters
except (ImportError, AttributeError, TypeError, ValueError):
    pass  # no signature to probe: the modern-toolchain path stays off
_needs_partial_manual = pytest.mark.skipif(
    not _partial_manual_ok,
    reason="jax<0.5 shard_map auto-axes partitioner cannot lower "
           "PartitionId (pp composed with dp/mp axes)")


def _block(params, act):
    # transformer-ish stage: matmul + gelu + residual + rms-ish norm
    h = act @ params["w"] + params["b"]
    h = jax.nn.gelu(h)
    act = act + h
    return act / jnp.sqrt(jnp.mean(act * act, -1, keepdims=True) + 1e-6)


def _stages(pp, width, seed=0):
    rs = np.random.RandomState(seed)
    return [{"w": jnp.asarray(rs.randn(width, width) * 0.1, jnp.float32),
             "b": jnp.asarray(rs.randn(width) * 0.1, jnp.float32)}
            for _ in range(pp)]


def _mesh(pp, extra=()):
    devs = jax.devices()
    need = pp * int(np.prod([d for _, d in extra])) if extra else pp
    assert len(devs) >= need, (len(devs), need)
    names = ("pp",) + tuple(n for n, _ in extra)
    shape = (pp,) + tuple(d for _, d in extra)
    return Mesh(np.array(devs[:int(np.prod(shape))]).reshape(shape), names)


@pytest.mark.parametrize("pp,m", [(2, 4), (4, 8), (4, 3)])
def test_spmd_pipeline_matches_sequential(pp, m):
    width, mb = 16, 2
    stages = _stages(pp, width)
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(m, mb, width), jnp.float32)
    want = spmd_pipeline_reference(_block, stages, x)
    got = spmd_pipeline(_block, stack_stages(stages), x, mesh=_mesh(pp))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("remat", [False, True])
def test_spmd_pipeline_grad_matches_sequential(remat):
    """jax.grad through the scanned ppermute schedule IS the backward
    pipeline; parameter and input grads must match the oracle (a psum/
    mask error would scale or misroute them)."""
    pp, m, width, mb = 4, 6, 8, 2
    stages = _stages(pp, width, seed=2)
    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.randn(m, mb, width), jnp.float32)
    tgt = jnp.asarray(rs.randn(m, mb, width), jnp.float32)
    mesh = _mesh(pp)

    def loss_seq(stages, x):
        y = spmd_pipeline_reference(_block, stages, x)
        return jnp.mean((y - tgt) ** 2)

    def loss_pp(stacked, x):
        y = spmd_pipeline(_block, stacked, x, mesh=mesh,
                          remat_stage=remat)
        return jnp.mean((y - tgt) ** 2)

    lw, (gw, gxw) = jax.value_and_grad(loss_seq, argnums=(0, 1))(stages, x)
    lp, (gp, gxp) = jax.value_and_grad(loss_pp, argnums=(0, 1))(
        stack_stages(stages), x)
    np.testing.assert_allclose(float(lp), float(lw), rtol=2e-5)
    np.testing.assert_allclose(np.asarray(gxp), np.asarray(gxw),
                               rtol=2e-4, atol=2e-6)
    want_stacked = stack_stages(gw)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(gp[k]),
                                   np.asarray(want_stacked[k]),
                                   rtol=2e-4, atol=2e-6)


@_needs_partial_manual
def test_spmd_pipeline_composes_with_dp_axis():
    """Partial-manual shard_map: only 'pp' is manual — a dp axis on the
    same mesh keeps sharding the microbatch dim through GSPMD, so the
    one-program pipeline composes with data parallelism."""
    pp, dp, m, width, mb = 2, 2, 4, 8, 4
    stages = _stages(pp, width, seed=4)
    mesh = _mesh(pp, extra=(("dp", dp),))
    rs = np.random.RandomState(5)
    xh = rs.randn(m, mb, width).astype(np.float32)
    x = jax.device_put(
        jnp.asarray(xh), NamedSharding(mesh, P(None, "dp", None)))
    stacked = jax.tree_util.tree_map(
        lambda l: jax.device_put(
            l, NamedSharding(mesh, P(*(("pp",) + (None,) * (l.ndim - 1))))),
        stack_stages(stages))
    got = jax.jit(lambda s, x: spmd_pipeline(_block, s, x, mesh=mesh))(
        stacked, x)
    want = spmd_pipeline_reference(_block, stages, jnp.asarray(xh))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


def test_spmd_pipeline_validates_inputs():
    stages = _stages(2, 8)
    x = jnp.zeros((4, 2, 8))
    with pytest.raises(ValueError, match="pp"):
        spmd_pipeline(_block, stack_stages(stages), x, mesh=_mesh(4))
    mesh2 = Mesh(np.array(jax.devices()[:2]), ("dp",))
    with pytest.raises(ValueError, match="axis"):
        spmd_pipeline(_block, stack_stages(stages), x, mesh=mesh2)


def test_spmd_pipeline_pp1_is_sequential():
    stages = _stages(1, 8)
    x = jnp.asarray(np.random.RandomState(6).randn(3, 2, 8), np.float32)
    got = spmd_pipeline(_block, stack_stages(stages), x, mesh=_mesh(1))
    want = spmd_pipeline_reference(_block, stages, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


def test_spmd_pipeline_carries_real_gpt_blocks():
    """The collective schedule must carry REAL transformer stages
    (attention + MLP + norms through the dispatch gate), not just pure
    toy closures: 4 GPTBlocks, one per stage, params stacked over pp —
    output parity vs running the same blocks sequentially."""
    import paddle_tpu as P
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models.gpt import GPTBlock, gpt_tiny

    cfg = gpt_tiny()
    pp, m, mb, seq = 4, 4, 2, 16
    P.seed(11)
    blocks = [GPTBlock(cfg) for _ in range(pp)]
    for b in blocks:
        b.eval()
    states = [b.functional_state() for b in blocks]
    stage_params = [dict(s[0]) for s in states]
    buffers = states[0][1]
    proto = blocks[0]

    def stage_fn(params, act):
        with proto.bind_state(params, buffers):
            return proto(Tensor(act))._value

    rs = np.random.RandomState(12)
    x = jnp.asarray(rs.randn(m, mb, seq, cfg.hidden_size), jnp.float32)
    want = spmd_pipeline_reference(stage_fn, stage_params, x)
    got = spmd_pipeline(stage_fn, stack_stages(stage_params), x,
                        mesh=_mesh(4))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_spmd_pipeline_full_lm_step_grads():
    """End-to-end LM training composition through the collective tier:
    tied embedding -> microbatched GPT blocks in the pipeline (2 blocks
    per stage via scan-over-local-layers) -> final norm -> tied-head CE.
    Gradients wrt the embedding (used at BOTH ends — its cotangent must
    accumulate through the masked-psum exit AND the stage-0 injection),
    the stacked block params, and the final norm must all match the
    sequential oracle."""
    import paddle_tpu as P
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models.gpt import GPTBlock, GPTConfig

    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=8,
                    num_heads=2, max_seq_len=16, dropout=0.0)
    pp, per_stage, m, mb, seq = 4, 2, 4, 2, 16
    P.seed(21)
    blocks = [GPTBlock(cfg) for _ in range(pp * per_stage)]
    for b in blocks:
        b.eval()
    states = [b.functional_state() for b in blocks]
    buffers = states[0][1]
    proto = blocks[0]
    # [pp] stages, each leaf [per_stage, ...]
    groups = [jax.tree_util.tree_map(
        lambda *ls: jnp.stack(ls),
        *[dict(states[s * per_stage + j][0]) for j in range(per_stage)])
        for s in range(pp)]
    rs = np.random.RandomState(22)
    wte = jnp.asarray(rs.randn(cfg.vocab_size, cfg.hidden_size) * 0.02,
                      jnp.float32)
    lnw = jnp.ones((cfg.hidden_size,), jnp.float32)
    lnb = jnp.zeros((cfg.hidden_size,), jnp.float32)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (m, mb, seq)), jnp.int32)
    labels = jnp.asarray(rs.randint(0, cfg.vocab_size, (m, mb, seq)),
                         jnp.int32)
    mesh = _mesh(pp)

    def stage_fn(params, act):
        def body(a, blk):
            with proto.bind_state(blk, buffers):
                return proto(Tensor(a))._value, None

        act, _ = jax.lax.scan(body, act, params)
        return act

    def loss_from(run_blocks, stages, wte, lnw, lnb):
        x = wte[ids]                                   # [m, mb, s, h]
        y = run_blocks(stages, x)
        mu = jnp.mean(y, -1, keepdims=True)
        var = jnp.var(y, -1, keepdims=True)
        y = (y - mu) / jnp.sqrt(var + 1e-5) * lnw + lnb
        logits = y @ wte.T                             # tied head
        lse = jax.scipy.special.logsumexp(logits, -1)
        tok = jnp.take_along_axis(logits, labels[..., None],
                                  -1)[..., 0]
        return jnp.mean(lse - tok)

    def loss_pp(stacked, wte, lnw, lnb):
        return loss_from(
            lambda s, x: spmd_pipeline(stage_fn, s, x, mesh=mesh,
                                       remat_stage=True),
            stacked, wte, lnw, lnb)

    def loss_seq(groups, wte, lnw, lnb):
        return loss_from(
            lambda gs, x: spmd_pipeline_reference(stage_fn, gs, x),
            groups, wte, lnw, lnb)

    lp, gp = jax.value_and_grad(loss_pp, argnums=(0, 1, 2, 3))(
        stack_stages(groups), wte, lnw, lnb)
    lw, gw = jax.value_and_grad(loss_seq, argnums=(0, 1, 2, 3))(
        groups, wte, lnw, lnb)
    np.testing.assert_allclose(float(lp), float(lw), rtol=2e-5)
    np.testing.assert_allclose(np.asarray(gp[1]), np.asarray(gw[1]),
                               rtol=3e-4, atol=3e-6)  # tied wte
    np.testing.assert_allclose(np.asarray(gp[2]), np.asarray(gw[2]),
                               rtol=3e-4, atol=3e-6)
    np.testing.assert_allclose(np.asarray(gp[3]), np.asarray(gw[3]),
                               rtol=3e-4, atol=3e-6)
    want_stacked = stack_stages(gw[0])
    for k in sorted(want_stacked):
        np.testing.assert_allclose(
            np.asarray(gp[0][k]), np.asarray(want_stacked[k]),
            rtol=3e-4, atol=3e-6, err_msg=k)


def test_spmd_pipeline_single_microbatch():
    """m=1 edge: the pipeline degenerates to a pp-tick relay — clamped
    injection must not corrupt the one real microbatch."""
    pp, width = 4, 8
    stages = _stages(pp, width, seed=7)
    x = jnp.asarray(np.random.RandomState(8).randn(1, 2, width),
                    np.float32)
    got = spmd_pipeline(_block, stack_stages(stages), x, mesh=_mesh(pp))
    want = spmd_pipeline_reference(_block, stages, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


@_needs_partial_manual
def test_spmd_pipeline_composes_with_mp_sharded_weights():
    """Stages whose WEIGHTS are tensor-parallel over an auto mp axis:
    GSPMD shards the per-stage GEMMs while the manual pp axis runs the
    schedule — the hybrid the one-program tier exists for."""
    pp, mp, m, width, mb = 2, 2, 4, 16, 2
    mesh = _mesh(pp, extra=(("mp", mp),))
    rs = np.random.RandomState(9)
    stages = [{"up": jnp.asarray(rs.randn(width, 4 * width) * 0.1,
                                 jnp.float32),
               "down": jnp.asarray(rs.randn(4 * width, width) * 0.1,
                                   jnp.float32)}
              for _ in range(pp)]

    def block(p, a):
        h = jax.nn.gelu(a @ p["up"])      # column-parallel under mp
        return a + h @ p["down"]          # row-parallel under mp

    spec = {"up": P("pp", None, "mp"), "down": P("pp", "mp", None)}
    stacked = {
        k: jax.device_put(v, NamedSharding(mesh, spec[k]))
        for k, v in stack_stages(stages).items()}
    x = jnp.asarray(rs.randn(m, mb, width), jnp.float32)
    got = jax.jit(lambda s, xv: spmd_pipeline(block, s, xv, mesh=mesh))(
        stacked, x)
    want = spmd_pipeline_reference(block, stages, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)
