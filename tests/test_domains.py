"""geometric / text / audio domain packages.

Parity model: reference tests `test/legacy_test/test_graph_send_recv_op.py`,
`test_viterbi_decode_op.py`, `test/legacy_test/test_audio_functions.py`.
"""
import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu import audio, geometric, text


# --- geometric ---------------------------------------------------------------

def test_send_u_recv_sum_mean():
    x = P.to_tensor(np.arange(12, dtype=np.float32).reshape(4, 3))
    src = P.to_tensor(np.array([0, 1, 2, 0], np.int32))
    dst = P.to_tensor(np.array([1, 2, 1, 0], np.int32))
    out = geometric.send_u_recv(x, src, dst, reduce_op="sum")
    ref = np.zeros((4, 3), np.float32)
    for s, d in zip([0, 1, 2, 0], [1, 2, 1, 0]):
        ref[d] += x.numpy()[s]
    np.testing.assert_allclose(out.numpy(), ref)
    out_mean = geometric.send_u_recv(x, src, dst, reduce_op="mean")
    ref_mean = ref.copy()
    ref_mean[1] /= 2
    np.testing.assert_allclose(out_mean.numpy(), ref_mean)


def test_send_u_recv_max_empty_segment_zero():
    x = P.to_tensor(np.array([[1.0], [2.0]], np.float32))
    src = P.to_tensor(np.array([0, 1], np.int32))
    dst = P.to_tensor(np.array([0, 0], np.int32))
    out = geometric.send_u_recv(x, src, dst, reduce_op="max", out_size=3)
    np.testing.assert_allclose(out.numpy(), [[2.0], [0.0], [0.0]])


def test_send_ue_recv_and_grad():
    x = P.to_tensor(np.ones((3, 2), np.float32), stop_gradient=False)
    e = P.to_tensor(np.full((4, 2), 0.5, np.float32))
    src = np.array([0, 1, 2, 0], np.int32)
    dst = np.array([1, 2, 0, 2], np.int32)
    out = geometric.send_ue_recv(x, e, P.to_tensor(src), P.to_tensor(dst),
                                 message_op="mul", reduce_op="sum")
    P.sum(out).backward()
    assert x.grad is not None
    np.testing.assert_allclose(x.grad.numpy(),
                               [[1.0, 1.0], [0.5, 0.5], [0.5, 0.5]])


def test_segment_ops():
    data = P.to_tensor(np.array([[1.0], [2.0], [3.0]], np.float32))
    seg = P.to_tensor(np.array([0, 0, 1], np.int32))
    np.testing.assert_allclose(
        geometric.segment_sum(data, seg).numpy(), [[3.0], [3.0]])
    np.testing.assert_allclose(
        geometric.segment_mean(data, seg).numpy(), [[1.5], [3.0]])
    np.testing.assert_allclose(
        geometric.segment_max(data, seg).numpy(), [[2.0], [3.0]])


def test_sample_and_reindex():
    # CSC: node j's in-neighbors are row[colptr[j]:colptr[j+1]]
    row = np.array([1, 2, 0, 2, 0, 1], np.int64)
    colptr = np.array([0, 2, 4, 6], np.int64)
    nbr, cnt = geometric.sample_neighbors(
        P.to_tensor(row), P.to_tensor(colptr),
        P.to_tensor(np.array([0, 2], np.int64)))
    assert cnt.numpy().tolist() == [2, 2]
    re_nb, dst, nodes = geometric.reindex_graph(
        P.to_tensor(np.array([0, 2], np.int64)), nbr, cnt)
    assert nodes.numpy()[0] == 0 and nodes.numpy()[1] == 2
    assert dst.numpy().tolist() == [0, 0, 1, 1]


# --- text --------------------------------------------------------------------

def test_viterbi_decode_simple():
    # 2 tags + BOS/EOS = 4 states; deterministic argmax chain
    np.random.seed(0)
    B, T, N = 2, 5, 4
    pot = np.random.rand(B, T, N).astype(np.float32)
    trans = np.random.rand(N, N).astype(np.float32)
    lens = np.array([5, 5], np.int64)
    scores, paths = text.viterbi_decode(
        P.to_tensor(pot), P.to_tensor(trans), P.to_tensor(lens),
        include_bos_eos_tag=False)
    assert list(paths.shape) == [B, T]
    # brute-force reference for batch 0
    best = None
    from itertools import product

    for seq in product(range(N), repeat=T):
        s = pot[0, 0, seq[0]]
        for t in range(1, T):
            s += trans[seq[t - 1], seq[t]] + pot[0, t, seq[t]]
        if best is None or s > best[0]:
            best = (s, seq)
    np.testing.assert_allclose(float(scores.numpy()[0]), best[0], rtol=1e-5)
    assert paths.numpy()[0].tolist() == list(best[1])


# --- audio -------------------------------------------------------------------

def test_windows_and_mel():
    w = audio.functional.get_window("hann", 16)
    assert w.shape == [16]
    np.testing.assert_allclose(w.numpy()[0], 0.0, atol=1e-7)
    fb = audio.functional.compute_fbank_matrix(16000, 512, n_mels=40)
    assert fb.shape == [40, 257]
    assert float(np.asarray(fb.numpy()).min()) >= 0.0


def test_spectrogram_and_mfcc_shapes():
    sr, n_fft, hop = 16000, 256, 128
    x = P.to_tensor(np.random.RandomState(0).randn(2, 1600)
                    .astype(np.float32))
    spec = audio.features.Spectrogram(n_fft=n_fft, hop_length=hop)(x)
    assert spec.shape[0] == 2 and spec.shape[1] == n_fft // 2 + 1
    mel = audio.features.MelSpectrogram(sr=sr, n_fft=n_fft, hop_length=hop,
                                        n_mels=32)(x)
    assert mel.shape[1] == 32
    mfcc = audio.features.MFCC(sr=sr, n_mfcc=13, n_mels=32, n_fft=n_fft,
                               hop_length=hop)(x)
    assert mfcc.shape[1] == 13
    db = audio.functional.power_to_db(mel)
    assert db.shape == mel.shape


def test_text_dataset_requires_local_archive():
    # real loaders now (tests/test_text_datasets.py); without a local
    # archive the zero-egress contract still raises with guidance
    with pytest.raises(RuntimeError, match="local archive"):
        text.datasets.Imdb()


@pytest.mark.slow
def test_incubate_fused_layer_zoo():
    """incubate.nn fused Layers (fused_transformer.py role): construct,
    forward, backward; pre-LN and post-LN variants."""
    from paddle_tpu.incubate.nn import (
        FusedBiasDropoutResidualLayerNorm, FusedDropoutAdd, FusedEcMoe,
        FusedFeedForward, FusedLinear, FusedMultiHeadAttention,
        FusedMultiTransformer, FusedTransformerEncoderLayer,
    )

    P.seed(0)
    rs = np.random.RandomState(0)
    x = P.to_tensor(rs.randn(2, 8, 16).astype(np.float32))

    lin = FusedLinear(16, 24)
    assert lin(x).shape == [2, 8, 24]

    da = FusedDropoutAdd(p=0.0)
    np.testing.assert_allclose(np.asarray(da(x, x).numpy()),
                               2 * np.asarray(x.numpy()), rtol=1e-6)

    bdr = FusedBiasDropoutResidualLayerNorm(16, dropout_rate=0.0)
    out = bdr(x, x)
    assert out.shape == [2, 8, 16]
    # layer-normalized output: ~zero mean, ~unit variance per row
    v = np.asarray(out.numpy())
    np.testing.assert_allclose(v.mean(-1), 0.0, atol=1e-4)

    for pre in (True, False):
        mha = FusedMultiHeadAttention(16, 4, dropout_rate=0.0,
                                      attn_dropout_rate=0.0,
                                      normalize_before=pre)
        assert mha(x).shape == [2, 8, 16]

        ffn = FusedFeedForward(16, 32, dropout_rate=0.0,
                               normalize_before=pre)
        assert ffn(x).shape == [2, 8, 16]

    enc = FusedTransformerEncoderLayer(16, 4, 32, dropout_rate=0.0)
    t = P.to_tensor(rs.randn(2, 8, 16).astype(np.float32),
                    stop_gradient=False)
    out = enc(t)
    out.sum().backward()
    assert t.grad is not None and np.isfinite(t.grad.numpy()).all()

    mt = FusedMultiTransformer(16, 4, 32, num_layers=2)
    assert mt(x).shape == [2, 8, 16]

    moe = FusedEcMoe(16, 32, num_experts=4)
    out = moe(x)
    assert out.shape == [2, 8, 16]
    assert np.isfinite(out.numpy()).all()


def test_incubate_lookahead_and_model_average():
    from paddle_tpu.incubate.optimizer import (
        DistributedFusedLamb, LookAhead, ModelAverage,
    )
    import paddle_tpu.nn as nn

    P.seed(0)
    lin = nn.Linear(4, 1)
    inner = P.optimizer.SGD(parameters=lin.parameters(), learning_rate=0.1)
    opt = LookAhead(inner, alpha=0.5, k=2)
    rs = np.random.RandomState(0)
    x = P.to_tensor(rs.randn(8, 4).astype(np.float32))
    y = P.to_tensor(rs.randn(8, 1).astype(np.float32))
    losses = []
    for _ in range(6):
        loss = ((lin(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]
    sd = opt.state_dict()
    assert "slow" in sd and sd["steps"] == 6

    ma = ModelAverage(0.15, parameters=lin.parameters(),
                      max_average_window=4)
    w_live = lin.weight.numpy().copy()
    for _ in range(3):
        ma.step()
    ma.apply()
    np.testing.assert_allclose(lin.weight.numpy(), w_live, rtol=1e-5)
    lin.weight.set_value(w_live * 0)  # averaged copy is active; mutate
    ma.restore()
    np.testing.assert_allclose(lin.weight.numpy(), w_live, rtol=1e-6)

    fl = DistributedFusedLamb(parameters=lin.parameters())
    loss = ((lin(x) - y) ** 2).mean()
    loss.backward()
    fl.step()
    fl.clear_grad()


def test_dataset_folder_and_image_folder(tmp_path):
    from PIL import Image

    from paddle_tpu.vision.datasets import DatasetFolder, ImageFolder

    rs = np.random.RandomState(0)
    for cls in ("cat", "dog"):
        d = tmp_path / "root" / cls
        d.mkdir(parents=True)
        for i in range(3):
            Image.fromarray((rs.rand(6, 6, 3) * 255).astype(np.uint8)) \
                .save(str(d / f"{i}.png"))
    ds = DatasetFolder(str(tmp_path / "root"))
    assert len(ds) == 6 and ds.classes == ["cat", "dog"]
    img, label = ds[0]
    assert img.shape == (6, 6, 3) and label == 0
    _, label_last = ds[5]
    assert label_last == 1

    flat = ImageFolder(str(tmp_path / "root"))
    assert len(flat) == 6
    (img,) = flat[0]
    assert img.shape == (6, 6, 3)


def test_audio_datasets_local(tmp_path):
    import wave

    from paddle_tpu.audio.datasets import ESC50, TESS

    # synthesize tiny wavs in both naming schemes
    def write_wav(path, n=160):
        with wave.open(str(path), "wb") as w:
            w.setnchannels(1)
            w.setsampwidth(2)
            w.setframerate(16000)
            w.writeframes((np.sin(np.arange(n)) * 3000)
                          .astype(np.int16).tobytes())

    tess_dir = tmp_path / "tess" / "OAF_angry"
    tess_dir.mkdir(parents=True)
    for i in range(4):
        write_wav(tess_dir / f"OAF_word_angry_{i}.wav")
    ds = TESS(mode="train", data_dir=str(tmp_path / "tess"))
    x, y = ds[0]
    assert y == 0 and x.dtype == np.float32 and len(ds) >= 2

    esc_dir = tmp_path / "esc50"
    esc_dir.mkdir()
    for fold in (1, 2):
        write_wav(esc_dir / f"{fold}-11111-A-{7 + fold}.wav")
    tr = ESC50(mode="train", split=1, data_dir=str(esc_dir))
    dv = ESC50(mode="dev", split=1, data_dir=str(esc_dir))
    assert len(tr) == 1 and len(dv) == 1
    _, y = dv[0]
    assert y == 8
    with pytest.raises(RuntimeError):
        TESS(download=True)
