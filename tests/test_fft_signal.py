"""fft / signal / linalg-namespace parity tests (reference:
`python/paddle/fft.py`, `python/paddle/signal.py`; SURVEY.md §2.6)."""
import numpy as np
import pytest

import paddle_tpu as P


def test_fft_roundtrip(rng):
    x = rng.randn(4, 64).astype(np.float32)
    t = P.to_tensor(x)
    s = P.fft.fft(t.astype("complex64"))
    back = P.fft.ifft(s)
    np.testing.assert_allclose(back.numpy().real, x, atol=1e-4)


def test_rfft_irfft_roundtrip(rng):
    x = rng.randn(4, 64).astype(np.float32)
    s = P.fft.rfft(P.to_tensor(x))
    assert list(s.shape) == [4, 33]
    back = P.fft.irfft(s)
    np.testing.assert_allclose(back.numpy(), x, atol=1e-4)


def test_fft2_matches_numpy(rng):
    x = rng.randn(3, 8, 8).astype(np.float32)
    out = P.fft.fft2(P.to_tensor(x).astype("complex64"))
    np.testing.assert_allclose(out.numpy(), np.fft.fft2(x), atol=1e-3)


def test_fftfreq_fftshift():
    f = P.fft.fftfreq(8, d=0.5)
    np.testing.assert_allclose(f.numpy(), np.fft.fftfreq(8, d=0.5), atol=1e-6)
    x = P.to_tensor(np.arange(8, dtype=np.float32))
    np.testing.assert_allclose(
        P.fft.fftshift(x).numpy(), np.fft.fftshift(np.arange(8)), atol=0)


def test_hfft2_matches_scipy(rng):
    import scipy.fft as sfft

    x = (rng.randn(4, 5) + 1j * rng.randn(4, 5)).astype(np.complex64)
    out = P.fft.hfft2(P.to_tensor(x))
    np.testing.assert_allclose(out.numpy(), sfft.hfft2(x), atol=1e-3)
    # ihfft2(hfft2(real)) recovers a real signal
    r = rng.randn(4, 8).astype(np.float32)
    spec = P.fft.ihfft2(P.to_tensor(r))
    back = P.fft.hfft2(spec, s=r.shape)
    np.testing.assert_allclose(back.numpy(), r, atol=1e-3)


def test_fft_grad(rng):
    x = P.to_tensor(rng.randn(16).astype(np.float32), stop_gradient=False)
    y = P.fft.rfft(x)
    loss = (y.abs() ** 2).sum()
    loss.backward()
    assert x.grad is not None and x.grad.shape == [16]


def test_frame_shapes(rng):
    x = P.to_tensor(rng.randn(2, 100).astype(np.float32))
    f = P.signal.frame(x, frame_length=10, hop_length=5)
    assert list(f.shape) == [2, 10, 19]


def test_overlap_add_inverts_frame_rect(rng):
    # hop == frame_length -> exact reconstruction
    x = rng.randn(2, 96).astype(np.float32)
    f = P.signal.frame(P.to_tensor(x), frame_length=16, hop_length=16)
    rec = P.signal.overlap_add(f, hop_length=16)
    np.testing.assert_allclose(rec.numpy(), x, atol=1e-5)


def test_stft_istft_roundtrip(rng):
    x = rng.randn(2, 400).astype(np.float32)
    win = np.hanning(64).astype(np.float32)
    spec = P.signal.stft(P.to_tensor(x), n_fft=64, hop_length=16,
                         window=P.to_tensor(win))
    assert list(spec.shape) == [2, 33, 26]
    rec = P.signal.istft(spec, n_fft=64, hop_length=16,
                         window=P.to_tensor(win), length=400)
    # edges lose energy; compare the interior
    np.testing.assert_allclose(rec.numpy()[:, 48:-48], x[:, 48:-48],
                               atol=1e-3)


def test_linalg_namespace(rng):
    a = rng.randn(5, 5).astype(np.float32)
    a = a @ a.T + 5 * np.eye(5, dtype=np.float32)
    t = P.to_tensor(a)
    assert float(P.linalg.cond(t).numpy()) > 0
    sv = P.linalg.svdvals(t)
    np.testing.assert_allclose(
        np.sort(sv.numpy()), np.sort(np.linalg.svd(a, compute_uv=False)),
        rtol=1e-3)
    np.testing.assert_allclose(
        P.linalg.vector_norm(t).numpy(), np.linalg.norm(a.ravel()), rtol=1e-4)
    np.testing.assert_allclose(
        P.linalg.matrix_norm(t).numpy(), np.linalg.norm(a, "fro"), rtol=1e-4)
    L = P.linalg.cholesky(t)
    np.testing.assert_allclose((L @ L.T).numpy(), a, atol=1e-3)


def test_ormqr(rng):
    a = rng.randn(4, 3).astype(np.float32)
    other = rng.randn(4, 2).astype(np.float32)
    import scipy.linalg as sla

    (h, tau), _ = sla.qr(a, mode="raw")
    out = P.linalg.ormqr(P.to_tensor(np.ascontiguousarray(h)),
                         P.to_tensor(tau.astype(np.float32)),
                         P.to_tensor(other))
    q = sla.qr(a)[0]
    np.testing.assert_allclose(out.numpy(), q @ other, atol=1e-3)


def test_regularizer_namespace():
    import paddle_tpu.regularizer as reg

    assert reg.L2Decay is P.optimizer.L2Decay
    assert issubclass(reg.L1DecayRegularizer, object)
