"""bench.py's probe-failure reuse path: capture-time records emit as
chip_session results, reconstructed records must declare themselves
(source=chip_session_reconstructed), stale records never emit. Pure
host-side logic — no device, no model build."""
import json
import time

import pytest


@pytest.fixture()
def bench_mod(tmp_path, monkeypatch):
    import bench

    monkeypatch.setattr(bench, "_GOOD_BENCH",
                        str(tmp_path / "last_good_bench.jsonl"))
    emitted = []
    monkeypatch.setattr(bench, "_emit", emitted.append)
    return bench, emitted, tmp_path / "last_good_bench.jsonl"


def _write(path, recs):
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def test_reuse_labels_reconstructed_vs_captured(bench_mod):
    bench, emitted, path = bench_mod
    now = time.time()
    _write(path, [
        {"metric": bench._HEADLINE, "value": 99972.6, "unit": "tokens/s",
         "vs_baseline": 0.84, "captured_at": now - 3600,
         "reconstructed": True, "provenance": "transcribed from PERF.md"},
        {"metric": "resnet50_train_images_per_sec_per_chip",
         "value": 1555.8, "unit": "images/s", "vs_baseline": 0.21,
         "captured_at": now - 1800},
    ])
    assert bench._emit_from_chip_session("probe-down") is True
    by_metric = {o["metric"]: o for o in emitted}
    head = by_metric[bench._HEADLINE]
    assert head["source"] == "chip_session_reconstructed"
    assert "reconstructed" in head["note"]
    assert head["provenance"] == "transcribed from PERF.md"
    sec = by_metric["resnet50_train_images_per_sec_per_chip"]
    assert sec["source"] == "chip_session"
    assert "reconstructed" not in sec["note"]
    # headline is the LAST line (driver contract)
    assert emitted[-1]["metric"] == bench._HEADLINE


def test_reuse_rejects_stale_and_degraded(bench_mod):
    bench, emitted, path = bench_mod
    now = time.time()
    _write(path, [
        {"metric": bench._HEADLINE, "value": 1.0, "unit": "tokens/s",
         "vs_baseline": 0.1,
         "captured_at": now - bench._MAX_REUSE_AGE_S - 60},
        {"metric": bench._HEADLINE, "value": 2.0, "unit": "tokens/s",
         "vs_baseline": 0.1, "captured_at": now - 60, "degraded": True},
    ])
    assert bench._emit_from_chip_session("probe-down") is False
    assert emitted == []


def test_reuse_prefers_freshest_headline(bench_mod):
    bench, emitted, path = bench_mod
    now = time.time()
    _write(path, [
        {"metric": bench._HEADLINE, "value": 1.0, "unit": "tokens/s",
         "vs_baseline": 0.1, "captured_at": now - 7200,
         "reconstructed": True},
        {"metric": bench._HEADLINE, "value": 2.0, "unit": "tokens/s",
         "vs_baseline": 0.2, "captured_at": now - 60},
    ])
    assert bench._emit_from_chip_session("x") is True
    # the fresh capture supersedes the reconstruction
    assert emitted[-1]["value"] == 2.0
    assert emitted[-1]["source"] == "chip_session"
