"""Quantized decode inside the engine (ISSUE 12).

Four layers of coverage, all CPU tier-1:

  * codec: the shared int8 codec in `ops/quant.py` is bit-pinned (the
    refactor out of `distributed/quantized.py` must never drift — the
    wire tier, the weight tier, and the KV pool share ONE definition);
  * kernel: the quantized-pool ragged paged-attention path (int8 pages
    + per-token-per-head scales) matches its reference and stays within
    the absmax/127 error envelope of the exact pool;
  * engine: per-tier determinism contracts — int8 weights bit-equal to
    `generate()` over the dequantized weights, int8 KV bit-stable
    run-to-run and leak-free under eviction, speculative decoding
    bit-equal to sequential greedy with ANY draft, and the tiers
    compose;
  * capacity/CI: the int8 pool admits ~2x the in-flight sequences of
    bf16 at a fixed `pool_hbm_mb` budget, the `gpt_quantized_decode_
    step` program holds its committed budget (PT406 dequant placement
    included), and the bench tier rows emit with the spec row beating
    the same-run sequential baseline.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as P
from test_engine import assert_drained  # noqa: E402
from paddle_tpu.inference.engine import (
    EngineConfig, InferenceEngine, Scheduler, Sequence,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _gpt(max_len=64, seed=0, hidden=32, layers=2, heads=4):
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(seed)
    cfg = GPTConfig(vocab_size=128, hidden_size=hidden,
                    num_layers=layers, num_heads=heads,
                    max_seq_len=max_len)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model


@pytest.fixture(scope="module")
def gpt_model():
    return _gpt()


@pytest.fixture(scope="module")
def draft_model():
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(7)
    cfg = GPTConfig(vocab_size=128, hidden_size=16, num_layers=1,
                    num_heads=2, max_seq_len=64)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model


@pytest.fixture(scope="module")
def prompts():
    rs = np.random.RandomState(0)
    return [rs.randint(0, 128, (n,)).astype(np.int32)
            for n in (3, 9, 17, 5, 12)]


@pytest.fixture(scope="module")
def refs(gpt_model, prompts):
    return [np.asarray(gpt_model.generate(
        P.to_tensor(p[None, :], "int32"), max_new_tokens=10)._value)[0]
        for p in prompts]


# ------------------------------ codec ------------------------------

def test_codec_bit_pinned_and_shared():
    """The refactored codec is pinned to the formulas the wire tier
    shipped with (PR 11) — and distributed/quantized re-exports the
    SAME objects, so the three int8 tiers cannot drift."""
    from paddle_tpu.distributed import quantized as DQ
    from paddle_tpu.ops import quant as QT

    # one definition, not a copy
    assert DQ.quantize_chunked is QT.quantize_chunked
    assert DQ.dequantize_chunked is QT.dequantize_chunked
    assert DQ.CHUNK == QT.CHUNK == 256

    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.randn(3, 200).astype(np.float32) * 5.0)
    q, scales, pad = QT.quantize_chunked(x, chunk=64)
    # hand-rolled reference of the shipped recipe
    flat = np.asarray(x, np.float32).reshape(-1)
    flat = np.concatenate([flat, np.zeros(pad, np.float32)])
    ch = flat.reshape(-1, 64)
    absmax = np.abs(ch).max(axis=1)
    want_scales = np.where(absmax > 0, absmax / 127.0, 1.0)
    want_q = np.clip(np.round(ch / want_scales[:, None]), -127, 127)
    assert np.array_equal(np.asarray(scales), want_scales.astype(
        np.float32))
    assert np.array_equal(np.asarray(q), want_q.astype(np.int8))
    rt = QT.dequantize_chunked(q, scales, x.shape, pad)
    assert np.array_equal(
        np.asarray(rt), (want_q * want_scales[:, None]).reshape(-1)[
            :x.size].reshape(x.shape).astype(np.float32))
    # zero chunk: scale clamps to 1, round-trips to exact zeros
    z, zs, _ = QT.quantize_chunked(jnp.zeros((64,)), chunk=64)
    assert float(zs[0]) == 1.0 and not np.asarray(z).any()


def test_codec_vector_roundtrip_error_bound():
    """Per-vector KV quantization round-trip error ≤ absmax/127 of the
    vector (the documented bound the KV-pool tier inherits)."""
    from paddle_tpu.ops import quant as QT

    rs = np.random.RandomState(4)
    x = jnp.asarray(rs.randn(6, 4, 32).astype(np.float32) * 3.0)
    q, s = QT.quantize_vectors(x)
    assert q.dtype == jnp.int8 and s.shape == (6, 4)
    rt = np.asarray(QT.dequantize_vectors(q, s))
    err = np.abs(rt - np.asarray(x))
    bound = np.abs(np.asarray(x)).max(axis=-1, keepdims=True) / 127.0
    assert (err <= bound + 1e-7).all(), err.max()


def test_codec_channel_roundtrip_matches_axes():
    from paddle_tpu.ops import quant as QT

    rs = np.random.RandomState(5)
    w = jnp.asarray(rs.randn(24, 16).astype(np.float32))
    q0, s0 = QT.quantize_channels(w, axis=0)   # [1, 16] scales
    q1, s1 = QT.quantize_channels(w, axis=1)   # [24, 1] scales
    assert s0.shape == (1, 16) and s1.shape == (24, 1)
    for q, s in ((q0, s0), (q1, s1)):
        rt = np.asarray(QT.dequantize_channels(q, s))
        bound = np.broadcast_to(np.asarray(s), w.shape) + 1e-7
        assert (np.abs(rt - np.asarray(w)) <= bound).all()


def test_collective_wire_tier_survives_refactor():
    """The EQuARX wire tier still produces the identical payload after
    the codec moved to ops/quant.py: qdq through distributed.quantized
    equals encode/decode through ops.quant."""
    from paddle_tpu.distributed import quantized as DQ
    from paddle_tpu.ops import quant as QT

    rs = np.random.RandomState(6)
    g = jnp.asarray(rs.randn(1000).astype(np.float32))
    out = DQ.qdq(g, "int8")
    q, s, pad = QT.quantize_chunked(g)
    want = QT.dequantize_chunked(q, s, g.shape, pad)
    assert np.array_equal(np.asarray(out), np.asarray(want))


# ------------------------------ kernel ------------------------------

def _quantize_pool(kf):
    from paddle_tpu.ops import quant as QT

    return QT.quantize_vectors(kf)


def test_paged_attention_quantized_matches_reference():
    """Int8 pools + scale tables through the kernel (interpret mode)
    == the dequantize-then-reference path, across page-boundary
    crossings and block_k splits."""
    from paddle_tpu.ops.pallas.paged_attention import (
        paged_attention, paged_attention_reference,
    )

    rs = np.random.RandomState(1)
    b, hq, hkv, d, ps, npool = 4, 8, 2, 16, 8, 12
    q = jnp.asarray(rs.randn(b, hq, d), jnp.float32)
    kf = jnp.asarray(rs.randn(npool, hkv, ps, d), jnp.float32)
    vf = jnp.asarray(rs.randn(npool, hkv, ps, d), jnp.float32)
    kq, ks = _quantize_pool(kf)
    vq, vs = _quantize_pool(vf)
    pt = jnp.asarray([[1, 2, 3, 4], [5, 6, 0, 0], [7, 0, 0, 0],
                      [8, 9, 10, 11]], jnp.int32)
    # boundary crossing (25), exact boundary (15), single token (0),
    # full table (31)
    pos = jnp.asarray([25, 15, 0, 31], jnp.int32)
    ref = paged_attention_reference(q, kq, vq, pt, pos,
                                    k_scales=ks, v_scales=vs)
    for block_k in (ps, 8):
        out = paged_attention(q, kq, vq, pt, pos, block_k=block_k,
                              interpret=True, k_scales=ks, v_scales=vs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("hq,hkv", [(8, 2), (4, 4)])
def test_paged_attention_quantized_rtol_vs_exact_pool(hq, hkv):
    """Quantized-pool attention stays within a small rtol of the exact
    (full-precision) pool — the per-vector absmax/127 error envelope
    barely moves a softmax-weighted average.  GQA (hq > hkv) included."""
    from paddle_tpu.ops.pallas.paged_attention import (
        paged_attention_reference,
    )

    rs = np.random.RandomState(2)
    b, d, ps, npool = 3, 16, 8, 10
    q = jnp.asarray(rs.randn(b, hq, d), jnp.float32)
    kf = jnp.asarray(rs.randn(npool, hkv, ps, d), jnp.float32)
    vf = jnp.asarray(rs.randn(npool, hkv, ps, d), jnp.float32)
    kq, ks = _quantize_pool(kf)
    vq, vs = _quantize_pool(vf)
    pt = jnp.asarray([[1, 2, 3], [4, 5, 0], [6, 7, 8]], jnp.int32)
    pos = jnp.asarray([19, 8, 23], jnp.int32)   # crossings + boundary
    exact = paged_attention_reference(q, kf, vf, pt, pos)
    quant = paged_attention_reference(q, kq, vq, pt, pos,
                                      k_scales=ks, v_scales=vs)
    np.testing.assert_allclose(np.asarray(quant), np.asarray(exact),
                               rtol=0.08, atol=0.08)


def test_paged_attention_available_int8_gate():
    from paddle_tpu.ops.pallas.paged_attention import (
        paged_attention_available,
    )

    # CPU/interpret never claims the compiled kernel; the int8 page-size
    # tile gate is still exercised via the pure-shape logic
    assert not paged_attention_available((8, 2, 32, 128), jnp.int8)
    assert not paged_attention_available((8, 2, 8, 128), jnp.int8)


# ------------------------------ engine: weight tier ------------------------------

def test_engine_int8_weights_bit_equal_to_dequantized_greedy(
        gpt_model, prompts):
    """The weight tier's determinism contract: quantization changes the
    MODEL once (at engine build); decode order changes nothing.  The
    engine's streams are bit-identical to sequential generate() run
    over the same dequantized weights."""
    eng = InferenceEngine(gpt_model, EngineConfig(
        page_size=8, max_slots=3, decode_chunk=2, max_seq_len=64,
        weight_precision="int8"))
    outs = eng.generate(prompts, max_new_tokens=10)
    with gpt_model.bind_state(eng.effective_params(), eng._buffers):
        want = [np.asarray(gpt_model.generate(
            P.to_tensor(p[None, :], "int32"),
            max_new_tokens=10)._value)[0] for p in prompts]
    for w, o in zip(want, outs):
        assert np.array_equal(w, o), (w.tolist(), o.tolist())
    assert_drained(eng)
    # every matmul weight (4 Linears x 2 layers + the tied lm head)
    # rides int8: the stored leaves are {"q": int8, "s": f32} dicts
    assert len(eng._wq_meta) == 9
    for name in eng._wq_meta:
        leaf = eng._params[name]
        assert leaf["q"].dtype == jnp.int8
        assert leaf["s"].dtype == jnp.float32


def test_engine_bf16_weight_tier_runs(gpt_model, prompts):
    eng = InferenceEngine(gpt_model, EngineConfig(
        page_size=8, max_slots=2, max_seq_len=64,
        weight_precision="bf16"))
    outs = eng.generate(prompts[:2], max_new_tokens=6)
    with gpt_model.bind_state(eng.effective_params(), eng._buffers):
        want = [np.asarray(gpt_model.generate(
            P.to_tensor(p[None, :], "int32"),
            max_new_tokens=6)._value)[0] for p in prompts[:2]]
    for w, o in zip(want, outs):
        assert np.array_equal(w, o)


def test_weight_precision_knob_validates():
    with pytest.raises(ValueError):
        EngineConfig(weight_precision="int7")
    with pytest.raises(ValueError):
        EngineConfig(kv_precision="bf16")   # kv tier is int8-or-exact
    assert EngineConfig(weight_precision="f32").weight_precision is None


# ------------------------------ engine: kv tier ------------------------------

def test_engine_kv_int8_bit_stable_and_close_to_exact(gpt_model,
                                                      prompts, refs):
    """Quantized-KV contract: NOT bit-equal to the bf16 pool (documented
    rtol instead), but bit-stable run-to-run, leak-free, and the early
    tokens (short cache, tiny accumulated error) match greedy."""
    def run():
        eng = InferenceEngine(gpt_model, EngineConfig(
            page_size=8, max_slots=3, decode_chunk=2, max_seq_len=64,
            kv_precision="int8"))
        outs = eng.generate(prompts, max_new_tokens=10)
        assert_drained(eng)
        return outs

    o1, o2 = run(), run()
    for a, b in zip(o1, o2):
        assert np.array_equal(a, b)      # bit-stable run-to-run
    # the prompt prefix is identity; the first generated token comes off
    # the DENSE prefill (quantization touches decode steps only after
    # packing), so it must match greedy exactly
    for r, o, p in zip(refs, o1, prompts):
        assert np.array_equal(r[:p.size + 1], o[:p.size + 1])


def test_engine_kv_int8_eviction_recompute_deterministic(gpt_model,
                                                         prompts):
    """Recompute eviction under the quantized pool: re-prefill replays
    the same dense-prefill→quantize-pack pipeline, so a rerun of the
    same workload is bit-identical and nothing leaks."""
    def run():
        eng = InferenceEngine(gpt_model, EngineConfig(
            page_size=4, max_slots=2, num_pages=10, max_seq_len=64,
            kv_precision="int8"))
        outs = eng.generate(prompts, max_new_tokens=10)
        assert_drained(eng)
        return outs

    o1, o2 = run(), run()
    for a, b in zip(o1, o2):
        assert np.array_equal(a, b)


def test_engine_kv_int8_llama_gqa():
    """GQA (llama, kv heads < heads) through the quantized pool: the
    grouped kernel path with per-kv-head scale vectors — bit-stable and
    leak-free."""
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    P.seed(3)
    cfg = LlamaConfig(vocab_size=128, hidden_size=32, num_layers=2,
                      num_heads=4, num_kv_heads=2, max_seq_len=64,
                      ffn_hidden=64)
    model = LlamaForCausalLM(cfg)
    model.eval()
    rs = np.random.RandomState(3)
    prompts = [rs.randint(0, 128, (n,)).astype(np.int32)
               for n in (4, 11, 7)]

    def run():
        eng = InferenceEngine(model, EngineConfig(
            page_size=8, max_slots=2, max_seq_len=64,
            kv_precision="int8"))
        outs = eng.generate(prompts, max_new_tokens=8)
        assert_drained(eng)
        return outs

    o1, o2 = run(), run()
    for a, b in zip(o1, o2):
        assert np.array_equal(a, b)


# ------------------------------ engine: speculative decoding ------------------------------

def test_spec_decode_bit_equal_to_greedy_random_draft(
        gpt_model, draft_model, prompts, refs):
    """The spec contract: with ANY draft (here: an unrelated random
    model, acceptance ~0) the committed stream is bit-identical to
    sequential greedy — the draft only moves throughput, never
    tokens."""
    eng = InferenceEngine(gpt_model, EngineConfig(
        page_size=8, max_slots=3, max_seq_len=64, spec_tokens=3),
        draft_model=draft_model)
    outs = eng.generate(prompts, max_new_tokens=10)
    for r, o in zip(refs, outs):
        assert np.array_equal(r, o), (r.tolist(), o.tolist())
    assert_drained(eng)


def test_spec_decode_bit_equal_with_agreeing_draft(prompts):
    """With a fully-agreeing draft (the target's extra layer zeroed to
    an exact identity) every pass accepts all k proposals — and the
    stream STILL equals sequential greedy bit-for-bit."""
    from paddle_tpu.observability import metrics

    import paddle_tpu.observability as obs

    model = _gpt(hidden=32, layers=2)
    draft = _gpt(hidden=32, layers=1, seed=1)
    tstate = {n: p for n, p in model.named_parameters()}
    for name, p in draft.named_parameters():
        p.set_value(tstate[name]._value)
    blk = model.gpt.h[1]
    for lin in (blk.attn.out_proj, blk.mlp.down_proj):
        lin.weight.set_value(np.zeros(lin.weight.shape, np.float32))
        lin.bias.set_value(np.zeros(lin.bias.shape, np.float32))
    refs = [np.asarray(model.generate(
        P.to_tensor(p[None, :], "int32"), max_new_tokens=10)._value)[0]
        for p in prompts]
    obs.attach(crash_hook=False)
    try:
        metrics.reset()
        obs.attach(crash_hook=False)
        eng = InferenceEngine(model, EngineConfig(
            page_size=8, max_slots=3, max_seq_len=64, spec_tokens=3),
            draft_model=draft)
        outs = eng.generate(prompts, max_new_tokens=10)
        for r, o in zip(refs, outs):
            assert np.array_equal(r, o)
        snap = metrics.snapshot()["counters"]
        acc = snap.get("engine.spec_decode{result=accepted}", 0)
        rej = snap.get("engine.spec_decode{result=rejected}", 0)
        # agreeing draft: acceptance is (near) total.  Tail passes at a
        # sequence's finish line commit fewer than k+1 tokens, so a few
        # "rejections" are length-clamps, not disagreements.
        assert acc > 0 and acc >= rej, (acc, rej)
    finally:
        obs.detach()


def test_spec_decode_eos_and_slot_reuse(gpt_model, draft_model,
                                        prompts):
    eos = 7
    refs = [np.asarray(gpt_model.generate(
        P.to_tensor(p[None, :], "int32"), max_new_tokens=10,
        eos_token_id=eos)._value)[0] for p in prompts]
    eng = InferenceEngine(gpt_model, EngineConfig(
        page_size=8, max_slots=2, max_seq_len=64, spec_tokens=4),
        draft_model=draft_model)
    outs = eng.generate(prompts, max_new_tokens=10, eos_token_id=eos)
    for r, o in zip(refs, outs):
        assert np.array_equal(r, o)
    assert_drained(eng)


def test_spec_decode_eviction_recompute(gpt_model, draft_model,
                                        prompts, refs):
    """Pool pressure under spec decoding: pages for the whole k+1 pass
    are provisioned, the youngest evicts, and recompute continues the
    greedy stream exactly."""
    eng = InferenceEngine(gpt_model, EngineConfig(
        page_size=4, max_slots=2, num_pages=10, max_seq_len=64,
        spec_tokens=3), draft_model=draft_model)
    outs = eng.generate(prompts, max_new_tokens=10)
    for r, o in zip(refs, outs):
        assert np.array_equal(r, o)
    assert_drained(eng)


def test_spec_decode_table_filling_sequence_exact(gpt_model,
                                                  draft_model):
    """Regression (review finding): a sequence whose prompt+max_new
    fills its page table EXACTLY, decoded with spec passes that
    overshoot the finish line.  Unmasked overflow rows used to clamp
    the page-table gather onto the row's LAST real page and overwrite
    a live committed position — which the same pass's valid rows then
    attended (the batched pass writes all rows before any row
    attends), corrupting the final tokens.  Overflow rows now mask to
    the scratch page, and the stream must stay bit-equal to greedy."""
    rs = np.random.RandomState(11)
    prompts = [rs.randint(0, 128, (4,)).astype(np.int32),
               rs.randint(0, 128, (3,)).astype(np.int32)]
    refs = [np.asarray(gpt_model.generate(
        P.to_tensor(p[None, :], "int32"),
        max_new_tokens=16 - p.size)._value)[0] for p in prompts]
    eng = InferenceEngine(gpt_model, EngineConfig(
        page_size=4, max_slots=2, max_seq_len=16, spec_tokens=4),
        draft_model=draft_model)
    outs = [eng.generate([p], max_new_tokens=16 - p.size)[0]
            for p in prompts]
    for r, o in zip(refs, outs):
        assert np.array_equal(r, o), (r.tolist(), o.tolist())
    assert_drained(eng)


def test_spec_requires_draft_and_vocab_match(gpt_model, draft_model):
    with pytest.raises(ValueError):
        InferenceEngine(gpt_model, EngineConfig(
            page_size=8, max_seq_len=64, spec_tokens=2))
    with pytest.raises(ValueError):
        InferenceEngine(gpt_model, EngineConfig(
            page_size=8, max_seq_len=64), draft_model=draft_model)
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(9)
    other = GPTForCausalLM(GPTConfig(
        vocab_size=64, hidden_size=16, num_layers=1, num_heads=2,
        max_seq_len=64))
    with pytest.raises(ValueError):
        InferenceEngine(gpt_model, EngineConfig(
            page_size=8, max_seq_len=64, spec_tokens=2),
            draft_model=other)


def test_all_tiers_compose_bit_stable(gpt_model, draft_model, prompts):
    """int8 weights + int8 KV + spec decoding in ONE engine: runs,
    leak-free, and bit-stable across runs (the composed determinism
    contract — kv int8 forfeits bit-equality to greedy, never
    stability)."""
    def run():
        eng = InferenceEngine(gpt_model, EngineConfig(
            page_size=8, max_slots=3, max_seq_len=64, spec_tokens=3,
            weight_precision="int8", kv_precision="int8"),
            draft_model=draft_model)
        outs = eng.generate(prompts, max_new_tokens=10)
        assert_drained(eng)
        return outs

    o1, o2 = run(), run()
    for a, b in zip(o1, o2):
        assert np.array_equal(a, b)


def test_spec_plus_int8_weights_bit_equal_to_dequantized_greedy(
        gpt_model, draft_model, prompts):
    eng = InferenceEngine(gpt_model, EngineConfig(
        page_size=8, max_slots=3, max_seq_len=64, spec_tokens=3,
        weight_precision="int8"), draft_model=draft_model)
    outs = eng.generate(prompts, max_new_tokens=10)
    with gpt_model.bind_state(eng.effective_params(), eng._buffers):
        want = [np.asarray(gpt_model.generate(
            P.to_tensor(p[None, :], "int32"),
            max_new_tokens=10)._value)[0] for p in prompts]
    for w, o in zip(want, outs):
        assert np.array_equal(w, o)


# ------------------------------ capacity ------------------------------

def test_kv_int8_doubles_effective_capacity():
    """At a FIXED pool HBM budget, the int8 pool admits ~2x the
    in-flight sequences of the bf16 pool before running out of pages —
    the capacity claim, asserted at the scheduler."""
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=128, num_layers=2,
                    num_heads=4, max_seq_len=64)
    model = GPTForCausalLM(cfg)
    model.to(dtype="bfloat16")
    model.eval()
    budget_mb = 0.125

    def admitted(kv_precision):
        eng = InferenceEngine(model, EngineConfig(
            page_size=8, max_slots=16, max_seq_len=64,
            pool_hbm_mb=budget_mb, kv_precision=kv_precision))
        for i in range(16):
            eng.scheduler.submit(Sequence(
                np.arange(1, 9, dtype=np.int32), 8,
                request_id=f"s{i}"))
        out = eng.scheduler.schedule(1)
        return len(out.prefills), eng.config.num_pages

    n_bf16, pages_bf16 = admitted(None)
    n_int8, pages_int8 = admitted("int8")
    # int8 pages cost half the KV bytes + a small f32 scale sidecar
    assert pages_int8 / pages_bf16 >= 1.7, (pages_int8, pages_bf16)
    assert n_int8 / n_bf16 >= 1.7, (n_int8, n_bf16)
    assert n_bf16 >= 1   # the budget is real on both sides


def test_stats_and_ready_carry_tier_info(gpt_model):
    eng = InferenceEngine(gpt_model, EngineConfig(
        page_size=8, max_slots=2, max_seq_len=64,
        weight_precision="int8", kv_precision="int8"))
    st = eng.stats()
    assert st["weight_precision"] == "int8"
    assert st["kv_precision"] == "int8"
    assert st["spec_tokens"] == 0
    assert st["page_bytes"] > 0


# ------------------------------ CI / bench satellites ------------------------------

def test_perf_smoke_quantized_decode_within_budget():
    """The quantized decode program audits cleanly and holds its
    committed budget — including PT406: every int8 dequant traced
    INSIDE the scan body (none hoisted, none missing)."""
    from paddle_tpu import analysis as A
    from paddle_tpu.analysis import perf_audit

    violations, metrics = perf_audit.audit_perf(
        programs=("quantized_decode_step",), repo_root=REPO)
    assert not [v for v in violations if v.rule == "PT400"], \
        A.render_report(violations)
    m = metrics["gpt_quantized_decode_step"]
    assert m["pt406_dequant_hoisted_count"] == 0
    assert m["pt406_dequant_deficit"] == 0
    assert m["pt406_dequant_in_loop_count"] >= 7
    assert m["pt405_loop_host_syncs"] == 0
    budget = A.load_budget(
        os.path.join(REPO, "tools", "perf_budget.json"))
    reg, _imp, _ = A.diff_against_budget(metrics, budget)
    assert reg == [], A.render_budget_diff(reg, [])


def test_bench_quantized_decode_emits_and_spec_beats_sequential():
    """The tier bench rows: all three emit (degraded-marked on the CPU
    proxy) and the spec-decode row beats the same-run sequential
    baseline — the ISSUE 12 acceptance comparison, measured
    in-process."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    rows = bench._bench_quantized_decode(True)
    by_metric = {r["metric"]: r for r in rows}
    assert set(by_metric) == {
        "serving_decode_int8w_tokens_per_sec",
        "serving_decode_kvint8_tokens_per_sec",
        "serving_decode_spec_tokens_per_sec"}
    for r in rows:
        assert r["value"] > 0 and r["degraded"]
        assert r["bf16_engine_tokens_per_sec"] > 0
        assert r["sequential_tokens_per_sec"] > 0
    spec = by_metric["serving_decode_spec_tokens_per_sec"]
    assert spec["speedup_vs_sequential"] > 1.0, spec
    assert spec["tokens_per_pass"] > 1.0, spec


def test_perf_gate_quantized_metric_round_trip(tmp_path):
    """The new tier metrics are gateable: --update registers the floor,
    an equal rerun passes, a drop beyond tolerance exits 2."""
    gate = os.path.join(REPO, "tools", "perf_gate.py")
    base = tmp_path / "baseline.jsonl"
    res = tmp_path / "results.json"
    row = {"metric": "serving_decode_spec_tokens_per_sec",
           "value": 2000.0, "unit": "tokens/s",
           "sequential_tokens_per_sec": 900.0,
           "speedup_vs_sequential": 2.2}
    base.write_text(json.dumps(row) + "\n")

    def run(value):
        res.write_text(json.dumps(dict(row, value=value)) + "\n")
        return subprocess.run(
            [sys.executable, gate, str(res), "--baseline", str(base),
             "--static-budget", ""],
            capture_output=True, text=True)

    assert run(2000.0).returncode == 0
    assert run(1900.0).returncode == 0       # within 10% tolerance
    p = run(900.0)
    assert p.returncode == 2 and "regression" in p.stderr
    res.write_text(json.dumps(dict(row, value=2600.0)) + "\n")
    p = subprocess.run(
        [sys.executable, gate, str(res), "--baseline", str(base),
         "--static-budget", "", "--update"],
        capture_output=True, text=True)
    assert p.returncode == 0 and "updated" in p.stdout
    assert run(2500.0).returncode == 0
    assert run(2000.0).returncode == 2


def test_spec_counters_and_tier_gauges_in_schema():
    import paddle_tpu.observability as obs
    from paddle_tpu.observability import metrics

    obs.attach(crash_hook=False)
    try:
        metrics.reset()
        obs.attach(crash_hook=False)
        snap = metrics.snapshot()
        c = snap["counters"]
        assert c.get("engine.spec_decode{result=accepted}") == 0
        assert c.get("engine.spec_decode{result=rejected}") == 0
        g = snap["gauges"]
        assert g.get("engine.spec_tokens") == 0
        assert g.get("engine.weight_precision{precision=int8}") == 0
        assert g.get("paged.pool_precision{precision=int8}") == 0
    finally:
        obs.detach()
