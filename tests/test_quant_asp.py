"""Quantization (QAT/PTQ) + ASP 2:4 sparsity workflows.

Parity model: reference `test/quantization/` (QAT swap + convert) and
`test/asp/` (mask creation, prune_model, optimizer guarantee).
"""
import numpy as np
import pytest

import paddle_tpu as P
import paddle_tpu.nn as nn
from paddle_tpu import quantization as Q
from paddle_tpu.incubate import asp


def _model():
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


def test_qat_swaps_and_trains():
    m = _model()
    cfg = Q.QuantConfig(
        activation=Q.quanters.FakeQuanterWithAbsMaxObserver,
        weight=Q.quanters.FakeQuanterChannelWiseAbsMax)
    qat = Q.QAT(cfg)
    qm = qat.quantize(m, inplace=False)
    kinds = [type(l).__name__ for l in qm.sublayers()]
    assert "QuantedLinear" in kinds
    x = P.to_tensor(np.random.RandomState(0).rand(4, 8).astype(np.float32))
    out = qm(x)
    assert out.shape == [4, 4]
    loss = P.mean(P.square(out))
    loss.backward()
    params = [p for p in qm.parameters() if not p.stop_gradient]
    assert any(p.grad is not None for p in params)
    # quantized forward stays near float forward (8-bit)
    ref = m(x)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=0.1)


def test_qat_type_config_targets_only_linear():
    m = _model()
    cfg = Q.QuantConfig(activation=None, weight=None)
    cfg.add_type_config(nn.Linear,
                        weight=Q.quanters.FakeQuanterChannelWiseAbsMax)
    qm = Q.QAT(cfg).quantize(m)
    assert sum(isinstance(l, Q.QuantedLinear) for l in qm.sublayers()) == 2


def test_ptq_observe_convert():
    m = _model()
    cfg = Q.QuantConfig(activation=Q.observers.AbsmaxObserver, weight=None)
    ptq = Q.PTQ(cfg)
    qm = ptq.quantize(m)
    rng = np.random.RandomState(1)
    for _ in range(3):  # calibration
        qm(P.to_tensor(rng.rand(4, 8).astype(np.float32)))
    frozen = ptq.convert(qm)
    x = P.to_tensor(rng.rand(4, 8).astype(np.float32))
    out = frozen(x)
    np.testing.assert_allclose(out.numpy(), m(x).numpy(), atol=0.2)


def test_asp_mask_and_density():
    w = P.to_tensor(np.random.RandomState(2).randn(8, 8).astype(np.float32))
    mask = asp.create_mask(w, n=2, m=4)
    masked = w.numpy() * mask.numpy()
    assert asp.check_sparsity(P.to_tensor(masked), n=2, m=4)
    assert abs(asp.calculate_density(P.to_tensor(masked)) - 0.5) < 1e-6


def test_asp_prune_model_and_decorate():
    m = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 4))
    asp.prune_model(m, n=2, m=4)
    assert asp.check_sparsity(m[0].weight, n=2, m=4)
    opt = asp.decorate(P.optimizer.SGD(
        0.1, parameters=list(m.parameters())))
    x = P.to_tensor(np.random.RandomState(3).rand(4, 8).astype(np.float32))
    loss = P.mean(P.square(m(x)))
    loss.backward()
    opt.step()
    # sparsity survives the update
    assert asp.check_sparsity(m[0].weight, n=2, m=4)
    assert asp.check_sparsity(m[2].weight, n=2, m=4)


# ---------------- nn.quant weight-only / LLM.int8 serving path -----------

def test_weight_quantize_roundtrip_int8_int4():
    from paddle_tpu.nn import quant as Q

    rs = np.random.RandomState(0)
    w = P.to_tensor(rs.randn(64, 32).astype(np.float32))
    for algo, tol in [("weight_only_int8", 0.02), ("weight_only_int4", 0.2)]:
        qv, scale = Q.weight_quantize(w, algo=algo)
        packed_in = 32 if algo.endswith("int8") else 32
        assert list(qv.shape) == ([32, 64] if algo.endswith("int8")
                                  else [32, 32])
        assert str(qv.dtype) == "int8" and list(scale.shape) == [32]
        back = Q.weight_dequantize(qv, scale, algo=algo)
        err = np.max(np.abs(back.numpy() - w.numpy()))
        assert err < tol * np.max(np.abs(w.numpy())), (algo, err)


def test_weight_quantize_grouped():
    from paddle_tpu.nn import quant as Q

    rs = np.random.RandomState(1)
    w = P.to_tensor(rs.randn(128, 16).astype(np.float32))
    qv, scale = Q.weight_quantize(w, group_size=64)
    assert list(scale.shape) == [2, 16]
    back = Q.weight_dequantize(qv, scale, group_size=64)
    err = np.max(np.abs(back.numpy() - w.numpy()))
    assert err < 0.02 * np.max(np.abs(w.numpy()))


def test_weight_only_linear_matches_float():
    from paddle_tpu.nn import quant as Q

    rs = np.random.RandomState(2)
    x = P.to_tensor(rs.randn(4, 64).astype(np.float32))
    w = P.to_tensor(rs.randn(64, 16).astype(np.float32))
    b = P.to_tensor(rs.randn(16).astype(np.float32))
    ref = (x.numpy() @ w.numpy()) + b.numpy()
    qv, scale = Q.weight_quantize(w)
    y = Q.weight_only_linear(x, qv, bias=b, weight_scale=scale)
    rel = np.max(np.abs(y.numpy() - ref)) / np.max(np.abs(ref))
    assert rel < 0.03, rel


def test_weight_only_linear_layer_from_linear():
    from paddle_tpu.nn import quant as Q

    P.seed(0)
    lin = P.nn.Linear(32, 8)
    wol = Q.WeightOnlyLinear.from_linear(lin)
    x = P.to_tensor(np.random.RandomState(3).randn(5, 32).astype(np.float32))
    ref = lin(x).numpy()
    got = wol(x).numpy()
    rel = np.max(np.abs(got - ref)) / max(np.max(np.abs(ref)), 1e-6)
    assert rel < 0.05, rel
    # int8 storage halves+ the weight bytes
    assert str(wol.quant_weight.dtype) == "int8"


def test_llm_int8_linear_outlier_decomposition():
    from paddle_tpu.nn import quant as Q

    rs = np.random.RandomState(4)
    x = rs.randn(8, 64).astype(np.float32)
    x[:, 7] *= 40.0   # one outlier channel far past threshold
    w = rs.randn(64, 16).astype(np.float32)
    ref = x @ w
    qv, scale = Q.weight_quantize(P.to_tensor(w))
    y = Q.llm_int8_linear(P.to_tensor(x), qv, weight_scale=scale,
                          threshold=6.0)
    rel = np.max(np.abs(y.numpy() - ref)) / np.max(np.abs(ref))
    assert rel < 0.03, rel
    # naive full-int8 (threshold huge -> no outlier split) must be worse
    y_naive = Q.llm_int8_linear(P.to_tensor(x), qv, weight_scale=scale,
                                threshold=1e9)
    rel_naive = np.max(np.abs(y_naive.numpy() - ref)) / np.max(np.abs(ref))
    assert rel_naive > rel


def test_int4_odd_in_features_raises():
    from paddle_tpu.nn import quant as Q

    w = P.to_tensor(np.random.RandomState(0).randn(33, 8).astype(np.float32))
    import pytest

    with pytest.raises(ValueError, match="even in_features"):
        Q.weight_quantize(w, algo="weight_only_int4")
    with pytest.raises(ValueError, match="even in_features"):
        Q.WeightOnlyLinear(33, 8, weight_dtype="int4")


@pytest.mark.slow
def test_weight_only_quantize_model_generates():
    """End-to-end serving quantization: swap a GPT's linears for int8
    weight-only layers and generate; outputs stay close to float greedy."""
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.quantization import weight_only_quantize

    P.seed(9)
    cfg = GPTConfig(vocab_size=61, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=64, use_rope=True)
    model = GPTForCausalLM(cfg)
    model.eval()
    qmodel = weight_only_quantize(model, weight_dtype="int8")
    assert qmodel is not model  # deepcopy by default
    from paddle_tpu.nn.quant import WeightOnlyLinear

    n_swapped = sum(1 for _, m in qmodel.named_sublayers()
                    if isinstance(m, WeightOnlyLinear))
    assert n_swapped >= 2 * cfg.num_layers  # qkv + out per block at least

    prompt = P.to_tensor(np.array([[1, 2, 3, 4]]), "int32")
    ref_logits = model(prompt).numpy()
    q_logits = qmodel(prompt).numpy()
    rel = np.max(np.abs(q_logits - ref_logits)) / np.max(np.abs(ref_logits))
    assert rel < 0.1, rel
    out = qmodel.generate(prompt, max_new_tokens=4)
    assert np.asarray(out._value).shape == (1, 8)


def test_nn_quant_surface_complete_vs_reference():
    """Every name in the reference nn.quant __all__ resolves here."""
    import ast
    import os

    import pytest as _pytest

    ref = "/root/reference/python/paddle/nn/quant/__init__.py"
    if not os.path.exists(ref):
        _pytest.skip("reference not mounted")
    names = []
    for node in ast.walk(ast.parse(open(ref).read())):
        if isinstance(node, ast.Assign):
            for tg in node.targets:
                if isinstance(tg, ast.Name) and tg.id == "__all__":
                    names = [e.value for e in node.value.elts
                             if isinstance(e, ast.Constant)]
    from paddle_tpu.nn import quant as Q

    missing = [n for n in names if not hasattr(Q, n)]
    assert not missing, f"nn.quant missing: {missing}"


def test_stub_identity_and_quanter_swap():
    from paddle_tpu.nn.quant import Stub
    from paddle_tpu.quantization import quanters

    x = P.to_tensor(np.linspace(-1, 1, 8).astype(np.float32))
    s = Stub()
    np.testing.assert_array_equal(s(x).numpy(), x.numpy())  # identity
    s2 = Stub(quanters.FakeQuanterWithAbsMaxObserver(moving_rate=0.9))
    s2.train()
    out = s2(x)
    assert out.shape == x.shape and np.isfinite(out.numpy()).all()


def test_qat_swaps_bare_stub_for_quanter():
    from paddle_tpu.nn.quant import Stub
    from paddle_tpu.quantization import QAT, QuantConfig, quanters

    class M(P.nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = P.nn.Linear(4, 4)
            self.pre = Stub()

        def forward(self, x):
            return self.lin(self.pre(x))

    cfg = QuantConfig(
        activation=quanters.FakeQuanterWithAbsMaxObserver(moving_rate=0.9),
        weight=quanters.FakeQuanterChannelWiseAbsMax())
    q = QAT(cfg).quantize(M())
    assert q.pre._observer is not None  # bare stub got the global quanter
    q.train()
    out = q(P.to_tensor(np.ones((2, 4), np.float32)))
    assert np.isfinite(out.numpy()).all()


def test_stub_factory_instantiates_once_and_keeps_state():
    from paddle_tpu.nn.quant import Stub
    from paddle_tpu.quantization import quanter_factory, quanters

    s = Stub(quanter_factory(quanters.FakeQuanterWithAbsMaxObserver,
                             moving_rate=0.5))
    s.train()
    q1 = s._observer
    s(P.to_tensor(np.ones((4,), np.float32)))
    s(P.to_tensor(np.full((4,), 2.0, np.float32)))
    assert s._observer is q1          # same instance across calls
    assert q1._initialized            # EMA state persisted


def test_ptq_coerces_self_configured_stub_to_observer():
    from paddle_tpu.nn.quant import Stub
    from paddle_tpu.quantization import (
        PTQ, BaseObserver, QuantConfig, observers, quanters,
    )

    class M(P.nn.Layer):
        def __init__(self):
            super().__init__()
            self.s = Stub(quanters.FakeQuanterWithAbsMaxObserver())

        def forward(self, x):
            return self.s(x)

    cfg = QuantConfig(activation=observers.AbsmaxObserver())
    q = PTQ(cfg).quantize(M())
    assert isinstance(q.s._observer, BaseObserver)
