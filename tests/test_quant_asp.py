"""Quantization (QAT/PTQ) + ASP 2:4 sparsity workflows.

Parity model: reference `test/quantization/` (QAT swap + convert) and
`test/asp/` (mask creation, prune_model, optimizer guarantee).
"""
import numpy as np

import paddle_tpu as P
import paddle_tpu.nn as nn
from paddle_tpu import quantization as Q
from paddle_tpu.incubate import asp


def _model():
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


def test_qat_swaps_and_trains():
    m = _model()
    cfg = Q.QuantConfig(
        activation=Q.quanters.FakeQuanterWithAbsMaxObserver,
        weight=Q.quanters.FakeQuanterChannelWiseAbsMax)
    qat = Q.QAT(cfg)
    qm = qat.quantize(m, inplace=False)
    kinds = [type(l).__name__ for l in qm.sublayers()]
    assert "QuantedLinear" in kinds
    x = P.to_tensor(np.random.RandomState(0).rand(4, 8).astype(np.float32))
    out = qm(x)
    assert out.shape == [4, 4]
    loss = P.mean(P.square(out))
    loss.backward()
    params = [p for p in qm.parameters() if not p.stop_gradient]
    assert any(p.grad is not None for p in params)
    # quantized forward stays near float forward (8-bit)
    ref = m(x)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=0.1)


def test_qat_type_config_targets_only_linear():
    m = _model()
    cfg = Q.QuantConfig(activation=None, weight=None)
    cfg.add_type_config(nn.Linear,
                        weight=Q.quanters.FakeQuanterChannelWiseAbsMax)
    qm = Q.QAT(cfg).quantize(m)
    assert sum(isinstance(l, Q.QuantedLinear) for l in qm.sublayers()) == 2


def test_ptq_observe_convert():
    m = _model()
    cfg = Q.QuantConfig(activation=Q.observers.AbsmaxObserver, weight=None)
    ptq = Q.PTQ(cfg)
    qm = ptq.quantize(m)
    rng = np.random.RandomState(1)
    for _ in range(3):  # calibration
        qm(P.to_tensor(rng.rand(4, 8).astype(np.float32)))
    frozen = ptq.convert(qm)
    x = P.to_tensor(rng.rand(4, 8).astype(np.float32))
    out = frozen(x)
    np.testing.assert_allclose(out.numpy(), m(x).numpy(), atol=0.2)


def test_asp_mask_and_density():
    w = P.to_tensor(np.random.RandomState(2).randn(8, 8).astype(np.float32))
    mask = asp.create_mask(w, n=2, m=4)
    masked = w.numpy() * mask.numpy()
    assert asp.check_sparsity(P.to_tensor(masked), n=2, m=4)
    assert abs(asp.calculate_density(P.to_tensor(masked)) - 0.5) < 1e-6


def test_asp_prune_model_and_decorate():
    m = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 4))
    asp.prune_model(m, n=2, m=4)
    assert asp.check_sparsity(m[0].weight, n=2, m=4)
    opt = asp.decorate(P.optimizer.SGD(
        0.1, parameters=list(m.parameters())))
    x = P.to_tensor(np.random.RandomState(3).rand(4, 8).astype(np.float32))
    loss = P.mean(P.square(m(x)))
    loss.backward()
    opt.step()
    # sparsity survives the update
    assert asp.check_sparsity(m[0].weight, n=2, m=4)
    assert asp.check_sparsity(m[2].weight, n=2, m=4)
