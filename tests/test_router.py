"""Fleet-serving tests (ISSUE 9): the admission-aware replica router
(least-loaded pick, heartbeat ejection, breaker skip, same-request-id
failover, stream failover semantics, fleet-level sheds), the
`ReplicaFleet` drain-before-SIGTERM ordering, the `/ready` payload
extension, the client's defensive Retry-After parse, and one real
multi-process kill/relaunch e2e.  Unit tests drive the router state
machine with fake replicas and an injectable transport/clock — no
sockets, no sleeps; the seeded 3-replica kill matrix lives under the
`chaos` marker (tools/chaos_check.py --scenario fleet).
"""
import io
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from paddle_tpu import observability as obs
from paddle_tpu.inference.fleet import (
    EchoPredictor, ReplicaFleet, ToyEngine, toy_token,
)
from paddle_tpu.inference.router import (
    HTTPTransport, ReplicaUnreachable, Router,
)
from paddle_tpu.inference.serving import (
    InferenceClient, InferenceServer, StreamInterrupted,
)
from paddle_tpu.observability import metrics, request_trace as rtrace
from paddle_tpu.resilience.overload import ShedError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _telemetry():
    metrics.reset()
    obs.attach(crash_hook=False)
    yield
    obs.detach()
    metrics.reset()


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# --------------------------------------------------------------------------
# fake replica plane: in-memory transport, no sockets
# --------------------------------------------------------------------------

class _FakeStream:
    def __init__(self, status, lines, die_after=None):
        self.status = status
        self.headers = {}
        self._lines = list(lines)
        self._die_after = die_after
        self.closed = False

    def lines(self):
        for i, line in enumerate(self._lines):
            if self._die_after is not None and i >= self._die_after:
                raise ConnectionResetError("replica died mid-stream")
            yield line
        if self._die_after is not None:
            raise ConnectionResetError("replica died mid-stream")

    def read_body(self):
        return b"".join(self._lines)

    def close(self):
        self.closed = True


class _FakeReplica:
    """In-memory stand-in: /ready signals + scripted /predict and
    /generate behavior, with a log of every request's headers."""

    def __init__(self, inflight=0, queued=0, limit=4, engine=None,
                 ready=True, reason="ok"):
        self.inflight = inflight
        self.queued = queued
        self.limit = limit
        self.engine = engine            # dict or None
        self.ready = ready
        self.reason = reason
        self.dead = False               # transport-level failure
        self.fail_next_predicts = 0     # fail N forwards, then serve
        self.shed_next = 0              # answer 429 N times
        self.requests = []              # (path, headers) log
        self.stream_tokens = 5          # tokens a /generate emits
        self.stream_die_after = None    # die after K lines (no final)

    def ready_payload(self):
        body = {"status": "ready" if self.ready else "not_ready",
                "reason": self.reason, "inflight": self.inflight,
                "queued": self.queued, "limit": self.limit,
                "admission_limit": self.limit}
        if self.engine is not None:
            body["engine"] = dict(self.engine)
        return ((200 if self.ready else 503), {},
                json.dumps(body).encode())

    def handle(self, method, path, body, headers):
        if self.dead:
            raise ReplicaUnreachable("fake replica down")
        if path == "/ready":
            return self.ready_payload()
        self.requests.append((path, dict(headers or {})))
        if path == "/predict":
            if self.fail_next_predicts > 0:
                self.fail_next_predicts -= 1
                raise ReplicaUnreachable("fake replica crashed")
            if self.shed_next > 0:
                self.shed_next -= 1
                return (429, {"Retry-After": "1"},
                        json.dumps({"error": "shed",
                                    "reason": "queue_full"}).encode())
            return 200, {"Content-Type": "application/json"}, \
                b'{"echo": true}'
        raise AssertionError(f"unexpected path {path}")

    def stream(self, path, body, headers):
        if self.dead:
            raise ReplicaUnreachable("fake replica down")
        self.requests.append((path, dict(headers or {})))
        if self.shed_next > 0:
            self.shed_next -= 1
            return _FakeStream(429, [json.dumps(
                {"error": "shed", "reason": "queue_full"}).encode()])
        prompt = json.loads(body or b"{}").get("input_ids", [])
        lines = [json.dumps({"token": toy_token(prompt, i)}).encode()
                 + b"\n" for i in range(self.stream_tokens)]
        lines.append(json.dumps({
            "done": True, "finish_reason": "length",
            "output_ids": list(prompt) + [toy_token(prompt, i)
                                          for i in
                                          range(self.stream_tokens)],
        }).encode() + b"\n")
        return _FakeStream(200, lines, die_after=self.stream_die_after)


class _FakeTransport:
    def __init__(self, replicas):
        self.replicas = dict(replicas)  # address -> _FakeReplica

    def request(self, address, method, path, body=None, headers=None,
                timeout=30.0):
        rep = self.replicas.get(address)
        if rep is None:
            raise ReplicaUnreachable(f"no fake replica at {address}")
        return rep.handle(method, path, body, headers)

    def stream(self, address, path, body, headers=None, timeout=30.0):
        rep = self.replicas.get(address)
        if rep is None:
            raise ReplicaUnreachable(f"no fake replica at {address}")
        return rep.stream(path, body, headers)


class _FakeHandler:
    """Captures what forward_generate writes to the client side."""

    class _W:
        def __init__(self):
            self.data = b""

        def write(self, b):
            self.data += b

        def flush(self):
            pass

    def __init__(self):
        self.wfile = self._W()
        self.status = None
        self.headers = []
        self._rt_ctx = None
        self.json_body = None

    def send_response(self, code):
        self.status = code

    def send_header(self, k, v):
        self.headers.append((k, v))

    def end_headers(self):
        pass

    def _json(self, code, obj, headers=()):
        self.status = code
        self.json_body = obj
        self.headers.extend(headers)

    def lines(self):
        return [json.loads(x) for x in
                self.wfile.data.splitlines() if x.strip()]


def _router(replicas, clock=None, **kw):
    """Router over fake replicas, probed once (no threads/sockets used
    by the tests beyond the constructor's unstarted listener)."""
    transport = _FakeTransport(
        {f"fake://{rid}": rep for rid, rep in replicas.items()})
    r = Router(replicas={rid: f"fake://{rid}" for rid in replicas},
               transport=transport, clock=clock or time.monotonic,
               **kw)
    r.probe_once()
    return r


def _close(router):
    router._httpd.server_close()


# --------------------------------------------------------------------------
# routing: least-loaded pick
# --------------------------------------------------------------------------

def test_pick_least_loaded_predict():
    reps = {"a": _FakeReplica(inflight=3, queued=2, limit=4),
            "b": _FakeReplica(inflight=0, queued=0, limit=4),
            "c": _FakeReplica(inflight=2, queued=0, limit=4)}
    r = _router(reps)
    try:
        assert r._pick("predict") == "b"
        assert r._pick("predict", exclude={"b"}) == "c"
        # router-side in-flight counts weigh in between probes
        for _ in range(9):
            r._begin_forward("b", "predict")
        assert r._pick("predict") == "c"
    finally:
        _close(r)


def test_pick_generate_routes_to_emptiest_engine():
    eng = dict(max_slots=4, waiting_sequences=0, active_sequences=0,
               batch_occupancy=0.0)
    reps = {
        "full": _FakeReplica(engine=dict(eng, active_sequences=4,
                                         waiting_sequences=3)),
        "half": _FakeReplica(engine=dict(eng, active_sequences=2)),
        "idle": _FakeReplica(engine=dict(eng)),
    }
    r = _router(reps)
    try:
        assert r._pick("generate") == "idle"
        assert r._pick("generate", exclude={"idle"}) == "half"
    finally:
        _close(r)


def test_capacity_tracks_routable_fleet():
    reps = {"a": _FakeReplica(limit=3,
                              engine=dict(max_slots=4)),
            "b": _FakeReplica(limit=5,
                              engine=dict(max_slots=2))}
    r = _router(reps)
    try:
        assert r.admission.max_inflight == 8
        assert r.gen_admission.max_inflight == 6
        reps["b"].dead = True
        for _ in range(r.heartbeat_miss_k):
            r.probe_once()
        assert r.admission.max_inflight == 3
        assert r.gen_admission.max_inflight == 4
    finally:
        _close(r)


# --------------------------------------------------------------------------
# ejection / re-admission: heartbeats and probes
# --------------------------------------------------------------------------

def test_ejection_on_missed_heartbeats_and_readmission():
    alive = {"a", "b"}
    reps = {"a": _FakeReplica(), "b": _FakeReplica()}
    r = _router(reps, heartbeats=lambda: alive, heartbeat_miss_k=3)
    try:
        assert r.replica_summary() == {"a": "up", "b": "up"}
        before = metrics.snapshot()["counters"].get(
            "router.ejections", 0)
        alive.discard("a")  # beats stop; probes still answer
        r.probe_once()
        r.probe_once()
        assert r.replica_summary()["a"] == "up"  # below K
        r.probe_once()
        assert r.replica_summary()["a"] == "ejected"
        assert r._pick("predict") == "b"
        snap = metrics.snapshot()["counters"]
        assert snap.get("router.ejections", 0) == before + 1
        # heartbeats return → re-admitted after a clean probe
        alive.add("a")
        r.probe_once()
        assert r.replica_summary()["a"] == "up"
        assert metrics.snapshot()["counters"].get(
            "router.readmissions", 0) >= 1
        # state gauges track the table
        g = metrics.snapshot()["gauges"]
        assert g.get("router.replicas{state=up}") == 2
        assert g.get("router.replicas{state=ejected}") == 0
    finally:
        _close(r)


def test_replica_that_never_beat_is_probe_governed():
    """A replica whose heartbeat plane never came up (fleet degrades
    it to probe-only liveness) must still be admitted and must stay in
    rotation — absence from the alive set only counts against a
    replica that has beat at least once (review fix)."""
    alive = {"b"}  # "a" never registers a heartbeat
    reps = {"a": _FakeReplica(), "b": _FakeReplica()}
    r = _router(reps, heartbeats=lambda: alive, heartbeat_miss_k=2)
    try:
        for _ in range(5):
            r.probe_once()
        assert r.replica_summary() == {"a": "up", "b": "up"}
        # and once it HAS beat, stopping counts again
        alive.add("a")
        r.probe_once()
        alive.discard("a")
        r.probe_once()
        r.probe_once()
        assert r.replica_summary()["a"] == "ejected"
    finally:
        _close(r)


def test_set_capacity_keeps_aimd_band_nonempty():
    """Shrinking capacity below min_limit must drag the live limit
    down with it — not leave the edge admitting min_limit concurrent
    requests against fewer slots (review fix)."""
    from paddle_tpu.resilience.overload import AdmissionController

    ctrl = AdmissionController(max_inflight=8, min_limit=4,
                               latency_target=1.0)
    ctrl.set_capacity(2)
    assert ctrl.limit <= 2 and ctrl.max_inflight == 2
    ctrl.set_capacity(6)  # growth re-opens the band
    assert ctrl.max_inflight == 6


def test_heartbeat_source_failure_does_not_eject():
    def broken():
        raise RuntimeError("store down")

    reps = {"a": _FakeReplica()}
    r = _router(reps, heartbeats=broken, heartbeat_miss_k=2)
    try:
        for _ in range(5):
            r.probe_once()
        assert r.replica_summary()["a"] == "up"  # probe liveness holds
    finally:
        _close(r)


def test_breaker_open_skips_replica_then_half_open_recovers():
    clk = _Clock()
    reps = {"a": _FakeReplica(), "b": _FakeReplica()}
    r = _router(reps, clock=clk, breaker_threshold=2, breaker_reset=10.0)
    try:
        ctx = rtrace.new_context()
        reps["a"].fail_next_predicts = 100
        # drive forwards until a's breaker opens (failures land on a
        # only when the pick chooses it; force by loading b)
        reps["b"].inflight = 10
        r.probe_once()
        for _ in range(2):
            code, _h, _d, rid = r.forward_predict(b"x", ctx)
            assert code == 200 and rid == "b"  # failover served it
        with r._lock:
            assert r._replicas["a"].breaker.state == "open"
        # an open breaker is skipped at pick time entirely
        assert r._pick("predict") == "b"
        # reset window passes → half-open admits one trial again
        clk.advance(11.0)
        reps["a"].fail_next_predicts = 0
        assert r._pick("predict") == "a"
        code, _h, _d, rid = r.forward_predict(b"x", ctx)
        assert code == 200 and rid == "a"
        with r._lock:
            assert r._replicas["a"].breaker.state == "closed"
    finally:
        _close(r)


# --------------------------------------------------------------------------
# failover: same request id, shed passthrough, fleet-level sheds
# --------------------------------------------------------------------------

def test_failover_reuses_same_request_id():
    reps = {"a": _FakeReplica(), "b": _FakeReplica()}
    r = _router(reps, failover_retries=2)
    try:
        ctx = rtrace.new_context()
        reps["a"].inflight = 0
        reps["b"].inflight = 5
        r.probe_once()
        reps["a"].fail_next_predicts = 1  # first attempt dies on a
        before = metrics.snapshot()["counters"].get(
            "router.failovers", 0)
        code, _h, _d, rid = r.forward_predict(b"payload", ctx)
        assert code == 200 and rid == "b"
        assert metrics.snapshot()["counters"].get(
            "router.failovers", 0) == before + 1
        # BOTH attempts carried the client's X-Request-Id (one hop ctx)
        ids = {hdrs.get("X-Request-Id")
               for rep in reps.values()
               for path, hdrs in rep.requests if path == "/predict"}
        assert ids == {ctx.request_id}
    finally:
        _close(r)


def test_replica_shed_tries_another_then_passes_honest_retry_after():
    reps = {"a": _FakeReplica(), "b": _FakeReplica()}
    r = _router(reps, failover_retries=2)
    try:
        ctx = rtrace.new_context()
        reps["a"].shed_next = 5
        reps["b"].shed_next = 5
        code, hdrs, data, rid = r.forward_predict(b"x", ctx)
        assert code == 429 and rid is None
        assert hdrs.get("Retry-After") == "1"  # the replica's estimate
        # one replica shedding while the other serves → served
        reps["a"].shed_next = 5
        reps["b"].shed_next = 0
        code, _h, _d, rid = r.forward_predict(b"x", ctx)
        assert code == 200 and rid == "b"
    finally:
        _close(r)


def test_fleet_level_no_replicas_shed_labels():
    reps = {"a": _FakeReplica()}
    r = _router(reps)
    try:
        ctx = rtrace.new_context()
        reps["a"].dead = True
        for _ in range(r.heartbeat_miss_k):
            r.probe_once()
        before = metrics.snapshot()["counters"].get(
            "resilience.shed_requests{reason=no_replicas}", 0)
        with pytest.raises(ShedError) as ei:
            r.forward_predict(b"x", ctx)
        assert ei.value.reason == "no_replicas"
        assert ei.value.http_status == 503
        assert ei.value.retry_after > 0
        assert metrics.snapshot()["counters"].get(
            "resilience.shed_requests{reason=no_replicas}", 0) \
            == before + 1
        ready, reason = r.readiness()
        assert (ready, reason) == (False, "no_replicas")
    finally:
        _close(r)


def test_draining_readiness_takes_replica_out_of_rotation():
    reps = {"a": _FakeReplica(), "b": _FakeReplica()}
    r = _router(reps)
    try:
        reps["a"].ready = False
        reps["a"].reason = "draining"
        r.probe_once()
        assert r.replica_summary()["a"] == "draining"
        assert r._pick("predict") == "b"
        # replica finishes draining and comes back (relaunch-free)
        reps["a"].ready = True
        reps["a"].reason = "ok"
        r.probe_once()
        assert r.replica_summary()["a"] == "up"
    finally:
        _close(r)


def test_mark_draining_stops_picks_before_any_probe():
    reps = {"a": _FakeReplica(), "b": _FakeReplica()}
    r = _router(reps)
    try:
        reps["b"].inflight = 9  # a would win every pick
        r.probe_once()
        assert r._pick("predict") == "a"
        r.mark_draining("a")    # the fleet's pre-SIGTERM step
        assert r._pick("predict") == "b"
    finally:
        _close(r)


# --------------------------------------------------------------------------
# /generate stream failover semantics
# --------------------------------------------------------------------------

def _gen_body(prompt, n=8):
    return json.dumps({"input_ids": prompt,
                       "max_new_tokens": n}).encode()


def test_stream_zero_token_failover_is_transparent():
    reps = {"a": _FakeReplica(), "b": _FakeReplica()}
    r = _router(reps, failover_retries=2)
    try:
        ctx = rtrace.new_context()
        reps["b"].inflight = 0
        reps["a"].engine = dict(max_slots=4)
        reps["b"].engine = dict(max_slots=4)
        r.probe_once()
        # the picked replica dies before emitting ANY line
        first = r._pick("generate")
        reps[first].stream_die_after = 0
        h = _FakeHandler()
        prompt = [3, 4]
        status = r.forward_generate(_gen_body(prompt), prompt, ctx, h)
        assert status == "ok"
        lines = h.lines()
        assert [ln["token"] for ln in lines[:-1]] == \
            [toy_token(prompt, i) for i in range(5)]
        assert lines[-1]["done"] is True
        assert metrics.snapshot()["counters"].get(
            "router.failovers", 0) >= 1
    finally:
        _close(r)


def test_stream_mid_failure_interrupts_with_resumable_prefix():
    reps = {"a": _FakeReplica(engine=dict(max_slots=4))}
    r = _router(reps, failover_retries=2)
    try:
        ctx = rtrace.new_context()
        reps["a"].stream_die_after = 3  # 3 tokens out, then death
        h = _FakeHandler()
        prompt = [9, 9, 1]
        status = r.forward_generate(_gen_body(prompt), prompt, ctx, h)
        assert status == "interrupted"
        lines = h.lines()
        toks = [ln["token"] for ln in lines if "token" in ln]
        assert toks == [toy_token(prompt, i) for i in range(3)]
        final = lines[-1]
        assert final["interrupted"] is True
        assert final["finish_reason"] == "replica_lost"
        # the resumable prefix: prompt + delivered tokens, no replay
        assert final["output_ids"] == prompt + toks
        assert final["tokens_delivered"] == 3
        # NO other replica saw the request after tokens flowed
        assert len(reps["a"].requests) == 1
    finally:
        _close(r)


def test_stream_all_replicas_shedding_returns_clean_status():
    reps = {"a": _FakeReplica(engine=dict(max_slots=2))}
    r = _router(reps)
    try:
        ctx = rtrace.new_context()
        reps["a"].shed_next = 5
        h = _FakeHandler()
        status = r.forward_generate(_gen_body([1]), [1], ctx, h)
        assert status == "shed"
        assert h.status == 429
        assert h.json_body.get("reason") == "queue_full"
    finally:
        _close(r)


def test_client_raises_stream_interrupted_with_prefix():
    """InferenceClient.generate surfaces a router-interrupted stream
    as StreamInterrupted carrying the resumable output_ids — never a
    silent retry (which would replay tokens)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    prompt = [5, 1]
    toks = [toy_token(prompt, i) for i in range(2)]

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            self.rfile.read(n)
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.end_headers()
            for t in toks:
                self.wfile.write(json.dumps({"token": t}).encode()
                                 + b"\n")
            self.wfile.write(json.dumps({
                "interrupted": True, "error": "replica failed",
                "finish_reason": "replica_lost",
                "output_ids": prompt + toks,
                "tokens_delivered": len(toks)}).encode() + b"\n")

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    host, port = httpd.server_address[:2]
    try:
        cli = InferenceClient(f"http://{host}:{port}", timeout=10,
                              retries=2)
        with pytest.raises(StreamInterrupted) as ei:
            cli.generate(prompt, max_new_tokens=8)
        assert ei.value.tokens == toks
        assert list(ei.value.output_ids) == prompt + toks
        assert ei.value.finish_reason == "replica_lost"
    finally:
        httpd.shutdown()
        httpd.server_close()


# --------------------------------------------------------------------------
# satellites: /ready payload, Retry-After parse, schema zeros
# --------------------------------------------------------------------------

def test_ready_payload_carries_router_signals():
    srv = InferenceServer(predictor=EchoPredictor(),
                          engine=ToyEngine(max_slots=3)).start()
    try:
        body = InferenceClient(srv.address, timeout=10).ready()
        assert body["ready"] is True
        assert body["admission_limit"] == body["limit"]
        eng = body["engine"]
        assert eng["max_slots"] == 3
        assert eng["batch_occupancy"] == 0.0
        assert eng["waiting_sequences"] == 0
        assert eng["active_sequences"] == 0
        # status semantics unchanged: draining still flips 503
        srv.admission.begin_drain()
        body = InferenceClient(srv.address, timeout=10).ready()
        assert body["ready"] is False and body["reason"] == "draining"
    finally:
        srv.shutdown()


def test_client_retry_after_parsed_defensively():
    cli = InferenceClient("http://127.0.0.1:1", max_retry_wait=5.0)
    assert cli._retry_wait({"Retry-After": "2"}) == 2.0
    assert cli._retry_wait({}) == 0.5                  # absent
    assert cli._retry_wait({"Retry-After": "abc"}) == 0.5
    assert cli._retry_wait({"Retry-After": None}) == 0.5
    # negatives clamp to 0 then take the anti-busy-spin floor
    assert cli._retry_wait({"Retry-After": "-3"}) == 0.05
    assert cli._retry_wait({"Retry-After": "0"}) == 0.05
    assert cli._retry_wait({"Retry-After": "1e9"}) == 5.0  # clamp high
    assert cli._retry_wait({"Retry-After": "inf"}) == 0.5
    # NaN must not poison the min/max clamp into sleep(nan)
    assert cli._retry_wait({"Retry-After": "nan"}) == 0.5


def test_router_schema_zeros_present_in_snapshot():
    snap = metrics.snapshot()
    c, g = snap["counters"], snap["gauges"]
    assert "router.failovers" in c
    assert "router.ejections" in c
    assert "router.readmissions" in c
    assert "router.requests{endpoint=predict,status=ok}" in c
    for state in ("up", "draining", "ejected", "down"):
        assert f"router.replicas{{state={state}}}" in g
    assert "resilience.shed_requests{reason=no_replicas}" in c
    assert "resilience.faults{point=router.forward}" in c
    assert "resilience.faults{point=replica.crash}" in c


def test_router_forward_fault_point_triggers_failover():
    from paddle_tpu.resilience import faults

    reps = {"a": _FakeReplica(), "b": _FakeReplica()}
    r = _router(reps, failover_retries=2)
    try:
        ctx = rtrace.new_context()
        with faults.inject("router.forward", at=faults.call_count(
                "router.forward") + 1):
            code, _h, _d, rid = r.forward_predict(b"x", ctx)
        assert code == 200  # the injected fault was failed over
        assert metrics.snapshot()["counters"].get(
            "resilience.faults{point=router.forward}", 0) >= 1
    finally:
        faults.clear()
        _close(r)


# --------------------------------------------------------------------------
# ReplicaFleet: drain ordering with fake processes
# --------------------------------------------------------------------------

class _FakeProc:
    def __init__(self, record, rank):
        self.record = record
        self.rank = rank
        self.rc = None
        self.pid = 90000 + rank

    def poll(self):
        return self.rc

    def wait(self, timeout=None):
        return self.rc

    def send_signal(self, sig):
        self.record.append(("signal", self.rank, int(sig)))
        self.rc = 0

    def kill(self):
        self.record.append(("kill", self.rank))
        self.rc = -9


def test_fleet_drain_marks_router_before_sigterm(tmp_path):
    """The drain protocol's load-bearing ORDER: rotation-out and
    in-flight quiesce happen strictly before the signal (ISSUE 9 (c))."""
    record = []
    reps = {"r0": _FakeReplica(), "r1": _FakeReplica()}
    transport = _FakeTransport(
        {f"fake://{rid}": rep for rid, rep in reps.items()})
    router = Router(transport=transport, probe_interval=0.02)

    def spawner(handle, cmd, env):
        with open(handle.announce + ".tmp", "w") as f:
            json.dump({"address": f"fake://{handle.rid}",
                       "pid": 90000 + handle.rank}, f)
        os.replace(handle.announce + ".tmp", handle.announce)
        return _FakeProc(record, handle.rank)

    fleet = ReplicaFleet(num_replicas=2, router=router,
                         heartbeat=False, spawner=spawner,
                         workdir=str(tmp_path), max_restarts=0,
                         monitor_interval=0.02)
    fleet.start()
    try:
        assert router.replica_summary() == {"r0": "up", "r1": "up"}
        # hold simulated router-side in-flight traffic toward r0, then
        # drain it on a helper thread: the SIGTERM must wait for zero
        router._begin_forward("r0", "predict")
        states_at_signal = {}
        orig = _FakeProc.send_signal

        def instrumented(self, sig):
            states_at_signal["state"] = router.replica_summary()["r0"]
            states_at_signal["inflight"] = router.inflight_to("r0")
            orig(self, sig)

        _FakeProc.send_signal = instrumented
        try:
            th = threading.Thread(
                target=fleet.drain_replica, args=(0,),
                kwargs={"grace": 5.0})
            th.start()
            time.sleep(0.1)
            assert "state" not in states_at_signal  # still quiescing
            assert router.replica_summary()["r0"] == "draining"
            router._end_forward("r0", "predict")    # traffic finishes
            th.join(timeout=5)
            assert not th.is_alive()
        finally:
            _FakeProc.send_signal = orig
        # at signal time: already out of rotation, zero in-flight
        assert states_at_signal == {"state": "draining", "inflight": 0}
        kinds = [e["kind"] for e in fleet.events]
        assert kinds.index("drain_mark") < kinds.index("drain_sigterm")
        assert ("signal", 0, 15) in record
    finally:
        fleet.stop()


# --------------------------------------------------------------------------
# real multi-process e2e: kill -9 under load, failover, relaunch
# --------------------------------------------------------------------------

def test_fleet_e2e_kill_failover_relaunch():
    """Acceptance e2e (tier-1 sized): a 2-replica echo fleet keeps
    serving through a hard replica kill (same-request-id failover) and
    heals back to full capacity via supervisor relaunch."""
    fleet = ReplicaFleet(num_replicas=2, kind="echo",
                         launch_timeout=60, monitor_interval=0.1)
    fleet.start()
    try:
        cli = InferenceClient(fleet.router.address, timeout=20,
                              retries=1)
        x = np.arange(4, dtype=np.float32).reshape(2, 2)
        assert np.array_equal(cli.predict(x=x)["y"], x)
        fleet.kill_replica(0)
        # every post-kill request succeeds (failover, no 5xx window)
        for i in range(6):
            out = cli.predict(x=x + i)
            assert np.array_equal(out["y"], x + i)
        assert fleet.wait_ready(n=2, timeout=45), fleet.describe()
        snap = metrics.snapshot()["counters"]
        assert snap.get("router.ejections", 0) >= 1
        assert snap.get("router.readmissions", 0) >= 1
        views = {v["id"]: v for v in fleet.router.replica_views()}
        assert views["r0"]["generation"] >= 1  # relaunched process
    finally:
        fleet.stop()


def test_perf_gate_fleet_metric_round_trip(tmp_path):
    """fleet_decode_tokens_per_sec is gateable: --update registers the
    baseline row, an equal rerun passes, a drop beyond tolerance exits
    2, and --update rolls the floor forward (ISSUE 9 satellite)."""
    gate = os.path.join(REPO, "tools", "perf_gate.py")
    base = tmp_path / "baseline.jsonl"
    res = tmp_path / "results.json"
    row = {"metric": "fleet_decode_tokens_per_sec", "value": 800.0,
           "unit": "tokens/s", "single_replica_tokens_per_sec": 450.0,
           "fleet_speedup": 1.8, "replicas": 2}
    base.write_text(json.dumps(row) + "\n")

    def run(value):
        res.write_text(json.dumps(dict(row, value=value)) + "\n")
        return subprocess.run(
            [sys.executable, gate, str(res), "--baseline", str(base),
             "--static-budget", ""],
            capture_output=True, text=True)

    assert run(800.0).returncode == 0
    assert run(790.0).returncode == 0        # within tolerance
    p = run(300.0)
    assert p.returncode == 2 and "regression" in p.stderr
    res.write_text(json.dumps(dict(row, value=1200.0)) + "\n")
    p = subprocess.run(
        [sys.executable, gate, str(res), "--baseline", str(base),
         "--static-budget", "", "--update"],
        capture_output=True, text=True)
    assert p.returncode == 0 and "updated" in p.stdout
    assert run(1150.0).returncode == 0
    assert run(800.0).returncode == 2


@pytest.mark.chaos
def test_fleet_chaos_scenario():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import chaos_check
    finally:
        sys.path.pop(0)
    report = chaos_check.run_fleet_chaos(seed=0)
    assert report["recovered"], report


# --------------------------------------------------------------------------
# deterministic mid-stream resume (ISSUE 20)
# --------------------------------------------------------------------------

def _pos_token(prompt, i):
    """Position-only token fn: the greedy determinism contract in
    miniature — any replica handed the delivered prefix re-derives the
    SAME continuation (what the real engine guarantees via greedy
    argmax), so a resume leg's first token matches the verify token."""
    return (37 * (len(prompt) + i)) % 997


class _ContractReplica(_FakeReplica):
    """Fake replica honoring the greedy determinism contract AND the
    resume request shape: obeys max_new_tokens, logs parsed bodies."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.bodies = []

    def stream(self, path, body, headers):
        if self.dead:
            raise ReplicaUnreachable("fake replica down")
        self.requests.append((path, dict(headers or {})))
        req = json.loads(body or b"{}")
        self.bodies.append(req)
        prompt = req.get("input_ids", [])
        n = int(req.get("max_new_tokens", self.stream_tokens))
        toks = [_pos_token(prompt, i) for i in range(n)]
        lines = [json.dumps({"token": t}).encode() + b"\n"
                 for t in toks]
        lines.append(json.dumps({
            "done": True, "finish_reason": "length",
            "output_ids": list(prompt) + toks}).encode() + b"\n")
        return _FakeStream(200, lines,
                           die_after=self.stream_die_after)


def _eng(active):
    return dict(max_slots=4, waiting_sequences=0,
                active_sequences=active,
                batch_occupancy=active / 4.0)


def test_stream_mid_failure_resumes_on_survivor():
    """The tentpole: a replica dying with 3 tokens delivered becomes
    INVISIBLE — the router resubmits prompt+delivered[:-1] to the
    survivor under the same request id, swallows the re-derived verify
    token, and the client sees one seamless 8-token stream ending in a
    done record (annotated `resumed: 1`), never an interrupted one."""
    reps = {"a": _ContractReplica(engine=_eng(0)),
            "b": _ContractReplica(engine=_eng(1))}
    r = _router(reps, failover_retries=0, stream_resume_max=2)
    try:
        ctx = rtrace.new_context()
        assert r._pick("generate") == "a"   # emptiest engine first
        reps["a"].stream_die_after = 3      # 3 tokens out, then death
        h = _FakeHandler()
        prompt = [3, 4]
        status = r.forward_generate(_gen_body(prompt), prompt, ctx, h,
                                    max_new_tokens=8)
        assert status == "ok"
        lines = h.lines()
        toks = [ln["token"] for ln in lines if "token" in ln]
        # the full greedy stream, exactly once: no replay, no gap
        assert toks == [_pos_token(prompt, i) for i in range(8)]
        final = lines[-1]
        assert final["done"] is True
        assert final["resumed"] == 1
        assert final["output_ids"] == prompt + toks
        assert not any(ln.get("interrupted") for ln in lines)
        # the resume leg's shape: delivered[:-1] resubmitted, budget
        # reduced (+1 verify), the verify token billed nowhere
        (leg,) = reps["b"].bodies
        assert leg["input_ids"] == prompt + toks[:2]
        assert leg["max_new_tokens"] == 8 - 3 + 1
        assert leg["prebilled_tokens"] == 1
        assert leg["resume"] == 1
        # same request id end to end
        assert reps["b"].requests[0][1]["X-Request-Id"] == \
            ctx.request_id
        snap = metrics.snapshot()
        assert snap["counters"][
            "router.stream_resumes{outcome=ok}"] == 1
        assert snap["counters"].get("router.failovers", 0) == 0
        assert snap["histograms"]["router.resume_gap_ms"]["count"] >= 1
    finally:
        _close(r)


def test_stream_resume_divergence_falls_back_loudly():
    """A resume leg whose first token does NOT re-derive delivered[-1]
    must fall back to the clean interrupted record — the wrong token is
    never streamed (replica b is toy_token-based: content-dependent, so
    it diverges from the position-only contract replica)."""
    reps = {"a": _ContractReplica(engine=_eng(0)),
            "b": _FakeReplica(engine=_eng(1))}
    r = _router(reps, failover_retries=0, stream_resume_max=2)
    try:
        ctx = rtrace.new_context()
        assert r._pick("generate") == "a"
        reps["a"].stream_die_after = 3
        h = _FakeHandler()
        prompt = [3, 4]
        status = r.forward_generate(_gen_body(prompt), prompt, ctx, h,
                                    max_new_tokens=8)
        assert status == "interrupted"
        lines = h.lines()
        toks = [ln["token"] for ln in lines if "token" in ln]
        # only the verified prefix was ever streamed
        assert toks == [_pos_token(prompt, i) for i in range(3)]
        final = lines[-1]
        assert final["interrupted"] is True
        assert final["output_ids"] == prompt + toks
        assert final["tokens_delivered"] == 3
        snap = metrics.snapshot()["counters"]
        assert snap["router.stream_resumes{outcome=diverged}"] == 1
        assert snap["router.stream_resumes{outcome=ok}"] == 0
    finally:
        _close(r)


def test_resume_verify_fault_injection_forces_fallback():
    """The faults-plane divergence drill: router.resume_verify injected
    on an otherwise-healthy resume forces the loud fallback — the chaos
    harness can rehearse divergence without a broken model."""
    from paddle_tpu.resilience import faults

    reps = {"a": _ContractReplica(engine=_eng(0)),
            "b": _ContractReplica(engine=_eng(1))}
    r = _router(reps, failover_retries=0, stream_resume_max=2)
    try:
        ctx = rtrace.new_context()
        assert r._pick("generate") == "a"
        reps["a"].stream_die_after = 3
        h = _FakeHandler()
        prompt = [3, 4]
        with faults.inject("router.resume_verify"):
            status = r.forward_generate(_gen_body(prompt), prompt,
                                        ctx, h, max_new_tokens=8)
        assert status == "interrupted"
        final = h.lines()[-1]
        assert final["interrupted"] is True
        assert final["tokens_delivered"] == 3
        snap = metrics.snapshot()["counters"]
        assert snap["router.stream_resumes{outcome=diverged}"] == 1
    finally:
        faults.clear()
        _close(r)


def test_stream_resume_budget_exhausted_interrupts():
    """Bounded resumption: with stream_resume_max=1, a SECOND
    mid-stream death lands on the interrupted record carrying every
    delivered token (both legs), and no third replica is tried."""
    reps = {"a": _ContractReplica(engine=_eng(0)),
            "b": _ContractReplica(engine=_eng(1)),
            "c": _ContractReplica(engine=_eng(2))}
    r = _router(reps, failover_retries=0, stream_resume_max=1)
    try:
        ctx = rtrace.new_context()
        assert r._pick("generate") == "a"
        reps["a"].stream_die_after = 3
        reps["b"].stream_die_after = 3  # verify + 2 more, then death
        h = _FakeHandler()
        prompt = [3, 4]
        status = r.forward_generate(_gen_body(prompt), prompt, ctx, h,
                                    max_new_tokens=8)
        assert status == "interrupted"
        lines = h.lines()
        toks = [ln["token"] for ln in lines if "token" in ln]
        # 3 from leg 1, verify swallowed, 2 more from leg 2 — in order
        assert toks == [_pos_token(prompt, i) for i in range(5)]
        final = lines[-1]
        assert final["interrupted"] is True
        assert final["output_ids"] == prompt + toks
        assert reps["c"].requests == []   # budget spent: no third leg
        snap = metrics.snapshot()["counters"]
        assert snap["router.stream_resumes{outcome=ok}"] == 1
        assert snap["router.stream_resumes{outcome=exhausted}"] == 1
    finally:
        _close(r)


def test_stream_resume_class_gated():
    """An operator may declare batch streams not worth the resume
    re-prefill: the class gate falls straight back to the interrupted
    record without touching another replica."""
    reps = {"a": _ContractReplica(engine=_eng(0)),
            "b": _ContractReplica(engine=_eng(1))}
    r = _router(reps, failover_retries=0, stream_resume_max=2,
                stream_resume_classes=("paid", "free"))
    try:
        ctx = rtrace.new_context(priority_class="batch")
        assert r._pick("generate") == "a"
        reps["a"].stream_die_after = 3
        h = _FakeHandler()
        prompt = [3, 4]
        status = r.forward_generate(_gen_body(prompt), prompt, ctx, h,
                                    max_new_tokens=8)
        assert status == "interrupted"
        assert reps["b"].requests == []
        snap = metrics.snapshot()["counters"]
        assert snap["router.stream_resumes{outcome=exhausted}"] == 1
    finally:
        _close(r)


def test_resume_refusal_reasons():
    clock = _Clock()
    reps = {"a": _FakeReplica()}
    r = _router(reps, clock=clock, stream_resume_max=1,
                stream_resume_classes=("paid",))
    try:
        paid = rtrace.new_context(priority_class="paid")
        assert r._resume_refusal(paid, 0, None) is None
        assert r._resume_refusal(paid, 1, None) == "budget"
        # the default class (free) is outside the configured set
        assert r._resume_refusal(rtrace.new_context(), 0, None) \
            == "class"
        clock.t = 100.0
        assert r._resume_refusal(paid, 0, 99.0) == "deadline"
        assert r._resume_refusal(paid, 0, 101.0) is None
    finally:
        _close(r)


def test_resume_env_knobs(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_STREAM_RESUME_MAX", "5")
    monkeypatch.setenv("PADDLE_TPU_STREAM_RESUME_CLASSES",
                       "paid, BATCH, nonsense")
    reps = {"a": _FakeReplica()}
    r = _router(reps)
    try:
        assert r.stream_resume_max == 5
        assert r.stream_resume_classes == frozenset({"paid", "batch"})
    finally:
        _close(r)


def test_resume_schema_zeros_present_in_snapshot():
    snap = metrics.snapshot()
    c = snap["counters"]
    for outcome in ("ok", "diverged", "exhausted"):
        assert f"router.stream_resumes{{outcome={outcome}}}" in c
    for cache in ("hit", "partial", "miss"):
        assert f"serving.resume_prefill{{cache={cache}}}" in c
    assert "resilience.shed_requests{reason=deadline_exceeded}" in c
    assert "resilience.faults{point=router.stream_read}" in c
    assert "resilience.faults{point=router.resume_verify}" in c
    assert "router.resume_gap_ms" in snap["histograms"]


def test_client_resume_continues_stream_same_request_id():
    """InferenceClient.generate(resume=True) turns StreamInterrupted
    into a client-side resume: the carried output_ids are resubmitted
    with the budget reduced, under the SAME X-Request-Id, and the
    caller sees one seamless result with `resumed` counted."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    prompt = [5, 1]
    leg1 = [toy_token(prompt, i) for i in range(2)]
    seen = []

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(n) or b"{}")
            seen.append((req, self.headers.get("X-Request-Id")))
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.end_headers()
            if len(seen) == 1:
                for t in leg1:
                    self.wfile.write(
                        json.dumps({"token": t}).encode() + b"\n")
                self.wfile.write(json.dumps({
                    "interrupted": True, "error": "replica failed",
                    "finish_reason": "replica_lost",
                    "output_ids": prompt + leg1,
                    "tokens_delivered": len(leg1)}).encode() + b"\n")
                return
            ids = list(req["input_ids"])
            leg2 = [toy_token(ids, i)
                    for i in range(req["max_new_tokens"])]
            for t in leg2:
                self.wfile.write(
                    json.dumps({"token": t}).encode() + b"\n")
            self.wfile.write(json.dumps({
                "done": True, "finish_reason": "length",
                "output_ids": ids + leg2}).encode() + b"\n")

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    host, port = httpd.server_address[:2]
    try:
        cli = InferenceClient(f"http://{host}:{port}", timeout=10,
                              retries=0)
        out = cli.generate(prompt, max_new_tokens=6, resume=True)
        assert len(seen) == 2
        req2, rid2 = seen[1]
        assert seen[0][1] == rid2                    # same request id
        assert req2["input_ids"] == prompt + leg1    # carried prefix
        assert req2["max_new_tokens"] == 6 - len(leg1)
        assert out["resumed"] == 1
        assert out["finish_reason"] == "length"
        assert out["tokens"][:2] == leg1
        assert len(out["tokens"]) == 6
        assert list(out["output_ids"]) == prompt + out["tokens"]
    finally:
        httpd.shutdown()
        httpd.server_close()


@pytest.mark.chaos
def test_resume_chaos_scenario():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import chaos_check
    finally:
        sys.path.pop(0)
    report = chaos_check.run_resume_chaos(seed=0)
    assert report["recovered"], report


def test_perf_gate_resume_gap_metric_round_trip(tmp_path):
    """serving_stream_resume_gap_ms is gateable lower-better: --update
    registers the baseline, an equal rerun passes, a blow-up beyond
    tolerance exits 2, and --update rolls the ceiling (ISSUE 20)."""
    gate = os.path.join(REPO, "tools", "perf_gate.py")
    base = tmp_path / "baseline.jsonl"
    res = tmp_path / "results.json"
    row = {"metric": "serving_stream_resume_gap_ms", "value": 40.0,
           "unit": "ms", "resumes": 4}
    base.write_text(json.dumps(row) + "\n")

    def run(value):
        res.write_text(json.dumps(dict(row, value=value)) + "\n")
        return subprocess.run(
            [sys.executable, gate, str(res), "--baseline", str(base),
             "--static-budget", ""],
            capture_output=True, text=True)

    assert run(40.0).returncode == 0
    assert run(41.0).returncode == 0         # within tolerance
    p = run(400.0)
    assert p.returncode == 2 and "regression" in p.stderr
    res.write_text(json.dumps(dict(row, value=20.0)) + "\n")
    p = subprocess.run(
        [sys.executable, gate, str(res), "--baseline", str(base),
         "--static-budget", "", "--update"],
        capture_output=True, text=True)
    assert p.returncode == 0 and "updated" in p.stdout
    assert run(21.0).returncode == 0
    assert run(40.0).returncode == 2
