"""Replica lifecycle observability tests (ISSUE 17): the per-process
phase ledger (ordering, double-stamp loudness, the spawn-wall back-date
join, the bounded compile sub-ledger), the supervisor-side fleet ledger
(bounded history, the skewed-clock join producing no negative
durations, validate/rollup helpers), the attach() schema zeros,
`GET /debug/lifecycle` end-to-end on a live toy fleet (router + replica
views, the autoscaler's observed_spawn_ms signal), the exporter's
`lifecycle` dump-key validation, the tools/telemetry_agg.py fleet
rollup, and the perf_gate --update round-trip for the new
`fleet_replica_cold_start_ms` bench row."""
import importlib.util
import json
import os
import urllib.request

import pytest

from paddle_tpu import observability as obs
from paddle_tpu.inference.autoscaler import Autoscaler
from paddle_tpu.inference.fleet import ReplicaFleet
from paddle_tpu.observability import export, lifecycle as lc, metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def telemetry():
    metrics.reset()
    obs.flight.clear()
    obs.attach(crash_hook=False)
    yield
    obs.detach()
    metrics.reset()
    obs.flight.clear()


class _Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _twin_clocks(mono0=100.0, wall0=1000.0):
    """A monotonic clock and a wall clock that tick together (one
    process's pair — the thing the join rule is allowed to difference)."""
    mono = _Clock(mono0)
    wall = _Clock(wall0)

    def advance(dt):
        mono.advance(dt)
        wall.advance(dt)

    return mono, wall, advance


# --------------------------------------------------------------------------
# the per-process ledger: ordering, durations, the wall-anchor join
# --------------------------------------------------------------------------

def test_phase_ordering_and_durations():
    mono, wall, advance = _twin_clocks()
    led = lc.LifecycleLedger(clock=mono, wall=wall)
    led.begin()
    advance(0.5)
    led.stamp("imports")
    advance(0.25)
    led.stamp("weight_load")
    advance(0.1)
    led.stamp("warmup")
    advance(0.01)
    led.stamp("announce")
    rec = led.record()
    assert rec["schema"] == lc.SCHEMA
    d = rec["durations_ms"]
    assert d["imports"] == pytest.approx(500.0)
    assert d["weight_load"] == pytest.approx(250.0)
    assert d["warmup"] == pytest.approx(100.0)
    assert d["announce"] == pytest.approx(10.0)
    assert rec["total_ms"] == pytest.approx(860.0)
    # phases are monotone on the ledger's own clock
    seq = [rec["phases"][p]["mono_ms"] for p in lc.PHASES
           if p in rec["phases"]]
    assert seq == sorted(seq)
    assert rec["double_stamps"] == 0


def test_spawn_wall_backdates_imports():
    """The supervisor's wall anchor back-dates proc_spawn so `imports`
    covers fork + interpreter start, not just post-import code."""
    mono, wall, advance = _twin_clocks(wall0=1000.0)
    led = lc.LifecycleLedger(clock=mono, wall=wall)
    # child came up 0.8s of wall time after the supervisor's Popen
    led.begin(spawn_wall=1000.0 - 0.8)
    advance(0.2)
    led.stamp("imports")
    rec = led.record()
    assert rec["spawn_wall"] == pytest.approx(999.2)
    assert rec["durations_ms"]["imports"] == pytest.approx(1000.0)


def test_insane_spawn_wall_ignored():
    """A skewed supervisor wall clock (child wall BEHIND the anchor, or
    anchor absurdly old) must not poison the ledger: the back-date is
    dropped and durations stay >= 0."""
    for bogus in (1000.0 + 5.0,       # delta < 0: child wall behind
                  1000.0 - 7200.0,    # delta > 1h: absurd
                  "not-a-float", None):
        mono, wall, advance = _twin_clocks(wall0=1000.0)
        led = lc.LifecycleLedger(clock=mono, wall=wall)
        led.begin(spawn_wall=bogus)
        advance(0.1)
        led.stamp("imports")
        rec = led.record()
        assert rec["durations_ms"]["imports"] == pytest.approx(100.0), bogus
        assert all(v >= 0 for v in rec["durations_ms"].values())


def test_double_stamp_is_loud(telemetry):
    led = lc.LifecycleLedger()
    led.begin()
    assert led.stamp("imports") is not None
    assert led.stamp("imports") is None          # strict: kept first
    rec = led.record()
    assert rec["double_stamps"] == 1
    snap = metrics.snapshot()["counters"]
    assert snap["lifecycle.double_stamps"] == 1
    assert any(e["kind"] == "lifecycle.double_stamp"
               for e in obs.flight.events())
    # stamp_once is the quiet first-wins variant (first_token races)
    assert led.stamp_once("first_token") is not None
    assert led.stamp_once("first_token") is None
    assert led.record()["double_stamps"] == 1    # unchanged


def test_unknown_phase_rejected():
    with pytest.raises(ValueError):
        lc.LifecycleLedger().stamp("reticulate_splines")


def test_stamp_before_begin_self_anchors():
    led = lc.LifecycleLedger()
    led.stamp("imports")                         # no begin(): still usable
    rec = led.record()
    assert "proc_spawn" in rec["phases"] and "imports" in rec["phases"]


def test_compile_ledger_bounded(telemetry, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_LIFECYCLE_COMPILE_CAP", "3")
    led = lc.LifecycleLedger()
    led.begin()
    for i in range(10):
        led.record_compile(f"prog_{i}", lower_ms=1.0, compile_ms=2.0)
    rec = led.record()
    assert len(rec["compiles"]) == 4             # 3 named + ~other
    assert "~other" in rec["compiles"]
    assert rec["compiles"]["~other"]["count"] == 7
    # nothing dropped: the total conserves every compile's wall time
    assert rec["compile_total_ms"] == pytest.approx(30.0)
    gauges = metrics.snapshot()["gauges"]
    assert gauges["lifecycle.compile_ms{program=~total}"] \
        == pytest.approx(30.0)


# --------------------------------------------------------------------------
# the supervisor-side fleet ledger: join, skew, bounds, rollup
# --------------------------------------------------------------------------

def _joined_record(rep_wall_skew=0.0, spawn_to_up=1.0):
    """One complete spawn story: supervisor and replica each on their
    OWN clock pair, the replica's wall clock skewed by `rep_wall_skew`
    seconds relative to the supervisor's."""
    sup_mono, sup_wall, sup_adv = _twin_clocks(100.0, 5000.0)
    fl = lc.FleetLifecycle(clock=sup_mono, wall=sup_wall)
    anchor = fl.spawn("r1", rank=1)

    rep_mono, rep_wall, rep_adv = _twin_clocks(7.0, 5000.0 + rep_wall_skew)
    rep_adv(0.3)                                 # fork + interpreter lag
    led = lc.LifecycleLedger(clock=rep_mono, wall=rep_wall)
    led.begin(spawn_wall=anchor)
    rep_adv(0.2)
    led.stamp("imports")
    rep_adv(0.05)
    led.stamp("weight_load")
    led.record_compile("decode_n1", lower_ms=3.0, compile_ms=9.0)
    rep_adv(0.02)
    led.stamp("warmup")
    led.stamp("announce")

    sup_adv(spawn_to_up - 0.1)
    fl.stamp("r1", "announce")
    sup_adv(0.1)
    fl.stamp("r1", "first_probe_up")
    assert fl.attach_replica_record("r1", led.record())
    fl.stamp("r1", "first_routable_request")
    return fl


def test_join_attributes_phases():
    fl = _joined_record()
    recs = fl.records()
    assert len(recs) == 1
    rec = recs[0]
    assert lc.validate_record(rec) == []
    ph = rec["phases_ms"]
    # compile and weight_load are ATTRIBUTED phases, never `other`
    assert ph["compile"] == pytest.approx(12.0)
    assert ph["weight_load"] == pytest.approx(50.0)
    assert ph["imports"] == pytest.approx(500.0)  # incl. 300ms fork lag
    assert ph["probe"] == pytest.approx(100.0)
    assert ph["other"] >= 0.0
    assert rec["total_ms"] == pytest.approx(1000.0)
    assert fl.observed_spawn_ms() == pytest.approx(1000.0)


@pytest.mark.parametrize("skew", [-45.0, 45.0])
def test_skewed_replica_wall_never_negative(skew):
    """Wall skew between supervisor and replica (either direction) must
    never produce a negative duration or an invalid record — the join
    rule only differences same-clock stamps, and the back-date guard
    drops a behind-anchor wall."""
    fl = _joined_record(rep_wall_skew=skew)
    rec = fl.records()[0]
    assert lc.validate_record(rec) == []
    assert all(v >= 0 for v in rec["phases_ms"].values())
    assert all(v >= 0
               for v in rec["replica"]["durations_ms"].values())


def test_fleet_history_bounded(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_LIFECYCLE_HISTORY", "5")
    fl = lc.FleetLifecycle()
    for i in range(40):
        fl.spawn(f"r{i % 3}", rank=i % 3)        # relaunches archive too
        fl.stamp(f"r{i % 3}", "first_probe_up")
    assert len(fl.records()) <= 10               # 5 active cap + 5 archive
    view = fl.fleet_view()
    assert view["spawns"] == 40
    assert view["observed_spawn_ms"] is not None


def test_validate_record_catches_incomplete():
    assert lc.validate_record(None) == ["not a dict"]
    fl = lc.FleetLifecycle()
    fl.spawn("r0", rank=0)
    rec = fl.records()[0]                        # nothing stamped yet
    probs = lc.validate_record(rec)
    assert "supervisor stamp missing: announce" in probs
    assert "supervisor stamp missing: first_probe_up" in probs
    assert "replica record missing" in probs
    # non-monotone supervisor stamps are named
    bad = {"supervisor_ms": {"announce": 50.0, "first_probe_up": 10.0},
           "replica": None, "phases_ms": {}}
    assert any("not monotone" in p for p in lc.validate_record(bad))
    assert any("negative joined phase" in p for p in lc.validate_record(
        {"supervisor_ms": {"announce": 1.0, "first_probe_up": 2.0},
         "replica": None, "phases_ms": {"probe": -3.0}}))


def test_rollup_percentiles():
    recs = [{"phases_ms": {"imports": float(i)}, "total_ms": float(i)}
            for i in range(1, 21)]
    roll = lc.rollup_records(recs)
    assert roll["count"] == 20
    assert roll["phases"]["imports"]["p50"] == pytest.approx(11.0)
    assert roll["phases"]["imports"]["max"] == pytest.approx(20.0)
    assert roll["total_ms"]["p95"] >= 19.0
    assert lc.rollup_records([]) == {"count": 0, "phases": {}}


# --------------------------------------------------------------------------
# attach() schema: every lifecycle series exists at zero
# --------------------------------------------------------------------------

def test_schema_zero_values(telemetry):
    snap = metrics.snapshot()
    assert snap["counters"]["lifecycle.spawns"] == 0
    assert snap["counters"]["lifecycle.double_stamps"] == 0
    for p in lc.PHASES[1:]:
        assert snap["gauges"][f"lifecycle.phase_ms{{phase={p}}}"] == 0
    assert snap["gauges"]["lifecycle.compile_ms{program=~total}"] == 0
    assert snap["gauges"]["autoscaler.observed_spawn_ms"] == 0


# --------------------------------------------------------------------------
# exporter: the `lifecycle` dump key validates like timeseries
# --------------------------------------------------------------------------

def _dump_entry(**over):
    e = {"phase": "telemetry_dump", "t": "2026-08-07T00:00:00",
         "schema": export.SCHEMA_VERSION, "host": "h", "pid": 1,
         "rank": None, "run_id": "p1", "seq": 1, "reason": "periodic",
         "wall": 1.0, "trace_wall_epoch": 0.0, "trace_events": [],
         "flight_events": [],
         "metrics": {"counters": {}, "gauges": {}, "histograms": {}}}
    e.update(over)
    return e


def test_validate_lifecycle_dump_key():
    ok = _dump_entry(lifecycle={"schema": lc.SCHEMA, "phases": {}})
    assert export.validate_telemetry_stream([ok]) == []
    bad = _dump_entry(lifecycle=["not", "a", "dict"])
    errs = export.validate_telemetry_stream([bad])
    assert any("lifecycle" in e and "not an object" in e for e in errs)


def test_exporter_dumps_lifecycle(tmp_path, telemetry):
    led = lc.LifecycleLedger()
    led.begin()
    led.stamp("imports")
    exp = export.TelemetryExporter(str(tmp_path), lifecycle=led.record)
    exp.dump_once(reason="test")
    dump, = [p for p in os.listdir(tmp_path) if p.endswith(".jsonl")]
    with open(tmp_path / dump) as f:
        lines = [json.loads(l) for l in f if l.strip()]
    assert lines and lines[-1]["lifecycle"]["schema"] == lc.SCHEMA
    assert "imports" in lines[-1]["lifecycle"]["durations_ms"]
    assert export.validate_telemetry_stream(lines) == []


# --------------------------------------------------------------------------
# tools/telemetry_agg.py: fleet rollup sees both dump shapes
# --------------------------------------------------------------------------

def test_telemetry_agg_rollup_lifecycle(tmp_path):
    spec = importlib.util.spec_from_file_location(
        "_tagg", os.path.join(REPO, "tools", "telemetry_agg.py"))
    agg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(agg)

    # a replica process dump: its own ledger record
    led = lc.LifecycleLedger()
    led.begin()
    led.stamp("imports")
    led.record_compile("decode_n1", compile_ms=7.0)
    rep_dump = _dump_entry(host="a", pid=11, run_id="proc_11",
                           lifecycle=led.record())
    # a supervisor dump: a fleet view with one joined record
    fl = _joined_record()
    sup_dump = _dump_entry(host="b", pid=22, run_id="proc_22",
                           lifecycle=fl.fleet_view())
    for name, d in (("a_11", rep_dump), ("b_22", sup_dump)):
        with open(tmp_path / f"telemetry_{name}.jsonl", "w") as f:
            f.write(json.dumps(d) + "\n")
    roll = agg.rollup(agg.load_dumps(str(tmp_path)))
    lcr = roll["lifecycle"]
    assert sorted(lcr["per_process"]) == ["a:11", "b:22"]
    fleet = lcr["fleet"]
    # both spawn stories rolled up: the replica-only dump synthesized a
    # phases row (with compile attributed), the fleet view contributed
    # its joined record
    assert fleet["count"] == 2
    assert fleet["phases"]["imports"]["count"] == 2
    assert fleet["phases"]["compile"]["max"] == pytest.approx(12.0)


# --------------------------------------------------------------------------
# e2e: a live toy fleet's 1 -> 2 scale-up tells a complete spawn story
# --------------------------------------------------------------------------

def _get_json(url, timeout=15):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def test_debug_lifecycle_e2e_toy_fleet(telemetry):
    """Acceptance e2e (tier-1 sized): real processes, a real
    add_replica(), and the full lifecycle plane — per-replica ledgers
    over /debug/lifecycle, the router's joined fleet view with complete
    monotone records, and the autoscaler's observed_spawn_ms signal."""
    import time as _time

    fleet = ReplicaFleet(num_replicas=1, kind="toy", token_time=0.005,
                         service_time=0.005, max_slots=4,
                         launch_timeout=60, monitor_interval=0.1)
    fleet.start()
    try:
        rank = fleet.add_replica()
        assert rank is not None
        deadline = _time.monotonic() + 30.0
        while _time.monotonic() < deadline and \
                fleet.router.routable_count() < 2:
            _time.sleep(0.05)
        assert fleet.router.routable_count() == 2

        # a generate through the router stamps first_routable_request
        # (supervisor side) and first_token (replica side)
        from paddle_tpu.inference.serving import InferenceClient
        cli = InferenceClient(fleet.router.address, timeout=20)
        for _ in range(4):                        # hit both replicas
            out = cli.generate([1, 2, 3], max_new_tokens=2)
            assert out["tokens"]

        dbg = _get_json(fleet.router.address + "/debug/lifecycle")
        assert dbg["role"] == "router"
        assert len(dbg["replicas"]) == 2
        for rec in dbg["replicas"].values():
            assert rec["schema"] == lc.SCHEMA
            for p in lc.REPLICA_PHASES:
                assert p in rec["phases"], p
        assert any("first_token" in rec["phases"]
                   for rec in dbg["replicas"].values())

        view = dbg["fleet"]
        assert view["spawns"] == 2
        assert view["observed_spawn_ms"] is not None
        assert len(view["records"]) == 2
        for rec in view["records"]:
            assert lc.validate_record(rec) == [], rec
            # cold-start attribution: compile + weight_load are named
            # (non-`other`) fractions of spawn-to-routable
            assert "compile" in rec["phases_ms"]
            assert "weight_load" in rec["phases_ms"]
            assert rec["phases_ms"]["other"] >= 0.0
        assert any("first_routable_request" in r["supervisor_ms"]
                   for r in view["records"])

        # the replica's own endpoint serves its ledger directly
        up = [v for v in fleet.router.replica_views()
              if v["state"] == "up"]
        rep_dbg = _get_json(up[0]["address"] + "/debug/lifecycle")
        assert rep_dbg["schema"] == lc.SCHEMA

        # /debug/telemetry embeds the fleet view (exporter contract)
        tele = _get_json(fleet.router.address + "/debug/telemetry")
        assert tele["lifecycle"]["spawns"] == 2

        # the autoscaler reads the observed estimate and publishes it
        scaler = Autoscaler(fleet)
        sig = scaler.signals()
        assert sig["observed_spawn_ms"] is not None
        assert sig["observed_spawn_ms"] == pytest.approx(
            view["observed_spawn_ms"], rel=0.01)
        assert metrics.snapshot()["gauges"][
            "autoscaler.observed_spawn_ms"] > 0
    finally:
        fleet.stop()


# --------------------------------------------------------------------------
# perf_gate: the fleet_replica_cold_start_ms row round-trips --update
# --------------------------------------------------------------------------

def _pg():
    spec = importlib.util.spec_from_file_location(
        "_perf_gate", os.path.join(REPO, "tools", "perf_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_emits_cold_start_metric():
    with open(os.path.join(REPO, "bench.py")) as f:
        src = f.read()
    assert '"fleet_replica_cold_start_ms"' in src


def test_cold_start_row_update_round_trip(tmp_path):
    """--update starts gating the cold-start row; it is lower-better
    (the `_ms` suffix), so a later SLOWER spawn fails the gate and a
    same-or-faster one passes.  Degraded (CPU-proxy) rows neither
    update nor gate."""
    pg = _pg()
    baseline = tmp_path / "baseline.jsonl"
    baseline.write_text("")
    row = {"metric": "fleet_replica_cold_start_ms", "value": 1000.0,
           "unit": "ms", "lower_better": True}
    assert pg.update_baseline([row], str(baseline)) == 1
    base = pg.load_baseline(str(baseline))
    ok = dict(row, value=1050.0)                 # within 10% tolerance
    failures, _ = pg.gate([ok], base, tolerance=0.10)
    assert failures == []
    slow = dict(row, value=1300.0)               # 30% slower spawn
    failures, report = pg.gate([slow], base, tolerance=0.10)
    assert len(failures) == 1 and "above" in failures[0], report
    degraded = dict(row, value=9999.0, degraded=True)
    assert pg.update_baseline([degraded], str(baseline)) == 0
    failures, report = pg.gate([degraded], pg.load_baseline(str(baseline)))
    assert failures == [] and any("SKIP" in l for l in report)
