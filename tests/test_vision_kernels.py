"""Fused vision kernels (ISSUE 10): Swin window attention and the
conv+norm+act fusion vs their jnp references, through the Pallas
interpreter on CPU (fake-backend strategy — the exact kernel code runs,
minus Mosaic lowering, which tests/test_tpu_lowering.py-style gates
cover on the real toolchain)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as P
from paddle_tpu.ops.pallas import conv_norm as CN
from paddle_tpu.ops.pallas import window_attention as WA


def _swin_mask(H, W, ws, shift):
    """The swin shifted-window additive mask ([nW, ws², ws²])."""
    img = np.zeros((1, H, W, 1))
    sl = (slice(0, -ws), slice(-ws, -shift), slice(-shift, None))
    cnt = 0
    for hs in sl:
        for wsl in sl:
            img[:, hs, wsl, :] = cnt
            cnt += 1
    m = img.reshape(1, H // ws, ws, W // ws, ws, 1)
    m = m.transpose(0, 1, 3, 2, 4, 5).reshape(-1, ws * ws)
    diff = m[:, None, :] - m[:, :, None]
    return jnp.asarray(np.where(diff != 0, -100.0, 0.0)
                       .astype(np.float32))


# ===================== window attention =====================


def test_window_attention_kernel_matches_ref_unshifted():
    """Unshifted windows, every band size: the kernel's forward is
    bit-exact against the jnp reference (identical op order)."""
    rs = np.random.RandomState(0)
    B, H, W, C, heads, ws = 2, 8, 8, 12, 3, 4
    P_ = ws * ws
    qkv = jnp.asarray(rs.randn(B, H, W, 3 * C), jnp.float32)
    bias = jnp.asarray(rs.randn(heads, P_, P_), jnp.float32)
    ref = WA.window_attention_ref(qkv, bias, None, window_size=ws,
                                  shift=0, num_heads=heads)
    for band in (1, 2):
        out = WA._fwd_pallas(qkv, bias, None, ws, 0, heads, band)
        assert np.array_equal(np.asarray(out), np.asarray(ref)), \
            f"band={band} forward differs from the reference"


def test_window_attention_kernel_matches_ref_shifted_masked():
    """Shifted windows WITH the swin attention mask: forward bit-exact,
    gradients (dqkv from the analytic backward kernel, dbias summed
    over batch/windows) match jax-AD of the reference."""
    rs = np.random.RandomState(1)
    B, H, W, C, heads, ws, shift = 2, 8, 8, 8, 2, 4, 2
    P_ = ws * ws
    qkv = jnp.asarray(rs.randn(B, H, W, 3 * C), jnp.float32)
    bias = jnp.asarray(rs.randn(heads, P_, P_), jnp.float32)
    mask = _swin_mask(H, W, ws, shift)
    ref = WA.window_attention_ref(qkv, bias, mask, window_size=ws,
                                  shift=shift, num_heads=heads)
    out = WA._fwd_pallas(qkv, bias, mask, ws, shift, heads, H // ws)
    assert np.array_equal(np.asarray(out), np.asarray(ref))

    core = WA._build_core(ws, shift, heads, H // ws, True)
    gk = jax.grad(lambda q, b: core(q, b, mask).sum(),
                  argnums=(0, 1))(qkv, bias)
    gr = jax.grad(
        lambda q, b: WA.window_attention_ref(
            q, b, mask, window_size=ws, shift=shift,
            num_heads=heads).sum(),
        argnums=(0, 1))(qkv, bias)
    for name, a, b in zip(("dqkv", "dbias"), gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5,
                                   err_msg=f"{name} mismatch")
    # the mask is stop-gradient by contract: zero cotangent
    dmask = jax.grad(lambda m: core(qkv, bias, m).sum())(mask)
    assert float(jnp.abs(dmask).max()) == 0.0


def test_window_attention_single_window_edge():
    """Edge tiling: a window covering the whole (odd-count) feature map
    — one window, no shift (the swin small-resolution stage shape)."""
    rs = np.random.RandomState(2)
    B, H, W, C, heads, ws = 1, 4, 4, 8, 2, 4
    qkv = jnp.asarray(rs.randn(B, H, W, 3 * C), jnp.float32)
    bias = jnp.asarray(rs.randn(heads, ws * ws, ws * ws), jnp.float32)
    ref = WA.window_attention_ref(qkv, bias, None, window_size=ws,
                                  shift=0, num_heads=heads)
    out = WA._fwd_pallas(qkv, bias, None, ws, 0, heads, 1)
    assert np.array_equal(np.asarray(out), np.asarray(ref))


def test_window_attention_dispatch_counters(monkeypatch):
    """The public entry is gated: CPU routes to the reference with a
    `swin_attn.dispatch{tier=fallback}` counter (the silent-fallback
    failure class becomes a metric)."""
    from paddle_tpu import observability as obs

    obs.attach()
    try:
        before = obs.metrics.snapshot().get("counters", {})
        n0 = sum(v for k, v in before.items()
                 if "swin_attn.dispatch" in k and "fallback" in k)
        rs = np.random.RandomState(3)
        qkv = jnp.asarray(rs.randn(1, 4, 4, 12), jnp.float32)
        bias = jnp.zeros((2, 16, 16), jnp.float32)
        WA.swin_window_attention(qkv, bias, None, window_size=4,
                                 shift=0, num_heads=2)
        after = obs.metrics.snapshot().get("counters", {})
        n1 = sum(v for k, v in after.items()
                 if "swin_attn.dispatch" in k and "fallback" in k)
        assert n1 == n0 + 1, (before, after)
    finally:
        obs.detach()


def test_window_attention_band_autotuned(monkeypatch):
    """The band size goes through the existing autotune cache
    (`autotune.pick` with the swin_window_attn op); shifted blocks pin
    the full image (the row roll crosses bands)."""
    from paddle_tpu.ops.pallas import autotune

    seen = {}

    def fake_pick(op, sig, cands, run, default):
        seen["op"] = op
        seen["cands"] = list(cands)
        return default

    monkeypatch.setattr(autotune, "pick", fake_pick)
    rs = np.random.RandomState(4)
    qkv = jnp.asarray(rs.randn(1, 16, 16, 12), jnp.float32)
    band = WA._tuned_band(qkv, 4, 0, 2, False)
    assert seen["op"] == "swin_window_attn"
    assert seen["cands"] == [1, 2, 4]
    assert band == 4  # default = full image
    # shifted: no search, full image forced
    seen.clear()
    assert WA._tuned_band(qkv, 4, 2, 2, True) == 4
    assert "op" not in seen


# ===================== swin model integration =====================


def test_swin_dense_bias_matches_gather():
    """WindowAttention.dense_bias (one-hot matmul, no per-forward
    gather) equals the reference gather/reshape/transpose chain."""
    from paddle_tpu.vision.models.swin import WindowAttention

    P.seed(0)
    wa = WindowAttention(dim=12, window_size=4, num_heads=3)
    dense = wa.dense_bias().numpy()
    tab = wa.rel_bias.numpy()
    n = 16
    ref = tab[wa._rel_index.reshape(-1)].reshape(n, n, 3)
    ref = ref.transpose(2, 0, 1)
    np.testing.assert_allclose(dense, ref, atol=1e-6, rtol=1e-6)


def test_swin_block_shifted_matches_manual_reference():
    """A shifted SwinBlock through the fused entry equals the manual
    roll/partition/attention/reverse composition it replaced."""
    from paddle_tpu.vision.models.swin import SwinBlock

    P.seed(1)
    blk = SwinBlock(dim=8, input_resolution=(8, 8), num_heads=2,
                    window_size=4, shift_size=2)
    assert blk.shift == 2 and blk._attn_mask is not None
    x = P.to_tensor(np.random.RandomState(7)
                    .randn(2, 64, 8).astype(np.float32))
    out = blk(x).numpy()

    # manual reference: same modules, composed by hand
    import jax.numpy as jnp_

    xs = blk.norm1(x).numpy().reshape(2, 8, 8, 8)
    qkv = np.asarray(
        blk.attn.qkv(P.to_tensor(xs.reshape(2, 64, 8)))._value
    ).reshape(2, 8, 8, 24)
    bias = blk.attn.dense_bias().numpy()
    ref_attn = WA.window_attention_ref(
        jnp_.asarray(qkv), jnp_.asarray(bias),
        jnp_.asarray(blk._attn_mask.numpy()), window_size=4, shift=2,
        num_heads=2)
    proj = blk.attn.proj(P.to_tensor(
        np.asarray(ref_attn).reshape(2, 64, 8)))
    mid = x.numpy() + proj.numpy()
    ref = mid + blk.mlp(blk.norm2(P.to_tensor(mid))).numpy()
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_swin_rel_bias_still_trains():
    """Gradient flows to the tied rel-pos table through the dense
    one-hot matmul (the satellite must not silently freeze it)."""
    from paddle_tpu.vision.models.swin import SwinBlock

    P.seed(2)
    blk = SwinBlock(dim=8, input_resolution=(8, 8), num_heads=2,
                    window_size=4, shift_size=0)
    x = P.to_tensor(np.random.RandomState(8)
                    .randn(1, 64, 8).astype(np.float32))
    P.mean(P.square(blk(x))).backward()
    g = blk.attn.rel_bias.grad
    assert g is not None
    assert float(np.abs(g.numpy()).max()) > 0.0


# ===================== conv+norm+act =====================


@pytest.mark.parametrize(
    "shape,stride,pad,dw,act",
    [((2, 3, 16, 16, 8, 7), 2, 3, False, "relu"),    # 7x7/2 stem
     ((2, 8, 14, 14, 16, 3), 1, 1, False, "relu"),   # 3x3 block
     ((2, 8, 14, 14, 16, 1), 1, 0, False, None),     # 1x1 projection
     ((1, 6, 7, 7, 6, 3), 2, 1, True, "relu6"),      # depthwise, odd HW
     ((1, 4, 9, 11, 7, 3), 2, 1, False, "relu")])    # odd H/W edge tiles
def test_conv_bn_act_kernel_matches_ref(shape, stride, pad, dw, act):
    B, Ci, H, W, Co, k = shape
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(B, Ci, H, W), jnp.float32)
    w = jnp.asarray(rs.randn(Co, 1 if dw else Ci, k, k),
                    jnp.float32) * 0.2
    sc = jnp.asarray(rs.rand(Co) + 0.5, jnp.float32)
    sh = jnp.asarray(rs.randn(Co), jnp.float32)
    ref = CN.conv_bn_act_ref(x, w, sc, sh, stride=stride, padding=pad,
                             act=act, depthwise=dw)
    h_out = (H + 2 * pad - k) // stride + 1
    for rows in sorted({1, h_out}):
        if h_out % rows:
            continue
        out = CN._conv_pallas(x, w, sc, sh, (stride, stride),
                              (pad, pad), act, dw, rows)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-5,
            err_msg=f"rows={rows}")


def test_conv_bn_act_helper_folding_matches_composed():
    """`_fused.conv_bn_act` in eval+no_grad (the fused-eligible route,
    folded scale/shift) equals the composed bn(conv(x))+relu ops."""
    from paddle_tpu import nn
    from paddle_tpu.vision.models._fused import conv_bn_act

    P.seed(3)
    conv = nn.Conv2D(4, 6, 3, stride=2, padding=1)
    bn = nn.BatchNorm2D(6)
    # non-trivial running stats + affine
    bn._mean.set_value(np.random.RandomState(1)
                       .randn(6).astype(np.float32))
    bn._variance.set_value((np.random.RandomState(2).rand(6) + 0.5)
                           .astype(np.float32))
    bn.weight.set_value((np.random.RandomState(3).rand(6) + 0.5)
                        .astype(np.float32))
    bn.bias.set_value(np.random.RandomState(4)
                      .randn(6).astype(np.float32))
    conv.eval()
    bn.eval()
    x = P.to_tensor(np.random.RandomState(5)
                    .rand(2, 4, 9, 9).astype(np.float32))
    with P.no_grad():
        fused = conv_bn_act(x, conv, bn, "relu").numpy()
    composed = nn.functional.relu(bn(conv(x))).numpy()
    np.testing.assert_allclose(fused, composed, atol=1e-5, rtol=1e-5)


def test_conv_bn_act_training_stays_composed():
    """Training mode must NOT fold (batch norm needs live batch stats):
    the helper routes to the composed ops and running stats update."""
    from paddle_tpu import nn
    from paddle_tpu.vision.models._fused import conv_bn_act

    P.seed(4)
    conv = nn.Conv2D(3, 4, 3, padding=1)
    bn = nn.BatchNorm2D(4)
    conv.train()
    bn.train()
    before = bn._mean.numpy().copy()
    x = P.to_tensor(np.random.RandomState(6)
                    .rand(2, 3, 8, 8).astype(np.float32) + 1.0)
    out = conv_bn_act(x, conv, bn, "relu")
    assert out.shape == [2, 4, 8, 8]
    assert not np.array_equal(before, bn._mean.numpy()), \
        "training batch-norm stats did not update — fused path leaked " \
        "into training"


def test_conv_bn_act_dispatch_counter():
    """The public fused entry counts its tier (fallback on CPU)."""
    from paddle_tpu import observability as obs

    obs.attach()
    try:
        rs = np.random.RandomState(9)
        x = jnp.asarray(rs.randn(1, 3, 8, 8), jnp.float32)
        w = jnp.asarray(rs.randn(4, 3, 3, 3), jnp.float32)
        CN.fused_conv_bn_act(x, w, jnp.ones((4,)), jnp.zeros((4,)),
                             stride=1, padding=1, act="relu")
        counters = obs.metrics.snapshot().get("counters", {})
        assert any("conv_norm.dispatch" in k and "fallback" in k
                   for k in counters), counters
    finally:
        obs.detach()


def test_resnet_eval_fused_route_matches_disabled():
    """ResNet18 eval forward is identical with the fused tier enabled
    vs FLAGS_disable_pallas_conv_norm (on CPU both run reference math —
    the equality proves the folding + routing, not the kernel)."""
    from paddle_tpu.core import flags
    from paddle_tpu.vision import models as V

    P.seed(5)
    m = V.resnet18(num_classes=4)
    m.eval()
    x = P.to_tensor(np.random.RandomState(10)
                    .rand(1, 3, 32, 32).astype(np.float32))
    with P.no_grad():
        a = m(x).numpy()
    flags.set_flags({"FLAGS_disable_pallas_conv_norm": True})
    try:
        with P.no_grad():
            b = m(x).numpy()
    finally:
        flags.set_flags({"FLAGS_disable_pallas_conv_norm": False})
    np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)


def test_fused_conv_vjp_matches_ref_grads():
    """jax.grad THROUGH the fused tier (`_conv_pallas_vjp`, the path
    `fused_conv_bn_act` dispatches on TPU) is bit-identical to the
    reference grads: the custom VJP runs the Pallas forward and replays
    the composed-ops backward, so frozen-BN fine-tuning / input-gradient
    probes under jit neither crash on a missing pallas AD rule nor drift
    from the composed path's gradients."""
    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.randn(2, 3, 8, 8), jnp.float32)
    w = jnp.asarray(rs.randn(4, 3, 3, 3), jnp.float32) * 0.2
    sc = jnp.asarray(rs.rand(4) + 0.5, jnp.float32)
    sh = jnp.asarray(rs.randn(4), jnp.float32)
    cfg = ((1, 1), (1, 1), "relu", False, 8)

    def loss_fused(*a):
        return CN._conv_pallas_vjp(cfg, *a).astype(jnp.float32).sum()

    def loss_ref(*a):
        return CN.conv_bn_act_ref(*a, stride=(1, 1), padding=(1, 1),
                                  act="relu").astype(jnp.float32).sum()

    g_fused = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(x, w, sc, sh)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(x, w, sc, sh)
    for name, a, b in zip(("dx", "dw", "dscale", "dshift"),
                          g_fused, g_ref):
        assert np.array_equal(np.asarray(a), np.asarray(b)), name
    # and under jit (the frozen-BN fine-tune shape of the failure)
    g_jit = jax.jit(jax.grad(loss_fused))(x, w, sc, sh)
    assert np.array_equal(np.asarray(g_jit), np.asarray(g_fused[0]))


def test_chip_session_swin_ablation_variants_run():
    """chip_session's phase_vision_breakdown monkey-patches
    WindowAttention.forward with ablated bodies; they must track the
    CURRENT forward contract (image-layout input, mask+shift kwargs —
    ISSUE 10) or the next hardware window silently loses the PERF.md
    Swin ablation rows to per-kind try/except. Runs each ablated kind
    through a real (tiny, shifted) Swin forward on CPU."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "_chip_session", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "chip_session.py"))
    cs = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cs)

    from paddle_tpu.vision.models import swin as swin_mod

    P.seed(0)
    model = swin_mod.SwinTransformer(img_size=32, patch_size=4,
                                     embed_dim=16, depths=(2,),
                                     num_heads=(2,), window_size=4,
                                     num_classes=4)
    rs = np.random.RandomState(0)
    x = P.to_tensor(rs.rand(2, 3, 32, 32).astype(np.float32))
    orig = swin_mod.WindowAttention.forward
    try:
        ref = np.asarray(model(x).numpy())
        for kind in ("no_bias", "mm_only", "identity"):
            swin_mod.WindowAttention.forward = (
                cs._swin_attention_variant(kind))
            out = model(x).numpy()
            assert out.shape == ref.shape and np.isfinite(out).all(), \
                kind
    finally:
        swin_mod.WindowAttention.forward = orig
