"""Distributed stack tests on the virtual 8-device CPU mesh (SURVEY §4 note:
mesh emulation via xla_force_host_platform_device_count)."""
import numpy as np
import pytest

import jax

import paddle_tpu as P
from paddle_tpu.distributed import fleet, topology
from paddle_tpu.distributed.auto_parallel import (
    ProcessMesh, Replicate, Shard, reshard, shard_tensor,
)


@pytest.fixture(autouse=True)
def fresh_topology():
    topology.reset_topology()
    yield
    topology.reset_topology()


def _init(dp=2, mp=2, sep=1, sharding_stage=0):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": dp, "mp_degree": mp, "pp_degree": 1, "sep_degree": sep,
        "sharding_degree": dp,
    }
    if sharding_stage:
        strategy.sharding = True
        strategy.sharding_configs = {"stage": sharding_stage}
    fleet.init(is_collective=True, strategy=strategy)
    return strategy


def test_topology_axes():
    _init(dp=2, mp=4)
    topo = fleet.get_hybrid_communicate_group()
    assert topo.spmd_mesh.shape["dp"] == 2
    assert topo.spmd_mesh.shape["mp"] == 4


def test_shard_tensor_and_reshard():
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["x", "y"])
    data = np.arange(64, dtype=np.float32).reshape(8, 8)
    t = shard_tensor(data, mesh, [Shard(0), Shard(1)])
    assert t.dist_attr is not None
    np.testing.assert_allclose(t.numpy(), data)  # global view unchanged
    r = reshard(t, mesh, [Replicate(), Replicate()])
    np.testing.assert_allclose(r.numpy(), data)
    # sharded layout actually covers distinct devices
    assert len(t._value.sharding.device_set) == 8


def test_collective_allreduce_eager():
    _init(dp=8, mp=1)
    from paddle_tpu.distributed import all_reduce

    from jax.sharding import NamedSharding, PartitionSpec as Pt

    topo = fleet.get_hybrid_communicate_group()
    # a dp-sharded array: each of 8 shards holds one row
    x = jax.device_put(
        np.ones((8, 4), np.float32),
        NamedSharding(topo.spmd_mesh, Pt("dp")))
    t = P.Tensor(x)
    all_reduce(t)
    # psum over dp of per-shard rows: every row becomes sum of its own shard
    # value across the axis => shape preserved, values * 1 (each shard had
    # distinct rows) — verify shape/finite rather than exact semantics here
    assert t.shape == [8, 4]
    assert np.isfinite(t.numpy()).all()


def test_dp_training_loss_decreases():
    _init(dp=8, mp=1)
    model = fleet.distributed_model(
        __import__("paddle_tpu").nn.Linear(16, 4))
    opt = fleet.distributed_optimizer(
        P.optimizer.SGD(parameters=model.parameters(), learning_rate=0.5))

    import paddle_tpu.nn as nn

    loss_fn = nn.MSELoss()
    x = P.randn([16, 16])
    y = P.randn([16, 4])
    losses = [float(model.train_batch((x, y), optimizer=opt,
                                      loss_fn=loss_fn)) for _ in range(8)]
    assert losses[-1] < losses[0]


def test_tp_matches_single_device():
    """TP-sharded GPT forward == replicated forward (numerical parity of the
    sharding recipe — the core mpu contract)."""
    from paddle_tpu.models.gpt import (
        GPTForCausalLM, GPTPretrainingCriterion, gpt_tiny,
    )

    P.seed(0)
    cfg = gpt_tiny()
    _init(dp=1, mp=4)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    ids = P.randint(0, cfg.vocab_size, [2, 16])
    labels = P.randint(0, cfg.vocab_size, [2, 16])

    model.eval()
    eager_loss = float(crit(model(ids), labels))

    dist_model = fleet.distributed_model(model)
    opt = fleet.distributed_optimizer(
        P.optimizer.SGD(parameters=model.parameters(), learning_rate=0.0))
    step = dist_model.build_train_step(opt, crit)
    dist_loss = float(step(ids, labels))
    np.testing.assert_allclose(dist_loss, eager_loss, rtol=2e-4)


def test_zero_stages_shard_state():
    from paddle_tpu.models.gpt import (
        GPTForCausalLM, GPTPretrainingCriterion, gpt_tiny,
    )

    P.seed(0)
    cfg = gpt_tiny()
    _init(dp=4, mp=2, sharding_stage=3)
    model = fleet.distributed_model(GPTForCausalLM(cfg))
    opt = fleet.distributed_optimizer(
        P.optimizer.AdamW(parameters=model.parameters(), learning_rate=1e-3))
    crit = GPTPretrainingCriterion()
    ids = P.randint(0, cfg.vocab_size, [4, 16])
    labels = P.randint(0, cfg.vocab_size, [4, 16])
    l0 = float(model.train_batch((ids, labels), optimizer=opt, loss_fn=crit))
    l1 = float(model.train_batch((ids, labels)))
    assert np.isfinite(l0) and np.isfinite(l1) and l1 < l0
    ts = model._train_step
    p_specs = [str(v.sharding.spec) for v in ts._state["params"].values()]
    assert any("dp" in s for s in p_specs), "stage3 must dp-shard params"
    s_specs = [str(v.sharding.spec)
               for sd in ts._state["opt"]["slots"].values()
               for v in sd.values()]
    assert any("dp" in s for s in s_specs), "opt slots must be dp-sharded"


def test_recompute_matches():
    from paddle_tpu.models.gpt import (
        GPTForCausalLM, GPTPretrainingCriterion, gpt_tiny,
    )

    _init(dp=2, mp=2)
    crit = GPTPretrainingCriterion()
    losses = {}
    # remat policies only change WHAT XLA saves vs replays — every
    # variant must train identically to the no-remat baseline ("dots"
    # is covered by the same plumbing; kept out of the fast tier to
    # save one full distributed compile)
    for rc in (False, True, "dots_no_batch"):
        P.seed(0)
        topology.reset_topology()
        _init(dp=2, mp=2)
        cfg = gpt_tiny(recompute=bool(rc), dropout=0.0,
                       recompute_policy=rc if isinstance(rc, str) else None)
        model = fleet.distributed_model(GPTForCausalLM(cfg))
        opt = fleet.distributed_optimizer(
            P.optimizer.SGD(parameters=model.parameters(), learning_rate=0.1))
        ids = P.randint(0, cfg.vocab_size, [2, 16])
        labels = P.randint(0, cfg.vocab_size, [2, 16])
        P.seed(1)  # same data
        ids = P.randint(0, cfg.vocab_size, [2, 16])
        labels = P.randint(0, cfg.vocab_size, [2, 16])
        l = [float(model.train_batch((ids, labels), optimizer=opt,
                                     loss_fn=crit)) for _ in range(2)]
        losses[rc] = l
    for rc in (True, "dots_no_batch"):
        np.testing.assert_allclose(losses[False], losses[rc], rtol=1e-4,
                                   err_msg=f"policy={rc}")
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.distributed.recompute import recompute as _rec

    with pytest.raises(ValueError, match="recompute policy"):
        with _flags.trace_guard():
            _rec(lambda x: x, P.ones([2]), policy="bogus")


@pytest.mark.slow
def test_graft_entry():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "__graft_entry__", os.path.join(os.path.dirname(__file__), "..",
                                        "__graft_entry__.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == 2
    mod.dryrun_multichip(8)


@pytest.mark.slow
def test_auto_parallel_engine_plans_and_fits():
    """Static auto-parallel Engine (engine.py role): the cost-model
    planner picks a feasible (dp, mp, pp) factorization of the mesh and
    the compiled step trains under it."""
    import jax

    from paddle_tpu.distributed.engine import Engine, plan
    from paddle_tpu.models.gpt import (
        GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
    )

    topology.reset_topology()
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=32)
    model = GPTForCausalLM(cfg)

    cands = plan(model, n_devices=8, global_batch=8, seq_len=32)
    assert cands, "planner returned nothing"
    best = cands[0]
    assert best.dp * best.mp * best.pp == 8
    assert best.est_time_s > 0 and best.est_mem_bytes > 0
    # ranked best-first by the cost model
    times = [c.est_time_s for c in cands]
    assert times == sorted(times)

    eng = Engine(model=model, loss=GPTPretrainingCriterion(),
                 optimizer=P.optimizer.AdamW(
                     parameters=model.parameters(), learning_rate=1e-3))
    # pp>1 engines need the pipeline runner; force a pp=1 plan for the
    # compiled-step smoke leg
    forced = next(c for c in cands if c.pp == 1)
    eng.strategy = forced.as_strategy()
    eng.prepare(global_batch=8, seq_len=32)
    rs = np.random.RandomState(0)
    ids = P.to_tensor(rs.randint(0, 256, (8, 32)), "int32")
    labels = P.to_tensor(rs.randint(0, 256, (8, 32)), "int32")
    losses = []
    for _ in range(3):
        loss = eng._step(ids, labels)
        losses.append(float(np.asarray(loss._value)))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_distributed_surface_complete_vs_reference():
    import ast
    import os

    ref = "/root/reference/python/paddle/distributed/__init__.py"
    if not os.path.exists(ref):
        pytest.skip("reference not mounted")
    names = []
    for node in ast.walk(ast.parse(open(ref).read())):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    names = [e.value for e in node.value.elts
                             if isinstance(e, ast.Constant)]
    from paddle_tpu import distributed as D

    missing = [n for n in names if not hasattr(D, n)]
    assert not missing, f"distributed missing: {missing}"


@pytest.mark.slow
def test_distributed_split_and_to_static():
    from paddle_tpu import distributed as D
    from paddle_tpu.models.gpt import (
        GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
    )

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                               "pp_degree": 1, "sep_degree": 1,
                               "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    P.seed(0)
    x = P.to_tensor(np.random.RandomState(0).randn(4, 8).astype(np.float32))
    out = D.split(x, (8, 6), operation="linear", axis=1)
    assert out.shape == [4, 6]
    ids = P.to_tensor(np.array([[1, 2], [3, 4]], np.int32))
    emb = D.split(ids, (16, 8), operation="embedding")
    assert emb.shape == [2, 2, 8]

    # to_static facade: DistModel runs a compiled step
    topology.reset_topology()
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=1,
                    num_heads=2, max_seq_len=16)
    model = GPTForCausalLM(cfg)
    strat = D.Strategy({"hybrid_configs": {
        "dp_degree": 2, "mp_degree": 1, "pp_degree": 1,
        "sep_degree": 1, "sharding_degree": 1}})
    dm = D.to_static(model, loss=GPTPretrainingCriterion(),
                     optimizer=P.optimizer.AdamW(
                         parameters=model.parameters(),
                         learning_rate=1e-3),
                     strategy=strat)
    rs = np.random.RandomState(0)
    ids = P.to_tensor(rs.randint(0, 128, (4, 16)), "int32")
    l1 = float(np.asarray(dm(ids, ids)._value))
    l2 = float(np.asarray(dm(ids, ids)._value))
    assert np.isfinite(l1) and l2 < l1
    # PS-era entries stay loudly gated
    with pytest.raises(NotImplementedError):
        D.QueueDataset()


def test_run_steps_matches_sequential_calls():
    """N steps in one scanned program == N individual compiled steps
    (same state evolution, same per-step losses)."""
    from paddle_tpu.models.gpt import (
        GPTForCausalLM, GPTPretrainingCriterion, gpt_tiny,
    )

    cfg = gpt_tiny()
    _init(dp=2, mp=2)
    rs = np.random.RandomState(0)
    ids_np = rs.randint(0, cfg.vocab_size, (3, 4, 16))
    lab_np = rs.randint(0, cfg.vocab_size, (3, 4, 16))

    def build():
        P.seed(0)
        m = fleet.distributed_model(GPTForCausalLM(cfg))
        o = fleet.distributed_optimizer(
            P.optimizer.AdamW(parameters=m.parameters(), learning_rate=1e-3))
        return m.build_train_step(o, GPTPretrainingCriterion())

    step_a = build()
    seq = [float(step_a(P.to_tensor(ids_np[i], "int32"),
                        P.to_tensor(lab_np[i], "int32")))
           for i in range(3)]

    step_b = build()
    losses = step_b.run_steps(P.to_tensor(ids_np, "int32"),
                              P.to_tensor(lab_np, "int32"))
    np.testing.assert_allclose(np.asarray(losses._value), seq, rtol=2e-4)


def test_run_steps_scheduler_semantics():
    """Scheduler mode (lrs=None) consumes the next n_steps of the schedule
    and advances the scheduler, matching sequential __call__+step();
    explicit lrs leaves the scheduler position untouched (caller-owned)
    (r3 review + r3 ADVICE: stale schedule position after run_steps)."""
    from paddle_tpu.models.gpt import (
        GPTForCausalLM, GPTPretrainingCriterion, gpt_tiny,
    )

    cfg = gpt_tiny()
    _init(dp=1, mp=1)
    P.seed(0)
    sched = P.optimizer.lr.StepDecay(learning_rate=1e-3, step_size=1,
                                     gamma=0.5)
    m = fleet.distributed_model(GPTForCausalLM(cfg))
    o = fleet.distributed_optimizer(
        P.optimizer.AdamW(parameters=m.parameters(), learning_rate=sched))
    step = m.build_train_step(o, GPTPretrainingCriterion())
    ids = P.to_tensor(np.zeros((2, 2, 16), np.int64), "int32")
    lab = P.to_tensor(np.zeros((2, 2, 16), np.int64), "int32")
    lr0 = float(o.get_lr())
    losses = step.run_steps(ids, lab)  # 2 steps off the schedule
    assert np.isfinite(np.asarray(losses._value)).all()
    # StepDecay gamma=0.5 per step: after 2 consumed steps lr = lr0/4
    np.testing.assert_allclose(float(o.get_lr()), lr0 * 0.25, rtol=1e-6)
    # explicit lrs: scheduler untouched
    before = float(o.get_lr())
    losses = step.run_steps(ids, lab, lrs=[1e-3, 5e-4])
    assert np.isfinite(np.asarray(losses._value)).all()
    assert float(o.get_lr()) == before


def test_run_steps_repeat_matches_stacked():
    """repeat=N over one batch == N stacked copies of that batch."""
    from paddle_tpu.models.gpt import (
        GPTForCausalLM, GPTPretrainingCriterion, gpt_tiny,
    )

    cfg = gpt_tiny()
    _init(dp=2, mp=1)
    rs = np.random.RandomState(7)
    ids1 = rs.randint(0, cfg.vocab_size, (4, 16))
    lab1 = rs.randint(0, cfg.vocab_size, (4, 16))

    def build():
        P.seed(0)
        m = fleet.distributed_model(GPTForCausalLM(cfg))
        o = fleet.distributed_optimizer(
            P.optimizer.AdamW(parameters=m.parameters(), learning_rate=1e-3))
        return m.build_train_step(o, GPTPretrainingCriterion())

    sa = build()
    stacked = sa.run_steps(
        P.to_tensor(np.broadcast_to(ids1, (3, 4, 16)).copy(), "int32"),
        P.to_tensor(np.broadcast_to(lab1, (3, 4, 16)).copy(), "int32"))
    sb = build()
    repeated = sb.run_steps(P.to_tensor(ids1, "int32"),
                            P.to_tensor(lab1, "int32"), repeat=3)
    np.testing.assert_allclose(np.asarray(repeated._value),
                               np.asarray(stacked._value), rtol=2e-4)


@pytest.mark.slow
def test_engine_search_validates_against_compiler():
    """Engine.search (VERDICT r4 Next #6): enumerate placements, rank
    analytically, compile the leaders on the live mesh, audit the
    predicted comm bytes against the collectives GSPMD actually inserted,
    and pick the winner on the measured-informed estimate."""
    from paddle_tpu.distributed.engine import Engine
    from paddle_tpu.models.gpt import (
        GPTConfig, GPTForCausalLM, GPTPretrainingCriterion,
    )

    topology.reset_topology()
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=32)
    eng = Engine(model=GPTForCausalLM(cfg),
                 loss=GPTPretrainingCriterion())
    rs = np.random.RandomState(0)
    xs = rs.randint(0, 256, (8, 32)).astype(np.int32)
    ys = rs.randint(0, 256, (8, 32)).astype(np.int32)
    best, trials = eng.search(
        model_factory=lambda: GPTForCausalLM(cfg),
        optimizer_factory=lambda params: P.optimizer.AdamW(
            parameters=params, learning_rate=1e-3),
        sample_batch=(xs, ys), global_batch=8, seq_len=32, top_k=3)
    # >=3 plans validated against compiler ground truth, within tolerance
    assert len(trials) >= 3, trials
    for t in trials:
        assert t["measured_bytes"] > 0, t
        assert 1 / 3 <= t["agreement"] <= 3, (
            f"predicted comm bytes disagree with compiler truth: {t}")
    s = best["strategy"]
    assert s["dp_degree"] * s["mp_degree"] * s["pp_degree"] == 8
    assert best["measured_time_s"] == min(
        t["measured_time_s"] for t in trials)
    # the engine carries the winner: prepare + one step trains under it,
    # including the ZeRO stage the search measured (not silently stage-0)
    eng.prepare(global_batch=8, seq_len=32)
    assert eng._step.sharding_stage == s.get("sharding_stage", 0)
    loss = eng._step(P.to_tensor(xs), P.to_tensor(ys))
    assert np.isfinite(float(np.asarray(loss._value)))


def test_completion_reshard_evidence():
    """distributed.completion: the compiled hybrid step must show GSPMD's
    completion (per-value shardings incl. the mp axis) and reshard
    (inserted collectives with nonzero bytes) — planner claims are
    auditable against the program that runs (r3 VERDICT: static
    auto-parallel depth)."""
    from paddle_tpu.distributed import completion
    from paddle_tpu.models.gpt import (
        GPTForCausalLM, GPTPretrainingCriterion, gpt_tiny,
    )

    _init(dp=2, mp=2, sep=2, sharding_stage=2)
    P.seed(0)
    cfg = gpt_tiny(sequence_parallel=True)
    m = fleet.distributed_model(GPTForCausalLM(cfg))
    o = fleet.distributed_optimizer(
        P.optimizer.AdamW(parameters=m.parameters(), learning_rate=1e-4))
    step = m.build_train_step(o, GPTPretrainingCriterion(),
                              amp_dtype="bfloat16")
    ids = P.randint(0, cfg.vocab_size, [4, 64])
    lab = P.randint(0, cfg.vocab_size, [4, 64])
    rep = completion.analyze(step, ids, lab)
    assert rep["mesh"] == {"dp": 2, "sep": 2, "mp": 2}
    sh = rep["shardings"]
    assert sh["n_annotated"] > 0
    # Shardy lowering names axes ("mp"); older GSPMD lowering emits
    # device arrays ("devices=[...]") — accept either
    assert any("mp" in spec or "devices=" in spec
               for spec in sh["by_spec"]), sh["by_spec"]
    # completion ground truth: the partitioner assigned shardings too
    assert sh["n_propagated"] > 0, "no compiler-propagated shardings"
    co = rep["collectives"]
    kinds = set(co["totals"])
    assert "all-reduce" in kinds, kinds       # grad/TP reductions
    assert co["total_bytes"] > 0
    assert all(op["bytes"] > 0 for op in co["ops"])
    # the report renders
    text = completion.format_report(rep)
    assert "collectives inserted" in text
    # lower() must not advance state: a subsequent real step still runs
    loss = float(step(ids, lab))
    assert np.isfinite(loss)
