"""OPS_MANIFEST drift check + correctness tests for the manifest-closure op
batch (inplace variants, losses, pooling masks, detection ops)."""
import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu as P
import paddle_tpu.nn.functional as F

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def test_manifest_no_drift_and_coverage():
    from gen_op_manifest import REF, generate

    if not os.path.exists(REF):
        pytest.skip("reference checkout not available on this host — "
                    "the manifest regenerates from the reference op "
                    "inventory (tools/gen_op_manifest.py REF)")
    with open(os.path.join(REPO, "OPS_MANIFEST.json")) as f:
        recorded = json.load(f)
    current = generate()
    assert current["present"] >= recorded["present"], (
        "op coverage regressed — fix or regenerate OPS_MANIFEST.json")
    assert current["coverage_pct"] >= 95.0
    cur_names = {e["name"]: (e["present"], e["internal"])
                 for e in current["ops"]}
    rec_names = {e["name"]: (e["present"], e["internal"])
                 for e in recorded["ops"]}
    assert cur_names == rec_names, "manifest drift — regenerate"


def test_op_table_generated_no_drift():
    """The emitted op table is a pure function of the recorded manifest
    (VERDICT r4 Next #7): hand edits to either side fail here."""
    from gen_op_manifest import OP_TABLE_PATH, emit_op_table

    with open(os.path.join(REPO, "OPS_MANIFEST.json")) as f:
        recorded = json.load(f)
    with open(OP_TABLE_PATH) as f:
        on_disk = f.read()
    assert emit_op_table(recorded) == on_disk, (
        "generated op table drifted — regenerate with "
        "python tools/gen_op_manifest.py --emit")
    from gen_op_manifest import OPS_DOC_PATH, emit_ops_doc

    with open(OPS_DOC_PATH) as f:
        doc_on_disk = f.read()
    assert emit_ops_doc(recorded) == doc_on_disk, (
        "generated docs/OPS.md drifted — regenerate with "
        "python tools/gen_op_manifest.py --emit")


def test_op_table_validates_against_live_package():
    """Every generated surface entry must resolve in the live package —
    the manifest→runtime direction of the drift guard."""
    from paddle_tpu.ops import _op_table

    assert _op_table.validate() == []


# --------------------------- inplace variants ---------------------------

def test_inplace_variants_exist_and_rebind():
    x = P.to_tensor(np.array([0.5, 1.0], np.float32))
    y = x.sin_()
    assert y is x
    np.testing.assert_allclose(x.numpy(), np.sin([0.5, 1.0]), rtol=1e-6)
    # module-level form too
    z = P.to_tensor(np.array([4.0], np.float32))
    P.sqrt_(z)
    np.testing.assert_allclose(z.numpy(), [2.0], rtol=1e-6)


def test_inplace_grad_flows():
    x = P.to_tensor(np.array([0.3, 0.7], np.float32), stop_gradient=False)
    y = (x * 2.0)
    y.exp_()
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), 2 * np.exp(2 * np.array(
        [0.3, 0.7], np.float32)), rtol=1e-5)


# ------------------------------ new math ------------------------------

def test_addmm_tril_triu_indices():
    a = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    x = np.random.RandomState(1).randn(3, 5).astype(np.float32)
    y = np.random.RandomState(2).randn(5, 4).astype(np.float32)
    out = P.addmm(P.to_tensor(a), P.to_tensor(x), P.to_tensor(y),
                  beta=0.5, alpha=2.0)
    np.testing.assert_allclose(out.numpy(), 0.5 * a + 2.0 * (x @ y),
                               rtol=1e-5)
    np.testing.assert_array_equal(
        P.tril_indices(4, 4, 0).numpy(), np.stack(np.tril_indices(4, 0, 4)))
    np.testing.assert_array_equal(
        P.triu_indices(3, 5, 1).numpy(), np.stack(np.triu_indices(3, 1, 5)))


def test_diag_embed_and_scatter():
    v = np.arange(6, dtype=np.float32).reshape(2, 3)
    out = P.diag_embed(P.to_tensor(v)).numpy()
    for b in range(2):
        np.testing.assert_array_equal(out[b], np.diag(v[b]))
    m = np.zeros((3, 3), np.float32)
    y = np.array([1.0, 2.0, 3.0], np.float32)
    ds = P.diagonal_scatter(P.to_tensor(m), P.to_tensor(y)).numpy()
    np.testing.assert_array_equal(np.diag(ds), y)


def test_gammaln_multigammaln_i_bessel():
    from scipy import special as sp  # available via jax.scipy parity check

    x = np.array([0.5, 1.5, 3.0], np.float32)
    np.testing.assert_allclose(P.gammaln(P.to_tensor(x)).numpy(),
                               sp.gammaln(x), rtol=1e-5)
    np.testing.assert_allclose(
        P.multigammaln(P.to_tensor(x + 2), 2).numpy(),
        sp.multigammaln(x + 2, 2), rtol=1e-5)
    np.testing.assert_allclose(P.i0e(P.to_tensor(x)).numpy(), sp.i0e(x),
                               rtol=1e-5)
    np.testing.assert_allclose(P.i1(P.to_tensor(x)).numpy(), sp.i1(x),
                               rtol=1e-5)


def test_vsplit_hsplit_unstack():
    m = np.arange(24, dtype=np.float32).reshape(4, 6)
    parts = P.vsplit(P.to_tensor(m), 2)
    assert len(parts) == 2 and parts[0].shape == [2, 6]
    parts = P.hsplit(P.to_tensor(m), 3)
    assert len(parts) == 3 and parts[0].shape == [4, 2]
    us = P.unstack(P.to_tensor(m), axis=0)
    assert len(us) == 4 and us[0].shape == [6]
    np.testing.assert_array_equal(us[1].numpy(), m[1])


def test_as_strided_and_slice_scatter():
    x = np.arange(12, dtype=np.float32)
    out = P.as_strided(P.to_tensor(x), [3, 4], [4, 1]).numpy()
    np.testing.assert_array_equal(out, x.reshape(3, 4))
    base = np.zeros((4, 4), np.float32)
    val = np.ones((2, 4), np.float32)
    ss = P.slice_scatter(P.to_tensor(base), P.to_tensor(val),
                         axes=[0], starts=[1], ends=[3], strides=[1]).numpy()
    assert ss[1:3].sum() == 8 and ss[0].sum() == 0


# ------------------------------ losses ------------------------------

def test_ctc_loss_matches_torch():
    torch = pytest.importorskip("torch")
    rs = np.random.RandomState(0)
    T, B, C, L = 12, 3, 6, 4
    logits = rs.randn(T, B, C).astype(np.float32)
    log_probs = torch.log_softmax(torch.tensor(logits), dim=-1)
    labels = rs.randint(1, C, (B, L)).astype(np.int32)
    in_len = np.array([12, 10, 8], np.int32)
    lab_len = np.array([4, 3, 2], np.int32)
    ref = torch.nn.functional.ctc_loss(
        log_probs, torch.tensor(labels.astype(np.int64)),
        torch.tensor(in_len.astype(np.int64)),
        torch.tensor(lab_len.astype(np.int64)),
        blank=0, reduction="none", zero_infinity=False).numpy()
    import jax

    lp = jax.nn.log_softmax(np.asarray(logits), axis=-1)
    out = F.ctc_loss(P.to_tensor(np.asarray(lp)), P.to_tensor(labels),
                     P.to_tensor(in_len), P.to_tensor(lab_len),
                     blank=0, reduction="none")
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)


def test_rnnt_loss_brute_force():
    rs = np.random.RandomState(1)
    B, T, U, V = 2, 4, 3, 5
    logits = rs.randn(B, T, U + 1, V).astype(np.float32)
    labels = rs.randint(1, V, (B, U)).astype(np.int32)
    in_len = np.array([4, 3], np.int32)
    lab_len = np.array([3, 2], np.int32)

    def brute(b):
        from scipy.special import log_softmax, logsumexp

        lp = log_softmax(logits[b], axis=-1)
        tt, uu = int(in_len[b]), int(lab_len[b])
        alpha = np.full((tt, uu + 1), -np.inf)
        alpha[0, 0] = 0.0
        for t in range(tt):
            for u in range(uu + 1):
                cands = []
                if t > 0:
                    cands.append(alpha[t - 1, u] + lp[t - 1, u, 0])
                if u > 0:
                    cands.append(alpha[t, u - 1]
                                 + lp[t, u - 1, labels[b, u - 1]])
                if cands:
                    alpha[t, u] = logsumexp(cands) if (t, u) != (0, 0) \
                        else alpha[0, 0]
        return -(alpha[tt - 1, uu] + lp[tt - 1, uu, 0])

    ref = np.array([brute(0), brute(1)], np.float32)
    out = F.rnnt_loss(P.to_tensor(logits), P.to_tensor(labels),
                      P.to_tensor(in_len), P.to_tensor(lab_len),
                      blank=0, reduction="none")
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)


def test_margin_cross_entropy_reduces_to_ce_without_margin():
    rs = np.random.RandomState(2)
    logits = np.clip(rs.randn(4, 10).astype(np.float32) * 0.3, -0.99, 0.99)
    label = rs.randint(0, 10, (4,)).astype(np.int64)
    out = F.margin_cross_entropy(P.to_tensor(logits), P.to_tensor(label),
                                 margin1=1.0, margin2=0.0, margin3=0.0,
                                 scale=1.0, reduction="none")
    import jax

    logp = jax.nn.log_softmax(logits, axis=-1)
    ref = -logp[np.arange(4), label].reshape(-1, 1)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-5)


# --------------------------- pooling + unpool ---------------------------

def test_max_pool2d_mask_and_unpool_roundtrip():
    rs = np.random.RandomState(3)
    x = rs.randn(2, 3, 8, 8).astype(np.float32)
    out, mask = F.max_pool2d(P.to_tensor(x), 2, 2, 0, return_mask=True)
    assert out.shape == [2, 3, 4, 4] and mask.shape == [2, 3, 4, 4]
    # indices point at the max elements
    flat = x.reshape(2, 3, -1)
    gathered = np.take_along_axis(flat, mask.numpy().reshape(2, 3, -1),
                                  axis=2).reshape(2, 3, 4, 4)
    np.testing.assert_allclose(gathered, out.numpy())
    up = F.max_unpool2d(out, mask, 2, 2, 0)
    assert up.shape == [2, 3, 8, 8]
    # unpooled tensor contains exactly the pooled maxima
    np.testing.assert_allclose(up.numpy().sum(), out.numpy().sum(),
                               rtol=1e-6)


# ----------------------------- detection -----------------------------

def test_box_coder_roundtrip():
    priors = np.array([[0., 0., 10., 10.], [5., 5., 15., 20.]], np.float32)
    var = np.full((2, 4), 0.1, np.float32)
    targets = np.array([[1., 1., 9., 9.], [6., 4., 14., 21.]], np.float32)
    from paddle_tpu.vision.ops import box_coder

    enc = box_coder(P.to_tensor(priors), P.to_tensor(var),
                    P.to_tensor(targets), code_type="encode_center_size")
    dec = box_coder(P.to_tensor(priors), P.to_tensor(var),
                    enc, code_type="decode_center_size", axis=0)
    d = dec.numpy()
    np.testing.assert_allclose(np.diagonal(d[:, :, :], axis1=0, axis2=1).T,
                               targets, rtol=1e-4, atol=1e-3)


def test_prior_box_shapes_and_range():
    from paddle_tpu.vision.ops import prior_box

    feat = P.zeros([1, 32, 4, 4])
    img = P.zeros([1, 3, 64, 64])
    boxes, var = prior_box(feat, img, min_sizes=[16.0], clip=True)
    assert boxes.shape[0] == 4 and boxes.shape[1] == 4
    b = boxes.numpy()
    assert b.min() >= 0.0 and b.max() <= 1.0
    assert var.shape == boxes.shape


def test_multiclass_nms_basic():
    from paddle_tpu.vision.ops import multiclass_nms

    boxes = np.array([[[0, 0, 10, 10], [0.5, 0.5, 10, 10],
                       [20, 20, 30, 30]]], np.float32)
    scores = np.zeros((1, 2, 3), np.float32)
    scores[0, 1] = [0.9, 0.85, 0.8]  # class 1 (0 = background)
    out, idx, num = multiclass_nms(
        P.to_tensor(boxes), P.to_tensor(scores), score_threshold=0.1,
        nms_threshold=0.5, background_label=0, return_index=True)
    assert int(num.numpy()[0]) == 2  # overlapping pair suppressed to one
    assert out.numpy().shape[1] == 6


def test_roi_pool_simple():
    from paddle_tpu.vision.ops import roi_pool

    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    rois = np.array([[0., 0., 3., 3.]], np.float32)
    out = roi_pool(P.to_tensor(x), P.to_tensor(rois),
                   P.to_tensor(np.array([1], np.int32)), 2)
    np.testing.assert_array_equal(out.numpy().reshape(2, 2),
                                  [[5, 7], [13, 15]])


def test_viterbi_decode_brute_force():
    rs = np.random.RandomState(4)
    B, T, N = 2, 5, 4
    emis = rs.randn(B, T, N).astype(np.float32)
    trans = rs.randn(N, N).astype(np.float32)
    lengths = np.array([5, 5], np.int64)
    scores, path = P.viterbi_decode(
        P.to_tensor(emis), P.to_tensor(trans), P.to_tensor(lengths),
        include_bos_eos_tag=False)
    # brute force over all tag sequences
    import itertools

    for b in range(B):
        best, best_seq = -np.inf, None
        for seq in itertools.product(range(N), repeat=T):
            s = emis[b, 0, seq[0]]
            for t in range(1, T):
                s += trans[seq[t - 1], seq[t]] + emis[b, t, seq[t]]
            if s > best:
                best, best_seq = s, seq
        np.testing.assert_allclose(scores.numpy()[b], best, rtol=1e-4)
        np.testing.assert_array_equal(path.numpy()[b], best_seq)


def test_edit_distance_known():
    a = np.array([[1, 2, 3, 4]], np.int64)
    b = np.array([[1, 3, 3, 5]], np.int64)
    d, n = P.edit_distance(P.to_tensor(a), P.to_tensor(b), normalized=False)
    assert float(d.numpy()[0, 0]) == 2.0


def test_gather_tree():
    ids = np.array([[[2, 2], [6, 1]], [[3, 9], [6, 1]],
                    [[0, 1], [9, 0]]], np.int64)
    parents = np.array([[[0, 0], [1, 1]], [[1, 0], [0, 0]],
                        [[0, 0], [0, 1]]], np.int64)
    out = P.gather_tree(P.to_tensor(ids), P.to_tensor(parents)).numpy()
    assert out.shape == ids.shape


def test_dy2static_ctc_and_extra_under_jit():
    """New ops must also run under trace (jit.to_static path)."""
    def f(x):
        return P.addmm(x, x, x, beta=1.0, alpha=1.0)

    x = P.to_tensor(np.eye(3, dtype=np.float32))
    static_f = P.jit.to_static(f)
    np.testing.assert_allclose(static_f(x).numpy(), f(x).numpy())
