"""Real ONNX emission tests: trace -> ONNX-17 protobuf -> numpy
reference evaluation matches the framework forward (no onnxruntime in
this image; the bundled evaluator implements exactly the emitted op set).
"""
import numpy as np
import pytest

import paddle_tpu as P
import paddle_tpu.nn as nn
from paddle_tpu.static import InputSpec


def _roundtrip(layer, path, *inputs, rtol=1e-4, atol=1e-5):
    import paddle_tpu.onnx as ponnx

    layer.eval()
    spec = [InputSpec(shape=list(x.shape), dtype=str(x.dtype))
            for x in inputs]
    out_path = ponnx.export(layer, str(path), input_spec=spec,
                            format="onnx")
    ref = layer(*[P.to_tensor(x) for x in inputs])
    got = ponnx.run_reference(out_path, list(inputs))
    (got_arr,) = got.values()
    np.testing.assert_allclose(got_arr, np.asarray(ref.numpy(), np.float32),
                               rtol=rtol, atol=atol)
    return out_path


def test_onnx_export_mlp(tmp_path):
    P.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4),
                        nn.Softmax())
    x = np.random.RandomState(0).randn(3, 8).astype(np.float32)
    path = _roundtrip(net, tmp_path / "mlp", x)
    # the file is standard ONNX: parseable, versioned, single graph
    from paddle_tpu.onnx._runtime import load_model

    m = load_model(path)
    assert m.ir_version == 8 and m.opset_import[0].version == 17
    assert m.producer_name == "paddle_tpu"
    assert len(m.graph.node) > 0
    ops = {n.op_type for n in m.graph.node}
    assert "Einsum" in ops or "Gemm" in ops  # the matmuls made it


def test_onnx_export_layernorm_gelu(tmp_path):
    P.seed(0)

    class Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.ln = nn.LayerNorm(12)
            self.fc = nn.Linear(12, 12)

        def forward(self, x):
            return nn.functional.gelu(self.fc(self.ln(x)))

    x = np.random.RandomState(1).randn(2, 5, 12).astype(np.float32)
    _roundtrip(Block(), tmp_path / "blk", x, rtol=1e-3, atol=1e-4)


def test_onnx_export_conv_pool(tmp_path):
    P.seed(0)
    net = nn.Sequential(nn.Conv2D(3, 4, 3, padding=1), nn.ReLU(),
                        nn.MaxPool2D(2, 2), nn.Flatten(),
                        nn.Linear(4 * 4 * 4, 5))
    x = np.random.RandomState(2).randn(2, 3, 8, 8).astype(np.float32)
    _roundtrip(net, tmp_path / "conv", x, rtol=1e-3, atol=1e-4)


def test_onnx_export_unsupported_is_loud(tmp_path):
    import paddle_tpu.onnx as ponnx

    class Weird(nn.Layer):
        def forward(self, x):
            return P.sort(x, axis=-1)  # sort prim is not exported

    x = np.random.RandomState(3).randn(2, 6).astype(np.float32)
    with pytest.raises(NotImplementedError, match="primitive"):
        ponnx.export(Weird(), str(tmp_path / "bad"),
                     input_spec=[InputSpec(shape=[2, 6], dtype="float32")],
                     format="onnx")
