"""MoE (expert parallel) + ring attention tests."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as P
from paddle_tpu.distributed import fleet, topology


@pytest.fixture(autouse=True)
def fresh_topology():
    topology.reset_topology()
    yield
    topology.reset_topology()


def _init(dp=2, mp=4, sep=1):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": mp,
                               "pp_degree": 1, "sep_degree": sep,
                               "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)


def test_moe_forward_backward_eager():
    from paddle_tpu.incubate import MoELayer

    P.seed(0)
    moe = MoELayer(d_model=32, d_hidden=64, num_experts=4, gate="gshard")
    x = P.randn([4, 8, 32])
    x.stop_gradient = False
    out = moe(x)
    assert out.shape == [4, 8, 32]
    (out.sum() + P.Tensor(moe.aux_loss._value
                          if hasattr(moe.aux_loss, "_value")
                          else moe.aux_loss)).backward()
    assert moe.w1.grad is not None
    assert moe.gate.weight.grad is not None


def test_moe_switch_gate():
    from paddle_tpu.incubate import MoELayer

    P.seed(0)
    moe = MoELayer(d_model=16, d_hidden=32, num_experts=2, gate="switch",
                   capacity_factor=2.0)
    x = P.randn([2, 8, 16])
    out = moe(x)
    assert out.shape == [2, 8, 16]
    assert np.isfinite(out.numpy()).all()


def test_moe_in_sharded_train_step():
    """MoE experts sharded over the mp axis inside the compiled step."""
    from paddle_tpu.incubate import MoELayer
    import paddle_tpu.nn as nn

    _init(dp=2, mp=4)

    class MoENet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.inp = nn.Linear(16, 32)
            self.moe = MoELayer(32, 64, num_experts=4)
            self.out = nn.Linear(32, 8)

        def forward(self, x):
            return self.out(self.moe(self.inp(x)))

    P.seed(0)
    model = fleet.distributed_model(MoENet())
    opt = fleet.distributed_optimizer(
        P.optimizer.AdamW(parameters=model.parameters(), learning_rate=1e-3))
    loss_fn = nn.MSELoss()
    x = P.randn([8, 4, 16])
    y = P.randn([8, 4, 8])
    losses = [float(model.train_batch((x, y), optimizer=opt,
                                      loss_fn=loss_fn)) for _ in range(4)]
    assert losses[-1] < losses[0]
    specs = [str(v.sharding.spec)
             for n, v in model._train_step._state["params"].items()
             if "moe.w" in n]
    assert all("mp" in s for s in specs), specs


def test_ring_attention_matches_reference():
    from paddle_tpu.ops.pallas.flash_attention import _ref_attention
    from paddle_tpu.ops.pallas.ring_attention import ring_attention

    _init(dp=2, mp=1, sep=4)
    topo = fleet.get_hybrid_communicate_group()
    rs = np.random.RandomState(0)
    B, S, H, D = 2, 64, 2, 16
    q = jnp.asarray(rs.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rs.randn(B, S, H, D), jnp.float32)
    v = jnp.asarray(rs.randn(B, S, H, D), jnp.float32)

    for causal in (False, True):
        out = ring_attention(q, k, v, mesh=topo.spmd_mesh, causal=causal)
        ref = _ref_attention(q, k, v, None, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5,
                                   err_msg=f"causal={causal}")


@pytest.mark.slow
def test_ring_attention_grad():
    from paddle_tpu.ops.pallas.flash_attention import _ref_attention
    from paddle_tpu.ops.pallas.ring_attention import ring_attention

    _init(dp=1, mp=1, sep=4)
    topo = fleet.get_hybrid_communicate_group()
    rs = np.random.RandomState(1)
    B, S, H, D = 1, 32, 2, 8
    q = jnp.asarray(rs.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rs.randn(B, S, H, D), jnp.float32)
    v = jnp.asarray(rs.randn(B, S, H, D), jnp.float32)

    g_ring = jax.grad(lambda *a: jnp.sum(
        ring_attention(*a, mesh=topo.spmd_mesh, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda *a: jnp.sum(
        _ref_attention(*a, None, True) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4,
                                   rtol=5e-4)


def test_gpt_with_sep_ring_attention():
    """GPT with context-parallel attention in the compiled hybrid step."""
    from paddle_tpu.models.gpt import (
        GPTForCausalLM, GPTPretrainingCriterion, gpt_tiny,
    )

    _init(dp=2, mp=2, sep=2)
    P.seed(0)
    cfg = gpt_tiny(sequence_parallel=True, context_parallel=True)
    model = fleet.distributed_model(GPTForCausalLM(cfg))
    opt = fleet.distributed_optimizer(
        P.optimizer.AdamW(parameters=model.parameters(), learning_rate=1e-3))
    crit = GPTPretrainingCriterion()
    ids = P.randint(0, cfg.vocab_size, [4, 32])
    labels = P.randint(0, cfg.vocab_size, [4, 32])
    losses = [float(model.train_batch((ids, labels), optimizer=opt,
                                      loss_fn=crit)) for _ in range(3)]
    assert losses[-1] < losses[0]


def test_ring_attention_uses_flash_blocks_when_tileable(monkeypatch):
    """Divisible shard shapes must take the VMEM-blocked flash ring (the
    long-context path: no O(s_local^2) logits in HBM); indivisible shapes
    fall back to the materialized-logits jnp body."""
    import paddle_tpu.ops.pallas.ring_attention as ra
    from paddle_tpu.ops.pallas import flash_attention as fa

    _init(dp=1, mp=1, sep=4)
    topo = fleet.get_hybrid_communicate_group()
    rs = np.random.RandomState(3)
    calls = {"fwd": 0}
    real_fwd = fa._fwd

    def counting_fwd(*a, **kw):
        calls["fwd"] += 1
        return real_fwd(*a, **kw)

    monkeypatch.setattr(fa, "_fwd", counting_fwd)

    B, S, H, D = 1, 64, 2, 16  # sl = 16: tileable
    q = jnp.asarray(rs.randn(B, S, H, D), jnp.float32)
    out = ra.ring_attention(q, q, q, mesh=topo.spmd_mesh, causal=True,
                            use_flash=True)
    ref = fa._ref_attention(q, q, q, None, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    assert calls["fwd"] > 0  # flash ring ran

    calls["fwd"] = 0
    S2 = 36  # sl = 9: not tileable -> jnp fallback
    q2 = jnp.asarray(rs.randn(B, S2, H, D), jnp.float32)
    out2 = ra.ring_attention(q2, q2, q2, mesh=topo.spmd_mesh, causal=True,
                             use_flash=True)
    ref2 = fa._ref_attention(q2, q2, q2, None, True)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref2),
                               atol=2e-5, rtol=2e-5)
    assert calls["fwd"] == 0  # fallback body, no flash kernel


def test_ring_attention_flash_path_grads():
    """Custom-VJP ring backward (dK/dV travel the ring) vs reference."""
    import paddle_tpu.ops.pallas.ring_attention as ra
    from paddle_tpu.ops.pallas import flash_attention as fa

    _init(dp=1, mp=1, sep=4)
    topo = fleet.get_hybrid_communicate_group()
    rs = np.random.RandomState(5)
    B, S, H, D = 1, 64, 2, 16
    q = jnp.asarray(rs.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rs.randn(B, S, H, D), jnp.float32)
    v = jnp.asarray(rs.randn(B, S, H, D), jnp.float32)
    for causal in (True, False):
        g_ring = jax.grad(lambda *a: jnp.sum(ra.ring_attention(
            *a, mesh=topo.spmd_mesh, causal=causal, use_flash=True) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(lambda *a: jnp.sum(fa._ref_attention(
            *a, None, causal) ** 2), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=3e-5, rtol=3e-5,
                                       err_msg=f"causal={causal}")
