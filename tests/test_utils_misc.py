"""utils/dlpack/onnx/hub/sysconfig + NaN-Inf watcher + amp debugging."""
import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu import amp, utils


def test_dlpack_roundtrip():
    x = P.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    cap = utils.dlpack.to_dlpack(x)
    y = utils.dlpack.from_dlpack(cap)
    np.testing.assert_array_equal(y.numpy(), x.numpy())


def test_unique_name():
    a = utils.unique_name.generate("fc")
    b = utils.unique_name.generate("fc")
    assert a != b and a.startswith("fc_")
    with utils.unique_name.guard("model/"):
        c = utils.unique_name.generate("fc")
        assert c.startswith("model/fc_")


def test_run_check_and_require_version():
    assert utils.run_check()
    assert utils.require_version("0.0.1")
    with pytest.raises(RuntimeError):
        utils.require_version("999.0.0")


def test_nan_inf_watcher():
    P.set_flags({"FLAGS_check_nan_inf": True})
    try:
        x = P.to_tensor(np.array([1.0, 0.0], np.float32))
        with pytest.raises(FloatingPointError) as e:
            P.divide(x, P.to_tensor(np.zeros(2, np.float32)))
        assert "Inf" in str(e.value)
        with pytest.raises(FloatingPointError):
            P.log(P.to_tensor(np.array([-1.0], np.float32)))
        # clean ops pass
        P.add(x, x)
    finally:
        P.set_flags({"FLAGS_check_nan_inf": False})


def test_nan_watcher_on_grad_path():
    P.set_flags({"FLAGS_check_nan_inf": True})
    try:
        x = P.to_tensor(np.array([0.0], np.float32), stop_gradient=False)
        with pytest.raises(FloatingPointError):
            P.rsqrt(x)  # 1/sqrt(0) = inf, on the autograd path
    finally:
        P.set_flags({"FLAGS_check_nan_inf": False})


def test_amp_operator_stats():
    with amp.debugging.collect_operator_stats():
        a = P.to_tensor(np.ones((2, 2), np.float32))
        P.matmul(a, a)
        P.add(a, a)
    stats = amp.debugging._stats
    assert any(k[0] == "matmul" for k in stats)


def test_onnx_export_stablehlo(tmp_path):
    import paddle_tpu.nn as nn
    from paddle_tpu import onnx, static

    m = nn.Linear(4, 2)
    p = onnx.export(m, str(tmp_path / "m"),
                    input_spec=[static.InputSpec([1, 4], "float32")])
    import os

    assert os.path.exists(p)
    # format="onnx" is now REAL emission (tests/test_onnx_export.py)
    p2 = onnx.export(m, str(tmp_path / "m2"), input_spec=[
        static.InputSpec([1, 4], "float32")], format="onnx")
    assert os.path.exists(p2) and p2.endswith(".onnx")


def test_hub_local(tmp_path):
    from paddle_tpu import hub

    (tmp_path / "hubconf.py").write_text(
        "def tiny_model(n=3):\n"
        "    'build a tiny model'\n"
        "    import paddle_tpu.nn as nn\n"
        "    return nn.Linear(n, n)\n")
    assert "tiny_model" in hub.list(str(tmp_path))
    assert "tiny" in hub.help(str(tmp_path), "tiny_model")
    m = hub.load(str(tmp_path), "tiny_model", n=5)
    assert m.weight.shape == [5, 5]
    with pytest.raises(RuntimeError):
        hub.load("user/repo", "x", source="github")


def test_sysconfig_paths():
    from paddle_tpu import sysconfig

    assert sysconfig.get_include().endswith("src")


def test_nn_utils_clip_and_vector():
    from paddle_tpu.nn.utils import (
        clip_grad_norm_, clip_grad_value_, parameters_to_vector,
        vector_to_parameters,
    )
    import paddle_tpu.nn as nn

    P.seed(0)
    lin = nn.Linear(4, 3)
    x = P.to_tensor(np.ones((2, 4), np.float32))
    (lin(x) * 100).sum().backward()
    total = clip_grad_norm_(lin.parameters(), max_norm=1.0)
    assert float(total.numpy()) > 1.0  # pre-clip norm was large
    gnorm = np.sqrt(sum(float((p.grad.numpy() ** 2).sum())
                        for p in lin.parameters()))
    np.testing.assert_allclose(gnorm, 1.0, rtol=1e-4)

    (lin(x) * 100).sum().backward()
    clip_grad_value_(lin.parameters(), 0.5)
    for p in lin.parameters():
        assert np.abs(p.grad.numpy()).max() <= 0.5 + 1e-6

    vec = parameters_to_vector(lin.parameters())
    assert vec.shape == [4 * 3 + 3]
    vector_to_parameters(vec * 0 + 1.0, lin.parameters())
    for p in lin.parameters():
        np.testing.assert_allclose(p.numpy(), 1.0)


def test_nn_utils_weight_norm_roundtrip():
    from paddle_tpu.nn.utils import remove_weight_norm, weight_norm
    import paddle_tpu.nn as nn

    P.seed(3)
    lin = nn.Linear(5, 4)
    w0 = lin.weight.numpy().copy()
    x = P.to_tensor(np.random.RandomState(1).randn(2, 5).astype(np.float32))
    y0 = lin(x).numpy()
    weight_norm(lin, "weight", dim=0)
    assert hasattr(lin, "weight_g") and hasattr(lin, "weight_v")
    # reparametrized forward must reproduce the original function
    np.testing.assert_allclose(lin(x).numpy(), y0, rtol=1e-5, atol=1e-6)
    # g/v are the trainable parameters now
    names = [n for n, _ in lin.named_parameters()]
    assert "weight_g" in names and "weight_v" in names
    remove_weight_norm(lin, "weight")
    np.testing.assert_allclose(lin(x).numpy(), y0, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(lin.weight.numpy(), w0, rtol=1e-5,
                               atol=1e-6)


def test_nn_utils_spectral_norm():
    from paddle_tpu.nn.utils import spectral_norm
    import paddle_tpu.nn as nn

    P.seed(4)
    lin = nn.Linear(6, 6)
    # give the weight a large known top singular value
    w = np.random.RandomState(2).randn(6, 6).astype(np.float32) * 5
    lin.weight.set_value(w)
    spectral_norm(lin, "weight", n_power_iterations=5)
    x = P.to_tensor(np.eye(6, dtype=np.float32))
    lin(x)  # triggers the hook
    eff = lin.weight.numpy()
    s = np.linalg.svd(eff, compute_uv=False)
    np.testing.assert_allclose(s[0], 1.0, rtol=1e-2)


def test_birnn_and_pairwise_distance():
    import paddle_tpu.nn as nn

    P.seed(0)
    cell_fw = nn.GRUCell(4, 6)
    cell_bw = nn.GRUCell(4, 6)
    rnn = nn.BiRNN(cell_fw, cell_bw)
    x = P.to_tensor(np.random.RandomState(0)
                    .randn(2, 5, 4).astype(np.float32))
    out, (st_f, st_b) = rnn(x)
    assert out.shape == [2, 5, 12]

    pd = nn.PairwiseDistance(p=2.0, epsilon=0.0)
    a = np.random.RandomState(1).randn(3, 4).astype(np.float32)
    b = np.random.RandomState(2).randn(3, 4).astype(np.float32)
    np.testing.assert_allclose(
        pd(P.to_tensor(a), P.to_tensor(b)).numpy(),
        np.linalg.norm(a - b, axis=-1), rtol=1e-5)


def test_register_pjrt_plugin_surface():
    """Custom-device plugin registration (device_ext.h role): loud on a
    missing library; discovery lists registered backends."""
    import pytest

    from paddle_tpu import device as D

    with pytest.raises(FileNotFoundError):
        D.register_pjrt_plugin("npu", "/nonexistent/libnpu_pjrt.so")
    backends = D.get_registered_backends()
    assert isinstance(backends, list) and "cpu" in backends
