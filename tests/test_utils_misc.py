"""utils/dlpack/onnx/hub/sysconfig + NaN-Inf watcher + amp debugging."""
import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu import amp, utils


def test_dlpack_roundtrip():
    x = P.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    cap = utils.dlpack.to_dlpack(x)
    y = utils.dlpack.from_dlpack(cap)
    np.testing.assert_array_equal(y.numpy(), x.numpy())


def test_unique_name():
    a = utils.unique_name.generate("fc")
    b = utils.unique_name.generate("fc")
    assert a != b and a.startswith("fc_")
    with utils.unique_name.guard("model/"):
        c = utils.unique_name.generate("fc")
        assert c.startswith("model/fc_")


def test_run_check_and_require_version():
    assert utils.run_check()
    assert utils.require_version("0.0.1")
    with pytest.raises(RuntimeError):
        utils.require_version("999.0.0")


def test_nan_inf_watcher():
    P.set_flags({"FLAGS_check_nan_inf": True})
    try:
        x = P.to_tensor(np.array([1.0, 0.0], np.float32))
        with pytest.raises(FloatingPointError) as e:
            P.divide(x, P.to_tensor(np.zeros(2, np.float32)))
        assert "Inf" in str(e.value)
        with pytest.raises(FloatingPointError):
            P.log(P.to_tensor(np.array([-1.0], np.float32)))
        # clean ops pass
        P.add(x, x)
    finally:
        P.set_flags({"FLAGS_check_nan_inf": False})


def test_nan_watcher_on_grad_path():
    P.set_flags({"FLAGS_check_nan_inf": True})
    try:
        x = P.to_tensor(np.array([0.0], np.float32), stop_gradient=False)
        with pytest.raises(FloatingPointError):
            P.rsqrt(x)  # 1/sqrt(0) = inf, on the autograd path
    finally:
        P.set_flags({"FLAGS_check_nan_inf": False})


def test_amp_operator_stats():
    with amp.debugging.collect_operator_stats():
        a = P.to_tensor(np.ones((2, 2), np.float32))
        P.matmul(a, a)
        P.add(a, a)
    stats = amp.debugging._stats
    assert any(k[0] == "matmul" for k in stats)


def test_onnx_export_stablehlo(tmp_path):
    import paddle_tpu.nn as nn
    from paddle_tpu import onnx, static

    m = nn.Linear(4, 2)
    p = onnx.export(m, str(tmp_path / "m"),
                    input_spec=[static.InputSpec([1, 4], "float32")])
    import os

    assert os.path.exists(p)
    # format="onnx" is now REAL emission (tests/test_onnx_export.py)
    p2 = onnx.export(m, str(tmp_path / "m2"), input_spec=[
        static.InputSpec([1, 4], "float32")], format="onnx")
    assert os.path.exists(p2) and p2.endswith(".onnx")


def test_hub_local(tmp_path):
    from paddle_tpu import hub

    (tmp_path / "hubconf.py").write_text(
        "def tiny_model(n=3):\n"
        "    'build a tiny model'\n"
        "    import paddle_tpu.nn as nn\n"
        "    return nn.Linear(n, n)\n")
    assert "tiny_model" in hub.list(str(tmp_path))
    assert "tiny" in hub.help(str(tmp_path), "tiny_model")
    m = hub.load(str(tmp_path), "tiny_model", n=5)
    assert m.weight.shape == [5, 5]
    with pytest.raises(RuntimeError):
        hub.load("user/repo", "x", source="github")


def test_sysconfig_paths():
    from paddle_tpu import sysconfig

    assert sysconfig.get_include().endswith("src")
