"""Breadth smoke sweep: executes every manifest op whose conformance kind
is "smoke" via its op_smoke_table.py entry (VERDICT r2 task 7 — the
manifest drives the parametrization, the table provides the executable
check, and tools/gen_op_manifest.py refuses to stamp a smoke entry for an
op the table doesn't cover)."""
import json
import os

import pytest

from op_smoke_table import SMOKE_OPS

with open(os.path.join(os.path.dirname(__file__), "..",
                       "OPS_MANIFEST.json")) as _f:
    _SMOKE_NAMES = sorted(
        e["name"] for e in json.load(_f)["ops"]
        if (e.get("conformance") or {}).get("kind") == "smoke")


def test_manifest_lists_smoke_ops():
    assert _SMOKE_NAMES, "manifest has no smoke conformance ops — regenerate"


@pytest.mark.parametrize("name", _SMOKE_NAMES)
def test_op_smoke(name):
    assert name in SMOKE_OPS, \
        f"manifest smoke entry for {name} has no op_smoke_table.py check"
    SMOKE_OPS[name]()
