"""SOT-role capture tier (jit/sot/): eager capture, graph breaks, guards.

Parity model: the reference's SOT tests (`test/sot/`) run real functions
through symbolic_translate and compare against plain eager, covering
control-flow specialization, guard-driven retrace, and fallback. Here the
capture mechanism differs (dispatch-gate recording, see package
docstring) but the observable contract is the same: identical results to
eager, per-branch compiled paths, source-less functions supported.
"""
import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu.jit.sot import SOTFunction, symbolic_translate


def _entry(fn):
    assert isinstance(fn, SOTFunction)
    assert len(fn._entries) >= 1
    return next(iter(fn._entries.values()))


def test_straight_line_capture_and_replay():
    calls = []

    def f(x, y):
        calls.append(1)
        return P.tanh(P.matmul(x, y)) + x.sum()

    sf = symbolic_translate(f)
    x = P.to_tensor(np.random.RandomState(0).rand(4, 4).astype(np.float32))
    y = P.to_tensor(np.random.RandomState(1).rand(4, 4).astype(np.float32))
    ref = f(x, y)
    n_eager = len(calls)
    out1 = sf(x, y)   # capture (runs the python body)
    out2 = sf(x, y)   # replay (must NOT run the python body)
    np.testing.assert_allclose(out1.numpy(), ref.numpy(), rtol=1e-6)
    np.testing.assert_allclose(out2.numpy(), ref.numpy(), rtol=1e-6)
    assert len(calls) == n_eager + 1  # only the capture ran the body


def test_graph_break_branches_both_paths():
    body_runs = []

    def f(x):
        h = x * 2.0
        if float(h.sum()) > 0.0:   # force -> graph break
            out = h + 1.0
        else:
            out = h - 1.0
        body_runs.append(1)
        return out

    sf = symbolic_translate(f)
    xp = P.to_tensor(np.ones((3,), np.float32))
    xn = P.to_tensor(-np.ones((3,), np.float32))
    np.testing.assert_allclose(sf(xp).numpy(), xp.numpy() * 2 + 1)
    np.testing.assert_allclose(sf(xn).numpy(), xn.numpy() * 2 - 1)  # recapture
    entry = _entry(sf)
    assert entry["paths"] == 2
    n = len(body_runs)
    # replays: neither branch re-runs python
    np.testing.assert_allclose(sf(xp).numpy(), xp.numpy() * 2 + 1)
    np.testing.assert_allclose(sf(xn).numpy(), xn.numpy() * 2 - 1)
    assert len(body_runs) == n


def test_sourceless_function_captures():
    # the AST dy2static tier must skip functions without retrievable
    # source; the SOT tier captures them (reference SOT capability)
    ns = {}
    exec("def g(x):\n    return x * 3.0 + 1.0", {"__builtins__": {}}, ns)
    sf = symbolic_translate(ns["g"])
    x = P.to_tensor(np.arange(4, dtype=np.float32))
    np.testing.assert_allclose(sf(x).numpy(), np.arange(4) * 3 + 1)
    np.testing.assert_allclose(sf(x).numpy(), np.arange(4) * 3 + 1)


def test_closure_and_dict_flow():
    scale = P.to_tensor(np.float32(2.5))

    def f(x):
        d = {"a": x * scale}          # dict flow + closure over a Tensor
        d["b"] = [v + 1.0 for v in [d["a"]]][0]   # comprehension
        return d["b"]

    sf = symbolic_translate(f)
    x = P.to_tensor(np.ones((2, 2), np.float32))
    np.testing.assert_allclose(sf(x).numpy(), np.full((2, 2), 3.5))
    np.testing.assert_allclose(sf(x).numpy(), np.full((2, 2), 3.5))


def test_grad_flows_through_replay():
    def f(x):
        return (P.tanh(x) * x).sum()

    sf = symbolic_translate(f)
    xv = np.random.RandomState(0).randn(5).astype(np.float32)

    x1 = P.to_tensor(xv, stop_gradient=False)
    loss1 = f(x1)
    loss1.backward()

    x2 = P.to_tensor(xv, stop_gradient=False)
    sf(x2)  # capture call
    x3 = P.to_tensor(xv, stop_gradient=False)
    loss3 = sf(x3)  # replay: one fused segment op
    loss3.backward()
    np.testing.assert_allclose(x3.grad.numpy(), x1.grad.numpy(), rtol=1e-5)


def test_int_force_used_as_python_value():
    def f(x, n):
        k = int(n.sum())          # force -> break; value baked per branch
        return x * float(k)

    sf = symbolic_translate(f)
    x = P.to_tensor(np.ones((2,), np.float32))
    np.testing.assert_allclose(
        sf(x, P.to_tensor(np.int32(3))).numpy(), [3, 3])
    np.testing.assert_allclose(
        sf(x, P.to_tensor(np.int32(5))).numpy(), [5, 5])
    assert _entry(sf)["paths"] == 2
    # replay of a seen value
    np.testing.assert_allclose(
        sf(x, P.to_tensor(np.int32(3))).numpy(), [3, 3])


def test_implicit_param_updates_visible():
    lin = P.nn.Linear(3, 2)

    def f(x):
        return lin(x)

    sf = symbolic_translate(f)
    x = P.to_tensor(np.ones((1, 3), np.float32))
    ref1 = lin(x).numpy()
    np.testing.assert_allclose(sf(x).numpy(), ref1, rtol=1e-6)
    # mutate the parameter in place (what an optimizer step does)
    lin.weight.set_value(lin.weight.numpy() * 2.0)
    ref2 = lin(x).numpy()
    out2 = sf(x)  # replay must read the CURRENT weight, not the baked one
    np.testing.assert_allclose(out2.numpy(), ref2, rtol=1e-6)
    assert not np.allclose(ref1, ref2)


def test_layer_via_to_static_backend_sot():
    net = P.nn.Sequential(P.nn.Linear(4, 8), P.nn.ReLU(), P.nn.Linear(8, 2))
    from paddle_tpu import jit

    sot_net = jit.to_static(net, backend="sot")
    x = P.to_tensor(np.random.RandomState(0).rand(2, 4).astype(np.float32))
    out1 = sot_net(x)
    out2 = sot_net(x)
    np.testing.assert_allclose(out1.numpy(), out2.numpy(), rtol=1e-6)
    assert isinstance(net.forward, SOTFunction)


def test_rng_resamples_across_replays():
    P.seed(1234)

    def f(x):
        return P.nn.functional.dropout(x, p=0.5, training=True)

    sf = symbolic_translate(f)
    x = P.to_tensor(np.ones((64,), np.float32))
    a = sf(x).numpy()   # capture
    b = sf(x).numpy()   # replay 1
    c = sf(x).numpy()   # replay 2
    # masks must differ across replays (key threaded per call, not baked)
    assert not np.array_equal(b, c) or not np.array_equal(a, b)


def test_paths_cap_falls_back_to_eager():
    from paddle_tpu.jit.sot import capture as cap

    def f(x, t):
        return x * float(int(t.sum()))

    sf = symbolic_translate(f)
    old = cap.MAX_PATHS_PER_SIG
    cap.MAX_PATHS_PER_SIG = 3
    try:
        for i in range(3):
            sf(P.to_tensor(np.ones(2, np.float32)), P.to_tensor(np.int32(i)))
        with pytest.warns(UserWarning, match="branch paths"):
            out = sf(P.to_tensor(np.ones(2, np.float32)),
                     P.to_tensor(np.int32(99)))
        np.testing.assert_allclose(out.numpy(), [99, 99])
    finally:
        cap.MAX_PATHS_PER_SIG = old


def test_nested_sot_inlines():
    inner = symbolic_translate(lambda x: x + 1.0)

    def f(x):
        return inner(x) * 2.0

    sf = symbolic_translate(f)
    x = P.to_tensor(np.zeros(3, np.float32))
    np.testing.assert_allclose(sf(x).numpy(), [2, 2, 2])
    np.testing.assert_allclose(sf(x).numpy(), [2, 2, 2])


def test_divergent_branches_bind_distinct_params():
    """Branch suffixes allocate overlapping SSA refs for different external
    layers; bindings are per-segment so paths must not clobber each other
    (r3 review finding)."""
    lin_pos = P.nn.Linear(3, 3)
    lin_neg = P.nn.Linear(3, 3)

    def f(x):
        if float(x.sum()) > 0:
            return lin_pos(x)
        return lin_neg(x)

    sf = symbolic_translate(f)
    xp = P.to_tensor(np.ones((1, 3), np.float32))
    xn = P.to_tensor(-np.ones((1, 3), np.float32))
    ref_p, ref_n = lin_pos(xp).numpy(), lin_neg(xn).numpy()
    np.testing.assert_allclose(sf(xp).numpy(), ref_p, rtol=1e-6)  # capture +
    np.testing.assert_allclose(sf(xn).numpy(), ref_n, rtol=1e-6)  # recapture
    # replays of BOTH paths must use their own layer's weights
    np.testing.assert_allclose(sf(xp).numpy(), ref_p, rtol=1e-6)
    np.testing.assert_allclose(sf(xn).numpy(), ref_n, rtol=1e-6)


def test_raw_jax_array_arg_not_baked():
    """A raw jnp array argument must flow as a dynamic input, not a baked
    literal (same-shape different-value call returned stale results)."""
    import jax.numpy as jnp

    def f(x, mask):
        return x * mask  # mask is a raw jax array

    sf = symbolic_translate(f)
    x = P.to_tensor(np.ones((4,), np.float32))
    m1 = jnp.asarray([1.0, 0.0, 1.0, 0.0])
    m2 = jnp.asarray([0.0, 1.0, 0.0, 1.0])
    np.testing.assert_allclose(sf(x, m1).numpy(), [1, 0, 1, 0])
    np.testing.assert_allclose(sf(x, m2).numpy(), [0, 1, 0, 1])  # replay


def test_np_asarray_force_breaks_graph():
    """np.asarray(tensor) escapes tensor-land -> must key a branch guard
    like item()/float() (r3 review finding: __array__ bypassed the hook)."""
    def f(x):
        s = float(np.asarray(x).mean())
        return x * s

    sf = symbolic_translate(f)
    x1 = P.to_tensor(np.full((2,), 2.0, np.float32))
    x2 = P.to_tensor(np.full((2,), 5.0, np.float32))
    np.testing.assert_allclose(sf(x1).numpy(), [4, 4])
    np.testing.assert_allclose(sf(x2).numpy(), [25, 25])
    np.testing.assert_allclose(sf(x1).numpy(), [4, 4])


def test_output_only_external_tensor_binds_on_replay():
    """An external tensor returned untouched (never an op input) must bind
    at replay (r3 review finding: unclaimed implicit ref -> KeyError)."""
    ext = P.to_tensor(np.full((2,), 7.0, np.float32))

    def f(x):
        return x * 2.0, ext

    sf = symbolic_translate(f)
    x = P.to_tensor(np.ones((2,), np.float32))
    a1, e1 = sf(x)
    a2, e2 = sf(x)  # replay
    np.testing.assert_allclose(a2.numpy(), [2, 2])
    np.testing.assert_allclose(e2.numpy(), [7, 7])


def test_while_loop_with_tensor_predicate_captures():
    """A data-dependent Python while loop: each iteration's bool force is a
    sequential graph break; repeated trip counts replay from the trie."""
    body_runs = []

    def f(x):
        while float(x.sum()) < 10.0:
            x = x * 2.0
        body_runs.append(1)
        return x

    sf = symbolic_translate(f)
    x1 = P.to_tensor(np.ones((2,), np.float32))  # 1+1=2 -> 4 -> 8 -> 16
    np.testing.assert_allclose(sf(x1).numpy(), [8, 8])
    n = len(body_runs)
    np.testing.assert_allclose(sf(x1).numpy(), [8, 8])  # replay
    assert len(body_runs) == n
    # different trip count (zero iterations): new path, still correct
    x2 = P.to_tensor(np.full((2,), 6.0, np.float32))  # sum 12 >= 10: no-op
    np.testing.assert_allclose(sf(x2).numpy(), [6, 6])
    x3 = P.to_tensor(np.full((2,), 3.0, np.float32))  # 6 -> 12: one iter
    np.testing.assert_allclose(sf(x3).numpy(), [6, 6])


# =================== adversarial section (VERDICT r3 Next #7) ===================


def test_container_mutation_between_ops():
    """Mutating Python containers between ops must not corrupt capture:
    the dataflow is SSA over tensors, list surgery is capture-time-only
    Python."""
    def f(x):
        acc = []
        for i in range(4):
            acc.append(x * float(i))
        acc.pop(1)             # mutate mid-build
        acc.insert(0, x + 10.0)
        acc[2] = acc[2] - acc[0]
        d = {"a": acc[0]}
        d["b"] = d.pop("a") * 2.0  # dict churn
        return sum(acc[1:], d["b"])

    sf = symbolic_translate(f)
    x = P.to_tensor(np.arange(3, dtype=np.float32))
    ref = f(x)
    np.testing.assert_allclose(sf(x).numpy(), ref.numpy(), rtol=1e-6)
    # replay (cached path), fresh value
    y = P.to_tensor(np.arange(3, dtype=np.float32) + 5)
    np.testing.assert_allclose(sf(y).numpy(), f(y).numpy(), rtol=1e-6)
    assert _entry(sf)["paths"] == 1  # no spurious branches


def test_input_list_mutation_is_capture_time_only():
    """In-place mutation of a PASSED container is a side effect: it runs
    at capture, not at replay (documented jit-like contract)."""
    def f(x, sink):
        y = x * 2.0
        sink.append("ran")
        return y

    sf = symbolic_translate(f)
    x = P.to_tensor(np.ones(2, np.float32))
    s1 = []
    sf(x, s1)
    assert s1 == ["ran"]  # capture executed the append
    s2 = []
    out = sf(P.to_tensor(np.ones(2, np.float32) * 3), s2)
    np.testing.assert_allclose(out.numpy(), [6, 6])
    assert s2 == []  # replay did NOT re-run the side effect


def test_non_tensor_side_effects_replay_skipped():
    """print/global counters run once (at capture) — same contract as
    jax.jit; results stay correct."""
    calls = {"n": 0}

    def f(x):
        calls["n"] += 1
        return x + 1.0

    sf = symbolic_translate(f)
    for i in range(5):
        out = sf(P.to_tensor(np.full(2, float(i), np.float32)))
        np.testing.assert_allclose(out.numpy(), [i + 1, i + 1])
    assert calls["n"] == 1  # captured once, replayed 4x


def test_python_scalar_closure_is_baked_per_signature():
    """A non-tensor closure value is a baked literal within a signature —
    the documented guard boundary (tensors guard by shape/dtype only)."""
    state = {"scale": 2.0}

    def f(x):
        return x * state["scale"]

    sf = symbolic_translate(f)
    x = P.to_tensor(np.ones(2, np.float32))
    np.testing.assert_allclose(sf(x).numpy(), [2, 2])
    state["scale"] = 5.0  # invisible to the cached path: baked at capture
    np.testing.assert_allclose(sf(x).numpy(), [2, 2])
    # a NEW signature recaptures and sees the current value
    x3 = P.to_tensor(np.ones(3, np.float32))
    np.testing.assert_allclose(sf(x3).numpy(), [5, 5, 5])


def test_trie_eviction_then_permanent_eager():
    """Overflow policy: trie evicted + recaptured MAX_TRIE_RESETS times,
    then permanently eager (ADVICE r3: no silent 64-path pin; loud final
    fallback with guidance)."""
    from paddle_tpu.jit.sot import capture as cap

    def f(x, t):
        return x * float(int(t.sum()))

    sf = symbolic_translate(f)
    old_paths, old_resets = cap.MAX_PATHS_PER_SIG, cap.MAX_TRIE_RESETS
    cap.MAX_PATHS_PER_SIG, cap.MAX_TRIE_RESETS = 2, 2
    try:
        x = P.to_tensor(np.ones(2, np.float32))
        n = 0
        evictions = 0
        with pytest.warns(UserWarning) as rec:
            for i in range(12):
                out = sf(x, P.to_tensor(np.int32(i)))
                np.testing.assert_allclose(out.numpy(), [i, i])
                n += 1
        msgs = [str(w.message) for w in rec]
        evictions = sum("evicting" in m for m in msgs)
        finals = sum("falling back to eager" in m for m in msgs)
        assert evictions == 2  # exactly MAX_TRIE_RESETS evictions
        assert finals >= 1     # then the permanent eager fallback
        # still correct after the fallback
        out = sf(x, P.to_tensor(np.int32(77)))
        np.testing.assert_allclose(out.numpy(), [77, 77])
    finally:
        cap.MAX_PATHS_PER_SIG, cap.MAX_TRIE_RESETS = old_paths, old_resets


def test_replay_container_tensor_inplace_vs_rebinding():
    """Replay-time container semantics (VERDICT r4 Next #8 torture): an
    implicit (closure-container) tensor binds by OBJECT IDENTITY and is
    re-read live at every replay — in-place value updates are visible
    (the optimizer-step contract), while REBINDING the container slot to
    a brand-new Tensor is invisible within a signature (identity guard,
    same observable contract as the reference's id()-based guards,
    `sot/opcode_translator/executor/guard.py`). docs/SOT.md §contract."""
    holder = [P.to_tensor(np.float32(2.0))]

    def f(x):
        return x * holder[0]

    sf = symbolic_translate(f)
    x = P.to_tensor(np.ones(2, np.float32))
    np.testing.assert_allclose(sf(x).numpy(), [2, 2])
    # in-place update of the SAME Tensor object: visible on replay
    holder[0].set_value(P.to_tensor(np.float32(7.0)))
    np.testing.assert_allclose(sf(x).numpy(), [7, 7])
    # rebinding the slot to a NEW Tensor: invisible within the signature
    holder[0] = P.to_tensor(np.float32(11.0))
    np.testing.assert_allclose(sf(x).numpy(), [7, 7])
    # a new signature recaptures and sees the rebound object
    x3 = P.to_tensor(np.ones(3, np.float32))
    np.testing.assert_allclose(sf(x3).numpy(), [11, 11, 11])


def test_returned_container_mutation_does_not_corrupt_cache():
    """Mutating the RETURNED container between calls must not corrupt the
    cached chain: outputs are rebuilt from the template per replay, never
    aliased to caller-visible structures."""
    def f(x):
        return {"a": x * 2.0, "b": [x + 1.0, x + 2.0]}

    sf = symbolic_translate(f)
    x = P.to_tensor(np.ones(2, np.float32))
    out1 = sf(x)
    out1["b"].pop()          # mutate returned structures
    out1["a"] = None
    out1["junk"] = object()
    y = P.to_tensor(np.full(2, 3.0, np.float32))
    out2 = sf(y)             # cached replay: fresh, correct structure
    np.testing.assert_allclose(out2["a"].numpy(), [6, 6])
    assert len(out2["b"]) == 2
    np.testing.assert_allclose(out2["b"][1].numpy(), [5, 5])
    assert _entry(sf)["paths"] == 1


def test_input_dict_structure_change_recaptures():
    """Container STRUCTURE is part of the entry signature: adding a key
    recaptures instead of replaying the stale path."""
    def f(d):
        out = d["a"] * 2.0
        if "b" in d:
            out = out + d["b"]
        return out

    sf = symbolic_translate(f)
    a = P.to_tensor(np.ones(2, np.float32))
    b = P.to_tensor(np.full(2, 10.0, np.float32))
    np.testing.assert_allclose(sf({"a": a}).numpy(), [2, 2])
    np.testing.assert_allclose(sf({"a": a, "b": b}).numpy(), [12, 12])
    # both signatures stay cached and correct
    np.testing.assert_allclose(sf({"a": a}).numpy(), [2, 2])
    assert len(sf._entries) == 2


def test_large_forced_array_key_is_bounded():
    """numpy()-forced arrays key branches by sha1 digest, not raw bytes —
    trie memory stays O(paths), not O(paths * array size) (ADVICE r3)."""
    import sys

    from paddle_tpu.jit.sot import capture as cap

    def f(x):
        m = (x > 0).numpy()  # force a big array (graph break)
        return x * 2.0 if m.all() else x * 3.0

    sf = symbolic_translate(f)
    big = P.to_tensor(np.ones(4096, np.float32))
    sf(big)
    node = _entry(sf)["head"]
    for outcome in node.branches:
        for part in outcome:
            if isinstance(part, bytes):
                assert len(part) <= 20, "branch key holds raw array bytes"
    # digest keys still separate branches correctly
    neg = P.to_tensor(-np.ones(4096, np.float32))
    np.testing.assert_allclose(sf(neg).numpy()[:2], [-3, -3])
    np.testing.assert_allclose(sf(big).numpy()[:2], [2, 2])
    assert _entry(sf)["paths"] == 2
