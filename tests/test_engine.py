"""Continuous-batching inference engine + paged KV cache (ISSUE 8).

Three layers of coverage, all CPU tier-1 unless marked:

  * unit: the page-pool allocator and the scheduler's admission/
    completion/eviction ordering under an injectable clock;
  * kernel: the ragged paged-attention Pallas kernel (interpret mode)
    against its jnp reference and the dense decode kernel;
  * engine: token-identical equivalence with sequential `generate()`
    greedy decoding under ragged batching, page-boundary crossings,
    chunked decode, slot reuse, eviction-with-recompute, eos, GQA
    (llama), and the serving `/generate` stream with the one-request-id
    retry discipline.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as P
from paddle_tpu.inference.engine import (
    EngineConfig, InferenceEngine, OutOfPages, PagePool, Scheduler,
    Sequence,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def assert_drained(eng):
    """A drained engine holds ONLY prefix-cache pages (each at exactly
    one reference — the cache's own); clearing the cache must return
    the pool to EMPTY.  This is the PR 8 zero-leak assertion, made
    aware of ISSUE 13's prefix cache deliberately retaining committed
    prompt pages across requests."""
    st = eng.pool.stats()
    assert st["logical_pages"] == st["used"], st   # no live-seq refs
    eng.clear_prefix_cache()
    assert eng.pool.used_pages == 0, eng.pool.stats()


def _gpt(max_len=64, seed=0):
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(seed)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=max_len)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model


@pytest.fixture(scope="module")
def gpt_model():
    return _gpt()


_PROMPT_LENS = (3, 9, 17, 5, 12)


@pytest.fixture(scope="module")
def prompts():
    rs = np.random.RandomState(0)
    return [rs.randint(0, 128, (n,)).astype(np.int32)
            for n in _PROMPT_LENS]


@pytest.fixture(scope="module")
def refs(gpt_model, prompts):
    """Sequential solo generate() per prompt — the ground truth every
    engine configuration must reproduce token-for-token."""
    return [np.asarray(gpt_model.generate(
        P.to_tensor(p[None, :], "int32"), max_new_tokens=10)._value)[0]
        for p in prompts]


# ------------------------------ page pool ------------------------------

def test_page_pool_alloc_free_oom():
    pool = PagePool(num_pages=6, page_size=8)
    assert pool.capacity == 5          # page 0 reserved
    a = pool.alloc(3)
    assert len(set(a)) == 3 and 0 not in a
    assert pool.used_pages == 3 and pool.free_pages == 2
    with pytest.raises(OutOfPages):
        pool.alloc(3)
    assert pool.used_pages == 3        # failed alloc grants nothing
    pool.free(a)
    assert pool.used_pages == 0
    assert pool.utilization() == 0.0
    b = pool.alloc(5)
    assert pool.stats()["peak_used"] == 5
    pool.free(b)


def test_page_pool_double_free_and_scratch_guard():
    pool = PagePool(num_pages=4, page_size=8)
    a = pool.alloc(2)
    pool.free(a)
    with pytest.raises(ValueError):
        pool.free([a[0]])              # double free
    with pytest.raises(ValueError):
        pool.free([0])                 # scratch page


def test_page_pool_defrag_compacts():
    pool = PagePool(num_pages=10, page_size=8)
    a = pool.alloc(3)
    b = pool.alloc(3)
    pool.free(a)                       # holes at the bottom
    moves = pool.defrag()
    # b's three pages must now occupy {1, 2, 3}; every move src > dst
    assert set(moves.values()) <= {1, 2, 3}
    assert all(src > dst for src, dst in moves.items())
    assert pool.used_pages == 3
    c = pool.alloc(6)                  # full capacity usable again
    assert len(c) == 6
    assert pool.defrag() == {}         # already compact


# ------------------------------ scheduler ------------------------------

def _seq(n, max_new=4, rid=None):
    return Sequence(np.arange(1, n + 1, dtype=np.int32), max_new,
                    request_id=rid)


def test_scheduler_fifo_admission_and_slot_fill():
    clock = [0.0]
    pool = PagePool(num_pages=64, page_size=4)
    sch = Scheduler(2, pool, max_pages_per_seq=8,
                    clock=lambda: clock[0])
    a, b, c = _seq(4, rid="a"), _seq(4, rid="b"), _seq(4, rid="c")
    for s in (a, b, c):
        sch.submit(s)
        clock[0] += 1.0
    out = sch.schedule()
    # FIFO: a and b admitted (2 slots), c waits
    assert [s.request_id for s in out.prefills] == ["a", "b"]
    assert {s.slot for s in out.prefills} == {0, 1}
    assert sch.waiting_sequences == 1
    assert all(s.pages for s in out.prefills)


def test_scheduler_completion_frees_slot_for_next_waiting():
    pool = PagePool(num_pages=64, page_size=4)
    sch = Scheduler(1, pool, max_pages_per_seq=8)
    a, b = _seq(4, rid="a"), _seq(4, rid="b")
    sch.submit(a)
    sch.submit(b)
    out = sch.schedule()
    assert [s.request_id for s in out.prefills] == ["a"]
    sch.finish(a, "length")
    out = sch.schedule()
    # the SAME schedule() that releases a admits b into its slot
    assert [s.request_id for s in out.prefills] == ["b"]
    assert b.slot == 0
    assert a.pages == [] and pool.used_pages == len(b.pages)


def test_scheduler_eviction_youngest_on_page_pressure():
    # pool sized so two sequences fit only while short
    pool = PagePool(num_pages=5, page_size=4)   # 4 allocatable pages
    sch = Scheduler(2, pool, max_pages_per_seq=4)
    a, b = _seq(6, max_new=8, rid="old"), _seq(6, max_new=8, rid="young")
    sch.submit(a)
    sch.submit(b)
    out = sch.schedule()
    assert len(out.prefills) == 2
    a.length, b.length = 6, 6
    # both need a 3rd page for the next 4 tokens: only 0 free ->
    # the YOUNGEST is evicted back to the waiting queue's front
    out = sch.schedule(chunk=4)
    assert [s.request_id for s in out.evicted] == ["young"]
    assert b.state == "waiting" and b.pages == [] and b.length == 0
    assert b.evictions == 1
    assert a.slot is not None            # the older request kept going
    assert sch.waiting_sequences == 1


def test_scheduler_growth_clamped_to_sequence_total():
    """Page demand near a sequence's finish line is clamped to what it
    can EVER use (prompt+max_new): a decode_chunk reaching past the end
    must not demand pages for scratch-bound tokens — that once evicted
    a fitting sequence into a permanent re-admission stall."""
    pool = PagePool(num_pages=3, page_size=8)     # capacity: 2 pages
    sch = Scheduler(1, pool, max_pages_per_seq=8)
    seq = Sequence(np.arange(1, 9, dtype=np.int32), 8)  # 16 = 2 pages
    sch.submit(seq)
    out = sch.schedule(chunk=5)
    assert out.prefills == [seq]
    seq.length = 13                                # 6 tokens generated
    out = sch.schedule(chunk=5)                    # 13+5 > 16: clamped
    assert out.evicted == [] and seq.slot is not None
    assert len(seq.pages) == 2                     # never needs a 3rd


def test_scheduler_youngest_self_preempts():
    """When the sequence that needs pages IS the youngest, it preempts
    itself rather than throwing away an older request's longer KV."""
    pool = PagePool(num_pages=5, page_size=4)      # 4 allocatable
    sch = Scheduler(2, pool, max_pages_per_seq=8)
    old = Sequence(np.arange(1, 5, dtype=np.int32), 12, request_id="old")
    young = Sequence(np.arange(1, 5, dtype=np.int32), 12,
                     request_id="young")
    sch.submit(old)
    sch.submit(young)
    sch.schedule(chunk=1)                          # both admitted, 2+2
    old.length, young.length = 4, 7                # only young grows
    out = sch.schedule(chunk=4)
    assert [s.request_id for s in out.evicted] == ["young"]
    assert old.slot is not None and old.pages     # the elder undisturbed


def test_scheduler_cancel_waiting_and_running():
    pool = PagePool(num_pages=64, page_size=4)
    sch = Scheduler(1, pool, max_pages_per_seq=8)
    a, b = _seq(4, rid="a"), _seq(4, rid="b")
    sch.submit(a)
    sch.submit(b)
    sch.schedule()
    assert sch.cancel("a") and sch.cancel("b")
    assert not sch.cancel("a")           # already done
    assert not sch.cancel("nope")
    out = sch.schedule()
    assert {s.request_id for s in out.finished} == {"a", "b"}
    assert pool.used_pages == 0 and sch.active_sequences == 0


def test_scheduler_rejects_oversized_and_duplicate():
    pool = PagePool(num_pages=64, page_size=4)
    sch = Scheduler(1, pool, max_pages_per_seq=2)   # 8-token cap
    with pytest.raises(ValueError):
        sch.submit(_seq(6, max_new=4))   # 10 > 8
    a = _seq(2, rid="dup")
    sch.submit(a)
    with pytest.raises(ValueError):
        sch.submit(_seq(2, rid="dup"))


def test_scheduler_sheds_expired_deadline_at_admission(monkeypatch):
    """Deadline shedding (ISSUE 20 satellite): a waiting sequence whose
    deadline passed while queued is shed AT ADMISSION with the honest
    `deadline_exceeded` finish reason — it never takes a slot or burns
    a prefill the nobody-is-waiting-for answer would waste — while
    sequences with live (or no) deadlines admit normally."""
    from paddle_tpu import observability as obs
    from paddle_tpu.observability import metrics

    metrics.reset()
    obs.attach(crash_hook=False)
    try:
        clock = [0.0]
        pool = PagePool(num_pages=64, page_size=4)
        sch = Scheduler(2, pool, max_pages_per_seq=8,
                        clock=lambda: clock[0])
        late = Sequence(np.arange(1, 5, dtype=np.int32), 4,
                        request_id="late", deadline=2.0)
        live = Sequence(np.arange(1, 5, dtype=np.int32), 4,
                        request_id="live", deadline=50.0)
        plain = Sequence(np.arange(1, 5, dtype=np.int32), 4,
                         request_id="plain")
        for s in (late, live, plain):
            sch.submit(s)
        clock[0] = 5.0              # the queue outlived late's deadline
        out = sch.schedule()
        assert [s.request_id for s in out.prefills] == \
            ["live", "plain"]
        (shed,) = out.finished
        assert shed.request_id == "late"
        assert shed.finish_reason == "deadline_exceeded"
        assert sch.waiting_sequences == 0
        snap = metrics.snapshot()["counters"]
        assert snap[
            "resilience.shed_requests{reason=deadline_exceeded}"] == 1
    finally:
        obs.detach()
        metrics.reset()


def test_engine_deadline_shed_closes_handle(gpt_model):
    """End to end through the engine: an expired-deadline submission
    comes back as a finished handle with `deadline_exceeded` — a clean
    final record for the serving layer, not a hang or a decode."""
    import time as _time

    eng = InferenceEngine(gpt_model, EngineConfig(
        page_size=8, max_slots=2, max_seq_len=64))
    h = eng.submit(np.arange(1, 5, dtype=np.int32), max_new_tokens=4,
                   request_id="expired",
                   deadline=_time.monotonic() - 1.0)
    for _ in range(50):
        eng.step()
        if h.done.is_set():
            break
    assert h.done.is_set()
    assert h.finish_reason == "deadline_exceeded"
    assert h.tokens == []           # no token was ever decoded
    assert_drained(eng)


def test_ledger_conservation_across_resume(gpt_model):
    """Exactly-once billing across a mid-stream resume (ISSUE 20): the
    dying replica's book keeps the tokens it delivered, the resume
    replica bills only NEW tokens (its re-derived verify token rides in
    prebilled — billed nowhere), and the fleet merge conserves decode
    tokens and KV page-seconds — while the resumed output stays
    bit-exact with the uninterrupted reference (greedy determinism)."""
    from paddle_tpu import observability as obs
    from paddle_tpu.observability import metrics
    from paddle_tpu.observability import tenant_ledger as tl

    metrics.reset()
    obs.attach(crash_hook=False)
    try:
        total = 8
        prompt = np.arange(1, 9, dtype=np.int32)
        ref = np.asarray(gpt_model.generate(
            P.to_tensor(prompt[None, :], "int32"),
            max_new_tokens=total)._value)[0]

        # leg 1: "replica A" delivers a few tokens, then dies (cancel
        # stands in for the kill — billing-wise identical)
        eng_a = InferenceEngine(gpt_model, EngineConfig(
            page_size=8, max_slots=2, max_seq_len=64))
        assert eng_a.tenant_ledger is not None
        h1 = eng_a.submit(prompt, max_new_tokens=total,
                          tenant_id="t0", request_id="r1")
        while len(h1.tokens) < 3 and not h1.done.is_set():
            eng_a.step()
        delivered = list(h1.tokens)
        assert 3 <= len(delivered) < total
        eng_a.cancel("r1")
        eng_a.step()               # slot/pages release, books close

        # leg 2: "replica B" tail-prefills prompt+delivered[:-1] and
        # re-derives delivered[-1] as its first (prebilled) token
        eng_b = InferenceEngine(gpt_model, EngineConfig(
            page_size=8, max_slots=2, max_seq_len=64))
        ids = np.concatenate(
            [prompt, np.asarray(delivered[:-1], np.int32)])
        h2 = eng_b.submit(ids,
                          max_new_tokens=total - len(delivered) + 1,
                          tenant_id="t0", request_id="r1",
                          prebilled_tokens=1)
        for _ in range(500):
            eng_b.step()
            if h2.done.is_set():
                break
        assert h2.done.is_set()
        assert h2.tokens[0] == delivered[-1]    # the verify token
        assert np.array_equal(h2.result(), ref)  # bit-exact splice

        sa = eng_a.tenant_ledger.snapshot()
        sb = eng_b.tenant_ledger.snapshot()
        # each book billed its own leg; the verify token nowhere
        assert sa["totals"]["decode_tokens"] == len(delivered)
        assert sb["totals"]["decode_tokens"] == total - len(delivered)
        fleet = tl.merge_snapshots([sa, sb])
        assert fleet["totals"]["decode_tokens"] == total
        assert fleet["tenants"]["t0"]["decode_tokens"] == total
        assert tl.conservation_delta(fleet) == {}
        # KV page-seconds accrued on BOTH legs; the merge is the sum
        assert sa["totals"]["kv_page_seconds"] > 0
        assert sb["totals"]["kv_page_seconds"] > 0
        assert fleet["totals"]["kv_page_seconds"] == pytest.approx(
            sa["totals"]["kv_page_seconds"]
            + sb["totals"]["kv_page_seconds"])
        # engine.tokens (both books share the process counter) agrees
        assert metrics.snapshot()["counters"].get(
            "engine.tokens", 0) == total
    finally:
        obs.detach()
        metrics.reset()


# ------------------------------ kernel ------------------------------

def test_paged_attention_kernel_matches_reference():
    from paddle_tpu.ops.pallas.paged_attention import (
        paged_attention, paged_attention_reference,
    )

    rs = np.random.RandomState(1)
    b, hq, hkv, d, ps, npool, p = 4, 8, 2, 16, 8, 12, 4
    q = jnp.asarray(rs.randn(b, hq, d), jnp.float32)
    kp = jnp.asarray(rs.randn(npool, hkv, ps, d), jnp.float32)
    vp = jnp.asarray(rs.randn(npool, hkv, ps, d), jnp.float32)
    pt = jnp.asarray([[1, 2, 3, 4], [5, 6, 0, 0], [7, 0, 0, 0],
                      [8, 9, 10, 11]], jnp.int32)
    # ragged: page-boundary crossing (25), exact boundary (15), single
    # token (0), full table (31)
    pos = jnp.asarray([25, 15, 0, 31], jnp.int32)
    ref = paged_attention_reference(q, kp, vp, pt, pos)
    for block_k in (ps, 8):
        out = paged_attention(q, kp, vp, pt, pos, block_k=block_k,
                              interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_paged_attention_matches_dense_decode_kernel():
    """Gathering each sequence's pages into a dense cache and running
    the existing decode kernel must agree — the paged kernel is the
    same attention, addressed through a page table."""
    from paddle_tpu.ops.pallas.decode_attention import decode_attention
    from paddle_tpu.ops.pallas.paged_attention import paged_attention

    rs = np.random.RandomState(2)
    b, hq, hkv, d, ps, npool, p = 2, 4, 4, 8, 8, 8, 2
    q = jnp.asarray(rs.randn(b, hq, d), jnp.float32)
    kp = jnp.asarray(rs.randn(npool, hkv, ps, d), jnp.float32)
    vp = jnp.asarray(rs.randn(npool, hkv, ps, d), jnp.float32)
    pt = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    pos = jnp.asarray([11, 6], jnp.int32)
    k = jnp.moveaxis(kp[pt], 2, 1).reshape(b, hkv, p * ps, d)
    v = jnp.moveaxis(vp[pt], 2, 1).reshape(b, hkv, p * ps, d)
    dense = decode_attention(q, k, v, pos, interpret=True)
    paged = paged_attention(q, kp, vp, pt, pos, interpret=True)
    np.testing.assert_allclose(np.asarray(paged), np.asarray(dense),
                               rtol=2e-5, atol=2e-5)


def test_paged_attention_available_gating():
    from paddle_tpu.core import flags
    from paddle_tpu.ops.pallas.paged_attention import (
        paged_attention_available,
    )

    # CPU (interpret) never claims the compiled kernel
    assert not paged_attention_available((8, 2, 8, 16))
    old = flags.get_flags("FLAGS_disable_pallas_paged")
    flags.set_flags({"FLAGS_disable_pallas_paged": 1})
    try:
        assert not paged_attention_available((8, 2, 8, 16))
    finally:
        flags.set_flags(old)


# ------------------------------ engine equivalence ------------------------------

@pytest.mark.parametrize("page_size,slots,chunk", [
    (4, 2, 1),     # tiny pages: every sequence crosses many boundaries
    (8, 3, 1),     # mid batch
    (8, 3, 4),     # chunked scanned decode
    (16, 5, 8),    # whole batch resident, big chunks
])
def test_engine_matches_sequential_generate(gpt_model, prompts, refs,
                                            page_size, slots, chunk):
    eng = InferenceEngine(gpt_model, EngineConfig(
        page_size=page_size, max_slots=slots, decode_chunk=chunk,
        max_seq_len=64))
    outs = eng.generate(prompts, max_new_tokens=10)
    for r, o in zip(refs, outs):
        assert np.array_equal(r, o), (r.tolist(), o.tolist())
    assert_drained(eng)               # drained engine leaks nothing


def test_engine_page_boundary_exact_crossings(gpt_model):
    """Prompt+generation lengths landing exactly ON page boundaries
    (the off-by-one habitat: len % ps == 0 means the next token opens
    a fresh page)."""
    ps = 4
    prompts = [np.arange(1, n + 1, dtype=np.int32) % 127 + 1
               for n in (4, 8, 3, 5)]       # 4 and 8 are exact pages
    refs = [np.asarray(gpt_model.generate(
        P.to_tensor(p[None, :], "int32"), max_new_tokens=9)._value)[0]
        for p in prompts]
    eng = InferenceEngine(gpt_model, EngineConfig(
        page_size=ps, max_slots=4, max_seq_len=64))
    outs = eng.generate(prompts, max_new_tokens=9)
    for r, o in zip(refs, outs):
        assert np.array_equal(r, o)


def test_engine_slot_reuse_after_completion(gpt_model, prompts, refs):
    """More requests than slots: completed sequences' slots (and
    pages) are reused by later admissions, and every stream still
    matches its solo reference."""
    eng = InferenceEngine(gpt_model, EngineConfig(
        page_size=8, max_slots=2, max_seq_len=64))
    outs = eng.generate(prompts, max_new_tokens=10)
    for r, o in zip(refs, outs):
        assert np.array_equal(r, o)
    assert_drained(eng)
    # 5 sequences through 2 slots: slots were genuinely reused
    assert eng.scheduler.stats()["running"] == 0


def test_engine_eviction_recompute_identical(gpt_model, prompts, refs):
    """A pool too small for the batch forces mid-flight eviction; the
    preempted sequence re-prefills from prompt+generated and must
    continue the greedy stream token-identically."""
    eng = InferenceEngine(gpt_model, EngineConfig(
        page_size=4, max_slots=2, num_pages=10, max_seq_len=64))
    outs = eng.generate(prompts, max_new_tokens=10)
    for r, o in zip(refs, outs):
        assert np.array_equal(r, o)
    assert_drained(eng)


def test_engine_eos_matches_generate(gpt_model, prompts):
    eos = 7
    refs = [np.asarray(gpt_model.generate(
        P.to_tensor(p[None, :], "int32"), max_new_tokens=10,
        eos_token_id=eos)._value)[0] for p in prompts]
    eng = InferenceEngine(gpt_model, EngineConfig(
        page_size=8, max_slots=3, decode_chunk=4, max_seq_len=64))
    outs = eng.generate(prompts, max_new_tokens=10, eos_token_id=eos)
    for r, o in zip(refs, outs):
        assert np.array_equal(r, o)


def test_engine_continuous_admission_mid_flight(gpt_model, prompts,
                                                refs):
    """Sequences submitted WHILE others are decoding enter freed/idle
    slots on the next step — continuous batching, not batch-boundary
    batching — and the late arrivals' outputs are unaffected by who
    they shared the batch with."""
    eng = InferenceEngine(gpt_model, EngineConfig(
        page_size=8, max_slots=2, max_seq_len=64))
    first = [eng.submit(p, max_new_tokens=10) for p in prompts[:2]]
    for _ in range(3):
        eng.step()                      # mid-decode
    late = [eng.submit(p, max_new_tokens=10) for p in prompts[2:]]
    idle = 0
    handles = first + late
    while any(not h.done.is_set() for h in handles):
        idle = 0 if eng.step() else idle + 1
        assert idle < 1000, "engine stalled"
    for h, r in zip(handles, refs):
        assert np.array_equal(h.result(timeout=1.0), r)
    assert_drained(eng)


def test_engine_cancel_mid_decode_survivors_identical(gpt_model,
                                                      prompts, refs):
    eng = InferenceEngine(gpt_model, EngineConfig(
        page_size=8, max_slots=3, max_seq_len=64))
    handles = [eng.submit(p, max_new_tokens=10) for p in prompts]
    for _ in range(2):
        eng.step()
    assert eng.cancel(handles[1].request_id)
    idle = 0
    while any(not h.done.is_set() for h in handles):
        idle = 0 if eng.step() else idle + 1
        assert idle < 1000, "engine stalled"
    assert handles[1].cancelled
    for i, h in enumerate(handles):
        if i != 1:
            assert np.array_equal(h.result(timeout=1.0), refs[i])
    assert_drained(eng)


def test_engine_defrag_mid_flight_preserves_streams(gpt_model, prompts,
                                                    refs):
    """Compacting the page pool between steps (device copies + table
    rewrite) must be invisible to the token streams."""
    eng = InferenceEngine(gpt_model, EngineConfig(
        page_size=4, max_slots=3, max_seq_len=64))
    handles = [eng.submit(p, max_new_tokens=10) for p in prompts[:3]]
    for _ in range(2):
        eng.step()
    # finish one so its freed pages leave holes, then compact
    eng.cancel(handles[0].request_id)
    eng.step()
    moved = eng.defrag()
    assert moved >= 0                   # compaction ran
    idle = 0
    while any(not h.done.is_set() for h in handles[1:]):
        idle = 0 if eng.step() else idle + 1
        assert idle < 1000, "engine stalled"
    for i in (1, 2):
        assert np.array_equal(handles[i].result(timeout=1.0), refs[i])
    eng.clear_prefix_cache()
    assert eng.defrag() == 0 or eng.pool.used_pages == 0


def test_engine_tight_pool_near_finish_line_completes(gpt_model):
    """End-to-end regression for the growth-clamp stall: a pool holding
    exactly one sequence's lifetime pages, with a decode chunk that
    overshoots the finish line, must run to completion (and still match
    sequential generate())."""
    p = np.arange(1, 9, dtype=np.int32)            # 8 + 8 = 2x8 pages
    ref = np.asarray(gpt_model.generate(
        P.to_tensor(p[None, :], "int32"), max_new_tokens=8)._value)[0]
    eng = InferenceEngine(gpt_model, EngineConfig(
        page_size=8, num_pages=3, max_slots=1, decode_chunk=5,
        max_seq_len=64))
    out = eng.generate([p], max_new_tokens=8)[0]
    assert np.array_equal(out, ref)
    assert_drained(eng)


def test_engine_cancel_drops_handle_and_config_not_mutated(gpt_model,
                                                           prompts):
    """Cancelled requests must not leak their handles (one per client
    disconnect on a long-running server), and a config object reused
    across engines must not carry the first engine's resolution."""
    cfg = EngineConfig(page_size=8, max_slots=2)
    eng = InferenceEngine(gpt_model, cfg)
    assert cfg.max_seq_len == 0 and cfg.num_pages == 0  # caller's copy
    assert eng.config.max_seq_len == 64                 # engine's own
    handles = [eng.submit(p, max_new_tokens=8) for p in prompts[:3]]
    eng.step()
    for h in handles:
        eng.cancel(h.request_id)
    eng.step()
    assert eng._handles == {}
    assert_drained(eng)
    # completed (non-cancelled) requests are dropped too
    out = eng.generate([prompts[0]], max_new_tokens=4)
    assert eng._handles == {} and len(out) == 1


def test_engine_llama_gqa_matches_generate():
    """GQA coverage: llama with num_kv_heads < num_heads runs the
    grouped paged kernel path (and rope over per-row vector
    positions)."""
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    P.seed(3)
    cfg = LlamaConfig(vocab_size=128, hidden_size=32, num_layers=2,
                      num_heads=4, num_kv_heads=2, max_seq_len=64,
                      ffn_hidden=64)
    model = LlamaForCausalLM(cfg)
    model.eval()
    rs = np.random.RandomState(3)
    prompts = [rs.randint(0, 128, (n,)).astype(np.int32)
               for n in (4, 11, 7)]
    refs = [np.asarray(model.generate(
        P.to_tensor(p[None, :], "int32"), max_new_tokens=8)._value)[0]
        for p in prompts]
    eng = InferenceEngine(model, EngineConfig(
        page_size=8, max_slots=2, max_seq_len=64))
    outs = eng.generate(prompts, max_new_tokens=8)
    for r, o in zip(refs, outs):
        assert np.array_equal(r, o)


def test_engine_gauges_spans_and_counters(gpt_model, prompts):
    from paddle_tpu import observability as obs
    from paddle_tpu.observability import metrics, trace

    obs.attach(crash_hook=False)
    metrics.reset()
    obs.attach(crash_hook=False)        # re-declare schema after reset
    try:
        eng = InferenceEngine(gpt_model, EngineConfig(
            page_size=8, max_slots=2, max_seq_len=64))
        eng.generate(prompts[:3], max_new_tokens=4)
        snap = metrics.snapshot()
        c = snap["counters"]
        assert c.get("engine.sequences{event=submitted}") == 3
        assert c.get("engine.sequences{event=admitted}") == 3
        assert c.get("engine.sequences{event=completed}") == 3
        assert c.get("engine.tokens") == 12
        g = snap["gauges"]
        assert g.get("engine.active_sequences") == 0
        # the prefix cache deliberately retains committed prompt pages
        # across requests (ISSUE 13): the published utilization matches
        # the pool's cache-held view, and clearing the cache empties it
        assert g.get("engine.page_utilization") == eng.pool.utilization()
        assert_drained(eng)
        assert eng.pool.utilization() == 0
        names = {e.get("name") for e in trace.events()}
        for phase in ("engine.schedule", "engine.prefill",
                      "engine.decode", "engine.detokenize"):
            assert phase in names, names
    finally:
        obs.detach()


# ------------------------------ serving ------------------------------

@pytest.fixture()
def gen_server(gpt_model):
    from paddle_tpu.inference.serving import InferenceServer

    eng = InferenceEngine(gpt_model, EngineConfig(
        page_size=8, max_slots=2, max_seq_len=64))
    srv = InferenceServer(engine=eng, request_timeout=60.0,
                          queue_depth=0).start()
    yield srv
    srv.shutdown()


def test_generate_endpoint_streams_and_matches(gen_server, prompts,
                                               refs):
    from paddle_tpu.inference.serving import InferenceClient

    cli = InferenceClient(gen_server.address, timeout=60.0)
    streamed = []
    r = cli.generate(prompts[0], max_new_tokens=10,
                     on_token=streamed.append)
    assert np.array_equal(r["output_ids"], refs[0])
    assert streamed == r["tokens"] and len(streamed) == 10
    assert r["finish_reason"] == "length"
    # concurrent clients, mixed lengths, same answers
    outs = [None] * 3

    def one(i):
        c = InferenceClient(gen_server.address, timeout=60.0)
        outs[i] = c.generate(prompts[i], max_new_tokens=10)

    ts = [threading.Thread(target=one, args=(i,)) for i in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for i in range(3):
        assert np.array_equal(outs[i]["output_ids"], refs[i])
    assert_drained(gen_server.engine)


def test_generate_endpoint_eos_and_bad_body(gen_server, prompts):
    import urllib.error
    import urllib.request

    from paddle_tpu.inference.serving import InferenceClient

    cli = InferenceClient(gen_server.address, timeout=60.0)
    r = cli.generate(prompts[0], max_new_tokens=10, eos_token_id=7)
    if 7 in r["tokens"]:
        assert r["finish_reason"] == "eos"
        assert r["tokens"][-1] == 7
    # undecodable body -> 400 with the request id echoed
    req = urllib.request.Request(
        gen_server.address + "/generate", data=b"not json",
        headers={"Content-Type": "application/json",
                 "X-Request-Id": "bad-body-req"})
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 400
    assert ei.value.headers.get("X-Request-Id") == "bad-body-req"


def test_generate_shed_retries_same_request_id(gpt_model, prompts):
    """Saturate the engine's admission (slots busy, queue 0), then a
    retrying client must shed with 429+Retry-After and succeed on a
    later attempt under the SAME X-Request-Id (the PR 7 discipline)."""
    from paddle_tpu.inference.serving import (
        InferenceClient, InferenceServer,
    )

    eng = InferenceEngine(gpt_model, EngineConfig(
        page_size=8, max_slots=1, max_seq_len=64))
    # warm the compiled programs: the blocker must hold the slot for
    # its DECODE time, not for a first-call XLA compile, or the shed
    # client exhausts its retry budget against the compiler
    eng.generate([prompts[2]], max_new_tokens=2)
    srv = InferenceServer(engine=eng, request_timeout=60.0,
                          queue_depth=0).start()
    try:
        seen_ids = []
        orig_submit = eng.submit

        def spy(ids, **kw):
            seen_ids.append(kw.get("request_id"))
            return orig_submit(ids, **kw)

        eng.submit = spy
        blocker = InferenceClient(srv.address, timeout=60.0)
        done = threading.Event()

        def long_one():
            blocker.generate(prompts[1], max_new_tokens=16)
            done.set()

        t = threading.Thread(target=long_one)
        t.start()
        # wait until the blocker owns the only admission slot
        for _ in range(200):
            if srv.gen_admission.stats()["inflight"] >= 1:
                break
            import time as _t
            _t.sleep(0.005)
        # the shed Retry-After is ~0 until the first completion seeds
        # the latency EWMA, so each retry waits the client-side 50 ms
        # floor — budget enough of them to outlast the blocker's decode
        cli = InferenceClient(srv.address, timeout=60.0, retries=60,
                              max_retry_wait=0.5)
        r = cli.generate(prompts[0], max_new_tokens=4)
        t.join(timeout=60)
        assert done.is_set()
        assert len(r["tokens"]) == 4
        # the successful attempt reused the id of the shed attempts:
        # exactly one engine submission, and the client counted sheds
        assert r["request_id"] in seen_ids
        from paddle_tpu.observability import metrics
        # the shed is visible in the SLO ledger under its reason
        slo = srv.slo.report(publish_gauges=False)
        gen_ep = slo.get("endpoints", {}).get("generate", {})
        sheds = {k: v for k, v in
                 gen_ep.get("errors_by_reason", {}).items()
                 if k.startswith("shed:")}
        assert sum(sheds.values()) >= 1, slo
    finally:
        srv.shutdown()


# ------------------------------ satellites ------------------------------

def test_perf_smoke_paged_decode_within_budget():
    """Tier-1 perf-audit gate for the NEW hot program: the paged decode
    step audits cleanly (no PT400 blindness) and every metric holds the
    committed tools/perf_budget.json ceiling — a layout/transpose
    regression in the paged path fails here before any hardware run."""
    from paddle_tpu import analysis as A
    from paddle_tpu.analysis import perf_audit

    violations, metrics = perf_audit.audit_perf(
        programs=("paged_decode_step",), repo_root=REPO)
    assert not [v for v in violations if v.rule == "PT400"], \
        A.render_report(violations)
    m = metrics["gpt_paged_decode_step"]
    assert m["pt405_loop_host_syncs"] == 0   # the scan stays on device
    budget = A.load_budget(
        os.path.join(REPO, "tools", "perf_budget.json"))
    reg, _imp, _ = A.diff_against_budget(metrics, budget)
    assert reg == [], A.render_budget_diff(reg, [])


def test_bench_serving_decode_emits_and_beats_sequential():
    """The multi-client continuous-batching bench line: emitted with
    the degraded mark on the CPU proxy, and the engine beats
    single-stream sequential decode on the same proxy by batching
    alone (the ISSUE 8 acceptance comparison, measured in-process)."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    r = bench._bench_serving_decode(True)
    assert r["metric"] == "serving_decode_tokens_per_sec"
    assert r["value"] > 0 and r["degraded"]
    assert r["sequential_tokens_per_sec"] > 0
    assert r["batching_speedup"] > 1.0, r


def test_perf_gate_serving_metric_round_trip(tmp_path):
    """serving_decode_tokens_per_sec is gateable: --update registers a
    non-degraded row in the baseline, an equal rerun passes, a drop
    beyond tolerance exits 2."""
    gate = os.path.join(REPO, "tools", "perf_gate.py")
    base = tmp_path / "baseline.jsonl"
    res = tmp_path / "results.json"
    row = {"metric": "serving_decode_tokens_per_sec", "value": 1000.0,
           "unit": "tokens/s", "sequential_tokens_per_sec": 300.0,
           "batching_speedup": 3.3}
    base.write_text(json.dumps(row) + "\n")

    def run(value):
        res.write_text(json.dumps(dict(row, value=value)) + "\n")
        return subprocess.run(
            [sys.executable, gate, str(res), "--baseline", str(base),
             "--static-budget", ""],
            capture_output=True, text=True)

    assert run(1000.0).returncode == 0
    assert run(990.0).returncode == 0        # within 10% tolerance
    p = run(500.0)
    assert p.returncode == 2 and "regression" in p.stderr
    # --update rolls the floor forward after a win
    res.write_text(json.dumps(dict(row, value=1500.0)) + "\n")
    p = subprocess.run(
        [sys.executable, gate, str(res), "--baseline", str(base),
         "--static-budget", "", "--update"],
        capture_output=True, text=True)
    assert p.returncode == 0 and "updated" in p.stdout
    assert run(1400.0).returncode == 0       # new floor active
    assert run(1000.0).returncode == 2


@pytest.mark.chaos
def test_engine_chaos_scenario():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import chaos_check
    finally:
        sys.path.pop(0)
    report = chaos_check.run_engine_chaos(seed=0)
    assert report["recovered"], report
