"""Eager autograd engine tests (backward walk, hooks, partial grad,
retain_graph, higher-order, PyLayer — reference capability checklist from
SURVEY.md §2.3)."""
import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu.autograd import PyLayer


def test_backward_simple():
    x = P.to_tensor([[1.0, 2.0], [3.0, 4.0]], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 2 * x.numpy())


def test_grad_accumulation():
    x = P.to_tensor([1.0, 2.0], stop_gradient=False)
    (x * 2).sum().backward()
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])
    x.clear_grad()
    assert x.grad is None


def test_shared_subexpression():
    x = P.to_tensor([2.0], stop_gradient=False)
    a = x * 3
    y = a * a  # d/dx = 2*9*x = 18x = 36
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [36.0])


def test_retain_graph():
    x = P.to_tensor([2.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [8.0])
    with pytest.raises(RuntimeError):
        y.backward()


def test_no_grad():
    x = P.to_tensor([1.0], stop_gradient=False)
    with P.no_grad():
        y = x * 2
    assert y.stop_gradient


def test_partial_grad():
    x = P.to_tensor([1.0, 2.0], stop_gradient=False)
    y = P.to_tensor([3.0, 4.0], stop_gradient=False)
    z = (x * y).sum()
    gx, = P.grad(z, x)
    np.testing.assert_allclose(gx.numpy(), y.numpy())
    assert x.grad is None  # paddle.grad does not touch .grad


def test_grad_intermediate():
    x = P.to_tensor([2.0], stop_gradient=False)
    mid = x * 3
    out = mid * mid
    gmid, = P.grad(out, mid)
    np.testing.assert_allclose(gmid.numpy(), [12.0])


def test_allow_unused():
    x = P.to_tensor([1.0], stop_gradient=False)
    y = P.to_tensor([1.0], stop_gradient=False)
    z = (x * 2).sum()
    with pytest.raises(RuntimeError):
        P.grad(z, [y])
    z = (x * 2).sum()  # graph was consumed by the failed call
    gx, gy = P.grad(z, [x, y], allow_unused=True)
    assert gy is None


def test_leaf_hook_and_remove():
    x = P.to_tensor([1.0], stop_gradient=False)
    h = x.register_hook(lambda g: g * 10)
    (x * 2).backward()
    np.testing.assert_allclose(x.grad.numpy(), [20.0])
    h.remove()
    x.clear_grad()
    (x * 2).backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])


def test_intermediate_hook():
    x = P.to_tensor([1.0], stop_gradient=False)
    mid = x * 2
    mid.register_hook(lambda g: g * 5)
    (mid * 3).backward()
    # dL/dmid = 3 -> hook -> 15 -> dL/dx = 30
    np.testing.assert_allclose(x.grad.numpy(), [30.0])


def test_higher_order():
    x = P.to_tensor([2.0], stop_gradient=False)
    y = x ** 4
    g1, = P.grad(y, x, create_graph=True)
    g2, = P.grad(g1, x, create_graph=True)
    g3, = P.grad(g2, x)
    np.testing.assert_allclose(g1.numpy(), [32.0])
    np.testing.assert_allclose(g2.numpy(), [48.0])
    np.testing.assert_allclose(g3.numpy(), [48.0])


def test_backward_nonscalar_with_grad_tensor():
    x = P.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 3
    y.backward(P.to_tensor([1.0, 10.0]))
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 30.0])


def test_detach():
    x = P.to_tensor([1.0], stop_gradient=False)
    y = (x * 2).detach()
    assert y.stop_gradient
    z = y * 3
    assert z.stop_gradient


def test_stop_gradient_island():
    x = P.to_tensor([1.0], stop_gradient=False)
    y = P.to_tensor([2.0])  # stop_gradient=True
    z = (x * y).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
    assert y.grad is None


def test_pylayer():
    class TimesK(PyLayer):
        @staticmethod
        def forward(ctx, x, k):
            ctx.k = k
            ctx.save_for_backward(x)
            return x * k

        @staticmethod
        def backward(ctx, gy):
            return gy * ctx.k

    x = P.to_tensor([3.0], stop_gradient=False)
    out = TimesK.apply(x, 5.0)
    out.backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])


def test_pylayer_multi_output():
    class SplitMul(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2, x * 3

        @staticmethod
        def backward(ctx, g1, g2):
            return g1 * 2 + g2 * 3

    x = P.to_tensor([1.0], stop_gradient=False)
    a, b = SplitMul.apply(x)
    (a + b).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])


def test_jacobian_hessian():
    from paddle_tpu.autograd import hessian, jacobian

    x = P.to_tensor([1.0, 2.0], stop_gradient=False)
    jac = jacobian(lambda t: t * t, x)
    np.testing.assert_allclose(jac.numpy(), np.diag([2.0, 4.0]))
    h = hessian(lambda t: (t * t * t).sum(), x)
    np.testing.assert_allclose(h.numpy(), np.diag([6.0, 12.0]))


def test_autocast_bf16():
    import paddle_tpu.amp as amp

    x = P.randn([4, 4])
    y = P.randn([4, 4])
    with amp.auto_cast():
        z = P.matmul(x, y)
    assert str(z.dtype) == "bfloat16"
    z2 = P.matmul(x, y)
    assert str(z2.dtype) == "float32"


def test_incubate_autograd_surface():
    """incubate.autograd parity (reference incubate/autograd/__init__.py
    __all__): functional vjp/jvp/Jacobian/Hessian + prim toggles +
    forward_grad/grad."""
    from paddle_tpu.incubate import autograd as IA

    for n in ("vjp", "jvp", "Jacobian", "Hessian", "enable_prim",
              "disable_prim", "forward_grad", "grad"):
        assert hasattr(IA, n), n
    x = P.to_tensor(np.array([3.0], np.float32))
    out, tang = IA.forward_grad(lambda t: t * t, x)
    np.testing.assert_allclose(np.asarray(tang._value), [6.0], rtol=1e-6)
    g = IA.grad(lambda t: t * t, x)
    gv = g.numpy() if hasattr(g, "numpy") else np.asarray(g)
    np.testing.assert_allclose(gv, [6.0], rtol=1e-6)
    IA.enable_prim()
    assert IA.prim_enabled()
    IA.disable_prim()
    assert not IA.prim_enabled()
