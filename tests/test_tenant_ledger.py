"""Per-tenant metering tests (ISSUE 16): the Space-Saving top-K sketch
(bounded cardinality, eviction folding, the conservation invariant),
the fleet snapshot merge, engine-token coherence, the bounded aggregate
mirror on the metrics registry (and the top-K table's deliberate
ABSENCE from /metrics), tenant identity propagation (headers, client
ctor, loadgen stamping), the serving-edge fallback chain over a live
toy server, and the telemetry_agg fleet rollup."""
import http.client
import json
import os
import sys
import urllib.error
import urllib.request

import pytest

from paddle_tpu import observability as obs
from paddle_tpu.inference.fleet import EchoPredictor, ToyEngine
from paddle_tpu.inference.serving import InferenceClient, InferenceServer
from paddle_tpu.observability import metrics, request_trace, trace
from paddle_tpu.observability import tenant_ledger as tl

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def telemetry():
    """Full stack on, clean registries, everything off again after.
    Reset BEFORE attach: attach() declares the schema zeros a reset
    would wipe."""
    metrics.reset()
    trace.clear()
    obs.flight.clear()
    obs.attach(crash_hook=False)
    yield
    obs.detach()
    metrics.reset()
    trace.clear()
    obs.flight.clear()


# --------------------------------------------------------------------------
# identity hygiene + env knobs
# --------------------------------------------------------------------------

def test_sanitize_tenant():
    assert tl.sanitize_tenant("acme-prod_1.eu:a") == "acme-prod_1.eu:a"
    assert tl.sanitize_tenant(None) is None
    assert tl.sanitize_tenant("") is None
    assert tl.sanitize_tenant("bad id") is None          # whitespace
    assert tl.sanitize_tenant("x" * 65) is None          # overlong
    assert tl.sanitize_tenant("a\nb") is None            # header-split
    assert tl.sanitize_tenant(123) == "123"              # stringified


def test_env_knobs(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_TENANT_LEDGER", raising=False)
    assert tl.enabled()
    monkeypatch.setenv("PADDLE_TPU_TENANT_LEDGER", "0")
    assert not tl.enabled()
    monkeypatch.setenv("PADDLE_TPU_TENANT_TOPK", "7")
    assert tl.topk() == 7
    assert tl.TenantLedger().k == 7
    monkeypatch.setenv("PADDLE_TPU_TENANT_TOPK", "bogus")
    assert tl.topk() == tl.DEFAULT_TOPK
    monkeypatch.setenv("PADDLE_TPU_TENANT_TOPK", "-3")
    assert tl.topk() == 1                                # floor at 1


# --------------------------------------------------------------------------
# the sketch: bounds, eviction folding, conservation
# --------------------------------------------------------------------------

def test_space_saving_bounds_and_folds():
    led = tl.TenantLedger(k=4)
    for i in range(100):
        led.record_request(f"t{i}", "ok")
        led.record_decode(f"t{i}", 3, count_engine_tokens=False)
    snap = led.snapshot()
    assert snap["schema"] == tl.SCHEMA_VERSION
    assert snap["tracked"] == 4 and len(snap["tenants"]) == 4
    assert snap["distinct_seen"] == 100
    assert snap["other"]["folds"] == 96
    # evicted tenants' EXACT counts live in ~other, nothing dropped
    assert snap["other"]["requests"]["ok"] == 96
    assert snap["other"]["decode_tokens"] == 96 * 3
    assert snap["totals"]["requests"]["ok"] == 100
    assert snap["totals"]["decode_tokens"] == 300
    assert tl.conservation_delta(snap) == {}
    # Space-Saving over-estimate bound: a late newcomer inherited the
    # victim's weight, and says so via err > 0
    assert any(e["err"] > 0 for e in snap["tenants"].values())


def test_heavy_hitter_survives_churn():
    led = tl.TenantLedger(k=4)
    for burst in range(25):
        led.record_request("whale", "ok")
        led.record_decode("whale", 50, count_engine_tokens=False)
        led.record_request(f"minnow-{burst}", "ok")
    snap = led.snapshot()
    assert "whale" in snap["tenants"]
    assert snap["tenants"]["whale"]["decode_tokens"] == 25 * 50
    assert tl.conservation_delta(snap) == {}


def test_conservation_mixed_fields():
    led = tl.TenantLedger(k=3)
    for i in range(20):
        t = f"t{i % 7}" if i % 3 else f"burst-{i}"
        led.record_request(t, ("ok", "shed", "error")[i % 3])
        led.record_prefill(t, computed=11 + i, saved=i % 5)
        led.record_decode(t, 1 + i % 4, count_engine_tokens=False)
        led.record_decode_slot_ms(t, 0.37 * (i + 1))
        led.record_page_seconds(t, 0.011 * (i + 1))
    assert led.conservation() == {}
    snap = led.snapshot()
    assert snap["totals"]["kv_page_seconds"] > 0
    assert snap["totals"]["decode_slot_ms"] > 0
    # a cooked snapshot must FAIL the check (the gate can actually trip)
    snap["totals"]["decode_tokens"] += 5
    assert tl.conservation_delta(snap) == {"decode_tokens": 5}


def test_status_discipline_and_anon_fallback():
    led = tl.TenantLedger(k=4)
    led.record_request("t1", "timeout")      # → error (bounded statuses)
    led.record_request("t1", "exploded")     # → error
    led.record_request("bad id!", "ok")      # hostile id → anon
    led.record_request(None, "ok")           # absent id → anon
    snap = led.snapshot()
    assert snap["tenants"]["t1"]["requests"] == {"error": 2}
    assert snap["tenants"][tl.ANON_TENANT]["requests"] == {"ok": 2}


def test_latency_reservoirs_top_k_only():
    led = tl.TenantLedger(k=2)
    led.record_request("a", "ok")
    led.record_request("b", "ok")
    for ms in (10.0, 20.0, 30.0):
        led.observe_ttft("a", ms)
        led.observe_itl("a", ms / 10)
    # an untracked tenant's sample is dropped, never admits it
    led.observe_ttft("stranger", 999.0)
    snap = led.snapshot()
    a = snap["tenants"]["a"]
    assert a["ttft_ms"]["n"] == 3 and a["ttft_ms"]["max"] == 30.0
    assert a["itl_ms"]["p50"] == pytest.approx(2.0)
    assert "ttft_ms" not in snap["tenants"]["b"]
    assert "stranger" not in snap["tenants"]
    assert snap["distinct_seen"] == 2


# --------------------------------------------------------------------------
# engine-token coherence + the bounded registry mirror
# --------------------------------------------------------------------------

def test_engine_token_coherence(telemetry):
    led = tl.TenantLedger(k=4)
    led.record_decode("t1", 5)               # owns the engine.tokens inc
    led.record_decode("t2", 2)
    led.record_decode("t3", 4, count_engine_tokens=False)  # alien bill
    snap = led.snapshot()
    assert snap["totals"]["decode_tokens"] == 11
    # the in-lock read-back: 7 engine tokens were billed THROUGH this
    # ledger; the count_engine_tokens=False path left the counter alone
    assert snap["metrics_engine_tokens"] == 7
    assert metrics.snapshot()["counters"]["engine.tokens"] == 7


def test_schema_zero_values(telemetry):
    counters = metrics.snapshot()["counters"]
    for s in ("ok", "shed", "client_error", "error"):
        assert counters[f"tenant.requests{{status={s}}}"] == 0
    gauges = metrics.snapshot()["gauges"]
    assert gauges["tenant.tracked"] == 0
    assert gauges["tenant.other_tokens"] == 0


def test_prometheus_excludes_tenant_table(telemetry):
    led = tl.TenantLedger(k=2)
    led.record_request("secret-tenant-alpha", "ok")
    led.record_request("secret-tenant-beta", "shed")
    led.record_request("secret-tenant-gamma", "ok")   # evicts one
    led.record_decode("secret-tenant-alpha", 9)
    snap = led.snapshot()                    # publishes the gauges
    assert snap["tracked"] == 2
    prom = metrics.to_prometheus()
    # the bounded aggregates ARE scrape-able...
    assert 'paddle_tpu_tenant_requests{status="ok"}' in prom
    assert "paddle_tpu_tenant_tracked 2" in prom
    # ...but no tenant id ever mints a metric series (cardinality
    # discipline: the top-K table lives ONLY in /debug/tenants + dumps)
    assert "secret-tenant" not in prom
    counters = metrics.snapshot()["counters"]
    assert counters["tenant.requests{status=ok}"] == 2
    assert counters["tenant.requests{status=shed}"] == 1


# --------------------------------------------------------------------------
# fleet merge
# --------------------------------------------------------------------------

def _mini_ledger(spec, k=4):
    led = tl.TenantLedger(k=k)
    for t, (ok, toks) in spec.items():
        for _ in range(ok):
            led.record_request(t, "ok")
        led.record_decode(t, toks, count_engine_tokens=False)
        led.record_page_seconds(t, toks * 0.25)
    return led


def test_merge_snapshots_sums_and_conserves():
    s1 = _mini_ledger({"a": (3, 30), "b": (1, 10)}).snapshot()
    s2 = _mini_ledger({"a": (2, 20), "c": (4, 40)}).snapshot()
    fleet = tl.merge_snapshots([s1, s2])
    assert fleet["merged_from"] == 2
    assert fleet["tenants"]["a"]["requests"]["ok"] == 5
    assert fleet["tenants"]["a"]["decode_tokens"] == 50
    assert fleet["tenants"]["a"]["kv_page_seconds"] == pytest.approx(
        12.5)
    assert fleet["totals"]["decode_tokens"] == 100
    assert fleet["distinct_seen"] == 4
    assert tl.conservation_delta(fleet) == {}
    # latency summaries are NOT additive → deliberately absent
    assert all("ttft_ms" not in e for e in fleet["tenants"].values())


def test_merge_truncates_union_to_k():
    snaps = [_mini_ledger({f"t{i}-{j}": (1, 10 + i + j)
                           for j in range(4)}, k=4).snapshot()
             for i in range(3)]
    fleet = tl.merge_snapshots(snaps, k=4)
    assert len(fleet["tenants"]) == 4
    # the 8 truncated tenants' counts folded into ~other, books balance
    assert fleet["other"]["folds"] == 8
    assert fleet["totals"]["requests"]["ok"] == 12
    assert tl.conservation_delta(fleet) == {}


def test_merge_sums_engine_tokens(telemetry):
    led = tl.TenantLedger(k=4)
    led.record_decode("t1", 6)
    s = led.snapshot()
    fleet = tl.merge_snapshots([s, dict(s)])
    assert fleet["metrics_engine_tokens"] == 12


# --------------------------------------------------------------------------
# identity propagation: headers, client ctor, loadgen stamping
# --------------------------------------------------------------------------

def test_request_context_header_roundtrip():
    ctx = request_trace.new_context(tenant_id="acme-1")
    h = ctx.to_headers()
    assert h[request_trace.HEADER_TENANT_ID] == "acme-1"
    back = request_trace.RequestContext.from_headers(h)
    assert back.tenant_id == "acme-1"
    assert back.child().tenant_id == "acme-1"            # survives hops
    # hostile header values are dropped at parse, not propagated
    h[request_trace.HEADER_TENANT_ID] = "bad id\r\nX-Evil: 1"
    assert request_trace.RequestContext.from_headers(h).tenant_id is None
    assert request_trace.new_context().tenant_id is None


def test_client_tenant_validation():
    with pytest.raises(ValueError):
        InferenceClient("http://h:1", tenant_id="bad id!")
    with pytest.raises(ValueError):
        InferenceClient("http://h:1", tenant_id="x" * 65)
    c = InferenceClient("http://h:1", tenant_id="team.red:eu-1")
    assert c.tenant_id == "team.red:eu-1"
    assert InferenceClient("http://h:1").tenant_id is None


def test_loadgen_stamps_tenant_header():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import loadgen
    finally:
        sys.path.pop(0)
    assert loadgen.tenant_name(3) == "tenant-3"
    # the stamped id is always ledger-legal (never degrades to anon)
    assert tl.sanitize_tenant(loadgen.tenant_name(7)) == "tenant-7"


# --------------------------------------------------------------------------
# the serving edge: fallback chain + /debug/tenants over a live server
# --------------------------------------------------------------------------

def _stream_generate(address, body, headers=()):
    host, port = address.split("//", 1)[1].rsplit(":", 1)
    conn = http.client.HTTPConnection(host, int(port), timeout=30)
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(dict(headers))
    conn.request("POST", "/generate", body=json.dumps(body),
                 headers=hdrs)
    resp = conn.getresponse()
    status = resp.status
    for line in resp:
        line = line.strip()
        if line and json.loads(line).get("done"):
            break
    conn.close()
    return status


def test_serving_edge_fallback_chain(telemetry):
    srv = InferenceServer(engine=ToyEngine(max_slots=4,
                                           token_time=0.001),
                          predictor=EchoPredictor(),
                          request_timeout=30.0).start()
    try:
        body = {"input_ids": [1, 2, 3], "max_new_tokens": 2}
        # 1) explicit header wins
        assert _stream_generate(srv.address, body,
                                {"X-Tenant-Id": "acme"}) == 200
        # 2) no header → prefix-fingerprint cohort key
        assert _stream_generate(
            srv.address, body,
            {"X-Prefix-Fingerprint": "abc123"}) == 200
        # 3) nothing at all → anon (the ledger never sees an
        #    unattributed request)
        assert _stream_generate(srv.address, body) == 200
        with urllib.request.urlopen(srv.address + "/debug/tenants",
                                    timeout=10) as r:
            snap = json.loads(r.read())
        rows = snap["tenants"]
        for t in ("acme", "fp:abc123", tl.ANON_TENANT):
            assert rows[t]["requests"]["ok"] == 1
            assert rows[t]["decode_tokens"] > 0
            assert rows[t]["ttft_ms"]["n"] >= 1
        assert tl.conservation_delta(snap) == {}
        # the toy engine bills decode THROUGH the adopted ledger, so
        # the in-lock read-back matches the books exactly
        assert snap["metrics_engine_tokens"] \
            == snap["totals"]["decode_tokens"]
        # the ledger also rides /debug/telemetry for the exporter
        with urllib.request.urlopen(srv.address + "/debug/telemetry",
                                    timeout=10) as r:
            tele = json.loads(r.read())
        assert tele["tenants"]["totals"]["requests"]["ok"] == 3
    finally:
        srv.shutdown()


def test_debug_tenants_404_when_disabled(telemetry, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_TENANT_LEDGER", "0")
    srv = InferenceServer(predictor=EchoPredictor(),
                          request_timeout=30.0).start()
    try:
        assert srv.tenant_ledger is None
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(srv.address + "/debug/tenants",
                                   timeout=10)
        assert ei.value.code == 404
    finally:
        srv.shutdown()


# --------------------------------------------------------------------------
# fleet rollup: tools/telemetry_agg.py
# --------------------------------------------------------------------------

def test_telemetry_agg_rollup_tenants(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_tagg", os.path.join(REPO, "tools", "telemetry_agg.py"))
    agg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(agg)

    def dump_line(host, pid, tenants_snap):
        return {"phase": "telemetry_dump", "t": "2026-08-04T00:00:00",
                "schema": "telemetry_dump/v1", "host": host,
                "pid": pid, "rank": None, "run_id": f"proc_{pid}",
                "seq": 1, "reason": "periodic", "wall": 1000.0,
                "trace_wall_epoch": 999.0, "trace_events": [],
                "flight_events": [],
                "metrics": {"counters": {}, "gauges": {},
                            "histograms": {}},
                "tenants": tenants_snap}

    s1 = _mini_ledger({"a": (3, 30), "b": (1, 10)}).snapshot()
    s2 = _mini_ledger({"a": (2, 20), "c": (4, 40)}).snapshot()
    for name, pid, snap in (("a", 11, s1), ("b", 22, s2)):
        with open(tmp_path / f"telemetry_{name}_{pid}.jsonl", "w") as f:
            f.write(json.dumps(dump_line(name, pid, snap)) + "\n")
    roll = agg.rollup(agg.load_dumps(str(tmp_path)))
    tenants = roll["tenants"]
    assert sorted(tenants["per_process"]) == ["a:11", "b:22"]
    fleet = tenants["fleet"]
    assert fleet["tenants"]["a"]["requests"]["ok"] == 5
    assert fleet["totals"]["decode_tokens"] == 100
    assert tl.conservation_delta(fleet) == {}
