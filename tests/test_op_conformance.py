"""Op conformance sweep (OpTest role at breadth): for every op in the
tables below assert
  * eager value matches the numpy reference (when numpy has one),
  * autodiff grad matches central finite differences (differentiable ops),
  * the op traces under jax.jit with identical output (dygraph/static leg),
  * 0-d and empty-tensor inputs keep elementwise shape semantics,
  * binary dtype promotion follows the jnp lattice.

Reference model: `test/legacy_test/` OpTest sweep + white_list policy
(SURVEY.md §4.1)."""
import numpy as np
import pytest

import jax

import paddle_tpu as P
from op_test import numeric_grad

rs = np.random.RandomState(11)


def _pos(shape):
    return np.asarray(rs.rand(*shape) + 0.5, np.float32)


def _std(shape):
    return np.asarray(rs.randn(*shape), np.float32)


def _unit(shape):
    return np.asarray(rs.rand(*shape) * 1.6 - 0.8, np.float32)


# name -> (input factory, numpy ref or None, grad-checkable)
UNARY_OPS = {
    "abs": (_std, np.abs, True),
    "acos": (_unit, np.arccos, True),
    "acosh": (lambda s: _pos(s) + 1.0, np.arccosh, True),
    "asin": (_unit, np.arcsin, True),
    "asinh": (_std, np.arcsinh, True),
    "atan": (_std, np.arctan, True),
    "atanh": (_unit, np.arctanh, True),
    "ceil": (_std, np.ceil, False),
    "cos": (_std, np.cos, True),
    "cosh": (_std, np.cosh, True),
    "digamma": (_pos, None, True),
    "erf": (_std, None, True),
    "erfinv": (_unit, None, True),
    "exp": (_std, np.exp, True),
    "expm1": (_std, np.expm1, True),
    "floor": (_std, np.floor, False),
    "frac": (_std, lambda x: x - np.trunc(x), False),
    "i0": (_pos, None, True),
    "i0e": (_pos, None, True),
    "i1": (_pos, None, True),
    "i1e": (_pos, None, True),
    "gammaln": (_pos, None, True),
    "lgamma": (_pos, None, True),
    "log": (_pos, np.log, True),
    "log10": (_pos, np.log10, True),
    "log1p": (_pos, np.log1p, True),
    "log2": (_pos, np.log2, True),
    "logit": (lambda s: np.asarray(rs.rand(*s) * 0.8 + 0.1, np.float32),
              None, True),
    "neg": (_std, np.negative, True),
    "reciprocal": (_pos, np.reciprocal, True),
    "round": (_std, np.round, False),
    "rsqrt": (_pos, lambda x: 1 / np.sqrt(x), True),
    "sigmoid": (_std, lambda x: 1 / (1 + np.exp(-x)), True),
    "sign": (_std, np.sign, False),
    "signbit": (_std, np.signbit, False),
    "sin": (_std, np.sin, True),
    "sinh": (_std, np.sinh, True),
    "sqrt": (_pos, np.sqrt, True),
    "square": (_std, np.square, True),
    "tan": (_unit, np.tan, True),
    "tanh": (_std, np.tanh, True),
    "trunc": (_std, np.trunc, False),
}

BINARY_OPS = {
    "add": (np.add, True),
    "subtract": (np.subtract, True),
    "multiply": (np.multiply, True),
    "divide": (np.true_divide, True),
    "maximum": (np.maximum, True),
    "minimum": (np.minimum, True),
    "pow": (None, True),
    "atan2": (np.arctan2, True),
    "fmax": (np.fmax, True),
    "fmin": (np.fmin, True),
    "hypot": (np.hypot, True),
    "ldexp": (None, False),
    "logaddexp": (np.logaddexp, True),
    "nextafter": (np.nextafter, False),
    "remainder": (None, False),
    "floor_divide": (None, False),
    "lerp": (None, True),
}

REDUCTIONS = {
    "sum": np.sum, "mean": np.mean, "max": np.max, "min": np.min,
    "prod": np.prod, "std": None, "var": None, "median": None,
    "logsumexp": None, "all": None, "any": None,
    "amax": np.max, "amin": np.min, "nansum": np.nansum,
    "nanmean": np.nanmean,
}


@pytest.mark.parametrize("name", sorted(UNARY_OPS))
def test_unary_conformance(name):
    make, ref, gradable = UNARY_OPS[name]
    fn = getattr(P, name)
    x = make((3, 4))
    out = fn(P.to_tensor(x))
    if ref is not None:
        np.testing.assert_allclose(out.numpy(), ref(x), rtol=2e-5,
                                   atol=2e-5)
    # jit parity (static leg)
    static = P.jit.to_static(lambda t: fn(t))
    np.testing.assert_allclose(static(P.to_tensor(x)).numpy(), out.numpy(),
                               rtol=1e-6, atol=1e-6)
    # 0-d and empty-tensor semantics
    z = fn(P.to_tensor(make(())))
    assert z.shape == []
    e = fn(P.to_tensor(make((0,))))
    assert e.shape == [0]
    if gradable:
        t = P.to_tensor(x, stop_gradient=False)
        fn(t).sum().backward()
        num = numeric_grad(lambda v: fn(P.to_tensor(v)), [x], 0)
        np.testing.assert_allclose(t.grad.numpy(), num, rtol=2e-2,
                                   atol=2e-2)


@pytest.mark.parametrize("name", sorted(BINARY_OPS))
def test_binary_conformance(name):
    ref, gradable = BINARY_OPS[name]
    fn = getattr(P, name)
    # per-test RNG: the module-level stream made inputs depend on which
    # tests ran before (fmin's grad check hit near-ties only in full runs)
    rs = np.random.RandomState(sum(map(ord, name)))
    x = (rs.rand(3, 4) + 0.5).astype(np.float32)
    y = (rs.rand(3, 4) + 0.5).astype(np.float32)
    if name in ("fmax", "fmin", "maximum", "minimum"):
        # finite differences (delta=1e-3) straddle the kink where x == y;
        # keep the operands separated so the subgradient choice can't flip
        y = np.where(np.abs(x - y) < 5e-3, y + 1e-2, y).astype(np.float32)
    if name == "lerp":
        out = fn(P.to_tensor(x), P.to_tensor(y), 0.3)
        call = lambda a, b: fn(P.to_tensor(a), P.to_tensor(b), 0.3)  # noqa
    else:
        out = fn(P.to_tensor(x), P.to_tensor(y))
        call = lambda a, b: fn(P.to_tensor(a), P.to_tensor(b))  # noqa
    if ref is not None:
        np.testing.assert_allclose(out.numpy(), ref(x, y), rtol=2e-5,
                                   atol=2e-5)
    # broadcasting leg
    yb = (rs.rand(4) + 0.5).astype(np.float32)
    if name != "lerp":
        outb = fn(P.to_tensor(x), P.to_tensor(yb))
        assert outb.shape == [3, 4]
    if gradable:
        tx = P.to_tensor(x, stop_gradient=False)
        ty = P.to_tensor(y, stop_gradient=False)
        if name == "lerp":
            fn(tx, ty, 0.3).sum().backward()
        else:
            fn(tx, ty).sum().backward()
        num_x = numeric_grad(lambda a, b: call(a, b), [x, y], 0)
        num_y = numeric_grad(lambda a, b: call(a, b), [x, y], 1)
        np.testing.assert_allclose(tx.grad.numpy(), num_x, rtol=2e-2,
                                   atol=2e-2)
        np.testing.assert_allclose(ty.grad.numpy(), num_y, rtol=2e-2,
                                   atol=2e-2)


@pytest.mark.parametrize("name", sorted(REDUCTIONS))
def test_reduction_conformance(name):
    fn = getattr(P, name)
    x = rs.rand(3, 4).astype(np.float32) + 0.1
    out = fn(P.to_tensor(x))
    ref = REDUCTIONS[name]
    if ref is not None:
        np.testing.assert_allclose(np.asarray(out.numpy(), np.float32),
                                   np.asarray(ref(x), np.float32),
                                   rtol=1e-5, atol=1e-5)
    # axis + keepdim semantics
    out_ax = fn(P.to_tensor(x), axis=1)
    assert out_ax.shape == [3]
    out_kd = fn(P.to_tensor(x), axis=1, keepdim=True)
    assert out_kd.shape == [3, 1]
    # 0-d input reduces to 0-d
    assert fn(P.to_tensor(np.float32(0.5))).shape == []


def test_dtype_promotion_matrix():
    cases = [
        ("float32", "float32", "float32"),
        ("float32", "int32", "float32"),
        # documented TPU-first demotion (core/dtypes.py convert_dtype):
        # with x64 disabled an `int64` request IS int32, so the widest
        # integer result of int32+int64 is int32 — asserted here as the
        # framework's contract, diverging from the reference's lattice
        ("int32", "int64", "int32"),
        ("bool", "int32", "int32"),
        ("bfloat16", "float32", "float32"),
    ]
    for da, db, expect in cases:
        a = P.ones([2], dtype=da)
        b = P.ones([2], dtype=db)
        out = P.add(a, b)
        assert expect in str(out.dtype), (da, db, out.dtype)


def test_empty_tensor_reductions_and_concat():
    e = P.to_tensor(np.zeros((0, 4), np.float32))
    assert float(P.sum(e).numpy()) == 0.0
    c = P.concat([e, P.ones([2, 4])], axis=0)
    assert c.shape == [2, 4]
    assert P.abs(e).shape == [0, 4]
