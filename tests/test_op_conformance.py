"""Op conformance sweep (OpTest role at breadth), driven FROM the manifest:
the parametrization lists are read out of OPS_MANIFEST.json `conformance`
entries (VERDICT r2 task 7), and each listed op must have a spec in
conformance_tables.py — so "present and conformance-tested" is a machine
property of the manifest, not a regex guess. For every op assert
  * eager value matches the numpy reference (when numpy has one),
  * autodiff grad matches central finite differences (differentiable ops),
  * the op traces under jax.jit with identical output (dygraph/static leg),
  * 0-d and empty-tensor inputs keep elementwise shape semantics,
  * binary dtype promotion follows the documented demotion lattice.

Reference model: `test/legacy_test/` OpTest sweep + white_list policy
(SURVEY.md §4.1)."""
import json
import os

import numpy as np
import pytest

import jax

import paddle_tpu as P
from op_test import numeric_grad
from conformance_tables import (
    UNARY_OPS, BINARY_OPS, REDUCTIONS, COMPARISON_OPS, INT_BINARY_OPS,
    INT_UNARY_OPS, rs, _pos, _std, _unit,
)

with open(os.path.join(os.path.dirname(__file__), "..",
                       "OPS_MANIFEST.json")) as _f:
    _MANIFEST_CONF = {
        e["name"]: e["conformance"]
        for e in json.load(_f)["ops"] if e.get("conformance")
    }


def _from_manifest(kind):
    names = sorted(n for n, c in _MANIFEST_CONF.items()
                   if c.get("kind") == kind)
    assert names, f"manifest lists no {kind} conformance ops — regenerate"
    return names


@pytest.mark.parametrize("name", _from_manifest("unary"))
def test_unary_conformance(name):
    assert name in UNARY_OPS, \
        f"manifest conformance entry for {name} has no table spec"
    make, ref, gradable = UNARY_OPS[name]
    fn = getattr(P, name)
    x = make((3, 4))
    out = fn(P.to_tensor(x))
    if ref is not None:
        np.testing.assert_allclose(out.numpy(), ref(x), rtol=2e-5,
                                   atol=2e-5)
    # jit parity (static leg)
    static = P.jit.to_static(lambda t: fn(t))
    np.testing.assert_allclose(static(P.to_tensor(x)).numpy(), out.numpy(),
                               rtol=1e-6, atol=1e-6)
    # 0-d and empty-tensor semantics
    z = fn(P.to_tensor(make(())))
    assert z.shape == []
    e = fn(P.to_tensor(make((0,))))
    assert e.shape == [0]
    if gradable:
        t = P.to_tensor(x, stop_gradient=False)
        fn(t).sum().backward()
        num = numeric_grad(lambda v: fn(P.to_tensor(v)), [x], 0)
        np.testing.assert_allclose(t.grad.numpy(), num, rtol=2e-2,
                                   atol=2e-2)


@pytest.mark.parametrize("name", _from_manifest("binary"))
def test_binary_conformance(name):
    assert name in BINARY_OPS, \
        f"manifest conformance entry for {name} has no table spec"
    ref, gradable = BINARY_OPS[name]
    fn = getattr(P, name)
    # per-test RNG: the module-level stream made inputs depend on which
    # tests ran before (fmin's grad check hit near-ties only in full runs)
    rs = np.random.RandomState(sum(map(ord, name)))
    x = (rs.rand(3, 4) + 0.5).astype(np.float32)
    y = (rs.rand(3, 4) + 0.5).astype(np.float32)
    if name in ("fmax", "fmin", "maximum", "minimum"):
        # finite differences (delta=1e-3) straddle the kink where x == y;
        # keep the operands separated so the subgradient choice can't flip
        y = np.where(np.abs(x - y) < 5e-3, y + 1e-2, y).astype(np.float32)
    if name == "lerp":
        out = fn(P.to_tensor(x), P.to_tensor(y), 0.3)
        call = lambda a, b: fn(P.to_tensor(a), P.to_tensor(b), 0.3)  # noqa
    else:
        out = fn(P.to_tensor(x), P.to_tensor(y))
        call = lambda a, b: fn(P.to_tensor(a), P.to_tensor(b))  # noqa
    if ref is not None:
        np.testing.assert_allclose(out.numpy(), ref(x, y), rtol=2e-5,
                                   atol=2e-5)
    # broadcasting leg
    yb = (rs.rand(4) + 0.5).astype(np.float32)
    if name != "lerp":
        outb = fn(P.to_tensor(x), P.to_tensor(yb))
        assert outb.shape == [3, 4]
    if gradable:
        tx = P.to_tensor(x, stop_gradient=False)
        ty = P.to_tensor(y, stop_gradient=False)
        if name == "lerp":
            fn(tx, ty, 0.3).sum().backward()
        else:
            fn(tx, ty).sum().backward()
        num_x = numeric_grad(lambda a, b: call(a, b), [x, y], 0)
        num_y = numeric_grad(lambda a, b: call(a, b), [x, y], 1)
        np.testing.assert_allclose(tx.grad.numpy(), num_x, rtol=2e-2,
                                   atol=2e-2)
        np.testing.assert_allclose(ty.grad.numpy(), num_y, rtol=2e-2,
                                   atol=2e-2)


@pytest.mark.parametrize("name", _from_manifest("reduction"))
def test_reduction_conformance(name):
    assert name in REDUCTIONS, \
        f"manifest conformance entry for {name} has no table spec"
    fn = getattr(P, name)
    x = rs.rand(3, 4).astype(np.float32) + 0.1
    out = fn(P.to_tensor(x))
    ref = REDUCTIONS[name]
    if ref is not None:
        np.testing.assert_allclose(np.asarray(out.numpy(), np.float32),
                                   np.asarray(ref(x), np.float32),
                                   rtol=1e-5, atol=1e-5)
    # axis + keepdim semantics
    out_ax = fn(P.to_tensor(x), axis=1)
    assert out_ax.shape == [3]
    out_kd = fn(P.to_tensor(x), axis=1, keepdim=True)
    assert out_kd.shape == [3, 1]
    # 0-d input reduces to 0-d
    assert fn(P.to_tensor(np.float32(0.5))).shape == []


@pytest.mark.parametrize("name", _from_manifest("comparison"))
def test_comparison_conformance(name):
    assert name in COMPARISON_OPS, \
        f"manifest conformance entry for {name} has no table spec"
    ref = COMPARISON_OPS[name]
    fn = getattr(P, name)
    r = np.random.RandomState(sum(map(ord, name)))
    x = r.randint(0, 3, (3, 4)).astype(np.float32)
    y = r.randint(0, 3, (3, 4)).astype(np.float32)
    out = fn(P.to_tensor(x), P.to_tensor(y))
    np.testing.assert_array_equal(np.asarray(out.numpy(), bool),
                                  ref(x, y))
    # jit parity
    static = P.jit.to_static(lambda a, b: fn(a, b))
    np.testing.assert_array_equal(
        np.asarray(static(P.to_tensor(x), P.to_tensor(y)).numpy(), bool),
        ref(x, y))


@pytest.mark.parametrize("name", _from_manifest("int_binary"))
def test_int_binary_conformance(name):
    assert name in INT_BINARY_OPS, \
        f"manifest conformance entry for {name} has no table spec"
    ref = INT_BINARY_OPS[name]
    fn = getattr(P, name)
    r = np.random.RandomState(sum(map(ord, name)))
    x = r.randint(1, 64, (3, 4)).astype(np.int32)
    y = r.randint(1, 64, (3, 4)).astype(np.int32)
    out = fn(P.to_tensor(x), P.to_tensor(y))
    np.testing.assert_array_equal(out.numpy(), ref(x, y))


@pytest.mark.parametrize("name", _from_manifest("int_unary"))
def test_int_unary_conformance(name):
    assert name in INT_UNARY_OPS, \
        f"manifest conformance entry for {name} has no table spec"
    ref = INT_UNARY_OPS[name]
    fn = getattr(P, name)
    r = np.random.RandomState(sum(map(ord, name)))
    x = r.randint(0, 64, (3, 4)).astype(np.int32)
    out = fn(P.to_tensor(x))
    np.testing.assert_array_equal(out.numpy(), ref(x))


def _inplace_names():
    return sorted(n for n, c in _MANIFEST_CONF.items()
                  if c.get("kind") == "inplace")


@pytest.mark.parametrize("name", _inplace_names())
def test_inplace_variant_matches_outofplace(name):
    """Every manifest op with kind=inplace: `op_(x)` must equal `op(x)`
    and mutate the tensor in place (reference inplace-map rows of
    ops.yaml)."""
    base = _MANIFEST_CONF[name]["base"]
    kind = _MANIFEST_CONF[base]["kind"]
    r = np.random.RandomState(sum(map(ord, name)) + 1)
    if kind == "unary":
        x = UNARY_OPS[base][0]((3, 4))
        args = ()
    elif kind == "int_unary":
        x = r.randint(0, 64, (3, 4)).astype(np.int32)
        args = ()
    elif kind == "int_binary":
        x = r.randint(1, 64, (3, 4)).astype(np.int32)
        args = (P.to_tensor(r.randint(1, 64, (3, 4)).astype(np.int32)),)
    elif kind == "comparison":
        x = r.randint(0, 3, (3, 4)).astype(np.float32)
        args = (P.to_tensor(r.randint(0, 3, (3, 4)).astype(np.float32)),)
    else:  # binary
        x = (r.rand(3, 4) + 0.5).astype(np.float32)
        args = (P.to_tensor((r.rand(3, 4) + 0.5).astype(np.float32)),)
        if base == "lerp":
            args = args + (0.3,)
    expect = getattr(P, base)(P.to_tensor(x), *args).numpy()
    t = P.to_tensor(x)
    out = getattr(P, name)(t, *args)
    if str(expect.dtype) == str(np.asarray(t.numpy()).dtype):
        # true in-place: the tensor itself carries the result
        np.testing.assert_allclose(t.numpy(), expect, rtol=1e-6,
                                   atol=1e-6)
    np.testing.assert_allclose(np.asarray(out.numpy(), expect.dtype),
                               expect, rtol=1e-6, atol=1e-6)


def test_dtype_promotion_matrix():
    cases = [
        ("float32", "float32", "float32"),
        ("float32", "int32", "float32"),
        # documented TPU-first demotion (core/dtypes.py convert_dtype):
        # with x64 disabled an `int64` request IS int32, so the widest
        # integer result of int32+int64 is int32 — asserted here as the
        # framework's contract, diverging from the reference's lattice
        ("int32", "int64", "int32"),
        ("bool", "int32", "int32"),
        ("bfloat16", "float32", "float32"),
    ]
    for da, db, expect in cases:
        a = P.ones([2], dtype=da)
        b = P.ones([2], dtype=db)
        out = P.add(a, b)
        assert expect in str(out.dtype), (da, db, out.dtype)


def test_empty_tensor_reductions_and_concat():
    e = P.to_tensor(np.zeros((0, 4), np.float32))
    assert float(P.sum(e).numpy()) == 0.0
    c = P.concat([e, P.ones([2, 4])], axis=0)
    assert c.shape == [2, 4]
    assert P.abs(e).shape == [0, 4]


# ---------------- seeded random-shape fuzz (robustness layer) ------------

_FUZZ_SHAPES = [
    (1,), (7,), (2, 3), (5, 1), (1, 1, 4), (3, 2, 5), (2, 1, 3, 2), (8, 8),
]


@pytest.mark.parametrize("trial", range(2))
def test_unary_fuzz_random_shapes(trial):
    """Shape fuzz: every manifest unary op at irregular shapes (odd
    sizes, leading 1s, 4-D) — eager values vs the numpy reference.
    Catches shape assumptions the fixed (3, 4) sweep can't. Inputs are
    reseeded per test so failures reproduce standalone."""
    rs.seed(1000 + trial)
    for i, name in enumerate(_from_manifest("unary")):
        make, ref, _ = UNARY_OPS[name]
        fn = getattr(P, name)
        # every op walks the whole shape list across (op index, trial)
        shape = _FUZZ_SHAPES[(i + trial * 3) % len(_FUZZ_SHAPES)]
        x = make(shape)
        out = fn(P.to_tensor(x))
        assert tuple(out.shape) == x.shape, (name, shape, out.shape)
        if ref is not None:
            np.testing.assert_allclose(out.numpy(), ref(x), rtol=3e-5,
                                       atol=3e-5, err_msg=f"{name}@{shape}")


@pytest.mark.parametrize("trial", range(4))
def test_binary_fuzz_broadcast_shapes(trial):
    """Broadcast fuzz: elementwise binary ops under broadcasting pairs
    (the fixed sweep uses equal shapes only). Reseeded per test so
    failures reproduce standalone."""
    rs.seed(2000 + trial)
    pairs = [((2, 3), (3,)), ((4, 1), (1, 5)), ((1,), (3, 2)),
             ((2, 1, 3), (1, 4, 1))]
    a_shape, b_shape = pairs[trial]
    for name in ("add", "subtract", "multiply", "maximum", "minimum",
                 "atan2", "fmax", "fmin", "hypot", "logaddexp", "divide"):
        ref, _ = BINARY_OPS[name]
        fn = getattr(P, name)
        x = _std(a_shape)
        y = _pos(b_shape) if name == "divide" else _std(b_shape)
        out = fn(P.to_tensor(x), P.to_tensor(y))
        expect_shape = np.broadcast_shapes(a_shape, b_shape)
        assert tuple(out.shape) == expect_shape, (name, out.shape)
        if ref is not None:
            np.testing.assert_allclose(out.numpy(), ref(x, y), rtol=3e-5,
                                       atol=3e-5,
                                       err_msg=f"{name}@{a_shape}x{b_shape}")
