"""Multi-tenant QoS tests (ISSUE 18): priority classes as the shared
vocabulary (`inference/qos.py`), class identity on the request-trace
headers (validate-or-drop), class-aware edge admission (partitioned
queue, displacement, strict-priority dequeue, starvation aging,
class-scaled Retry-After, the queue_timeout/deadline reason split),
preemptive decode scheduling through the recompute-eviction path
(bit-identical resume across the bf16 / int8-KV / speculative tiers,
warm re-admission), per-tenant decode-slot quotas, per-class SLO
burn, loadgen class cohorts, and the `serving_qos_paid_p99_ratio`
perf-gate round trip.  Deterministic, CPU-only; fake clocks wherever
waiting would otherwise be real.
"""
import importlib.util
import json
import os
import random
import threading
import time

import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu.inference import qos
from paddle_tpu.inference.engine import (
    EngineConfig, InferenceEngine, PagePool, Scheduler, Sequence,
)
from paddle_tpu.inference.engine.scheduler import RUNNING, WAITING
from paddle_tpu.inference.serving import InferenceClient
from paddle_tpu.observability import request_trace as rtrace
from paddle_tpu.observability.slo import SLOTracker
from paddle_tpu.resilience.overload import AdmissionController, ShedError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# --------------------------------------------------------------------------
# the class vocabulary
# --------------------------------------------------------------------------

def test_class_order_and_knobs():
    """paid > free > batch is the one ordering every layer prices."""
    assert qos.CLASSES == ("paid", "free", "batch")
    assert qos.class_rank("paid") > qos.class_rank("free") \
        > qos.class_rank("batch")
    assert qos.class_weight("paid") > qos.class_weight("free") \
        > qos.class_weight("batch")
    assert qos.retry_after_factor("paid") < qos.retry_after_factor(
        "free") < qos.retry_after_factor("batch")
    # unknown input behaves like the default class, never crashes
    assert qos.class_rank("???") == qos.class_rank(qos.DEFAULT_CLASS)


def test_normalize_class_validate_or_drop():
    assert qos.normalize_class(" Paid ") == "paid"
    assert qos.normalize_class("FREE") == "free"
    assert qos.normalize_class(None) is None
    assert qos.normalize_class("platinum") is None
    assert qos.normalize_class("") is None


def test_class_map_from_env_and_resolution_order():
    rules = qos.class_map_from_env(
        "tenant-0:paid, team-*:batch, bogus, x:platinum, *:free")
    # malformed / unknown-class entries dropped, order preserved
    assert rules == [("tenant-0", "paid"), ("team-*", "batch"),
                     ("*", "free")]
    # explicit (validated) class always wins
    assert qos.resolve_class("tenant-0", explicit="batch",
                             rules=rules) == "batch"
    # garbage explicit falls through to the map
    assert qos.resolve_class("tenant-0", explicit="platinum",
                             rules=rules) == "paid"
    # first match wins; no match -> default
    assert qos.resolve_class("team-7", rules=rules) == "batch"
    assert qos.resolve_class("anyone", rules=rules) == "free"
    assert qos.resolve_class("anyone", rules=[]) == qos.DEFAULT_CLASS


# --------------------------------------------------------------------------
# request-trace identity headers
# --------------------------------------------------------------------------

def test_priority_headers_round_trip():
    ctx = rtrace.new_context(tenant_id="t0", priority_class="paid",
                             deadline_ms=1500)
    h = ctx.to_headers()
    assert h[rtrace.HEADER_PRIORITY_CLASS] == "paid"
    assert h[rtrace.HEADER_DEADLINE_MS] == "1500"
    back = rtrace.RequestContext.from_headers(h)
    assert back.priority_class == "paid"
    assert back.deadline_ms == 1500
    # the forwarded hop keeps both (the router's child() carries them)
    child = back.child()
    assert child.priority_class == "paid" and child.deadline_ms == 1500


def test_priority_headers_validate_or_drop():
    h = rtrace.new_context().to_headers()
    h[rtrace.HEADER_PRIORITY_CLASS] = "platinum; DROP TABLE"
    h[rtrace.HEADER_DEADLINE_MS] = "-5"
    bad = rtrace.RequestContext.from_headers(h)
    assert bad.priority_class is None
    assert bad.deadline_ms is None
    h[rtrace.HEADER_DEADLINE_MS] = "999999999999"
    huge = rtrace.RequestContext.from_headers(h)
    assert huge.deadline_ms == 3_600_000  # clamped, not trusted


def test_inference_client_validates_loudly():
    """A misconfigured CLIENT raises at construction — silent dropping
    is for untrusted wire input, not for the caller's own config."""
    with pytest.raises(ValueError, match="priority_class"):
        InferenceClient("http://localhost:1", priority_class="platinum")
    with pytest.raises(ValueError, match="deadline_ms"):
        InferenceClient("http://localhost:1", deadline_ms=0)
    cli = InferenceClient("http://localhost:1", priority_class="PAID",
                          deadline_ms=250)
    assert cli.priority_class == "paid" and cli.deadline_ms == 250


# --------------------------------------------------------------------------
# class-aware edge admission
# --------------------------------------------------------------------------

def _ctl(**kw):
    kw.setdefault("max_inflight", 1)
    kw.setdefault("queue_depth", 8)
    kw.setdefault("queue_timeout", 10.0)
    return AdmissionController(**kw)


def _waiter_thread(ctl, cls, out, deadline=None):
    def run():
        try:
            t = ctl.admit(deadline=deadline, priority_class=cls)
            out.append(("ok", cls, t))
        except ShedError as e:
            out.append(("shed", cls, e))
    th = threading.Thread(target=run, daemon=True)
    th.start()
    return th


def _wait_queued(ctl, n, timeout=5.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if ctl.stats()["queued"] >= n:
            return
        time.sleep(0.005)
    raise AssertionError(f"never saw {n} queued: {ctl.stats()}")


def test_strict_priority_dequeue():
    """With the slot held, a batch waiter then a paid waiter queue up;
    the freed slot goes to paid first — FIFO only within a class."""
    ctl = _ctl()
    holder = ctl.admit(priority_class="paid")
    out = []
    t1 = _waiter_thread(ctl, "batch", out)
    _wait_queued(ctl, 1)
    t2 = _waiter_thread(ctl, "paid", out)
    _wait_queued(ctl, 2)
    holder.release()
    # paid admits first; release it so batch can follow
    for _ in range(500):
        if out:
            break
        time.sleep(0.005)
    assert out and out[0][:2] == ("ok", "paid")
    out[0][2].release()
    t1.join(timeout=5)
    t2.join(timeout=5)
    assert [o[:2] for o in out] == [("ok", "paid"), ("ok", "batch")]


def test_queue_partition_caps_lower_classes():
    """The nested weighted shares: batch may hold at most its share of
    the queue; free+batch theirs; paid the whole depth.  A batch flood
    can never camp the slots a paid request needs."""
    ctl = _ctl(queue_depth=7)
    with ctl._cv:
        batch_cap = ctl._class_cap_locked(qos.class_rank("batch"))
        free_cap = ctl._class_cap_locked(qos.class_rank("free"))
        paid_cap = ctl._class_cap_locked(qos.class_rank("paid"))
    # weights 4/2/1: batch 1/7, free+batch 3/7, paid everything
    assert batch_cap == 1 and free_cap == 3 and paid_cap == 7
    assert batch_cap < free_cap < paid_cap


def test_higher_class_arrival_displaces_lowest_youngest():
    """A full queue sheds the lowest-class YOUNGEST waiter to make room
    for a paid arrival — the displaced waiter sheds politely (429 +
    Retry-After), it does not fail."""
    ctl = _ctl(queue_depth=1)
    holder = ctl.admit(priority_class="free")
    out = []
    _waiter_thread(ctl, "batch", out)
    _wait_queued(ctl, 1)
    t2 = _waiter_thread(ctl, "paid", out)
    # paid takes the queue spot; the displaced batch waiter sheds
    for _ in range(500):
        if any(o[0] == "shed" for o in out):
            break
        time.sleep(0.005)
    sheds = [o for o in out if o[0] == "shed"]
    assert sheds and sheds[0][1] == "batch"
    assert sheds[0][2].reason == "queue_full"
    assert sheds[0][2].http_status == 429
    holder.release()
    t2.join(timeout=5)
    assert ("ok", "paid") in [o[:2] for o in out]
    stats = ctl.stats()
    assert stats["shed_by_class"]["batch"] == 1
    assert stats["shed_by_class"]["paid"] == 0


def test_paid_never_displaced_by_anyone():
    """Nothing outranks the top class: a second paid arrival into a
    paid-full queue shed ITSELF (queue_full), never the waiter."""
    ctl = _ctl(queue_depth=1)
    holder = ctl.admit(priority_class="paid")
    out = []
    t1 = _waiter_thread(ctl, "paid", out)
    _wait_queued(ctl, 1)
    with pytest.raises(ShedError) as ei:
        ctl.admit(priority_class="paid")
    assert ei.value.reason == "queue_full"
    holder.release()
    t1.join(timeout=5)
    assert out and out[0][:2] == ("ok", "paid")


def test_aging_bounds_starvation():
    """A batch waiter gains one rank per qos_age_s: after enough queued
    time it outranks a newly-arrived paid request and runs — strict
    priority, but never forever."""
    clock = _Clock()
    ctl = _ctl(clock=clock, qos_age_s=1.0)
    holder = ctl.admit(priority_class="paid")
    out = []
    t1 = _waiter_thread(ctl, "batch", out)
    _wait_queued(ctl, 1)
    clock.advance(2.5)  # batch effective rank: 0 + 2 == paid's
    t2 = _waiter_thread(ctl, "paid", out)
    _wait_queued(ctl, 2)
    holder.release()
    for _ in range(500):
        if out:
            break
        time.sleep(0.005)
    # the STARVED batch waiter wins the freed slot (FIFO at equal
    # effective rank) — with aging off it would have waited forever
    assert out and out[0][:2] == ("ok", "batch")
    out[0][2].release()
    t1.join(timeout=5)
    t2.join(timeout=5)
    assert ("ok", "paid") in [o[:2] for o in out]


def test_retry_after_scales_by_class():
    """The same pressure estimate, class-scaled: a shed batch client is
    told to back off 4x longer than a shed paid one."""
    clock = _Clock()
    ctl = _ctl(queue_depth=0, clock=clock)
    t = ctl.admit(priority_class="paid")
    clock.advance(1.0)
    t.release()               # EWMA = 1.0s -> estimate is nonzero
    holder = ctl.admit(priority_class="paid")
    sheds = {}
    for cls in ("paid", "batch"):
        with pytest.raises(ShedError) as ei:
            ctl.admit(priority_class=cls)
        sheds[cls] = ei.value.retry_after
    holder.release()
    assert sheds["paid"] > 0
    assert sheds["batch"] == pytest.approx(4.0 * sheds["paid"])


def test_shed_reason_split_queue_timeout_vs_deadline():
    """The bugfix: a plain operator queue-timeout sheds
    `queue_timeout`; a queue wait bounded by the request's own deadline
    sheds `deadline` — the client's actionable signal differs (retry
    later vs give up)."""
    ctl = AdmissionController(max_inflight=1, queue_depth=4,
                              queue_timeout=0.15)
    holder = ctl.admit(priority_class="free")
    with pytest.raises(ShedError) as ei:
        ctl.admit(priority_class="free")  # no deadline of its own
    assert ei.value.reason == "queue_timeout"
    with pytest.raises(ShedError) as ei:
        ctl.admit(deadline=time.monotonic() + 0.05,
                  priority_class="free")  # its deadline binds first
    assert ei.value.reason == "deadline"
    holder.release()
    stats = ctl.stats()
    assert stats["shed"]["queue_timeout"] == 1
    assert stats["shed"]["deadline"] == 1


# --------------------------------------------------------------------------
# preemptive decode scheduling
# --------------------------------------------------------------------------

def _sched(clock, max_slots=1, quotas=None):
    pool = PagePool(num_pages=32, page_size=8)
    return Scheduler(max_slots=max_slots, pool=pool,
                     max_pages_per_seq=8, clock=clock,
                     qos_age_s=30.0, quotas=quotas or {}), pool


def test_paid_preempts_running_free():
    clock = _Clock()
    sch, pool = _sched(clock)
    free = Sequence(np.arange(8), 4, priority_class="free")
    sch.submit(free)
    assert sch.schedule().prefills == [free]
    assert free.state == RUNNING
    paid = Sequence(np.arange(8), 4, priority_class="paid")
    sch.submit(paid)
    out = sch.schedule()
    assert paid in out.prefills
    assert out.evicted == [free]
    # the victim went through the recompute-eviction path: pages freed,
    # back at the FRONT of the waiting queue, resumable
    assert free.state == WAITING and free.pages == [] \
        and free.evictions == 1
    assert sch.stats()["by_class"]["paid"]["running"] == 1
    assert sch.stats()["by_class"]["free"]["waiting"] == 1


def test_preemption_never_evicts_a_class_peer():
    clock = _Clock()
    sch, _ = _sched(clock)
    a = Sequence(np.arange(8), 4, priority_class="free")
    sch.submit(a)
    sch.schedule()
    b = Sequence(np.arange(8), 4, priority_class="free")
    sch.submit(b)
    out = sch.schedule()
    assert out.evicted == [] and a.state == RUNNING \
        and b.state == WAITING


def test_aging_earns_a_slot_not_someone_elses():
    """The policy rule: ADMISSION order uses the aged rank (a starved
    batch sequence beats a fresh paid one to a FREE slot), but
    preemption victims are chosen on STATIC rank only — an aged batch
    request must never evict a running free one."""
    clock = _Clock()
    sch, _ = _sched(clock)
    free = Sequence(np.arange(8), 4, priority_class="free")
    sch.submit(free)
    sch.schedule()
    batch = Sequence(np.arange(8), 4, priority_class="batch")
    sch.submit(batch)
    clock.advance(95.0)  # batch effective rank aged past paid's
    out = sch.schedule()
    assert out.evicted == [] and batch.state == WAITING  # no eviction
    # ...admission ORDER does honor the aged rank: with room for both,
    # the starved batch sequence prefills ahead of a fresh paid one
    clock2 = _Clock()
    sch2, _ = _sched(clock2, max_slots=2)
    batch2 = Sequence(np.arange(8), 4, priority_class="batch")
    sch2.submit(batch2)
    clock2.advance(95.0)
    paid = Sequence(np.arange(8), 4, priority_class="paid")
    sch2.submit(paid)
    out = sch2.schedule()
    assert out.prefills == [batch2, paid] and out.evicted == []


def test_over_quota_tenant_admitted_last_within_class():
    """Per-tenant decode-slot quotas, priced in decode-slot-ms: the
    tenant over its class's slot budget queues behind on-quota peers of
    the SAME class (work-conserving — it still runs when slots are
    spare), and quota never reorders ACROSS classes."""
    clock = _Clock()
    sch, _ = _sched(clock, quotas={"free": 0.25})
    clock.advance(1.0)
    # tenant "hog" burned a full slot over the 10s quota window
    sch.note_decode_slot_ms("hog", 10_000.0)
    hog = Sequence(np.arange(8), 4, tenant_id="hog",
                   priority_class="free")
    polite = Sequence(np.arange(8), 4, tenant_id="polite",
                      priority_class="free")
    sch.submit(hog)      # hog arrived FIRST...
    clock.advance(0.1)
    sch.submit(polite)
    out = sch.schedule()
    assert out.prefills == [polite]  # ...but admits after the on-quota
    # quota does not trump class: an over-quota PAID still beats free
    with sch._lock:
        assert sch._over_quota_locked(hog)
        assert not sch._over_quota_locked(polite)


# --------------------------------------------------------------------------
# preemption-resume bit-identity across decode tiers
# --------------------------------------------------------------------------

def _tier_model(seed=0, hidden=32, layers=2):
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(seed)
    cfg = GPTConfig(vocab_size=128, hidden_size=hidden,
                    num_layers=layers, num_heads=4, max_seq_len=64)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model


@pytest.mark.parametrize("tier", ["bf16", "int8kv", "spec"])
def test_preemption_resume_bit_identical_across_tiers(tier):
    """Policy preemption rides the recompute-eviction path: a paid
    submission mid-decode evicts the free youngest, and the preempted
    free stream resumes WARM from the prefix cache and finishes
    bit-identical to an unloaded same-tier reference — on the bf16,
    int8-KV, and speculative tiers alike."""
    model = _tier_model()
    draft = None
    kw = dict(page_size=8, max_slots=2, decode_chunk=2, max_seq_len=64)
    if tier == "int8kv":
        kw["kv_precision"] = "int8"
    elif tier == "spec":
        kw["spec_tokens"] = 3
        draft = _tier_model(seed=7, hidden=16, layers=1)
    rs = np.random.RandomState(3)
    free_prompts = [rs.randint(0, 128, (n,)).astype(np.int32)
                    for n in (12, 14)]
    paid_prompt = rs.randint(0, 128, (11,)).astype(np.int32)

    ref_eng = InferenceEngine(model, EngineConfig(
        prefix_cache=False, **kw), draft_model=draft)
    refs = ref_eng.generate(free_prompts + [paid_prompt],
                            max_new_tokens=8)
    assert ref_eng.pool.used_pages == 0

    eng = InferenceEngine(model, EngineConfig(prefix_cache=True, **kw),
                          draft_model=draft)
    free_handles = [eng.submit(p, max_new_tokens=8,
                               priority_class="free")
                    for p in free_prompts]
    for _ in range(3):
        eng.step()  # both slots running, a few chunks decoded
    paid_handle = eng.submit(paid_prompt, max_new_tokens=8,
                             priority_class="paid")
    handles = free_handles + [paid_handle]
    idle = 0
    while any(not h.done.is_set() for h in handles) and idle < 2000:
        idle = idle if eng.step() else idle + 1
    for h, ref in zip(handles, refs):
        assert np.array_equal(h.result(timeout=1.0), ref), tier

    ring = eng.decisions.events()
    preempts = [e for e in ring if e.get("kind") == "evict_preempt"]
    assert preempts, f"no policy preemption happened ({tier})"
    assert all(e["victim_class"] == "free" and e["for_class"] == "paid"
               for e in preempts)
    # warm re-admission: every preempted request's resume rode the
    # prefix cache (its own prefill pages were still committed)
    victims = {e["request_id"] for e in preempts}
    readmits = [e for e in ring if e.get("kind") == "admit"
                and e.get("request_id") in victims
                and e.get("evictions", 0) > 0]
    assert readmits
    assert all(e["cache_state"] in ("hit", "partial")
               for e in readmits), readmits
    # zero page/refcount leak once the cache lets go
    eng.clear_prefix_cache()
    assert eng.pool.used_pages == 0
    assert len(eng.pool.ref_counts()) == 0


# --------------------------------------------------------------------------
# per-class SLO burn
# --------------------------------------------------------------------------

def test_slo_per_class_burn_and_objective_inheritance():
    clock = _Clock()
    t = SLOTracker(window_s=60.0, clock=clock)
    t.objective("predict", latency_target_ms=100, availability=0.99)
    t.objective("predict", latency_target_ms=50, availability=0.999,
                cls="paid")
    t.observe("predict", 40.0, ok=True, cls="paid")
    t.observe("predict", 40.0, ok=True, cls="free")
    t.observe("predict", None, ok=False, reason="error", cls="free")
    t.record_shed("predict", "queue_timeout", cls="free")
    rep = t.report(publish_gauges=False)
    classes = rep["endpoints"]["predict"]["classes"]
    # paid judged against ITS objective (tighter budget), zero burn
    assert classes["paid"]["burn_rate"] == 0.0
    assert classes["paid"]["objective"]["availability"] == 0.999
    # free INHERITS the endpoint objective; 2/3 errors over a 1% budget
    assert classes["free"]["objective"]["availability"] == 0.99
    assert classes["free"]["burn_rate"] == pytest.approx(
        (2 / 3) / 0.01, rel=1e-3)
    assert classes["free"]["errors_by_reason"][
        "shed:queue_timeout"] == 1


def test_slo_class_gauges_published():
    from paddle_tpu import observability as obs
    from paddle_tpu.observability import metrics

    obs.attach(crash_hook=False)
    try:
        metrics.reset()
        obs.attach(crash_hook=False)
        snap = metrics.snapshot()
        # the attach() schema declares the QoS keys at zero — absence
        # is the one thing dashboards can never alert on
        for c in ("paid", "free", "batch"):
            assert snap["counters"][f"qos.shed{{class={c}}}"] == 0
            assert snap["counters"][f"qos.preemptions{{class={c}}}"] == 0
            assert snap["gauges"][
                f"slo.burn_rate{{class={c},endpoint=generate}}"] == 0.0
        t = SLOTracker(window_s=60.0)
        t.objective("generate", 100, 0.999)
        t.observe("generate", 10.0, ok=True, cls="paid")
        t.report()
        snap = metrics.snapshot()
        assert "slo.burn_rate{class=paid,endpoint=generate}" \
            in snap["gauges"]
    finally:
        obs.detach()


# --------------------------------------------------------------------------
# loadgen class cohorts
# --------------------------------------------------------------------------

def _loadgen():
    spec = importlib.util.spec_from_file_location(
        "_loadgen", os.path.join(REPO, "tools", "loadgen.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_loadgen_class_cohorts_deterministic():
    lg = _loadgen()
    got = lg._assign_classes(8, {"paid": 0.25, "free": 0.5,
                                 "batch": 0.25})
    assert got == ["paid", "paid", "free", "free", "free", "free",
                   "batch", "batch"]
    assert lg._assign_classes(3, None) == [None, None, None]
    # the class is a property of the TENANT: every request a tenant
    # makes carries the same class
    wl = lg.SharedPrefixWorkload(seed=0, tenants=4,
                                 class_split={"paid": 0.5, "free": 0.5})
    seen = {}
    rng = random.Random(0)
    for _ in range(40):
        s = wl.sample(rng)
        cls = seen.setdefault(s["tenant"], s["priority_class"])
        assert s["priority_class"] == cls
    assert set(seen.values()) == {"paid", "free"}


# --------------------------------------------------------------------------
# bench row + perf-gate round trip
# --------------------------------------------------------------------------

def _pg():
    spec = importlib.util.spec_from_file_location(
        "_perf_gate", os.path.join(REPO, "tools", "perf_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


QOS_METRIC = "serving_qos_paid_p99_ratio"


def test_bench_emits_qos_ratio_metric():
    with open(os.path.join(REPO, "bench.py")) as f:
        src = f.read()
    assert f'"{QOS_METRIC}"' in src


def test_qos_ratio_update_round_trip(tmp_path):
    """--update appends the (lower-better) ratio row; a later run gates
    it: holding or improving passes, paid p99 degrading relative to the
    single-class baseline beyond tolerance fails."""
    pg = _pg()
    baseline = tmp_path / "baseline.jsonl"
    baseline.write_text("")
    row = {"metric": QOS_METRIC, "value": 0.5, "unit": "ratio",
           "lower_better": True}
    assert pg.update_baseline([row], str(baseline)) == 1
    base = pg.load_baseline(str(baseline))
    ok = [{"metric": QOS_METRIC, "value": 0.52, "unit": "ratio",
           "lower_better": True}]
    failures, _ = pg.gate(ok, base, tolerance=0.10)
    assert failures == []
    bad = [{"metric": QOS_METRIC, "value": 0.9, "unit": "ratio",
            "lower_better": True}]
    failures, report = pg.gate(bad, base, tolerance=0.10)
    assert len(failures) == 1 and QOS_METRIC in failures[0], report
    # degraded (CPU-proxy) rows neither update nor gate
    degraded = [{"metric": QOS_METRIC, "value": 5.0, "unit": "ratio",
                 "lower_better": True, "degraded": True}]
    assert pg.update_baseline(degraded, str(baseline)) == 0
    failures, report = pg.gate(degraded, base)
    assert failures == [] and any("SKIP" in ln for ln in report)


def test_chaos_check_has_qos_scenario():
    with open(os.path.join(REPO, "tools", "chaos_check.py")) as f:
        src = f.read()
    assert '"qos"' in src and "def run_qos_chaos" in src
