"""OpTest harness: golden-value + numeric-grad checking.

Role parity: `test/legacy_test/op_test.py:420` — subclass declares the op,
inputs, and a NumPy reference; `check_output` compares eager results,
`check_grad` compares tape-autograd grads against central finite differences
(`get_numeric_gradient` role, op_test.py:150). A third mode runs the op under
`jax.jit` tracing to assert eager/compiled parity (the dygraph-vs-static leg
of the reference harness).
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as P


def numeric_grad(fn, inputs, wrt_idx, out_reduce=None, delta=1e-3):
    """Central finite differences of sum(fn(*inputs)) w.r.t inputs[wrt_idx]."""
    inputs = [np.asarray(x, np.float64) for x in inputs]

    def scalar(*xs):
        out = fn(*[x.astype(np.float32) for x in xs])
        arr = out.numpy() if hasattr(out, "numpy") else np.asarray(out)
        if out_reduce is not None:
            return float(out_reduce(arr))
        return float(np.sum(arr.astype(np.float64)))

    x = inputs[wrt_idx]
    g = np.zeros_like(x)
    flat = x.reshape(-1)
    gflat = g.reshape(-1)
    for i in range(flat.size):
        old = flat[i]
        flat[i] = old + delta
        hi = scalar(*inputs)
        flat[i] = old - delta
        lo = scalar(*inputs)
        flat[i] = old
        gflat[i] = (hi - lo) / (2 * delta)
    return g


class OpTest:
    """Subclass sets:
      op          — callable taking Tensors
      ref         — numpy reference callable
      inputs      — dict name -> np.ndarray (float inputs get grad-checked)
      attrs       — extra kwargs
      atol / rtol — tolerances
    """

    op = None
    ref = None
    inputs = {}
    attrs = {}
    atol = 1e-5
    rtol = 1e-5
    grad_atol = 1e-2
    grad_rtol = 1e-2

    def _tensors(self, stop_gradient=True):
        return {k: P.to_tensor(v, stop_gradient=stop_gradient)
                for k, v in self.inputs.items()}

    def test_output(self):
        ts = self._tensors()
        out = type(self).op(*ts.values(), **self.attrs)
        expected = type(self).ref(*self.inputs.values(), **self.attrs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        exps = expected if isinstance(expected, (list, tuple)) else [expected]
        for o, e in zip(outs, exps):
            np.testing.assert_allclose(
                np.asarray(o.numpy(), np.float64),
                np.asarray(e, np.float64), atol=self.atol, rtol=self.rtol)

    def test_jit_parity(self):
        """Eager vs traced-under-jax.jit results must agree."""
        import jax

        ts = self._tensors()
        eager = type(self).op(*ts.values(), **self.attrs)

        from paddle_tpu.core import flags

        def pure(*vals):
            with flags.trace_guard():
                wrapped = [P.Tensor(v) for v in vals]
                out = type(self).op(*wrapped, **self.attrs)
            if isinstance(out, (list, tuple)):
                return [o._value for o in out]
            return out._value

        vals = [t._value for t in ts.values()]
        jitted = jax.jit(pure)(*vals)
        eag = eager if isinstance(eager, (list, tuple)) else [eager]
        jit_ = jitted if isinstance(jitted, (list, tuple)) else [jitted]
        for o, e in zip(eag, jit_):
            np.testing.assert_allclose(
                np.asarray(o.numpy(), np.float64), np.asarray(e, np.float64),
                atol=self.atol, rtol=self.rtol)

    def test_grad(self):
        float_keys = [k for k, v in self.inputs.items()
                      if np.issubdtype(np.asarray(v).dtype, np.floating)]
        if not float_keys:
            return
        ts = {k: P.to_tensor(v, stop_gradient=k not in float_keys)
              for k, v in self.inputs.items()}
        out = type(self).op(*ts.values(), **self.attrs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        loss = None
        for o in outs:
            if not o.stop_gradient:
                term = P.sum(o)
                loss = term if loss is None else loss + term
        assert loss is not None, "no differentiable output"
        loss.backward()

        def fn(*vals):
            tensors = [P.to_tensor(v) for v in vals]
            o = type(self).op(*tensors, **self.attrs)
            os_ = o if isinstance(o, (list, tuple)) else [o]
            diff = [x for x, ox in zip(os_, outs) if not ox.stop_gradient]
            acc = None
            for d in diff:
                s = P.sum(d)
                acc = s if acc is None else acc + s
            return acc

        for i, k in enumerate(self.inputs):
            if k not in float_keys:
                continue
            analytic = ts[k].grad
            assert analytic is not None, f"no grad for input {k}"
            numeric = numeric_grad(fn, list(self.inputs.values()), i)
            np.testing.assert_allclose(
                np.asarray(analytic.numpy(), np.float64), numeric,
                atol=self.grad_atol, rtol=self.grad_rtol,
                err_msg=f"grad mismatch for {k}")
