"""Distribution package: stats vs scipy, sampling moments, KL, transforms,
gradient flow through log_prob/rsample (reference test model:
test/distribution/ parameterized scipy-comparison suite)."""
import numpy as np
import pytest
import scipy.stats as st

import paddle_tpu as P
from paddle_tpu import distribution as D


def a(t):
    return np.asarray(t.numpy(), np.float64)


@pytest.fixture(autouse=True)
def _seed():
    P.seed(1234)


class TestScipyParity:
    def test_normal(self):
        d = D.Normal(1.5, 2.0)
        x = np.array([0.3, 1.5, 4.0])
        ref = st.norm(1.5, 2.0)
        np.testing.assert_allclose(a(d.log_prob(P.to_tensor(x))),
                                   ref.logpdf(x), rtol=1e-5)
        np.testing.assert_allclose(a(d.cdf(P.to_tensor(x))), ref.cdf(x),
                                   rtol=1e-5)
        np.testing.assert_allclose(float(d.entropy()), ref.entropy(),
                                   rtol=1e-5)
        np.testing.assert_allclose(
            a(d.icdf(P.to_tensor(np.array([0.1, 0.5, 0.9], np.float32)))),
            ref.ppf([0.1, 0.5, 0.9]), rtol=1e-4)

    def test_uniform(self):
        d = D.Uniform(-1.0, 3.0)
        x = np.array([-0.5, 0.0, 2.9])
        ref = st.uniform(-1.0, 4.0)
        np.testing.assert_allclose(a(d.log_prob(P.to_tensor(x))),
                                   ref.logpdf(x), rtol=1e-5)
        np.testing.assert_allclose(float(d.entropy()), ref.entropy(),
                                   rtol=1e-5)

    def test_beta(self):
        d = D.Beta(2.0, 3.0)
        x = np.array([0.1, 0.5, 0.9])
        ref = st.beta(2.0, 3.0)
        np.testing.assert_allclose(a(d.log_prob(P.to_tensor(x))),
                                   ref.logpdf(x), rtol=1e-4)
        np.testing.assert_allclose(float(d.mean), ref.mean(), rtol=1e-5)
        np.testing.assert_allclose(float(d.variance), ref.var(), rtol=1e-5)
        np.testing.assert_allclose(float(d.entropy()), ref.entropy(),
                                   rtol=1e-4)

    def test_gamma(self):
        d = D.Gamma(3.0, 2.0)
        x = np.array([0.5, 1.5, 4.0])
        ref = st.gamma(3.0, scale=0.5)
        np.testing.assert_allclose(a(d.log_prob(P.to_tensor(x))),
                                   ref.logpdf(x), rtol=1e-4)
        np.testing.assert_allclose(float(d.entropy()), ref.entropy(),
                                   rtol=1e-4)

    def test_laplace(self):
        d = D.Laplace(0.5, 2.0)
        x = np.array([-1.0, 0.5, 3.0])
        ref = st.laplace(0.5, 2.0)
        np.testing.assert_allclose(a(d.log_prob(P.to_tensor(x))),
                                   ref.logpdf(x), rtol=1e-5)
        np.testing.assert_allclose(a(d.cdf(P.to_tensor(x))), ref.cdf(x),
                                   rtol=1e-5)

    def test_gumbel(self):
        d = D.Gumbel(1.0, 2.0)
        x = np.array([0.0, 1.0, 5.0])
        ref = st.gumbel_r(1.0, 2.0)
        np.testing.assert_allclose(a(d.log_prob(P.to_tensor(x))),
                                   ref.logpdf(x), rtol=1e-5)
        np.testing.assert_allclose(float(d.mean), ref.mean(), rtol=1e-5)

    def test_cauchy(self):
        d = D.Cauchy(0.0, 1.5)
        x = np.array([-2.0, 0.0, 2.0])
        ref = st.cauchy(0.0, 1.5)
        np.testing.assert_allclose(a(d.log_prob(P.to_tensor(x))),
                                   ref.logpdf(x), rtol=1e-5)
        np.testing.assert_allclose(float(d.entropy()), ref.entropy(),
                                   rtol=1e-5)

    def test_lognormal(self):
        d = D.LogNormal(0.5, 0.8)
        x = np.array([0.5, 1.0, 3.0])
        ref = st.lognorm(0.8, scale=np.exp(0.5))
        np.testing.assert_allclose(a(d.log_prob(P.to_tensor(x))),
                                   ref.logpdf(x), rtol=1e-4)
        np.testing.assert_allclose(float(d.mean), ref.mean(), rtol=1e-5)

    def test_exponential(self):
        d = D.Exponential(2.0)
        x = np.array([0.1, 1.0, 3.0])
        ref = st.expon(scale=0.5)
        np.testing.assert_allclose(a(d.log_prob(P.to_tensor(x))),
                                   ref.logpdf(x), rtol=1e-5)

    def test_studentt(self):
        d = D.StudentT(5.0, 1.0, 2.0)
        x = np.array([-1.0, 1.0, 4.0])
        ref = st.t(5.0, 1.0, 2.0)
        np.testing.assert_allclose(a(d.log_prob(P.to_tensor(x))),
                                   ref.logpdf(x), rtol=1e-4)
        np.testing.assert_allclose(float(d.entropy()), ref.entropy(),
                                   rtol=1e-4)

    def test_poisson(self):
        d = D.Poisson(3.0)
        x = np.array([0.0, 2.0, 5.0])
        ref = st.poisson(3.0)
        np.testing.assert_allclose(a(d.log_prob(P.to_tensor(x))),
                                   ref.logpmf(x.astype(int)), rtol=1e-4)
        np.testing.assert_allclose(float(d.entropy()), ref.entropy(),
                                   rtol=1e-3)
        # large rate: the series window must scale with the rate
        np.testing.assert_allclose(float(D.Poisson(100.0).entropy()),
                                   st.poisson(100.0).entropy(), rtol=1e-3)

    def test_bernoulli(self):
        d = D.Bernoulli(0.3)
        np.testing.assert_allclose(float(d.log_prob(P.to_tensor(1.0))),
                                   np.log(0.3), rtol=1e-5)
        np.testing.assert_allclose(float(d.entropy()),
                                   st.bernoulli(0.3).entropy(), rtol=1e-5)

    def test_geometric(self):
        d = D.Geometric(0.4)
        x = np.array([0.0, 1.0, 4.0])
        # scipy geom counts trials (support 1..), ours counts failures (0..)
        ref = st.geom(0.4, loc=-1)
        np.testing.assert_allclose(a(d.log_prob(P.to_tensor(x))),
                                   ref.logpmf(x), rtol=1e-5)

    def test_binomial(self):
        d = D.Binomial(10.0, 0.3)
        x = np.array([0.0, 3.0, 10.0])
        ref = st.binom(10, 0.3)
        np.testing.assert_allclose(a(d.log_prob(P.to_tensor(x))),
                                   ref.logpmf(x.astype(int)), rtol=1e-4)
        np.testing.assert_allclose(float(d.entropy()), ref.entropy(),
                                   rtol=1e-3)

    def test_dirichlet(self):
        c = np.array([1.0, 2.0, 3.0])
        d = D.Dirichlet(c)
        x = np.array([0.2, 0.3, 0.5])
        ref = st.dirichlet(c)
        np.testing.assert_allclose(float(d.log_prob(P.to_tensor(x))),
                                   ref.logpdf(x), rtol=1e-4)
        np.testing.assert_allclose(float(d.entropy()), ref.entropy(),
                                   rtol=1e-4)

    def test_mvn(self):
        mu = np.array([1.0, -1.0])
        cov = np.array([[2.0, 0.5], [0.5, 1.0]])
        d = D.MultivariateNormal(mu, covariance_matrix=cov)
        x = np.array([0.5, 0.0])
        ref = st.multivariate_normal(mu, cov)
        np.testing.assert_allclose(float(d.log_prob(P.to_tensor(x))),
                                   ref.logpdf(x), rtol=1e-5)
        np.testing.assert_allclose(float(d.entropy()), ref.entropy(),
                                   rtol=1e-5)


class TestSampling:
    @pytest.mark.parametrize("dist,mean,std", [
        (lambda: D.Normal(2.0, 1.5), 2.0, 1.5),
        (lambda: D.Uniform(0.0, 4.0), 2.0, 4.0 / np.sqrt(12)),
        (lambda: D.Exponential(0.5), 2.0, 2.0),
        (lambda: D.Laplace(1.0, 1.0), 1.0, np.sqrt(2)),
        (lambda: D.Gamma(4.0, 2.0), 2.0, 1.0),
    ])
    def test_moments(self, dist, mean, std):
        s = a(dist().sample((20000,)))
        assert abs(s.mean() - mean) < 0.1 * max(1.0, abs(mean))
        assert abs(s.std() - std) < 0.12 * std

    def test_categorical_freqs(self):
        logits = np.log(np.array([0.2, 0.3, 0.5], np.float32))
        d = D.Categorical(logits)
        s = a(d.sample((20000,)))
        freq = np.bincount(s.astype(int), minlength=3) / len(s)
        np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.02)

    def test_multinomial_counts(self):
        d = D.Multinomial(100, np.array([0.2, 0.8], np.float32))
        s = a(d.sample((500,)))
        assert s.shape == (500, 2)
        np.testing.assert_allclose(s.sum(-1), 100.0)
        np.testing.assert_allclose(s.mean(0), [20, 80], rtol=0.1)

    def test_dirichlet_simplex(self):
        d = D.Dirichlet(np.array([2.0, 3.0, 4.0], np.float32))
        s = a(d.sample((1000,)))
        np.testing.assert_allclose(s.sum(-1), 1.0, rtol=1e-5)
        np.testing.assert_allclose(s.mean(0), np.array([2, 3, 4]) / 9.0,
                                   atol=0.03)

    def test_mvn_sample_cov(self):
        mu = np.array([0.0, 1.0])
        cov = np.array([[1.0, 0.6], [0.6, 2.0]])
        d = D.MultivariateNormal(mu, covariance_matrix=cov)
        s = a(d.sample((30000,)))
        np.testing.assert_allclose(np.cov(s.T), cov, atol=0.08)


class TestKL:
    def test_kl_normal_vs_mc(self):
        p = D.Normal(0.0, 1.0)
        q = D.Normal(1.0, 2.0)
        kl = float(D.kl_divergence(p, q))
        s = p.sample((100000,))
        mc = float((p.log_prob(s) - q.log_prob(s)).mean())
        assert abs(kl - mc) < 0.02

    def test_kl_registry_pairs(self):
        pairs = [
            (D.Beta(2.0, 3.0), D.Beta(3.0, 2.0)),
            (D.Gamma(2.0, 1.0), D.Gamma(3.0, 2.0)),
            (D.Exponential(1.0), D.Exponential(2.0)),
            (D.Laplace(0.0, 1.0), D.Laplace(1.0, 2.0)),
            (D.Bernoulli(0.3), D.Bernoulli(0.6)),
            (D.Geometric(0.3), D.Geometric(0.5)),
            (D.Dirichlet(np.array([1.0, 2.0])),
             D.Dirichlet(np.array([2.0, 1.0]))),
            (D.Categorical(np.array([0.1, 0.9], np.float32)),
             D.Categorical(np.array([0.5, 0.5], np.float32))),
        ]
        for p, q in pairs:
            kl = a(D.kl_divergence(p, q))
            assert np.all(kl >= -1e-5), type(p).__name__
            assert np.all(np.isfinite(kl)), type(p).__name__
        # KL(p, p) == 0
        p = D.Normal(np.array([0.0, 1.0]), np.array([1.0, 2.0]))
        np.testing.assert_allclose(a(D.kl_divergence(p, p)), 0.0, atol=1e-6)

    def test_kl_mvn(self):
        p = D.MultivariateNormal(np.zeros(2), covariance_matrix=np.eye(2))
        q = D.MultivariateNormal(np.ones(2),
                                 covariance_matrix=2 * np.eye(2))
        # closed form for diagonal case
        expect = 0.5 * (2 * 0.5 + 2 * 0.5 - 2 + 2 * np.log(2.0))
        np.testing.assert_allclose(float(D.kl_divergence(p, q)), expect,
                                   rtol=1e-5)


class TestTransforms:
    def test_exp_roundtrip(self):
        t = D.ExpTransform()
        x = P.to_tensor(np.array([0.1, 1.0, -0.5], np.float32))
        y = t.forward(x)
        np.testing.assert_allclose(a(t.inverse(y)), a(x), rtol=1e-5)
        np.testing.assert_allclose(a(t.forward_log_det_jacobian(x)), a(x))

    def test_affine_sigmoid_tanh(self):
        x = P.to_tensor(np.array([-0.9, 0.0, 0.9], np.float32))
        for t in [D.AffineTransform(1.0, 2.0), D.SigmoidTransform(),
                  D.TanhTransform()]:
            y = t.forward(x)
            np.testing.assert_allclose(a(t.inverse(y)), a(x), rtol=1e-4,
                                       atol=1e-5)
            # ldj vs numeric derivative
            eps = 1e-4
            xp = P.to_tensor(a(x) + eps)
            num = (a(t.forward(xp)) - a(y)) / eps
            np.testing.assert_allclose(a(t.forward_log_det_jacobian(x)),
                                       np.log(np.abs(num)), atol=1e-2)

    def test_stickbreaking(self):
        t = D.StickBreakingTransform()
        x = P.to_tensor(np.array([0.2, -0.3, 0.5], np.float32))
        y = t.forward(x)
        assert a(y).shape == (4,)
        np.testing.assert_allclose(a(y).sum(), 1.0, rtol=1e-5)
        np.testing.assert_allclose(a(t.inverse(y)), a(x), rtol=1e-4,
                                   atol=1e-5)

    def test_chain_mixed_event_rank_ldj(self):
        # elementwise Affine inside an event-rank-1 chain: its per-element
        # ldj must be summed over the event axis, giving a scalar total
        t = D.ChainTransform([D.AffineTransform(0.0, 2.0),
                              D.StickBreakingTransform()])
        x = P.to_tensor(np.array([0.2, -0.3, 0.5], np.float32))
        ldj = t.forward_log_det_jacobian(x)
        assert ldj.shape == []
        sb = D.StickBreakingTransform()
        x2 = D.AffineTransform(0.0, 2.0).forward(x)
        expect = 3 * np.log(2.0) + float(sb.forward_log_det_jacobian(x2))
        np.testing.assert_allclose(float(ldj), expect, rtol=1e-5)

    def test_reshape_transformed_event_shape(self):
        base = D.Independent(
            D.Normal(np.zeros(6, np.float32), np.ones(6, np.float32)), 1)
        td = D.TransformedDistribution(
            base, [D.ReshapeTransform((6,), (2, 3))])
        assert td.batch_shape == ()
        assert td.event_shape == (2, 3)
        x = P.to_tensor(np.zeros((2, 3), np.float32))
        np.testing.assert_allclose(
            float(td.log_prob(x)),
            float(base.log_prob(P.to_tensor(np.zeros(6, np.float32)))),
            rtol=1e-6)

    def test_chain_and_shapes(self):
        t = D.ChainTransform([D.AffineTransform(0.0, 2.0), D.ExpTransform()])
        x = P.to_tensor(np.array([0.5], np.float32))
        y = t.forward(x)
        np.testing.assert_allclose(a(y), np.exp(2 * 0.5), rtol=1e-5)
        np.testing.assert_allclose(a(t.inverse(y)), a(x), rtol=1e-5)
        r = D.ReshapeTransform((2, 3), (6,))
        z = P.to_tensor(np.zeros((4, 2, 3), np.float32))
        assert a(r.forward(z)).shape == (4, 6)

    def test_transformed_distribution(self):
        # LogNormal == exp(Normal) via TransformedDistribution
        base = D.Normal(0.5, 0.8)
        td = D.TransformedDistribution(base, [D.ExpTransform()])
        ref = D.LogNormal(0.5, 0.8)
        x = P.to_tensor(np.array([0.5, 1.5], np.float32))
        np.testing.assert_allclose(a(td.log_prob(x)), a(ref.log_prob(x)),
                                   rtol=1e-5)
        s = a(td.sample((5000,)))
        assert abs(np.log(s).mean() - 0.5) < 0.05


class TestGradients:
    def test_logprob_grad(self):
        loc = P.to_tensor(0.5, stop_gradient=False)
        scale = P.to_tensor(2.0, stop_gradient=False)
        d = D.Normal(loc, scale)
        lp = d.log_prob(P.to_tensor(1.5))
        lp.backward()
        # d/dloc logN = (x-loc)/scale^2
        np.testing.assert_allclose(float(loc.grad), 1.0 / 4.0, rtol=1e-5)

    def test_rsample_pathwise_grad(self):
        loc = P.to_tensor(0.0, stop_gradient=False)
        d = D.Normal(loc, 1.0)
        s = d.rsample((256,))
        assert not s.stop_gradient
        s.mean().backward()
        np.testing.assert_allclose(float(loc.grad), 1.0, rtol=1e-5)

    def test_independent(self):
        base = D.Normal(np.zeros((3, 4), np.float32),
                        np.ones((3, 4), np.float32))
        ind = D.Independent(base, 1)
        assert ind.batch_shape == (3,)
        assert ind.event_shape == (4,)
        x = P.to_tensor(np.zeros((3, 4), np.float32))
        np.testing.assert_allclose(a(ind.log_prob(x)),
                                   a(base.log_prob(x)).sum(-1), rtol=1e-6)
