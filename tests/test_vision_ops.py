"""vision.ops (nms/roi_align/deform_conv) + Swin.

Parity model: reference `test/legacy_test/test_nms_op.py`,
`test_roi_align_op.py`, `test_deform_conv2d.py` — NumPy references.
"""
import numpy as np

import paddle_tpu as P
from paddle_tpu.vision import ops as VO
from paddle_tpu.vision import models as V


def test_box_iou_and_nms():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30],
                      [0, 0, 5, 5]], np.float32)
    scores = np.array([0.9, 0.8, 0.7, 0.6], np.float32)
    iou = VO.box_iou(P.to_tensor(boxes), P.to_tensor(boxes)).numpy()
    assert abs(iou[0, 0] - 1.0) < 1e-6 and iou[0, 2] == 0.0
    kept = VO.nms(P.to_tensor(boxes), 0.5, P.to_tensor(scores)).numpy()
    # box1 suppressed by box0 (IoU≈0.68); box2 and box3 survive
    assert kept.tolist() == [0, 2, 3]


def test_nms_class_aware():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11]], np.float32)
    scores = np.array([0.9, 0.8], np.float32)
    cats = np.array([0, 1], np.int32)
    kept = VO.nms(P.to_tensor(boxes), 0.5, P.to_tensor(scores),
                  category_idxs=P.to_tensor(cats),
                  categories=[0, 1]).numpy()
    assert sorted(kept.tolist()) == [0, 1]  # different classes both live


def test_roi_align_identity():
    # a ROI covering exactly one aligned cell grid reproduces avg pooling
    H = W = 4
    feat = np.arange(H * W, dtype=np.float32).reshape(1, 1, H, W)
    boxes = np.array([[0, 0, 4, 4]], np.float32)
    out = VO.roi_align(P.to_tensor(feat), P.to_tensor(boxes),
                       P.to_tensor(np.array([1])), output_size=2,
                       spatial_scale=1.0, sampling_ratio=2,
                       aligned=True).numpy()
    assert out.shape == (1, 1, 2, 2)
    # aligned=True samples land exactly on the pixel centers of each 2x2
    # cell, so the result equals 2x2 average pooling
    ref = feat.reshape(1, 1, 2, 2, 2, 2).mean(axis=(3, 5))[0, 0]
    np.testing.assert_allclose(out[0, 0], ref, rtol=1e-5)


def test_deform_conv2d_zero_offset_matches_conv():
    import jax

    rng = np.random.RandomState(0)
    x = rng.rand(1, 4, 6, 6).astype(np.float32)
    w = rng.rand(8, 4, 3, 3).astype(np.float32)
    off = np.zeros((1, 2 * 9, 6, 6), np.float32)
    out = VO.deform_conv2d(P.to_tensor(x), P.to_tensor(off), P.to_tensor(w),
                           padding=1).numpy()
    ref = jax.lax.conv_general_dilated(
        x, w, (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    np.testing.assert_allclose(out, np.asarray(ref), rtol=1e-4, atol=1e-4)


def test_deform_conv2d_mask_halves_output():
    rng = np.random.RandomState(1)
    x = rng.rand(1, 2, 4, 4).astype(np.float32)
    w = rng.rand(2, 2, 3, 3).astype(np.float32)
    off = np.zeros((1, 18, 4, 4), np.float32)
    full = VO.deform_conv2d(P.to_tensor(x), P.to_tensor(off),
                            P.to_tensor(w), padding=1).numpy()
    half_mask = np.full((1, 9, 4, 4), 0.5, np.float32)
    half = VO.deform_conv2d(P.to_tensor(x), P.to_tensor(off),
                            P.to_tensor(w), padding=1,
                            mask=P.to_tensor(half_mask)).numpy()
    np.testing.assert_allclose(half, full * 0.5, rtol=1e-5)


def test_swin_forward_and_grads():
    m = V.SwinTransformer(img_size=32, patch_size=4, embed_dim=24,
                          depths=(2, 2), num_heads=(2, 4), window_size=4,
                          num_classes=5)
    x = P.to_tensor(np.random.RandomState(2).rand(2, 3, 32, 32)
                    .astype(np.float32))
    out = m(x)
    assert out.shape == [2, 5]
    P.mean(P.square(out)).backward()
    wa = [l for l in m.sublayers()
          if type(l).__name__ == "WindowAttention"][0]
    assert wa.rel_bias.grad is not None
    # shifted blocks exist (every second block in each stage)
    shifts = [b.shift for b in m.sublayers()
              if type(b).__name__ == "SwinBlock"]
    assert any(s > 0 for s in shifts)


def test_swin_jit_parity():
    m = V.swin_t(img_size=32, patch_size=4, window_size=4, num_classes=4)
    m.eval()
    x = P.to_tensor(np.random.RandomState(3).rand(1, 3, 32, 32)
                    .astype(np.float32))
    e = m(x)
    j = P.jit.to_static(m)(x)
    np.testing.assert_allclose(e.numpy(), j.numpy(), rtol=2e-5, atol=1e-5)
