"""vision.ops (nms/roi_align/deform_conv) + Swin.

Parity model: reference `test/legacy_test/test_nms_op.py`,
`test_roi_align_op.py`, `test_deform_conv2d.py` — NumPy references.
"""
import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu.vision import ops as VO
from paddle_tpu.vision import models as V


def test_box_iou_and_nms():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30],
                      [0, 0, 5, 5]], np.float32)
    scores = np.array([0.9, 0.8, 0.7, 0.6], np.float32)
    iou = VO.box_iou(P.to_tensor(boxes), P.to_tensor(boxes)).numpy()
    assert abs(iou[0, 0] - 1.0) < 1e-6 and iou[0, 2] == 0.0
    kept = VO.nms(P.to_tensor(boxes), 0.5, P.to_tensor(scores)).numpy()
    # box1 suppressed by box0 (IoU≈0.68); box2 and box3 survive
    assert kept.tolist() == [0, 2, 3]


def test_nms_class_aware():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11]], np.float32)
    scores = np.array([0.9, 0.8], np.float32)
    cats = np.array([0, 1], np.int32)
    kept = VO.nms(P.to_tensor(boxes), 0.5, P.to_tensor(scores),
                  category_idxs=P.to_tensor(cats),
                  categories=[0, 1]).numpy()
    assert sorted(kept.tolist()) == [0, 1]  # different classes both live


def test_roi_align_identity():
    # a ROI covering exactly one aligned cell grid reproduces avg pooling
    H = W = 4
    feat = np.arange(H * W, dtype=np.float32).reshape(1, 1, H, W)
    boxes = np.array([[0, 0, 4, 4]], np.float32)
    out = VO.roi_align(P.to_tensor(feat), P.to_tensor(boxes),
                       P.to_tensor(np.array([1])), output_size=2,
                       spatial_scale=1.0, sampling_ratio=2,
                       aligned=True).numpy()
    assert out.shape == (1, 1, 2, 2)
    # aligned=True samples land exactly on the pixel centers of each 2x2
    # cell, so the result equals 2x2 average pooling
    ref = feat.reshape(1, 1, 2, 2, 2, 2).mean(axis=(3, 5))[0, 0]
    np.testing.assert_allclose(out[0, 0], ref, rtol=1e-5)


def test_deform_conv2d_zero_offset_matches_conv():
    import jax

    rng = np.random.RandomState(0)
    x = rng.rand(1, 4, 6, 6).astype(np.float32)
    w = rng.rand(8, 4, 3, 3).astype(np.float32)
    off = np.zeros((1, 2 * 9, 6, 6), np.float32)
    out = VO.deform_conv2d(P.to_tensor(x), P.to_tensor(off), P.to_tensor(w),
                           padding=1).numpy()
    ref = jax.lax.conv_general_dilated(
        x, w, (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    np.testing.assert_allclose(out, np.asarray(ref), rtol=1e-4, atol=1e-4)


def test_deform_conv2d_mask_halves_output():
    rng = np.random.RandomState(1)
    x = rng.rand(1, 2, 4, 4).astype(np.float32)
    w = rng.rand(2, 2, 3, 3).astype(np.float32)
    off = np.zeros((1, 18, 4, 4), np.float32)
    full = VO.deform_conv2d(P.to_tensor(x), P.to_tensor(off),
                            P.to_tensor(w), padding=1).numpy()
    half_mask = np.full((1, 9, 4, 4), 0.5, np.float32)
    half = VO.deform_conv2d(P.to_tensor(x), P.to_tensor(off),
                            P.to_tensor(w), padding=1,
                            mask=P.to_tensor(half_mask)).numpy()
    np.testing.assert_allclose(half, full * 0.5, rtol=1e-5)


@pytest.mark.slow
def test_swin_forward_and_grads():
    m = V.SwinTransformer(img_size=32, patch_size=4, embed_dim=24,
                          depths=(2, 2), num_heads=(2, 4), window_size=4,
                          num_classes=5)
    x = P.to_tensor(np.random.RandomState(2).rand(2, 3, 32, 32)
                    .astype(np.float32))
    out = m(x)
    assert out.shape == [2, 5]
    P.mean(P.square(out)).backward()
    wa = [l for l in m.sublayers()
          if type(l).__name__ == "WindowAttention"][0]
    assert wa.rel_bias.grad is not None
    # shifted blocks exist (every second block in each stage)
    shifts = [b.shift for b in m.sublayers()
              if type(b).__name__ == "SwinBlock"]
    assert any(s > 0 for s in shifts)


@pytest.mark.slow
def test_swin_jit_parity():
    m = V.swin_t(img_size=32, patch_size=4, window_size=4, num_classes=4)
    m.eval()
    x = P.to_tensor(np.random.RandomState(3).rand(1, 3, 32, 32)
                    .astype(np.float32))
    e = m(x)
    j = P.jit.to_static(m)(x)
    np.testing.assert_allclose(e.numpy(), j.numpy(), rtol=2e-5, atol=1e-5)


def test_vision_surface_and_new_transforms(tmp_path):
    import ast
    import os

    import paddle_tpu.vision.transforms as T
    from paddle_tpu.vision import ops as V

    ref = "/root/reference/python/paddle/vision/transforms/__init__.py"
    if os.path.exists(ref):
        names = []
        for node in ast.walk(ast.parse(open(ref).read())):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "__all__":
                        names = [e.value for e in node.value.elts
                                 if isinstance(e, ast.Constant)]
        missing = [n for n in names if not hasattr(T, n)]
        assert not missing, f"transforms missing: {missing}"

    rs = np.random.RandomState(0)
    img = (rs.rand(8, 10, 3) * 255).astype(np.uint8)
    # crop/center_crop/erase round-trip basics
    np.testing.assert_array_equal(T.crop(img, 1, 2, 4, 5),
                                  img[1:5, 2:7])
    assert T.center_crop(img, 6).shape == (6, 6, 3)
    er = T.erase(img, 2, 3, 2, 2, 7)
    assert (er[2:4, 3:5] == 7).all()
    # color ops stay in range and keep dtype
    for f in (lambda i: T.adjust_brightness(i, 1.5),
              lambda i: T.adjust_contrast(i, 0.5),
              lambda i: T.adjust_saturation(i, 2.0),
              lambda i: T.adjust_hue(i, 0.2)):
        out = f(img)
        assert out.dtype == np.uint8 and out.shape == img.shape
    # identity affine == original; rotate 360 ~ original interior
    same = T.affine(img, angle=0.0)
    np.testing.assert_array_equal(same, img)
    rot = T.rotate(img.astype(np.float32), 360.0,
                   interpolation="bilinear")
    np.testing.assert_allclose(rot[2:-2, 2:-2], img[2:-2, 2:-2], atol=2.0)
    # perspective identity corners
    corners = [(0, 0), (9, 0), (9, 7), (0, 7)]
    same = T.perspective(img, corners, corners)
    np.testing.assert_array_equal(same, img)
    # transform classes execute
    for t in (T.ColorJitter(0.2, 0.2, 0.2, 0.1), T.Grayscale(3),
              T.RandomResizedCrop(6), T.RandomRotation(10),
              T.RandomAffine(10, translate=(0.1, 0.1)),
              T.RandomPerspective(prob=1.0), T.RandomErasing(prob=1.0)):
        out = t(img)
        assert out is not None

    # read_file + decode_jpeg round-trip via PIL
    from PIL import Image

    p = str(tmp_path / "t.jpg")
    Image.fromarray(img).save(p, quality=95)
    raw = V.read_file(p)
    dec = np.asarray(V.decode_jpeg(raw, mode="rgb").numpy())
    assert dec.shape == (3, 8, 10)

    # RoIPool layer forward
    x = P.to_tensor(rs.rand(1, 2, 8, 8).astype(np.float32))
    boxes = P.to_tensor(np.array([[0, 0, 6, 6]], np.float32))
    num = P.to_tensor(np.array([1], np.int32))
    out = V.RoIPool(2)(x, boxes, num)
    assert list(out.shape) == [1, 2, 2, 2]
