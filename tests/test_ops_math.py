"""Op kernel tests via the OpTest harness (math/reduction/linalg slice)."""
import numpy as np
import pytest

import paddle_tpu as P
from op_test import OpTest

rs = np.random.RandomState(7)


class TestAdd(OpTest):
    op = staticmethod(P.add)
    ref = staticmethod(np.add)
    inputs = {"x": rs.rand(3, 4).astype(np.float32),
              "y": rs.rand(3, 4).astype(np.float32)}


class TestAddBroadcast(OpTest):
    op = staticmethod(P.add)
    ref = staticmethod(np.add)
    inputs = {"x": rs.rand(3, 4).astype(np.float32),
              "y": rs.rand(4).astype(np.float32)}


class TestMultiply(OpTest):
    op = staticmethod(P.multiply)
    ref = staticmethod(np.multiply)
    inputs = {"x": rs.rand(5).astype(np.float32),
              "y": rs.rand(5).astype(np.float32)}


class TestDivide(OpTest):
    op = staticmethod(P.divide)
    ref = staticmethod(np.true_divide)
    inputs = {"x": rs.rand(4, 4).astype(np.float32),
              "y": (rs.rand(4, 4) + 0.5).astype(np.float32)}


class TestExp(OpTest):
    op = staticmethod(P.exp)
    ref = staticmethod(np.exp)
    inputs = {"x": rs.randn(3, 3).astype(np.float32)}


class TestLog(OpTest):
    op = staticmethod(P.log)
    ref = staticmethod(np.log)
    inputs = {"x": (rs.rand(3, 3) + 0.5).astype(np.float32)}


class TestSqrt(OpTest):
    op = staticmethod(P.sqrt)
    ref = staticmethod(np.sqrt)
    inputs = {"x": (rs.rand(3, 3) + 0.1).astype(np.float32)}


class TestTanh(OpTest):
    op = staticmethod(P.tanh)
    ref = staticmethod(np.tanh)
    inputs = {"x": rs.randn(3, 3).astype(np.float32)}


class TestSigmoid(OpTest):
    op = staticmethod(P.sigmoid)
    ref = staticmethod(lambda x: 1 / (1 + np.exp(-x)))
    inputs = {"x": rs.randn(3, 3).astype(np.float32)}


class TestPow(OpTest):
    op = staticmethod(lambda x: P.pow(x, 3.0))
    ref = staticmethod(lambda x: np.power(x, 3.0))
    inputs = {"x": (rs.rand(3, 3) + 0.5).astype(np.float32)}


class TestClip(OpTest):
    op = staticmethod(lambda x: P.clip(x, 0.2, 0.8))
    ref = staticmethod(lambda x: np.clip(x, 0.2, 0.8))
    inputs = {"x": rs.rand(4, 4).astype(np.float32)}
    grad_atol = 5e-2  # kink points


class TestMaximum(OpTest):
    op = staticmethod(P.maximum)
    ref = staticmethod(np.maximum)
    inputs = {"x": rs.randn(3, 4).astype(np.float32),
              "y": rs.randn(3, 4).astype(np.float32)}


class TestSum(OpTest):
    op = staticmethod(lambda x: P.sum(x, axis=1))
    ref = staticmethod(lambda x: np.sum(x, axis=1))
    inputs = {"x": rs.rand(3, 5).astype(np.float32)}


class TestMean(OpTest):
    op = staticmethod(lambda x: P.mean(x, axis=0, keepdim=True))
    ref = staticmethod(lambda x: np.mean(x, axis=0, keepdims=True))
    inputs = {"x": rs.rand(3, 5).astype(np.float32)}


class TestMax(OpTest):
    op = staticmethod(lambda x: P.max(x, axis=1))
    ref = staticmethod(lambda x: np.max(x, axis=1))
    inputs = {"x": rs.rand(4, 6).astype(np.float32)}


class TestProd(OpTest):
    op = staticmethod(lambda x: P.prod(x, axis=1))
    ref = staticmethod(lambda x: np.prod(x, axis=1))
    inputs = {"x": (rs.rand(3, 4) + 0.5).astype(np.float32)}


class TestStd(OpTest):
    op = staticmethod(lambda x: P.std(x))
    ref = staticmethod(lambda x: np.std(x, ddof=1))
    inputs = {"x": rs.rand(10).astype(np.float32)}


class TestLogsumexp(OpTest):
    op = staticmethod(lambda x: P.logsumexp(x, axis=1))
    ref = staticmethod(
        lambda x: np.log(np.sum(np.exp(x), axis=1)))
    inputs = {"x": rs.randn(3, 5).astype(np.float32)}


class TestCumsum(OpTest):
    op = staticmethod(lambda x: P.cumsum(x, axis=1))
    ref = staticmethod(lambda x: np.cumsum(x, axis=1))
    inputs = {"x": rs.rand(3, 4).astype(np.float32)}


class TestMatmul(OpTest):
    op = staticmethod(P.matmul)
    ref = staticmethod(np.matmul)
    inputs = {"x": rs.rand(4, 5).astype(np.float32),
              "y": rs.rand(5, 3).astype(np.float32)}


class TestMatmulTranspose(OpTest):
    op = staticmethod(lambda x, y: P.matmul(x, y, transpose_y=True))
    ref = staticmethod(lambda x, y: x @ y.T)
    inputs = {"x": rs.rand(4, 5).astype(np.float32),
              "y": rs.rand(3, 5).astype(np.float32)}


class TestBmm(OpTest):
    op = staticmethod(P.bmm)
    ref = staticmethod(np.matmul)
    inputs = {"x": rs.rand(2, 3, 4).astype(np.float32),
              "y": rs.rand(2, 4, 5).astype(np.float32)}


class TestEinsum(OpTest):
    op = staticmethod(lambda x, y: P.einsum("ij,jk->ik", x, y))
    ref = staticmethod(lambda x, y: np.einsum("ij,jk->ik", x, y))
    inputs = {"x": rs.rand(3, 4).astype(np.float32),
              "y": rs.rand(4, 2).astype(np.float32)}


class TestNorm(OpTest):
    op = staticmethod(lambda x: P.norm(x, p=2, axis=1))
    ref = staticmethod(lambda x: np.linalg.norm(x, axis=1))
    inputs = {"x": (rs.rand(3, 4) + 0.1).astype(np.float32)}


def test_argmax_argmin():
    x = P.to_tensor(rs.randn(4, 6).astype(np.float32))
    np.testing.assert_array_equal(P.argmax(x, axis=1).numpy(),
                                  np.argmax(x.numpy(), axis=1))
    np.testing.assert_array_equal(P.argmin(x, axis=0).numpy(),
                                  np.argmin(x.numpy(), axis=0))


def test_topk_sort():
    x = P.to_tensor(rs.randn(3, 8).astype(np.float32))
    vals, idxs = P.topk(x, 3, axis=1)
    ref_idx = np.argsort(-x.numpy(), axis=1)[:, :3]
    np.testing.assert_allclose(
        vals.numpy(), np.take_along_axis(x.numpy(), ref_idx, 1), rtol=1e-6)
    s = P.sort(x, axis=1, descending=True)
    np.testing.assert_allclose(s.numpy(), -np.sort(-x.numpy(), axis=1),
                               rtol=1e-6)


def test_comparison_and_logical():
    a = P.to_tensor([1.0, 2.0, 3.0])
    b = P.to_tensor([3.0, 2.0, 1.0])
    assert (a == b).numpy().tolist() == [False, True, False]
    assert (a < b).numpy().tolist() == [True, False, False]
    assert P.logical_and(a > 1, b > 1).numpy().tolist() == [False, True, False]
    assert bool(P.allclose(a, a))


def test_where_nonzero():
    x = P.to_tensor([[0.0, 1.0], [2.0, 0.0]])
    idx = P.nonzero(x)
    np.testing.assert_array_equal(idx.numpy(), [[0, 1], [1, 0]])
    w = P.where(x > 0, x, P.zeros_like(x))
    np.testing.assert_allclose(w.numpy(), [[0, 1], [2, 0]])


def test_inplace_ops():
    x = P.to_tensor([1.0, 2.0])
    x += P.to_tensor([1.0, 1.0])
    np.testing.assert_allclose(x.numpy(), [2.0, 3.0])
    x.add_(P.to_tensor([1.0, 1.0]))
    np.testing.assert_allclose(x.numpy(), [3.0, 4.0])


def test_setitem_getitem():
    x = P.zeros([3, 3])
    x[0, 0] = 5.0
    x[1] = P.ones([3])
    assert float(x[0, 0]) == 5.0
    np.testing.assert_allclose(x[1].numpy(), [1, 1, 1])
    # grad flows through setitem (rebind semantics)
    y = P.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    z = y * 2
    z[0] = 10.0
    z.sum().backward()
    np.testing.assert_allclose(y.grad.numpy(), [0.0, 2.0, 2.0])
