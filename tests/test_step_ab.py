"""The layout A/B harness itself runs in tier-1 (--smoke CPU mode) —
round 5 lost its deciding measurement to an untested harness inside a
tunnel window; this keeps the harness green between windows."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STEP_AB = os.path.join(REPO, "tools", "step_ab.py")


def _run(*argv, timeout=420):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("FLAGS_flash_layout", None)
    return subprocess.run([sys.executable, STEP_AB, *argv],
                         capture_output=True, text=True, cwd=REPO,
                         timeout=timeout, env=env)


def _rows(stdout):
    out = []
    for line in stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            out.append(json.loads(line))
    return out


def test_step_ab_gpt_smoke_emits_ab_line_and_gate_row():
    """CPU smoke of the gpt train A/B point: the chip_session-parsed
    "AB layout=..." line AND a perf_gate-compatible row (degraded off
    accelerator, so it can never gate a CPU number against an on-chip
    floor) both come out."""
    p = _run("flat", "--smoke", "--iters", "1")
    assert p.returncode == 0, p.stdout + p.stderr
    ab = [l for l in p.stdout.splitlines() if l.startswith("AB ")]
    assert ab and "layout=flat" in ab[0] and "tokens/s=" in ab[0], \
        p.stdout
    rows = _rows(p.stdout)
    assert rows, p.stdout
    r = rows[0]
    assert r["metric"] == "step_ab_gpt_flat_train_tokens_per_sec"
    assert r["unit"] == "tokens/s" and r["value"] > 0
    assert r.get("degraded") is True


@pytest.mark.slow
def test_step_ab_swin_smoke():
    """Vision variant axis: fused vs fallback — the swin smoke point
    emits an images/s gate row."""
    p = _run("fallback", "--model", "swin", "--smoke", "--iters", "1")
    assert p.returncode == 0, p.stdout + p.stderr
    rows = _rows(p.stdout)
    assert rows and rows[0]["metric"] == \
        "step_ab_swin_fallback_train_images_per_sec"
    assert rows[0]["unit"] == "images/s" and rows[0]["value"] > 0


@pytest.mark.slow
def test_step_ab_decode_point():
    p = _run("transpose", "--smoke", "--iters", "1", "--decode")
    assert p.returncode == 0, p.stdout + p.stderr
    metrics = [r["metric"] for r in _rows(p.stdout)]
    assert "step_ab_gpt_transpose_train_tokens_per_sec" in metrics
    assert "step_ab_gpt_transpose_decode_tokens_per_sec" in metrics


def test_step_ab_rejects_bad_vision_variant():
    p = _run("flat", "--model", "swin", "--smoke")
    assert p.returncode == 1
    assert "fused|fallback" in p.stderr
