"""Time-series telemetry plane + per-token latency attribution
(ISSUE 15).

Coverage map:
  * math: counter-aware reset-safe rate(), derivative sign (least
    squares), EWMA recency weighting, windowing;
  * bounded memory: the frame ring, the decision ring, and the
    timeline's token-stamp decimation are all provably capacity-bound;
  * sampler: declared-name resolution (exact + label-variant sum),
    health gauge, /debug/timeseries payload;
  * schema: attach() declares the new names at zero (`serving.itl_ms`
    empty histogram rendered by to_prometheus, `telemetry.anomalies`,
    `autoscaler.decisions{action=up_predictive}`,
    `telemetry.timeseries_samples`);
  * anomaly watchdog: fires on an injected latency cliff, stays silent
    on steady noise, honors the cooldown;
  * export/aggregation: incremental frames in TelemetryExporter dumps,
    per-process + fleet-sum series and Perfetto counter tracks in
    tools/telemetry_agg.py;
  * engine attribution (jax tier): a pressure-forced eviction plants a
    stall, and GET /debug/requests/<id> reconstructs it — the token
    gap's events name the co-scheduled cause — both inline and over a
    LIVE serving HTTP plane, with `serving.itl_ms` percentiles on
    /metrics and /debug/telemetry.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from paddle_tpu import observability as obs
from paddle_tpu.observability import metrics
from paddle_tpu.observability import timeseries as ts

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _telemetry():
    obs.attach(crash_hook=False)
    yield
    obs.detach()


class _Clock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# series math
# ---------------------------------------------------------------------------

def test_rate_is_counter_aware_across_reset():
    clk = _Clock()
    s = ts.TimeSeries(capacity=32, clock=clk)
    # 10→20→30, process restart (reset to 5), →15: deltas 10+10+5+10
    for t, v in ((0, 10), (1, 20), (2, 30), (3, 5), (4, 15)):
        clk.t = float(t)
        s.record({"c": v})
    assert s.rate("c", 10) == pytest.approx(35 / 4)
    # a naive last-first over the reset would be (15-10)/4 = 1.25 —
    # the reset-safe rate must NOT be that
    assert s.rate("c", 10) != pytest.approx((15 - 10) / 4)
    # windows with <2 samples answer None, not garbage
    assert s.rate("missing", 10) is None
    assert ts.TimeSeries(capacity=8).rate("c", 10) is None


def test_derivative_sign_and_least_squares():
    clk = _Clock()
    up, down = ts.TimeSeries(clock=clk), ts.TimeSeries(clock=clk)
    for i in range(6):
        clk.t = float(i)
        up.record({"g": 2.0 * i})
        down.record({"g": 10.0 - 3.0 * i})
    assert up.derivative("g", 10) == pytest.approx(2.0)
    assert down.derivative("g", 10) == pytest.approx(-3.0)
    # one outlier cannot own the sign (least squares, not last-first)
    clk.t = 6.0
    up.record({"g": 0.0})
    assert up.derivative("g", 3.0) < 0  # trailing window does turn
    assert up.derivative("g", 100.0) > 0  # long window holds the trend


def test_ewma_weights_recent_samples():
    clk = _Clock()
    s = ts.TimeSeries(clock=clk)
    for i in range(10):
        clk.t = float(i)
        s.record({"g": 0.0 if i < 9 else 100.0})
    e = s.ewma("g", 10.0)
    assert 0.0 < e < 100.0
    # a shorter halflife leans harder on the last sample
    assert s.ewma("g", 10.0, halflife=0.5) > e


def test_ring_and_decision_ring_memory_is_bounded():
    s = ts.TimeSeries(capacity=16, clock=_Clock())
    for i in range(1000):
        s.record({"x": i}, t=float(i))
    assert len(s) == 16
    assert [v for _, v in s.window("x", None)][0] == 984.0
    ring = ts.DecisionRing(capacity=32, clock=_Clock())
    for i in range(1000):
        ring.record("admit", request_id=f"r{i}")
    assert len(ring) == 32
    tail = ring.events()
    assert tail[0]["request_id"] == "r968"
    # window() answers only the asked interval
    clk = _Clock()
    ring2 = ts.DecisionRing(capacity=64, clock=clk)
    for i in range(10):
        clk.t = float(i)
        ring2.record("evict_recompute", request_id=f"v{i}")
    got = ring2.window(3.0, 5.0)
    assert [e["request_id"] for e in got] == ["v3", "v4", "v5"]


def test_timeline_token_stamps_decimate_and_keep_top_gaps():
    clk = _Clock()
    tl = ts.RequestTimeline("req", clock=clk, token_cap=8)
    tl.event("submitted")
    for i in range(200):
        clk.advance(0.5 if i == 120 else 0.01)  # one planted stall
        tl.token()
    d = tl.describe()
    assert d["tokens"] == 200
    assert len(d["token_stamps"]) <= 8          # bounded, provably
    assert d["token_stamps"][0]["token"] == 0   # coverage spans start
    assert d["gaps"][0]["token"] == 120         # the stall is kept EXACT
    assert d["gaps"][0]["gap_ms"] == pytest.approx(500.0)
    assert d["itl_max_ms"] == pytest.approx(500.0)
    # event list is bounded too
    for i in range(200):
        tl.event("noise", i=i)
    d2 = tl.describe()
    assert len(d2["events"]) <= ts.RequestTimeline._EVENT_CAP + 1
    assert d2["events"][-1]["kind"] == "events_truncated"


# ---------------------------------------------------------------------------
# sampler + schema
# ---------------------------------------------------------------------------

def test_sampler_resolves_names_and_publishes_health():
    # a PRIVATE registry: the process-global one accumulates counters
    # from every other test in the suite — this test is about the
    # sampler's resolution rules, not that shared state
    reg = metrics.MetricsRegistry(enabled=True)
    reg.inc("engine.tokens", 42)
    reg.set_gauge("serving.inflight", 3)
    reg.inc("serving.requests", 5, status="ok")
    reg.inc("serving.requests", 2, status="shed")
    sam = ts.TimeSeriesSampler(
        names=("engine.tokens", "serving.inflight", "serving.requests",
               "never.seen"),
        registry=reg, interval_s=0.1, capacity=64)
    vals = sam.sample()
    assert vals["engine.tokens"] == 42.0          # exact counter
    assert vals["serving.inflight"] == 3.0        # exact gauge
    assert vals["serving.requests"] == 7.0        # label-variant sum
    assert "never.seen" not in vals               # absent, not zero
    # health gauge is labeled per sampler (a router + server in one
    # process must not hide behind each other's count)
    snap = reg.snapshot()
    assert snap["gauges"][
        "telemetry.timeseries_samples{sampler=sampler}"] == 1
    assert sam.stats()["samples"] == 1
    assert sam.stats()["kinds"]["engine.tokens"] == "counter"
    assert sam.stats()["kinds"]["serving.inflight"] == "gauge"
    d = sam.describe()
    # rate only for counters, derivative only for gauges — a falling
    # gauge must never fabricate a positive reset-safe "rate"
    assert "serving.inflight" not in d["rate_30s"]
    assert "engine.tokens" not in d["derivative_30s"]
    assert d["samples"] == 1 and d["capacity"] == 64
    assert "engine.tokens" in d["series"]
    # two more samples with a moving counter → a live rate
    reg.inc("engine.tokens", 10)
    sam.sample()
    assert sam.latest("engine.tokens") == 52.0


def test_attach_declares_new_schema_names_at_zero():
    # fresh registry state: this test is about what attach() declares,
    # not what earlier tests in the process accumulated
    metrics.reset()
    obs.attach(crash_hook=False)
    snap = metrics.snapshot()
    assert snap["counters"][
        "autoscaler.decisions{action=up_predictive}"] == 0
    for kind in ("ttft", "itl"):
        assert snap["counters"][f"telemetry.anomalies{{kind={kind}}}"] \
            == 0
    for role in ("serving", "router"):
        assert snap["gauges"][
            f"telemetry.timeseries_samples{{sampler={role}}}"] == 0
    # the ITL histogram renders EMPTY — full bucket ladder at zero —
    # before any observation (declare_hist)
    h = snap["histograms"]["serving.itl_ms{endpoint=generate}"]
    assert h["count"] == 0
    prom = metrics.to_prometheus()
    assert "paddle_tpu_serving_itl_ms_bucket" in prom
    assert 'le="+Inf"} 0' in prom
    # one observation flips the same series live with the standard
    # bucket ladder and the quantile family
    metrics.observe("serving.itl_ms", 12.5, endpoint="generate")
    prom = metrics.to_prometheus()
    assert 'paddle_tpu_serving_itl_ms_quantile{endpoint="generate"' \
        in prom


# ---------------------------------------------------------------------------
# anomaly watchdog
# ---------------------------------------------------------------------------

def test_anomaly_fires_on_cliff_not_on_noise():
    metrics.reset()
    obs.attach(crash_hook=False)  # re-declare the schema post-reset
    clk = _Clock()
    det = ts.AnomalyDetector(ratio=3.0, window=8, baseline=64,
                             min_baseline=8, cooldown_s=5.0, clock=clk)
    rs = np.random.RandomState(0)
    fired = []
    for _ in range(200):                       # steady noisy 10±2 ms
        clk.advance(0.01)
        fired.append(det.observe("itl", 10.0 + rs.uniform(-2, 2)))
    assert not any(fired), "steady noise must stay silent"
    before = metrics.snapshot()["counters"][
        "telemetry.anomalies{kind=itl}"]
    assert before == 0
    for _ in range(12):                        # the cliff: 10 → 200 ms
        clk.advance(0.01)
        fired.append(det.observe("itl", 200.0))
    assert any(fired)
    snap = metrics.snapshot()["counters"]
    assert snap["telemetry.anomalies{kind=itl}"] == 1  # cooldown: ONCE
    rep = det.report()["itl"]
    assert rep["fired"] == 1 and rep["baseline_n"] >= 8
    # after the cooldown the still-degraded window may fire again
    clk.advance(10.0)
    again = [det.observe("itl", 220.0) for _ in range(4)]
    assert any(again)
    assert metrics.snapshot()["counters"][
        "telemetry.anomalies{kind=itl}"] == 2


# ---------------------------------------------------------------------------
# export + fleet aggregation
# ---------------------------------------------------------------------------

def _load_agg():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import telemetry_agg
    finally:
        sys.path.pop(0)
    return telemetry_agg


def test_exporter_ships_frames_incrementally_and_agg_merges(tmp_path):
    from paddle_tpu.observability.export import (
        TelemetryExporter, validate_telemetry_stream,
    )

    sam = ts.TimeSeriesSampler(names=("engine.tokens",), interval_s=1.0)
    prev = ts.get_default_sampler()
    ts.set_default_sampler(sam, force=True)
    try:
        exp = TelemetryExporter(outdir=str(tmp_path), run_id="t",
                                timelines=lambda: [
                                    {"request_id": "req-1",
                                     "tokens": 3}])
        metrics.inc("engine.tokens", 5)
        sam.sample()
        sam.sample()
        exp.dump_once()
        metrics.inc("engine.tokens", 7)
        sam.sample()
        exp.dump_once()
        entries = [json.loads(line) for line in
                   open(exp.path).read().splitlines()]
        assert validate_telemetry_stream(entries) == []
        # incremental: 2 frames in the first dump, 1 in the second
        assert len(entries[0]["timeseries"]["frames"]) == 2
        assert len(entries[1]["timeseries"]["frames"]) == 1
        assert entries[1]["timeseries"]["frames"][0]["values"][
            "engine.tokens"] == 12.0
        assert entries[0]["request_timelines"][0]["request_id"] \
            == "req-1"
        agg = _load_agg()
        streams = agg.load_dumps(str(tmp_path))
        roll = agg.rollup(streams)
        ident = next(iter(roll["timeseries"]["per_process"]))
        series = roll["timeseries"]["per_process"][ident][
            "engine.tokens"]
        assert series["v"] == [5.0, 5.0, 12.0]   # full series rebuilt
        assert roll["timeseries"]["fleet"]["engine.tokens"]["v"][-1] \
            == 12.0
        assert roll["request_timelines"][ident][0]["request_id"] \
            == "req-1"
        merged = agg.merge_timeline(streams)
        counters = [e for e in merged["traceEvents"]
                    if e.get("ph") == "C"]
        assert len(counters) == 3                # one per frame
        assert counters[0]["name"] == "engine.tokens"
        assert counters[0]["args"]["value"] == 5.0
    finally:
        ts.set_default_sampler(None)
        ts.set_default_sampler(prev)


def test_fleet_sum_is_a_step_function_over_processes():
    agg = _load_agg()
    per_proc = {
        "a:1": {"q": [(10.0, 2.0), (12.0, 4.0)]},
        "b:2": {"q": [(11.0, 1.0), (13.0, 5.0)]},
    }
    fleet = agg.fleet_timeseries(per_proc)["q"]
    # t=10: a=2; t=11: a=2+b=1; t=12: a=4+b=1; t=13: a=4+b=5
    assert fleet["wall"] == [10.0, 11.0, 12.0, 13.0]
    assert fleet["v"] == [2.0, 3.0, 5.0, 9.0]


# ---------------------------------------------------------------------------
# bench: the telemetry-overhead honesty row
# ---------------------------------------------------------------------------

def test_perf_gate_telemetry_overhead_round_trip(tmp_path):
    """serving_telemetry_overhead_frac is gateable as LOWER-better:
    --update registers it, an equal rerun passes, an overhead spike
    beyond the row tolerance exits 2."""
    import subprocess

    gate = os.path.join(REPO, "tools", "perf_gate.py")
    base = tmp_path / "baseline.jsonl"
    res = tmp_path / "results.json"
    row = {"metric": "serving_telemetry_overhead_frac", "value": 0.05,
           "unit": "frac", "lower_better": True, "tolerance": 1.0,
           "tokens_per_sec_on": 900.0, "tokens_per_sec_off": 950.0}

    def run(value, extra=()):
        res.write_text(json.dumps(dict(row, value=value)) + "\n")
        return subprocess.run(
            [sys.executable, gate, str(res), "--baseline", str(base),
             "--static-budget", "", *extra],
            capture_output=True, text=True)

    base.write_text(json.dumps(row) + "\n")
    assert run(0.05).returncode == 0
    assert run(0.09).returncode == 0          # inside the 100% row tol
    p = run(0.25)                             # a real telemetry tax
    assert p.returncode == 2 and "regression" in p.stderr
    # --update ratchets the ceiling DOWN after a win (lower-better)
    p = run(0.02, extra=("--update",))
    assert p.returncode == 0 and "updated" in p.stdout, p.stdout
    assert run(0.03).returncode == 0          # inside tol vs 0.02
    assert run(0.05).returncode == 2          # old value now a tax
    # degraded rows (the CPU proxy) are reported but never gated
    res.write_text(json.dumps(dict(row, value=0.9,
                                   degraded=True)) + "\n")
    p = subprocess.run(
        [sys.executable, gate, str(res), "--baseline", str(base),
         "--static-budget", ""], capture_output=True, text=True)
    assert p.returncode == 0 and "SKIP" in p.stdout


# ---------------------------------------------------------------------------
# engine attribution (jax tier): the planted stall
# ---------------------------------------------------------------------------

def _tiny_gpt():
    import paddle_tpu as P
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=64)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model


@pytest.fixture(scope="module")
def gpt_model():
    return _tiny_gpt()


def _tight_engine(model):
    """A pool sized so two long-running sequences CANNOT coexist at
    full length: the younger one must be recompute-evicted when the
    pool fills — the planted stall."""
    from paddle_tpu.inference.engine import EngineConfig, InferenceEngine

    ecfg = EngineConfig(page_size=4, max_slots=2, decode_chunk=1,
                        prefill_bucket=4, max_seq_len=64, num_pages=11,
                        prefix_cache=False)
    return InferenceEngine(model, ecfg)


def test_request_debug_reconstructs_pressure_forced_stall(gpt_model):
    eng = _tight_engine(gpt_model)
    rs = np.random.RandomState(0)
    B = rs.randint(0, 128, (8,)).astype(np.int32)   # 8+24 → 8 pages
    A = rs.randint(0, 128, (4,)).astype(np.int32)   # 4+24 → 7 pages
    hb = eng.submit(B, max_new_tokens=24, request_id="req-B")
    eng.step()                                      # B admitted first
    ha = eng.submit(A, max_new_tokens=24, request_id="req-A")
    idle = 0
    while not (hb.done.is_set() and ha.done.is_set()):
        idle = 0 if eng.step() else idle + 1
        assert idle < 2000, "engine stuck"
    dbg = eng.request_debug("req-A")
    kinds = [e["kind"] for e in dbg["events"]]
    assert "evicted" in kinds, kinds                # the stall happened
    assert kinds.count("prefill_start") == 2        # recompute resume
    assert dbg["tokens"] == 24                      # stream still exact
    top = dbg["gaps"][0]
    gap_kinds = [e["kind"] for e in top["events"]]
    # the gap NAMES its cause: the recompute eviction (with the pool
    # pressure at decision time) and the re-admission land inside it
    assert "evict_recompute" in gap_kinds, top
    evict = next(e for e in top["events"]
                 if e["kind"] == "evict_recompute")
    assert evict["request_id"] == "req-A"
    assert 0.0 < evict["pressure"] <= 1.0
    assert "pool at" in top["cause"]
    assert dbg["decision_ring_tail"]
    # unknown ids answer None, not a crash
    assert eng.request_debug("nope") is None
    # the timeline survives completion (bounded LRU)
    assert eng.request_debug("req-B")["tokens"] == 24
    assert eng.recent_timelines()
    # PADDLE_TPU_ITL_TIMELINE_CAP=0 disables stamping entirely
    os.environ["PADDLE_TPU_ITL_TIMELINE_CAP"] = "0"
    try:
        eng2 = _tight_engine(gpt_model)
        h = eng2.submit(A, max_new_tokens=2, request_id="req-off")
        while not h.done.is_set():
            eng2.step()
        assert eng2.request_debug("req-off") is None
    finally:
        os.environ.pop("PADDLE_TPU_ITL_TIMELINE_CAP", None)


def test_live_serving_stall_attribution_and_itl_plane(gpt_model):
    """The acceptance surface, end to end over HTTP: a live engine, a
    deliberately induced pressure stall, GET /debug/requests/<id>
    naming the co-scheduled cause, and serving.itl_ms percentiles on
    /metrics + /debug/telemetry + /debug/timeseries present."""
    from paddle_tpu.inference.serving import InferenceClient, InferenceServer

    eng = _tight_engine(gpt_model)
    srv = InferenceServer(engine=eng, request_timeout=60).start()
    try:
        cli = InferenceClient(srv.address, timeout=60)
        rs = np.random.RandomState(0)
        B = rs.randint(0, 128, (8,)).astype(np.int32)
        A = rs.randint(0, 128, (4,)).astype(np.int32)
        cli.generate(A, max_new_tokens=2)  # warm both prefill buckets
        cli.generate(B, max_new_tokens=2)

        results = {}
        b_started = threading.Event()

        def run(name, prompt, wait=None):
            c = InferenceClient(srv.address, timeout=60)
            on_token = (lambda t: b_started.set()) if name == "B" \
                else None
            if wait is not None:
                wait.wait(timeout=30)
            results[name] = c.generate(prompt, max_new_tokens=24,
                                       on_token=on_token)

        tb = threading.Thread(target=run, args=("B", B))
        ta = threading.Thread(target=run, args=("A", A, b_started))
        tb.start()
        ta.start()
        tb.join(timeout=120)
        ta.join(timeout=120)
        assert "A" in results and "B" in results
        rid = results["A"]["request_id"]

        def get(path):
            with urllib.request.urlopen(srv.address + path,
                                        timeout=10) as r:
                return json.loads(r.read())

        dbg = get(f"/debug/requests/{rid}")
        kinds = [e["kind"] for e in dbg["events"]]
        assert "evicted" in kinds, kinds
        top_with_cause = [g for g in dbg["gaps"] if g["events"]]
        assert top_with_cause, dbg["gaps"]
        assert any("pool at" in (g["cause"] or "")
                   for g in top_with_cause)
        # the ITL surface: histogram live on all three planes
        with urllib.request.urlopen(srv.address + "/metrics",
                                    timeout=10) as r:
            prom = r.read().decode()
        assert "paddle_tpu_serving_itl_ms_bucket" in prom
        assert 'paddle_tpu_serving_itl_ms_quantile{' \
            'endpoint="generate",quantile="0.99"}' in prom
        snap = get("/debug/telemetry")
        h = snap["metrics"]["histograms"][
            "serving.itl_ms{endpoint=generate}"]
        assert h["count"] >= 20 and "p95" in h
        assert snap["request_timelines"]
        assert "anomalies" in snap
        tsd = get("/debug/timeseries")
        assert "engine.tokens" in tsd["series"] or tsd["samples"] == 0
        # unknown request id → 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            get("/debug/requests/definitely-not-a-request")
        assert ei.value.code == 404
    finally:
        srv.shutdown()
