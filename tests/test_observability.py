"""Observability subsystem tests (ISSUE 1): metrics registry, flight
recorder, step-stats stream, profiler scheduler edge cases, and the
flash dispatch-tier / gate-reject / autotune telemetry wiring —
asserting end-to-end that the snapshot schema bench.py --telemetry
embeds carries the dispatch-tier counts, autotune hit/miss, retrace
count, and per-step wall stats the acceptance criteria name.
"""
from __future__ import annotations

import json
import os
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu import observability as obs
from paddle_tpu.observability import flight, metrics, step_stats


@pytest.fixture(autouse=True)
def _clean_registry():
    """Each test starts from a disabled, empty registry and an empty
    flight ring (the default registry is process-global)."""
    metrics.reset()
    flight.clear()
    metrics.disable()
    yield
    metrics.reset()
    flight.clear()
    metrics.disable()


def _rand(shape, dtype=jnp.float32):
    return jnp.asarray(np.random.RandomState(0).randn(*shape), dtype)


# ============================ metrics ============================

def test_counter_labels_and_snapshot():
    metrics.enable()
    metrics.inc("flash.dispatch", tier="flat")
    metrics.inc("flash.dispatch", tier="flat")
    metrics.inc("flash.dispatch", tier="kv")
    metrics.inc("plain")
    metrics.set_gauge("mem.peak_bytes_in_use", 123)
    metrics.observe("step.wall_ms", 2.0)
    metrics.observe("step.wall_ms", 4.0)
    snap = metrics.snapshot()
    assert snap["counters"]["flash.dispatch{tier=flat}"] == 2
    assert snap["counters"]["flash.dispatch{tier=kv}"] == 1
    assert snap["counters"]["plain"] == 1
    assert snap["gauges"]["mem.peak_bytes_in_use"] == 123
    h = snap["histograms"]["step.wall_ms"]
    assert h["count"] == 2 and h["mean"] == 3.0
    assert h["min"] == 2.0 and h["max"] == 4.0


def test_declare_pre_registers_zero():
    # declare works even while disabled — schema, not a hot path
    metrics.declare("autotune.hit")
    metrics.declare("flash.dispatch", tier="mh")
    snap = metrics.snapshot()
    assert snap["counters"]["autotune.hit"] == 0
    assert snap["counters"]["flash.dispatch{tier=mh}"] == 0


def test_disabled_path_is_noop_and_cheap():
    assert not metrics.enabled()
    t0 = time.perf_counter()
    for _ in range(20000):
        metrics.inc("hot.path", tier="x")
        metrics.observe("hot.hist", 1.0)
    dt = time.perf_counter() - t0
    snap = metrics.snapshot()
    assert snap["counters"] == {} and snap["histograms"] == {}
    # generous bound: 40k disabled calls in well under a second
    assert dt < 1.0, f"disabled-path overhead too high: {dt:.3f}s"


def test_thread_safety():
    metrics.enable()
    n_threads, n_inc = 8, 2000

    def worker():
        for _ in range(n_inc):
            metrics.inc("concurrent.counter")
            metrics.observe("concurrent.hist", 1.0)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = metrics.snapshot()
    assert snap["counters"]["concurrent.counter"] == n_threads * n_inc
    assert snap["histograms"]["concurrent.hist"]["count"] == \
        n_threads * n_inc


def test_prometheus_export():
    metrics.enable()
    metrics.inc("flash.dispatch", tier="flat")
    metrics.set_gauge("mem.peak_bytes_in_use", 7)
    metrics.observe("step.wall_ms", 3.5)
    text = metrics.to_prometheus()
    assert '# TYPE paddle_tpu_flash_dispatch counter' in text
    assert 'paddle_tpu_flash_dispatch{tier="flat"} 1' in text
    assert 'paddle_tpu_mem_peak_bytes_in_use 7' in text
    assert 'paddle_tpu_step_wall_ms_count 1' in text


def test_jsonl_dump(tmp_path):
    metrics.enable()
    metrics.inc("a.b", kind="x")
    path = str(tmp_path / "metrics.jsonl")
    metrics.dump_jsonl(path, extra={"run": "t"})
    lines = [json.loads(l) for l in open(path)]
    assert lines[0]["phase"] == "metrics_snapshot"
    assert lines[0]["counters"]["a.b{kind=x}"] == 1
    assert lines[0]["run"] == "t"


def test_record_event_scope_tags_metrics():
    """profiler.RecordEvent spans tag HISTOGRAMS and flight events with
    the active scope (the RecordEvent <-> telemetry integration);
    counters are never auto-tagged so their keys stay schema-stable."""
    import paddle_tpu.profiler as profiler

    metrics.enable()
    with profiler.RecordEvent("train_step"):
        metrics.observe("inside.hist", 1.0)
        metrics.inc("inside.counter")
        metrics.inc("explicit.counter", scope="train_step")
        flight.record("inside.event")
        assert metrics.current_scope() == "train_step"
    assert metrics.current_scope() is None
    snap = metrics.snapshot()
    assert snap["histograms"]["inside.hist{scope=train_step}"][
        "count"] == 1
    # counters keep their exact label set (schema stability)
    assert snap["counters"]["inside.counter"] == 1
    assert snap["counters"]["explicit.counter{scope=train_step}"] == 1
    evts = [e for e in flight.events() if e["kind"] == "inside.event"]
    assert evts and evts[0]["scope"] == "train_step"


# ========================= flight recorder =========================

def test_flight_ring_bounded_and_dump(tmp_path):
    rec = flight.FlightRecorder(capacity=8)
    for i in range(20):
        rec.record("test.event", i=i)
    evts = rec.events()
    assert len(evts) == 8
    assert [e["i"] for e in evts] == list(range(12, 20))  # newest kept
    path = str(tmp_path / "flight.jsonl")
    rec.dump(path, reason="unit")
    lines = [json.loads(l) for l in open(path)]
    assert lines[0]["kind"] == "flight.dump"
    assert lines[0]["reason"] == "unit" and lines[0]["n_events"] == 8
    assert [l["i"] for l in lines[1:]] == list(range(12, 20))


def test_flight_disabled_records_nothing():
    rec = flight.FlightRecorder()
    rec.enabled = False
    rec.record("x")
    assert rec.events() == []


# ====================== profiler make_scheduler ======================

def test_make_scheduler_repeat_expiry():
    import paddle_tpu.profiler as profiler

    sched = profiler.make_scheduler(closed=1, ready=1, record=2, repeat=2)
    S = profiler.ProfilerState
    period = 4
    # two full periods follow the closed/ready/record pattern
    for base in (0, period):
        assert sched(base + 0) == S.CLOSED
        assert sched(base + 1) == S.READY
        assert sched(base + 2) == S.RECORD
        assert sched(base + 3) == S.RECORD_AND_RETURN
    # after `repeat` periods the scheduler stays CLOSED forever
    for step in range(2 * period, 2 * period + 8):
        assert sched(step) == S.CLOSED


def test_make_scheduler_zero_period():
    """record=0 with nothing else => never anything to record: CLOSED,
    not a perpetual RECORD (and no ZeroDivisionError)."""
    import paddle_tpu.profiler as profiler

    sched = profiler.make_scheduler(record=0)
    S = profiler.ProfilerState
    for step in range(5):
        assert sched(step) == S.CLOSED


def test_make_scheduler_skip_first():
    import paddle_tpu.profiler as profiler

    sched = profiler.make_scheduler(record=1, skip_first=3)
    S = profiler.ProfilerState
    assert [sched(i) for i in range(3)] == [S.CLOSED] * 3
    assert sched(3) == S.RECORD_AND_RETURN


# ===================== flash dispatch telemetry =====================

def _flash_fa():
    from paddle_tpu.ops.pallas import flash_attention as fa

    return fa


def test_flash_dispatch_tier_counters(monkeypatch):
    """End-to-end dispatch-tier counters for representative shapes: the
    layout flag routes to flat/kv/transpose (interpret-mode kernels on
    CPU) and each dispatch increments its tier counter; the CPU
    fallback increments tier=fallback."""
    fa = _flash_fa()
    metrics.enable()
    q = _rand((1, 128, 2, 64))

    # fallback: flash unavailable on CPU
    fa.flash_attention_fwd(q, q, q, is_causal=True)
    snap = metrics.snapshot()
    assert snap["counters"]["flash.dispatch{tier=fallback}"] == 1
    assert snap["counters"][
        "flash.fallback_reason{reason=unavailable}"] == 1

    monkeypatch.setattr(fa, "flash_attention_available", lambda q_: True)
    for layout, tier in (("transpose", "transpose"), ("kv", "kv"),
                         ("flat", "flat"), ("auto", "flat")):
        monkeypatch.setenv("FLAGS_flash_layout", layout)
        fa.flash_attention_fwd(q, q, q, is_causal=True)
        snap = metrics.snapshot()
        assert snap["counters"].get(
            "flash.dispatch{tier=%s}" % tier, 0) >= 1, (layout, snap)
    assert snap["counters"]["flash.dispatch{tier=flat}"] == 2  # flat+auto


def test_flash_gate_reject_metric_and_flight(monkeypatch):
    """Satellite: gate rejects increment flash.gate_reject with the
    reason and leave shape evidence in the flight recorder."""
    fa = _flash_fa()
    metrics.enable()
    monkeypatch.setattr(fa, "flash_attention_available", lambda q_: True)
    monkeypatch.setenv("FLAGS_flash_layout", "flat")
    # d=32: lane-aligned (4*32=128) but head width not compile-proven
    q = _rand((1, 128, 4, 32))
    fa.flash_attention_fwd(q, q, q, is_causal=True)
    snap = metrics.snapshot()
    assert snap["counters"][
        "flash.gate_reject{gate=flat,reason=head_width}"] == 1
    # the reject fell back to the transpose core
    assert snap["counters"]["flash.dispatch{tier=transpose}"] == 1
    evts = [e for e in flight.events() if e["kind"] == "flash.gate_reject"]
    assert evts and evts[-1]["reason"] == "head_width"
    assert evts[-1]["q_shape"] == [1, 128, 4, 32]

    # vmem reject at tuned-size blocks (gate-only: no kernel runs)
    class _Mid:
        shape = (1, 1024, 12, 64)
        dtype = jnp.dtype(jnp.bfloat16)

    assert not fa._kv_native_ok(_Mid(), _Mid(), 1024, 1024)
    snap = metrics.snapshot()
    assert snap["counters"]["flash.gate_reject{gate=kv,reason=vmem}"] == 1


def test_autotune_cross_layout_reject(monkeypatch):
    """Satellite: a transpose-tuned cache entry is NOT silently reused
    by the kv/flat cores — the refusal counts
    autotune.cross_layout_reject."""
    fa = _flash_fa()
    from paddle_tpu.ops.pallas import autotune

    metrics.enable()
    b, sq, sk, h, d = 2, 1024, 1024, 4, 64
    base_sig = f"{b}x{sq}x{sk}x{h}x{d}|bfloat16|c1"
    devkind = jax.devices()[0].platform  # "cpu" in tests
    monkeypatch.setattr(autotune, "_cache", {
        f"{devkind}|flash_fwdbwd|{base_sig}": {"config": [512, 1024]}})
    monkeypatch.setattr(autotune, "_devkind", lambda: devkind)
    assert autotune.cached_config("flash_fwdbwd", base_sig) == (512, 1024)
    fa._tuned_blocks(b, sq, sk, h, d, jnp.bfloat16, True, layout="flat")
    snap = metrics.snapshot()
    assert snap["counters"][
        "autotune.cross_layout_reject{layout=flat}"] == 1
    # transpose signature itself does NOT count a refusal
    fa._tuned_blocks(b, sq, sk, h, d, jnp.bfloat16, True,
                     layout="transpose")
    snap = metrics.snapshot()
    assert snap["counters"][
        "autotune.cross_layout_reject{layout=flat}"] == 1


def test_autotune_hit_miss_counters(monkeypatch):
    from paddle_tpu.ops.pallas import autotune

    metrics.enable()
    monkeypatch.setattr(autotune, "_enabled", lambda: True)
    monkeypatch.setattr(autotune, "_devkind", lambda: "testdev")
    monkeypatch.setattr(autotune, "_cache",
                        {"testdev|op1|s1": {"config": [1, 2]}})
    monkeypatch.setattr(autotune, "_save", lambda: None)
    assert autotune.pick("op1", "s1", [(1, 2), (3, 4)], None, (3, 4)) \
        == (1, 2)
    snap = metrics.snapshot()
    assert snap["counters"]["autotune.hit"] == 1

    def run(cfg):
        return (lambda y: y + 1.0), jnp.zeros((8, 8), jnp.float32)

    monkeypatch.setattr(autotune, "_slope_time", lambda f, x: 1.0)
    autotune.pick("op1", "s2", [(1, 2), (3, 4)], run, (3, 4))
    snap = metrics.snapshot()
    assert snap["counters"]["autotune.miss"] == 1


# ===================== jit trace-cache telemetry =====================

def test_jit_retrace_counter():
    import paddle_tpu as P

    metrics.enable()

    @P.jit.to_static
    def f(x):
        return x * 2.0

    a = P.to_tensor(np.ones((4,), np.float32))
    f(a)  # first build: miss, but NOT a retrace
    f(a)  # hit
    snap = metrics.snapshot()
    assert snap["counters"]["jit.trace_cache.miss"] == 1
    assert snap["counters"]["jit.trace_cache.hit"] == 1
    assert "jit.retrace" not in snap["counters"]
    b = P.to_tensor(np.ones((8,), np.float32))
    f(b)  # new signature: miss AND retrace
    snap = metrics.snapshot()
    assert snap["counters"]["jit.trace_cache.miss"] == 2
    assert snap["counters"]["jit.retrace"] == 1
    evts = [e for e in flight.events() if e["kind"] == "jit.retrace"]
    assert evts and evts[-1]["fn"] == "f"


# ======================= collective telemetry =======================

def test_collective_call_counter():
    import paddle_tpu as P
    from paddle_tpu.distributed import collective, fleet, topology

    topology.reset_topology()
    fleet.init(is_collective=True)
    metrics.enable()
    t = P.to_tensor(np.ones((4,), np.float32))
    collective.all_reduce(t)
    snap = metrics.snapshot()
    key = [k for k in snap["counters"]
           if k.startswith("collective.calls") and "all_reduce" in k]
    assert key and snap["counters"][key[0]] == 1


# ========================== step stats ==========================

def test_step_timer_records_and_summary(tmp_path):
    metrics.enable()
    sink = str(tmp_path / "steps.jsonl")
    timer = step_stats.StepTimer(
        run_id="t1", tokens_per_step=1000, flops_per_step=1e9,
        peak_flops=1e12, sink=sink, read_device_memory=False)
    timer.record(2.0, compile_step=True, transfer_bytes=64)
    for _ in range(4):
        timer.record(0.01)
    s = timer.summary()
    assert s["schema"] == step_stats.SCHEMA_VERSION
    assert s["run_id"] == "t1"
    assert s["steps"] == 5 and s["records"] == 5
    assert s["compile_ms"]["count"] == 1
    assert s["compile_ms"]["total"] == pytest.approx(2000.0)
    assert s["wall_ms"]["count"] == 4
    assert s["wall_ms"]["mean"] == pytest.approx(10.0, rel=1e-3)
    assert s["tokens_per_s"] == pytest.approx(1000 / 0.01, rel=1e-3)
    assert s["mfu"] == pytest.approx(1e9 / 0.01 / 1e12, rel=1e-3)
    assert s["transfer_bytes"] == 64
    # metrics side-channel: wall histogram observed
    snap = metrics.snapshot()
    assert snap["histograms"]["step.wall_ms{run_id=t1}"]["count"] == 4
    assert snap["histograms"]["step.compile_ms{run_id=t1}"]["count"] == 1


def test_step_stats_jsonl_roundtrip(tmp_path):
    """Round-trip: StepTimer sink -> chip-log loader -> schema validate
    -> summarize (the analyze_chip_log consumption path)."""
    sink = str(tmp_path / "steps.jsonl")
    timer = step_stats.StepTimer(run_id="rt", tokens_per_step=512,
                                 sink=sink, read_device_memory=False)
    timer.record(1.5, compile_step=True)
    timer.record(0.25, n_steps=5)
    entries = [json.loads(l) for l in open(sink)]
    assert len(entries) == 2
    assert step_stats.validate_stream(entries) == []
    summ = step_stats.summarize_stream(entries)
    assert summ["rt"]["records"] == 2 and summ["rt"]["steps"] == 6
    assert summ["rt"]["compile_ms_total"] == pytest.approx(1500.0)
    assert summ["rt"]["steady_wall_ms"]["mean"] == pytest.approx(50.0)
    # the stream is chip-session-log compatible: every line has phase+t
    assert all(e["phase"] == "step_stats" and "t" in e for e in entries)


def test_step_stats_validation_catches_bad_entries():
    good = {"phase": "step_stats", "t": "2026-08-04T00:00:00",
            "run_id": "x", "step": 0, "n_steps": 1, "wall_ms": 1.0,
            "compile": False}
    assert step_stats.validate_stream([good]) == []
    bad_missing = {k: v for k, v in good.items() if k != "wall_ms"}
    bad_type = dict(good, wall_ms="fast")
    bad_neg = dict(good, wall_ms=-1.0)
    other_phase = {"phase": "bench", "whatever": 1}  # ignored
    errs = step_stats.validate_stream(
        [good, bad_missing, bad_type, bad_neg, other_phase])
    assert len(errs) == 3
    assert any("missing required key 'wall_ms'" in e for e in errs)
    assert any("has type str" in e for e in errs)
    assert any("negative wall_ms" in e for e in errs)


def test_analyze_chip_log_digests_step_stats(tmp_path):
    """tools/analyze_chip_log.py consumes interleaved chip-session +
    step-stats streams uniformly (the satellite CI/tooling item)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_acl", os.path.join(os.path.dirname(__file__), os.pardir,
                             "tools", "analyze_chip_log.py"))
    acl = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(acl)
    log = tmp_path / "log.jsonl"
    rows = [
        {"phase": "bench", "t": "t0", "metric": "m", "value": 1.0},
        {"phase": "step_stats", "t": "t1", "run_id": "r1", "step": 0,
         "n_steps": 1, "wall_ms": 100.0, "compile": True},
        {"phase": "step_stats", "t": "t2", "run_id": "r1", "step": 1,
         "n_steps": 4, "wall_ms": 10.0, "compile": False,
         "tokens_per_s": 200.0},
    ]
    log.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    entries = acl.load(str(log))
    text = acl.digest(entries)
    assert "## step_stats" in text
    assert "r1" in text and "compile_ms_total" in text
    assert "schema errors" not in text
    # a corrupt stream is called out
    rows.append({"phase": "step_stats", "t": "t3"})
    log.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    text = acl.digest(acl.load(str(log)))
    assert "schema errors" in text


# ==================== attach() snapshot schema ====================

def test_attach_snapshot_schema_end_to_end(monkeypatch):
    """The acceptance-criteria schema: after attach(), a run that
    dispatches flash attention and feeds a StepTimer yields a snapshot
    containing (at least) flash dispatch-tier counts, autotune hit/miss,
    retrace count, and per-step wall-time stats — the exact keys
    bench.py --telemetry embeds in the bench JSON."""
    fa = _flash_fa()
    reg = obs.attach(crash_hook=False)
    assert metrics.enabled()
    # drive a dispatch (CPU fallback tier) and a couple of steps
    q = _rand((1, 64, 2, 32))
    fa.flash_attention_fwd(q, q, q, is_causal=True)
    timer = obs.StepTimer(run_id="e2e", tokens_per_step=128,
                          read_device_memory=False)
    timer.record(0.5, compile_step=True)
    timer.record(0.02, n_steps=2)
    snap = reg.snapshot()
    c = snap["counters"]
    # dispatch tiers all present (pre-declared), fallback actually fired
    # ON the declared key — declared schema keys carry exactly the label
    # sets the live increments use
    for tier in ("transpose", "kv", "flat", "mh", "fallback", "biased"):
        assert "flash.dispatch{tier=%s}" % tier in c
    assert c["flash.dispatch{tier=fallback}"] >= 1
    assert c["flash.fallback_reason{reason=unavailable}"] >= 1
    # autotune + retrace + collective schema present even when cold
    for key in ("autotune.hit", "autotune.miss",
                "autotune.cross_layout_reject{layout=flat}",
                "autotune.cross_layout_reject{layout=kv}",
                "jit.retrace", "jit.trace_cache.hit",
                "jit.trace_cache.miss",
                "collective.calls{kind=all_reduce}",
                "collective.calls{kind=barrier}"):
        assert key in c, key
    # per-step wall stats
    assert snap["histograms"]["step.wall_ms{run_id=e2e}"]["count"] == 1
    summ = timer.summary()
    assert summ["wall_ms"]["mean"] == pytest.approx(10.0, rel=1e-3)
    assert summ["compile_ms"]["count"] == 1


def test_bench_telemetry_stack_importable():
    """Satellite CI gate: the bench entrypoint and the whole telemetry
    stack import under JAX_PLATFORMS=cpu (conftest pins cpu), and the
    bench knows its --telemetry flag."""
    import bench

    assert bench._TELEMETRY_FLAG == "--telemetry"
    assert callable(bench._attach_telemetry)
    import paddle_tpu.observability  # noqa: F401
    import paddle_tpu.observability.flight  # noqa: F401
    import paddle_tpu.observability.metrics  # noqa: F401
    import paddle_tpu.observability.step_stats  # noqa: F401
    from paddle_tpu.ops import pallas  # noqa: F401  # dispatch wiring


@pytest.mark.slow
def test_bench_telemetry_subprocess(tmp_path):
    """Full acceptance run: `python bench.py --force-cpu --telemetry`
    emits a headline JSON line with the metrics snapshot embedded."""
    import subprocess
    import sys as _sys

    root = os.path.join(os.path.dirname(__file__), os.pardir)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [_sys.executable, os.path.join(root, "bench.py"), "--force-cpu",
         "--telemetry"],
        capture_output=True, text=True, timeout=1200, env=env, cwd=root)
    lines = [l for l in r.stdout.splitlines() if l.strip().startswith("{")]
    assert lines, r.stderr[-2000:]
    head = json.loads(lines[-1])
    tele = head.get("telemetry")
    assert tele, head
    c = tele["metrics"]["counters"]
    assert any(k.startswith("flash.dispatch") for k in c)
    assert "autotune.hit" in c and "autotune.miss" in c
    assert "jit.retrace" in c
    assert tele["step_stats"]["wall_ms"]["count"] >= 1
