"""TPU-lowering CI gate for the Pallas kernel tier (VERDICT r2 task 2).

Every Pallas kernel is lowered FOR THE TPU PLATFORM on the CPU host via
`jax.export(..., platforms=['tpu'])`. Mosaic runs its BlockSpec/layout
checks at lowering time, so the exact class of failure that crashed the
round-2 bench on hardware (rank-1 LSE block) is caught here without a chip.
Interpreter mode is disabled through `force_tpu_lowering()`; each test
asserts the lowered module really contains the Mosaic custom call so a
silent interpreter fallback can't make the gate vacuous.

Reference parity: kernels are compiled and run on-device in CI
(test/cpp/phi/, SURVEY §4) — this is the no-hardware TPU equivalent.
"""
import functools

import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.core.export_compat import (
    get_jax_export, jax_export_available,
)
from paddle_tpu.ops.pallas import flash_attention as fa

# collection-safe on builds lacking jax.export: the whole gate skips
# with a reason instead of dying at import
pytestmark = pytest.mark.skipif(
    not jax_export_available(),
    reason="jax.export unavailable in this jax build "
           "(core.export_compat.ExportUnavailableError)")
from paddle_tpu.ops.pallas.decode_attention import decode_attention as da_fn
from paddle_tpu.ops.pallas import fused_norm as fn
from paddle_tpu.ops.pallas import rope as rp


def _lower_for_tpu(f, *args):
    """Export f for TPU from the CPU host; return StableHLO text."""
    specs = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in args]
    with fa.force_tpu_lowering():
        exported = get_jax_export().export(
            jax.jit(f), platforms=["tpu"])(*specs)
    return exported.mlir_module()


def _assert_mosaic(mlir: str):
    # a silently-interpreted kernel would produce no custom call at all
    assert "tpu_custom_call" in mlir or "mosaic" in mlir.lower(), (
        "Pallas kernel did not lower through Mosaic — interpreter fallback?")


# bench shapes (B, H=12, S=1024, D=64) + model-zoo shapes:
# GPT-125M (12h, 64d), GPT-1.3B proxy (32h, 64d), LLaMA-ish (32h, 128d)
FLASH_SHAPES = [
    (8, 1024, 12, 64),
    (16, 1024, 12, 64),
    (32, 1024, 12, 64),
    (4, 2048, 32, 64),
    (2, 2048, 32, 128),
]


@pytest.mark.parametrize("shape", FLASH_SHAPES)
@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_fwd_lowers(shape, causal):
    b, s, h, d = shape
    q = jax.ShapeDtypeStruct((b, s, h, d), jnp.bfloat16)
    f = lambda q, k, v: fa._flash_core(q, k, v, causal, 128, 128)
    mlir = _lower_for_tpu(f, q, q, q)
    _assert_mosaic(mlir)


@pytest.mark.parametrize("shape", [(8, 1024, 12, 64), (2, 2048, 32, 128)])
def test_flash_attention_bwd_lowers(shape):
    b, s, h, d = shape
    q = jax.ShapeDtypeStruct((b, s, h, d), jnp.bfloat16)

    def loss(q, k, v):
        return jnp.sum(
            fa._flash_core(q, k, v, True, 128, 128).astype(jnp.float32))

    mlir = _lower_for_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, q, q)
    _assert_mosaic(mlir)


def test_flash_attention_default_blocks_lower():
    """The untuned default pair is whatever the hardware sweep last won
    ((512,1024) since r5) and runs UNVALIDATED when autotune is off — so
    the gate must prove it lowers, fwd and bwd, at the bench shape."""
    b, s, h, d = 32, 1024, 12, 64
    bq, bk = fa._tuned_blocks(b, s, s, h, d, jnp.bfloat16, True)
    q = jax.ShapeDtypeStruct((b, s, h, d), jnp.bfloat16)

    def loss(q, k, v):
        return jnp.sum(
            fa._flash_core(q, k, v, True, bq, bk).astype(jnp.float32))

    _assert_mosaic(_lower_for_tpu(jax.grad(loss, argnums=(0, 1, 2)),
                                  q, q, q))


@pytest.mark.parametrize("kind", ["ln", "rms"])
@pytest.mark.parametrize("rows,d", [(32 * 1024, 768), (4096, 1024)])
def test_fused_norm_lowers(kind, rows, d):
    x = jnp.zeros((rows, d), jnp.bfloat16)
    w = jnp.ones((d,), jnp.bfloat16)
    b = jnp.zeros((d,), jnp.bfloat16)

    def f(x, w, b):
        return fn.fused_norm_pallas(x, w, b, None, None, eps=1e-5, kind=kind)

    mlir = _lower_for_tpu(f, x, w, b)
    _assert_mosaic(mlir)


def test_fused_norm_bwd_lowers():
    x = jnp.zeros((8192, 768), jnp.bfloat16)
    w = jnp.ones((768,), jnp.bfloat16)

    def loss(x, w):
        out = fn.fused_norm_pallas(x, w, None, None, None,
                                   eps=1e-5, kind="rms")
        return jnp.sum(out.astype(jnp.float32))

    # value_and_grad: with grad alone XLA DCEs the pallas forward (the
    # saved residuals are (x, w), not y) and the gate would test nothing
    mlir = _lower_for_tpu(jax.value_and_grad(loss, argnums=(0, 1)), x, w)
    _assert_mosaic(mlir)


@pytest.mark.parametrize("b,s,h,d", [(8, 1024, 12, 64), (2, 2048, 32, 128)])
def test_rope_lowers(b, s, h, d):
    x = jnp.zeros((b, s, h, d), jnp.bfloat16)
    cos = jnp.zeros((1, s, 1, d), jnp.float32)  # rope phase layout
    sin = jnp.zeros((1, s, 1, d), jnp.float32)
    mlir = _lower_for_tpu(rp.rope_pallas, x, cos, sin)
    _assert_mosaic(mlir)


@pytest.mark.parametrize("b,h,s,d", [(8, 12, 1024, 64), (4, 32, 2048, 128)])
def test_decode_attention_lowers(b, h, s, d):
    q = jnp.zeros((b, h, d), jnp.bfloat16)
    cache = jnp.zeros((b, h, s, d), jnp.bfloat16)
    pos = jnp.zeros((b,), jnp.int32)
    f = functools.partial(da_fn, block_k=256)
    mlir = _lower_for_tpu(f, q, cache, cache, pos)
    _assert_mosaic(mlir)


@pytest.mark.parametrize("hq,hkv", [(32, 8), (12, 12), (16, 2)])
def test_decode_attention_gqa_lowers(hq, hkv):
    """Grouped-query decode: q block [G, D] per KV head + [2,B] scalar
    prefetch (pos+start) must lower through Mosaic."""
    b, s, d = 4, 1024, 64
    q = jnp.zeros((b, hq, d), jnp.bfloat16)
    cache = jnp.zeros((b, hkv, s, d), jnp.bfloat16)
    pos = jnp.zeros((b,), jnp.int32)
    start = jnp.zeros((b,), jnp.int32)
    f = functools.partial(da_fn, block_k=256)
    mlir = _lower_for_tpu(lambda q, kc, vc, p, st: f(q, kc, vc, p, start=st),
                          q, cache, cache, pos, start)
    _assert_mosaic(mlir)


@pytest.mark.parametrize("sq,sk", [(128, 1024), (1024, 128)])
def test_flash_cross_length_causal_lowers(sq, sk):
    """Bottom-right-aligned causal with seq_q != seq_k (decode/chunked
    shapes): traced offset loop bounds must lower."""
    q = jnp.zeros((2, sq, 8, 64), jnp.bfloat16)
    k = jnp.zeros((2, sk, 8, 64), jnp.bfloat16)
    mlir = _lower_for_tpu(
        lambda q, k, v: fa._flash_core(q, k, v, True, 128, 128), q, k, k)
    _assert_mosaic(mlir)


def test_gate_catches_bad_blockspec():
    """Meta-test: the gate actually fails on a Mosaic-illegal kernel (the
    round-2 bug shape — rank-1 stats output block)."""
    from jax.experimental import pallas as pl

    def bad_kernel(x_ref, o_ref):
        o_ref[:] = jnp.sum(x_ref[:], axis=1)

    def bad(x):
        return pl.pallas_call(
            bad_kernel,
            grid=(4,),
            in_specs=[pl.BlockSpec((None, 128, 128), lambda i: (i, 0, 0))],
            out_specs=pl.BlockSpec((None, 128), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((4, 128), jnp.float32),
        )(x)

    x = jnp.zeros((4, 128, 128), jnp.float32)
    with pytest.raises(Exception):
        _lower_for_tpu(bad, x)


@pytest.mark.parametrize("shape", [(8, 1024, 12, 64), (2, 2048, 32, 128)])
def test_flash_mh_fwd_lowers(shape):
    """The multi-head-block forward reads [B,S,H,D] in place (full-H
    blocks — the equal-to-array-dim rule); the squeezed-H alternative is
    un-lowerable, so this gate is what keeps the transpose-free path
    honest."""
    b, s, h, d = shape
    q = jax.ShapeDtypeStruct((b, s, h, d), jnp.bfloat16)
    f = lambda q, k, v: fa._fwd_mh(q, k, v, True, 128, 128)[0]
    mlir = _lower_for_tpu(f, q, q, q)
    _assert_mosaic(mlir)


def test_flash_padded_vit_length_lowers():
    """The padded odd-length path (flash_attention_fwd at ViT's S=197)
    must lower: pad -> kernel with real-length masking -> slice."""
    b, s, h, d = 2, 197, 12, 64
    q = jax.ShapeDtypeStruct((b, s, h, d), jnp.bfloat16)

    def f(q, k, v):
        return fa.flash_attention_fwd(q, k, v, is_causal=False,
                                      block_q=128, block_k=128)

    mlir = _lower_for_tpu(f, q, q, q)
    _assert_mosaic(mlir)


@pytest.mark.parametrize("shape", [(8, 1024, 12, 64), (2, 2048, 32, 128)])
def test_flash_mh_bwd_lowers(shape):
    b, s, h, d = shape
    q = jax.ShapeDtypeStruct((b, s, h, d), jnp.bfloat16)

    def loss(q, k, v):
        return jnp.sum(
            fa._flash_core_mh(q, k, v, True, 128, 128).astype(jnp.float32))

    mlir = _lower_for_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, q, q)
    _assert_mosaic(mlir)


@pytest.mark.parametrize("shape", [(8, 1024, 12, 64), (2, 1024, 12, 64)])
def test_flash_flat_fwd_bwd_lowers(shape):
    """The flat-native core (unpadded [B,S,H*D] views, per-head 64-lane
    slices — round-5 kernels) must lower for both directions. NOTE: the
    local gate is necessary but not sufficient for this tier — the
    deployed server Mosaic has stricter rules, see docs/ATTENTION.md
    'The layout story'."""
    b, s, h, d = shape
    q = jax.ShapeDtypeStruct((b, s, h, d), jnp.bfloat16)
    f = lambda q, k, v: fa._flash_core_flat(q, k, v, True, 128, 128)
    mlir = _lower_for_tpu(f, q, q, q)
    _assert_mosaic(mlir)

    def loss(q, k, v):
        return jnp.sum(
            fa._flash_core_flat(q, k, v, True, 128, 128)
            .astype(jnp.float32))

    mlir = _lower_for_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, q, q)
    _assert_mosaic(mlir)


def test_flash_kv_native_fwd_bwd_lowers():
    """The kv-native core (K/V/dK/dV native layout, Pallas relayouts for
    Q/O) must lower for both directions."""
    b, s, h, d = 2, 1024, 12, 64
    q = jax.ShapeDtypeStruct((b, s, h, d), jnp.bfloat16)

    def loss(q, k, v):
        return jnp.sum(
            fa._flash_core_kv(q, k, v, True, 128, 128)
            .astype(jnp.float32))

    mlir = _lower_for_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, q, q)
    _assert_mosaic(mlir)
    n_calls = mlir.count("tpu_custom_call")
    assert n_calls >= 6, (
        f"kv core backward should contain relayout + fwd + dq + dkv "
        f"kernels (got {n_calls} custom calls)")


@pytest.mark.parametrize("shape", [(4, 2048, 32, 8, 128)])
def test_flash_gqa_lowers(shape):
    """LLaMA-2/3-class GQA (32 query / 8 KV heads): grouped index maps
    must lower for both directions."""
    b, s, hq, hkv, d = shape
    q = jax.ShapeDtypeStruct((b, s, hq, d), jnp.bfloat16)
    kv = jax.ShapeDtypeStruct((b, s, hkv, d), jnp.bfloat16)

    def loss(q, k, v):
        return jnp.sum(
            fa._flash_core(q, k, v, True, 128, 128).astype(jnp.float32))

    mlir = _lower_for_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, kv, kv)
    _assert_mosaic(mlir)


def test_varlen_attention_lowers():
    """Segment-masked packed attention (flash_attn_unpadded role) must
    lower for both directions at a real packed size."""
    from paddle_tpu.ops.pallas import varlen_attention as vla

    T, H, D = 4096, 12, 64
    cu = jnp.asarray([0, 1024, 2560, 4096], jnp.int32)
    q = jax.ShapeDtypeStruct((T, H, D), jnp.bfloat16)

    def loss(q, k, v):
        o = vla.varlen_attention(q, k, v, cu, cu, causal=True)
        return jnp.sum(o.astype(jnp.float32))

    mlir = _lower_for_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, q, q)
    _assert_mosaic(mlir)


def test_flash_biased_lowers():
    """Biased kernels (additive mask on the fused tier) must lower for
    both directions at the bench shape with a broadcast [1,H,S,S] bias."""
    b, s, h, d = 8, 1024, 12, 64
    q = jax.ShapeDtypeStruct((b, s, h, d), jnp.bfloat16)
    bias = jax.ShapeDtypeStruct((1, h, s, s), jnp.float32)

    def loss(q, k, v, bias):
        o = fa._flash_core_b(q, k, v, bias, False, 256, 512)
        return jnp.sum(o.astype(jnp.float32))

    mlir = _lower_for_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, q, q, bias)
    _assert_mosaic(mlir)
