"""Native C++ tier tests: shm ring transport + TCPStore."""
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu.core.export_compat import jax_export_available

requires_jax_export = pytest.mark.skipif(
    not jax_export_available(),
    reason="jax.export unavailable in this jax build")


def test_native_builds():
    from paddle_tpu import native

    lib = native.load()
    assert lib is not None


def test_shm_ring_roundtrip():
    from paddle_tpu.io.shm_queue import ShmQueue

    q = ShmQueue(n_slots=4, slot_size=1 << 20)
    q.put({"a": np.arange(10), "b": "hello"})
    out = q.get()
    np.testing.assert_array_equal(out["a"], np.arange(10))
    assert out["b"] == "hello"
    assert q.qsize() == 0


def test_shm_ring_cross_process():
    from paddle_tpu.io.shm_queue import ShmQueue

    q = ShmQueue(n_slots=4, slot_size=1 << 20)
    pid = os.fork()
    if pid == 0:
        try:
            wq = q.attach()
            for i in range(5):
                wq.put(("msg", i, np.full(100, i)))
            os._exit(0)
        except Exception:
            os._exit(1)
    got = [q.get() for _ in range(5)]
    _, status = os.waitpid(pid, 0)
    assert status == 0
    assert sorted(g[1] for g in got) == list(range(5))
    np.testing.assert_array_equal(got[0][2], np.full(100, got[0][1]))


def test_shm_queue_too_large():
    from paddle_tpu.io.shm_queue import ShmQueue

    q = ShmQueue(n_slots=2, slot_size=1024)
    with pytest.raises(ValueError):
        q.put(np.zeros(10000))


def test_multiprocess_dataloader():
    from paddle_tpu.io.dataloader import default_collate_fn
    from paddle_tpu.io.shm_queue import run_process_workers
    from paddle_tpu.vision.datasets import FakeData

    ds = FakeData(size=32, image_shape=(3, 8, 8))
    batches = [list(range(i, i + 8)) for i in range(0, 32, 8)]
    out = list(run_process_workers(ds, batches, default_collate_fn,
                                   num_workers=2, slot_size=4 << 20))
    assert len(out) == 4
    img, label = out[0]
    assert img.shape == [8, 3, 8, 8]
    # order preserved + deterministic content
    ref = FakeData(size=32, image_shape=(3, 8, 8))
    np.testing.assert_allclose(img.numpy()[0], ref[0][0])


def test_tcp_store():
    from paddle_tpu.distributed.store import TCPStore

    port = 18571 + os.getpid() % 4096  # parallel-safe: unique per worker
    master = TCPStore(is_master=True, port=port, world_size=2)
    client = TCPStore(is_master=False, port=port, world_size=2)

    master.set("hello", b"world")
    assert client.get("hello") == b"world"
    assert client.add("counter", 3) == 3
    assert master.add("counter", 4) == 7
    assert client.check("hello")
    assert not client.check("missing")

    # blocking get from another thread
    result = {}

    def getter():
        result["v"] = client.get("later")

    t = threading.Thread(target=getter)
    t.start()
    time.sleep(0.2)
    master.set("later", b"done")
    t.join(5)
    assert result.get("v") == b"done"

    # barrier with 2 participants
    errs = []

    def b(store):
        try:
            store.barrier("b1", world_size=2)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    t1 = threading.Thread(target=b, args=(master,))
    t2 = threading.Thread(target=b, args=(client,))
    t1.start()
    t2.start()
    t1.join(5)
    t2.join(5)
    assert not errs

    # barrier is reusable: a second round on the same key must still
    # synchronize (regression: count/go keys were single-use)
    order = []

    def b2(store, tag):
        store.barrier("b1", world_size=2)
        order.append(tag)

    t3 = threading.Thread(target=b2, args=(master, "m"))
    t3.start()
    time.sleep(0.3)
    assert not order, "barrier round 2 passed with only 1/2 arrivals"
    b2(client, "c")
    t3.join(5)
    assert sorted(order) == ["c", "m"]

    # get() on a missing key honors the timeout instead of hanging
    with pytest.raises(TimeoutError):
        client.get("never-set", timeout=0.5)


class _BrokenDataset:
    """Module-level so spawn workers can unpickle it."""

    def __len__(self):
        return 8

    def __getitem__(self, i):
        if i == 5:
            raise IndexError("poisoned sample 5")
        return np.zeros(3, np.float32)


def test_worker_error_surfaces():
    from paddle_tpu.io.dataloader import default_collate_fn
    from paddle_tpu.io.shm_queue import run_process_workers

    batches = [[0, 1], [4, 5]]
    with pytest.raises(RuntimeError, match="poisoned sample 5"):
        list(run_process_workers(_BrokenDataset(), batches,
                                 default_collate_fn,
                                 num_workers=1, slot_size=1 << 20))


SWISH_CC = r"""
#include <cstdint>
#include <cmath>

extern "C" void my_swish(const float** ins, int n_in, float* out,
                         int64_t n) {
    const float* x = ins[0];
    for (int64_t i = 0; i < n; ++i) {
        float s = 1.0f / (1.0f + std::exp(-x[i]));
        out[i] = x[i] * s;
    }
}

extern "C" void my_swish_grad(const float** ins, int n_in,
                              const float* gout, float** gins, int64_t n) {
    const float* x = ins[0];
    for (int64_t i = 0; i < n; ++i) {
        float s = 1.0f / (1.0f + std::exp(-x[i]));
        gins[0][i] = gout[i] * (s + x[i] * s * (1.0f - s));
    }
}

extern "C" void my_scaled_add(const float** ins, int n_in, float* out,
                              int64_t n) {
    for (int64_t i = 0; i < n; ++i)
        out[i] = 2.0f * ins[0][i] + 3.0f * ins[1][i];
}
"""


def test_custom_op_runtime_registration():
    """cpp_extension.load: real C++ compiled at runtime, registered as a
    paddle op — eager, autodiff, and jit legs (custom_operator.cc role)."""
    from paddle_tpu.utils import cpp_extension

    lib = cpp_extension.load(
        "my_ops", [SWISH_CC],
        functions={
            "my_swish": {"symbol": "my_swish",
                         "grad_symbol": "my_swish_grad", "n_inputs": 1},
            "my_scaled_add": {"symbol": "my_scaled_add", "n_inputs": 2},
        })
    rs = np.random.RandomState(3)
    x = rs.randn(4, 5).astype(np.float32)

    # eager value
    out = lib.my_swish(P.to_tensor(x))
    ref = x / (1 + np.exp(-x)) * 1.0  # x*sigmoid(x)
    ref = x * (1 / (1 + np.exp(-x)))
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-6)

    # autodiff (analytic C++ grad vs numeric)
    t = P.to_tensor(x, stop_gradient=False)
    lib.my_swish(t).sum().backward()
    from op_test import numeric_grad

    num = numeric_grad(lambda v: lib.my_swish(P.to_tensor(v)), [x], 0)
    np.testing.assert_allclose(t.grad.numpy(), num, rtol=2e-2, atol=2e-2)

    # jit leg: custom host op embedded in a compiled program
    f = P.jit.to_static(lambda a: lib.my_swish(a) * 2.0)
    np.testing.assert_allclose(f(P.to_tensor(x)).numpy(), ref * 2.0,
                               rtol=1e-5, atol=1e-5)

    # two-input op, no grad
    y = rs.randn(4, 5).astype(np.float32)
    out2 = lib.my_scaled_add(P.to_tensor(x), P.to_tensor(y))
    np.testing.assert_allclose(out2.numpy(), 2 * x + 3 * y, rtol=1e-5)


@requires_jax_export
def test_c_inference_api(tmp_path):
    """C inference ABI (reference capi_exp role): build libpaddle_tpu_capi,
    load it with ctypes, and run a saved model end-to-end through the raw
    C structs — the same path a C/Go deployment uses."""
    import ctypes

    import paddle_tpu.nn as nn
    from paddle_tpu import static
    from paddle_tpu.native import capi

    static.reset_default_programs()
    P.enable_static()
    try:
        x = static.data("x", [-1, 4], "float32")
        lin = nn.Linear(4, 3)
        out = lin(x)
        exe = static.Executor()
        prefix = str(tmp_path / "cmodel")
        static.save_inference_model(prefix, [x], [out], exe)

        lib = capi.load()
        h = lib.PD_PredictorCreate(prefix.encode())
        assert h > 0, lib.PD_LastError().decode()
        assert lib.PD_PredictorInputNum(h) == 1
        assert lib.PD_PredictorOutputNum(h) == 1
        buf = ctypes.create_string_buffer(64)
        n = lib.PD_PredictorInputName(h, 0, buf, 64)
        assert n > 0 and buf.value == b"x"

        xv = np.random.RandomState(0).rand(2, 4).astype(np.float32)
        td_in = capi.np_to_td(xv)
        outs = (capi.PD_TensorData * 4)()
        n_out = lib.PD_PredictorRun(h, ctypes.byref(td_in), 1, outs, 4)
        assert n_out == 1, lib.PD_LastError().decode()
        got = capi.td_to_np(outs[0])
        lib.PD_ReleaseOutputs(outs, n_out)

        (ref,) = exe.run(feed={"x": xv}, fetch_list=[out])
        np.testing.assert_allclose(got, ref, rtol=1e-5)

        # error surface: bad handle
        assert lib.PD_PredictorRun(9999, ctypes.byref(td_in), 1, outs,
                                   4) < 0
        assert b"9999" in lib.PD_LastError() or lib.PD_LastError()
        assert lib.PD_PredictorDestroy(h) == 1
        assert lib.PD_PredictorDestroy(h) == 0
    finally:
        # a mid-test failure must not leave global static mode on —
        # it silently breaks every later dygraph/SOT test in the run
        P.disable_static()
        static.reset_default_programs()
