"""Layout-parity suite (ISSUE 10): the transpose-free FLAT attention
layout is the default — these tests hold it bit-identical to the
transpose core at the kernel level AND at the real model call sites
(GPT causal MHA, LLaMA GQA+RoPE, ERNIE bidirectional + additive mask),
so the default flip can never silently change training numerics.

All kernels run through the Pallas interpreter on CPU (the fake-backend
strategy, SURVEY §4.5): every layout executes the same shared
recurrences (_online_softmax/_dq_loop/_dkv_loop) on the same block
shapes, so equality is exact — asserted with array_equal, not
allclose."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as P
from paddle_tpu.ops.pallas import flash_attention as fa


def _rand(shape, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape),
                       jnp.float32)


def _loss(core, q, k, v, causal, bq, bk):
    return core(q, k, v, causal, bq, bk).astype(jnp.float32).sum()


@pytest.mark.parametrize("hq,hkv", [(2, 2), (4, 2)])
def test_flat_vs_transpose_core_bit_identical(hq, hkv):
    """Forward AND all three gradients of the flat core are bit-equal to
    the transpose core (MHA and GQA) at shared block sizes — the
    acceptance bar for making flat the default layout."""
    B, S, D = 2, 64, 64
    q = _rand((B, S, hq, D), 0)
    k = _rand((B, S, hkv, D), 1)
    v = _rand((B, S, hkv, D), 2)
    for causal in (False, True):
        out_t = fa._flash_core(q, k, v, causal, 32, 32)
        out_f = fa._flash_core_flat(q, k, v, causal, 32, 32)
        assert np.array_equal(np.asarray(out_t), np.asarray(out_f)), \
            f"flat fwd differs from transpose (causal={causal})"
        g_t = jax.grad(lambda *a: _loss(fa._flash_core, *a, causal,
                                        32, 32),
                       argnums=(0, 1, 2))(q, k, v)
        g_f = jax.grad(lambda *a: _loss(fa._flash_core_flat, *a, causal,
                                        32, 32),
                       argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g_t, g_f):
            assert np.array_equal(np.asarray(a), np.asarray(b)), \
                f"d{name} differs between layouts (causal={causal})"


def test_default_layout_is_flat(monkeypatch):
    """With no FLAGS_flash_layout set, eligible shapes route to the
    flat core (the ISSUE-10 default flip: _DEFAULT_LAYOUT='auto'
    prefers flat wherever the static gates admit it)."""
    monkeypatch.delenv("FLAGS_flash_layout", raising=False)
    assert fa._DEFAULT_LAYOUT == "auto"
    monkeypatch.setattr(fa, "flash_attention_available", lambda q_: True)
    B, S, H, D = 2, 64, 2, 64
    q = _rand((B, S, H, D))
    called = {}
    orig = fa._flash_core_flat

    def spy(*a, **kw):
        called["flat"] = True
        return orig(*a, **kw)

    monkeypatch.setattr(fa, "_flash_core_flat", spy)
    out = fa.flash_attention_fwd(q, q, q, is_causal=True)
    assert called.get("flat"), \
        "default layout did not route an eligible shape to the flat core"
    ref = fa._ref_attention(q, q, q, None, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # ineligible head width (d % 64 != 0) still lands on transpose
    q2 = _rand((2, 64, 4, 32))
    called2 = {}
    orig_t = fa._flash_core

    def spy_t(*a, **kw):
        called2["transpose"] = True
        return orig_t(*a, **kw)

    monkeypatch.setattr(fa, "_flash_core", spy_t)
    fa.flash_attention_fwd(q2, q2, q2, is_causal=True)
    assert called2.get("transpose"), \
        "gate-rejected shape did not fall back to the transpose core"


def _llama_attention_grads(monkeypatch, layout):
    """One LLaMA attention call site (GQA + RoPE + row/col projections)
    forward + backward under the given layout; returns (out, dx, dw)."""
    import paddle_tpu.ops.pallas as _pl
    from paddle_tpu.models.llama import LlamaAttention, LlamaConfig

    monkeypatch.setenv("FLAGS_flash_layout", layout)
    monkeypatch.setattr(fa, "flash_attention_available", lambda q_: True)
    monkeypatch.setattr(_pl, "flash_attention_available",
                        lambda q_: True)
    P.seed(7)
    cfg = LlamaConfig(vocab_size=128, hidden_size=128, num_layers=1,
                      num_heads=2, num_kv_heads=1, max_seq_len=32,
                      ffn_hidden=128)
    attn = LlamaAttention(cfg)
    x = P.to_tensor(np.random.RandomState(5)
                    .randn(2, 32, 128).astype(np.float32))
    x.stop_gradient = False
    out = attn(x)
    P.sum(out).backward()
    return (out.numpy(), x.grad.numpy(),
            attn.qkv_proj.weight.grad.numpy())


def test_llama_call_site_flat_bit_identical(monkeypatch):
    """The REAL LLaMA attention call site (fused qkv split, RoPE, GQA
    with Hkv < Hq, out projection): forward, input grad, and qkv weight
    grad are bit-identical between the transpose and flat layouts."""
    out_t, dx_t, dw_t = _llama_attention_grads(monkeypatch, "transpose")
    out_f, dx_f, dw_f = _llama_attention_grads(monkeypatch, "flat")
    assert np.array_equal(out_t, out_f)
    assert np.array_equal(dx_t, dx_f)
    assert np.array_equal(dw_t, dw_f)


def _gpt_attention_grads(monkeypatch, layout):
    import paddle_tpu.ops.pallas as _pl
    from paddle_tpu.models.gpt import GPTAttention, GPTConfig

    monkeypatch.setenv("FLAGS_flash_layout", layout)
    monkeypatch.setattr(fa, "flash_attention_available", lambda q_: True)
    monkeypatch.setattr(_pl, "flash_attention_available",
                        lambda q_: True)
    P.seed(9)
    cfg = GPTConfig(vocab_size=128, hidden_size=128, num_layers=1,
                    num_heads=2, max_seq_len=32)
    attn = GPTAttention(cfg)
    x = P.to_tensor(np.random.RandomState(6)
                    .randn(2, 32, 128).astype(np.float32))
    x.stop_gradient = False
    out = attn(x)
    P.sum(out).backward()
    return (out.numpy(), x.grad.numpy(),
            attn.qkv_proj.weight.grad.numpy())


def test_gpt_call_site_flat_bit_identical(monkeypatch):
    """The REAL GPT attention call site (fused qkv unbind, causal MHA,
    out projection): forward + grads bit-identical across layouts."""
    out_t, dx_t, dw_t = _gpt_attention_grads(monkeypatch, "transpose")
    out_f, dx_f, dw_f = _gpt_attention_grads(monkeypatch, "flat")
    assert np.array_equal(out_t, out_f)
    assert np.array_equal(dx_t, dx_f)
    assert np.array_equal(dw_t, dw_f)


def _ernie_encoder_grads(monkeypatch, layout):
    """One ERNIE encoder forward + backward (bidirectional attention
    with an additive padding-mask bias — the biased, NON-causal flash
    path) under the given layout; returns (seq_out, d_word_emb)."""
    import paddle_tpu.ops.pallas as _pl
    from paddle_tpu.models.ernie import ErnieConfig, ErnieModel

    monkeypatch.setenv("FLAGS_flash_layout", layout)
    monkeypatch.setattr(fa, "flash_attention_available", lambda q_: True)
    monkeypatch.setattr(_pl, "flash_attention_available",
                        lambda q_: True)
    P.seed(11)
    cfg = ErnieConfig(vocab_size=128, hidden_size=128, num_layers=1,
                      num_heads=2, ffn_hidden=128, dropout=0.0)
    model = ErnieModel(cfg)
    rs = np.random.RandomState(3)
    ids = P.to_tensor(rs.randint(1, 128, (2, 32)), "int32")
    mask = np.ones((2, 32), np.float32)
    mask[:, 24:] = 0.0  # padded tail: the additive bias band is live
    seq, pooled = model(ids, attention_mask=P.to_tensor(mask))
    (P.sum(seq) + P.sum(pooled)).backward()
    return (seq.numpy(),
            model.embeddings.word_embeddings.weight.grad.numpy())


def test_ernie_call_site_flat_bit_identical(monkeypatch):
    """The REAL ERNIE call site (bidirectional attention + additive
    stop-gradient padding mask through the biased flash tier): forward
    and embedding grads bit-identical between layouts — the third
    attention family (after causal-MHA GPT and GQA+RoPE LLaMA) the
    default flip must not perturb."""
    out_t, demb_t = _ernie_encoder_grads(monkeypatch, "transpose")
    out_f, demb_f = _ernie_encoder_grads(monkeypatch, "flat")
    assert np.array_equal(out_t, out_f)
    assert np.array_equal(demb_t, demb_f)


def test_window_partition_reverse_roundtrip():
    """window_reverse(window_partition(x)) == x for every (H, W, ws)
    tiling — the property the fused Swin kernel's in-kernel partition
    rests on — and partition produces row-major window order."""
    from paddle_tpu.ops.pallas.window_attention import (
        window_partition, window_reverse,
    )

    rs = np.random.RandomState(0)
    for (H, W, ws, C) in ((8, 8, 4, 6), (12, 8, 4, 3), (14, 14, 7, 5),
                          (4, 4, 4, 2)):
        x = jnp.asarray(rs.randn(2, H, W, C), jnp.float32)
        wins = window_partition(x, ws)
        assert wins.shape == (2 * (H // ws) * (W // ws), ws * ws, C)
        back = window_reverse(wins, ws, H, W)
        assert np.array_equal(np.asarray(back), np.asarray(x))
        # first window is the top-left tile, row-major
        assert np.array_equal(
            np.asarray(wins[0].reshape(ws, ws, C)),
            np.asarray(x[0, :ws, :ws, :]))
