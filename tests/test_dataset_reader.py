"""Legacy paddle.dataset / paddle.reader tiers (reference
`python/paddle/dataset/`, `python/paddle/reader/decorator.py`): reader
decorators and the reader-creator dataset APIs against tiny synthetic
archives in the official formats (no network)."""
import gzip
import io
import struct
import tarfile

import numpy as np
import pytest

import paddle_tpu as P


# --------------------------- reader decorators ---------------------------

def _r(items):
    def reader():
        yield from items

    return reader


def test_reader_cache_and_firstn():
    calls = {"n": 0}

    def reader():
        calls["n"] += 1
        yield from range(5)

    c = P.reader.cache(reader)
    assert list(c()) == list(range(5))
    assert list(c()) == list(range(5))
    assert calls["n"] == 1  # second pass served from memory
    assert list(P.reader.firstn(_r(range(100)), 3)()) == [0, 1, 2]


def test_reader_cache_abandoned_pass_not_corrupted():
    """An abandoned partial first pass must not poison the cache with
    duplicated samples."""
    import itertools

    c = P.reader.cache(_r(range(5)))
    assert list(itertools.islice(c(), 3)) == [0, 1, 2]  # abandoned
    assert list(c()) == [0, 1, 2, 3, 4]
    assert list(c()) == [0, 1, 2, 3, 4]


def test_reader_map_chain_shuffle_buffered():
    assert list(P.reader.map_readers(
        lambda a, b: a + b, _r([1, 2]), _r([10, 20]))()) == [11, 22]
    assert list(P.reader.chain(_r([1, 2]), _r([3]))()) == [1, 2, 3]
    got = sorted(P.reader.shuffle(_r(range(10)), 4)())
    assert got == list(range(10))
    assert sorted(P.reader.buffered(_r(range(7)), 2)()) == list(range(7))


def test_reader_compose_alignment():
    comp = P.reader.compose(_r([1, 2]), _r([(3, 4), (5, 6)]))
    assert list(comp()) == [(1, 3, 4), (2, 5, 6)]
    bad = P.reader.compose(_r([1, 2, 3]), _r([1]))
    with pytest.raises(P.reader.ComposeNotAligned):
        list(bad())
    ok = P.reader.compose(_r([1, 2, 3]), _r([1]), check_alignment=False)
    assert list(ok()) == [(1, 1)]


def test_reader_xmap_ordered_and_unordered():
    sq = lambda x: x * x  # noqa: E731
    ordered = list(P.reader.xmap_readers(sq, _r(range(20)), 3, 4,
                                         order=True)())
    assert ordered == [i * i for i in range(20)]
    unordered = sorted(P.reader.xmap_readers(sq, _r(range(20)), 3, 4)())
    assert unordered == sorted(i * i for i in range(20))


def test_reader_xmap_mapper_error_surfaces():
    """A crashing mapper must raise in the consumer, not hang the
    pipeline (the worker forwards the exception and always emits its
    end token)."""
    def bad(x):
        if x == 5:
            raise ValueError("boom at 5")
        return x

    with pytest.raises(ValueError, match="boom at 5"):
        list(P.reader.xmap_readers(bad, _r(range(10)), 2, 4)())


def test_reader_errors_surface_not_truncate():
    """A broken stream must raise, never masquerade as a short dataset:
    buffered() and xmap_readers() forward producer/reader exceptions."""
    def bad_reader():
        yield 1
        yield 2
        raise IOError("disk gone")

    with pytest.raises(IOError, match="disk gone"):
        list(P.reader.buffered(bad_reader, 2)())
    with pytest.raises(IOError, match="disk gone"):
        list(P.reader.xmap_readers(lambda v: v, bad_reader, 2, 4)())


def test_reader_multiprocess():
    merged = P.reader.multiprocess_reader(
        [_r([1, 2, 3]), _r([4, 5])], queue_size=8)
    assert sorted(merged()) == [1, 2, 3, 4, 5]


def test_common_split_and_cluster_reader(tmp_path):
    from paddle_tpu.dataset import common

    n = common.split(_r(list(range(10))), 4,
                     suffix=str(tmp_path / "part-%05d.pickle"))
    assert n >= 2
    shard0 = common.cluster_files_reader(
        str(tmp_path / "part-*.pickle"), 2, 0)
    shard1 = common.cluster_files_reader(
        str(tmp_path / "part-*.pickle"), 2, 1)
    assert sorted(list(shard0()) + list(shard1())) == list(range(10))


def test_download_is_zero_egress(tmp_path, monkeypatch):
    from paddle_tpu.dataset import common

    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path))
    with pytest.raises(RuntimeError, match="no network egress"):
        common.download("http://example.com/foo.tgz", "foo")
    d = tmp_path / "foo"
    d.mkdir()
    (d / "foo.tgz").write_bytes(b"hello")
    assert common.download("http://example.com/foo.tgz", "foo") == \
        str(d / "foo.tgz")
    assert common.md5file(str(d / "foo.tgz")) == \
        __import__("hashlib").md5(b"hello").hexdigest()


# --------------------------- dataset modules ---------------------------

def _add_bytes(tf, name, data):
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tf.addfile(info, io.BytesIO(data))


def test_dataset_imdb(tmp_path):
    from paddle_tpu.dataset import imdb

    p = tmp_path / "aclImdb_v1.tar.gz"
    docs = {
        "aclImdb/train/pos/0.txt": b"good great good film",
        "aclImdb/train/neg/0.txt": b"bad awful bad film",
        "aclImdb/test/pos/0.txt": b"great good",
        "aclImdb/test/neg/0.txt": b"awful bad",
    }
    with tarfile.open(p, "w:gz") as tf:
        for name, data in docs.items():
            _add_bytes(tf, name, data)
    wd = imdb.word_dict(data_file=str(p), cutoff=1)
    assert b"good" in wd and "<unk>" in wd
    samples = list(imdb.train(wd, data_file=str(p))())
    assert len(samples) == 2
    labels = sorted(lab for _, lab in samples)
    assert labels == [0, 1]
    assert all(isinstance(ids, list) for ids, _ in samples)


def test_dataset_imikolov(tmp_path):
    from paddle_tpu.dataset import imikolov

    p = tmp_path / "simple-examples.tgz"
    with tarfile.open(p, "w:gz") as tf:
        _add_bytes(tf, "./simple-examples/data/ptb.train.txt",
                   b"the cat sat\nthe dog sat\n")
        _add_bytes(tf, "./simple-examples/data/ptb.valid.txt",
                   b"the cat ran\n")
    wd = imikolov.build_dict(min_word_freq=1, data_file=str(p))
    assert "the" in wd and "<unk>" in wd
    grams = list(imikolov.train(wd, 2, data_file=str(p))())
    assert grams and all(len(g) == 2 for g in grams)
    pairs = list(imikolov.test(wd, -1, imikolov.DataType.SEQ,
                               data_file=str(p))())
    src, trg = pairs[0]
    assert src[0] == wd["<s>"] and trg[-1] == wd["<e>"]


def test_dataset_uci_housing(tmp_path):
    from paddle_tpu.dataset import uci_housing

    rows = np.arange(10 * 14, dtype=np.float64).reshape(10, 14)
    p = tmp_path / "housing.data"
    with open(p, "w") as f:
        for row in rows:
            f.write(" ".join(str(v) for v in row) + "\n")
    uci_housing.UCI_TRAIN_DATA = uci_housing.UCI_TEST_DATA = None
    train = list(uci_housing.train(data_file=str(p))())
    test = list(uci_housing.test(data_file=str(p))())
    assert len(train) == 8 and len(test) == 2
    x, y = train[0]
    assert x.shape == (13,) and y.shape == (1,)
    uci_housing.UCI_TRAIN_DATA = uci_housing.UCI_TEST_DATA = None


def test_dataset_mnist(tmp_path):
    from paddle_tpu.dataset import mnist

    def idx_images(path, n):
        with gzip.open(path, "wb") as f:
            f.write(struct.pack(">IIII", 2051, n, 28, 28))
            f.write(np.full(n * 28 * 28, 128, np.uint8).tobytes())

    def idx_labels(path, n):
        with gzip.open(path, "wb") as f:
            f.write(struct.pack(">II", 2049, n))
            f.write(np.arange(n, dtype=np.uint8).tobytes())

    img, lab = tmp_path / "im.gz", tmp_path / "lb.gz"
    idx_images(str(img), 3)
    idx_labels(str(lab), 3)
    samples = list(mnist.train(image_path=str(img),
                               label_path=str(lab))())
    assert len(samples) == 3
    x, y = samples[0]
    assert x.shape == (784,) and x.dtype == np.float32
    assert -1.0 <= x.min() and x.max() <= 1.0
    assert [s[1] for s in samples] == [0, 1, 2]


def test_dataset_cifar_real_archives(tmp_path):
    """CIFAR loaders parse the official pickled-batch tar format (and
    raise on the wrong archive) — the legacy reader yields the flat
    [0, 1] float vector exactly once normalized."""
    import pickle

    from paddle_tpu.dataset import cifar

    rs = np.random.RandomState(0)

    def make_tar(path, members):
        with tarfile.open(path, "w:gz") as tf:
            for name, batch in members.items():
                data = pickle.dumps(batch)
                _add_bytes(tf, name, data)

    img = (rs.rand(4, 3072) * 255).astype(np.uint8)
    p10 = tmp_path / "cifar-10-python.tar.gz"
    make_tar(p10, {
        "cifar-10-batches-py/data_batch_1":
            {b"data": img[:2], b"labels": [1, 2]},
        "cifar-10-batches-py/data_batch_2":
            {b"data": img[2:], b"labels": [3, 4]},
        "cifar-10-batches-py/test_batch":
            {b"data": img[:1], b"labels": [5]},
    })
    train = list(cifar.train10(data_file=str(p10))())
    assert len(train) == 4
    x, y = train[0]
    assert x.shape == (3072,) and x.dtype == np.float32
    assert 0.0 <= x.min() and x.max() <= 1.0 and x.max() > 0.01
    assert sorted(s[1] for s in train) == [1, 2, 3, 4]
    assert len(list(cifar.test10(data_file=str(p10))())) == 1

    p100 = tmp_path / "cifar-100-python.tar.gz"
    make_tar(p100, {
        "cifar-100-python/train":
            {b"data": img[:3], b"fine_labels": [10, 20, 30]},
        "cifar-100-python/test":
            {b"data": img[3:], b"fine_labels": [40]},
    })
    assert [s[1] for s in cifar.train100(data_file=str(p100))()] == \
        [10, 20, 30]
    # wrong archive fails loudly, never parses as the other format
    with pytest.raises(RuntimeError, match="wrong archive"):
        list(cifar.train100(data_file=str(p10))())


def test_dataset_voc2012(tmp_path):
    from PIL import Image

    from paddle_tpu.dataset import voc2012

    p = tmp_path / "VOCtrainval_11-May-2012.tar"

    def png_bytes(arr):
        buf = io.BytesIO()
        Image.fromarray(arr).save(buf, format="PNG")
        return buf.getvalue()

    def jpg_bytes(arr):
        buf = io.BytesIO()
        Image.fromarray(arr).save(buf, format="JPEG")
        return buf.getvalue()

    rs = np.random.RandomState(0)
    with tarfile.open(p, "w") as tf:
        _add_bytes(tf,
                   "VOCdevkit/VOC2012/ImageSets/Segmentation/trainval.txt",
                   b"a\nb\n")
        _add_bytes(tf, "VOCdevkit/VOC2012/ImageSets/Segmentation/train.txt",
                   b"a\n")
        _add_bytes(tf, "VOCdevkit/VOC2012/ImageSets/Segmentation/val.txt",
                   b"b\n")
        for name in ("a", "b"):
            _add_bytes(tf, f"VOCdevkit/VOC2012/JPEGImages/{name}.jpg",
                       jpg_bytes(rs.randint(0, 255, (8, 8, 3), np.uint8)))
            _add_bytes(tf, f"VOCdevkit/VOC2012/SegmentationClass/{name}.png",
                       png_bytes(rs.randint(0, 20, (8, 8), np.uint8)))
    samples = list(voc2012.train(data_file=str(p))())
    assert len(samples) == 2  # reference quirk: train == trainval list
    img, label = samples[0]
    assert img.shape == (8, 8, 3) and label.shape == (8, 8)
    assert len(list(voc2012.val(data_file=str(p))())) == 1


def test_dataset_flowers(tmp_path):
    from PIL import Image
    from scipy.io import savemat

    from paddle_tpu.dataset import common, flowers

    d = tmp_path / "flowers"
    d.mkdir()
    rs = np.random.RandomState(0)
    with tarfile.open(d / "102flowers.tgz", "w:gz") as tf:
        for i in range(1, 5):
            buf = io.BytesIO()
            Image.fromarray(
                rs.randint(0, 255, (6, 6, 3), np.uint8)).save(
                buf, format="JPEG")
            _add_bytes(tf, f"jpg/image_{i:05d}.jpg", buf.getvalue())
    savemat(d / "imagelabels.mat",
            {"labels": np.array([[1, 2, 1, 2]])})
    savemat(d / "setid.mat", {"trnid": np.array([[1]]),
                              "tstid": np.array([[2, 3]]),
                              "valid": np.array([[4]])})
    import pytest as _pytest

    mp = _pytest.MonkeyPatch()
    mp.setattr(common, "DATA_HOME", str(tmp_path))
    try:
        train = list(flowers.train(use_xmap=False)())
        assert len(train) == 2  # tstid (the larger split) trains
        img, label = train[0]
        assert img.shape == (6, 6, 3)
        test = list(flowers.test(use_xmap=False)())
        assert len(test) == 1
    finally:
        mp.undo()


def test_dataset_image_helpers(tmp_path):
    from paddle_tpu.dataset import image as dimg

    im = np.zeros((10, 20, 3), np.uint8)
    small = dimg.resize_short(im, 5)
    assert min(small.shape[:2]) == 5
    crop = dimg.center_crop(small, 4)
    assert crop.shape[:2] == (4, 4)
    chw = dimg.to_chw(crop)
    assert chw.shape == (3, 4, 4)
    out = dimg.simple_transform(im, 8, 4, is_train=False,
                                mean=[1.0, 2.0, 3.0])
    assert out.shape == (3, 4, 4) and out.dtype == np.float32


def test_dataset_namespace_importable():
    import paddle_tpu.dataset as D

    for mod in ("cifar", "common", "conll05", "flowers", "image", "imdb",
                "imikolov", "mnist", "movielens", "uci_housing",
                "voc2012", "wmt14", "wmt16"):
        assert hasattr(D, mod), mod
