"""Prefix caching (ISSUE 13): refcounted page sharing, the radix
prefix index, warm-vs-cold bit parity, the LRU idle-prefix eviction
tier, router prefix affinity, and the TTFT/observability surface.

Layers:

  * pool units — share/refcount/free semantics, double-free-of-shared
    loud, defrag moves a shared page exactly once, the shared/logical
    stats split;
  * index units — insert/lookup/evict incl. page-boundary off-by-one
    lengths, LRU order, the max-tokens bound, defrag remap;
  * engine — warm streams BIT-IDENTICAL to cold-cache streams and to
    sequential greedy `generate()` across precision tiers, GQA llama,
    spec decoding, and eviction/recompute;
  * router — affinity pick vs slack vs drain with fake replicas, the
    fingerprint round-trip;
  * schema zeros, the TTFT histogram, the perf-audit budget smoke, and
    the perf_gate --update round-trip for the bench rows.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu.inference.engine import (
    EngineConfig, InferenceEngine, PagePool, PrefixIndex,
)
from test_engine import assert_drained

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _gpt(layers=2, seed=0, max_len=64):
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    P.seed(seed)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=layers,
                    num_heads=4, max_seq_len=max_len)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model


@pytest.fixture(scope="module")
def gpt_model():
    return _gpt()


@pytest.fixture(scope="module")
def draft_model():
    return _gpt(layers=1, seed=7)


PS = 4          # page size every engine test uses
SYS_LEN = 12    # 3 full pages of shared system prompt


@pytest.fixture(scope="module")
def tenant_prompts():
    """Two tenants with 3-page system prompts; suffix lengths include
    the page-boundary off-by-ones (total lengths k*ps-1, k*ps, k*ps+1)."""
    rs = np.random.RandomState(0)
    sysp = [rs.randint(0, 128, (SYS_LEN,)).astype(np.int32)
            for _ in range(2)]
    sfx = (3, 4, 5, 1, 7, 4)   # 12+4=16 (exact page), 15, 17 covered
    return [np.concatenate([
        sysp[i % 2], rs.randint(0, 128, (n,)).astype(np.int32)])
        for i, n in enumerate(sfx)]


@pytest.fixture(scope="module")
def refs(gpt_model, tenant_prompts):
    return [np.asarray(gpt_model.generate(
        P.to_tensor(p[None, :], "int32"), max_new_tokens=8)._value)[0]
        for p in tenant_prompts]


def _ecfg(**kw):
    base = dict(page_size=PS, max_slots=2, prefill_bucket=PS,
                max_seq_len=64)
    base.update(kw)
    return EngineConfig(**base)


# ------------------------------ pool units ------------------------------

def test_pool_share_refcount_and_free():
    pool = PagePool(num_pages=8, page_size=4)
    a = pool.alloc(3)
    assert all(pool.refcount(p) == 1 for p in a)
    shared = pool.share(a[:2])
    assert shared == [int(x) for x in a[:2]]
    assert pool.refcount(a[0]) == 2
    st = pool.stats()
    assert st["used"] == 3                 # physical: shared counted ONCE
    assert st["shared_pages"] == 2
    assert st["logical_pages"] == 5
    pool.free(a)                           # one holder gone
    assert pool.used_pages == 2            # shared pair still live
    assert pool.refcount(a[0]) == 1
    pool.free(a[:2])                       # last refs drop
    assert pool.used_pages == 0
    assert pool.ref_counts() == {}


def test_pool_double_free_of_shared_loud():
    pool = PagePool(num_pages=6, page_size=4)
    a = pool.alloc(1)
    pool.share(a)
    pool.free(a)
    pool.free(a)                           # second holder's legit free
    with pytest.raises(ValueError):        # now it IS a double free
        pool.free(a)
    with pytest.raises(ValueError):        # dead pages cannot be shared
        pool.share(a)
    with pytest.raises(ValueError):
        pool.share([0])                    # nor the scratch page


def test_pool_defrag_moves_shared_page_once_and_remaps_refs():
    pool = PagePool(num_pages=10, page_size=4)
    a = pool.alloc(2)
    b = pool.alloc(1)
    pool.share(b)
    pool.free(a)                           # holes below b's page
    moves = pool.defrag()
    assert list(moves.keys()) == [b[0]]    # ONE physical move
    new = moves[b[0]]
    assert pool.refcount(new) == 2         # both holders repointed
    assert pool.refcount(b[0]) == 0
    pool.free([new])
    pool.free([new])
    assert pool.used_pages == 0


def test_pool_peak_counts_shared_once():
    pool = PagePool(num_pages=8, page_size=4)
    a = pool.alloc(2)
    pool.share(a)
    assert pool.stats()["peak_used"] == 2  # sharing is not allocation
    pool.free(a)
    pool.free(a)


# ------------------------------ index units ------------------------------

def _toks(n, seed=0):
    return np.random.RandomState(seed).randint(0, 99, (n,)).astype(
        np.int32)


def test_index_insert_lookup_page_boundaries():
    pool = PagePool(num_pages=16, page_size=4)
    idx = PrefixIndex(pool)
    toks = _toks(12)                       # 3 full pages
    pages = pool.alloc(3)
    assert idx.insert(toks, pages) == 3
    assert all(pool.refcount(p) == 2 for p in pages)
    # off-by-one lengths around each boundary: matched pages must be
    # the longest FULL-page prefix the cap allows
    for n, max_pages, want in ((11, 2, 2), (12, 2, 2), (12, 3, 3),
                               (13, 3, 3), (4, 1, 1), (3, 0, 0),
                               (5, 1, 1)):
        got_tokens, got_pages, nodes = idx.lookup(toks[:n], max_pages)
        assert got_tokens == want * 4, (n, max_pages)
        assert got_pages == [int(p) for p in pages[:want]]
        assert len(nodes) == want
    # a diverging second page matches only the first
    other = toks.copy()
    other[5] = (other[5] + 1) % 99
    t, pgs, _ = idx.lookup(other, 3)
    assert t == 4 and pgs == [int(pages[0])]


def test_index_lru_eviction_and_busy_pages_skipped():
    pool = PagePool(num_pages=16, page_size=4)
    clock = [0.0]
    idx = PrefixIndex(pool, clock=lambda: clock[0])
    a_pages, b_pages = pool.alloc(2), pool.alloc(2)
    idx.insert(_toks(8, seed=1), a_pages)
    clock[0] = 1.0
    idx.insert(_toks(8, seed=2), b_pages)
    pool.free(a_pages)                     # cache is now sole holder
    pool.free(b_pages)
    clock[0] = 2.0
    idx.lookup(_toks(8, seed=1), 2)        # touch chain A -> B is LRU
    assert idx.evict_idle(1) == 1
    t, _, _ = idx.lookup(_toks(8, seed=2), 2)
    assert t == 4                          # B's LEAF died first (LRU)
    t, _, _ = idx.lookup(_toks(8, seed=1), 2)
    assert t == 8                          # A untouched
    # a page shared with a live holder is never reclaimed for pressure
    t, pgs, _ = idx.lookup(_toks(8, seed=1), 2)
    pool.share(pgs)                        # live sequence pins them
    assert idx.evict_idle(8) == 1          # only B's remaining idle page
    assert idx.nodes == 2
    pool.free(pgs)
    assert idx.clear() == 2
    assert pool.used_pages == 0


def test_index_max_tokens_bound():
    pool = PagePool(num_pages=32, page_size=4)
    clock = [0.0]
    idx = PrefixIndex(pool, max_tokens=8, clock=lambda: clock[0])
    a = pool.alloc(2)
    idx.insert(_toks(8, seed=1), a)
    pool.free(a)                           # idx is sole holder
    clock[0] = 1.0
    b = pool.alloc(2)
    idx.insert(_toks(8, seed=2), b)
    pool.free(b)
    # bound is 8 tokens = 2 pages: the older chain was reclaimed
    assert idx.cached_tokens <= 8
    assert idx.lookup(_toks(8, seed=2), 2)[0] == 8
    assert idx.lookup(_toks(8, seed=1), 2)[0] == 0
    idx.clear()
    assert pool.used_pages == 0


def test_index_apply_moves():
    pool = PagePool(num_pages=8, page_size=4)
    idx = PrefixIndex(pool)
    filler = pool.alloc(1)
    pages = pool.alloc(2)
    idx.insert(_toks(8), pages)
    pool.free(pages)
    pool.free(filler)                      # hole at the bottom
    moves = pool.defrag()
    idx.apply_moves(moves)
    t, pgs, _ = idx.lookup(_toks(8), 2)
    assert t == 8 and pgs == [moves.get(p, p) for p in pages]
    idx.clear()
    assert pool.used_pages == 0


# ------------------------------ engine parity ------------------------------

def _run_engine(model, prompts, draft=None, **cfg_kw):
    eng = InferenceEngine(model, _ecfg(**cfg_kw), draft_model=draft)
    outs = [eng.generate([p], max_new_tokens=8)[0] for p in prompts]
    return outs, eng


def test_warm_equals_cold_and_sequential(gpt_model, tenant_prompts,
                                         refs):
    warm, eng = _run_engine(gpt_model, tenant_prompts)
    cold, _ = _run_engine(gpt_model, tenant_prompts, prefix_cache=False)
    for w, c, r in zip(warm, cold, refs):
        assert np.array_equal(w, r)
        assert np.array_equal(w, c)
    st = eng.prefix_cache_stats()
    assert st["hits"] >= 4 and st["misses"] >= 2
    assert st["prefill_tokens_saved"] > 0
    assert_drained(eng)


def test_warm_repeat_prompt_full_hit_and_states(gpt_model,
                                                tenant_prompts, refs):
    eng = InferenceEngine(gpt_model, _ecfg())
    h1 = eng.submit(tenant_prompts[0], max_new_tokens=8)
    while not h1.done.is_set():
        eng.step()
    assert h1.cache_state == "miss"
    h2 = eng.submit(tenant_prompts[0], max_new_tokens=8)
    while not h2.done.is_set():
        eng.step()
    # the full sharable prefix (all but the last page-aligned token
    # span) was cached by the first request
    assert h2.cache_state == "hit"
    assert np.array_equal(h2.result(), refs[0])
    # deeper prefixes commit over time: the repeat run re-prefilled
    # only the tail
    assert eng.prefix_cache_stats()["prefill_tokens_saved"] > 0
    assert_drained(eng)


def test_warm_exact_page_aligned_prompt_keeps_one_tail_token(gpt_model):
    """A prompt of EXACTLY k pages may share at most k-1 pages — the
    prefill must still produce the first token from a real tail."""
    rs = np.random.RandomState(3)
    p = rs.randint(0, 128, (16,)).astype(np.int32)   # 4 full pages
    ref = np.asarray(gpt_model.generate(
        P.to_tensor(p[None, :], "int32"), max_new_tokens=6)._value)[0]
    eng = InferenceEngine(gpt_model, _ecfg())
    a = eng.generate([p], max_new_tokens=6)[0]
    b = eng.generate([p], max_new_tokens=6)[0]
    assert np.array_equal(a, ref) and np.array_equal(b, ref)
    assert eng.prefix_cache_stats()["hits"] == 1
    assert_drained(eng)


@pytest.mark.parametrize("tier", [
    {"kv_precision": "int8"},
    {"weight_precision": "int8"},
    {"weight_precision": "int8", "kv_precision": "int8"},
])
def test_warm_equals_cold_quantized_tiers(gpt_model, tenant_prompts,
                                          tier):
    """Warm streams bit-identical to cold-cache streams at every
    precision tier — under kv int8 the warm prefill attends the EXACT
    sidecar, so the first token is computed from the same values a
    cold dense prefill sees."""
    warm, eng = _run_engine(gpt_model, tenant_prompts, **tier)
    cold, _ = _run_engine(gpt_model, tenant_prompts,
                          prefix_cache=False, **tier)
    for w, c in zip(warm, cold):
        assert np.array_equal(w, c), tier
    assert eng.prefix_cache_stats()["hits"] > 0
    assert_drained(eng)


def test_warm_committed_chunks_rematch_int8(gpt_model):
    """Chunks committed FROM a warm prefill (a prompt that extends an
    already-cached prefix) must carry CORRECT exact sidecars: a third
    prompt matching the deepened prefix streams bit-identically to
    cold.  Regression: the warm commit offset once sliced the sidecar
    a whole prefix past the real tokens — re-matching the warm-
    committed chunk then crashed on ragged sidecar shapes or silently
    attended garbage prefix K/V."""
    rs = np.random.RandomState(9)
    sysp = rs.randint(0, 128, (12,)).astype(np.int32)    # 3 pages
    common = rs.randint(0, 128, (4,)).astype(np.int32)   # page 4
    prompts = [
        np.concatenate([sysp,
                        rs.randint(0, 128, (2,)).astype(np.int32)]),
        # extends the cached 3-page prefix: page 4 commits WARM
        np.concatenate([sysp, common,
                        rs.randint(0, 128, (1,)).astype(np.int32)]),
        # matches all 4 pages incl. the warm-committed one
        np.concatenate([sysp, common,
                        rs.randint(0, 128, (3,)).astype(np.int32)]),
    ]
    warm, eng = _run_engine(gpt_model, prompts, kv_precision="int8")
    cold, _ = _run_engine(gpt_model, prompts, prefix_cache=False,
                          kv_precision="int8")
    for w, c in zip(warm, cold):
        assert np.array_equal(w, c)
    assert eng.prefix_cache_stats()["hits"] == 2
    assert_drained(eng)


def test_warm_equals_cold_spec_decoding(gpt_model, draft_model,
                                        tenant_prompts):
    warm, eng = _run_engine(gpt_model, tenant_prompts,
                            draft=draft_model, spec_tokens=2)
    cold, _ = _run_engine(gpt_model, tenant_prompts, draft=draft_model,
                          spec_tokens=2, prefix_cache=False)
    for w, c in zip(warm, cold):
        assert np.array_equal(w, c)
    assert eng.prefix_cache_stats()["hits"] > 0
    assert_drained(eng)


def test_warm_llama_gqa_matches_generate():
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    P.seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=32, num_layers=2,
                      num_heads=4, num_kv_heads=2, max_seq_len=64)
    model = LlamaForCausalLM(cfg)
    model.eval()
    rs = np.random.RandomState(0)
    sysp = rs.randint(0, 128, (SYS_LEN,)).astype(np.int32)
    prompts = [np.concatenate([
        sysp, rs.randint(0, 128, (n,)).astype(np.int32)])
        for n in (3, 4, 6)]
    refs = [np.asarray(model.generate(
        P.to_tensor(p[None, :], "int32"), max_new_tokens=6)._value)[0]
        for p in prompts]
    eng = InferenceEngine(model, _ecfg())
    outs = [eng.generate([p], max_new_tokens=6)[0] for p in prompts]
    for o, r in zip(outs, refs):
        assert np.array_equal(o, r)
    assert eng.prefix_cache_stats()["hits"] == 2
    assert_drained(eng)


def test_eviction_recompute_with_cache_and_pressure(gpt_model,
                                                    tenant_prompts,
                                                    refs):
    """A deliberately tight pool: the LRU idle-prefix tier reclaims
    cold cache first, recompute eviction handles the rest, and every
    stream still matches the sequential reference bit-for-bit."""
    eng = InferenceEngine(gpt_model, _ecfg(num_pages=14, max_slots=3))
    handles = [eng.submit(p, max_new_tokens=8) for p in tenant_prompts]
    idle = 0
    while any(not h.done.is_set() for h in handles) and idle < 3000:
        idle = idle if eng.step() else idle + 1
    for h, r in zip(handles, refs):
        assert np.array_equal(h.result(timeout=1.0), r)
    assert_drained(eng)


def test_cache_disabled_engine_has_no_index(gpt_model, tenant_prompts):
    outs, eng = _run_engine(gpt_model, tenant_prompts[:2],
                            prefix_cache=False)
    st = eng.prefix_cache_stats()
    assert st["enabled"] is False and st["hits"] == 0
    assert eng.clear_prefix_cache() == 0
    assert eng.pool.used_pages == 0        # nothing retained at all


def test_config_knob_validation():
    assert _ecfg(prefix_cache=0).prefix_cache is False
    assert _ecfg(prefix_cache=1).prefix_cache is True
    with pytest.raises(ValueError):
        _ecfg(prefix_cache_max_tokens=-1)


# ------------------------------ router affinity ------------------------------

def _affinity_router(loads, slack=0.25):
    """Fake-transport router with N /generate replicas at given engine
    loads (active sequences out of 4 slots)."""
    from test_router import _FakeReplica, _FakeTransport

    from paddle_tpu.inference.router import Router

    reps = {}
    addrs = {}
    for i, act in enumerate(loads):
        rep = _FakeReplica(engine={"max_slots": 4,
                                   "active_sequences": act,
                                   "waiting_sequences": 0})
        reps[f"r{i}"] = rep
        addrs[f"http://fake-{i}"] = rep
    router = Router(replicas={rid: f"http://fake-{i}"
                              for i, rid in enumerate(reps)},
                    transport=_FakeTransport(addrs), probe_interval=0.05,
                    affinity_slack=slack)
    router.probe_once()
    return router, reps


def test_router_affinity_within_slack_sticks():
    router, reps = _affinity_router([0, 0])
    # first fingerprinted pick: least-loaded (r0 on tie), recorded
    assert router._pick("generate", fingerprint="fp1") == "r0"
    # r0 slightly more loaded but within slack -> affinity sticks
    reps["r0"].engine["active_sequences"] = 1   # load 0.25 vs 0.0
    router.probe_once()
    assert router._pick("generate", fingerprint="fp1") == "r0"
    # beyond slack -> least-loaded wins and the map re-learns
    reps["r0"].engine["active_sequences"] = 3   # load 0.75
    router.probe_once()
    assert router._pick("generate", fingerprint="fp1") == "r1"
    reps["r0"].engine["active_sequences"] = 0
    router.probe_once()
    # re-learned affinity now points at r1; equal loads keep it there
    assert router._pick("generate", fingerprint="fp1") == "r1"
    router.shutdown()


def test_router_affinity_never_picks_drained_and_no_fp_is_plain():
    router, reps = _affinity_router([0, 1])
    assert router._pick("generate", fingerprint="fpX") == "r0"
    router.mark_draining("r0")
    assert router._pick("generate", fingerprint="fpX") == "r1"
    # un-fingerprinted picks never touch the affinity map
    before = dict(router._affinity)
    assert router._pick("generate") == "r1"
    assert router._affinity == before
    router.shutdown()


def test_router_affinity_bounded_map():
    router, _ = _affinity_router([0, 0])
    router.AFFINITY_CAP = 8
    for i in range(20):
        router._pick("generate", fingerprint=f"fp{i}")
    assert len(router._affinity) == 8
    assert "fp19" in router._affinity and "fp0" not in router._affinity
    router.shutdown()


def test_fingerprint_helper_and_header_roundtrip():
    from test_router import _FakeReplica, _FakeTransport

    from paddle_tpu.inference.router import Router
    from paddle_tpu.inference.serving import InferenceClient

    fp = InferenceClient.prefix_fingerprint
    ids = list(range(40))
    # floored to the granule: extending within the same page keeps the
    # fingerprint; crossing the cap does not change it either (first N)
    assert fp(ids) == fp(ids + [1, 2, 3])
    assert fp(ids, tokens=16) == fp(ids[:16] + [99] * 24, tokens=16)
    assert fp(list(range(8))) is None          # shorter than one granule
    assert fp(ids) != fp([7] + ids[1:])        # content-sensitive
    # the router forwards the client's header to the replica
    rep = _FakeReplica(engine={"max_slots": 4, "active_sequences": 0,
                               "waiting_sequences": 0})
    router = Router(replicas={"r0": "http://fake-0"},
                    transport=_FakeTransport({"http://fake-0": rep}),
                    probe_interval=0.05)
    router.probe_once()
    from test_router import _FakeHandler

    from paddle_tpu.observability import request_trace as rtrace

    ctx = rtrace.new_context()
    router.forward_generate(
        json.dumps({"input_ids": ids, "max_new_tokens": 2}).encode(),
        ids, ctx, _FakeHandler(), fingerprint=fp(ids))
    gen_headers = [h for p, h in rep.requests if p == "/generate"]
    assert gen_headers and gen_headers[0].get(
        "X-Prefix-Fingerprint") == fp(ids)
    router.shutdown()


# ------------------------------ observability ------------------------------

def test_schema_zeros_and_counters(gpt_model, tenant_prompts):
    from paddle_tpu import observability as obs
    from paddle_tpu.observability import metrics

    obs.attach(crash_hook=False)
    metrics.reset()
    obs.attach(crash_hook=False)
    try:
        snap = metrics.snapshot()
        c, g = snap["counters"], snap["gauges"]
        for ev in ("hit", "miss", "evict"):
            assert c.get(f"engine.prefix_cache{{event={ev}}}") == 0
        for oc in ("affine", "least_loaded"):
            assert c.get(f"router.affinity{{outcome={oc}}}") == 0
        assert g.get("engine.prefix_cached_tokens") == 0
        assert g.get("engine.prefix_cache_hit_rate") == 0
        eng = InferenceEngine(gpt_model, _ecfg())
        for p in tenant_prompts[:4]:
            eng.generate([p], max_new_tokens=4)
        snap = metrics.snapshot()
        c, g = snap["counters"], snap["gauges"]
        assert c.get("engine.prefix_cache{event=hit}") == 2
        assert c.get("engine.prefix_cache{event=miss}") == 2
        assert g.get("engine.prefix_cached_tokens") > 0
        assert g.get("engine.prefix_cache_hit_rate") == 0.5
        eng.clear_prefix_cache()
    finally:
        obs.detach()


def test_ttft_histogram_and_ready_payload(gpt_model, tenant_prompts):
    from paddle_tpu import observability as obs
    from paddle_tpu.inference.serving import (
        InferenceClient, InferenceServer,
    )
    from paddle_tpu.observability import metrics

    obs.attach(crash_hook=False)
    metrics.reset()
    obs.attach(crash_hook=False)
    eng = InferenceEngine(gpt_model, _ecfg())
    srv = InferenceServer(engine=eng, request_timeout=60.0,
                          queue_depth=0).start()
    try:
        cli = InferenceClient(srv.address, timeout=60.0)
        cli.generate(tenant_prompts[0], max_new_tokens=4)
        cli.generate(tenant_prompts[0], max_new_tokens=4)
        hists = metrics.snapshot()["histograms"]
        assert "serving.ttft_ms{cache=miss,endpoint=generate}" in hists
        assert "serving.ttft_ms{cache=hit,endpoint=generate}" in hists
        ready = cli.ready()
        pc = ready["engine"]["prefix_cache"]
        assert pc["enabled"] is True
        assert pc["hit_rate"] == 0.5
        assert pc["cached_tokens"] > 0
        # the ttft SLO objective exists and saw both streams
        rep = srv.slo.report()
        assert rep["endpoints"]["ttft"]["requests"] == 2
        assert rep["endpoints"]["ttft"]["errors"] == 0
        # /debug/telemetry carries the engine section with the split
        snap = srv.telemetry_snapshot()
        assert "shared_pages" in snap["engine"]["pages"]
        assert "prefix_cache" in snap["engine"]
    finally:
        srv.shutdown()
        eng.clear_prefix_cache()
        obs.detach()


# ------------------------------ perf audit + gate ------------------------------

def test_perf_smoke_cached_prefill_within_budget():
    """The warm tail-prefill program audits cleanly and holds its
    committed budget — a shape leak of the actual shared length (the
    PT402 recompile hazard this program exists to pin) or a layout
    regression fails here before any hardware run."""
    from paddle_tpu import analysis as A
    from paddle_tpu.analysis import perf_audit

    violations, m = perf_audit.audit_perf(
        programs=("cached_prefill_step",), repo_root=REPO)
    assert not [v for v in violations if v.rule == "PT400"], \
        A.render_report(violations)
    prog = m["gpt_cached_prefill_step"]
    assert prog["pt402_weak_inputs"] == 0
    assert prog["pt405_host_syncs"] == 0
    budget = A.load_budget(
        os.path.join(REPO, "tools", "perf_budget.json"))
    reg, _imp, _ = A.diff_against_budget(m, budget)
    assert reg == [], A.render_budget_diff(reg, [])


def test_perf_gate_prefix_rows_round_trip(tmp_path):
    """The shared-prefix bench rows are gateable: --update registers
    them, an equal rerun passes, a hit-rate collapse exits 2."""
    gate = os.path.join(REPO, "tools", "perf_gate.py")
    base = tmp_path / "baseline.jsonl"
    res = tmp_path / "results.json"
    rows = [
        {"metric": "serving_prefix_cache_hit_rate", "value": 0.75,
         "unit": "frac"},
        {"metric": "serving_ttft_warm_vs_cold_speedup", "value": 1.8,
         "unit": "x"},
        {"metric": "serving_prefill_tokens_saved_frac", "value": 0.62,
         "unit": "frac"},
    ]
    base.write_text("".join(json.dumps(r) + "\n" for r in rows))

    def run(hit_rate):
        out = [dict(rows[0], value=hit_rate)] + rows[1:]
        res.write_text("".join(json.dumps(r) + "\n" for r in out))
        return subprocess.run(
            [sys.executable, gate, str(res), "--baseline", str(base),
             "--static-budget", ""],
            capture_output=True, text=True)

    assert run(0.75).returncode == 0
    assert run(0.74).returncode == 0       # within tolerance
    p = run(0.2)
    assert p.returncode == 2 and "regression" in p.stderr
    # --update rolls the floor forward after a win
    res.write_text("".join(
        json.dumps(dict(r, value=r["value"] * 1.2)) + "\n"
        for r in rows))
    p = subprocess.run(
        [sys.executable, gate, str(res), "--baseline", str(base),
         "--static-budget", "", "--update"],
        capture_output=True, text=True)
    assert p.returncode == 0 and "updated" in p.stdout


def test_bench_prefix_cache_rows():
    """The bench emits all three rows with the acceptance floors met
    on the CPU proxy (degraded-marked): hit rate > 0.5 and saved
    fraction > 0.4 on the shared-prefix tenant workload."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    rows = bench._bench_prefix_cache(True)
    by = {r["metric"]: r for r in rows}
    assert set(by) == {"serving_prefix_cache_hit_rate",
                       "serving_ttft_warm_vs_cold_speedup",
                       "serving_prefill_tokens_saved_frac"}
    assert all(r["degraded"] for r in rows)
    assert by["serving_prefix_cache_hit_rate"]["value"] > 0.5
    assert by["serving_prefill_tokens_saved_frac"]["value"] > 0.4
    assert by["serving_ttft_warm_vs_cold_speedup"]["value"] > 0


@pytest.mark.chaos
def test_prefix_chaos_scenario():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import chaos_check
    finally:
        sys.path.pop(0)
    report = chaos_check.run_prefix_chaos(seed=0)
    assert report["recovered"], report
