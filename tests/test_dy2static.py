"""dy2static control-flow capture: data-dependent if/while under to_static
must match eager execution (reference: test/dygraph_to_static suite role)."""
import numpy as np
import pytest

import paddle_tpu as P
import paddle_tpu.nn as nn
from paddle_tpu.jit.dy2static import Dy2StaticError, convert


def test_data_dependent_if_matches_eager():
    def f(x):
        y = x * 2
        if y.sum() > 0:
            out = y + 1
        else:
            out = y - 1
        return out

    xs_pos = P.to_tensor(np.ones((2, 3), np.float32))
    xs_neg = P.to_tensor(-np.ones((2, 3), np.float32))
    static_f = P.jit.to_static(f)
    for xs in (xs_pos, xs_neg):
        eager = f(xs).numpy()
        comp = static_f(xs)
        np.testing.assert_allclose(comp.numpy(), eager, rtol=1e-6)


def test_data_dependent_while_matches_eager():
    def f(x):
        s = x.sum()
        n = P.to_tensor(np.zeros((), np.float32))
        while s < 100.0:
            s = s * 2
            n = n + 1
        return s, n

    xs = P.to_tensor(np.full((2, 2), 1.5, np.float32))
    eager_s, eager_n = f(xs)
    static_f = P.jit.to_static(f)
    comp_s, comp_n = static_f(xs)
    np.testing.assert_allclose(comp_s.numpy(), eager_s.numpy(), rtol=1e-6)
    np.testing.assert_allclose(comp_n.numpy(), eager_n.numpy())


def test_model_with_branch_matches_eager():
    class Gated(nn.Layer):
        def __init__(self):
            super().__init__()
            self.a = nn.Linear(8, 8)
            self.b = nn.Linear(8, 8)

        def forward(self, x):
            h = self.a(x)
            if h.mean() > 0:
                out = self.b(h)
            else:
                out = self.b(-h)
            return out

    P.seed(0)
    net = Gated()
    xs = P.to_tensor(np.random.RandomState(0).randn(4, 8).astype(np.float32))
    eager = net(xs).numpy()
    static_net = P.jit.to_static(net)
    comp = static_net(xs)
    np.testing.assert_allclose(comp.numpy(), eager, rtol=1e-5, atol=1e-6)


def test_backward_through_converted_branch():
    class Gated(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            h = self.fc(x)
            if h.sum() > 0:
                out = h * 2
            else:
                out = h * 3
            return out

    P.seed(0)
    net = Gated()
    xs = P.to_tensor(np.ones((2, 4), np.float32))
    static_net = P.jit.to_static(net)
    loss = static_net(xs).sum()
    loss.backward()
    assert net.fc.weight.grad is not None


def test_tensor_bool_ops_in_predicate():
    def f(x):
        if (x.sum() > 0) and (x.max() < 10):
            out = x + 1
        else:
            out = x - 1
        return out

    xs = P.to_tensor(np.ones((2, 2), np.float32))
    static_f = P.jit.to_static(f)
    np.testing.assert_allclose(static_f(xs).numpy(), f(xs).numpy())


def test_python_control_flow_still_works():
    """Static (non-tensor) conditions keep plain Python semantics."""
    def f(x, flag=True):
        if flag:
            x = x + 1
        for _ in range(3):  # python for: unrolls under trace
            x = x * 2
        return x

    xs = P.to_tensor(np.ones((2,), np.float32))
    static_f = P.jit.to_static(f)
    np.testing.assert_allclose(static_f(xs).numpy(), f(xs).numpy())


def test_loud_error_on_python_var_in_traced_branch():
    def f(x):
        tag = 0
        if x.sum() > 0:
            tag = 1  # python int diverges across traced branches
            out = x + 1
        else:
            out = x - 1
        return out * (tag + 1)

    xs = P.to_tensor(np.ones((2,), np.float32))
    static_f = P.jit.to_static(f)
    with pytest.raises(Dy2StaticError):
        static_f(xs)


def test_convert_preserves_plain_functions():
    def g(a, b):
        return a + b

    assert convert(g)(1, 2) == 3


def test_closure_with_branch_matches_eager():
    """Closures are converted, not silently skipped (VERDICT r2 task 6):
    a closure-using fn with a tensor-dependent branch must run under jit
    and match eager."""
    scale = P.to_tensor(np.float32(3.0))
    offset = 2.0

    def f(x):
        if x.sum() > 0:
            out = x * scale
        else:
            out = x - offset
        return out

    xs = P.to_tensor(np.ones((2,), np.float32))
    neg = P.to_tensor(-np.ones((2,), np.float32))
    static_f = P.jit.to_static(f)
    np.testing.assert_allclose(static_f(xs).numpy(), f(xs).numpy())
    np.testing.assert_allclose(static_f(neg).numpy(), f(neg).numpy())


def test_closure_cells_stay_live():
    """The converted function shares the ORIGINAL cells: rebinding the
    free variable through the maker is visible to the converted fn."""
    from paddle_tpu.jit.dy2static import convert

    def make():
        k = 10.0

        def f(x):
            if x.sum() > 0:
                y = x * k
            else:
                y = x
            return y

        def bump():
            nonlocal k
            k = k + 1.0

        return f, bump

    f, bump = make()
    cf = convert(f)
    xs = P.to_tensor(np.ones((2,), np.float32))
    np.testing.assert_allclose(cf(xs).numpy(), 10.0 * np.ones(2))
    bump()
    np.testing.assert_allclose(cf(xs).numpy(), 11.0 * np.ones(2))


def test_tensor_dependent_for_range_converts():
    """`for i in range(n)` with traced n converts to lax.while_loop and
    matches eager (upgraded from the round-2 loud-error contract)."""
    def f(x, n):
        acc = x
        for _ in range(n):
            acc = acc + 1
        return acc

    xs = P.to_tensor(np.ones((2,), np.float32))
    static_f = P.jit.to_static(f)
    for k in (0, 3, 5):
        n = P.to_tensor(np.int32(k))
        np.testing.assert_allclose(static_f(xs, n).numpy(), 1.0 + k)

    # loop variable used in the body, explicit start/step
    def g(x, n):
        s = x * 0
        for i in range(1, n, 2):
            s = s + i
        return s

    static_g = P.jit.to_static(g)
    n = P.to_tensor(np.int32(7))
    np.testing.assert_allclose(static_g(xs, n).numpy(),
                               float(1 + 3 + 5))


def test_loud_error_on_tensor_iterable_for():
    def f(x, idxs):
        acc = x
        for i in zip(idxs):  # non-range tensor iterable: loud
            acc = acc + 1
        return acc

    xs = P.to_tensor(np.ones((2,), np.float32))
    n = P.to_tensor(np.int32(0))
    static_f = P.jit.to_static(f)
    with pytest.raises((Dy2StaticError, Exception)):
        static_f(xs, n)
