"""Static-analysis subsystem tests (docs/STATIC_ANALYSIS.md).

Three kinds of coverage:
  * fixture snippets — one positive and one negative per rule ID, so
    every rule's firing condition is pinned by a test, not by folklore;
  * repo gates — the whole tree runs through the ast+lock layers and
    must produce no violations beyond tools/lint_baseline.json, and the
    OPS_MANIFEST audit must show no drift (these ARE the CI gate);
  * meta-properties — determinism (two runs, byte-identical reports),
    suppression scoping, baseline diff semantics, CLI exit codes.

The jaxpr layer's *fixtures* (tiny traces) run in tier-1; the full
op-table + train-step audits build real programs and live in the slow
tier.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

import paddle_tpu.analysis as A
from paddle_tpu.analysis import hlo_audit, lock_check, trace_safety
from paddle_tpu.analysis.report import Suppressions, Violation

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(violations):
    return {v.rule for v in violations}


def run_ast(src):
    return trace_safety.analyze_source(textwrap.dedent(src), "fix.py")


def run_ast_tests(src):
    return trace_safety.analyze_source(
        textwrap.dedent(src), "tests/fix.py")


def run_lock(src):
    return lock_check.analyze_source(textwrap.dedent(src), "fix.py")


# --------------------------- PT001 tracer leak ---------------------------

PT001_POS = """
    import jax

    class M:
        @jax.jit
        def step(self, x):
            y = x * 2
            self.cache = y
            return y
"""

PT001_NEG = """
    import jax

    class M:
        def configure(self, x):     # not jit-traced: storing is fine
            self.cache = x * 2

        @jax.jit
        def step(self, x):
            return x * 2
"""


def test_pt001_positive():
    v = [x for x in run_ast(PT001_POS) if x.rule == "PT001"]
    assert len(v) == 1 and "self.cache" in v[0].message


def test_pt001_negative():
    assert "PT001" not in rules_of(run_ast(PT001_NEG))


def test_pt001_reaches_through_call_graph():
    # helper() is only traced because the jitted entry calls it
    src = """
        import jax

        def helper(self, x):
            self.state = x + 1
            return x

        @jax.jit
        def entry(self, x):
            return helper(self, x)
    """
    assert "PT001" in rules_of(run_ast(src))


# ----------------------- PT002 concretization -----------------------

PT002_POS = """
    from paddle_tpu import jit

    @jit.to_static
    def f(x):
        if x:
            return x.item()
        return float(x)
"""

PT002_NEG = """
    from paddle_tpu import jit

    @jit.to_static
    def f(x, n):
        y = x * int("4")      # int() of a constant: fine
        return y + len([n])

    def eager(x):
        return float(x)       # not traced: fine
"""


def test_pt002_positive():
    v = [x for x in run_ast(PT002_POS) if x.rule == "PT002"]
    # if-on-param, .item(), float(param)
    assert len(v) == 3


def test_pt002_negative():
    assert "PT002" not in rules_of(run_ast(PT002_NEG))


# ----------------------- PT003 PRNG key reuse -----------------------

PT003_POS = """
    import jax

    def sample(shape):
        key = jax.random.PRNGKey(0)
        a = jax.random.normal(key, shape)
        b = jax.random.uniform(key, shape)
        return a, b
"""

PT003_NEG = """
    import jax

    def sample(shape):
        key = jax.random.PRNGKey(0)
        k1, k2 = jax.random.split(key)
        a = jax.random.normal(k1, shape)
        b = jax.random.uniform(k2, shape)
        return a, b
"""


def test_pt003_positive():
    v = [x for x in run_ast(PT003_POS) if x.rule == "PT003"]
    assert len(v) == 1 and "`key`" in v[0].message


def test_pt003_negative():
    assert "PT003" not in rules_of(run_ast(PT003_NEG))


def test_pt003_branches_are_alternatives_not_reuse():
    # one branch runs, not both — the multinomial false-positive shape
    src = """
        import jax

        def pick(shape, replacement):
            key = jax.random.PRNGKey(0)
            if replacement:
                out = jax.random.categorical(key, shape)
            else:
                out = jax.random.gumbel(key, shape)
            return out
    """
    assert "PT003" not in rules_of(run_ast(src))


def test_pt003_loop_reuse_fires():
    src = """
        import jax

        def noisy(xs):
            key = jax.random.PRNGKey(0)
            out = []
            for x in xs:
                out.append(jax.random.normal(key, x.shape))
            return out
    """
    assert "PT003" in rules_of(run_ast(src))


def test_pt003_string_split_is_not_a_key():
    src = """
        def parse(line):
            cats = line.strip()
            cats = cats.split("|")
            use(cats)
            use(cats)
            return cats
    """
    assert "PT003" not in rules_of(run_ast(src))


# ----------------------- PT004 static args -----------------------

PT004_POS = """
    import jax

    def f(x, mode="train"):
        return x

    g = jax.jit(f, static_argnames="mdoe")   # typo: never static
"""

PT004_NEG = """
    import jax

    def f(x, mode="train"):
        return x

    g = jax.jit(f, static_argnames="mode")
"""


def test_pt004_positive():
    v = [x for x in run_ast(PT004_POS) if x.rule == "PT004"]
    assert len(v) == 1 and "mdoe" in v[0].message


def test_pt004_negative():
    assert "PT004" not in rules_of(run_ast(PT004_NEG))


def test_pt004_nonhashable_static_default():
    src = """
        import jax

        def f(x, cfg=[1, 2]):
            return x

        g = jax.jit(f, static_argnames="cfg")
    """
    v = [x for x in run_ast(src) if x.rule == "PT004"]
    assert len(v) == 1 and "non-hashable" in v[0].message


def test_pt004_argnums_out_of_range():
    src = """
        import jax

        def f(x):
            return x

        g = jax.jit(f, static_argnums=(3,))
    """
    v = [x for x in run_ast(src) if x.rule == "PT004"]
    assert len(v) == 1 and "out of range" in v[0].message


# ----------------------- PT005 silent swallow -----------------------

PT005_POS = """
    def f():
        try:
            work()
        except Exception:
            pass
"""

PT005_NEG = """
    def f():
        try:
            work()
        except Exception as e:
            log.warning("work failed: %s", e)
        try:
            work()
        except ValueError:
            pass                    # narrow: allowed
"""


def test_pt005_positive():
    assert "PT005" in rules_of(run_ast(PT005_POS))


def test_pt005_negative():
    assert "PT005" not in rules_of(run_ast(PT005_NEG))


# ----------------------- PT006 mutable default -----------------------


def test_pt006_positive_and_negative():
    pos = run_ast("def f(x, acc=[]):\n    return acc\n")
    neg = run_ast("def f(x, acc=None, n=3, s='a'):\n    return x\n")
    assert "PT006" in rules_of(pos)
    assert "PT006" not in rules_of(neg)


# ----------------------- PT007 unmarked slow test -----------------------

PT007_POS = """
    import time

    def test_waits():
        time.sleep(2.0)
"""

PT007_NEG = """
    import time
    import pytest

    @pytest.mark.slow
    def test_waits():
        time.sleep(2.0)

    def test_quick():
        time.sleep(0.01)
"""


def test_pt007_positive():
    assert "PT007" in rules_of(run_ast_tests(PT007_POS))


def test_pt007_negative():
    assert "PT007" not in rules_of(run_ast_tests(PT007_NEG))


def test_pt007_only_applies_to_test_files():
    assert "PT007" not in rules_of(run_ast(PT007_POS))


# ----------------------- PT101/PT102 lock discipline -----------------------

LOCK_POS = """
    import threading

    class Ring:
        def __init__(self):
            self._lock = threading.Lock()
            self._events = []
            self._seq = 0

        def record(self, e):
            with self._lock:
                self._seq += 1
                self._events.append(e)

        def drain(self):
            out = list(self._events)    # PT102: read outside lock
            self._events = []           # PT101: write outside lock
            return out
"""

LOCK_NEG = """
    import threading

    class Ring:
        def __init__(self):
            self._lock = threading.Lock()
            self._events = []

        def record(self, e):
            with self._lock:
                self._events.append(e)

        def drain(self):
            with self._lock:
                out = list(self._events)
                self._events = []
            return out
"""


def test_lock_positive():
    v = run_lock(LOCK_POS)
    assert {"PT101", "PT102"} <= rules_of(v)
    assert all("_events" in x.message for x in v)


def test_lock_negative():
    assert run_lock(LOCK_NEG) == []


def test_lock_init_excluded_and_unguarded_ignored():
    src = """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0          # construction: never flagged
                self.flag = False

            def bump(self):
                with self._lock:
                    self._n += 1

            def toggle(self):
                self.flag = True     # never written under lock: free
    """
    assert run_lock(src) == []


def test_lock_event_attrs_are_threadsafe():
    src = """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._stop = threading.Event()
                self._n = 0

            def start(self):
                with self._lock:
                    self._stop.clear()
                    self._n += 1

            def stop(self):
                self._stop.set()     # Event: internally synchronized
    """
    assert run_lock(src) == []


def test_pt007_three_arg_range():
    # the trip count is the STOP arg, not args[-1] (which is the step)
    src = """
        def test_spin():
            total = 0
            for i in range(0, 1000000, 1):
                total += i
    """
    assert "PT007" in rules_of(run_ast_tests(src))


def test_lock_module_read_without_global_stmt():
    # reads never need a `global` declaration — they must still count
    src = """
        import threading

        _lock = threading.Lock()
        _cache = {}

        def fill(k, v):
            with _lock:
                _cache[k] = v

        def peek(k):
            return _cache.get(k)     # PT102, no global stmt needed
    """
    v = run_lock(src)
    assert rules_of(v) == {"PT102"} and "peek" in v[0].message


def test_lock_module_local_shadow_not_flagged():
    src = """
        import threading

        _lock = threading.Lock()
        _cache = {}

        def fill(k, v):
            with _lock:
                _cache[k] = v

        def local_only():
            _cache = {}              # local shadow: not the global
            return _cache
    """
    assert run_lock(src) == []


def test_lock_module_level_globals():
    src = """
        import threading

        _lock = threading.Lock()
        _cache = None

        def put(k, v):
            global _cache
            with _lock:
                if _cache is None:
                    _cache = {}
                _cache[k] = v

        def peek():
            global _cache
            return _cache            # PT102
    """
    v = run_lock(src)
    assert rules_of(v) == {"PT102"} and "peek" in v[0].message


# ----------------------- suppressions -----------------------


def test_suppression_same_line_and_line_above():
    src = textwrap.dedent("""
        def f():
            try:
                work()
            except Exception:  # pt-lint: ok[PT005]
                pass

        def g():
            try:
                work()
            # pt-lint: ok[PT005]
            except Exception:
                pass
    """)
    raw = trace_safety.analyze_source(src, "fix.py")
    assert len([v for v in raw if v.rule == "PT005"]) == 2
    import ast as _ast

    kept = Suppressions(src, _ast.parse(src)).apply(raw)
    assert kept == []


def test_suppression_def_scope_and_rule_filter():
    src = textwrap.dedent("""
        def helper():  # pt-lint: ok[PT005]
            try:
                work()
            except Exception:
                pass

        def other():
            try:
                work()
            except Exception:  # pt-lint: ok[PT003] (wrong rule)
                pass
    """)
    import ast as _ast

    raw = trace_safety.analyze_source(src, "fix.py")
    kept = Suppressions(src, _ast.parse(src)).apply(raw)
    assert len(kept) == 1 and kept[0].rule == "PT005"
    # the survivor is the one whose suppression names the wrong rule
    assert kept[0].line > 6


# ----------------------- baseline semantics -----------------------


def test_baseline_diff_new_vs_known(tmp_path):
    v1 = Violation("a.py", 10, "PT005", "msg")
    v2 = Violation("a.py", 90, "PT005", "msg")   # same key, new instance
    v3 = Violation("b.py", 5, "PT101", "other")
    baseline = {v1.key(): 1}
    new, known, stale = A.diff_against_baseline([v1, v2, v3], baseline)
    assert known == [v1]          # earliest line is the baselined one
    assert set(new) == {v2, v3}
    assert stale == []


def test_baseline_stale_detection():
    baseline = {"gone.py|PT005|msg": 2}
    new, known, stale = A.diff_against_baseline([], baseline)
    assert new == [] and known == [] and stale == ["gone.py|PT005|msg"]


def test_baseline_round_trip(tmp_path):
    path = str(tmp_path / "baseline.json")
    vs = [Violation("x.py", 1, "PT006", "m"),
          Violation("x.py", 2, "PT006", "m")]
    A.save_baseline(path, vs)
    loaded = A.load_baseline(path)
    assert loaded == {"x.py|PT006|m": 2}


# ----------------------- repo gates (tier-1 CI) -----------------------


def test_repo_gate_no_new_ast_lock_violations():
    violations = A.analyze_repo(REPO, layers=("ast", "lock"))
    baseline = A.load_baseline(
        os.path.join(REPO, "tools", "lint_baseline.json"))
    new, _known, _stale = A.diff_against_baseline(violations, baseline)
    assert new == [], "new pt_lint violations:\n" + A.render_report(new)


def test_repo_gate_manifest_no_drift():
    from paddle_tpu.analysis.manifest_check import audit_manifest

    drift = audit_manifest()
    assert drift == [], A.render_report(drift)


def test_report_is_deterministic():
    r1 = A.render_report(A.analyze_repo(REPO, layers=("ast", "lock")))
    r2 = A.render_report(A.analyze_repo(REPO, layers=("ast", "lock")))
    assert r1 == r2


def test_cli_check_passes_and_exits_zero():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "pt_lint.py"),
         "--check", "--layers", "ast,lock"],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout


def test_cli_check_fails_on_new_violation(tmp_path):
    bad = tmp_path / "bad_module.py"
    bad.write_text("def f():\n"
                   "    try:\n"
                   "        work()\n"
                   "    except Exception:\n"
                   "        pass\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "pt_lint.py"),
         "--check", "--layers", "ast,lock", str(bad)],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "PT005" in proc.stdout


# ----------------------- jaxpr layer fixtures (tier-1) -----------------------


def test_pt201_host_transfer_fixture():
    import jax
    import jax.numpy as jnp
    import numpy as np

    def f(x):
        return jax.pure_callback(
            lambda a: np.asarray(a),
            jax.ShapeDtypeStruct((2,), jnp.float32), x)

    v = hlo_audit.audit_callable(f, jnp.ones(2, jnp.float32),
                                 where="fix", enable_x64=False)
    assert rules_of(v) == {"PT201"}


def test_pt202_f64_promotion_fixture():
    import jax.numpy as jnp

    def f(x):
        return x.astype("float64") * 2.0

    v = hlo_audit.audit_callable(f, jnp.ones(2, jnp.float32),
                                 where="fix")
    assert "PT202" in rules_of(v)


def test_jaxpr_clean_program_fixture():
    import jax.numpy as jnp

    def f(x):
        return (x * 2.0).sum()

    assert hlo_audit.audit_callable(f, jnp.ones(2, jnp.float32),
                                    where="fix") == []


def test_pt203_donation_fixture():
    import jax
    import jax.numpy as jnp

    def f(p, x):
        return {k: w - x.sum() for k, w in p.items()}, x

    args = ({"w": jnp.ones((512, 512))}, jnp.ones((4,)))
    plain = jax.jit(f).lower(*args).as_text()
    donated = jax.jit(f, donate_argnums=(0,)).lower(*args).as_text()
    pos = hlo_audit.audit_lowered_donation(plain, "fix", min_mbytes=0.5)
    neg = hlo_audit.audit_lowered_donation(donated, "fix",
                                           min_mbytes=0.5)
    assert rules_of(pos) == {"PT203"} and neg == []


def test_pt301_manifest_drift_fixture(tmp_path):
    from paddle_tpu.analysis.manifest_check import audit_manifest

    fake = tmp_path / "manifest.json"
    fake.write_text(json.dumps({"ops": [
        {"name": "definitely_not_an_op_xyz", "present": True,
         "where": "paddle_tpu", "tensor_method": False},
        {"name": "abs", "present": True, "where": "paddle_tpu",
         "tensor_method": True},
    ]}))
    drift = audit_manifest(str(fake))
    assert len(drift) == 1 and drift[0].rule == "PT301"
    assert "definitely_not_an_op_xyz" in drift[0].message


# ----------------------- perf layer: PT401 layout tax -----------------------


def test_pt401_positive_real_program():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.analysis import perf_audit

    def f(x):
        return jnp.transpose(x, (0, 2, 1, 3)) * 2.0

    lowered = jax.jit(f).lower(jnp.ones((2, 64, 64, 32), jnp.float32))
    v, m = perf_audit.audit_program_texts(
        "fix", stablehlo_text=lowered.as_text(),
        opt_hlo_text=lowered.compile().as_text())
    assert m["pt401_transpose_count"] >= 1
    assert m["pt401_transpose_mbytes"] > 0
    assert "PT401" in rules_of(v)


def test_pt401_negative_real_program():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.analysis import perf_audit

    def f(x):
        return (x * 2.0).sum()

    lowered = jax.jit(f).lower(jnp.ones((8, 8), jnp.float32))
    v, m = perf_audit.audit_program_texts(
        "fix", stablehlo_text=lowered.as_text())
    assert m["pt401_transpose_count"] == 0
    assert "PT401" not in rules_of(v)


# ----------------------- PT402 recompile hazards -----------------------


def test_pt402_weak_input_positive_and_negative():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.analysis import perf_audit

    def f(x, lr):
        return x * lr

    weak = jax.make_jaxpr(f)(jnp.ones(4), 0.1)          # python scalar
    strong = jax.make_jaxpr(f)(jnp.ones(4),
                               jnp.float32(0.1))         # typed scalar
    assert perf_audit.weak_input_count(weak) == 1
    assert perf_audit.weak_input_count(strong) == 0
    v, m = perf_audit.audit_program_texts("fix", closed_jaxpr=weak)
    assert m["pt402_weak_inputs"] == 1 and "PT402" in rules_of(v)


PT402_CALLSITE_POS = """
    import jax

    def f(x, n):
        return x * n

    g = jax.jit(f)

    def run(x, batch):
        return g(x, int(batch.shape[0])), g(x, [1, 2])
"""

PT402_CALLSITE_NEG = """
    import jax

    def f(x, n):
        return x * n

    g = jax.jit(f)

    def run(x, n_arr):
        return g(x, n_arr)       # array arg: no host scalar, hashable

    def eager(x, batch):
        return f(x, int(batch.shape[0]))   # not the jitted wrapper
"""


def test_pt402_call_site_positive():
    from paddle_tpu.analysis import perf_audit

    v = perf_audit.call_site_hazards(
        textwrap.dedent(PT402_CALLSITE_POS), "fix.py")
    assert len(v) == 2 and rules_of(v) == {"PT402"}
    assert any("int(" in x.message for x in v)
    assert any("mutable literal" in x.message for x in v)


def test_pt402_call_site_negative():
    from paddle_tpu.analysis import perf_audit

    assert perf_audit.call_site_hazards(
        textwrap.dedent(PT402_CALLSITE_NEG), "fix.py") == []


# ----------------------- PT403 replicated state -----------------------


def test_pt403_replicated_positive_and_sharded_negative():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as PS

    from paddle_tpu.analysis import perf_audit

    mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
    rep = NamedSharding(mesh, PS())
    shd = NamedSharding(mesh, PS("dp", None))
    big = jnp.ones((512, 512), jnp.float32)              # 1 MiB

    def f(p):
        return p * 2.0

    rep_text = jax.jit(f, in_shardings=(rep,),
                       out_shardings=rep).lower(big).as_text()
    shd_text = jax.jit(f, in_shardings=(shd,),
                       out_shardings=shd).lower(big).as_text()
    pos = perf_audit.replicated_args(rep_text, min_mbytes=0.5)
    neg = perf_audit.replicated_args(shd_text, min_mbytes=0.5)
    assert pos["pt403_replicated_count"] == 1
    assert pos["pt403_replicated_mbytes"] == 1.0
    assert neg["pt403_replicated_count"] == 0
    v, _ = perf_audit.audit_program_texts(
        "fix", stablehlo_text=rep_text, min_replicated_mbytes=0.5)
    assert "PT403" in rules_of(v)


# ----------------------- PT404 collective patterns -----------------------


def _shard_map_jaxpr(fn, n=4):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as PS

    mesh = Mesh(np.array(jax.devices()[:n]), ("dp",))
    wrapped = shard_map(fn, mesh=mesh, in_specs=PS("dp"),
                        out_specs=PS(), check_rep=False)
    return jax.make_jaxpr(wrapped)(jnp.ones((8, 4), jnp.float32))


def test_pt404_allgather_then_reduce_positive():
    import jax

    from paddle_tpu.analysis import perf_audit

    def f(x):
        g = jax.lax.all_gather(x, "dp", tiled=True)
        return g.sum(axis=0).sum()                   # gather-then-reduce

    m = perf_audit.collective_patterns(_shard_map_jaxpr(f))
    assert m["pt404_allgather_reduce"] >= 1
    v, _ = perf_audit.audit_program_texts(
        "fix", closed_jaxpr=_shard_map_jaxpr(f))
    assert "PT404" in rules_of(v)


def test_pt404_chained_collectives_positive():
    import jax

    from paddle_tpu.analysis import perf_audit

    def f(x):
        s = jax.lax.psum(x.sum(axis=0), "dp")
        return jax.lax.all_gather(s, "dp", tiled=True).sum()  # chained

    m = perf_audit.collective_patterns(_shard_map_jaxpr(f))
    assert m["pt404_chained_collectives"] >= 1


def test_pt404_lone_collective_negative():
    import jax

    from paddle_tpu.analysis import perf_audit

    def f(x):
        return jax.lax.psum(x.sum(axis=0), "dp").sum()  # one collective,
        # compute on both sides: nothing chained, nothing gather-reduced

    m = perf_audit.collective_patterns(_shard_map_jaxpr(f))
    assert m["pt404_allgather_reduce"] == 0
    assert m["pt404_chained_collectives"] == 0


# ----------------------- PT405 hot-loop host syncs -----------------------


def _callback_fn(in_loop):
    import jax
    import jax.numpy as jnp
    import numpy as np

    def sync(c):
        return jax.pure_callback(
            lambda a: np.asarray(a),
            jax.ShapeDtypeStruct((), jnp.float32), c)

    if in_loop:
        def f(x):
            def body(c, _):
                return c + sync(c), None
            out, _ = jax.lax.scan(body, x, None, length=3)
            return out
    else:
        def f(x):
            return x + sync(x)
    return f


def test_pt405_callback_in_loop_positive():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.analysis import perf_audit

    jaxpr = jax.make_jaxpr(_callback_fn(True))(jnp.float32(1.0))
    m = perf_audit.host_sync_counts(jaxpr)
    assert m["pt405_loop_host_syncs"] == 1
    v, _ = perf_audit.audit_program_texts("fix", closed_jaxpr=jaxpr)
    assert any(x.rule == "PT405" and "loop" in x.message for x in v)


def test_pt405_callback_outside_loop_negative():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.analysis import perf_audit

    jaxpr = jax.make_jaxpr(_callback_fn(False))(jnp.float32(1.0))
    m = perf_audit.host_sync_counts(jaxpr)
    assert m["pt405_loop_host_syncs"] == 0
    assert m["pt405_host_syncs"] == 1        # still a sync, not in-loop


def test_pt405_clean_loop_negative():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.analysis import perf_audit

    def f(x):
        def body(c, _):
            return c * 2.0, None
        out, _ = jax.lax.scan(body, x, None, length=3)
        return out

    jaxpr = jax.make_jaxpr(f)(jnp.float32(1.0))
    m = perf_audit.host_sync_counts(jaxpr)
    assert m["pt405_host_syncs"] == 0
    assert m["pt405_loop_host_syncs"] == 0


# ----------------------- budget semantics -----------------------


def test_budget_diff_regress_improve_unbudgeted():
    metrics = {"prog": {"a_count": 3, "b_mbytes": 1.5, "new_zero": 0,
                        "new_hot": 2}}
    budget = {"prog": {"a_count": 2, "b_mbytes": 2.0}}
    reg, imp, unb = A.diff_against_budget(metrics, budget)
    assert ("prog", "a_count", 3, 2) in reg          # over budget
    assert ("prog", "new_hot", 2, None) in reg       # nonzero, unbudgeted
    assert ("prog", "b_mbytes", 1.5, 2.0) in imp     # ratchet note
    assert ("prog", "new_zero", 0, None) in unb      # zero: passes
    assert len(reg) == 2


def test_budget_only_judges_audited_programs():
    # a fast-subset audit must not vouch for (or trip over) the
    # slow-tier op_table entry
    metrics = {"call_sites": {"pt402_call_site_hazards": 0}}
    budget = {"call_sites": {"pt402_call_site_hazards": 0},
              "op_table": {"pt401_transpose_count": 0}}
    reg, imp, _ = A.diff_against_budget(metrics, budget)
    assert reg == [] and imp == []


def test_budget_round_trip_and_determinism(tmp_path):
    from paddle_tpu.analysis import perf_audit

    _, m1 = perf_audit.audit_perf(programs=("call_sites",),
                                  repo_root=REPO)
    _, m2 = perf_audit.audit_perf(programs=("call_sites",),
                                  repo_root=REPO)
    p1, p2 = str(tmp_path / "b1.json"), str(tmp_path / "b2.json")
    A.save_budget(p1, m1)
    A.save_budget(p2, m2)
    with open(p1, "rb") as f1, open(p2, "rb") as f2:
        assert f1.read() == f2.read()        # byte-identical across runs
    assert A.load_budget(p1) == m1


def test_budget_cli_round_trip(tmp_path):
    """emit -> check ok -> deliberate regress -> exit 2 ->
    --update-budget -> exit 0 (the acceptance-criteria loop, on the
    jax-free call_sites program so the subprocesses are cheap)."""
    budget = str(tmp_path / "budget.json")
    lint = os.path.join(REPO, "tools", "pt_lint.py")

    def run(*extra):
        return subprocess.run(
            [sys.executable, lint, "--perf",
             "--perf-programs", "call_sites", "--budget", budget]
            + list(extra),
            capture_output=True, text=True, cwd=REPO, timeout=300)

    p = run("--update-budget")
    assert p.returncode == 0, p.stdout + p.stderr
    p = run("--check")
    assert p.returncode == 0, p.stdout + p.stderr
    # deliberately regress the committed budget below reality
    data = json.load(open(budget))
    data["budgets"]["call_sites"]["pt402_call_site_hazards"] = -1
    with open(budget, "w") as f:
        json.dump(data, f)
    p = run("--check")
    assert p.returncode == 2, p.stdout + p.stderr
    assert "REGRESS" in p.stdout
    p = run("--update-budget")
    assert p.returncode == 0, p.stdout + p.stderr
    p = run("--check")
    assert p.returncode == 0, p.stdout + p.stderr


def test_budget_subset_update_merges_not_clobbers(tmp_path):
    """--perf-programs X --update-budget must keep the OTHER programs'
    committed ceilings (a subset rewrite that dropped them would let
    their costs regress silently — dropped-zero metrics pass --check)."""
    budget = str(tmp_path / "budget.json")
    A.save_budget(budget, {"op_table": {"pt401_transpose_count": 7}})
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "pt_lint.py"),
         "--perf", "--perf-programs", "call_sites",
         "--update-budget", "--budget", budget],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert p.returncode == 0, p.stdout + p.stderr
    merged = A.load_budget(budget)
    assert merged["op_table"] == {"pt401_transpose_count": 7}
    assert "call_sites" in merged


def test_perf_gate_merges_static_budget(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_perf_gate", os.path.join(REPO, "tools", "perf_gate.py"))
    pg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pg)

    budget = str(tmp_path / "perf_budget.json")
    A.save_budget(budget, {"prog": {"pt401_transpose_count": 13}})
    static = pg.load_static_budget(budget)
    row = static["static.prog.pt401_transpose_count"]
    assert row["lower_better"] and row["tolerance"] == 0.0

    ok_rows = [{"metric": "static.prog.pt401_transpose_count",
                "value": 13, "lower_better": True}]
    bad_rows = [{"metric": "static.prog.pt401_transpose_count",
                 "value": 14, "lower_better": True}]
    fails, _ = pg.gate(ok_rows, dict(static))
    assert fails == []
    fails, _ = pg.gate(bad_rows, dict(static))
    assert len(fails) == 1                    # budgets have no slack


# ----------------------- perf CI smoke (tier-1) -----------------------


def test_perf_smoke_train_step_within_budget():
    """The tier-1 perf-audit gate: the GPT train step audits under
    JAX_PLATFORMS=cpu, reports a NONZERO PT401 layout tax for the
    current transpose-default attention layout, and every metric holds
    its committed budget. When the flat-layout work (ROADMAP item 2)
    lands, the transpose numbers drop and --update-budget ratchets the
    floor down."""
    from paddle_tpu.analysis import perf_audit

    violations, metrics = perf_audit.audit_perf(
        programs=("train_step",), repo_root=REPO)
    assert not [v for v in violations if v.rule == "PT400"], \
        A.render_report(violations)
    m = metrics["gpt125m_train_step"]
    assert m["pt401_transpose_count"] > 0       # today's layout tax,
    assert m["pt401_transpose_mbytes"] > 0      # statically visible
    budget = A.load_budget(
        os.path.join(REPO, "tools", "perf_budget.json"))
    reg, _imp, _ = A.diff_against_budget(metrics, budget)
    assert reg == [], A.render_budget_diff(reg, [])


# ----------------------- slow tier: whole-program audits -----------------------


@pytest.mark.slow
def test_op_table_audit_clean():
    v = hlo_audit.audit_op_table()
    assert v == [], A.render_report(v)


@pytest.mark.slow
def test_train_step_audit_clean():
    v = hlo_audit.audit_train_step()
    assert v == [], A.render_report(v)


@pytest.mark.slow
def test_perf_full_audit_within_budget():
    """Slow tier: the FULL program set (decode step + op-table sweep
    included) audits cleanly against tools/perf_budget.json."""
    from paddle_tpu.analysis import perf_audit

    violations, metrics = perf_audit.audit_perf(
        programs=perf_audit.FULL_PROGRAMS, repo_root=REPO)
    assert not [v for v in violations if v.rule == "PT400"], \
        A.render_report(violations)
    budget = A.load_budget(
        os.path.join(REPO, "tools", "perf_budget.json"))
    reg, _imp, _ = A.diff_against_budget(metrics, budget)
    assert reg == [], A.render_budget_diff(reg, [])
