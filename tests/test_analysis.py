"""Static-analysis subsystem tests (docs/STATIC_ANALYSIS.md).

Three kinds of coverage:
  * fixture snippets — one positive and one negative per rule ID, so
    every rule's firing condition is pinned by a test, not by folklore;
  * repo gates — the whole tree runs through the ast+lock layers and
    must produce no violations beyond tools/lint_baseline.json, and the
    OPS_MANIFEST audit must show no drift (these ARE the CI gate);
  * meta-properties — determinism (two runs, byte-identical reports),
    suppression scoping, baseline diff semantics, CLI exit codes.

The jaxpr layer's *fixtures* (tiny traces) run in tier-1; the full
op-table + train-step audits build real programs and live in the slow
tier.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

import paddle_tpu.analysis as A
from paddle_tpu.analysis import hlo_audit, lock_check, trace_safety
from paddle_tpu.analysis.report import Suppressions, Violation

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(violations):
    return {v.rule for v in violations}


def run_ast(src):
    return trace_safety.analyze_source(textwrap.dedent(src), "fix.py")


def run_ast_tests(src):
    return trace_safety.analyze_source(
        textwrap.dedent(src), "tests/fix.py")


def run_lock(src):
    return lock_check.analyze_source(textwrap.dedent(src), "fix.py")


# --------------------------- PT001 tracer leak ---------------------------

PT001_POS = """
    import jax

    class M:
        @jax.jit
        def step(self, x):
            y = x * 2
            self.cache = y
            return y
"""

PT001_NEG = """
    import jax

    class M:
        def configure(self, x):     # not jit-traced: storing is fine
            self.cache = x * 2

        @jax.jit
        def step(self, x):
            return x * 2
"""


def test_pt001_positive():
    v = [x for x in run_ast(PT001_POS) if x.rule == "PT001"]
    assert len(v) == 1 and "self.cache" in v[0].message


def test_pt001_negative():
    assert "PT001" not in rules_of(run_ast(PT001_NEG))


def test_pt001_reaches_through_call_graph():
    # helper() is only traced because the jitted entry calls it
    src = """
        import jax

        def helper(self, x):
            self.state = x + 1
            return x

        @jax.jit
        def entry(self, x):
            return helper(self, x)
    """
    assert "PT001" in rules_of(run_ast(src))


# ----------------------- PT002 concretization -----------------------

PT002_POS = """
    from paddle_tpu import jit

    @jit.to_static
    def f(x):
        if x:
            return x.item()
        return float(x)
"""

PT002_NEG = """
    from paddle_tpu import jit

    @jit.to_static
    def f(x, n):
        y = x * int("4")      # int() of a constant: fine
        return y + len([n])

    def eager(x):
        return float(x)       # not traced: fine
"""


def test_pt002_positive():
    v = [x for x in run_ast(PT002_POS) if x.rule == "PT002"]
    # if-on-param, .item(), float(param)
    assert len(v) == 3


def test_pt002_negative():
    assert "PT002" not in rules_of(run_ast(PT002_NEG))


# ----------------------- PT003 PRNG key reuse -----------------------

PT003_POS = """
    import jax

    def sample(shape):
        key = jax.random.PRNGKey(0)
        a = jax.random.normal(key, shape)
        b = jax.random.uniform(key, shape)
        return a, b
"""

PT003_NEG = """
    import jax

    def sample(shape):
        key = jax.random.PRNGKey(0)
        k1, k2 = jax.random.split(key)
        a = jax.random.normal(k1, shape)
        b = jax.random.uniform(k2, shape)
        return a, b
"""


def test_pt003_positive():
    v = [x for x in run_ast(PT003_POS) if x.rule == "PT003"]
    assert len(v) == 1 and "`key`" in v[0].message


def test_pt003_negative():
    assert "PT003" not in rules_of(run_ast(PT003_NEG))


def test_pt003_branches_are_alternatives_not_reuse():
    # one branch runs, not both — the multinomial false-positive shape
    src = """
        import jax

        def pick(shape, replacement):
            key = jax.random.PRNGKey(0)
            if replacement:
                out = jax.random.categorical(key, shape)
            else:
                out = jax.random.gumbel(key, shape)
            return out
    """
    assert "PT003" not in rules_of(run_ast(src))


def test_pt003_loop_reuse_fires():
    src = """
        import jax

        def noisy(xs):
            key = jax.random.PRNGKey(0)
            out = []
            for x in xs:
                out.append(jax.random.normal(key, x.shape))
            return out
    """
    assert "PT003" in rules_of(run_ast(src))


def test_pt003_string_split_is_not_a_key():
    src = """
        def parse(line):
            cats = line.strip()
            cats = cats.split("|")
            use(cats)
            use(cats)
            return cats
    """
    assert "PT003" not in rules_of(run_ast(src))


# ----------------------- PT004 static args -----------------------

PT004_POS = """
    import jax

    def f(x, mode="train"):
        return x

    g = jax.jit(f, static_argnames="mdoe")   # typo: never static
"""

PT004_NEG = """
    import jax

    def f(x, mode="train"):
        return x

    g = jax.jit(f, static_argnames="mode")
"""


def test_pt004_positive():
    v = [x for x in run_ast(PT004_POS) if x.rule == "PT004"]
    assert len(v) == 1 and "mdoe" in v[0].message


def test_pt004_negative():
    assert "PT004" not in rules_of(run_ast(PT004_NEG))


def test_pt004_nonhashable_static_default():
    src = """
        import jax

        def f(x, cfg=[1, 2]):
            return x

        g = jax.jit(f, static_argnames="cfg")
    """
    v = [x for x in run_ast(src) if x.rule == "PT004"]
    assert len(v) == 1 and "non-hashable" in v[0].message


def test_pt004_argnums_out_of_range():
    src = """
        import jax

        def f(x):
            return x

        g = jax.jit(f, static_argnums=(3,))
    """
    v = [x for x in run_ast(src) if x.rule == "PT004"]
    assert len(v) == 1 and "out of range" in v[0].message


# ----------------------- PT005 silent swallow -----------------------

PT005_POS = """
    def f():
        try:
            work()
        except Exception:
            pass
"""

PT005_NEG = """
    def f():
        try:
            work()
        except Exception as e:
            log.warning("work failed: %s", e)
        try:
            work()
        except ValueError:
            pass                    # narrow: allowed
"""


def test_pt005_positive():
    assert "PT005" in rules_of(run_ast(PT005_POS))


def test_pt005_negative():
    assert "PT005" not in rules_of(run_ast(PT005_NEG))


# ----------------------- PT006 mutable default -----------------------


def test_pt006_positive_and_negative():
    pos = run_ast("def f(x, acc=[]):\n    return acc\n")
    neg = run_ast("def f(x, acc=None, n=3, s='a'):\n    return x\n")
    assert "PT006" in rules_of(pos)
    assert "PT006" not in rules_of(neg)


# ----------------------- PT007 unmarked slow test -----------------------

PT007_POS = """
    import time

    def test_waits():
        time.sleep(2.0)
"""

PT007_NEG = """
    import time
    import pytest

    @pytest.mark.slow
    def test_waits():
        time.sleep(2.0)

    def test_quick():
        time.sleep(0.01)
"""


def test_pt007_positive():
    assert "PT007" in rules_of(run_ast_tests(PT007_POS))


def test_pt007_negative():
    assert "PT007" not in rules_of(run_ast_tests(PT007_NEG))


def test_pt007_only_applies_to_test_files():
    assert "PT007" not in rules_of(run_ast(PT007_POS))


# ----------------------- PT101/PT102 lock discipline -----------------------

LOCK_POS = """
    import threading

    class Ring:
        def __init__(self):
            self._lock = threading.Lock()
            self._events = []
            self._seq = 0

        def record(self, e):
            with self._lock:
                self._seq += 1
                self._events.append(e)

        def drain(self):
            out = list(self._events)    # PT102: read outside lock
            self._events = []           # PT101: write outside lock
            return out
"""

LOCK_NEG = """
    import threading

    class Ring:
        def __init__(self):
            self._lock = threading.Lock()
            self._events = []

        def record(self, e):
            with self._lock:
                self._events.append(e)

        def drain(self):
            with self._lock:
                out = list(self._events)
                self._events = []
            return out
"""


def test_lock_positive():
    v = run_lock(LOCK_POS)
    assert {"PT101", "PT102"} <= rules_of(v)
    assert all("_events" in x.message for x in v)


def test_lock_negative():
    assert run_lock(LOCK_NEG) == []


def test_lock_init_excluded_and_unguarded_ignored():
    src = """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0          # construction: never flagged
                self.flag = False

            def bump(self):
                with self._lock:
                    self._n += 1

            def toggle(self):
                self.flag = True     # never written under lock: free
    """
    assert run_lock(src) == []


def test_lock_event_attrs_are_threadsafe():
    src = """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._stop = threading.Event()
                self._n = 0

            def start(self):
                with self._lock:
                    self._stop.clear()
                    self._n += 1

            def stop(self):
                self._stop.set()     # Event: internally synchronized
    """
    assert run_lock(src) == []


def test_pt007_three_arg_range():
    # the trip count is the STOP arg, not args[-1] (which is the step)
    src = """
        def test_spin():
            total = 0
            for i in range(0, 1000000, 1):
                total += i
    """
    assert "PT007" in rules_of(run_ast_tests(src))


def test_lock_module_read_without_global_stmt():
    # reads never need a `global` declaration — they must still count
    src = """
        import threading

        _lock = threading.Lock()
        _cache = {}

        def fill(k, v):
            with _lock:
                _cache[k] = v

        def peek(k):
            return _cache.get(k)     # PT102, no global stmt needed
    """
    v = run_lock(src)
    assert rules_of(v) == {"PT102"} and "peek" in v[0].message


def test_lock_module_local_shadow_not_flagged():
    src = """
        import threading

        _lock = threading.Lock()
        _cache = {}

        def fill(k, v):
            with _lock:
                _cache[k] = v

        def local_only():
            _cache = {}              # local shadow: not the global
            return _cache
    """
    assert run_lock(src) == []


def test_lock_module_level_globals():
    src = """
        import threading

        _lock = threading.Lock()
        _cache = None

        def put(k, v):
            global _cache
            with _lock:
                if _cache is None:
                    _cache = {}
                _cache[k] = v

        def peek():
            global _cache
            return _cache            # PT102
    """
    v = run_lock(src)
    assert rules_of(v) == {"PT102"} and "peek" in v[0].message


# ----------------------- suppressions -----------------------


def test_suppression_same_line_and_line_above():
    src = textwrap.dedent("""
        def f():
            try:
                work()
            except Exception:  # pt-lint: ok[PT005]
                pass

        def g():
            try:
                work()
            # pt-lint: ok[PT005]
            except Exception:
                pass
    """)
    raw = trace_safety.analyze_source(src, "fix.py")
    assert len([v for v in raw if v.rule == "PT005"]) == 2
    import ast as _ast

    kept = Suppressions(src, _ast.parse(src)).apply(raw)
    assert kept == []


def test_suppression_def_scope_and_rule_filter():
    src = textwrap.dedent("""
        def helper():  # pt-lint: ok[PT005]
            try:
                work()
            except Exception:
                pass

        def other():
            try:
                work()
            except Exception:  # pt-lint: ok[PT003] (wrong rule)
                pass
    """)
    import ast as _ast

    raw = trace_safety.analyze_source(src, "fix.py")
    kept = Suppressions(src, _ast.parse(src)).apply(raw)
    assert len(kept) == 1 and kept[0].rule == "PT005"
    # the survivor is the one whose suppression names the wrong rule
    assert kept[0].line > 6


# ----------------------- baseline semantics -----------------------


def test_baseline_diff_new_vs_known(tmp_path):
    v1 = Violation("a.py", 10, "PT005", "msg")
    v2 = Violation("a.py", 90, "PT005", "msg")   # same key, new instance
    v3 = Violation("b.py", 5, "PT101", "other")
    baseline = {v1.key(): 1}
    new, known, stale = A.diff_against_baseline([v1, v2, v3], baseline)
    assert known == [v1]          # earliest line is the baselined one
    assert set(new) == {v2, v3}
    assert stale == []


def test_baseline_stale_detection():
    baseline = {"gone.py|PT005|msg": 2}
    new, known, stale = A.diff_against_baseline([], baseline)
    assert new == [] and known == [] and stale == ["gone.py|PT005|msg"]


def test_baseline_round_trip(tmp_path):
    path = str(tmp_path / "baseline.json")
    vs = [Violation("x.py", 1, "PT006", "m"),
          Violation("x.py", 2, "PT006", "m")]
    A.save_baseline(path, vs)
    loaded = A.load_baseline(path)
    assert loaded == {"x.py|PT006|m": 2}


# ----------------------- repo gates (tier-1 CI) -----------------------


def test_repo_gate_no_new_ast_lock_violations():
    violations = A.analyze_repo(REPO, layers=("ast", "lock"))
    baseline = A.load_baseline(
        os.path.join(REPO, "tools", "lint_baseline.json"))
    new, _known, _stale = A.diff_against_baseline(violations, baseline)
    assert new == [], "new pt_lint violations:\n" + A.render_report(new)


def test_repo_gate_manifest_no_drift():
    from paddle_tpu.analysis.manifest_check import audit_manifest

    drift = audit_manifest()
    assert drift == [], A.render_report(drift)


def test_report_is_deterministic():
    r1 = A.render_report(A.analyze_repo(REPO, layers=("ast", "lock")))
    r2 = A.render_report(A.analyze_repo(REPO, layers=("ast", "lock")))
    assert r1 == r2


def test_cli_check_passes_and_exits_zero():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "pt_lint.py"),
         "--check", "--layers", "ast,lock"],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout


def test_cli_check_fails_on_new_violation(tmp_path):
    bad = tmp_path / "bad_module.py"
    bad.write_text("def f():\n"
                   "    try:\n"
                   "        work()\n"
                   "    except Exception:\n"
                   "        pass\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "pt_lint.py"),
         "--check", "--layers", "ast,lock", str(bad)],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "PT005" in proc.stdout


# ----------------------- jaxpr layer fixtures (tier-1) -----------------------


def test_pt201_host_transfer_fixture():
    import jax
    import jax.numpy as jnp
    import numpy as np

    def f(x):
        return jax.pure_callback(
            lambda a: np.asarray(a),
            jax.ShapeDtypeStruct((2,), jnp.float32), x)

    v = hlo_audit.audit_callable(f, jnp.ones(2, jnp.float32),
                                 where="fix", enable_x64=False)
    assert rules_of(v) == {"PT201"}


def test_pt202_f64_promotion_fixture():
    import jax.numpy as jnp

    def f(x):
        return x.astype("float64") * 2.0

    v = hlo_audit.audit_callable(f, jnp.ones(2, jnp.float32),
                                 where="fix")
    assert "PT202" in rules_of(v)


def test_jaxpr_clean_program_fixture():
    import jax.numpy as jnp

    def f(x):
        return (x * 2.0).sum()

    assert hlo_audit.audit_callable(f, jnp.ones(2, jnp.float32),
                                    where="fix") == []


def test_pt203_donation_fixture():
    import jax
    import jax.numpy as jnp

    def f(p, x):
        return {k: w - x.sum() for k, w in p.items()}, x

    args = ({"w": jnp.ones((512, 512))}, jnp.ones((4,)))
    plain = jax.jit(f).lower(*args).as_text()
    donated = jax.jit(f, donate_argnums=(0,)).lower(*args).as_text()
    pos = hlo_audit.audit_lowered_donation(plain, "fix", min_mbytes=0.5)
    neg = hlo_audit.audit_lowered_donation(donated, "fix",
                                           min_mbytes=0.5)
    assert rules_of(pos) == {"PT203"} and neg == []


def test_pt301_manifest_drift_fixture(tmp_path):
    from paddle_tpu.analysis.manifest_check import audit_manifest

    fake = tmp_path / "manifest.json"
    fake.write_text(json.dumps({"ops": [
        {"name": "definitely_not_an_op_xyz", "present": True,
         "where": "paddle_tpu", "tensor_method": False},
        {"name": "abs", "present": True, "where": "paddle_tpu",
         "tensor_method": True},
    ]}))
    drift = audit_manifest(str(fake))
    assert len(drift) == 1 and drift[0].rule == "PT301"
    assert "definitely_not_an_op_xyz" in drift[0].message


# ----------------------- slow tier: whole-program audits -----------------------


@pytest.mark.slow
def test_op_table_audit_clean():
    v = hlo_audit.audit_op_table()
    assert v == [], A.render_report(v)


@pytest.mark.slow
def test_train_step_audit_clean():
    v = hlo_audit.audit_train_step()
    assert v == [], A.render_report(v)
